"""Timestamp → state-root index: state-at-a-time for reads/proofs.

Reference: storage/state_ts_store.py (StateTsDbStorage — set /
get_equal_or_prev per ledger). Keys are (ledger_id, timestamp) packed
big-endian so KV range iteration is chronological; an in-memory sorted
cache gives O(log n) get_equal_or_prev while the KV store provides
durability (cache is rebuilt from the store on restart).
"""
from __future__ import annotations

import struct
from bisect import bisect_right, insort
from typing import Dict, List, Optional

from plenum_tpu.common.constants import DOMAIN_LEDGER_ID

_KEY = struct.Struct(">BQ")


class StateTsStore:
    def __init__(self, storage):
        self._storage = storage
        self._ts_cache: Dict[int, List[int]] = {}
        for key, _ in storage.iterator():
            if len(key) != _KEY.size:
                continue
            lid, ts = _KEY.unpack(key)
            insort(self._ts_cache.setdefault(lid, []), ts)

    def set(self, timestamp: int, root_hash: bytes,
            ledger_id: int = DOMAIN_LEDGER_ID):
        timestamp = int(timestamp)
        self._storage.put(_KEY.pack(ledger_id, timestamp), root_hash)
        cache = self._ts_cache.setdefault(ledger_id, [])
        idx = bisect_right(cache, timestamp)
        if idx == 0 or cache[idx - 1] != timestamp:
            cache.insert(idx, timestamp)

    def get(self, timestamp: int,
            ledger_id: int = DOMAIN_LEDGER_ID) -> Optional[bytes]:
        try:
            return self._storage.get(_KEY.pack(ledger_id, int(timestamp)))
        except KeyError:
            return None

    def get_equal_or_prev(self, timestamp: int,
                          ledger_id: int = DOMAIN_LEDGER_ID
                          ) -> Optional[bytes]:
        """Root hash at the latest point not after `timestamp`."""
        cache = self._ts_cache.get(ledger_id)
        if not cache:
            return None
        idx = bisect_right(cache, int(timestamp))
        if idx == 0:
            return None
        return self.get(cache[idx - 1], ledger_id)

    def get_last_ts(self, ledger_id: int = DOMAIN_LEDGER_ID
                    ) -> Optional[int]:
        cache = self._ts_cache.get(ledger_id)
        return cache[-1] if cache else None

    def close(self):
        self._storage.close()
