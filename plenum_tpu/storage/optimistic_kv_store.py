"""Uncommitted-batch staging over a KV store (reference:
storage/optimistic_kv_store.py). Writes accumulate per batch; commit flushes
the oldest batch to the underlying store; reject discards the newest."""
from collections import deque
from typing import Dict, List

from plenum_tpu.storage.kv_store import KeyValueStorage, to_bytes


class OptimisticKVStore:
    def __init__(self, store: KeyValueStorage):
        self._store = store
        self._batches = deque()        # deque of dict key->value|None
        self._current: Dict[bytes, bytes] = {}

    def set(self, key, value):
        self._current[to_bytes(key)] = to_bytes(value)

    def remove(self, key):
        self._current[to_bytes(key)] = None

    def get(self, key, is_committed: bool = False) -> bytes:
        key = to_bytes(key)
        if not is_committed:
            if key in self._current:
                val = self._current[key]
                if val is None:
                    raise KeyError(key)
                return val
            for batch in reversed(self._batches):
                if key in batch:
                    val = batch[key]
                    if val is None:
                        raise KeyError(key)
                    return val
        return self._store.get(key)

    def create_batch_from_current(self, state_root=None):
        self._batches.append(self._current)
        self._current = {}

    def first_batch_idr(self):
        return 0 if self._batches else None

    def commit_batch(self):
        if not self._batches:
            raise ValueError("no uncommitted batch")
        batch = self._batches.popleft()
        ops = [('put', k, v) if v is not None else ('remove', k)
               for k, v in batch.items()]
        self._store.do_ops_in_batch(ops)

    def reject_batch(self):
        if self._current:
            self._current = {}
        elif self._batches:
            self._batches.pop()

    @property
    def un_committed_count(self):
        return len(self._batches) + (1 if self._current else 0)
