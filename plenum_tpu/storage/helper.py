"""KV storage factory (reference: storage/helper.py:20 initKeyValueStorage)."""
from plenum_tpu.storage.kv_memory import KeyValueStorageInMemory
from plenum_tpu.storage.kv_file import KeyValueStorageFile


_BACKENDS = {
    'memory': lambda d, n, **kw: KeyValueStorageInMemory(),
    'file': KeyValueStorageFile,
}

try:
    from plenum_tpu.storage.native import NativeKVStore  # noqa
    _BACKENDS['native'] = NativeKVStore
except ImportError:
    pass


def initKeyValueStorage(storage_type: str, data_dir: str, db_name: str,
                        read_only: bool = False, **kwargs):
    backend = _BACKENDS.get(storage_type)
    if backend is None:
        raise ValueError("unknown storage type {}".format(storage_type))
    return backend(data_dir, db_name, read_only=read_only, **kwargs) \
        if storage_type != 'memory' else backend(data_dir, db_name)
