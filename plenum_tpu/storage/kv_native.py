"""Native log-structured KV store — ctypes bridge to
plenum_tpu/native/kvlog.c (the framework's RocksDB-equivalent,
reference storage/kv_store_rocksdb.py:15).

Same .kvlog on-disk format as KeyValueStorageFile, so the two backends
open each other's files; unlike the Python backend, VALUES STAY ON
DISK — only the C index (key bytes + offsets) is resident. A sorted
key cache on the Python side provides ordered iteration; it is rebuilt
from the C index snapshot on open and maintained incrementally after.
"""
from __future__ import annotations

import ctypes
import os
import struct
from typing import Iterable, Iterator, Tuple

try:
    from sortedcontainers import SortedSet
except ImportError:            # soft dep: stdlib fallback
    from plenum_tpu.utils.sorted_fallback import SortedSet

from plenum_tpu.storage.kv_store import KeyValueStorage, to_bytes

_lib = None


def _get_lib():
    global _lib
    if _lib is None:
        from plenum_tpu.native import build_and_load
        lib = build_and_load("kvlog")
        lib.kv_open.argtypes = [ctypes.c_char_p]
        lib.kv_open.restype = ctypes.c_void_p
        lib.kv_close.argtypes = [ctypes.c_void_p]
        lib.kv_flush.argtypes = [ctypes.c_void_p]
        lib.kv_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_uint32, ctypes.c_char_p,
                               ctypes.c_uint32]
        lib.kv_put.restype = ctypes.c_int
        lib.kv_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_uint32, ctypes.c_char_p,
                               ctypes.c_uint64]
        lib.kv_get.restype = ctypes.c_long
        lib.kv_remove.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_uint32]
        lib.kv_remove.restype = ctypes.c_int
        lib.kv_batch_begin.argtypes = [ctypes.c_void_p]
        lib.kv_batch_begin.restype = ctypes.c_int
        lib.kv_batch_end.argtypes = [ctypes.c_void_p]
        lib.kv_batch_end.restype = ctypes.c_int
        lib.kv_apply_packed.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_uint64]
        lib.kv_apply_packed.restype = ctypes.c_int
        lib.kv_count.argtypes = [ctypes.c_void_p]
        lib.kv_count.restype = ctypes.c_uint64
        lib.kv_garbage.argtypes = [ctypes.c_void_p]
        lib.kv_garbage.restype = ctypes.c_uint64
        lib.kv_keys_size.argtypes = [ctypes.c_void_p]
        lib.kv_keys_size.restype = ctypes.c_uint64
        lib.kv_keys.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.kv_compact.argtypes = [ctypes.c_void_p]
        lib.kv_compact.restype = ctypes.c_int
        _lib = lib
    return _lib


def available() -> bool:
    try:
        _get_lib()
        return True
    except Exception:
        return False


class KeyValueStorageNative(KeyValueStorage):
    def __init__(self, db_dir: str, db_name: str):
        os.makedirs(db_dir, exist_ok=True)
        self._path = os.path.join(db_dir, db_name + ".kvlog")
        self._lib = _get_lib()
        self._db = self._lib.kv_open(self._path.encode())
        if not self._db:
            raise IOError("kvlog open failed: {}".format(self._path))
        self._closed = False
        self._keys = SortedSet(self._snapshot_keys())

    def _handle(self):
        """The C engine dereferences the handle unchecked — a NULL from
        a closed store would segfault the process, so guard here."""
        if self._closed or not self._db:
            raise ValueError("operation on closed kvlog store {}".format(
                self._path))
        return self._db

    def _snapshot_keys(self):
        size = self._lib.kv_keys_size(self._handle())
        if size == 0:
            return []
        buf = ctypes.create_string_buffer(size)
        self._lib.kv_keys(self._handle(), buf)
        keys, pos, raw = [], 0, buf.raw
        while pos + 4 <= size:
            (klen,) = struct.unpack_from("<I", raw, pos)
            keys.append(raw[pos + 4:pos + 4 + klen])
            pos += 4 + klen
        return keys

    # ------------------------------------------------------------- ops

    def put(self, key, value):
        key, value = to_bytes(key), to_bytes(value)
        if self._lib.kv_put(self._handle(), key, len(key), value,
                            len(value)) != 0:
            raise IOError("kvlog put failed")
        self._keys.add(key)

    def get(self, key) -> bytes:
        key = to_bytes(key)
        cap = 4096
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.kv_get(self._handle(), key, len(key), buf, cap)
            if n < 0:
                if n == -2:
                    raise IOError("kvlog read failed")
                raise KeyError(key)
            if n <= cap:
                return buf.raw[:n]
            cap = n

    def get_or_none(self, key):
        key = to_bytes(key)
        if key not in self._keys:
            return None
        return self.get(key)

    def remove(self, key):
        key = to_bytes(key)
        if self._lib.kv_remove(self._handle(), key, len(key)) != 0:
            raise IOError("kvlog remove failed")
        self._keys.discard(key)

    def _apply_packed(self, parts, ordered_ops):
        """ordered_ops = [(key, is_put)] in BATCH ORDER — the key cache
        must see remove-then-put of one key end live, like the engine."""
        packed = b"".join(parts)
        if self._lib.kv_apply_packed(self._handle(), packed,
                                     len(packed)) != 0:
            raise IOError("kvlog batch failed")
        for key, is_put in ordered_ops:
            if is_put:
                self._keys.add(key)
            else:
                self._keys.discard(key)

    def setBatch(self, batch: Iterable[Tuple]):
        """One FFI call: records packed host-side into the wire format,
        applied by the engine as a single atomic batch frame."""
        parts, ops = [], []
        for key, value in batch:
            key, value = to_bytes(key), to_bytes(value)
            parts.append(struct.pack("<II", len(key), len(value)))
            parts.append(key)
            parts.append(value)
            ops.append((key, True))
        self._apply_packed(parts, ops)

    def do_ops_in_batch(self, batch: Iterable[Tuple]):
        """batch of ('put', key, value) / ('remove', key) — one atomic
        on-disk frame, like setBatch."""
        parts, ops = [], []
        for op, key, *rest in batch:
            key = to_bytes(key)
            if op == "put":
                value = to_bytes(rest[0])
                parts.append(struct.pack("<II", len(key), len(value)))
                parts.append(key)
                parts.append(value)
                ops.append((key, True))
            elif op == "remove":
                parts.append(struct.pack("<II", len(key), 0xFFFFFFFF))
                parts.append(key)
                ops.append((key, False))
            else:
                raise ValueError("unknown batch op {}".format(op))
        self._apply_packed(parts, ops)

    def iterator(self, start=None, end=None,
                 include_value=True) -> Iterator:
        start = to_bytes(start) if start is not None else None
        end = to_bytes(end) if end is not None else None
        keys = list(self._keys.irange(start, end))
        if include_value:
            # materialized snapshot, like the file backend: mutations
            # during consumption must not change what the iterator yields
            return iter([(k, self.get(k)) for k in keys])
        return iter(keys)

    # ------------------------------------------------------ maintenance

    def compact(self):
        if self._lib.kv_compact(self._handle()) != 0:
            raise IOError("kvlog compact failed")

    @property
    def garbage_bytes(self) -> int:
        return self._lib.kv_garbage(self._handle())

    def __len__(self):
        return self._lib.kv_count(self._handle())

    @property
    def size(self) -> int:
        return self._lib.kv_count(self._handle())

    def drop(self):
        self._lib.kv_close(self._db)
        if os.path.exists(self._path):
            os.unlink(self._path)
        self._db = self._lib.kv_open(self._path.encode())
        self._keys = SortedSet()

    def close(self):
        if not self._closed:
            self._lib.kv_close(self._db)
            self._db = None
            self._closed = True

    @property
    def closed(self) -> bool:
        return self._closed
