"""In-memory KV store (reference: storage/kv_in_memory.py)."""
from typing import Iterable, Tuple

from sortedcontainers import SortedDict

from plenum_tpu.storage.kv_store import KeyValueStorage, to_bytes


class KeyValueStorageInMemory(KeyValueStorage):
    def __init__(self, *args, **kwargs):
        self._dict = SortedDict()
        self._closed = False

    def put(self, key, value):
        self._dict[to_bytes(key)] = to_bytes(value)

    def get(self, key) -> bytes:
        return self._dict[to_bytes(key)]

    def remove(self, key):
        self._dict.pop(to_bytes(key), None)

    def setBatch(self, batch: Iterable[Tuple]):
        for key, value in batch:
            self.put(key, value)

    def do_ops_in_batch(self, batch: Iterable[Tuple]):
        for op, key, *rest in batch:
            if op == 'put':
                self.put(key, rest[0])
            elif op == 'remove':
                self.remove(key)
            else:
                raise ValueError("unknown batch op {}".format(op))

    def iterator(self, start=None, end=None, include_value=True):
        start = to_bytes(start) if start is not None else None
        end = to_bytes(end) if end is not None else None
        keys = self._dict.irange(minimum=start, maximum=end)
        if include_value:
            return ((k, self._dict[k]) for k in keys)
        return iter(list(keys))

    def drop(self):
        self._dict.clear()

    def close(self):
        self._closed = True

    @property
    def closed(self):
        return self._closed

    @property
    def size(self):
        return len(self._dict)
