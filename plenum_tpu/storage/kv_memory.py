"""In-memory KV store (reference: storage/kv_in_memory.py).

Backed by a plain dict: put/get ride the per-trie-node hot path (every
MPT spine persist lands here), so writes must be O(1) C-dict ops.
Ordered range scans are only needed by catchup/recovery iterators, so
keys are sorted lazily per iterator() call instead of on every put.
"""
from typing import Iterable, Tuple

from plenum_tpu.storage.kv_store import KeyValueStorage, to_bytes


class KeyValueStorageInMemory(KeyValueStorage):
    def __init__(self, *args, **kwargs):
        self._dict = {}
        self._closed = False

    def put(self, key, value):
        # hot path: trie-node persists pass bytes already — an exact
        # type check dodges two function calls per put
        self._dict[key if type(key) is bytes else to_bytes(key)] = \
            value if type(value) is bytes else to_bytes(value)

    def get(self, key) -> bytes:
        return self._dict[key if type(key) is bytes else to_bytes(key)]

    def get_or_none(self, key):
        return self._dict.get(key if type(key) is bytes else to_bytes(key))

    def remove(self, key):
        self._dict.pop(key if type(key) is bytes else to_bytes(key), None)

    def setBatch(self, batch: Iterable[Tuple]):
        for key, value in batch:
            self.put(key, value)

    def do_ops_in_batch(self, batch: Iterable[Tuple]):
        for op, key, *rest in batch:
            if op == 'put':
                self.put(key, rest[0])
            elif op == 'remove':
                self.remove(key)
            else:
                raise ValueError("unknown batch op {}".format(op))

    def iterator(self, start=None, end=None, include_value=True):
        start = to_bytes(start) if start is not None else None
        end = to_bytes(end) if end is not None else None
        keys = sorted(k for k in self._dict
                      if (start is None or k >= start)
                      and (end is None or k <= end))
        if include_value:
            return ((k, self._dict[k]) for k in keys)
        return iter(keys)

    def drop(self):
        self._dict.clear()

    def close(self):
        self._closed = True

    @property
    def closed(self):
        return self._closed

    @property
    def size(self):
        return len(self._dict)
