"""Durable file-backed KV store: append-only log + in-memory index, with
log compaction on open. Fills the role RocksDB plays in the reference
(storage/kv_store_rocksdb.py:15) until the native C++ engine
(plenum_tpu/storage/native) is preferred; simple, crash-safe (torn tails are
truncated on recovery), and adequate for ledgers whose hot path is
sequential append.

Record format: [klen u32][vlen u32 | 0xFFFFFFFF=tombstone][key][value].
Batches are framed as one [klen=0xFFFFFFFE][body_len][records...] record,
so a crash mid-batch truncates the WHOLE batch on recovery (atomicity —
the role RocksDB WriteBatch plays in the reference).
"""
import os
import struct
from typing import Iterable, Tuple

try:
    from sortedcontainers import SortedDict
except ImportError:            # soft dep: stdlib fallback
    from plenum_tpu.utils.sorted_fallback import SortedDict

from plenum_tpu.storage.kv_store import KeyValueStorage, to_bytes

_HDR = struct.Struct('<II')
_TOMBSTONE = 0xFFFFFFFF
_BATCH = 0xFFFFFFFE


class KeyValueStorageFile(KeyValueStorage):
    def __init__(self, db_dir: str, db_name: str, read_only: bool = False):
        self._path = os.path.join(db_dir, db_name + '.kvlog')
        os.makedirs(db_dir, exist_ok=True)
        self._index = SortedDict()
        self._closed = False
        self._read_only = read_only
        self._recover()
        self._fh = None if read_only else open(self._path, 'ab')

    def _recover(self):
        if not os.path.exists(self._path):
            return
        valid_end = 0
        with open(self._path, 'rb') as fh:
            data = fh.read()
        pos = 0
        while pos + _HDR.size <= len(data):
            klen, vlen = _HDR.unpack_from(data, pos)
            if klen == _BATCH:
                if pos + _HDR.size + vlen > len(data):
                    break  # torn batch: drop it whole
                end = pos + _HDR.size + vlen
                self._apply_records(data, pos + _HDR.size, end)
                pos = end
            else:
                body = klen + (0 if vlen == _TOMBSTONE else vlen)
                if pos + _HDR.size + body > len(data):
                    break  # torn tail
                self._apply_records(data, pos, pos + _HDR.size + body)
                pos += _HDR.size + body
            valid_end = pos
        if valid_end < len(data) and not self._read_only:
            with open(self._path, 'r+b') as fh:
                fh.truncate(valid_end)

    def _apply_records(self, data: bytes, pos: int, end: int):
        while pos + _HDR.size <= end:
            klen, vlen = _HDR.unpack_from(data, pos)
            body = klen + (0 if vlen == _TOMBSTONE else vlen)
            if pos + _HDR.size + body > end:
                break  # defensive: malformed interior record
            key = data[pos + _HDR.size: pos + _HDR.size + klen]
            if vlen == _TOMBSTONE:
                self._index.pop(key, None)
            else:
                val = data[pos + _HDR.size + klen: pos + _HDR.size + klen + vlen]
                self._index[key] = val
            pos += _HDR.size + body

    def _append(self, key: bytes, value) -> None:
        if self._read_only:
            raise RuntimeError("read-only store")
        if value is None:
            rec = _HDR.pack(len(key), _TOMBSTONE) + key
        else:
            rec = _HDR.pack(len(key), len(value)) + key + value
        self._fh.write(rec)

    def put(self, key, value):
        key, value = to_bytes(key), to_bytes(value)
        self._append(key, value)
        self._fh.flush()
        self._index[key] = value

    def get(self, key) -> bytes:
        return self._index[to_bytes(key)]

    def remove(self, key):
        key = to_bytes(key)
        if key in self._index:
            self._append(key, None)
            self._fh.flush()
            del self._index[key]

    @staticmethod
    def _record(key: bytes, value) -> bytes:
        if value is None:
            return _HDR.pack(len(key), _TOMBSTONE) + key
        return _HDR.pack(len(key), len(value)) + key + value

    def _write_framed(self, records, updates):
        """One atomic batch frame: all-or-nothing on crash recovery."""
        if self._read_only:
            raise RuntimeError("read-only store")
        body = b''.join(records)
        self._fh.write(_HDR.pack(_BATCH, len(body)) + body)
        self._fh.flush()
        for key, value in updates:
            if value is None:
                self._index.pop(key, None)
            else:
                self._index[key] = value

    def setBatch(self, batch: Iterable[Tuple]):
        records, updates = [], []
        for key, value in batch:
            key, value = to_bytes(key), to_bytes(value)
            records.append(self._record(key, value))
            updates.append((key, value))
        self._write_framed(records, updates)

    def do_ops_in_batch(self, batch: Iterable[Tuple]):
        records, updates = [], []
        for op, key, *rest in batch:
            key = to_bytes(key)
            if op == 'put':
                value = to_bytes(rest[0])
                records.append(self._record(key, value))
                updates.append((key, value))
            elif op == 'remove':
                records.append(self._record(key, None))
                updates.append((key, None))
            else:
                raise ValueError("unknown batch op {}".format(op))
        self._write_framed(records, updates)

    def iterator(self, start=None, end=None, include_value=True):
        start = to_bytes(start) if start is not None else None
        end = to_bytes(end) if end is not None else None
        keys = list(self._index.irange(minimum=start, maximum=end))
        if include_value:
            return iter([(k, self._index[k]) for k in keys])
        return iter(keys)

    def compact(self):
        """Rewrite the log with only live records."""
        tmp = self._path + '.compact'
        with open(tmp, 'wb') as fh:
            for k, v in self._index.items():
                fh.write(_HDR.pack(len(k), len(v)) + k + v)
        if self._fh:
            self._fh.close()
        os.replace(tmp, self._path)
        self._fh = open(self._path, 'ab')

    def drop(self):
        self._index.clear()
        if self._fh:
            self._fh.close()
        if os.path.exists(self._path):
            os.remove(self._path)
        if not self._read_only:
            self._fh = open(self._path, 'ab')

    def close(self):
        if self._fh:
            self._fh.close()
            self._fh = None
        self._closed = True

    @property
    def closed(self):
        return self._closed

    @property
    def size(self):
        return len(self._index)
