"""Client request: operation dict + signature(s) + digests.

Reference: plenum/common/request.py:13 (Request), :42 (digest). The digest is
sha256 over the canonical serialization of all signed fields; payload_digest
excludes signatures (dedup key — seqNoDB maps payload_digest → txn).
"""
from hashlib import sha256
from typing import Dict, Optional

from plenum_tpu.common.constants import (
    CURRENT_PROTOCOL_VERSION, IDENTIFIER, OPERATION, REQ_ID, SIGNATURE,
    SIGNATURES, TAA_ACCEPTANCE)
from plenum_tpu.common.serializers.serialization import serialize_msg_for_signing

from plenum_tpu.native import try_load_ext

_fp = try_load_ext("fastpath")


class Request:
    def __init__(self,
                 identifier: str = None,
                 reqId: int = None,
                 operation: Dict = None,
                 signature: str = None,
                 signatures: Dict[str, str] = None,
                 protocolVersion: int = CURRENT_PROTOCOL_VERSION,
                 taaAcceptance: Dict = None,
                 endorser: str = None):
        self.identifier = identifier
        self.reqId = reqId
        self.operation = operation or {}
        self.signature = signature
        self.signatures = signatures
        self.protocolVersion = protocolVersion
        self.taaAcceptance = taaAcceptance
        self.endorser = endorser
        # cached: read ~6x per request across intake/apply/commit, and
        # the operation dict never mutates after construction
        self.txn_type = (operation or {}).get('type')
        self._digest = None
        self._payload_digest = None
        self._payload_state = None  # cached signingPayloadState()
        # canonical signing bytes, pre-computed by the C intake path
        # (fastpath.request_intake) — authentication reuses them
        self._signing_ser = None

    @property
    def digest(self) -> str:
        if self._digest is None:
            self._digest = self.getDigest()
        return self._digest

    @property
    def payload_digest(self) -> str:
        if self._payload_digest is None:
            self._payload_digest = self.getPayloadDigest()
        return self._payload_digest

    def getDigest(self) -> str:
        if _fp is not None:
            try:
                return _fp.digest_hex(self.signingState())
            except TypeError:
                pass
        return sha256(serialize_msg_for_signing(self.signingState())).hexdigest()

    def getPayloadDigest(self) -> str:
        if _fp is not None:
            try:
                return _fp.digest_hex(self.signingPayloadState())
            except TypeError:
                pass
        return sha256(serialize_msg_for_signing(
            self.signingPayloadState())).hexdigest()

    def signingState(self, identifier=None) -> Dict:
        # copy: signingPayloadState may hand back its cached dict, and
        # the signature keys added here must not leak into it
        state = dict(self.signingPayloadState(identifier))
        if self.signatures is not None:
            state[SIGNATURES] = self.signatures
        if self.signature is not None:
            state[SIGNATURE] = self.signature
        return state

    def signingPayloadState(self, identifier=None) -> Dict:
        if identifier is None or identifier == self.identifier:
            # hot path: digest, payload digest, and signature prep all
            # build this same dict — once per request, not three times
            state = self._payload_state
            if state is not None:
                return state
        state = {
            IDENTIFIER: identifier or self.identifier,
            REQ_ID: self.reqId,
            OPERATION: self.operation,
        }
        if self.protocolVersion is not None:
            state['protocolVersion'] = self.protocolVersion
        if self.taaAcceptance is not None:
            state[TAA_ACCEPTANCE] = self.taaAcceptance
        if self.endorser is not None:
            state['endorser'] = self.endorser
        if identifier is None or identifier == self.identifier:
            self._payload_state = state
        return state

    @property
    def key(self) -> str:
        return self.digest

    def all_identifiers(self):
        ids = []
        if self.signatures:
            ids.extend(self.signatures.keys())
        if self.identifier is not None and self.identifier not in ids:
            ids.append(self.identifier)
        return sorted(ids)

    def as_dict(self) -> Dict:
        d = {
            IDENTIFIER: self.identifier,
            REQ_ID: self.reqId,
            OPERATION: self.operation,
            'protocolVersion': self.protocolVersion,
        }
        if self.signature is not None:
            d[SIGNATURE] = self.signature
        if self.signatures is not None:
            d[SIGNATURES] = self.signatures
        if self.taaAcceptance is not None:
            d[TAA_ACCEPTANCE] = self.taaAcceptance
        if self.endorser is not None:
            d['endorser'] = self.endorser
        return d

    @classmethod
    def from_dict(cls, d: Dict) -> 'Request':
        return cls(identifier=d.get(IDENTIFIER),
                   reqId=d.get(REQ_ID),
                   operation=d.get(OPERATION),
                   signature=d.get(SIGNATURE),
                   signatures=d.get(SIGNATURES),
                   protocolVersion=d.get('protocolVersion',
                                         CURRENT_PROTOCOL_VERSION),
                   taaAcceptance=d.get(TAA_ACCEPTANCE),
                   endorser=d.get('endorser'))

    def __eq__(self, other):
        return isinstance(other, Request) and self.as_dict() == other.as_dict()

    def __hash__(self):
        return hash(self.digest)

    def __repr__(self):
        return "Request(identifier={}, reqId={}, type={})".format(
            self.identifier, self.reqId, self.txn_type)
