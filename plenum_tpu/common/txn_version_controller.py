"""Transaction payload version control.

Reference: plenum/server/txn_version_controller.py — the base plenum
controller is deliberately minimal (pool version is None; downstream
ledgers like indy-node override it to gate request validation rules on
the pool's upgraded version). Same seam here: WriteRequestManager holds
one and handlers may consult `get_txn_version` when validation rules
differ across payload versions.
"""
from typing import Optional

from plenum_tpu.common.constants import TXN_PAYLOAD, TXN_PAYLOAD_PROTOCOL_VERSION


class TxnVersionController:
    @property
    def version(self) -> Optional[str]:
        return None

    def update_version(self, txn: dict) -> None:
        """Called per committed txn; the base controller tracks nothing."""

    def get_txn_version(self, txn: dict) -> str:
        version = (txn.get(TXN_PAYLOAD) or {}).get(
            TXN_PAYLOAD_PROTOCOL_VERSION)
        return "1" if version is None else str(version)

    def get_pool_version(self, timestamp) -> Optional[str]:
        return None
