"""State-leaf encoding shared by write handlers and verifying clients.

The domain-state MPT stores, per nym, a canonical-JSON envelope
``{"val": <record>, "lsn": seq_no, "lut": txn_time}`` (reference:
plenum/server/request_handlers/utils.py encode_state_value /
decode_state_value). A client checking a state proof must rebuild the
leaf byte-for-byte from the reply's (data, seqNo, txnTime), so the
codec lives here in `common` — imported by both sides — rather than in
the server package.
"""
from __future__ import annotations

import json

from plenum_tpu.native import try_load_ext

_fp = try_load_ext("fastpath")


def nym_to_state_key(nym: str) -> bytes:
    return nym.encode()


def encode_state_value(value: dict, seq_no, txn_time) -> bytes:
    payload = {"val": value, "lsn": seq_no, "lut": txn_time}
    if _fp is not None:
        try:
            return _fp.canonical_json_ascii(payload)
        except TypeError:
            pass
    return json.dumps(payload, sort_keys=True, separators=(",", ":")).encode()


def decode_state_value(data: bytes):
    if data is None:
        return None, None, None
    parsed = json.loads(bytes(data).decode())
    return parsed.get("val"), parsed.get("lsn"), parsed.get("lut")
