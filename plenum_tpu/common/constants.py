"""Protocol constants (reference: plenum/common/constants.py — ledger ids,
txn types, roles, field keys)."""

# --- Ledger ids (reference constants.py POOL_LEDGER_ID..AUDIT_LEDGER_ID;
# ordering of catchup follows docs/source/catchup.md: audit first)
POOL_LEDGER_ID = 0
DOMAIN_LEDGER_ID = 1
CONFIG_LEDGER_ID = 2
AUDIT_LEDGER_ID = 3

VALID_LEDGER_IDS = (POOL_LEDGER_ID, DOMAIN_LEDGER_ID, CONFIG_LEDGER_ID,
                    AUDIT_LEDGER_ID)

# --- Transaction types (numeric strings on the wire, as in the reference)
NODE = "0"
NYM = "1"
AUDIT_TXN = "2"
GET_TXN = "3"
TXN_AUTHOR_AGREEMENT = "4"
TXN_AUTHOR_AGREEMENT_AML = "5"
GET_TXN_AUTHOR_AGREEMENT = "6"
GET_TXN_AUTHOR_AGREEMENT_AML = "7"
TXN_AUTHOR_AGREEMENT_DISABLE = "8"
LEDGERS_FREEZE = "9"
GET_FROZEN_LEDGERS = "10"

# --- Roles
TRUSTEE = "0"
STEWARD = "2"
IDENTITY_OWNER = None  # a NYM with no role

# --- Node services
VALIDATOR = "VALIDATOR"
OBSERVER = "OBSERVER"

# --- Common field keys (wire names kept for parity with the reference)
TXN_TYPE = "type"
TXN_TIME = "txnTime"
TXN_PAYLOAD = "txn"
TXN_PAYLOAD_TYPE = "type"
TXN_PAYLOAD_DATA = "data"
TXN_PAYLOAD_METADATA = "metadata"
TXN_PAYLOAD_METADATA_FROM = "from"
TXN_PAYLOAD_METADATA_REQ_ID = "reqId"
TXN_PAYLOAD_METADATA_DIGEST = "digest"
TXN_PAYLOAD_METADATA_PAYLOAD_DIGEST = "payloadDigest"
TXN_PAYLOAD_METADATA_TAA_ACCEPTANCE = "taaAcceptance"
TXN_PAYLOAD_METADATA_ENDORSER = "endorser"
TXN_PAYLOAD_PROTOCOL_VERSION = "protocolVersion"
TXN_METADATA = "txnMetadata"
TXN_METADATA_TIME = "txnTime"
TXN_METADATA_ID = "txnId"
TXN_METADATA_SEQ_NO = "seqNo"
TXN_SIGNATURE = "reqSignature"
TXN_VERSION = "ver"
TXN_SIGNATURE_TYPE = "type"
ED25519 = "ED25519"
TXN_SIGNATURE_VALUES = "values"
TXN_SIGNATURE_FROM = "from"
TXN_SIGNATURE_VALUE = "value"

IDENTIFIER = "identifier"
REQ_ID = "reqId"
OPERATION = "operation"
SIGNATURE = "signature"
SIGNATURES = "signatures"
DIGEST = "digest"
PROTOCOL_VERSION = "protocolVersion"
CURRENT_PROTOCOL_VERSION = 2
TAA_ACCEPTANCE = "taaAcceptance"
TAA_ACCEPTANCE_DIGEST = "taaDigest"
TAA_ACCEPTANCE_MECHANISM = "mechanism"
TAA_ACCEPTANCE_TIME = "time"

# --- TAA txn payload fields (reference plenum/common/constants.py:197-208)
TXN_AUTHOR_AGREEMENT_TEXT = "text"
TXN_AUTHOR_AGREEMENT_VERSION = "version"
TXN_AUTHOR_AGREEMENT_DIGEST = "digest"
TXN_AUTHOR_AGREEMENT_RETIREMENT_TS = "retirement_ts"
TXN_AUTHOR_AGREEMENT_RATIFICATION_TS = "ratification_ts"
AML_VERSION = "version"
AML = "aml"
AML_CONTEXT = "amlContext"

TARGET_NYM = "dest"
VERKEY = "verkey"
ROLE = "role"
ALIAS = "alias"
DATA = "data"
TXN_ID = "txnId"

NODE_IP = "node_ip"
NODE_PORT = "node_port"
CLIENT_IP = "client_ip"
CLIENT_PORT = "client_port"
SERVICES = "services"
BLS_KEY = "blskey"
BLS_KEY_PROOF = "blskey_pop"

# state-proof reply keys (reference plenum/common/constants.py:128-141)
STATE_PROOF = "state_proof"
ROOT_HASH = "root_hash"
PROOF_NODES = "proof_nodes"
MULTI_SIGNATURE = "multi_signature"

# --- Audit txn fields (reference plenum/common/constants.py AUDIT_TXN_*)
AUDIT_TXN_VIEW_NO = "viewNo"
AUDIT_TXN_PP_SEQ_NO = "ppSeqNo"
AUDIT_TXN_LEDGERS_SIZE = "ledgerSize"
AUDIT_TXN_LEDGER_ROOT = "ledgerRoot"
AUDIT_TXN_STATE_ROOT = "stateRoot"
AUDIT_TXN_PRIMARIES = "primaries"
AUDIT_TXN_DIGEST = "digest"
AUDIT_TXN_NODE_REG = "nodeReg"

# --- TAA state keys
TAA_LATEST = "taa:latest"
TAA_VERSION_PREFIX = "taa:v"
TAA_DIGEST_PREFIX = "taa:d"
TAA_AML_LATEST = "taa:aml:latest"
TAA_AML_VERSION_PREFIX = "taa:aml:v"

# --- Frozen ledgers state key
FROZEN_LEDGERS = "frozen_ledgers"

# --- Mode of a node (reference plenum/common/startable.py Mode)
class Mode:
    starting = 100
    discovering = 200    # catching up pool txns
    discovered = 300
    syncing = 400        # catching up other ledgers
    synced = 450
    participating = 500

    @classmethod
    def is_done_discovering(cls, mode):
        return mode is not None and mode >= cls.discovered

    @classmethod
    def is_done_syncing(cls, mode):
        return mode is not None and mode >= cls.synced


# --- Stack auth modes
class AuthMode:
    ALLOW_ANY = 1
    RESTRICTED = 2


# --- Misc protocol constants
BATCH = "BATCH"
OP_FIELD_NAME = "op"
PLUGIN_FIELDS = "plugin_fields"
GENERAL_LIMIT_SIZE = 256

# seed/key sizes
SEED_SIZE = 32
ED25519_SIG_SIZE = 64
ED25519_PK_SIZE = 32

LAST_SENT_PRE_PREPARE = "lastSentPrePrepare"
