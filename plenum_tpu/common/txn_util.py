"""Canonical transaction envelope helpers.

Reference: plenum/common/txn_util.py:335 — a committed txn is
{ver, txn: {type, data, metadata, protocolVersion}, txnMetadata: {txnTime,
seqNo, txnId}, reqSignature: {type, values}}.
"""
from typing import Optional

from plenum_tpu.common.constants import (
    TXN_PAYLOAD, TXN_PAYLOAD_TYPE, TXN_PAYLOAD_DATA, TXN_PAYLOAD_METADATA,
    TXN_PAYLOAD_METADATA_FROM, TXN_PAYLOAD_METADATA_REQ_ID,
    TXN_PAYLOAD_METADATA_DIGEST, TXN_PAYLOAD_METADATA_PAYLOAD_DIGEST,
    TXN_PAYLOAD_METADATA_TAA_ACCEPTANCE, TXN_PAYLOAD_METADATA_ENDORSER,
    TXN_PAYLOAD_PROTOCOL_VERSION, TXN_METADATA, TXN_METADATA_TIME,
    TXN_METADATA_SEQ_NO, TXN_METADATA_ID, TXN_SIGNATURE, TXN_SIGNATURE_TYPE,
    TXN_SIGNATURE_VALUES, TXN_SIGNATURE_FROM, TXN_SIGNATURE_VALUE,
    TXN_VERSION, ED25519)


def init_empty_txn(txn_type, protocol_version=None) -> dict:
    txn = {
        TXN_PAYLOAD: {
            TXN_PAYLOAD_TYPE: txn_type,
            TXN_PAYLOAD_DATA: {},
            TXN_PAYLOAD_METADATA: {},
        },
        TXN_METADATA: {},
        TXN_SIGNATURE: {},
        TXN_VERSION: "1",
    }
    if protocol_version is not None:
        txn[TXN_PAYLOAD][TXN_PAYLOAD_PROTOCOL_VERSION] = protocol_version
    return txn


def reqToTxn(req) -> dict:
    """Build the txn envelope from a Request (reference txn_util.py
    reqToTxn). Runs once per write on the apply hot path — the envelope
    is built as one literal instead of init_empty_txn + patching."""
    if isinstance(req, dict):
        from plenum_tpu.common.request import Request
        req = Request(**req) if 'operation' in req else Request(**req.get('req', req))
    op = dict(req.operation)
    txn_type = op.pop('type')
    md = {TXN_PAYLOAD_METADATA_DIGEST: req.digest,
          TXN_PAYLOAD_METADATA_PAYLOAD_DIGEST: req.payload_digest}
    if req.identifier is not None:
        md[TXN_PAYLOAD_METADATA_FROM] = req.identifier
    if req.reqId is not None:
        md[TXN_PAYLOAD_METADATA_REQ_ID] = req.reqId
    if req.taaAcceptance is not None:
        md[TXN_PAYLOAD_METADATA_TAA_ACCEPTANCE] = req.taaAcceptance
    if req.endorser is not None:
        md[TXN_PAYLOAD_METADATA_ENDORSER] = req.endorser
    payload = {TXN_PAYLOAD_TYPE: txn_type,
               TXN_PAYLOAD_DATA: op,
               TXN_PAYLOAD_METADATA: md}
    if req.protocolVersion is not None:
        payload[TXN_PAYLOAD_PROTOCOL_VERSION] = req.protocolVersion
    sig = {}
    if req.signature or req.signatures:
        sig[TXN_SIGNATURE_TYPE] = ED25519
        values = []
        if req.signature:
            values.append({TXN_SIGNATURE_FROM: req.identifier,
                           TXN_SIGNATURE_VALUE: req.signature})
        if req.signatures:
            for frm, value in sorted(req.signatures.items()):
                values.append({TXN_SIGNATURE_FROM: frm,
                               TXN_SIGNATURE_VALUE: value})
        sig[TXN_SIGNATURE_VALUES] = values
    return {TXN_PAYLOAD: payload,
            TXN_METADATA: {},
            TXN_SIGNATURE: sig,
            TXN_VERSION: "1"}


def append_txn_metadata(txn: dict, seq_no: int = None, txn_time: int = None,
                        txn_id: str = None) -> dict:
    md = txn.setdefault(TXN_METADATA, {})
    if seq_no is not None:
        md[TXN_METADATA_SEQ_NO] = seq_no
    if txn_time is not None:
        md[TXN_METADATA_TIME] = txn_time
    if txn_id is not None:
        md[TXN_METADATA_ID] = txn_id
    return txn


def get_type(txn: dict):
    return txn[TXN_PAYLOAD][TXN_PAYLOAD_TYPE]


def get_payload_data(txn: dict) -> dict:
    return txn[TXN_PAYLOAD][TXN_PAYLOAD_DATA]


def get_from(txn: dict) -> Optional[str]:
    return txn[TXN_PAYLOAD][TXN_PAYLOAD_METADATA].get(TXN_PAYLOAD_METADATA_FROM)


def get_req_id(txn: dict) -> Optional[int]:
    return txn[TXN_PAYLOAD][TXN_PAYLOAD_METADATA].get(TXN_PAYLOAD_METADATA_REQ_ID)


def get_digest(txn: dict) -> Optional[str]:
    return txn[TXN_PAYLOAD][TXN_PAYLOAD_METADATA].get(TXN_PAYLOAD_METADATA_DIGEST)


def get_payload_digest(txn: dict) -> Optional[str]:
    return txn[TXN_PAYLOAD][TXN_PAYLOAD_METADATA].get(
        TXN_PAYLOAD_METADATA_PAYLOAD_DIGEST)


def get_seq_no(txn: dict) -> Optional[int]:
    return txn.get(TXN_METADATA, {}).get(TXN_METADATA_SEQ_NO)


def get_txn_time(txn: dict) -> Optional[int]:
    return txn.get(TXN_METADATA, {}).get(TXN_METADATA_TIME)


def get_txn_id(txn: dict) -> Optional[str]:
    return txn.get(TXN_METADATA, {}).get(TXN_METADATA_ID)


def get_version(txn: dict):
    return txn.get(TXN_VERSION)


def get_protocol_version(txn: dict):
    return txn[TXN_PAYLOAD].get(TXN_PAYLOAD_PROTOCOL_VERSION)


def get_req_signature(txn: dict) -> dict:
    return txn.get(TXN_SIGNATURE, {})


class TxnMarker:
    """Sort marker for deterministic txn iteration."""
