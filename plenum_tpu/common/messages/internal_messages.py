"""Internal bus messages — lightweight NamedTuples, never on the wire.

Reference: plenum/common/messages/internal_messages.py.
"""
from typing import Any, List, NamedTuple, Optional


class RaisedSuspicion(NamedTuple):
    inst_id: int
    ex: Any  # SuspiciousNode


class VoteForViewChange(NamedTuple):
    suspicion: Any  # Suspicion
    view_no: Optional[int] = None


class NodeNeedViewChange(NamedTuple):
    view_no: int


class NeedViewChange(NamedTuple):
    view_no: Optional[int] = None


class ViewChangeStarted(NamedTuple):
    view_no: int


class NewViewAccepted(NamedTuple):
    view_no: int
    view_changes: List
    checkpoint: Any
    batches: List


class NewViewCheckpointsApplied(NamedTuple):
    view_no: int
    view_changes: List
    checkpoint: Any
    batches: List


class ReOrderedInNewView(NamedTuple):
    pass


class CatchupDone(NamedTuple):
    ledger_id: int


class CatchupFinished(NamedTuple):
    last_caught_up_3pc: tuple
    master_last_ordered: tuple


class NeedMasterCatchup(NamedTuple):
    pass


class NeedBackupCatchup(NamedTuple):
    inst_id: int
    caught_up_till_3pc: tuple


class CheckpointStabilized(NamedTuple):
    last_stable_3pc: tuple


class PrimaryDisconnected(NamedTuple):
    inst_id: int


class PrimarySelected(NamedTuple):
    pass


class MissingMessage(NamedTuple):
    msg_type: str
    key: Any
    inst_id: int
    dst: Optional[List[str]]
    stash_data: Optional[Any] = None


class RequestPropagates(NamedTuple):
    bad_requests: List


class PreSigVerification(NamedTuple):
    cmsg: Any


class BackupSetupLastOrdered(NamedTuple):
    inst_id: int


class MasterReorderedAfterVC(NamedTuple):
    pass


class Cleanup(NamedTuple):
    pass


class StartViewChange(NamedTuple):
    view_no: int


class ApplyNewView(NamedTuple):
    view_no: int
