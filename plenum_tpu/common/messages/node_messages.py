"""The wire protocol: all inter-node message schemas.

Reference: plenum/common/messages/node_messages.py:26-525 — message op names
and field wire names are kept for parity (they are protocol facts, the
"what"; the implementation around them is new).

Deliberately dropped reference classes (superseded, not missing):

- ``ViewChangeDone`` / ``CurrentState`` (node_messages.py:~500) — the
  *legacy pre-2.0* view-change protocol. This framework implements only
  the reference's own replacement (the "plenum 2.0" consensus used by
  ``ReplicaService``): ``ViewChange`` / ``ViewChangeAck`` / ``NewView``
  below, matching view_change_service.py. Carrying both protocols is
  the dual-path legacy the reference itself was migrating off.
- ``FutureViewChangeDone`` / ``ViewChangeStartMessage`` /
  ``ViewChangeContinueMessage`` — internal shims of that same legacy
  protocol (node restart mid-ViewChangeDone); our restart path recovers
  via the audit ledger + catchup instead (server/node.py restart flow).
- ``PoolLedgerTxns`` — legacy client push of pool txns; clients learn
  the pool via catchup (LedgerStatus/CatchupReq on the client stack).
- ``BlacklistMsg`` — defined but vestigial in the reference (blacklists
  are node-local; nothing ever processes a received BlacklistMsg).
  Suspicion accounting lives in server/blacklister.py.
"""
from plenum_tpu.common.messages.fields import (
    AnyField, AnyMapField, AnyValueField, BatchIDField, BlsMultiSignatureField,
    BooleanField, ChooseField, IterableField, LedgerIdField,
    LimitedLengthStringField, MapField, MerkleRootField, MessageField,
    NonEmptyStringField, NonNegativeNumberField, ProtocolVersionField,
    SerializedValueField, SignatureField, StringifiedNonNegativeNumberField,
    TimestampField, ViewChangeField)
from plenum_tpu.common.messages.message_base import MessageBase

# ---------------------------------------------------------------- transport

class Batch(MessageBase):
    """Outbox coalescing envelope (reference node_messages.py:26,
    plenum/common/batched.py)."""
    typename = "BATCH"
    schema = (
        ("messages", IterableField(SerializedValueField())),
        ("signature", SignatureField(nullable=True)),
    )


# ------------------------------------------------------------ client-facing

class RequestAck(MessageBase):
    typename = "REQACK"
    schema = (
        ("identifier", LimitedLengthStringField()),
        ("reqId", NonNegativeNumberField()),
    )


class RequestNack(MessageBase):
    typename = "REQNACK"
    schema = (
        ("identifier", LimitedLengthStringField()),
        ("reqId", NonNegativeNumberField()),
        ("reason", LimitedLengthStringField(max_length=4096)),
    )


class Reject(MessageBase):
    typename = "REJECT"
    schema = (
        ("identifier", LimitedLengthStringField()),
        ("reqId", NonNegativeNumberField()),
        ("reason", LimitedLengthStringField(max_length=4096)),
    )


class Reply(MessageBase):
    typename = "REPLY"
    schema = (
        ("result", AnyMapField()),
    )


# ------------------------------------------------------------- propagation

class Propagate(MessageBase):
    typename = "PROPAGATE"
    schema = (
        ("request", AnyMapField()),
        ("senderClient", LimitedLengthStringField(nullable=True)),
    )


class PropagateBatch(MessageBase):
    """Many PROPAGATEs in one wire message (no reference equivalent —
    the reference sends one PROPAGATE per request, plenum/server/
    propagator.py:204, and amortizes only at the ZMQ frame layer).
    At n nodes every request is handled n-1 times per node; batching at
    the MESSAGE level amortizes handler dispatch, validation, and sim/
    transport delivery across a whole tick of requests — the difference
    between the 25-node pool collapsing and draining. `clients` uses ""
    for requests whose submitting client is unknown."""

    typename = "PROPAGATE_BATCH"
    schema = (
        ("requests", IterableField(AnyMapField(), min_length=1)),
        # "" = submitting client unknown (relay hop)
        ("clients", IterableField(AnyField())),
        # advisory causal stamp [origin, flush_seq, perf_ts, wall_ts]
        # (flat_wire.TraceStamp.as_list) — observability-only; malformed
        # content decodes to None and never affects request handling
        ("traceCtx", AnyField(nullable=True, optional=True)),
    )


# ----------------------------------------------------------------- 3PC

class PrePrepare(MessageBase):
    typename = "PREPREPARE"
    schema = (
        ("instId", NonNegativeNumberField()),
        ("viewNo", NonNegativeNumberField()),
        ("ppSeqNo", NonNegativeNumberField()),
        ("ppTime", TimestampField()),
        ("reqIdr", IterableField(NonEmptyStringField())),   # request digests
        ("discarded", StringifiedNonNegativeNumberField(nullable=True)),
        ("digest", NonEmptyStringField()),
        ("ledgerId", LedgerIdField()),
        ("stateRootHash", MerkleRootField(nullable=True)),
        ("txnRootHash", MerkleRootField(nullable=True)),
        ("sub_seq_no", NonNegativeNumberField()),
        ("final", BooleanField()),
        ("poolStateRootHash", MerkleRootField(nullable=True, optional=True)),
        ("auditTxnRootHash", MerkleRootField(nullable=True, optional=True)),
        ("blsMultiSig", BlsMultiSignatureField(nullable=True, optional=True)),
        ("blsMultiSigs", IterableField(BlsMultiSignatureField(),
                                       nullable=True, optional=True)),
        ("originalViewNo", NonNegativeNumberField(nullable=True, optional=True)),
    )


class Prepare(MessageBase):
    typename = "PREPARE"
    schema = (
        ("instId", NonNegativeNumberField()),
        ("viewNo", NonNegativeNumberField()),
        ("ppSeqNo", NonNegativeNumberField()),
        ("ppTime", TimestampField()),
        ("digest", NonEmptyStringField()),
        ("stateRootHash", MerkleRootField(nullable=True)),
        ("txnRootHash", MerkleRootField(nullable=True)),
        ("auditTxnRootHash", MerkleRootField(nullable=True, optional=True)),
    )


class Commit(MessageBase):
    typename = "COMMIT"
    schema = (
        ("instId", NonNegativeNumberField()),
        ("viewNo", NonNegativeNumberField()),
        ("ppSeqNo", NonNegativeNumberField()),
        ("blsSig", NonEmptyStringField(nullable=True, optional=True)),
        ("blsSigs", MapField(StringifiedNonNegativeNumberField(),
                             NonEmptyStringField(),
                             nullable=True, optional=True)),
    )


class ThreePCBatch(MessageBase):
    """One sender's whole tick of broadcast 3PC votes — PRE-PREPAREs,
    PREPAREs and COMMITs across ALL of its protocol instances — in ONE
    wire message (no reference equivalent; the reference sends each vote
    separately and amortizes only at the ZMQ frame layer). At n nodes
    with f+1 RBFT instances every 3PC phase is otherwise its own
    broadcast per instance per in-flight batch; coalescing at the
    MESSAGE level amortizes serialization (one msgpack pack for the
    whole batch), transport delivery, and receive-side dispatch — and
    hands the receiver a COLUMN of same-sender votes for the columnar
    `process_prepare_batch` / `process_commit_batch` intake.

    `messages` entries are the inner messages' `to_dict()` wire form
    (op field included) in SEND ORDER — FIFO per sender preserves the
    PP-before-PREPARE-before-COMMIT causality the per-message wire had.
    In-process transports (SimNetwork) deliver live MessageBase objects
    instead; `as_dict` normalizes to wire form only when a real
    transport serializes the envelope."""

    typename = "THREE_PC_BATCH"
    schema = (
        ("messages", IterableField(AnyField(), min_length=1)),
        # advisory causal stamp [origin, flush_seq, perf_ts, wall_ts]
        # (flat_wire.TraceStamp.as_list) — observability-only; malformed
        # content decodes to None and never affects vote handling
        ("traceCtx", AnyField(nullable=True, optional=True)),
    )

    def as_dict(self):
        d = {"messages": [
            m.to_dict() if isinstance(m, MessageBase) else m
            for m in self.messages]}
        if getattr(self, "traceCtx", None) is not None:
            d["traceCtx"] = list(self.traceCtx)
        return d


class FlatBatch(MessageBase):
    """Flat zero-copy wire envelope (common/serializers/flat_wire.py):
    PREPARE/COMMIT votes as contiguous typed columns, PRE-PREPAREs and
    PROPAGATEs as length-prefixed sections — ONE pack and ONE parse per
    peer per tick, zero intermediate Python message objects on the
    receive path. The payload is opaque bytes to the transport (msgpack
    wraps it as a single bin field, no canonical-sort recursion into
    the votes); `to_legacy_messages` re-materializes typed messages for
    the fault-injection unwrap seams. The typed THREE_PC_BATCH /
    PROPAGATE_BATCH path stays as validated fallback
    (Config.FLAT_WIRE=False or an installed adversary tap)."""

    typename = "FLAT_WIRE"
    schema = (
        ("payload", SerializedValueField()),
    )


class Ordered(MessageBase):
    typename = "ORDERED"
    schema = (
        ("instId", NonNegativeNumberField()),
        ("viewNo", NonNegativeNumberField()),
        ("valid_reqIdr", IterableField(NonEmptyStringField())),
        ("invalid_reqIdr", IterableField(NonEmptyStringField())),
        ("ppSeqNo", NonNegativeNumberField()),
        ("ppTime", TimestampField()),
        ("ledgerId", LedgerIdField()),
        ("stateRootHash", MerkleRootField(nullable=True)),
        ("txnRootHash", MerkleRootField(nullable=True)),
        ("auditTxnRootHash", MerkleRootField(nullable=True, optional=True)),
        ("primaries", IterableField(NonEmptyStringField())),
        ("nodeReg", IterableField(NonEmptyStringField(), nullable=True,
                                  optional=True)),
        ("originalViewNo", NonNegativeNumberField(nullable=True, optional=True)),
        ("digest", NonEmptyStringField(nullable=True, optional=True)),
    )


class Checkpoint(MessageBase):
    typename = "CHECKPOINT"
    schema = (
        ("instId", NonNegativeNumberField()),
        ("viewNo", NonNegativeNumberField()),
        ("seqNoStart", NonNegativeNumberField()),
        ("seqNoEnd", NonNegativeNumberField()),
        ("digest", NonEmptyStringField()),
    )


# ----------------------------------------------------------- view change

class InstanceChange(MessageBase):
    typename = "INSTANCE_CHANGE"
    schema = (
        ("viewNo", NonNegativeNumberField()),
        ("reason", NonNegativeNumberField()),
    )


class ViewChange(MessageBase):
    typename = "VIEW_CHANGE"
    schema = (
        ("viewNo", NonNegativeNumberField()),
        ("stableCheckpoint", NonNegativeNumberField()),
        ("prepared", IterableField(BatchIDField())),
        ("preprepared", IterableField(BatchIDField())),
        ("checkpoints", IterableField(AnyMapField())),  # Checkpoint dicts
    )


class ViewChangeAck(MessageBase):
    typename = "VIEW_CHANGE_ACK"
    schema = (
        ("viewNo", NonNegativeNumberField()),
        ("name", NonEmptyStringField()),
        ("digest", NonEmptyStringField()),
    )


class NewView(MessageBase):
    typename = "NEW_VIEW"
    schema = (
        ("viewNo", NonNegativeNumberField()),
        ("viewChanges", IterableField(ViewChangeField())),
        ("checkpoint", AnyMapField(nullable=True)),      # Checkpoint dict
        ("batches", IterableField(BatchIDField())),
        ("primary", NonEmptyStringField(nullable=True, optional=True)),
    )


class OldViewPrePrepareRequest(MessageBase):
    typename = "OLD_VIEW_PREPREPARE_REQ"
    schema = (
        ("instId", NonNegativeNumberField()),
        ("batch_ids", IterableField(BatchIDField())),
    )


class OldViewPrePrepareReply(MessageBase):
    typename = "OLD_VIEW_PREPREPARE_REP"
    schema = (
        ("instId", NonNegativeNumberField()),
        ("preprepares", IterableField(AnyMapField())),
    )


# --------------------------------------------------------------- catchup

class LedgerStatus(MessageBase):
    typename = "LEDGER_STATUS"
    schema = (
        ("ledgerId", LedgerIdField()),
        ("txnSeqNo", NonNegativeNumberField()),
        ("viewNo", NonNegativeNumberField(nullable=True)),
        ("ppSeqNo", NonNegativeNumberField(nullable=True)),
        ("merkleRoot", MerkleRootField()),
        ("protocolVersion", ProtocolVersionField(nullable=True)),
    )


class ConsistencyProof(MessageBase):
    typename = "CONSISTENCY_PROOF"
    schema = (
        ("ledgerId", LedgerIdField()),
        ("seqNoStart", NonNegativeNumberField()),
        ("seqNoEnd", NonNegativeNumberField()),
        ("viewNo", NonNegativeNumberField(nullable=True)),
        ("ppSeqNo", NonNegativeNumberField(nullable=True)),
        ("oldMerkleRoot", MerkleRootField()),
        ("newMerkleRoot", MerkleRootField()),
        ("hashes", IterableField(NonEmptyStringField())),
    )


class CatchupReq(MessageBase):
    typename = "CATCHUP_REQ"
    schema = (
        ("ledgerId", LedgerIdField()),
        ("seqNoStart", NonNegativeNumberField()),
        ("seqNoEnd", NonNegativeNumberField()),
        ("catchupTill", NonNegativeNumberField()),
    )


class CatchupRep(MessageBase):
    typename = "CATCHUP_REP"
    schema = (
        ("ledgerId", LedgerIdField()),
        ("txns", MapField(StringifiedNonNegativeNumberField(), AnyMapField())),
        ("consProof", IterableField(NonEmptyStringField())),
        # optional per-txn RFC 6962 audit paths (seqNo → b58 sibling
        # hashes) against the leecher's agreed (target_size, target_root)
        # — lets a leecher reject a lying chunk at rep time instead of
        # after buffering the whole range; absent from legacy reps
        ("auditPaths", MapField(StringifiedNonNegativeNumberField(),
                                IterableField(NonEmptyStringField()),
                                optional=True, nullable=True)),
    )


# ----------------------------------------------------- message re-request

class MessageReq(MessageBase):
    """Request a missing protocol message (reference node_messages.py:460)."""
    typename = "MESSAGE_REQUEST"
    allowed_types = {"LEDGER_STATUS", "CONSISTENCY_PROOF", "PREPREPARE",
                     "PREPARE", "COMMIT", "PROPAGATE", "VIEW_CHANGE",
                     "NEW_VIEW"}
    schema = (
        ("msg_type", ChooseField(values=allowed_types)),
        ("params", AnyMapField()),
    )


class MessageRep(MessageBase):
    typename = "MESSAGE_RESPONSE"
    schema = (
        ("msg_type", ChooseField(values=MessageReq.allowed_types)),
        ("params", AnyMapField()),
        ("msg", AnyValueField()),
    )


# ---------------------------------------------------------------- observer

class BatchCommitted(MessageBase):
    typename = "BATCH_COMMITTED"
    schema = (
        ("requests", IterableField(AnyMapField())),
        ("ledgerId", LedgerIdField()),
        ("instId", NonNegativeNumberField()),
        ("viewNo", NonNegativeNumberField()),
        ("ppSeqNo", NonNegativeNumberField()),
        ("ppTime", TimestampField()),
        ("stateRoot", MerkleRootField(nullable=True)),
        ("txnRoot", MerkleRootField(nullable=True)),
        ("seqNoStart", NonNegativeNumberField()),
        ("seqNoEnd", NonNegativeNumberField()),
        ("auditTxnRootHash", MerkleRootField(nullable=True, optional=True)),
        ("primaries", IterableField(NonEmptyStringField())),
        ("nodeReg", IterableField(NonEmptyStringField(), nullable=True,
                                  optional=True)),
        ("originalViewNo", NonNegativeNumberField(nullable=True, optional=True)),
        ("digest", NonEmptyStringField(nullable=True, optional=True)),
    )


class ObservedData(MessageBase):
    typename = "OBSERVED_DATA"
    schema = (
        ("msg_type", ChooseField(values={"BATCH"})),
        ("msg", AnyField()),
    )


# ------------------------------------------------------- replica lifecycle

class BackupInstanceFaulty(MessageBase):
    typename = "BACKUP_INSTANCE_FAULTY"
    schema = (
        ("viewNo", NonNegativeNumberField()),
        ("instances", IterableField(NonNegativeNumberField())),
        ("reason", NonNegativeNumberField()),
    )
