"""MessageBase: declarative typed message schemas validated on construction.

Reference: plenum/common/messages/message_base.py — `schema` is a tuple of
(field_name, FieldValidator); messages construct from positional or keyword
args, validate immediately, serialize to a plain dict with `op` = typename.
"""
from typing import Any, Dict, Optional, Tuple

from plenum_tpu.common.constants import OP_FIELD_NAME
from plenum_tpu.common.exceptions import InvalidNodeMessageException
from plenum_tpu.common.messages.fields import FieldValidator


class MessageValidationError(InvalidNodeMessageException):
    pass


class MessageBase:
    typename: str = None
    schema: Tuple[Tuple[str, FieldValidator], ...] = ()
    # per-class caches derived from schema (set by __init_subclass__;
    # rebuilding these per message construction dominated the hot wire
    # path before)
    _schema_names: Tuple[str, ...] = ()
    _schema_name_set: frozenset = frozenset()
    # fields not included in the digest/signature
    _frozen = False

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        cls._schema_names = tuple(name for name, _ in cls.schema)
        cls._schema_name_set = frozenset(cls._schema_names)

    def __init__(self, *args, **kwargs):
        field_names = self._schema_names
        if len(args) > len(field_names):
            raise MessageValidationError(
                "too many positional arguments for {}".format(self.typename))
        values: Dict[str, Any] = dict(zip(field_names, args))
        for k, v in kwargs.items():
            if k in values:
                raise MessageValidationError(
                    "duplicate argument {} for {}".format(k, self.typename))
            if k not in self._schema_name_set:
                raise MessageValidationError(
                    "unknown argument {} for {}".format(k, self.typename))
            values[k] = v
        self._validate_and_set(values)
        self._frozen = True

    def _validate_and_set(self, values: Dict[str, Any]):
        for name, validator in self.schema:
            if name not in values or values[name] is None:
                if validator.optional or validator.nullable:
                    values.setdefault(name, None)
                    continue
                raise MessageValidationError(
                    "validation error [{}]: missed fields - {}"
                    .format(type(self).__name__, name))
            err = validator.validate(values[name])
            if err:
                raise MessageValidationError(
                    "validation error [{}]: {} ({}={})"
                    .format(type(self).__name__, err, name,
                            repr(values[name])[:128]))
        for name in self._schema_names:
            object.__setattr__(self, name, values.get(name))

    def __setattr__(self, key, value):
        if self._frozen and key in self._schema_name_set:
            raise AttributeError("message fields are immutable")
        object.__setattr__(self, key, value)

    @property
    def _field_names(self):
        return self._schema_names

    def as_dict(self) -> Dict[str, Any]:
        """Plain-dict form of the payload, tuples normalized to lists so
        dict equality and canonical serialization are stable."""
        return {name: _plain(getattr(self, name)) for name in self._field_names}

    def to_dict(self) -> Dict[str, Any]:
        """Wire form: payload + op field."""
        d = self.as_dict()
        d[OP_FIELD_NAME] = self.typename
        return d

    def items(self):
        return self.as_dict().items()

    def __getitem__(self, item):
        if isinstance(item, int):
            return getattr(self, self._field_names[item])
        return getattr(self, item)

    def __iter__(self):
        return iter(getattr(self, name) for name in self._field_names)

    def __len__(self):
        return len(self.schema)

    def __eq__(self, other):
        if not isinstance(other, MessageBase):
            return NotImplemented
        return self.typename == other.typename and self.as_dict() == other.as_dict()

    def __hash__(self):
        return hash((self.typename, _hashable(self.as_dict())))

    def __repr__(self):
        return "{}({})".format(
            type(self).__name__,
            ", ".join("{}={!r}".format(n, getattr(self, n))
                      for n in self._field_names))


def _plain(v):
    # Exact-type fast paths first: wire payloads are overwhelmingly
    # plain scalars/dicts (txn envelopes in Replies, request dicts in
    # Propagates) and the recursion over them is pure copying — this
    # runs per field per outgoing message. CONTRACT: a container that
    # needs no conversion is returned BY REFERENCE, so as_dict()/
    # to_dict() output must be treated as read-only below the top
    # level (a nested mutation would write through into the frozen
    # message). Tuples always convert — as_dict's list normalization
    # is what keeps local-vs-wire message equality stable.
    t = type(v)
    if t is str or t is int or t is bool or t is float or v is None:
        return v
    if t is dict:
        if not _needs_conversion(v):
            return v
        return _convert(v)
    if isinstance(v, MessageBase):
        return v.as_dict()
    if isinstance(v, (list, tuple)):
        if t is list and not _needs_conversion(v):
            return v
        return _convert(v)
    if isinstance(v, dict):
        return _convert(v)
    return v


def _convert(v):
    """Unconditional deep rebuild (the pre-fast-path behavior): used
    once a subtree is known to need conversion, so clean inner nodes
    aren't re-scanned per nesting level."""
    if isinstance(v, MessageBase):
        return v.as_dict()
    if isinstance(v, (list, tuple)):
        return [_convert(x) for x in v]
    if isinstance(v, dict):
        return {k: _convert(x) for k, x in v.items()}
    return v


_PLAIN_TYPES = (str, int, bool, float, type(None), bytes)


def _needs_conversion(v, _depth=0) -> bool:
    """True if anything inside a plain container requires _plain to
    rebuild it: a MessageBase, an exotic type, or a TUPLE (which must
    normalize to a list so deserialized copies compare equal)."""
    if _depth > 12:
        return True  # absurd nesting: fall back to the copying path
    t = type(v)
    if t in _PLAIN_TYPES:
        return False
    if t is dict:
        return any(_needs_conversion(x, _depth + 1) for x in v.values())
    if t is list:
        return any(_needs_conversion(x, _depth + 1) for x in v)
    return True  # MessageBase, tuple, or exotic type: must convert


def _hashable(v):
    """Order-insensitive hashable form: equal as_dict()s (dict equality
    ignores insertion order) must hash identically."""
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    return v
