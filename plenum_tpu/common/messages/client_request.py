"""Client request schema validation.

Reference: plenum/common/messages/client_request.py:234 —
ClientMessageValidator checks the envelope; operation schemas are
per-txn-type (registered by request handlers for static validation).
"""
from plenum_tpu.common.constants import (
    CURRENT_PROTOCOL_VERSION, IDENTIFIER, OPERATION, REQ_ID, SIGNATURE,
    SIGNATURES, TAA_ACCEPTANCE, TAA_ACCEPTANCE_DIGEST,
    TAA_ACCEPTANCE_MECHANISM, TAA_ACCEPTANCE_TIME, TXN_TYPE)
from plenum_tpu.native import try_load_ext

_fp = try_load_ext("fastpath")
from plenum_tpu.common.exceptions import InvalidClientRequest
from plenum_tpu.common.messages.fields import (
    IdentifierField, LimitedLengthStringField, MapField, NonEmptyStringField,
    NonNegativeNumberField, ProtocolVersionField, Sha256HexField,
    SignatureField, TimestampField)


class ClientTAAAcceptance:
    schema = (
        (TAA_ACCEPTANCE_DIGEST, Sha256HexField()),
        (TAA_ACCEPTANCE_MECHANISM, LimitedLengthStringField()),
        (TAA_ACCEPTANCE_TIME, NonNegativeNumberField()),
    )


class ClientMessageValidator:
    """Validates the client request envelope dict."""

    schema = (
        (IDENTIFIER, IdentifierField(nullable=True)),
        (REQ_ID, NonNegativeNumberField()),
        (OPERATION, None),  # checked structurally below
        (SIGNATURE, SignatureField(nullable=True)),
        (SIGNATURES, MapField(IdentifierField(), SignatureField(),
                              nullable=True)),
        ('protocolVersion', ProtocolVersionField(nullable=True)),
        (TAA_ACCEPTANCE, None),
    )

    def __init__(self, operation_schema_is_strict: bool = False):
        self._strict = operation_schema_is_strict

    def validate(self, dct: dict):
        # C fast path (fastpath.c validate_client_request): returns None
        # only when the envelope is PROVABLY valid; anything else falls
        # through to the Python checks below, which either pass or raise
        # with their exact error message — clients never see C-made text
        if _fp is not None:
            try:
                if _fp.validate_client_request(
                        dct, CURRENT_PROTOCOL_VERSION) is None:
                    return
            except TypeError:
                pass
        self._validate_py(dct)

    def _validate_py(self, dct: dict):
        if not isinstance(dct, dict):
            raise InvalidClientRequest(None, None, 'request must be a dict')
        identifier = dct.get(IDENTIFIER)
        req_id = dct.get(REQ_ID)
        op = dct.get(OPERATION)
        if op is None:
            raise InvalidClientRequest(identifier, req_id,
                                       'missed fields - operation')
        if not isinstance(op, dict):
            raise InvalidClientRequest(identifier, req_id,
                                       'operation must be a dict')
        if TXN_TYPE not in op:
            raise InvalidClientRequest(identifier, req_id,
                                       'missed fields in operation - type')
        if req_id is None:
            raise InvalidClientRequest(identifier, req_id,
                                       'missed fields - {}'.format(REQ_ID))
        if identifier is None and not dct.get(SIGNATURES):
            raise InvalidClientRequest(
                identifier, req_id,
                'missed fields - {} or {}'.format(IDENTIFIER, SIGNATURES))
        for name, validator in self.schema:
            if validator is None:
                continue
            val = dct.get(name)
            if val is None and (validator.nullable or name not in dct):
                continue
            err = validator.validate(val)
            if err:
                raise InvalidClientRequest(identifier, req_id,
                                           '{} ({})'.format(err, name))
        taa = dct.get(TAA_ACCEPTANCE)
        if taa is not None:
            self._validate_taa(identifier, req_id, taa)

    def _validate_taa(self, identifier, req_id, taa):
        if not isinstance(taa, dict):
            raise InvalidClientRequest(identifier, req_id,
                                       'taaAcceptance must be a dict')
        for name, validator in ClientTAAAcceptance.schema:
            if name not in taa:
                raise InvalidClientRequest(
                    identifier, req_id,
                    'missed fields in taaAcceptance - {}'.format(name))
            err = validator.validate(taa[name])
            if err:
                raise InvalidClientRequest(identifier, req_id,
                                           '{} ({})'.format(err, name))
