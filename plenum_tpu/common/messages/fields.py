"""Declarative field validators for wire messages.

Reference: plenum/common/messages/fields.py (748 LoC, ~50 validators) — these
are the wire-compat spec of the protocol. A validator's `validate(value)`
returns None when valid, else an error string.

Parity delta vs the reference's class list (enumerated r5): the
reference-only names are `FieldBase` (its ABC — `FieldValidator` here
fills that role), `LedgerInfoField` (used ONLY by the legacy
ViewChangeDone message of the pre-"plenum 2.0" view-change protocol,
node_messages.py:434 — superseded by ViewChange/NewView, which this
framework implements natively), and `TieAmongField` (no non-test usage
in the reference at all — vestige of the removed election protocol).
This module adds `AlphaNumericField`, `Base64Field`, and
`PositiveNumberField`, which the reference folds into ad-hoc checks.
Every validator used by a LIVE reference message type has an equivalent
here.
"""
import base64
import ipaddress
import json
import re
from abc import ABC, abstractmethod
from datetime import datetime
from typing import Iterable, Optional

from plenum_tpu.common.serializers.base58 import b58decode


class FieldValidator(ABC):
    optional = False

    def __init__(self, optional: bool = False, nullable: bool = False):
        self.optional = optional
        self.nullable = nullable

    def validate(self, val) -> Optional[str]:
        if val is None:
            if self.nullable:
                return None
            return 'expected not-None value'
        return self._specific_validation(val)

    @abstractmethod
    def _specific_validation(self, val) -> Optional[str]:
        ...


class AnyField(FieldValidator):
    def _specific_validation(self, val):
        return None


class BooleanField(FieldValidator):
    def _specific_validation(self, val):
        if not isinstance(val, bool):
            return 'expected types bool, got {}'.format(type(val).__name__)


class IntegerField(FieldValidator):
    def _specific_validation(self, val):
        if not isinstance(val, int) or isinstance(val, bool):
            return 'expected types int, got {}'.format(type(val).__name__)


class NonNegativeNumberField(IntegerField):
    def _specific_validation(self, val):
        err = super()._specific_validation(val)
        if err:
            return err
        if val < 0:
            return 'negative value'


class PositiveNumberField(IntegerField):
    def _specific_validation(self, val):
        err = super()._specific_validation(val)
        if err:
            return err
        if val <= 0:
            return 'non-positive value'


class NonEmptyStringField(FieldValidator):
    def _specific_validation(self, val):
        if not isinstance(val, str):
            return 'expected types str, got {}'.format(type(val).__name__)
        if not val:
            return 'empty string'


class LimitedLengthStringField(FieldValidator):
    def __init__(self, max_length: int = 256, **kwargs):
        super().__init__(**kwargs)
        assert max_length > 0
        self._max_length = max_length

    def _specific_validation(self, val):
        if not isinstance(val, str):
            return 'expected types str, got {}'.format(type(val).__name__)
        if not val:
            return 'empty string'
        if len(val) > self._max_length:
            return '{} is longer than {} symbols'.format(val[:100], self._max_length)


class FixedLengthField(FieldValidator):
    def __init__(self, length: int, **kwargs):
        super().__init__(**kwargs)
        self._length = length

    def _specific_validation(self, val):
        if not isinstance(val, str):
            return 'expected types str, got {}'.format(type(val).__name__)
        if len(val) != self._length:
            return 'should have length {}'.format(self._length)


class SignatureField(LimitedLengthStringField):
    def __init__(self, max_length: int = 512, **kwargs):
        super().__init__(max_length=max_length, **kwargs)


class RoleField(FieldValidator):
    def __init__(self, roles=("0", "2", None), **kwargs):
        kwargs.setdefault('nullable', True)
        super().__init__(**kwargs)
        self._roles = roles

    def _specific_validation(self, val):
        if val not in self._roles:
            return 'expected one of {}'.format(self._roles)


class Base58Field(FieldValidator):
    def __init__(self, byte_lengths: Iterable[int] = None, **kwargs):
        super().__init__(**kwargs)
        self.byte_lengths = tuple(byte_lengths) if byte_lengths else None

    def _specific_validation(self, val):
        if not isinstance(val, str):
            return 'expected types str, got {}'.format(type(val).__name__)
        try:
            raw = b58decode(val)
        except Exception:
            return 'should not contain chars other than base58'
        if self.byte_lengths is not None and len(raw) not in self.byte_lengths:
            return 'b58 decoded value length {} should be one of {}'.format(
                len(raw), list(self.byte_lengths))


class DestNodeField(Base58Field):
    """Node target: 16 or 32 byte base58 (verkey or abbreviated)."""
    def __init__(self, **kwargs):
        super().__init__(byte_lengths=(16, 32), **kwargs)


class DestNymField(Base58Field):
    def __init__(self, **kwargs):
        super().__init__(byte_lengths=(16, 32), **kwargs)


class IdentifierField(Base58Field):
    def __init__(self, **kwargs):
        super().__init__(byte_lengths=(16, 32), **kwargs)


class FullVerkeyField(Base58Field):
    def __init__(self, **kwargs):
        super().__init__(byte_lengths=(32,), **kwargs)


class AbbreviatedVerkeyField(FieldValidator):
    """'~' + 16-byte base58 (the abbreviated verkey form)."""
    def _specific_validation(self, val):
        if not isinstance(val, str) or not val.startswith('~'):
            return 'should start with ~'
        return Base58Field(byte_lengths=(16,))._specific_validation(val[1:])


class VerkeyField(FieldValidator):
    def _specific_validation(self, val):
        if not isinstance(val, str):
            return 'expected types str, got {}'.format(type(val).__name__)
        if val.startswith('~'):
            return AbbreviatedVerkeyField()._specific_validation(val)
        return FullVerkeyField()._specific_validation(val)


class MerkleRootField(Base58Field):
    def __init__(self, **kwargs):
        super().__init__(byte_lengths=(32,), **kwargs)


class TimestampField(FieldValidator):
    _oldest_time = 1499906902  # reference fields.py TimestampField

    def _specific_validation(self, val):
        if not isinstance(val, (int, float)) or isinstance(val, bool):
            return 'expected types int or float, got {}'.format(type(val).__name__)
        if val < self._oldest_time:
            return 'should be greater than {} but was {}'.format(
                self._oldest_time, val)


class LedgerIdField(FieldValidator):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        from plenum_tpu.common.constants import VALID_LEDGER_IDS
        self.ledger_ids = VALID_LEDGER_IDS

    def _specific_validation(self, val):
        if val not in self.ledger_ids:
            return 'expected one of {}, unknown ledger id {}'.format(
                self.ledger_ids, val)


class RequestIdentifierField(FieldValidator):
    def _specific_validation(self, val):
        if not isinstance(val, (list, tuple)) or len(val) != 2:
            return 'should be a list/tuple of 2 elements'
        err = IdentifierField()._specific_validation(val[0])
        if err:
            return err
        return NonNegativeNumberField()._specific_validation(val[1])


class IterableField(FieldValidator):
    def __init__(self, inner_field_type: FieldValidator, min_length=None,
                 max_length=None, **kwargs):
        super().__init__(**kwargs)
        self.inner_field_type = inner_field_type
        self.min_length = min_length
        self.max_length = max_length

    def _specific_validation(self, val):
        if not isinstance(val, (list, tuple)):
            return 'expected types list or tuple, got {}'.format(type(val).__name__)
        if self.min_length is not None and len(val) < self.min_length:
            return 'length should be at least {}'.format(self.min_length)
        if self.max_length is not None and len(val) > self.max_length:
            return 'length should be at most {}'.format(self.max_length)
        for v in val:
            err = self.inner_field_type.validate(v)
            if err:
                return err


class MapField(FieldValidator):
    def __init__(self, key_field: FieldValidator, value_field: FieldValidator,
                 **kwargs):
        super().__init__(**kwargs)
        self.key_field = key_field
        self.value_field = value_field

    def _specific_validation(self, val):
        if not isinstance(val, dict):
            return 'expected types dict, got {}'.format(type(val).__name__)
        for k, v in val.items():
            err = self.key_field.validate(k)
            if err:
                return err
            err = self.value_field.validate(v)
            if err:
                return err


class AnyMapField(FieldValidator):
    def _specific_validation(self, val):
        if not isinstance(val, dict):
            return 'expected types dict, got {}'.format(type(val).__name__)


class NetworkPortField(FieldValidator):
    def _specific_validation(self, val):
        if not isinstance(val, int) or isinstance(val, bool):
            return 'expected types int, got {}'.format(type(val).__name__)
        if val <= 0 or val > 65535:
            return 'network port out of the range 1-65535'


class NetworkIpAddressField(FieldValidator):
    def _specific_validation(self, val):
        if not isinstance(val, str):
            return 'expected types str, got {}'.format(type(val).__name__)
        invalid = ('0.0.0.0', '0:0:0:0:0:0:0:0', '::')
        try:
            ipaddress.ip_address(val)
        except ValueError:
            return 'invalid network ip address ({})'.format(val)
        if val in invalid:
            return 'invalid network ip address ({})'.format(val)


class ChooseField(FieldValidator):
    def __init__(self, values, **kwargs):
        super().__init__(**kwargs)
        self._possible_values = tuple(values)

    def _specific_validation(self, val):
        if val not in self._possible_values:
            return 'expected one of {}, unknown value {}'.format(
                self._possible_values, val)


class ConstantField(FieldValidator):
    """Exactly one permitted value (reference fields.py ConstantField)."""

    def __init__(self, value, **kwargs):
        super().__init__(**kwargs)
        self._value = value

    def _specific_validation(self, val):
        if val != self._value:
            return 'has to be equal {}'.format(self._value)


class DatetimeStringField(FieldValidator):
    """ISO-8601 datetime string (reference fields.py
    DatetimeStringField — TAA acceptance-mechanism timestamps)."""

    def _specific_validation(self, val):
        if not isinstance(val, str):
            return 'expected types str, got {}'.format(type(val).__name__)
        try:
            datetime.fromisoformat(val)
        except ValueError:
            return 'datetime {} is not valid ISO 8601'.format(val)


class HexField(FieldValidator):
    def __init__(self, length=None, **kwargs):
        super().__init__(**kwargs)
        self._length = length

    def _specific_validation(self, val):
        if not isinstance(val, str):
            return 'expected types str, got {}'.format(type(val).__name__)
        try:
            int(val, 16)
        except ValueError:
            return 'invalid hex number {}'.format(val[:64])
        if self._length is not None and len(val) != self._length:
            return 'length should be {} length'.format(self._length)


class Sha256HexField(HexField):
    def __init__(self, **kwargs):
        super().__init__(length=64, **kwargs)


class JsonField(LimitedLengthStringField):
    def __init__(self, max_length: int = 5 * 1024, **kwargs):
        super().__init__(max_length=max_length, **kwargs)

    def _specific_validation(self, val):
        err = super()._specific_validation(val)
        if err:
            return err
        try:
            json.loads(val)
        except json.JSONDecodeError:
            return 'should be a valid JSON string'


class SerializedValueField(FieldValidator):
    def _specific_validation(self, val):
        if not isinstance(val, (str, bytes)):
            return 'expected types str or bytes, got {}'.format(type(val).__name__)
        if not val:
            return 'empty serialized value'


class Base64Field(FieldValidator):
    def _specific_validation(self, val):
        try:
            base64.b64decode(val, validate=True)
        except Exception:
            return 'should be a valid base64 string'


class VersionField(FieldValidator):
    """Dotted numeric version, 1-3 components (reference fields.py)."""
    def __init__(self, components_number=(3,), **kwargs):
        super().__init__(**kwargs)
        self._comp_num = components_number

    def _specific_validation(self, val):
        if not isinstance(val, str):
            return 'expected types str, got {}'.format(type(val).__name__)
        parts = val.split('.')
        if len(parts) not in self._comp_num:
            return 'version consists of {} components, but it should contain {}'\
                .format(len(parts), self._comp_num)
        for p in parts:
            if not p.isdigit():
                return 'version component should contain only digits'


class ProtocolVersionField(FieldValidator):
    def __init__(self, **kwargs):
        kwargs.setdefault('nullable', True)
        super().__init__(**kwargs)

    def _specific_validation(self, val):
        from plenum_tpu.common.constants import CURRENT_PROTOCOL_VERSION
        if not isinstance(val, int) or isinstance(val, bool):
            return 'expected types int, got {}'.format(type(val).__name__)
        if val != CURRENT_PROTOCOL_VERSION:
            return 'Unknown protocol version value {}'.format(val)


class BlsMultiSignatureValueField(FieldValidator):
    """(ledger_id, state_root, pool_state_root, txn_root, timestamp)
    (reference fields.py BlsMultiSignatureValueField)."""
    def _specific_validation(self, val):
        if not isinstance(val, (list, tuple)) or len(val) != 5:
            return 'should be a list of 5 elements'
        lid, state_root, pool_root, txn_root, ts = val
        err = LedgerIdField()._specific_validation(lid)
        if err:
            return err
        for root in (state_root, pool_root, txn_root):
            err = MerkleRootField()._specific_validation(root)
            if err:
                return err
        return TimestampField()._specific_validation(ts)


class BlsMultiSignatureField(FieldValidator):
    """(signature, participants, value) (reference fields.py)."""
    def _specific_validation(self, val):
        if not isinstance(val, (list, tuple)) or len(val) != 3:
            return 'should be a list of 3 elements'
        sig, participants, value = val
        err = NonEmptyStringField()._specific_validation(sig)
        if err:
            return err
        err = IterableField(NonEmptyStringField(),
                            min_length=1)._specific_validation(participants)
        if err:
            return err
        return BlsMultiSignatureValueField()._specific_validation(value)


class BatchIDField(FieldValidator):
    """(view_no, pp_view_no, pp_seq_no, pp_digest) (reference fields.py)."""
    def _specific_validation(self, val):
        if not isinstance(val, (list, tuple)) or len(val) != 4:
            return 'should be a list of 4 elements'
        for n in val[:3]:
            err = NonNegativeNumberField()._specific_validation(n)
            if err:
                return err
        return NonEmptyStringField()._specific_validation(val[3])


class ViewChangeField(FieldValidator):
    """(frm, view_change_digest)."""
    def _specific_validation(self, val):
        if not isinstance(val, (list, tuple)) or len(val) != 2:
            return 'should be a list of 2 elements'
        err = NonEmptyStringField()._specific_validation(val[0])
        if err:
            return err
        return NonEmptyStringField()._specific_validation(val[1])


class StringifiedNonNegativeNumberField(FieldValidator):
    def _specific_validation(self, val):
        if isinstance(val, int) and not isinstance(val, bool):
            return NonNegativeNumberField()._specific_validation(val)
        if isinstance(val, str):
            if not val.isdigit():
                return 'stringified int expected, but was {}'.format(val[:32])
            return None
        return 'expected types str or int, got {}'.format(type(val).__name__)


class TxnSeqNoField(PositiveNumberField):
    pass


class MessageField(FieldValidator):
    """A nested MessageBase instance (or its dict form)."""
    def __init__(self, message_type=None, **kwargs):
        super().__init__(**kwargs)
        self._message_type = message_type

    def _specific_validation(self, val):
        if self._message_type is not None and isinstance(val, self._message_type):
            return None
        if isinstance(val, dict):
            return None
        return 'expected a message or dict, got {}'.format(type(val).__name__)


class AnyValueField(FieldValidator):
    def __init__(self, **kwargs):
        kwargs.setdefault('nullable', True)
        super().__init__(**kwargs)

    def _specific_validation(self, val):
        return None


class AlphaNumericField(FieldValidator):
    _pattern = re.compile(r'^[A-Za-z0-9]+$')

    def _specific_validation(self, val):
        if not isinstance(val, str):
            return 'expected types str, got {}'.format(type(val).__name__)
        if not self._pattern.match(val):
            return 'should contain only alphanumeric characters'
