"""Wire-name → message-class registry for deserialization.

Reference: plenum/common/messages/node_message_factory.py.
"""
from typing import Dict, Type

from plenum_tpu.common.constants import OP_FIELD_NAME
from plenum_tpu.common.exceptions import InvalidNodeOp, MissingNodeOp
from plenum_tpu.common.messages.message_base import MessageBase
from plenum_tpu.common.messages import node_messages


class MessageFactory:
    def __init__(self, *modules):
        self._classes: Dict[str, Type[MessageBase]] = {}
        for module in modules:
            for attr in vars(module).values():
                if (isinstance(attr, type) and issubclass(attr, MessageBase)
                        and attr is not MessageBase
                        and attr.typename is not None):
                    self._classes[attr.typename] = attr

    def get_type(self, typename: str) -> Type[MessageBase]:
        cls = self._classes.get(typename)
        if cls is None:
            raise InvalidNodeOp("unknown message type {}".format(typename))
        return cls

    def get_instance(self, **msg_dict) -> MessageBase:
        typename = msg_dict.pop(OP_FIELD_NAME, None)
        if typename is None:
            raise MissingNodeOp("missed op field")
        cls = self.get_type(typename)
        known = {name for name, _ in cls.schema}
        kwargs = {k: _detuple(v) for k, v in msg_dict.items() if k in known}
        return cls(**kwargs)

    def set_message_class(self, cls: Type[MessageBase]):
        self._classes[cls.typename] = cls


def _detuple(v):
    if isinstance(v, tuple):
        return [_detuple(x) for x in v]
    if isinstance(v, list):
        return [_detuple(x) for x in v]
    return v


node_message_factory = MessageFactory(node_messages)
