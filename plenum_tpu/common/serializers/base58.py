"""Base58 codec (bitcoin alphabet) — no external dependency available, so
implemented here. Used for merkle/state roots and DIDs (reference:
common/serializers/base58_serializer.py)."""

ALPHABET = b'123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz'
_INDEX = {c: i for i, c in enumerate(ALPHABET)}


def b58encode(data: bytes) -> str:
    n = int.from_bytes(data, 'big')
    out = bytearray()
    while n > 0:
        n, r = divmod(n, 58)
        out.append(ALPHABET[r])
    # preserve leading zero bytes
    pad = 0
    for b in data:
        if b == 0:
            pad += 1
        else:
            break
    return (ALPHABET[0:1] * pad + bytes(reversed(out))).decode('ascii')


def b58decode(s) -> bytes:
    if isinstance(s, bytes):
        s = s.decode('ascii')
    n = 0
    for ch in s.encode('ascii'):
        try:
            n = n * 58 + _INDEX[ch]
        except KeyError:
            raise ValueError("Invalid base58 character: {!r}".format(chr(ch)))
    full = n.to_bytes((n.bit_length() + 7) // 8, 'big') if n else b''
    pad = 0
    for ch in s:
        if ch == '1':
            pad += 1
        else:
            break
    return b'\x00' * pad + full


def is_b58(s, length: int = None) -> bool:
    try:
        raw = b58decode(s)
    except Exception:
        return False
    return length is None or len(raw) == length
