"""Base58 codec (bitcoin alphabet) — no external dependency available, so
implemented here. Used for merkle/state roots and DIDs (reference:
common/serializers/base58_serializer.py)."""

ALPHABET = b'123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz'
_INDEX = {c: i for i, c in enumerate(ALPHABET)}
_A = ALPHABET.decode('ascii')
# chunked conversion: peel 10 digits (58^10 < 2^59) per bigint divmod —
# ~10x fewer bigint ops than digit-at-a-time (hot path: every merkle /
# state root crossing a serialization boundary goes through here)
_B58_10 = 58 ** 10
_DIGITS10 = {}


def _enc10(r: int) -> str:
    """10-digit base58 block with leading '1' padding, memoized."""
    got = _DIGITS10.get(r)
    if got is None:
        out = []
        v = r
        for _ in range(10):
            v, d = divmod(v, 58)
            out.append(_A[d])
        got = ''.join(reversed(out))
        if len(_DIGITS10) < 1 << 16:
            _DIGITS10[r] = got
    return got


# hash-sized payloads repeat heavily (audit-path nodes shared by every
# Reply in a batch; roots re-encoded per peer), so memoize those
_ENC32 = {}


def b58encode(data: bytes) -> str:
    if type(data) is bytes and len(data) == 32:
        got = _ENC32.get(data)
        if got is not None:
            return got
        out = _encode_backend(data)
        if len(_ENC32) >= 1 << 16:
            for stale in list(_ENC32)[:1 << 15]:
                del _ENC32[stale]
        _ENC32[data] = out
        return out
    return _encode_backend(data)


def _b58encode_raw(data: bytes) -> str:
    n = int.from_bytes(data, 'big')
    blocks = []
    while n >= _B58_10:
        n, r = divmod(n, _B58_10)
        blocks.append(_enc10(r))
    head = ''
    while n > 0:
        n, d = divmod(n, 58)
        head = _A[d] + head
    body = head + ''.join(reversed(blocks))
    # preserve leading zero bytes
    pad = 0
    for b in data:
        if b == 0:
            pad += 1
        else:
            break
    return '1' * pad + body


def _b58decode_py(s) -> bytes:
    if isinstance(s, bytes):
        s = s.decode('ascii')
    n = 0
    for ch in s.encode('ascii'):
        try:
            n = n * 58 + _INDEX[ch]
        except KeyError:
            raise ValueError("Invalid base58 character: {!r}".format(chr(ch)))
    full = n.to_bytes((n.bit_length() + 7) // 8, 'big') if n else b''
    pad = 0
    for ch in s:
        if ch == '1':
            pad += 1
        else:
            break
    return b'\x00' * pad + full


# native backend when the compiler is available (byte-identical output;
# tests/test_fastpath_native.py cross-checks both directions)
from plenum_tpu.native import try_load_ext as _try_load_ext

_fp = _try_load_ext("fastpath")
if _fp is not None:
    _encode_backend = _fp.b58encode
    b58decode = _fp.b58decode
else:
    _encode_backend = _b58encode_raw
    b58decode = _b58decode_py


def is_b58(s, length: int = None) -> bool:
    try:
        raw = b58decode(s)
    except Exception:
        return False
    return length is None or len(raw) == length
