"""Flat zero-copy wire format for the 3PC / propagate money path.

Every THREE_PC_BATCH envelope used to round-trip each inner vote
through per-field msgpack of a Python message object: one canonical
``_sort_deep`` + packb per vote on the send side, one
``node_message_factory.get_instance`` (full schema validation + object
construction) per vote on the receive side — only for the columnar
intake to strip the objects back down to digest/view/seq columns.
This module replaces that with ONE pack and ONE parse per envelope:

* **PREPARE / COMMIT votes become contiguous typed columns** — instId,
  viewNo, ppSeqNo (little-endian unsigned ints), ppTime (f64), digest
  (32 raw bytes, hex-decoded) packed as flat buffers and parsed back
  as ``np.frombuffer`` views over the envelope bytes. No intermediate
  Python message objects exist on the receive path: the parsed columns
  go straight into the ordering service's vectorized precheck,
  ``digest_match_mask`` and the incremental ``_prepare_vote_count`` /
  ``_commit_vote_count`` counters; a typed ``Prepare``/``Commit``
  object is materialized ONLY for the votes that actually enter a vote
  store, a stash bucket, or a suspicion report.
* **Ragged payloads ride the same envelope as length-prefixed
  sections**: PRE-PREPAREs (ragged reqIdr) and PROPAGATE request
  payloads are stored as msgpack blobs behind a u32 offset table —
  still one wire message, one parse, with per-item unpacking deferred
  to the consumer.
* **The typed-object path stays as validated fallback** — the codec
  slots into the serializer registry boundary exactly like
  MsgPackSerializer does; ``Config.FLAT_WIRE = False`` (or an
  installed adversary tap) restores the per-message / THREE_PC_BATCH
  wire unchanged, and ``to_legacy_messages`` re-materializes a flat
  envelope into typed messages so fault-injection taps keep seeing
  per-type granularity.

Envelope layout (all integers little-endian; see docs/wire.md):

    magic   2 bytes  b"PW"
    version u8       1
    nsect   u8       number of sections
    section*  kind u8 | count u32 | payload_len u32 | payload

Section payloads:

    PREPARE (kind 1), n votes:
        instId   n × u32
        viewNo   n × u64
        ppSeqNo  n × u64
        ppTime   n × f64
        digest   n × 32 bytes      (raw sha256; lowercase-hex decode)
        flags    n × u8            bit0 stateRootHash present
                                   bit1 txnRootHash present
                                   bit2 auditTxnRootHash present
                                   bit3 digest in string table (not a
                                        canonical 64-char hex digest)
                                   bit4 ppTime was an int
        offsets  (4n+1) × u32      string-table boundaries, column-
                                   major: state roots, txn roots,
                                   audit roots, odd digests
        blob     offsets[-1] bytes

    COMMIT (kind 2), n votes:
        instId   n × u32
        viewNo   n × u64
        ppSeqNo  n × u64
        flags    n × u8            bit0 blsSig present
                                   bit1 blsSigs present
        offsets  (2n+1) × u32      blsSig strings, blsSigs msgpack
        blob     offsets[-1] bytes

    PREPREPARE (kind 3), n messages:
        offsets  (n+1) × u32
        blob                        canonical msgpack of to_dict()

    PROPAGATE (kind 4), n requests:
        offsets  (2n+1) × u32      request msgpack blobs, client ids
        blob

    TRACE (kind 5, version 2 only), advisory causal stamp:
        name_len u8 | origin utf-8 (≤ 64 bytes)
        flush_seq u64              sender's per-seam flush counter
        perf_ts   f64              sender perf-counter at flush
        wall_ts   f64              sender wall clock at flush

The TRACE section is **advisory observability context**: it is decoded
by :func:`decode_trace_stamp` into ``ParsedEnvelope.stamp`` and never
enters ``ParsedEnvelope.sections`` — consensus consumers iterate
sections and cannot see it. Any CONTENT problem inside the stamp
(bad length, non-finite floats, undecodable name) yields ``stamp =
None`` and the rest of the envelope parses normally; only the shared
structural framing (payload bounds) can fail the envelope. Version 1
envelopes reject kind 5 like any unknown kind, so the golden byte
vectors for version 1 are unchanged. plenum-lint PT015 enforces that
no consensus path can reach the stamp decode.

A structurally invalid envelope (bad magic/version, truncated or
over-length payload, non-monotonic offsets, counts that do not fit)
raises :class:`FlatWireError` — the node handler converts that into a
per-sender suspicion and drops the envelope; it can never crash the
prod loop. Entry-LEVEL garbage (a root string failing schema
validation, an unparseable PRE-PREPARE blob) costs only that entry,
exactly like a bad entry in a legacy THREE_PC_BATCH.
"""
from __future__ import annotations

import logging
import math
import struct
from typing import List, Optional, Tuple

import msgpack
import numpy as np

logger = logging.getLogger(__name__)

MAGIC = b"PW"
VERSION = 1
# version 2 = version 1 + an optional advisory TRACE section; the
# sender only bumps the byte when a stamp actually rides the envelope,
# so version-1 peers (and the version-1 golden vectors) never see it
VERSION_TRACE = 2

KIND_PREPARE = 1
KIND_COMMIT = 2
KIND_PREPREPARE = 3
KIND_PROPAGATE = 4
KIND_TRACE = 5

# advisory-stamp bounds: origin name capped (encode truncates, decode
# rejects over-length into stamp=None) so the section can never exceed
# 1 + 64 + 8 + 8 + 8 = 89 payload bytes
TRACE_NAME_MAX = 64

# PREPARE flag bits
F_STATE = 1
F_TXN = 2
F_AUDIT = 4
F_ODD_DIGEST = 8
F_TIME_INT = 16
# COMMIT flag bits
F_BLSSIG = 1
F_BLSSIGS = 2

# structural sanity cap: votes per section. The senders chunk far below
# this (ThreePCOutbox.BATCH_LIMIT=300 / Propagator.BATCH_LIMIT=200);
# the cap only bounds what a hostile count field can make the parser
# believe before the fits-in-payload check runs.
SECTION_COUNT_MAX = 1 << 16

_U32 = np.dtype("<u4")
_U64 = np.dtype("<u8")
_F64 = np.dtype("<f8")
_U8 = np.dtype("u1")


class FlatWireError(Exception):
    """Structurally invalid flat envelope (attributable to the sender)."""


class FlatWireUnencodable(Exception):
    """A message whose field values the flat layout cannot carry
    (e.g. an out-of-range integer); the sender falls back to the
    typed-object wire for that chunk."""


def _serializer():
    # late import: this module must stay importable without the full
    # serializer registry loaded (and vice versa)
    from plenum_tpu.common.serializers.serializers import MsgPackSerializer
    return MsgPackSerializer()


def _check_uint(value, bits: int, what: str) -> int:
    if not isinstance(value, int) or isinstance(value, bool) \
            or value < 0 or value >> bits:
        raise FlatWireUnencodable(
            "%s=%r does not fit u%d" % (what, value, bits))
    return value


def _ragged_table(columns: List[List[bytes]]) -> Tuple[bytes, bytes]:
    """Column-major string table → (offsets_bytes, blob). ``columns``
    is a list of per-item byte-string lists, all the same length."""
    pieces: List[bytes] = []
    for col in columns:
        pieces.extend(col)
    lens = np.fromiter((len(p) for p in pieces), dtype=np.int64,
                       count=len(pieces))
    offs = np.zeros(len(pieces) + 1, dtype=_U32)
    if len(pieces):
        total = np.cumsum(lens)
        if int(total[-1]) >> 32:
            raise FlatWireUnencodable("string table exceeds u32 offsets")
        offs[1:] = total
    return offs.tobytes(), b"".join(pieces)


class TraceStamp:
    """Advisory causal stamp carried by a version-2 envelope (and by
    the typed THREE_PC_BATCH / PROPAGATE fallback as a plain list).
    Pure data — the timestamp VALUES are produced at the sender's
    flush seam and passed in as arguments; nothing in this module
    reads a clock."""

    __slots__ = ("origin", "seq", "perf_ts", "wall_ts")

    def __init__(self, origin: str, seq: int, perf_ts: float,
                 wall_ts: float):
        self.origin = origin
        self.seq = seq
        self.perf_ts = perf_ts
        self.wall_ts = wall_ts

    def as_list(self) -> list:
        """Typed-fallback wire form (rides a nullable message field)."""
        return [self.origin, self.seq, self.perf_ts, self.wall_ts]

    @classmethod
    def from_wire(cls, value) -> Optional["TraceStamp"]:
        """Typed-fallback decode: ANY content problem → None (the
        stamp is advisory; it can never fail the carrying message)."""
        try:
            origin, seq, perf_ts, wall_ts = value
            origin = str(origin)
            if len(origin.encode("utf-8")) > TRACE_NAME_MAX:
                return None
            seq = int(seq)
            perf_ts = float(perf_ts)
            wall_ts = float(wall_ts)
            if seq < 0 or seq >> 64 \
                    or not math.isfinite(perf_ts) \
                    or not math.isfinite(wall_ts):
                return None
            return cls(origin, seq, perf_ts, wall_ts)
        except Exception:
            return None

    def __repr__(self):
        return ("TraceStamp(origin=%r, seq=%d, perf_ts=%r, wall_ts=%r)"
                % (self.origin, self.seq, self.perf_ts, self.wall_ts))


def encode_trace_stamp(origin: str, flush_seq: int, perf_ts: float,
                       wall_ts: float) -> bytes:
    """TRACE section payload. Deliberately total: the stamp is
    advisory, so an odd origin name or counter is clamped rather than
    failing the envelope it rides on."""
    name = str(origin).encode("utf-8", "replace")[:TRACE_NAME_MAX]
    return b"".join((
        bytes((len(name),)), name,
        (int(flush_seq) & ((1 << 64) - 1)).to_bytes(8, "little"),
        struct.pack("<dd", float(perf_ts), float(wall_ts))))


def decode_trace_stamp(payload: bytes) -> Optional[TraceStamp]:
    """TRACE section payload → TraceStamp, or None on ANY content
    problem — the stamp is advisory and must never fail the envelope."""
    try:
        if len(payload) < 1:
            return None
        nl = payload[0]
        if nl > TRACE_NAME_MAX or len(payload) != 1 + nl + 24:
            return None
        origin = payload[1:1 + nl].decode("utf-8")
        seq = int.from_bytes(payload[1 + nl:9 + nl], "little")
        perf_ts, wall_ts = struct.unpack_from("<dd", payload, 9 + nl)
        if not math.isfinite(perf_ts) or not math.isfinite(wall_ts):
            return None
        return TraceStamp(origin, seq, perf_ts, wall_ts)
    except Exception:
        return None


# ================================================================ encode

def encode_prepares(msgs) -> bytes:
    """PREPARE section payload from typed Prepare messages."""
    n = len(msgs)
    inst = np.empty(n, dtype=_U32)
    view = np.empty(n, dtype=_U64)
    seq = np.empty(n, dtype=_U64)
    tim = np.empty(n, dtype=_F64)
    digest = np.zeros((n, 32), dtype=_U8)
    flags = np.zeros(n, dtype=_U8)
    states: List[bytes] = []
    txns: List[bytes] = []
    audits: List[bytes] = []
    odds: List[bytes] = []
    for i, m in enumerate(msgs):
        inst[i] = _check_uint(m.instId, 32, "instId")
        view[i] = _check_uint(m.viewNo, 64, "viewNo")
        seq[i] = _check_uint(m.ppSeqNo, 64, "ppSeqNo")
        f = 0
        t = m.ppTime
        if isinstance(t, int) and not isinstance(t, bool):
            if int(float(t)) != t:
                raise FlatWireUnencodable("ppTime int exceeds f64")
            f |= F_TIME_INT
        tim[i] = float(t)
        d = m.digest
        hb = None
        if isinstance(d, str) and len(d) == 64:
            try:
                hb = bytes.fromhex(d)
            except ValueError:
                hb = None
            if hb is not None and hb.hex() != d:   # non-canonical hex
                hb = None
        if hb is not None:
            digest[i] = np.frombuffer(hb, dtype=_U8)
            odds.append(b"")
        else:
            f |= F_ODD_DIGEST
            odds.append(str(d).encode("utf-8"))
        for attr, bit, col in (("stateRootHash", F_STATE, states),
                               ("txnRootHash", F_TXN, txns),
                               ("auditTxnRootHash", F_AUDIT, audits)):
            v = getattr(m, attr, None)
            if v is None:
                col.append(b"")
            else:
                f |= bit
                col.append(str(v).encode("utf-8"))
        flags[i] = f
    offs, blob = _ragged_table([states, txns, audits, odds])
    return b"".join((inst.tobytes(), view.tobytes(), seq.tobytes(),
                     tim.tobytes(), digest.tobytes(), flags.tobytes(),
                     offs, blob))


def encode_commits(msgs) -> bytes:
    """COMMIT section payload from typed Commit messages."""
    n = len(msgs)
    inst = np.empty(n, dtype=_U32)
    view = np.empty(n, dtype=_U64)
    seq = np.empty(n, dtype=_U64)
    flags = np.zeros(n, dtype=_U8)
    sigs: List[bytes] = []
    sig_maps: List[bytes] = []
    for i, m in enumerate(msgs):
        inst[i] = _check_uint(m.instId, 32, "instId")
        view[i] = _check_uint(m.viewNo, 64, "viewNo")
        seq[i] = _check_uint(m.ppSeqNo, 64, "ppSeqNo")
        f = 0
        sig = getattr(m, "blsSig", None)
        if sig is None:
            sigs.append(b"")
        else:
            f |= F_BLSSIG
            sigs.append(str(sig).encode("utf-8"))
        sig_map = getattr(m, "blsSigs", None)
        if sig_map is None:
            sig_maps.append(b"")
        else:
            f |= F_BLSSIGS
            sig_maps.append(msgpack.packb(dict(sig_map),
                                          use_bin_type=True))
        flags[i] = f
    offs, blob = _ragged_table([sigs, sig_maps])
    return b"".join((inst.tobytes(), view.tobytes(), seq.tobytes(),
                     flags.tobytes(), offs, blob))


def encode_blobs(blobs: List[bytes]) -> bytes:
    """Length-prefixed-section payload (PREPREPARE / one column of
    PROPAGATE encoded elsewhere): u32 offset table + concatenated
    blobs."""
    offs, blob = _ragged_table([list(blobs)])
    return offs + blob


def encode_preprepares(msgs) -> bytes:
    ser = _serializer()
    return encode_blobs([ser.serialize(m.to_dict()) for m in msgs])


def encode_propagates(raw_requests: List[bytes],
                      clients: List[str]) -> bytes:
    """PROPAGATE section payload: already-packed request payload blobs
    (the sender packs each request exactly once — the same bytes feed
    the size budget) + client-id strings ("" = unknown)."""
    offs, blob = _ragged_table(
        [list(raw_requests),
         [(c or "").encode("utf-8") for c in clients]])
    return offs + blob


def build_envelope(sections: List[Tuple[int, int, bytes]],
                   trace: Optional[bytes] = None) -> bytes:
    """(kind, count, payload) sections → one flat envelope. ``trace``
    is an already-encoded TRACE payload (encode_trace_stamp) — when
    present the envelope is version 2 and the stamp rides as a
    trailing advisory section; when absent the bytes are version 1,
    identical to the pre-trace wire (golden vectors pin this)."""
    version = VERSION if trace is None else VERSION_TRACE
    nsect = len(sections) + (0 if trace is None else 1)
    if nsect > 255:
        raise FlatWireUnencodable("too many sections")
    out = [MAGIC, bytes((version, nsect))]
    for kind, count, payload in sections:
        out.append(bytes((kind,)))
        out.append(int(count).to_bytes(4, "little"))
        out.append(len(payload).to_bytes(4, "little"))
        out.append(payload)
    if trace is not None:
        out.append(bytes((KIND_TRACE,)))
        out.append((1).to_bytes(4, "little"))
        out.append(len(trace).to_bytes(4, "little"))
        out.append(trace)
    return b"".join(out)


def encode_three_pc(pps, prepares, commits,
                    trace: Optional[bytes] = None) -> bytes:
    """One sender's tick of broadcast 3PC votes → one flat envelope.
    Raises FlatWireUnencodable when a field value cannot ride the flat
    layout (the caller falls back to the typed envelope)."""
    sections = []
    if pps:
        sections.append((KIND_PREPREPARE, len(pps),
                         encode_preprepares(pps)))
    if prepares:
        sections.append((KIND_PREPARE, len(prepares),
                         encode_prepares(prepares)))
    if commits:
        sections.append((KIND_COMMIT, len(commits),
                         encode_commits(commits)))
    return build_envelope(sections, trace=trace)


def encode_propagate_envelope(raw_requests: List[bytes],
                              clients: List[str],
                              trace: Optional[bytes] = None) -> bytes:
    return build_envelope([
        (KIND_PROPAGATE, len(raw_requests),
         encode_propagates(raw_requests, clients))], trace=trace)


# ================================================================ parse

class _Reader:
    """Bounds-checked cursor over the envelope bytes; every numpy view
    aliases the original buffer (zero copies until materialization)."""

    __slots__ = ("buf", "pos", "end")

    def __init__(self, buf: bytes, pos: int, end: int):
        self.buf = buf
        self.pos = pos
        self.end = end

    def take(self, nbytes: int) -> int:
        start = self.pos
        if nbytes < 0 or start + nbytes > self.end:
            raise FlatWireError("section payload truncated")
        self.pos = start + nbytes
        return start

    def view(self, dtype: np.dtype, count: int) -> np.ndarray:
        start = self.take(count * dtype.itemsize)
        return np.frombuffer(self.buf, dtype=dtype, count=count,
                             offset=start)

    def view2d(self, count: int, width: int) -> np.ndarray:
        start = self.take(count * width)
        return np.frombuffer(self.buf, dtype=_U8, count=count * width,
                             offset=start).reshape(count, width)


def _ragged_views(r: _Reader, n_pieces: int):
    """Offset table + blob for a section's string table → (offs view,
    blob_start). Offsets must start at 0, be monotone, and the blob
    must consume the rest of the section exactly."""
    offs = r.view(_U32, n_pieces + 1)
    # unsigned elementwise compare: one fused pass, no temporaries
    # beyond the bool array (diff+astype measured 3x the whole parse
    # at wire-typical sizes)
    if offs[0] != 0 or bool((offs[:-1] > offs[1:]).any()):
        raise FlatWireError("non-monotonic string-table offsets")
    blob_len = int(offs[-1])
    blob_start = r.take(blob_len)
    if r.pos != r.end:
        raise FlatWireError("trailing bytes after section blob")
    return offs, blob_start


class _Section:
    __slots__ = ("n", "_buf", "_offs", "_blob0")

    def _piece(self, col: int, i: int) -> bytes:
        """String-table piece for column ``col``, item ``i``."""
        p = col * self.n + i
        a = self._blob0 + int(self._offs[p])
        b = self._blob0 + int(self._offs[p + 1])
        return self._buf[a:b]


class PrepareColumns(_Section):
    """Parsed PREPARE columns: numpy views over the envelope."""

    kind = KIND_PREPARE
    __slots__ = ("inst", "view", "seq", "time", "digest", "flags")

    def __init__(self, r: _Reader, n: int):
        self.n = n
        self._buf = r.buf
        self.inst = r.view(_U32, n)
        self.view = r.view(_U64, n)
        self.seq = r.view(_U64, n)
        self.time = r.view(_F64, n)
        self.digest = r.view2d(n, 32)
        self.flags = r.view(_U8, n)
        self._offs, self._blob0 = _ragged_views(r, 4 * n)

    def digest_hex(self, i: int) -> str:
        if self.flags[i] & F_ODD_DIGEST:
            return self._piece(3, i).decode("utf-8", "replace")
        return self.digest[i].tobytes().hex()

    def _root(self, i: int, col: int, bit: int) -> Optional[str]:
        if not (self.flags[i] & bit):
            return None
        return self._piece(col, i).decode("utf-8")

    def materialize(self, i: int):
        """Typed, fully validated Prepare for vote-store / stash /
        suspicion insertion; None (logged) when the entry fails schema
        validation — the same fate a bad entry meets on the typed
        envelope path."""
        from plenum_tpu.common.messages.node_messages import Prepare
        t = float(self.time[i])
        if self.flags[i] & F_TIME_INT:
            t = int(t)
        try:
            return Prepare(
                instId=int(self.inst[i]),
                viewNo=int(self.view[i]),
                ppSeqNo=int(self.seq[i]),
                ppTime=t,
                digest=self.digest_hex(i),
                stateRootHash=self._root(i, 0, F_STATE),
                txnRootHash=self._root(i, 1, F_TXN),
                auditTxnRootHash=self._root(i, 2, F_AUDIT))
        except Exception as e:
            logger.warning("flat wire: bad PREPARE entry: %s", e)
            return None


class CommitColumns(_Section):
    """Parsed COMMIT columns: numpy views over the envelope."""

    kind = KIND_COMMIT
    __slots__ = ("inst", "view", "seq", "flags")

    def __init__(self, r: _Reader, n: int):
        self.n = n
        self._buf = r.buf
        self.inst = r.view(_U32, n)
        self.view = r.view(_U64, n)
        self.seq = r.view(_U64, n)
        self.flags = r.view(_U8, n)
        self._offs, self._blob0 = _ragged_views(r, 2 * n)

    def materialize(self, i: int):
        from plenum_tpu.common.messages.node_messages import Commit
        sig = None
        sig_map = None
        try:
            if self.flags[i] & F_BLSSIG:
                sig = self._piece(0, i).decode("utf-8")
            if self.flags[i] & F_BLSSIGS:
                sig_map = msgpack.unpackb(self._piece(1, i), raw=False,
                                          strict_map_key=False)
            return Commit(instId=int(self.inst[i]),
                          viewNo=int(self.view[i]),
                          ppSeqNo=int(self.seq[i]),
                          blsSig=sig, blsSigs=sig_map)
        except Exception as e:
            logger.warning("flat wire: bad COMMIT entry: %s", e)
            return None


class BlobSection(_Section):
    """Length-prefixed ragged section (PREPREPARE)."""

    kind = KIND_PREPREPARE
    __slots__ = ()

    def __init__(self, r: _Reader, n: int):
        self.n = n
        self._buf = r.buf
        self._offs, self._blob0 = _ragged_views(r, n)

    def raw(self, i: int) -> bytes:
        return self._piece(0, i)

    def materialize(self, i: int):
        """→ typed PrePrepare (validated) or None on a bad entry."""
        from plenum_tpu.common.messages.message_factory import (
            node_message_factory)
        from plenum_tpu.common.messages.node_messages import PrePrepare
        try:
            d = msgpack.unpackb(self.raw(i), raw=False,
                                strict_map_key=False)
            msg = node_message_factory.get_instance(**d)
        except Exception as e:
            logger.warning("flat wire: bad PREPREPARE entry: %s", e)
            return None
        if not isinstance(msg, PrePrepare):
            logger.warning("flat wire: non-PREPREPARE entry %s in "
                           "PREPREPARE section — dropped",
                           type(msg).__name__)
            return None
        return msg


class PropagateColumns(_Section):
    """Parsed PROPAGATE section: per-item msgpack request blobs +
    client-id strings, unpacked lazily by the consumer."""

    kind = KIND_PROPAGATE
    __slots__ = ()

    def __init__(self, r: _Reader, n: int):
        self.n = n
        self._buf = r.buf
        self._offs, self._blob0 = _ragged_views(r, 2 * n)

    def request_raw(self, i: int) -> bytes:
        return self._piece(0, i)

    def request(self, i: int) -> dict:
        """Unpacked request payload dict; raises on a bad entry (the
        propagator logs + skips that entry)."""
        d = msgpack.unpackb(self._piece(0, i), raw=False,
                            strict_map_key=False)
        if not isinstance(d, dict):
            raise FlatWireError("PROPAGATE entry is not a map")
        return d

    def client(self, i: int) -> str:
        return self._piece(1, i).decode("utf-8", "replace")


_SECTION_TYPES = {
    KIND_PREPARE: PrepareColumns,
    KIND_COMMIT: CommitColumns,
    KIND_PREPREPARE: BlobSection,
    KIND_PROPAGATE: PropagateColumns,
}


class ParsedEnvelope:
    __slots__ = ("sections", "nbytes", "stamp")

    def __init__(self, sections, nbytes, stamp=None):
        self.sections = sections
        self.nbytes = nbytes
        # advisory TraceStamp (or None) — deliberately OUTSIDE
        # ``sections`` so consensus consumers iterating sections can
        # never observe it; only the observability receive hook reads it
        self.stamp = stamp


def parse_envelope(data, max_bytes: Optional[int] = None
                   ) -> ParsedEnvelope:
    """One flat envelope → parsed sections (numpy views, zero copies).
    Raises FlatWireError on ANY structural violation.

    ``max_bytes`` bounds the whole envelope BEFORE any section header
    is trusted — client-facing intakes (the gateway tier) pass their
    wire limit (Config.MSG_LEN_LIMIT) so an over-length envelope is a
    sender-attributable FlatWireError, not a memory bill. Node-to-node
    callers already ride the transport's frame limit and pass None."""
    if isinstance(data, (bytearray, memoryview)):
        data = bytes(data)
    if not isinstance(data, bytes):
        raise FlatWireError("envelope is not bytes")
    if max_bytes is not None and len(data) > max_bytes:
        raise FlatWireError(
            "envelope of %d bytes exceeds the %d-byte limit"
            % (len(data), max_bytes))
    if len(data) < 4 or data[:2] != MAGIC:
        raise FlatWireError("bad magic")
    version = data[2]
    if version not in (VERSION, VERSION_TRACE):
        raise FlatWireError("unsupported version %d" % version)
    nsect = data[3]
    pos = 4
    sections = []
    stamp = None
    for _ in range(nsect):
        if pos + 9 > len(data):
            raise FlatWireError("section header truncated")
        kind = data[pos]
        count = int.from_bytes(data[pos + 1:pos + 5], "little")
        payload_len = int.from_bytes(data[pos + 5:pos + 9], "little")
        pos += 9
        if pos + payload_len > len(data):
            raise FlatWireError("section payload truncated")
        if kind == KIND_TRACE and version >= VERSION_TRACE:
            # advisory: content problems (decode → None) and duplicate
            # stamps are silently tolerated; only the structural
            # payload-bounds check above can fail the envelope
            if stamp is None:
                stamp = decode_trace_stamp(data[pos:pos + payload_len])
            pos += payload_len
            continue
        cls = _SECTION_TYPES.get(kind)
        if cls is None:
            raise FlatWireError("unknown section kind %d" % kind)
        if count == 0 or count > SECTION_COUNT_MAX:
            raise FlatWireError("bad section count %d" % count)
        r = _Reader(data, pos, pos + payload_len)
        sections.append(cls(r, count))
        pos += payload_len
    if pos != len(data):
        raise FlatWireError("trailing bytes after last section")
    if not sections:
        raise FlatWireError("empty envelope")
    return ParsedEnvelope(sections, len(data), stamp)


def unwrap_for_tap(payload) -> Optional[list]:
    """The fault-injection unwrap policy, shared by BOTH tap seams
    (ExternalBus taps and SimNetwork processors): a flat envelope's
    typed per-message contents, or None when the envelope should be
    delivered WHOLE — malformed (the receiving node's evidence to
    judge: per-sender suspicion) or all-entries-invalid (the node's
    own intake does the per-entry dropping and its warn accounting,
    not the tap)."""
    try:
        inner = to_legacy_messages(payload)
    except FlatWireError:
        return None
    return inner or None


def to_legacy_messages(data) -> List:
    """Re-materialize a flat envelope into the typed messages the
    per-message wire would have carried (FIFO section order): 3PC
    sections become individual votes, a PROPAGATE section becomes the
    legacy Propagate / PropagateBatch. Used by the fault-injection
    unwrap seams (ExternalBus tap, SimNetwork processors) so adversary
    behaviors keep matching on per-type messages; entries that fail
    validation are dropped exactly as the typed intake would drop
    them."""
    from plenum_tpu.common.messages.node_messages import (
        Propagate, PropagateBatch)
    env = parse_envelope(data)
    out: List = []
    for sec in env.sections:
        if sec.kind == KIND_PROPAGATE:
            reqs, clients = [], []
            for i in range(sec.n):
                try:
                    reqs.append(sec.request(i))
                except Exception:
                    logger.warning("flat wire: bad PROPAGATE entry "
                                   "— dropped")
                    continue
                clients.append(sec.client(i))
            if not reqs:
                continue
            if len(reqs) == 1:
                out.append(Propagate(request=reqs[0],
                                     senderClient=clients[0] or None))
            else:
                out.append(PropagateBatch(requests=reqs,
                                          clients=clients))
        else:
            for i in range(sec.n):
                msg = sec.materialize(i)
                if msg is not None:
                    out.append(msg)
    return out
