"""Serializer registry: which codec each subsystem uses (reference:
common/serializers/serialization.py:9-23)."""
from plenum_tpu.common.serializers.serializers import (
    MsgPackSerializer, OrderedJsonSerializer, Base58Serializer,
    Base64Serializer, SigningSerializer)

ledger_txn_serializer = MsgPackSerializer()        # txn log entries
ledger_hash_serializer = MsgPackSerializer()       # tree hash store values
client_req_rep_serializer = OrderedJsonSerializer()
domain_state_serializer = OrderedJsonSerializer()  # MPT values, domain
pool_state_serializer = OrderedJsonSerializer()
config_state_serializer = OrderedJsonSerializer()
node_status_db_serializer = OrderedJsonSerializer()
instance_change_db_serializer = OrderedJsonSerializer()
multi_sig_store_serializer = OrderedJsonSerializer()
state_roots_serializer = Base58Serializer()        # roots on the wire
proof_nodes_serializer = Base64Serializer()        # MPT proof nodes
txn_root_serializer = Base58Serializer()

_signing_serializer = SigningSerializer()


def serialize_msg_for_signing(msg, topLevelKeysToIgnore=None) -> bytes:
    """Canonical bytes whose ed25519 signature all nodes agree on
    (reference serialization.py:27)."""
    return _signing_serializer.serialize(
        msg, topLevelKeysToIgnore=topLevelKeysToIgnore)
