from plenum_tpu.common.serializers.serialization import (  # noqa: F401
    ledger_txn_serializer,
    ledger_hash_serializer,
    domain_state_serializer,
    pool_state_serializer,
    config_state_serializer,
    client_req_rep_serializer,
    node_status_db_serializer,
    state_roots_serializer,
    proof_nodes_serializer,
    multi_sig_store_serializer,
    instance_change_db_serializer,
    serialize_msg_for_signing,
)
