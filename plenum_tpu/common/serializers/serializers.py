"""Serializer implementations (reference: common/serializers/*.py).

MsgPack for the ledger + wire (compact, fast C extension), ordered JSON for
state values (bit-identical across nodes — consensus-critical), Base58 for
roots, Base64 for proof nodes, and the canonical signing serialization.
"""
import base64
import json
from abc import ABC, abstractmethod
from typing import Any

import msgpack

from plenum_tpu.common.serializers.base58 import b58encode, b58decode

from plenum_tpu.native import try_load_ext

_fp = try_load_ext("fastpath")


class Serializer(ABC):
    @abstractmethod
    def serialize(self, data: Any, to_bytes=True) -> Any:
        ...

    @abstractmethod
    def deserialize(self, data: Any) -> Any:
        ...


def _sort_deep(data: Any) -> Any:
    """Recursively order dict keys (incl. inside lists/tuples) so msgpack
    output is bit-identical regardless of insertion order — consensus
    digests and merkle roots depend on it. exact-type checks + scalar
    fast path: this runs on every wire/ledger serialization."""
    t = type(data)
    if t is dict:
        return {k: _sort_deep(data[k]) for k in sorted(data)}
    if t is list or t is tuple:
        return [_sort_deep(v) for v in data]
    if isinstance(data, dict):  # dict subclass (e.g. MessageBase views)
        return {k: _sort_deep(data[k]) for k in sorted(data)}
    if isinstance(data, (list, tuple)):  # list/tuple subclass (NamedTuple)
        return [_sort_deep(v) for v in data]
    return data


class MsgPackSerializer(Serializer):
    """Reference: common/serializers/msgpack_serializer.py:13.
    Keys are sorted at every nesting level so serialization is canonical
    across nodes (consensus digests depend on it)."""

    def serialize(self, data: Any, to_bytes=True) -> bytes:
        if _fp is not None:
            try:
                return _fp.canonical_msgpack(data)
            except TypeError:
                pass  # non-str keys etc. — the Python path decides
        return msgpack.packb(_sort_deep(data), use_bin_type=True)

    def deserialize(self, data: Any) -> Any:
        if isinstance(data, (bytes, bytearray, memoryview)):
            return msgpack.unpackb(bytes(data), raw=False, strict_map_key=False)
        return data


class OrderedJsonSerializer(Serializer):
    """Canonical JSON: sorted keys, no whitespace (reference:
    common/serializers/json_serializer.py:46 — state values must serialize
    bit-identically on every node)."""

    def serialize(self, data: Any, to_bytes=True):
        if to_bytes and _fp is not None:
            try:
                return _fp.canonical_json_ascii(data)
            except TypeError:
                pass
        out = json.dumps(data, sort_keys=True, separators=(',', ':'))
        return out.encode('utf-8') if to_bytes else out

    def deserialize(self, data: Any) -> Any:
        if isinstance(data, (bytes, bytearray, memoryview)):
            data = bytes(data).decode('utf-8')
        return json.loads(data)


JsonSerializer = OrderedJsonSerializer


class Base58Serializer(Serializer):
    def serialize(self, data: bytes, to_bytes=False) -> str:
        return b58encode(data)

    def deserialize(self, data) -> bytes:
        return b58decode(data)


class Base64Serializer(Serializer):
    def serialize(self, data, to_bytes=True):
        return base64.b64encode(data)

    def deserialize(self, data):
        return base64.b64decode(data)


class SigningSerializer(Serializer):
    """Canonical msg → bytes for signing (reference:
    common/serializers/signing_serializer.py + serialize_msg_for_signing):
    deterministic field order, nested dicts flattened the same way on every
    node. We use canonical JSON with sorted keys over the 'plain' dict."""

    def serialize(self, data: Any, to_bytes=True, topLevelKeysToIgnore=None):
        if hasattr(data, 'as_dict'):
            data = data.as_dict()
        elif hasattr(data, '_asdict'):
            data = data._asdict()
        if isinstance(data, dict) and topLevelKeysToIgnore:
            data = {k: v for k, v in data.items()
                    if k not in topLevelKeysToIgnore}
        out = json.dumps(data, sort_keys=True, separators=(',', ':'),
                         ensure_ascii=False)
        return out.encode('utf-8') if to_bytes else out

    def deserialize(self, data):
        if isinstance(data, (bytes, bytearray)):
            data = data.decode('utf-8')
        return json.loads(data)
