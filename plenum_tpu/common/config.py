"""Framework configuration defaults.

Reference: plenum/config.py (~189 knobs) + stp_core/config.py. Kept as a
simple attribute namespace; override via Config(**overrides) or attribute
assignment (tests use the `tconf` fixture pattern); layered file/env
loading via Config.load (reference plenum/common/config_util.py).
"""
import os


class Config:
    # ---- 3PC batching (reference plenum/config.py:253-276)
    Max3PCBatchSize = 1000
    Max3PCBatchWait = 3          # seconds before sending a partial batch
    Max3PCBatchesInFlight = 4
    MAX_BATCHES_IN_QUEUE = 100

    CHK_FREQ = 100               # checkpoint every N batches
    LOG_SIZE = 3 * CHK_FREQ      # watermark window [h, h+LOG_SIZE]

    # ---- columnar 3PC dataflow (server/three_pc_outbox.py +
    # OrderingService.process_*_batch): coalesce every instance's
    # broadcast 3PC votes into one THREE_PC_BATCH wire message per prod
    # tick, and process inbound envelopes through the vectorized
    # columnar intake. Inbound batches are always understood; this knob
    # only gates our own coalesced SENDING. While an adversary tap is
    # installed the outbox degrades to per-message sends regardless.
    THREE_PC_BATCH_WIRE = True
    # flat zero-copy wire codec (common/serializers/flat_wire.py):
    # PREPARE/COMMIT votes travel as contiguous typed columns and
    # PROPAGATE payloads as length-prefixed sections inside ONE
    # FLAT_WIRE envelope per peer per tick — one pack / one parse
    # instead of per-message serializer calls, zero intermediate
    # message objects on the receive path. Inbound flat envelopes are
    # always understood; this knob gates only our own SENDING (the
    # typed THREE_PC_BATCH / PROPAGATE_BATCH path is the validated
    # fallback, and an installed adversary tap degrades to it
    # regardless so fault injection keeps per-message granularity).
    FLAT_WIRE = True
    # micro-batching window for delivery-provoked votes (seconds): a
    # vote provoked outside a prod tick waits at most this long for
    # same-window siblings before the outbox flushes — peer deliveries
    # arrive jittered, and a zero-delay flush would ship every provoked
    # vote as its own wire message (measured: 18 singles / 0 envelopes
    # per 3PC round per node at 25 validators). One spare timer turn of
    # a few ms costs nothing against consensus timeouts.
    THREE_PC_FLUSH_WINDOW = 0.002

    # ---- fused per-3PC-batch device dispatch (server/executor.py):
    # launch the batch's ledger leaf-hash dispatch (SHA-256 seam) and
    # kick any queued verifier-hub generation BEFORE the MPT pending-
    # apply runs, collecting the staged hashes after — one overlapped
    # device window per applied batch instead of serialized round trips
    FUSED_BATCH_DISPATCH = True

    # ---- conflict-lane execution (server/executor.py +
    # server/execution_lanes.py): partition each ordered batch into
    # deterministic execution lanes from the handlers' declared state
    # touches — batched pre-batch read prefetch for every declared read
    # key, one bulk structural trie merge per written state, and ONE
    # merged level-wise SHA3 resolve across all written states per
    # batch. False restores the pre-lane serial apply path (the bench
    # A/B baseline; results are byte-equal either way).
    EXEC_LANES = True
    # batches below this many requests skip lane planning — the plan +
    # prefetch overhead only pays for itself on real batches
    EXEC_LANE_MIN = 8
    # merged-resolve hash routing: "auto" = device dispatches only on
    # hosts with a real accelerator (on CPU hosts hashlib beats
    # per-level dispatch overhead at MPT node counts — the SHA-256
    # "tiled" CPU-backend precedent); True/False force one side
    EXEC_MERGED_DEVICE_HASH = "auto"

    # ---- propagation
    PROPAGATE_REQUEST_DELAY = 0

    # ---- monitor thresholds (reference plenum/config.py:140-142)
    DELTA = 0.1                  # min master throughput ratio (Δ)
    LAMBDA = 240                 # max master request latency sec (Λ)
    OMEGA = 20                   # max master-vs-backup avg latency gap (Ω)
    SendMonitorStats = False
    ThroughputWindowSize = 15
    ThroughputFirstWindowSize = 450
    ThroughputMinActivityThreshold = 0
    ThroughputInnerWindowSize = 15
    LatencyWindowSize = 30
    MIN_LATENCY_COUNT = 10

    # ---- view change (reference plenum/config.py:197-201, 295)
    ToleratePrimaryDisconnection = 60
    NEW_VIEW_TIMEOUT = 30
    # PBFT-style timeout escalation: each consecutive FAILED view change
    # (NEW_VIEW timeout or mismatch) doubles the next NEW_VIEW wait, up
    # to the cap; any completed view change resets to NEW_VIEW_TIMEOUT.
    # Without this a pool whose view changes keep colliding (partition
    # just healing, slow links) thrashes at the base period forever.
    NEW_VIEW_TIMEOUT_MAX = 480
    VIEW_CHANGE_RESEND_TIMEOUT = 10
    # while waiting_for_new_view: period of the self-heal timer that
    # re-sends our own VIEW_CHANGE and re-requests the missing NEW_VIEW
    # / referenced VIEW_CHANGEs via MessageReq (lossy-wire liveness —
    # without it a lost NEW_VIEW only ever escalates into a vote for
    # the NEXT view, splitting the pool further)
    VIEW_CHANGE_REREQUEST_INTERVAL = 5
    INSTANCE_CHANGE_RESEND_TIMEOUT = 300
    OUTDATED_INSTANCE_CHANGES_CHECK_INTERVAL = 300

    # ---- freshness (reference plenum/config.py STATE_FRESHNESS_UPDATE_INTERVAL)
    UPDATE_STATE_FRESHNESS = True
    STATE_FRESHNESS_UPDATE_INTERVAL = 300
    # stale periods before non-primaries vote a view change (reference
    # ACCEPTABLE_FRESHNESS_INTERVALS_COUNT)
    ACCEPTABLE_FRESHNESS_INTERVALS_COUNT = 3
    # periodic forced view changes (chaos/debug; 0 = disabled)
    ForceViewChangeFreq = 0
    ACCEPTABLE_DEVIATION_PREPREPARE_SECS = 300

    # ---- merkle hashing (TreeHasher TPU seam, ledger/tree_hasher.py)
    SHA256_BACKEND = "jax"       # "jax" (batched device kernel) | "scalar"
    SHA256_BATCH_THRESHOLD = 512  # below this, hashlib wins on latency
    # CPU-backend cache tiling for the XLA SHA-256 expression
    # (ops/sha256.py): without tiling every one of the ~1600 u32 ops
    # per compression materializes a batch-wide temp that overflows
    # L2, making the kernel memory-bound (~2.4x measured recovery at
    # this tile). Batches below 2 tiles run untiled.
    SHA256_CPU_TILE = 4096
    # batch rows at which the Pallas SHA-256 kernel takes over from
    # the XLA lowering on accelerators (one kernel block = 1024 rows)
    SHA256_PALLAS_MIN_BATCH = 1024
    # fused multi-level tree append (ops/merkle.py): hash K tree
    # levels per device dispatch (pair in-kernel between levels),
    # cutting dispatches-per-append from O(log n) to O(log n / K).
    # 1 = the PR-2 level-at-a-time behavior (kept for A/B tests).
    MERKLE_FUSED_LEVELS = 4

    # ---- device merkle proof engine (ops/merkle.py + ledger routing):
    # large reply-proof / catchup-proof batches are served from the
    # device-resident tree; small batches keep the host memo path
    MERKLE_DEVICE_PROOFS = True
    MERKLE_DEVICE_PROOF_MIN = 2048   # below this the host memo path wins
    MERKLE_DEVICE_PROOF_CHUNK = 4096  # pipelined sub-batch size
    MERKLE_DEVICE_PIPELINE_DEPTH = 2  # gathers kept in flight

    # ---- device MPT state engine (state/device_state.py behind
    # PruningState): batched multi-key get / batch apply / batched SPV
    # proof generation with level-wise SHA3 dispatches (ops/sha3.py).
    # Calls below BATCH_MIN keys keep the host trie path (per-call
    # dispatch latency wins there); inside a batched call, levels with
    # fewer than HASH_FLOOR nodes hash via hashlib (the root level is
    # one node — a device round trip per spine level would dominate).
    STATE_DEVICE_ENGINE = True
    STATE_DEVICE_BATCH_MIN = 8
    STATE_DEVICE_HASH_FLOOR = 128

    # decoded-node cache cap per Trie (state/trie.py): ~1-1.5KB per
    # decoded branch node → tens of MB per trie at the cap; large
    # enough to hold a full batch's spine working set
    STATE_DECODE_CACHE_MAX = 1 << 16

    # ---- catchup
    CATCHUP_BATCH_SIZE = 5
    CATCHUP_REP_CHUNK = 1000      # txns per CatchupRep message
    # attach per-txn audit paths to CatchupReps (lets leechers reject a
    # lying chunk at rep time; costs ~2-3x rep wire size — integrity is
    # still guaranteed by the whole-range root replay when off)
    CATCHUP_REP_AUDIT_PATHS = True
    CATCHUP_TXN_TIMEOUT = 6
    CatchupTransactionsTimeout = 6
    MAX_CATCHUP_RETRY = 3
    # leecher retry policy (server/catchup.py): capped exponential
    # backoff from CATCHUP_TXN_TIMEOUT — retry i waits
    # min(base * 2^i, MAX) plus up to JITTER_FRAC of that (deterministic
    # per (ledger, retry) so sim runs replay). Progress (an adopted
    # target or a buffered rep) resets the backoff. A fixed period
    # hammers dead peers and synchronizes the whole pool's re-requests.
    CATCHUP_RETRY_BACKOFF_MAX = 60
    CATCHUP_RETRY_JITTER_FRAC = 0.25

    # ---- transport (reference stp_core/config.py)
    MSG_LEN_LIMIT = 128 * 1024
    MAX_CONNECTED_CLIENTS_NUM = 15360
    ENABLE_HEARTBEATS = True
    HEARTBEAT_FREQ = 5
    RETRY_TIMEOUT_NOT_RESTRICTED = 6
    RETRY_TIMEOUT_RESTRICTED = 15
    MAX_RECONNECT_RETRY_ON_SAME_SOCKET = 1

    # ---- client-signature verification provider (the TPU seam;
    # crypto/batch_verifier.py). "remote" offloads to the verify daemon
    # (server/verify_daemon.py) — the multi-process deployment shape,
    # where one daemon process owns the accelerator for the whole host.
    VERIFIER_PROVIDER = "adaptive"
    VERIFIER_DAEMON_HOST = "127.0.0.1"
    VERIFIER_DAEMON_PORT = 9988
    # verify-daemon coalescing (server/verify_daemon.py): window seconds
    # a first frame waits for co-resident nodes' frames; device launches
    # are chunked to exactly BUCKET items (one compiled shape); fused
    # batches below CPU_FLOOR take the OpenSSL path
    VERIFY_DAEMON_WINDOW = 0.002
    VERIFY_DAEMON_BUCKET = 4096
    VERIFY_DAEMON_CPU_FLOOR = 512
    # seconds a dispatched client-auth batch may stay in flight before
    # the prod loop harvests it blocking (wedged daemon/device fallback)
    CLIENT_AUTH_TIMEOUT = 10.0

    # ---- gateway tier (plenum_tpu/gateway/): the client-facing front
    # door — device-batched ed25519 pre-screen, admission control and
    # the signed-read cache. GATEWAY_BATCH_MAX bounds one intake
    # batch's fused verify dispatch; the admission ladder degrades
    # READS first when either pressure signal crosses its high-water
    # mark (backlog depth in requests, ordered p99 in ms) and WRITES
    # only past the hard marks; recovery needs BOTH signals back under
    # the low-water marks (hysteresis — a gauge oscillating around one
    # mark must not flap the shed decision per batch).
    GATEWAY_BATCH_MAX = 2048
    GATEWAY_BACKLOG_HIGH = 6000      # shed reads above this backlog
    GATEWAY_BACKLOG_LOW = 4000      # readmit reads below this
    GATEWAY_BACKLOG_HARD = 12000    # shed writes too above this
    GATEWAY_P99_HIGH_MS = 4000.0    # shed reads above this ordered p99
    GATEWAY_P99_LOW_MS = 2000.0     # readmit reads below this
    GATEWAY_P99_HARD_MS = 12000.0   # shed writes too above this
    # signed-read cache: entries carry a BLS-multi-signed state proof;
    # a hit is served only while the proof's multi-sig timestamp is
    # inside the freshness window (seconds) AND the entry's root is
    # still the newest root the cache has observed for its ledger
    GATEWAY_CACHE_MAX = 9216
    GATEWAY_CACHE_FRESH_S = 300.0
    # misbehaving-sender registry: a sender shed after this many
    # structural wire violations (FlatWireError envelopes); bounded
    # registry so client-chosen sender ids cannot grow it unboundedly
    GATEWAY_SENDER_STRIKES = 3
    GATEWAY_SENDER_REGISTRY_MAX = 16384

    # ---- pipeline runtime (plenum_tpu/runtime/pipeline.py): wire
    # parse + ed25519 pre-screen on worker threads feeding the prod
    # thread via bounded SPSC queues; execution fan-out across the same
    # pool. The prod thread keeps sole ownership of all consensus
    # state; the serial path stays the validated fallback (step-down
    # philosophy). PIPELINE_WORKERS is the SINGLE sizing knob (PT005):
    # None = auto (cores−1, capped at 4) for the node pipeline, while
    # the verify daemon resolves the same knob with a fallback of 1 —
    # its serialize-by-one coalescing floor — unless set explicitly.
    # PIPELINE_QUEUE_DEPTH bounds the parse queue; a full queue blocks
    # intake (backpressure that folds into the BACKLOG_DEPTH gauge the
    # gateway admission ladder sheds on).
    PIPELINE_ENABLED = False
    PIPELINE_WORKERS = None
    PIPELINE_QUEUE_DEPTH = 256

    # runtime ownership sanitizer (runtime/sanitizer.py): region pins
    # on consensus-critical objects + handoff tokens at the pipeline
    # queues — the runtime twin of plenum-lint PT016/PT017. Tri-state:
    # True/False win outright; None defers to PLENUM_TPU_SANITIZE=1
    # (the sim-pool test fixtures' suite-wide switch). Default off in
    # production: the checks are cheap (a dict lookup per guarded
    # seam, gated <2% by the sanitizer_overhead bench) but the point
    # is debugging, not defense in depth.
    SANITIZER_ENABLED = None

    # ---- quotas per prod tick (reference stp_core/config.py:29+,
    # plenum/server/quota_control.py)
    NODE_TO_NODE_STACK_QUOTA = 1024
    NODE_TO_NODE_STACK_SIZE = 1024 * 1024
    CLIENT_TO_NODE_STACK_QUOTA = 100
    CLIENT_TO_NODE_STACK_SIZE = 1024 * 1024
    EnsureListenerQuota = True
    MAX_REQUEST_QUEUE_SIZE = 10000

    # ---- replicas
    REPLICAS_REMOVING_WITH_DEGRADATION = "local"
    REPLICAS_REMOVING_WITH_PRIMARY_DISCONNECTED = "local"

    # ---- metrics / validator info (reference plenum/config.py
    # METRICS_COLLECTOR_TYPE + DUMP_VALIDATOR_INFO_PERIOD_SEC)
    METRICS_FLUSH_INTERVAL = 10          # seconds between KV flushes
    VALIDATOR_INFO_DUMP_INTERVAL = 60    # seconds between JSON dumps
    # logging (reference stp_core/config.py:9-17): per-node rotating
    # log file, gzip-compressed rotated segments (utils/log.py)
    LOG_LEVEL = 20                       # logging.INFO; TRACE=5
    LOG_FORMAT = None                    # None = utils.log.DEFAULT_FORMAT
    LOG_MAX_BYTES = 50 * 1024 * 1024
    LOG_BACKUP_COUNT = 10

    # ---- TAA acceptance time window (reference plenum/config.py
    # TXN_AUTHOR_AGREEMENT_ACCEPTANCE_TIME_{BEFORE_TAA,AFTER_PP}_TIME)
    TAA_ACCEPTANCE_TIME_BEFORE_TAA = 120
    TAA_ACCEPTANCE_TIME_AFTER_PP_TIME = 120

    # ---- blacklisting: auto-blacklist on (attributable) suspicions is
    # OFF by default, matching the reference (node.py:2883 "TODO:
    # Consider blacklisting nodes again"); suspicions are always logged
    BLACKLIST_ON_SUSPICION = False

    # ---- request-handler caches (server/request_handlers.py): NYM
    # record lookups memoized per uncommitted view; bounded because
    # identifiers are client-chosen (attacker-controlled allocation)
    NYM_CACHE_MAX = 4096

    # ---- storage
    domainStateStorage = "memory"
    poolStateStorage = "memory"
    configStateStorage = "memory"
    reqIdToTxnStorage = "memory"
    nodeStatusStorage = "memory"

    # ---- BLS (networked nodes derive the signer from the transport
    # seed; False skips BLS share generation/aggregation entirely)
    BLS_SIGN = True
    # Optimistic batch verification of commit shares: COMMIT arrival
    # does only cheap share decoding; ordering verifies the AGGREGATE
    # once (2 pairings per batch instead of one pairing per share) and
    # falls back to per-share checks to assign blame if the aggregate
    # fails. False restores the reference's verify-each-share-on-
    # arrival behavior (a bad share then rejects that COMMIT message).
    BLS_DEFER_SHARE_VERIFY = True

    # ---- TPU crypto dispatch (new — the north-star gated boundary)
    # provider: 'cpu' (scalar C path via `cryptography`) or 'tpu_batch'
    # (JAX batched kernels). 'auto' picks by queue depth.
    ED25519_PROVIDER = "auto"
    ED25519_TPU_MIN_BATCH = 64   # below this the CPU scalar path wins
    SHA256_PROVIDER = "auto"
    SHA256_TPU_MIN_BATCH = 256
    BLS_PROVIDER = "cpu"

    # ---- device BLS12-381 pairing / MSM (ops/bls381_pairing.py behind
    # crypto/bls_ops): batches of pairing-product checks run as one
    # bucketed Miller-loop launch with a SINGLE shared final
    # exponentiation; below the MIN the native scalar path (prepared
    # Miller lines, cached decompressions) wins on latency. Env
    # PLENUM_TPU_BLS_TOWER=native|off forces the host path.
    BLS_DEVICE_PAIRING = True
    BLS_PAIRING_DEVICE_MIN = 4
    BLS_MSM_DEVICE_MIN = 8       # Σ sᵢ·Pᵢ points below this stay host

    # batch size at which AdaptiveVerifier / CoalescingVerifierHub leave
    # the scalar CPU floor for a device launch (single-sourced here,
    # like the MERKLE_DEVICE_* knobs)
    VERIFIER_BATCH_THRESHOLD = 32

    # ---- device-mesh crypto dispatch (ops/mesh.py): shard verify /
    # BLS-aggregate / merkle batches over every available chip on the
    # batch axis (zero collectives — the kernels are row-wise pure).
    # Single-device hosts and batches below MESH_SHARD_MIN take the
    # passthrough path (bench-gated <5% overhead).
    MESH_ENABLED = True
    MESH_MAX_DEVICES = 0         # 0 = all devices (rounded down to 2^k)
    MESH_SHARD_MIN = 2048        # below this one chip wins on latency
    # shard over a multi-device CPU backend too. XLA's virtual CPU
    # "devices" (xla_force_host_platform_device_count) share the same
    # physical cores, so sharding over them is pure partition overhead
    # (measured ~5x SLOWER on 1M-leaf merkle builds) — production
    # keeps this off; tests / dryrun_multichip force it (env
    # PLENUM_TPU_MESH_CPU_SHARD=1 or configure(cpu_shard=True)) to
    # exercise the sharded code paths without TPU hardware.
    MESH_CPU_SHARD = False

    # ---- device circuit breaker (utils/device_breaker.py, shared by
    # the merkle + MPT engine seams): after max_failures consecutive
    # engine failures the breaker opens for this many seconds — every
    # call serves the host fallback with zero device I/O — then allows
    # ONE probe call through; success re-attaches, failure re-trips
    # quietly for another cooldown
    BREAKER_COOLDOWN_S = 30

    # ---- recovery SLOs (sim-time seconds; bench.py `recovery` config
    # and the soak scenarios gate on these): primary crash → ordering
    # resumes on every honest node; lagging node under adversarial
    # seeding completes catchup. Violations auto-dump a flight-recorder
    # timeline with the measured latency in the filename.
    RECOVERY_FAILOVER_SLO_S = 40.0
    RECOVERY_CATCHUP_SLO_S = 60.0

    # ---- metrics
    METRICS_COLLECTOR_TYPE = None

    # ---- flight recorder (observability/): per-node span tracing of
    # the batch lifecycle + device-dispatch seams, exportable as a
    # Perfetto timeline (scripts/trace_view). Off by default; enabled
    # cost is bench-gated to low single-digit percent on the ordering
    # hot path (bench.py tracing_overhead).
    TRACING_ENABLED = False
    TRACING_BUFFER_SPANS = 1 << 16   # ring slots per node; newest kept

    # ---- journey plane (observability/journey.py): wire-carried trace
    # context. When on, flat envelopes ride as version 2 with an
    # advisory TRACE section (origin node, flush seq, perf+wall send
    # timestamps; ≤89 payload bytes) and the typed THREE_PC_BATCH /
    # PROPAGATE_BATCH fallback carries the same stamp in a nullable
    # traceCtx field, so receivers can join per-node tracer buffers
    # into per-request cross-node journeys. Purely advisory: stamps are
    # decoded outside the consensus sections (plenum-lint PT015 pins
    # unreachability), malformed stamps degrade to None without
    # touching message handling, and bench.py trace_context_overhead
    # hard-gates the on/off A/B under 2%. Follows TRACING_ENABLED —
    # stamps without tracer buffers join nothing.
    TRACE_CONTEXT_ENABLED = True

    # ---- telemetry plane (observability/telemetry.py): always-on
    # latency histograms (p50/p95/p99/p999 on the ordered money path),
    # device-efficiency lane accounting at every bucket-padding
    # dispatch seam, and pool-health gauges. ON by default — bench.py
    # telemetry_overhead A/Bs the identical pool with it off and gates
    # the cost under 2% (BENCH_TELEMETRY_GATE).
    TELEMETRY_ENABLED = True
    TELEMETRY_FLUSH_INTERVAL_S = 10   # gauge sample + prom write period
    # directory for per-node Prometheus text exposition files
    # (<dir>/<node>.prom, rewritten atomically per flush); None = none
    TELEMETRY_PROM_DIR = None
    # log-linear histogram shape: `sub` linear sub-buckets per
    # power-of-two octave bounds quantile relative error to 1/sub
    # (6.25% at 16); 30 octaves from 1 µs cover ~18 minutes
    TELEMETRY_HIST_LO_MS = 0.001
    TELEMETRY_HIST_OCTAVES = 30
    TELEMETRY_HIST_SUB_BUCKETS = 16
    # intake-timestamp map cap: e2e latency tracking stops (and counts
    # TM.E2E_DROPPED) past this many in-flight requests
    TELEMETRY_PENDING_MAX = 1 << 17
    # flush-history ring (Perfetto counter tracks) + per-seam distinct
    # bucket-shape set cap (compile-event accounting)
    TELEMETRY_FLUSH_HISTORY = 512
    TELEMETRY_SHAPE_CAP = 4096

    # ---- plugins (reference plenum/config.py:164
    # notifierEventTriggeringConfig + SpikeEventsEnabled; plugin dirs
    # from plenum/server/plugin_loader.py usage)
    NOTIFIER_EVENTS_ENABLED = True
    SPIKE_EVENTS_ENABLED = False      # reference default: off
    SPIKE_EVENTS_FREQ = 60            # seconds between spike samples
    SPIKE_EVENT_TRIGGERING = {
        "NodeRequestSuspiciousSpike": {
            "bounds_coeff": 10, "min_cnt": 15,
            "min_activity_threshold": 10,
            "use_weighted_bounds_coeff": True, "enabled": True},
        "ClusterThroughputSuspiciousSpike": {
            "bounds_coeff": 10, "min_cnt": 15,
            "min_activity_threshold": 10,
            "use_weighted_bounds_coeff": True, "enabled": True},
    }
    NOTIFIER_PLUGINS_DIR = None       # dir of notifier*.py/plugin*.py
    PLUGINS_DIR = None                # dir of typed plugin*.py classes

    # ---- TAA
    TXN_AUTHOR_AGREEMENT_EXPIRATION = None

    def __init__(self, **overrides):
        for k, v in overrides.items():
            setattr(self, k, v)

    # ------------------------------------------------ layered loading

    @classmethod
    def load(cls, base_dir: str = None, env: dict = None,
             **overrides) -> "Config":
        """Layered config (reference plenum/common/config_util.py
        getConfig: package defaults ← /etc ← user dir ← env):

            1. class defaults
            2. `plenum_tpu_config.py` in base_dir (exec'd; UPPERCASE and
               known keys become attributes)
            3. PLENUM_TPU_<KEY>=value environment overrides (parsed as
               Python literals, falling back to raw strings)
            4. explicit **overrides (strongest)
        """
        import ast
        conf = cls()
        known = {k for k in dir(cls)
                 if not k.startswith("_") and not callable(getattr(cls, k))}
        explicit = set()
        if base_dir:
            path = os.path.join(base_dir, "plenum_tpu_config.py")
            if os.path.exists(path):
                # ONE namespace: separate globals/locals would break
                # top-level references from genexps/functions
                ns = {}
                with open(path) as f:
                    exec(compile(f.read(), path, "exec"), ns)
                for k, v in ns.items():
                    if k != "__builtins__" and (k in known or k.isupper()):
                        setattr(conf, k, v)
                        explicit.add(k)
        env = os.environ if env is None else env
        for k in known:
            raw = env.get("PLENUM_TPU_" + k.upper())
            if raw is None:
                continue
            setattr(conf, k, cls._parse_env(k, raw))
            explicit.add(k)
        for k, v in overrides.items():
            setattr(conf, k, v)
            explicit.add(k)
        # derived invariant: the checkpoint window must fit the log
        # window or 3PC stalls (no checkpoint ever stabilizes). If the
        # operator moved CHK_FREQ without touching LOG_SIZE, re-derive
        # the usual 3x relation; an explicit inconsistent pair is an
        # error, not a silent stall.
        if "CHK_FREQ" in explicit and "LOG_SIZE" not in explicit:
            conf.LOG_SIZE = 3 * conf.CHK_FREQ
        if conf.LOG_SIZE < conf.CHK_FREQ:
            raise ValueError(
                "LOG_SIZE ({}) must be >= CHK_FREQ ({}) or no checkpoint "
                "can ever stabilize".format(conf.LOG_SIZE, conf.CHK_FREQ))
        return conf

    @staticmethod
    def _parse_env(key: str, raw: str):
        """Literal if possible; common booleans; otherwise raw ONLY for
        string-typed knobs — a typo'd number must fail loudly, not ride
        along as a truthy string."""
        import ast
        try:
            return ast.literal_eval(raw)
        except (ValueError, SyntaxError):
            pass
        low = raw.strip().lower()
        if low in ("true", "yes", "on"):
            return True
        if low in ("false", "no", "off"):
            return False
        default = getattr(Config, key, None)
        if default is None or isinstance(default, str):
            return raw
        raise ValueError(
            "cannot parse PLENUM_TPU_{}={!r} as a {}".format(
                key.upper(), raw, type(default).__name__))


def getConfig(**overrides) -> Config:
    return Config(**overrides)
