"""Exception hierarchy (reference: common/exceptions.py:5 PlenumError,
plenum/common/exceptions.py)."""


class PlenumError(Exception):
    """Base for all framework errors."""


class PlenumTypeError(PlenumError, TypeError):
    def __init__(self, v_name, v_value, v_exp_t, *args):
        super().__init__("variable '{}', type {}, expected: {}"
                         .format(v_name, type(v_value), v_exp_t), *args)


class PlenumValueError(PlenumError, ValueError):
    def __init__(self, v_name, v_value, v_exp_value, *args):
        super().__init__("variable '{}', value {}, expected: {}"
                         .format(v_name, v_value, v_exp_value), *args)


class LogicError(PlenumError, RuntimeError):
    """Intended to be raised when an internal invariant is broken."""


class InvalidMessageException(PlenumError):
    pass


class MissingNodeOp(InvalidMessageException):
    pass


class InvalidNodeOp(InvalidMessageException):
    pass


class InvalidNodeMessageException(InvalidMessageException):
    pass


class InvalidClientMessageException(InvalidMessageException):
    def __init__(self, identifier, reqId, reason=None, code=None):
        self.identifier = identifier
        self.reqId = reqId
        self.reason = reason
        self.code = code
        super().__init__(reason or "invalid client message")


class InvalidClientRequest(InvalidClientMessageException):
    pass


class InvalidClientTaaAcceptanceError(InvalidClientRequest):
    pass


class UnauthorizedClientRequest(InvalidClientMessageException):
    pass


class InvalidSignature(InvalidClientMessageException):
    def __init__(self, identifier=None, reqId=None, reason="invalid signature"):
        super().__init__(identifier, reqId, reason)


class CouldNotAuthenticate(InvalidClientMessageException):
    pass


class InsufficientSignatures(InvalidClientMessageException):
    def __init__(self, provided, required, identifier=None, reqId=None):
        super().__init__(identifier, reqId,
                         "insufficient signatures, {} provided but {} required"
                         .format(provided, required))


class InsufficientCorrectSignatures(InvalidClientMessageException):
    def __init__(self, valid, required, identifier=None, reqId=None):
        super().__init__(identifier, reqId,
                         "insufficient number of valid signatures, {} is valid "
                         "but {} required".format(valid, required))


class SuspiciousNode(PlenumError):
    def __init__(self, node: str, suspicion, offending_msg=None):
        self.node = node
        self.suspicion = suspicion
        self.offendingMsg = offending_msg
        code = getattr(suspicion, 'code', None)
        reason = getattr(suspicion, 'reason', suspicion)
        super().__init__("suspicious node {}: ({}) {}".format(node, code, reason))


class SuspiciousClient(PlenumError):
    pass


class BlowUp(PlenumError):
    """Unrecoverable error: the node must halt."""


class StorageException(PlenumError):
    pass


class KeysNotFoundException(PlenumError):
    pass


class MismatchedMessageReplyException(PlenumError):
    pass
