"""plenum-lint whole-program engine — symtab, callgraph, summaries.

PT001–PT011 are per-function AST rules: each looks at one module in
isolation. The bug classes PR 13 (lane planning must be a pure function
of the ordered batch), PR 8/11 (every ``*_dispatch`` half must be
collected) and PR 9 (every device launch must route through a bounded
bucket shape) are *inter-procedural*: the property lives in how
functions compose across files, which no single-module walk can see.

This package gives rules a whole-program view in three layers, each
built on the one below:

* **symtab** (`symtab.py`) — per-file fact extraction: every function
  and class in the project indexed by module-qualified name, with
  decorator records, import maps, call sites (and how each call's
  result flows: returned / named / escaped / discarded), plus the raw
  rule facts (nondeterminism sources, dispatch/collect effects, device
  launch sites, bucket-routing evidence). Facts are plain JSON-able
  dicts — deliberately AST-free — so they cache per file.
* **callgraph** (`callgraph.py`) — whole-program linking: call sites
  resolved to project symbols (module functions through import maps,
  ``self.method`` through base-class resolution, unique-name fallback
  for attribute calls), Tarjan SCC condensation so cyclic call
  clusters get one fixpoint, and a bottom-up order for summaries.
* **summaries** (`summaries.py`) — per-function summaries computed
  bottom-up over the condensation: nondeterminism taint, open
  dispatch generations handed to callers, bucket-routing evidence.

`cache.py` persists the extraction layer keyed by file content hash
(``.plenum_lint_cache.json`` at the repo root): linking and summaries
are cheap enough to recompute every run, so a warm run re-parses only
files whose bytes changed and the tier-1 gate stays fast.

Entry point::

    from plenum_tpu.analysis.engine import Engine
    eng = Engine.build(files, root=repo_root)   # cached per content hash
    eng.summaries["plenum_tpu.ops.sha3:pad_sha3_messages"]
    eng.callees(sym), eng.callers(sym)
"""
from __future__ import annotations

from plenum_tpu.analysis.engine.callgraph import CallGraph
from plenum_tpu.analysis.engine.engine import Engine
from plenum_tpu.analysis.engine.symtab import extract_file_facts
from plenum_tpu.analysis.engine.summaries import FunctionSummary

__all__ = ["CallGraph", "Engine", "FunctionSummary",
           "extract_file_facts"]
