"""Per-file facts cache keyed by content hash.

Parsing + extraction is the only expensive step of the engine (linking
and summaries are dict-walks), and it is per-file and deterministic —
the textbook shape for a content-addressed cache. Entries key on the
sha256 of the file BYTES (not mtime: a ``git checkout`` back and forth
must re-hit, an edit must miss) plus the extractor version, so bumping
``symtab.FACTS_VERSION`` invalidates every entry at once.

The cache lives at ``<root>/.plenum_lint_cache.json`` (gitignored).
All I/O is best-effort: a corrupt, unreadable or unwritable cache
degrades to a cold run, never to an error — the tier-1 gate must not
depend on scratch-file health. Writes are atomic (tmp + rename) so a
killed run can't leave a truncated JSON behind.
"""
from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Dict, Optional

from plenum_tpu.analysis.engine.symtab import FACTS_VERSION

CACHE_BASENAME = ".plenum_lint_cache.json"
CACHE_SCHEMA = 1


def content_hash(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


class FactsCache:
    def __init__(self, path: Optional[str]):
        """path=None disables persistence (in-memory only)."""
        self.path = path
        self.entries: Dict[str, dict] = {}
        self.hits = 0
        self.misses = 0
        self._dirty = False
        self._load()

    @classmethod
    def for_root(cls, root: str) -> "FactsCache":
        return cls(os.path.join(root, CACHE_BASENAME))

    def _load(self) -> None:
        if not self.path:
            return
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError):
            return
        if not isinstance(data, dict) \
                or data.get("schema") != CACHE_SCHEMA \
                or data.get("facts_version") != FACTS_VERSION:
            return
        entries = data.get("entries")
        if isinstance(entries, dict):
            self.entries = entries

    def get(self, rel_path: str, sha: str) -> Optional[dict]:
        e = self.entries.get(rel_path)
        if e and e.get("sha") == sha:
            self.hits += 1
            return e.get("facts")
        self.misses += 1
        return None

    def put(self, rel_path: str, sha: str, facts: dict) -> None:
        self.entries[rel_path] = {"sha": sha, "facts": facts}
        self._dirty = True

    def prune(self, keep_rel_paths) -> None:
        """Drop entries for files no longer in the scan set so the
        cache tracks the tree instead of growing forever."""
        keep = set(keep_rel_paths)
        stale = [k for k in self.entries if k not in keep]
        for k in stale:
            del self.entries[k]
            self._dirty = True

    def save(self) -> None:
        if not self.path or not self._dirty:
            return
        data = {"schema": CACHE_SCHEMA, "facts_version": FACTS_VERSION,
                "entries": self.entries}
        try:
            d = os.path.dirname(self.path) or "."
            fd, tmp = tempfile.mkstemp(prefix=CACHE_BASENAME,
                                       dir=d, suffix=".tmp")
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(data, f, separators=(",", ":"))
            os.replace(tmp, self.path)
            self._dirty = False
        except OSError:
            try:
                os.unlink(tmp)
            except (OSError, UnboundLocalError):
                pass
