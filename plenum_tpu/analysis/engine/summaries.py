"""Per-function summaries, computed bottom-up over the SCC condensation.

A summary is the whole-program rules' unit of composition: what a
function *does* to its callers, independent of how it does it.

* ``nondet`` — transitive nondeterminism-taint kinds (PT012 reports at
  the concrete source sites via forward reach; the summary powers
  ``--callgraph`` triage and the engine tests).
* ``pure`` — no attribute/global/subscript writes in the function or
  any resolved callee (advisory: unresolved calls don't poison it).
* ``returns_open`` — dispatch families whose un-collected generation
  this function hands BACK to its caller (the ``*_dispatch`` /
  ``begin_*`` effect system of PT013): a dispatch half returning its
  handle transfers the collect obligation up one frame.
* ``closes`` — families this function collect/resolve-calls.
* ``routes_bucket`` — bucket-shape evidence for PT014: the function
  itself (or a direct callee, one level deep — full transitivity would
  let any distant pow2 call excuse a raw local launch) calls one of
  the sanctioned bounded-shape helpers.

Cycles: every SCC is iterated to a true fixpoint — passes repeat
until no member's summary changes (the domain is finite and every
update monotone, so termination is bounded by the component's total
fact count; a fixed pass count is NOT enough when taint must cross
several hops against the component's processing order).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from plenum_tpu.analysis.engine.callgraph import CallGraph
from plenum_tpu.analysis.engine.symtab import (
    collect_families, dispatch_family)

# irregular closers: seams whose collect half doesn't follow the
# X_collect / collect_X / end_X / resolve_X naming (the merged device
# hash resolve closes BOTH deferred-apply families)
ALIAS_CLOSERS = {
    "resolve_applies": ("apply", "applies", "flush_deferred"),
    "flush_states_merged": ("flush_deferred",),
    "_resolve_and_store": ("apply",),
}

# materializing calls: handing a handle to one of these awaits it
GENERIC_CLOSERS = frozenset({
    "asarray", "array", "results", "result", "collect",
    "block_until_ready", "device_get", "copy_to_host_async",
})


def closer_closes(closer: str, family: str) -> bool:
    if family in collect_families(closer):
        return True
    if family in ALIAS_CLOSERS.get(closer, ()):
        return True
    return closer in GENERIC_CLOSERS


class FunctionSummary:
    __slots__ = ("sym", "nondet", "pure", "returns_open", "closes",
                 "routes_bucket", "opens_local",
                 "launches_param_shapes", "regions")

    def __init__(self, sym: str):
        self.sym = sym
        self.nondet: Set[str] = set()
        self.pure = True
        # family -> (line, col, via) of the site whose open generation
        # this function returns to its caller
        self.returns_open: Dict[str, Tuple[int, int, str]] = {}
        self.closes: Set[str] = set()
        self.routes_bucket = False
        # locally opened families (any disposition) — debugging aid
        self.opens_local: Set[str] = set()
        # PT014 pass-through seam: this function launches compiled
        # work whose operand shapes come in verbatim through its own
        # parameters — callers carry the bucket obligation
        self.launches_param_shapes = False
        # thread regions this function can execute in (PT016/PT017):
        # subset of {"prod", "worker", "daemon"} — filled in by
        # compute_regions after the callee-first fixpoint
        self.regions: Set[str] = set()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return ("FunctionSummary(%s nondet=%r pure=%r returns_open=%r "
                "closes=%r buckets=%r)" % (
                    self.sym, sorted(self.nondet), self.pure,
                    sorted(self.returns_open), sorted(self.closes),
                    self.routes_bucket))


def site_families(call: dict, callee: Optional[str],
                  summaries: Dict[str, FunctionSummary]
                  ) -> Dict[str, str]:
    """Families whose generation this call site OPENS, mapped to a
    'via' description: the syntactic ``*_dispatch``/``begin_*`` name,
    or the resolved callee's ``returns_open`` (a generation handed
    across functions)."""
    out: Dict[str, str] = {}
    term = call["chain"][-1] if call["chain"] else ""
    fam = dispatch_family(term)
    if fam:
        out[fam] = term
    if callee is not None:
        csum = summaries.get(callee)
        if csum:
            for f in csum.returns_open:
                out.setdefault(f, callee)
    return out


def site_verdict(call: dict, families: Dict[str, str], fn: dict,
                 local_closes: Set[str]) -> Tuple[str, List[str]]:
    """('leak'|'returned'|'ok', leaked_families) for one opening site.

    * discarded result → every family leaks;
    * bound to locals that are never used (no closer call, not
      returned, never escaping) → leaks, unless the function closes
      the family through another path (split-handle idioms);
    * returned (or produced inside a lambda) → the caller inherits;
    * anything else (stored on self, passed onward, tuple-embedded)
      escapes this frame's responsibility.
    """
    flow = call["flow"]
    if call.get("in_lambda"):
        return "ok", []
    if flow == "returned":
        return "returned", sorted(families)
    if flow == "discarded":
        leaked = [f for f in families if f not in local_closes]
        return ("leak", leaked) if leaked else ("ok", [])
    if flow == "named":
        flows = fn.get("name_flows", {})
        used = returned = False
        closers: List[str] = []
        for nm in call.get("names", ()):
            nf = flows.get(nm)
            if not nf:
                continue
            used = True
            returned = returned or nf.get("returned", False)
            closers.extend(nf.get("closers", ()))
            if nf.get("escapes"):
                return "ok", []
        if returned:
            return "returned", sorted(families)
        leaked = []
        for f in sorted(families):
            if f in local_closes:
                continue
            if any(closer_closes(c, f) for c in closers):
                continue
            if closers:
                # handed to some call we can't pair — delegated, not
                # provably leaked
                continue
            if not used:
                leaked.append(f)
        return ("leak", leaked) if leaked else ("ok", [])
    return "ok", []


def _local_closes(fn: dict) -> Set[str]:
    out: Set[str] = set()
    for call in fn["calls"]:
        term = call["chain"][-1] if call["chain"] else ""
        out.update(collect_families(term))
        out.update(ALIAS_CLOSERS.get(term, ()))
    return out


def _fingerprint(s: Optional[FunctionSummary]):
    if s is None:
        return None
    return (len(s.nondet), s.pure, tuple(sorted(s.returns_open)),
            len(s.closes), s.routes_bucket, len(s.opens_local),
            s.launches_param_shapes)


def compute_summaries(graph: CallGraph) -> Dict[str, FunctionSummary]:
    summaries: Dict[str, FunctionSummary] = {}
    for comp in graph.sccs():
        # iterate the component to a TRUE fixpoint: a fact may need
        # several passes to cross the component against its member
        # order (finite monotone domain -> guaranteed termination)
        while True:
            before = [_fingerprint(summaries.get(sym))
                      for sym in comp]
            for sym in comp:
                _summarize(graph, summaries, sym)
            if len(comp) == 1 or before == [
                    _fingerprint(summaries.get(sym))
                    for sym in comp]:
                break
    return summaries


THREAD_REGION_LABELS = ("worker", "daemon")

# terminal names that shadow builtin-container methods: the callgraph's
# unique-name fallback may bind ``some_list.extend(...)`` to the one
# project symbol named ``extend``, and region labels spread through the
# transitive closure — one bad edge mislabels a whole subsystem as
# worker-side. Region propagation therefore refuses fallback-resolved
# edges for these names; precisely resolved edges still traverse.
_CONTAINER_SHADOWS = frozenset({
    "append", "appendleft", "extend", "add", "update", "pop", "popleft",
    "get", "put", "put_nowait", "clear", "remove", "discard", "insert",
    "sort", "copy", "keys", "values", "items", "setdefault", "run",
    "send", "close", "submit", "start", "stop", "reset", "extend_hashes",
})


def _region_callees(graph: CallGraph, sym: str) -> Set[str]:
    """Callees for region propagation: precise resolution always,
    unique-name fallback only for terminals that cannot be builtin
    container/handle methods."""
    out: Set[str] = set()
    for call in graph.functions[sym].get("calls", ()):
        chain = call["chain"]
        if not chain:
            continue
        callee = graph.resolve_call(sym, chain, fallback=False)
        if callee is None and chain[-1] not in _CONTAINER_SHADOWS:
            callee = graph.resolve_call(sym, chain)
        if callee is not None:
            out.add(callee)
    return out


def _region_reach(graph: CallGraph, seeds: List[str]) -> Set[str]:
    seen: Set[str] = set()
    frontier = [s for s in seeds if s in graph.functions]
    while frontier:
        sym = frontier.pop()
        if sym in seen:
            continue
        seen.add(sym)
        frontier.extend(c for c in _region_callees(graph, sym)
                        if c not in seen)
    return seen


def spawn_roots(graph: CallGraph) -> Dict[str, str]:
    """Resolved spawn-target symbols → thread-region label.

    A function handed to ``Thread(target=...)``, ``pool.submit(...)``
    or ``loop.run_in_executor(...)`` seeds a non-prod region. The
    label is ``daemon`` when either end of the spawn lives in a
    *daemon* module/class (the verify daemon's device worker),
    ``worker`` otherwise (pipeline parse stage, exec pool)."""
    roots: Dict[str, str] = {}
    for sym, fn in graph.functions.items():
        for spawn in fn.get("spawns", ()):
            for chain in spawn.get("targets", ()):
                callee = graph.resolve_call(sym, chain, fallback=False)
                if callee is None \
                        and chain[-1] not in _CONTAINER_SHADOWS:
                    callee = graph.resolve_call(sym, chain)
                if callee is None:
                    continue
                label = "daemon" if (
                    "daemon" in sym.lower()
                    or "daemon" in callee.lower()) else "worker"
                # daemon is the more specific label — keep it if any
                # spawn site says so
                if roots.get(callee) != "daemon":
                    roots[callee] = label
    return roots


def compute_regions(graph: CallGraph) -> Dict[str, Set[str]]:
    """Executing-region sets for every function symbol.

    Forward closure from the spawn roots labels the worker/daemon
    side; everything NOT reachable from a spawn root seeds ``prod``,
    and prod's own forward closure then re-adds ``prod`` to shared
    helpers — a function called from both sides ends up
    ``{"prod", "worker"}``, which is exactly the multi-region evidence
    PT016 keys on. Functions only ever entered from a spawned thread
    (worker loops, their private callees) stay single-region."""
    regions: Dict[str, Set[str]] = {
        sym: set() for sym in graph.functions}
    roots = spawn_roots(graph)
    for label in THREAD_REGION_LABELS:
        seeds = [s for s, r in roots.items() if r == label]
        if not seeds:
            continue
        for sym in _region_reach(graph, seeds):
            regions[sym].add(label)
    prod_seeds = [sym for sym, regs in regions.items() if not regs]
    for sym in _region_reach(graph, prod_seeds):
        regions[sym].add("prod")
    return regions


def _summarize(graph: CallGraph,
               summaries: Dict[str, FunctionSummary],
               sym: str) -> None:
    fn = graph.functions[sym]
    s = summaries.get(sym) or FunctionSummary(sym)
    summaries[sym] = s
    s.nondet |= {rec["kind"] for rec in fn["nondet"]}
    s.pure = s.pure and not fn["mutates"]
    s.closes |= _local_closes(fn)
    s.routes_bucket = s.routes_bucket or fn["buckets"]
    resolved = {id(call): callee for callee, call in graph.edges[sym]}
    for call in fn["calls"]:
        callee = resolved.get(id(call))
        csum = summaries.get(callee) if callee is not None else None
        if csum:
            s.nondet |= csum.nondet
            s.pure = s.pure and csum.pure
            if graph.functions[callee]["buckets"]:
                s.routes_bucket = True
        launcher = call.get("builder") \
            or (csum is not None and csum.launches_param_shapes) \
            or graph.is_jit_callee(sym, call["chain"])
        if launcher and call.get("arg_param_only") \
                and not call.get("arg_bucketed") \
                and not fn["buckets"]:
            s.launches_param_shapes = True
        families = site_families(call, callee, summaries)
        if not families:
            continue
        s.opens_local |= set(families)
        verdict, fams = site_verdict(call, families, fn, s.closes)
        if verdict == "returned":
            for f in fams:
                s.returns_open.setdefault(
                    f, (call["line"], call["col"], families[f]))
