"""Whole-program call graph over extracted file facts.

Symbols are keyed ``module:qname`` (``plenum_tpu.ops.sha3:pad_sha3_messages``,
``plenum_tpu.state.pruning_state:PruningState.flush``). Resolution, in
decreasing confidence:

* plain name ``f()`` — a function defined in the same module, else a
  ``from m import f`` target when ``m`` is a project module;
* ``alias.f()`` — ``import m as alias`` → ``m:f`` when ``m`` is a
  project module (or ``m:Class.f`` is NOT attempted: two-element
  chains only resolve module functions);
* ``self.m()`` / ``cls.m()`` — method lookup through the enclosing
  class and its project base classes (linearized depth-first, cycle
  guarded — the decorator/method-resolution tests pin this);
* any other attribute call ``obj.m()`` — linked iff exactly ONE
  project symbol has terminal name ``m`` (the unique-name fallback:
  over-linking common verbs like ``get``/``send`` would flood the
  taint rules, so ambiguous names stay unresolved).

Cycles are first-class: ``sccs()`` returns Tarjan's strongly-connected
components in reverse topological (callee-first) order, which is the
bottom-up schedule `summaries.py` computes over — every function in a
cycle shares one fixpoint.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set, Tuple


class CallGraph:
    def __init__(self, files: Dict[str, dict]):
        """files: rel_path -> file facts (symtab.extract_file_facts)."""
        self.files = files
        # symbol -> function facts;  symbol = "module:qname"
        self.functions: Dict[str, dict] = {}
        self.fn_path: Dict[str, str] = {}
        # module -> {plain name -> symbol} for module-level functions
        self._module_funcs: Dict[str, Dict[str, str]] = {}
        # module -> file facts (import maps for base-class resolution)
        self._module_facts: Dict[str, dict] = {}
        # (module, class qname) -> class record
        self._classes: Dict[Tuple[str, str], dict] = {}
        # terminal name -> [symbols] (unique-name fallback)
        self._by_name: Dict[str, List[str]] = {}
        # jitted callables: symbols + (module, name) assignment targets
        self.jit_symbols: Set[str] = set()
        self._jit_assigned: Set[Tuple[str, str]] = set()
        self._index()
        self.edges: Dict[str, List[Tuple[str, dict]]] = {}
        self.redges: Dict[str, List[str]] = {}
        self._link()

    # ------------------------------------------------------------ index

    def _index(self) -> None:
        for path, facts in self.files.items():
            mod = facts["module"]
            self._module_facts.setdefault(mod, facts)
            mfuncs = self._module_funcs.setdefault(mod, {})
            for cname, crec in facts.get("classes", {}).items():
                self._classes[(mod, cname)] = crec
            for f in facts["functions"]:
                sym = "%s:%s" % (mod, f["qname"])
                self.functions[sym] = f
                self.fn_path[sym] = path
                if "." not in f["qname"]:
                    mfuncs[f["qname"]] = sym
                self._by_name.setdefault(f["name"], []).append(sym)
                if f.get("jitted"):
                    self.jit_symbols.add(sym)
            for jn in facts.get("jit_names", ()):
                self._jit_assigned.add((mod, jn))

    def display(self, sym: str) -> str:
        return sym.replace(":", ".", 1)

    def find_symbol(self, needle: str) -> List[str]:
        """Symbols whose display name ends with `needle` (CLI lookup)."""
        needle = needle.strip()
        out = [s for s in self.functions
               if self.display(s) == needle or s == needle]
        if out:
            return out
        return sorted(s for s in self.functions
                      if self.display(s).endswith("." + needle)
                      or self.functions[s]["qname"] == needle
                      or self.functions[s]["name"] == needle)

    # -------------------------------------------------------- resolution

    def _resolve_base(self, mod: str, base: str):
        """(module, class qname) of a base-class reference, or None."""
        facts = self._module_facts.get(mod)
        if facts is None:
            return None
        if "." in base:
            root, rest = base.split(".", 1)
            target_mod = facts["imports"].get(root)
            if target_mod and (target_mod, rest) in self._classes:
                return (target_mod, rest)
            return None
        if (mod, base) in self._classes:
            return (mod, base)
        fi = facts["from_imports"].get(base)
        if fi and (fi[0], fi[1]) in self._classes:
            return (fi[0], fi[1])
        return None

    def resolve_method(self, mod: str, cls: str,
                       name: str) -> Optional[str]:
        """Walk cls and its project bases depth-first for `name`."""
        seen: Set[Tuple[str, str]] = set()
        stack = [(mod, cls)]
        while stack:
            m, c = stack.pop(0)
            if (m, c) in seen:
                continue
            seen.add((m, c))
            rec = self._classes.get((m, c))
            if rec is None:
                continue
            if name in rec["methods"]:
                sym = "%s:%s.%s" % (m, c, name)
                if sym in self.functions:
                    return sym
            for base in rec["bases"]:
                resolved = self._resolve_base(m, base)
                if resolved:
                    stack.append(resolved)
        return None

    def resolve_call(self, caller_sym: str, chain: List[str],
                     fallback: bool = True) -> Optional[str]:
        facts = self.files[self.fn_path[caller_sym]]
        mod = facts["module"]
        fn = self.functions[caller_sym]
        if not chain:
            return None
        if len(chain) == 1:
            name = chain[0]
            local = self._module_funcs.get(mod, {}).get(name)
            if local:
                return local
            fi = facts["from_imports"].get(name)
            if fi:
                target = self._module_funcs.get(fi[0], {}).get(fi[1])
                if target:
                    return target
            return None
        if chain[0] in ("self", "cls") and fn.get("cls") \
                and len(chain) == 2:
            hit = self.resolve_method(mod, fn["cls"], chain[1])
            if hit:
                return hit
        if len(chain) == 2:
            target_mod = facts["imports"].get(chain[0])
            if target_mod:
                hit = self._module_funcs.get(target_mod, {}) \
                    .get(chain[1])
                if hit:
                    return hit
            fi = facts["from_imports"].get(chain[0])
            if fi:
                # `from pkg import mod` then mod.f()
                sub = "%s.%s" % (fi[0], fi[1])
                hit = self._module_funcs.get(sub, {}).get(chain[1])
                if hit:
                    return hit
        # unique-name fallback for attribute calls on unknown receivers
        if not fallback:
            return None
        term = chain[-1]
        cands = self._by_name.get(term, [])
        if len(cands) == 1:
            return cands[0]
        return None

    def is_jit_callee(self, caller_sym: str, chain: List[str]) -> bool:
        """Does this call site invoke a compiled (jit/pallas) callable?"""
        resolved = self.resolve_call(caller_sym, chain)
        if resolved is not None and resolved in self.jit_symbols:
            return True
        facts = self.files[self.fn_path[caller_sym]]
        mod = facts["module"]
        if len(chain) == 1:
            if (mod, chain[0]) in self._jit_assigned:
                return True
            fi = facts["from_imports"].get(chain[0])
            if fi and (fi[0], fi[1]) in self._jit_assigned:
                return True
        if len(chain) == 2:
            target_mod = facts["imports"].get(chain[0])
            if target_mod and (target_mod, chain[1]) \
                    in self._jit_assigned:
                return True
        return False

    # ----------------------------------------------------------- linking

    def _link(self) -> None:
        for sym, fn in self.functions.items():
            out: List[Tuple[str, dict]] = []
            for call in fn["calls"]:
                callee = self.resolve_call(sym, call["chain"])
                if callee is not None and callee != sym:
                    out.append((callee, call))
                    self.redges.setdefault(callee, []).append(sym)
            self.edges[sym] = out

    def callees(self, sym: str) -> List[str]:
        seen, out = set(), []
        for callee, _ in self.edges.get(sym, ()):
            if callee not in seen:
                seen.add(callee)
                out.append(callee)
        return out

    def callers(self, sym: str) -> List[str]:
        seen, out = set(), []
        for caller in self.redges.get(sym, ()):
            if caller not in seen:
                seen.add(caller)
                out.append(caller)
        return out

    def reachable_from(self, roots: Iterable[str]) -> Set[str]:
        """Forward closure over call edges (cycle-safe)."""
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.functions]
        while stack:
            sym = stack.pop()
            if sym in seen:
                continue
            seen.add(sym)
            stack.extend(c for c in self.callees(sym) if c not in seen)
        return seen

    # -------------------------------------------------------------- SCC

    def sccs(self) -> List[List[str]]:
        """Tarjan strongly-connected components, callee-first (reverse
        topological) — the bottom-up summary schedule. Iterative: the
        project graph is deep enough to blow the recursion limit."""
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        out: List[List[str]] = []
        counter = [0]

        for start in self.functions:
            if start in index:
                continue
            work: List[Tuple[str, int]] = [(start, 0)]
            while work:
                node, ei = work[-1]
                if ei == 0:
                    index[node] = low[node] = counter[0]
                    counter[0] += 1
                    stack.append(node)
                    on_stack.add(node)
                advanced = False
                callees = self.callees(node)
                while ei < len(callees):
                    nxt = callees[ei]
                    ei += 1
                    if nxt not in index:
                        work[-1] = (node, ei)
                        work.append((nxt, 0))
                        advanced = True
                        break
                    if nxt in on_stack:
                        low[node] = min(low[node], index[nxt])
                if advanced:
                    continue
                work[-1] = (node, ei)
                if ei >= len(callees):
                    work.pop()
                    if work:
                        parent = work[-1][0]
                        low[parent] = min(low[parent], low[node])
                    if low[node] == index[node]:
                        comp = []
                        while True:
                            w = stack.pop()
                            on_stack.discard(w)
                            comp.append(w)
                            if w == node:
                                break
                        out.append(comp)
        return out
