"""Engine facade — build facts (cached), link, summarize, query.

``Engine.build(files, root)`` is what the Analyzer and the CLI call:
it reads every file once, reuses cached facts for unchanged content,
links the call graph and computes summaries. ``stats`` records how
much work the cache saved (the repeat-run speedup test pins this).
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence, Set

from plenum_tpu.analysis.engine.cache import FactsCache, content_hash
from plenum_tpu.analysis.engine.callgraph import CallGraph
from plenum_tpu.analysis.engine.summaries import (
    FunctionSummary, compute_regions, compute_summaries)
from plenum_tpu.analysis.engine.symtab import extract_file_facts


class Engine:
    def __init__(self, files: Dict[str, dict], root: str,
                 parse_errors: Dict[str, str], stats: dict):
        self.files = files              # rel_path -> facts
        self.root = root
        self.parse_errors = parse_errors
        self.stats = stats
        self.graph = CallGraph(files)
        self.summaries: Dict[str, FunctionSummary] = \
            compute_summaries(self.graph)
        # executing-region sets (prod/worker/daemon) per symbol, and
        # mirrored onto the summaries for --callgraph triage
        self.regions: Dict[str, Set[str]] = compute_regions(self.graph)
        for sym, regs in self.regions.items():
            s = self.summaries.get(sym)
            if s is not None:
                s.regions = regs

    # ------------------------------------------------------------ build

    @classmethod
    def build(cls, paths: Sequence[str], root: str,
              cache: Optional[FactsCache] = None,
              use_cache: bool = True) -> "Engine":
        """paths: absolute .py files forming the program scope."""
        root = os.path.abspath(root)
        if cache is None and use_cache:
            cache = FactsCache.for_root(root)
        t0 = time.perf_counter()
        files: Dict[str, dict] = {}
        parse_errors: Dict[str, str] = {}
        parsed = cached = 0
        for path in paths:
            rel = os.path.relpath(os.path.abspath(path), root) \
                .replace(os.sep, "/")
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError as e:
                parse_errors[rel] = str(e)
                continue
            sha = content_hash(data)
            facts = cache.get(rel, sha) if cache else None
            if facts is None:
                try:
                    facts = extract_file_facts(
                        rel, data.decode("utf-8", errors="replace"))
                except (SyntaxError, ValueError) as e:
                    parse_errors[rel] = str(e)
                    continue
                parsed += 1
                if cache:
                    cache.put(rel, sha, facts)
            else:
                cached += 1
            files[rel] = facts
        if cache:
            cache.prune(list(files) + list(parse_errors))
            cache.save()
        stats = {"files": len(files), "parsed": parsed,
                 "cached": cached, "build_s": 0.0}
        eng = cls(files, root, parse_errors, stats)
        # whole build including linking + summaries: the cache-speedup
        # gate compares THIS cold vs warm, not just extraction
        stats["build_s"] = time.perf_counter() - t0
        return eng

    # ------------------------------------------------------------ query

    def suppressed(self, rel_path: str, code: str, line: int) -> bool:
        facts = self.files.get(rel_path)
        if not facts:
            return False
        pragmas = facts.get("pragmas", {})
        code = code.upper()
        head = pragmas.get("file", ())
        if "ALL" in head or code in head:
            return True
        at = pragmas.get("lines", {}).get(str(line), ())
        return "ALL" in at or code in at

    def symbol_display(self, sym: str) -> str:
        return self.graph.display(sym)

    def function(self, sym: str) -> Optional[dict]:
        return self.graph.functions.get(sym)

    def path_of(self, sym: str) -> str:
        return self.graph.fn_path[sym]

    def roots_matching(self, specs) -> List[str]:
        """Symbols matching (rel_path, compiled-regex-on-qname) specs."""
        out: List[str] = []
        for sym, fn in self.graph.functions.items():
            path = self.graph.fn_path[sym]
            for spec_path, rx in specs:
                if path == spec_path and rx.search(fn["qname"]):
                    out.append(sym)
                    break
        return out

    def reachable(self, roots: Sequence[str]) -> Set[str]:
        return self.graph.reachable_from(roots)
