"""Per-file fact extraction — the cacheable layer of the engine.

``extract_file_facts(rel_path, source)`` parses one module and distills
everything the whole-program layers need into plain JSON-able dicts:

* the **symbol table** entry for every function/method (including
  nested defs), with decorator records and the enclosing class;
* the **import maps** (``import x.y as z`` / ``from a import b as c``)
  the callgraph resolves names through;
* every **call site** with its terminal name chain and the local flow
  of its *result* — ``returned`` / ``named`` (bound to locals, whose
  later uses are summarized) / ``escapes`` (stored, passed on,
  embedded in a container) / ``discarded`` (bare expression
  statement). PT013 reads dispatch-handle lifecycles straight off
  this;
* **rule facts**: nondeterminism sources (PT012), dispatch/collect
  effects (PT013), jitted-callable definitions, device-launch shapes
  and bucket-routing evidence (PT014);
* the file's **pragma map**, so whole-program findings still honor
  ``# plenum-lint: disable=PTxxx``.

No AST node survives into the output — that is what makes the cache
(`cache.py`) a straight JSON dump keyed by content hash.
"""
from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional, Set, Tuple

from plenum_tpu.analysis.core import attr_parts, dotted, iter_pragmas

# bump when the extraction output changes shape or meaning — stale
# cache entries from an older extractor must never feed the linker
FACTS_VERSION = 2

# sanctioned bounded-shape helpers: a device launch routes through a
# bucket iff one of these is called on the way to the shape (PR 9's
# r05 regression and the PR 6 per-distinct-size Keccak compiles are
# both "forgot to round the batch axis" bugs)
BUCKET_HELPERS = frozenset({
    "pow2_at_least", "launch_lanes", "padded_size",
    "pad_messages", "pad_sha3_messages", "scatter_ragged_rows",
})

# random-module entropy sources (an unseeded module-level generator;
# seeded instances — self._rng.choice — resolve to a different chain
# root and stay out)
RANDOM_FNS = frozenset({
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "getrandbits", "randbytes", "betavariate",
    "gauss", "normalvariate", "expovariate",
})

# wall-clock reads that are nondeterministic across replicas when the
# VALUE escapes into state/messages (timer deltas never escape)
TIME_FNS = frozenset({
    "time", "time_ns", "monotonic", "monotonic_ns",
    "perf_counter", "perf_counter_ns",
})

_STR_BUILDERS = frozenset({"str", "repr", "format", "hex", "chr"})
_STR_METHODS = frozenset({"format", "encode", "decode", "join", "hex",
                          "lower", "upper", "strip"})

# ---- thread-region facts (PT016/PT017) --------------------------------

# names that mean "this context manager is a lock" — shared vocabulary
# with the PT004 heuristic so the engine-backed rules agree with the
# fallback on what counts as locked
LOCKISH = ("lock", "mutex", "cond", "sem")

# ast nodes that build a fresh MUTABLE container — the shapes that must
# not cross a thread queue (immutable bytes/views/frozen records do)
_MUTABLE_BUILDS = (ast.Dict, ast.List, ast.Set, ast.ListComp,
                   ast.SetComp, ast.DictComp)
_MUTABLE_CTORS = frozenset({"dict", "list", "set", "bytearray",
                            "defaultdict", "deque"})

# method names that mutate their receiver in place — used to detect a
# payload mutated AFTER it was handed over a queue. Deliberately a
# whitelist: matching any later line would false-positive on
# else-branches that merely mention the name (job.run() after put)
_MUTATOR_METHODS = frozenset({
    "append", "appendleft", "add", "update", "extend", "insert",
    "remove", "discard", "pop", "popleft", "clear", "setdefault",
})


def _lockish_name(name: str) -> bool:
    low = name.lower()
    return any(frag in low for frag in LOCKISH)


def _root_name(node: ast.AST) -> Optional[str]:
    """Base Name of an attribute/subscript chain, or None."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def is_bucket_helper(name: str) -> bool:
    """Sanctioned-helper check, alias-tolerant: ``from ops import
    pow2_at_least as _pow2_at_least`` must still count."""
    return name.lstrip("_") in BUCKET_HELPERS


def module_name(rel_path: str) -> str:
    """'plenum_tpu/ops/sha3.py' → 'plenum_tpu.ops.sha3';
    '__init__.py' collapses onto its package."""
    mod = rel_path[:-3] if rel_path.endswith(".py") else rel_path
    parts = mod.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def dispatch_family(name: str) -> Optional[str]:
    """The effect family a call NAME opens, or None. ``X_dispatch`` /
    ``dispatch_X`` / ``begin_X`` all open family ``X``."""
    if name.endswith("_dispatch") and len(name) > len("_dispatch"):
        return name[: -len("_dispatch")]
    if name.startswith("dispatch_") and len(name) > len("dispatch_"):
        return name[len("dispatch_"):]
    if name.startswith("begin_") and len(name) > len("begin_"):
        return name[len("begin_"):]
    return None


def collect_families(name: str) -> List[str]:
    """Families a call NAME closes: ``X_collect`` / ``collect_X`` /
    ``end_X`` / ``resolve_X`` / ``X_resolve``."""
    out: List[str] = []
    if name.endswith("_collect"):
        out.append(name[: -len("_collect")])
    if name.startswith("collect_"):
        out.append(name[len("collect_"):])
    if name.startswith("end_"):
        out.append(name[len("end_"):])
    if name.startswith("resolve_"):
        out.append(name[len("resolve_"):])
    if name.endswith("_resolve"):
        out.append(name[: -len("_resolve")])
    return out


def _chain(node: ast.AST) -> List[str]:
    """Root-first attribute chain of a call target: ``self.state.get``
    → ['self', 'state', 'get']; [] when the root is dynamic."""
    parts = attr_parts(node)
    if not parts:
        return []
    # attr_parts is leaf-first, with the Name root appended last only
    # when the chain bottoms out at a Name
    if isinstance(node, ast.Name) or (
            isinstance(node, ast.Attribute) and _has_name_root(node)):
        return list(reversed(parts))
    return ["<dyn>"] + list(reversed(parts))


def _has_name_root(node: ast.AST) -> bool:
    while isinstance(node, ast.Attribute):
        node = node.value
    return isinstance(node, ast.Name)


def _decorator_record(dec: ast.AST) -> str:
    """Stable string for one decorator: dotted name, or
    ``outer(inner)`` for call decorators like
    ``functools.partial(jax.jit, ...)``."""
    if isinstance(dec, ast.Call):
        outer = dotted(dec.func) or "<dyn>"
        inner = ""
        if dec.args:
            inner = dotted(dec.args[0]) or ""
        return "%s(%s)" % (outer, inner) if inner else outer
    return dotted(dec) or "<dyn>"


def _is_jit_expr(node: ast.AST) -> bool:
    """True for expressions producing a compiled callable:
    ``jax.jit(...)``, ``partial(jax.jit, ...)``, ``pl.pallas_call(...)``."""
    if not isinstance(node, ast.Call):
        return False
    name = dotted(node.func)
    if name in ("jax.jit", "jit") or (name or "").endswith("pallas_call"):
        return True
    if name in ("functools.partial", "partial") and node.args:
        first = dotted(node.args[0])
        return first in ("jax.jit", "jit")
    return False


def _jit_decorated(decorators: List[str]) -> bool:
    for d in decorators:
        if d in ("jit", "jax.jit") or d.startswith(("jax.jit(", "jit(")):
            return True
        if d.startswith(("functools.partial(", "partial(")) \
                and ("jax.jit" in d or "(jit" in d):
            return True
        if "pallas_call" in d:
            return True
    return False


class _FunctionExtractor:
    """One function's facts: call sites with result flow, local-name
    flows, nondeterminism sources, dispatch effects, launch evidence."""

    def __init__(self, fn: ast.AST, qname: str, cls: Optional[str],
                 imports: Dict[str, str],
                 from_imports: Dict[str, Tuple[str, str]]):
        self.fn = fn
        self.qname = qname
        self.cls = cls
        self.imports = imports
        self.from_imports = from_imports
        self.parents: Dict[int, ast.AST] = {}
        self.calls: List[dict] = []
        self.nondet: List[dict] = []
        self.attr_writes: List[dict] = []
        self.spawns: List[dict] = []
        self.handoffs: List[dict] = []
        self.name_flows: Dict[str, dict] = {}
        self.mutates = False
        self.buckets = False
        self.params: Set[str] = set()
        self._str_names: Set[str] = set()
        self._set_names: Set[str] = set()
        self._bucket_names: Set[str] = set()
        self._cond_names: Set[str] = set()
        self._const_names: Set[str] = set()
        # locals derived purely from parameters/consts: launches fed
        # by these are pass-through too (the caller owns the shapes)
        self._passthrough: Set[str] = set()

    # ------------------------------------------------------------ walk

    def run(self) -> dict:
        fn = self.fn
        for parent in self._walk_own(fn):
            for child in ast.iter_child_nodes(parent):
                self.parents[id(child)] = parent
        args = fn.args
        self.params = {a.arg for a in
                       list(args.posonlyargs) + list(args.args)
                       + list(args.kwonlyargs)}
        if args.vararg:
            self.params.add(args.vararg.arg)
        if args.kwarg:
            self.params.add(args.kwarg.arg)
        self._prepass()
        self._extract_calls()
        self._extract_name_flows()
        self._extract_nondet()
        self._extract_threading()
        decorators = [_decorator_record(d)
                      for d in getattr(fn, "decorator_list", ())]
        return {
            "qname": self.qname,
            "name": fn.name,
            "cls": self.cls,
            "params": sorted(self.params),
            "line": fn.lineno,
            "col": fn.col_offset,
            "is_async": isinstance(fn, ast.AsyncFunctionDef),
            "decorators": decorators,
            "jitted": _jit_decorated(decorators),
            "calls": self.calls,
            "nondet": self.nondet,
            "name_flows": self.name_flows,
            "mutates": self.mutates,
            "buckets": self.buckets,
            "attr_writes": self.attr_writes,
            "spawns": self.spawns,
            "handoffs": self.handoffs,
        }

    def _walk_own(self, fn: ast.AST):
        """The function's own statements — nested def/class bodies are
        separate symbols (lambdas stay: they share the local scope)."""
        yield fn
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            n = stack.pop()
            yield n
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                stack.extend(ast.iter_child_nodes(n))

    def _parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(id(node))

    def _enclosing(self, node: ast.AST, kinds) -> bool:
        cur = self._parent(node)
        while cur is not None and cur is not self.fn:
            if isinstance(cur, kinds):
                return True
            cur = self._parent(cur)
        return False

    # --------------------------------------------------------- prepass

    def _prepass(self) -> None:
        """Flow-insensitive fixpoint binding local names to string-ish
        / set-origin / bucket-derived / const / param-passthrough
        values (iterated until no set grows: assignment chains resolve
        regardless of statement order)."""
        assigns = [n for n in self._walk_own(self.fn)
                   if isinstance(n, ast.Assign)]
        changed = True
        while changed:
            changed = False
            for a in assigns:
                names = [t.id for t in a.targets
                         if isinstance(t, ast.Name)]
                if not names:
                    continue
                before = (len(self._str_names), len(self._set_names),
                          len(self._bucket_names),
                          len(self._cond_names),
                          len(self._const_names),
                          len(self._passthrough))
                if self._stringish(a.value):
                    self._str_names.update(names)
                if self._set_origin(a.value):
                    self._set_names.update(names)
                if self._bucket_expr(a.value):
                    self._bucket_names.update(names)
                if self._cond_bucket_expr(a.value):
                    self._cond_names.update(names)
                roots = self._filtered_roots(a.value) \
                    - self._const_names
                if not roots:
                    # value carries no caller data at all (config
                    # reads, literals): shape-innocent
                    self._const_names.update(names)
                elif roots <= (self.params | self._passthrough):
                    self._passthrough.update(names)
                after = (len(self._str_names), len(self._set_names),
                         len(self._bucket_names),
                         len(self._cond_names),
                         len(self._const_names),
                         len(self._passthrough))
                changed = changed or before != after
        for n in self._walk_own(self.fn):
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (n.targets if isinstance(n, ast.Assign)
                           else [n.target])
                for t in targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        self.mutates = True
            elif isinstance(n, (ast.Global, ast.Nonlocal)):
                self.mutates = True

    # ------------------------------------------------- value predicates

    def _stringish(self, expr: ast.AST) -> bool:
        """Provably str/bytes-valued (so ``hash()`` of it is salted by
        PYTHONHASHSEED and diverges across replica processes)."""
        if isinstance(expr, ast.Constant):
            return isinstance(expr.value, (str, bytes))
        if isinstance(expr, ast.JoinedStr):
            return True
        if isinstance(expr, ast.Tuple):
            return any(self._stringish(e) for e in expr.elts)
        if isinstance(expr, ast.BinOp) and isinstance(
                expr.op, (ast.Add, ast.Mod)):
            return self._stringish(expr.left) \
                or self._stringish(expr.right)
        if isinstance(expr, ast.Call):
            if isinstance(expr.func, ast.Name) \
                    and expr.func.id in _STR_BUILDERS:
                return True
            if isinstance(expr.func, ast.Attribute) \
                    and expr.func.attr in _STR_METHODS:
                return True
            return False
        if isinstance(expr, ast.Name):
            return expr.id in self._str_names
        return False

    def _set_origin(self, expr: ast.AST) -> bool:
        """Iteration over this expression is hash-order (unordered):
        set literals, set()/frozenset(), set algebra. Dicts stay out —
        CPython dict iteration is insertion-ordered, deterministic
        whenever the insertions are."""
        if isinstance(expr, (ast.Set, ast.SetComp)):
            return True
        if isinstance(expr, ast.Call):
            if isinstance(expr.func, ast.Name) \
                    and expr.func.id in ("set", "frozenset"):
                return True
            if isinstance(expr.func, ast.Attribute) \
                    and expr.func.attr in (
                        "union", "intersection", "difference",
                        "symmetric_difference") \
                    and self._set_origin(expr.func.value):
                return True
            return False
        if isinstance(expr, ast.BinOp) and isinstance(
                expr.op, (ast.BitAnd, ast.BitOr, ast.Sub, ast.BitXor)):
            return self._set_origin(expr.left) \
                and self._set_origin(expr.right)
        if isinstance(expr, ast.Name):
            return expr.id in self._set_names
        return False

    def _bucket_expr(self, expr: ast.AST) -> bool:
        """Bucket-routed on EVERY branch: an IfExp only counts when
        both arms route (``padded_size(B) if sharded else B`` is the
        r05 bug shape, not evidence)."""
        if isinstance(expr, ast.IfExp):
            return self._bucket_expr(expr.body) \
                and self._bucket_expr(expr.orelse)
        if isinstance(expr, ast.Call):
            ch = _chain(expr.func)
            if ch and is_bucket_helper(ch[-1]):
                return True
            return any(self._bucket_expr(a) for a in
                       list(expr.args) +
                       [k.value for k in expr.keywords])
        if isinstance(expr, ast.Name):
            return expr.id in self._bucket_names
        return any(self._bucket_expr(c)
                   for c in ast.iter_child_nodes(expr))

    def _cond_bucket_expr(self, expr: ast.AST) -> bool:
        """Bucket-routed on SOME branch but raw on another — the
        conditional half-bucketing PT014 flags outright."""
        if isinstance(expr, ast.IfExp):
            body = self._bucket_expr(expr.body)
            orelse = self._bucket_expr(expr.orelse)
            if body != orelse:
                return True
            return self._cond_bucket_expr(expr.body) \
                or self._cond_bucket_expr(expr.orelse)
        if isinstance(expr, ast.Name):
            return expr.id in self._cond_names
        return any(self._cond_bucket_expr(c)
                   for c in ast.iter_child_nodes(expr))

    # ----------------------------------------------------------- calls

    def _disposition(self, call: ast.Call) -> Tuple[str, List[str]]:
        """How the call's RESULT flows locally."""
        node: ast.AST = call
        parent = self._parent(node)
        while isinstance(parent, (ast.Await, ast.Tuple, ast.Starred)):
            node = parent
            parent = self._parent(node)
        if isinstance(parent, (ast.Return, ast.Yield, ast.YieldFrom)):
            return "returned", []
        if isinstance(parent, ast.Lambda):
            # a lambda body's value is returned to the lambda's caller
            return "returned", []
        if isinstance(parent, ast.Assign) and parent.value in (
                call, node):
            names, escapes = [], False
            for t in parent.targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        names.append(sub.id)
                    elif isinstance(sub, (ast.Attribute, ast.Subscript)):
                        escapes = True
            if escapes and not names:
                return "escapes", []
            if names:
                return "named", names
            return "escapes", []
        if isinstance(parent, ast.Expr):
            return "discarded", []
        return "escapes", []

    def _extract_calls(self) -> None:
        for node in self._walk_own(self.fn):
            if not isinstance(node, ast.Call):
                continue
            chain = _chain(node.func)
            if not chain:
                # dynamic callee: record the builder-launch pattern
                # _build_x(...)(...) — the repo's lru_cached jit
                # builders — and drop the rest
                if isinstance(node.func, ast.Call):
                    inner = _chain(node.func.func)
                    if inner and inner[-1].startswith("_build"):
                        flow, names = self._disposition(node)
                        self.calls.append(self._call_record(
                            node, ["<built>", inner[-1]], flow, names,
                            builder=True))
                continue
            terminal = chain[-1]
            if is_bucket_helper(terminal):
                self.buckets = True
            flow, names = self._disposition(node)
            self.calls.append(self._call_record(node, chain, flow,
                                                names))

    def _call_record(self, node: ast.Call, chain: List[str],
                     flow: str, names: List[str],
                     builder: bool = False) -> dict:
        args_all_const = True
        for a in list(node.args) + [k.value for k in node.keywords]:
            if not isinstance(a, ast.Constant):
                args_all_const = False
                break
        call_args = list(node.args) + [k.value for k in node.keywords]
        arg_bucketed = any(self._bucket_expr(a) for a in call_args)
        arg_cond = any(self._cond_bucket_expr(a) for a in call_args)
        # data roots of the operand expressions: a launch whose
        # operands all come in through the function's own parameters
        # is a pass-through seam — the CALLER shaped them, so the
        # bucket obligation lifts one frame up (summaries propagate
        # it as launches_param_shapes); self-rooted operands belong
        # to the object, whose class carries the evidence
        roots: Set[str] = set()
        for a in call_args:
            self._data_roots(a, roots)
        roots = {r for r in roots
                 if r not in self.imports
                 and r not in self.from_imports
                 and not r.isupper()
                 and r not in self._const_names}
        self_rooted = bool(roots & {"self", "cls"})
        caller_shaped = self.params | self._passthrough
        # empty roots = operands carry no caller data at all (module
        # constants, literal shapes): fixed per process, neither a
        # lift nor a finding — param_only must NOT be vacuously true
        # or const-shaped helpers would push a phantom bucket
        # obligation onto every caller
        arg_static = not roots
        arg_param_only = (not self_rooted and bool(roots)
                          and roots <= caller_shaped)
        arg_self_rooted = self_rooted and (
            roots - {"self", "cls"}) <= caller_shaped
        rec = {
            "chain": chain,
            "line": node.lineno,
            "col": node.col_offset,
            "flow": flow,
            "names": names,
            "in_except": self._enclosing(node, ast.ExceptHandler),
            "in_lambda": self._enclosing(node, ast.Lambda),
            "args_all_const": args_all_const,
            "arg_static": arg_static,
            "arg_bucketed": arg_bucketed,
            "arg_cond_bucketed": arg_cond,
            "arg_param_only": arg_param_only,
            "arg_self_rooted": arg_self_rooted,
        }
        if builder:
            rec["builder"] = True
        return rec

    def _filtered_roots(self, expr: ast.AST) -> Set[str]:
        roots: Set[str] = set()
        self._data_roots(expr, roots)
        return {r for r in roots
                if r not in self.imports
                and r not in self.from_imports
                and not r.isupper()}

    # size aggregators: their result is a NEW scalar shape decision
    # made HERE, not a caller-shaped extent passing through — a launch
    # fed by one owns the bucket obligation locally (the pre-fix
    # per-level Keccak shape: nblocks = max(need), raw len(msgs) rows)
    _SIZE_DECIDERS = frozenset({"len", "max", "min", "sum"})

    def _data_roots(self, expr: ast.AST, out: Set[str]) -> None:
        """Base names of the value-carrying chains in an operand
        expression — subscript indices and callee NAMES are not data
        (``self._levels[h]`` is rooted at self). ``len()``/``max()``
        results root at the '<decided>' sentinel (never a parameter),
        severing pass-through."""
        if isinstance(expr, ast.Name):
            out.add(expr.id)
            return
        if isinstance(expr, (ast.Attribute, ast.Subscript)):
            self._data_roots(expr.value, out)
            return
        if isinstance(expr, ast.Call):
            if isinstance(expr.func, ast.Name) \
                    and expr.func.id in self._SIZE_DECIDERS:
                out.add("<decided>")
                return
            if isinstance(expr.func, ast.Attribute):
                self._data_roots(expr.func.value, out)
            for a in expr.args:
                self._data_roots(a, out)
            for k in expr.keywords:
                self._data_roots(k.value, out)
            return
        for c in ast.iter_child_nodes(expr):
            self._data_roots(c, out)

    # ------------------------------------------------------ name flows

    def _extract_name_flows(self) -> None:
        """Summarize how each local is USED — enough for handle
        lifecycle checks without keeping the AST."""
        for node in self._walk_own(self.fn):
            if not isinstance(node, ast.Name) \
                    or not isinstance(node.ctx, ast.Load):
                continue
            flow = self.name_flows.setdefault(
                node.id, {"returned": False, "escapes": False,
                          "closers": []})
            parent = self._parent(node)
            # receiver of a method call: h.results() / h.collect()
            if isinstance(parent, ast.Attribute) \
                    and parent.value is node:
                gp = self._parent(parent)
                if isinstance(gp, ast.Call) and gp.func is parent:
                    if parent.attr not in flow["closers"]:
                        flow["closers"].append(parent.attr)
                    continue
                flow["escapes"] = True
                continue
            # climb through tuple/await wrappers
            n: ast.AST = node
            while isinstance(parent, (ast.Tuple, ast.Await,
                                      ast.Starred, ast.List)):
                n = parent
                parent = self._parent(n)
            if isinstance(parent, (ast.Return, ast.Yield,
                                   ast.YieldFrom)):
                flow["returned"] = True
            elif isinstance(parent, ast.Call) and parent.func is not n:
                ch = _chain(parent.func)
                closer = ch[-1] if ch else "<dyn>"
                if closer not in flow["closers"]:
                    flow["closers"].append(closer)
            elif isinstance(parent, (ast.Assign, ast.AnnAssign)):
                # value re-bound to another local: treat as escaping
                # (handle aliasing is out of scope for v1)
                flow["escapes"] = True
            elif parent is not None and not isinstance(
                    parent, (ast.Expr, ast.Compare, ast.BoolOp,
                             ast.UnaryOp, ast.If, ast.While)):
                flow["escapes"] = True

    # --------------------------------------------------------- nondet

    def _extract_nondet(self) -> None:
        for node in self._walk_own(self.fn):
            if isinstance(node, ast.Call):
                self._nondet_call(node)
            elif isinstance(node, ast.For):
                self._nondet_iter(node.iter)
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.DictComp, ast.GeneratorExp)):
                for gen in node.generators:
                    self._nondet_iter(gen.iter)

    def _note(self, node: ast.AST, kind: str, detail: str) -> None:
        self.nondet.append({"kind": kind, "line": node.lineno,
                            "col": node.col_offset, "detail": detail})

    def _module_of(self, chain: List[str]) -> Optional[Tuple[str, str]]:
        """(module, func) for a 1–2 element chain through the import
        maps; None when the root isn't an imported module."""
        if len(chain) == 1:
            tgt = self.from_imports.get(chain[0])
            if tgt:
                return tgt[0], tgt[1]
            return None
        if len(chain) == 2:
            mod = self.imports.get(chain[0])
            if mod:
                return mod, chain[1]
        return None

    def _nondet_call(self, node: ast.Call) -> None:
        chain = _chain(node.func)
        if not chain:
            return
        if chain == ["hash"] and node.args:
            if self._stringish(node.args[0]):
                self._note(node, "hash-salted",
                           "hash() of a str/bytes value")
            return
        if chain == ["id"] and node.args:
            self._note(node, "id", "id() of an object")
            return
        resolved = self._module_of(chain)
        if resolved:
            mod, fn_name = resolved
            if mod == "random" and fn_name in RANDOM_FNS:
                self._note(node, "random",
                           "unseeded random.%s()" % fn_name)
            elif mod == "time" and fn_name in TIME_FNS:
                flow, names = self._disposition(node)
                returned = flow == "returned" or any(
                    self.name_flows.get(nm, {}).get("returned")
                    for nm in names)
                if returned:
                    self._note(node, "time-value",
                               "time.%s() escapes as a value"
                               % fn_name)

    def _nondet_iter(self, it: ast.AST) -> None:
        if self._set_origin(it):
            self._note(it, "set-iter",
                       "iteration over a set (hash order)")

    # ---------------------------------------- thread regions (PT016/17)

    def _under_lock(self, node: ast.AST) -> bool:
        """Enclosed by a ``with <something lock-ish>`` block."""
        cur = self._parent(node)
        while cur is not None and cur is not self.fn:
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    for sub in ast.walk(item.context_expr):
                        name = sub.attr if isinstance(
                            sub, ast.Attribute) else (
                            sub.id if isinstance(sub, ast.Name)
                            else None)
                        if name and _lockish_name(name):
                            return True
            cur = self._parent(cur)
        return False

    def _spawn_payload(self, expr: ast.AST):
        """(target chains, captured self-attrs) of a callable handed to
        another thread. A lambda target contributes every call chain in
        its body (they all run on the spawned thread) plus the self
        attributes it closes over — the closure-capture evidence
        PT017's escape check reads."""
        if isinstance(expr, ast.Lambda):
            targets: List[List[str]] = []
            captured: Set[str] = set()
            for n in ast.walk(expr.body):
                if isinstance(n, ast.Call):
                    ch = _chain(n.func)
                    if ch:
                        targets.append(ch)
                elif isinstance(n, ast.Attribute) \
                        and isinstance(n.value, ast.Name) \
                        and n.value.id == "self" \
                        and isinstance(n.ctx, ast.Load):
                    parent = self._parent(n)
                    invoked = isinstance(parent, ast.Call) \
                        and parent.func is n
                    if not invoked:
                        captured.add(n.attr)
            return targets, sorted(captured)
        ch = _chain(expr)
        return ([ch] if ch else []), []

    def _extract_threading(self) -> None:
        """Thread-creation, queue-handoff, and self-attr write facts —
        the raw material the region propagation (summaries.py) and the
        PT016/PT017 ownership rules consume."""
        # self-attribute rebinds (subscript stores excluded: the
        # sanctioned Tracer fixed-slot pattern writes into preallocated
        # ring slots, which is not an attribute rebind). A ``*_locked``
        # function name is the repo's caller-holds-the-lock convention
        # (ops/mesh.py) — its writes count as locked.
        fn_locked = self.fn.name.endswith("_locked")
        for node in self._walk_own(self.fn):
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    if isinstance(tgt, ast.Attribute) \
                            and isinstance(tgt.value, ast.Name) \
                            and tgt.value.id == "self":
                        self.attr_writes.append({
                            "attr": tgt.attr,
                            "line": node.lineno,
                            "col": node.col_offset,
                            "locked": fn_locked
                            or self._under_lock(node),
                        })
        # in-place name mutations, for the mutated-after-handoff check
        mutations: List[Tuple[int, str]] = []
        for node in self._walk_own(self.fn):
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        root = _root_name(tgt)
                        if root:
                            mutations.append((node.lineno, root))
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATOR_METHODS:
                root = _root_name(node.func.value)
                if root:
                    mutations.append((node.lineno, root))
        # spawns and handoffs
        for node in self._walk_own(self.fn):
            if not isinstance(node, ast.Call):
                continue
            chain = _chain(node.func)
            if not chain:
                continue
            terminal = chain[-1]
            target_expr = None
            kind = None
            if terminal == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        target_expr = kw.value
                        kind = "thread"
            elif terminal == "submit" and len(chain) >= 2 and node.args:
                target_expr = node.args[0]
                kind = "submit"
            elif terminal == "run_in_executor" and len(node.args) >= 2:
                target_expr = node.args[1]
                kind = "run_in_executor"
            if kind is not None and target_expr is not None:
                targets, captured = self._spawn_payload(target_expr)
                if targets or captured:
                    self.spawns.append({
                        "kind": kind,
                        "targets": targets,
                        "captured_attrs": captured,
                        "line": node.lineno,
                        "col": node.col_offset,
                    })
                continue
            if terminal in ("put", "put_nowait") and len(chain) >= 2 \
                    and node.args:
                arg0 = node.args[0]
                mutable = isinstance(arg0, _MUTABLE_BUILDS) or (
                    isinstance(arg0, ast.Call)
                    and isinstance(arg0.func, ast.Name)
                    and arg0.func.id in _MUTABLE_CTORS)
                arg_names = sorted({a.id for a in node.args
                                    if isinstance(a, ast.Name)})
                mutated_after = sorted({
                    nm for ln, nm in mutations
                    if nm in arg_names and ln > node.lineno})
                self.handoffs.append({
                    "op": terminal,
                    "recv": ".".join(chain[:-1]),
                    "line": node.lineno,
                    "col": node.col_offset,
                    "arg_mutable": mutable,
                    "mutable_kind": (type(arg0).__name__.lower()
                                     if isinstance(arg0, _MUTABLE_BUILDS)
                                     else (arg0.func.id if mutable
                                           else "")),
                    "arg_names": arg_names,
                    "mutated_after": mutated_after,
                })


def _scan_pragmas(source: str) -> dict:
    """The engine's JSON-able view of core.iter_pragmas (one shared
    pragma implementation — suppression must agree across layers)."""
    lines: Dict[str, List[str]] = {}
    file_codes: List[str] = []
    for i, codes, file_wide in iter_pragmas(source.splitlines()):
        lines.setdefault(str(i), []).extend(sorted(codes))
        if file_wide:
            file_codes.extend(codes)
    return {"file": sorted(set(file_codes)), "lines": lines}


def extract_file_facts(rel_path: str, source: str) -> dict:
    """Parse one module → its JSON-able fact record. Raises
    SyntaxError/ValueError like ast.parse (callers map that to PT000)."""
    tree = ast.parse(source, filename=rel_path)
    imports: Dict[str, str] = {}
    from_imports: Dict[str, Tuple[str, str]] = {}
    classes: Dict[str, dict] = {}
    functions: List[dict] = []
    jit_names: List[str] = []

    for node in tree.body:
        if isinstance(node, ast.Import):
            for alias in node.names:
                imports[alias.asname or alias.name.split(".")[0]] = \
                    alias.name
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for alias in node.names:
                from_imports[alias.asname or alias.name] = \
                    (node.module, alias.name)
        # jit assignments are picked up by visit_scope below (it walks
        # module scope too — one detector, class-level included)

    def _block_stmts(body):
        """Statements of a scope INCLUDING control-flow blocks — a def
        nested inside ``if config.PIPELINE_ENABLED:`` (the node's
        pipeline wiring) is still a symbol. Function/class bodies stay
        out: they are their own scopes."""
        for node in body:
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for field in ("body", "orelse", "finalbody"):
                yield from _block_stmts(getattr(node, field, None) or [])
            for h in getattr(node, "handlers", None) or []:
                yield from _block_stmts(h.body)

    def visit_scope(body, qprefix: str, cls: Optional[str]) -> None:
        for node in _block_stmts(body):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qname = (qprefix + "." + node.name) if qprefix \
                    else node.name
                fx = _FunctionExtractor(node, qname, cls, imports,
                                        from_imports)
                functions.append(fx.run())
                visit_scope(node.body, qname, cls)
            elif isinstance(node, ast.ClassDef):
                qname = (qprefix + "." + node.name) if qprefix \
                    else node.name
                classes[qname] = {
                    "bases": [dotted(b) or "" for b in node.bases],
                    "line": node.lineno,
                    "methods": [n.name for n in node.body
                                if isinstance(n, (ast.FunctionDef,
                                                  ast.AsyncFunctionDef))],
                }
                visit_scope(node.body, qname, qname)
            elif isinstance(node, ast.Assign) \
                    and _is_jit_expr(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        jit_names.append(t.id)

    visit_scope(tree.body, "", None)
    return {
        "version": FACTS_VERSION,
        "path": rel_path,
        "module": module_name(rel_path),
        "imports": imports,
        "from_imports": {k: list(v) for k, v in from_imports.items()},
        "classes": classes,
        "functions": functions,
        "jit_names": jit_names,
        "pragmas": _scan_pragmas(source),
    }
