"""plenum-lint CLI — text/JSON reporting, --changed mode, baselines.

    plenum_lint plenum_tpu/                # full tree vs the baseline
    plenum_lint --changed                  # pre-commit: git-diff files only
    plenum_lint --json plenum_tpu/ops/     # machine-readable findings
    plenum_lint --write-baseline           # (re)grandfather current findings

Exit codes: 0 clean (or warnings only), 1 non-baselined error findings,
2 usage errors. ``--changed`` with an empty diff prints a clean message
and exits 0 (the scripts/metrics_stats empty-store convention).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Dict, List

from plenum_tpu.analysis import repo_root, run_analysis
from plenum_tpu.analysis.baseline import Baseline
from plenum_tpu.analysis.core import Analyzer, Finding
from plenum_tpu.analysis.rules import RULE_CLASSES, build_rules

JSON_SCHEMA_VERSION = 1


def changed_py_files(root: str) -> List[str]:
    """Tracked-modified + untracked .py files, repo-relative. A failing
    git (not a repo, binary missing, hang) raises RuntimeError — the
    pre-commit gate must fail CLOSED, not read as an empty diff.

    Renames are followed: ``--name-status -M`` reports ``R<score>\\t
    old\\tnew`` and the NEW path joins the scan set (a plain
    ``--name-only``/``--diff-filter`` diff dropped renamed files, so a
    renamed file with findings exited clean)."""
    def run_git(*args: str) -> str:
        try:
            res = subprocess.run(["git", *args], cwd=root,
                                 capture_output=True, text=True,
                                 timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise RuntimeError("cannot run git for --changed: %s" % e)
        if res.returncode != 0:
            raise RuntimeError(
                "git failed for --changed (git %s): %s" % (
                    " ".join(args),
                    res.stderr.strip() or res.returncode))
        return res.stdout

    out: List[str] = []
    for line in run_git("diff", "--name-status", "-M", "HEAD",
                        "--").splitlines():
        parts = line.rstrip("\n").split("\t")
        if len(parts) < 2 or not parts[0]:
            continue
        status = parts[0][0]
        if status == "D":
            continue
        # renames/copies list "R<score>\told\tnew" — scan the NEW path
        out.append(parts[2] if status in ("R", "C") and len(parts) > 2
                   else parts[1])
    out.extend(line.strip() for line in
               run_git("ls-files", "--others",
                       "--exclude-standard").splitlines()
               if line.strip())
    seen, files = set(), []
    for rel in out:
        if rel.endswith(".py") and rel not in seen:
            seen.add(rel)
            path = os.path.join(root, rel)
            if os.path.isfile(path):
                files.append(path)
    return files


def _parse_severities(specs: List[str]) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for spec in specs:
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            code, _, sev = item.partition("=")
            if not sev:
                raise ValueError(
                    "--severity takes CODE=LEVEL, got %r" % item)
            out[code.strip().upper()] = sev.strip().lower()
    return out


def _to_json(findings: List[Finding], baselined: set,
             files_scanned: int) -> dict:
    items = []
    for f in findings:
        items.append({
            "rule": f.rule, "severity": f.severity, "path": f.path,
            "line": f.line, "col": f.col, "message": f.message,
            "symbol": f.symbol, "baselined": f in baselined})
    new = [f for f in findings if f not in baselined]
    return {
        "version": JSON_SCHEMA_VERSION,
        "tool": "plenum-lint",
        "findings": items,
        "summary": {
            "files": files_scanned,
            "findings": len(findings),
            "new": len(new),
            "baselined": len(findings) - len(new),
            "errors": sum(1 for f in new if f.severity == "error"),
            "warnings": sum(1 for f in new if f.severity == "warning"),
        },
    }


def _callgraph_mode(root: str, needle: str) -> int:
    """Resolve one symbol in the whole-program engine and print its
    summary, direct callees and callers — the triage companion for
    PT012–PT014 findings (which report at the SOURCE site; this walks
    the reach)."""
    from plenum_tpu.analysis.core import Analyzer
    from plenum_tpu.analysis.engine import Engine
    pkg = os.path.join(root, "plenum_tpu")
    if not os.path.isdir(pkg):
        print("plenum_lint: no plenum_tpu/ package under %s" % root,
              file=sys.stderr)
        return 2
    files = Analyzer([], root).collect_files([pkg])
    eng = Engine.build(files, root)
    matches = eng.graph.find_symbol(needle)
    if not matches:
        print("plenum_lint: no symbol matches %r" % needle,
              file=sys.stderr)
        return 2
    for sym in matches[:10]:
        fn = eng.function(sym)
        s = eng.summaries.get(sym)
        print("%s  (%s:%d)" % (eng.symbol_display(sym),
                               eng.path_of(sym), fn["line"]))
        if s is not None:
            print("  summary: pure=%s nondet=%s returns_open=%s "
                  "closes=%s buckets=%s" % (
                      s.pure, sorted(s.nondet) or "-",
                      sorted(s.returns_open) or "-",
                      sorted(s.closes) or "-", s.routes_bucket))
        callees = eng.graph.callees(sym)
        callers = eng.graph.callers(sym)
        print("  callees (%d):" % len(callees))
        for c in callees:
            print("    -> %s" % eng.symbol_display(c))
        print("  callers (%d):" % len(callers))
        for c in callers:
            print("    <- %s" % eng.symbol_display(c))
        print()
    if len(matches) > 10:
        print("plenum_lint: %d more matches not shown"
              % (len(matches) - 10))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="plenum_lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: plenum_tpu/)")
    ap.add_argument("--changed", action="store_true",
                    help="lint only .py files in the git diff "
                         "(tracked-modified + untracked)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--sarif", action="store_true", dest="as_sarif",
                    help="emit SARIF 2.1.0 (CI/code-review ingestion; "
                         "baselined findings carry baselineState="
                         "unchanged)")
    ap.add_argument("--callgraph", default=None, metavar="SYMBOL",
                    help="debugging mode: resolve SYMBOL (qualified "
                         "or bare name) in the whole-program engine "
                         "and print its summary, callees and callers")
    ap.add_argument("--root", default=None,
                    help="repo root (default: autodetected from the "
                         "package location)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: <root>/"
                         "lint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, grandfathered or not")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current "
                         "findings (justifications default to TODO)")
    ap.add_argument("--disable", default="",
                    help="comma-separated rule codes to skip")
    ap.add_argument("--select", default="",
                    help="comma-separated rule codes to run exclusively")
    ap.add_argument("--severity", action="append", default=[],
                    metavar="CODE=LEVEL",
                    help="override a rule's severity (error|warning); "
                         "warnings never affect the exit code")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for cls in RULE_CLASSES:
            print("%s %-32s %s" % (cls.code, cls.name, cls.severity))
        return 0

    root = os.path.abspath(args.root) if args.root else repo_root()

    if args.callgraph:
        return _callgraph_mode(root, args.callgraph)

    try:
        severities = _parse_severities(args.severity)
        rules = build_rules(
            disable=[c for c in args.disable.split(",") if c],
            select=[c for c in args.select.split(",") if c],
            severities=severities, root=root)
    except ValueError as e:
        print("plenum_lint: %s" % e, file=sys.stderr)
        return 2

    if args.changed:
        try:
            files = changed_py_files(root)
        except RuntimeError as e:
            print("plenum_lint: %s" % e, file=sys.stderr)
            return 2
        if args.paths:
            scopes = [os.path.abspath(p) for p in args.paths]
            files = [f for f in files
                     if any(os.path.abspath(f) == s
                            or os.path.abspath(f).startswith(s + os.sep)
                            for s in scopes)]
        if not files:
            print("plenum_lint: no changed Python files — nothing "
                  "to lint")
            return 0
    else:
        paths = args.paths or [os.path.join(root, "plenum_tpu")]
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            # a typo'd path must not read as a permanently-clean lint
            print("plenum_lint: no such path(s): %s"
                  % ", ".join(missing), file=sys.stderr)
            return 2
        files = Analyzer(rules, root).collect_files(paths)

    analyzer = Analyzer(rules, root)
    findings = analyzer.run_files(files)

    baseline_path = args.baseline or os.path.join(root,
                                                  "lint_baseline.json")
    if args.write_baseline:
        # merge, don't clobber: entries for files outside this run's
        # scope (or rules not run) were not re-checked — a scoped
        # rewrite must never delete their justifications
        scanned = {analyzer._rel(p) for p in files}
        active = {r.code for r in rules}
        kept = [e for e in Baseline.load(baseline_path).entries
                if e["path"] not in scanned or e["rule"] not in active]
        fresh = Baseline.from_findings(findings).entries
        Baseline(kept + fresh).save(baseline_path)
        print("plenum_lint: wrote %d baseline entr%s (+%d out-of-scope "
              "kept) to %s — fill in the justifications before "
              "committing" % (len(fresh),
                              "y" if len(fresh) == 1 else "ies",
                              len(kept), baseline_path))
        return 0

    baseline = (Baseline([]) if args.no_baseline
                else Baseline.load(baseline_path))
    new, old = baseline.match(findings)
    baselined = set(old)

    if args.as_sarif:
        from plenum_tpu.analysis.sarif import to_sarif
        print(json.dumps(to_sarif(findings, baselined, rules),
                         indent=2))
    elif args.as_json:
        print(json.dumps(_to_json(findings, baselined, len(files)),
                         indent=2))
    else:
        for f in new:
            print(f.render())
        scanned = {analyzer._rel(p) for p in files}
        active = {r.code for r in rules}
        # an entry for a file outside this run's scope (or a rule not
        # run) is not stale — it just wasn't checked
        stale = [k for k in baseline.stale()
                 if k[1] in scanned and k[0] in active]
        if stale:
            print("plenum_lint: %d stale baseline entr%s (fixed code? "
                  "prune lint_baseline.json):" % (
                      len(stale), "y" if len(stale) == 1 else "ies"))
            for rule, path, symbol, _ in stale:
                print("  %s %s [%s]" % (rule, path, symbol))
        print("plenum_lint: %d file(s), %d finding(s) — %d new, %d "
              "baselined" % (len(files), len(findings), len(new),
                             len(old)))
    return 1 if any(f.severity == "error" for f in new) else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
