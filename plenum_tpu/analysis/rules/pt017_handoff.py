"""PT017 handoff-discipline.

The pipeline's thread boundary (runtime/pipeline.py) is safe because
of a contract, not a lock: values crossing the SPSC queues are
immutable (bytes, numpy views, frozen job records), and once a payload
is ``put()`` the producer stops touching it. This rule checks the
contract at every handoff site the engine extracted:

* **fresh-mutable payload** — a ``put``/``put_nowait`` whose argument
  is a freshly built mutable container (dict/list/set literal,
  comprehension, or ``dict()``-style constructor call) hands the
  consumer state the producer can still reach. Same shape and message
  as PT004's queue check (migration re-keys cleanly);
* **mutate-after-put** — the payload name is mutated *after* the
  handoff line while the producer retains the alias (attribute or
  subscript store rooted at the name, or an in-place mutator method
  call: append/update/…). Only queue-ish receivers are held to this
  (``*queue*``, ``_in``/``_out``, inbox/outbox): a KV-store ``put``
  persists a copy, it does not share the object with another thread;
* **consensus capture** — a closure handed to ``Thread(target=...)``,
  ``pool.submit`` or ``run_in_executor`` closes over a consensus-named
  ``self`` attribute (the PT004/PT016 vocabulary). That is a
  consensus-owned object escaping into a worker region — the exact
  bug class the pipeline's "workers parse, prod counts" contract
  forbids. Reading a method off ``self`` to *call* it is not a
  capture; reading prod-owned state is.

Runtime twin: the sanitizer's ``HandoffToken`` enforces release/
acquire at the same queues this rule checks statically.
"""
from __future__ import annotations

from typing import List

from plenum_tpu.analysis.core import Finding, ProgramRule
from plenum_tpu.analysis.rules.pt004_threads import _consensus_attr

# receiver names that mean "this put() crosses a thread boundary" —
# KV-store puts (self._store.put(key, val)) stay out of the
# mutate-after check: they persist a snapshot, not a shared reference
_QUEUEISH_TERMINALS = frozenset({"_in", "_out", "q", "inbox", "outbox"})


def _queueish(recv: str) -> bool:
    low = recv.lower()
    if "queue" in low:
        return True
    return low.rsplit(".", 1)[-1] in _QUEUEISH_TERMINALS


class HandoffDisciplineRule(ProgramRule):
    code = "PT017"
    name = "handoff-discipline"

    def applies(self, rel_path: str) -> bool:
        return rel_path.startswith("plenum_tpu/")

    def check_program(self, engine, rel_paths) -> List[Finding]:
        out: List[Finding] = []
        seen = set()

        def report(path, line, col, message, symbol):
            key = (path, line, col, message)
            if key in seen:
                return
            seen.add(key)
            out.append(Finding(
                rule=self.code, severity=self.severity, path=path,
                line=line, col=col, message=message, symbol=symbol))

        for sym in sorted(engine.graph.functions):
            fn = engine.graph.functions[sym]
            path = engine.path_of(sym)
            for h in fn.get("handoffs", ()):
                if h["arg_mutable"]:
                    report(
                        path, h["line"], h["col"],
                        "a freshly built mutable %s crosses a thread "
                        "queue via %s() — queue payloads must be "
                        "immutable (bytes, numpy views, frozen "
                        "records): the consumer would share state the "
                        "producer can still mutate" % (
                            h["mutable_kind"], h["op"]),
                        fn["qname"])
                elif h["mutated_after"] and _queueish(h["recv"]):
                    report(
                        path, h["line"], h["col"],
                        "queue payload %s is mutated after %s() while "
                        "the producer retains the alias — the consumer "
                        "may already be reading it; hand over an "
                        "immutable snapshot (bytes, tuple, frozen "
                        "record) and drop the reference" % (
                            "/".join(h["mutated_after"]), h["op"]),
                        fn["qname"])
            for spawn in fn.get("spawns", ()):
                owned = sorted(a for a in spawn.get("captured_attrs", ())
                               if _consensus_attr(a))
                if owned:
                    report(
                        path, spawn["line"], spawn["col"],
                        "consensus-owned state (self.%s) is captured "
                        "into a thread-spawned closure (%s) — prod-"
                        "owned consensus objects must not escape into "
                        "a worker region; pass immutable inputs and "
                        "hand results back over the queue" % (
                            "/self.".join(owned), spawn["kind"]),
                        fn["qname"])
        return out
