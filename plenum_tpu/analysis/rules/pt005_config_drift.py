"""PT005 config-literal-drift.

Historical bug class: tuning knobs hard-coded at their use sites drift
apart from ``common/config.py``. PR 2 single-sourced the
MERKLE_DEVICE_* routing thresholds after the ledger and the engine
disagreed; PR 4 did the same for VERIFIER_BATCH_THRESHOLD across the
AdaptiveVerifier, the hub and the node. A literal that silently equals
a Config value is a knob the operator cannot turn.

Encoding: ``common/config.py`` is parsed (AST only, constant folding
for ``a * b`` / ``1 << k`` style definitions) into a value → knob-names
map. Integer literals >= 32 in ``ops/`` and ``server/`` that equal a
knob value are flagged, but ONLY in threshold-shaped positions —
parameter defaults, call keyword arguments and comparison operands —
where a tunable hides. Arithmetic, indexing and shape math (the 32s
and 64s of digest widths and SHA blocks all over the kernels) are
structure, not tuning, and stay out of scope.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Tuple

from plenum_tpu.analysis.core import Finding, ModuleContext, Rule

MIN_VALUE = 32   # below this, collisions are noise (0/1/8/16 everywhere)


def _fold(node: ast.AST):
    """Constant-fold the arithmetic subset Config definitions use."""
    if isinstance(node, ast.Constant) \
            and isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.BinOp):
        left, right = _fold(node.left), _fold(node.right)
        if left is None or right is None:
            return None
        try:
            if isinstance(node.op, ast.Mult):
                return left * right
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
            if isinstance(node.op, ast.FloorDiv):
                return left // right
            if isinstance(node.op, ast.LShift):
                return left << right
            if isinstance(node.op, ast.Pow):
                return left ** right
        except (TypeError, ZeroDivisionError):
            return None
    return None


def load_config_values(config_path: str) -> Dict[int, List[str]]:
    """value → [knob names] for every int-valued Config class default."""
    with open(config_path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=config_path)
    values: Dict[int, List[str]] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ClassDef) and node.name == "Config"):
            continue
        for stmt in node.body:
            if not isinstance(stmt, ast.Assign):
                continue
            v = _fold(stmt.value)
            if not isinstance(v, int) or isinstance(v, bool) \
                    or v < MIN_VALUE:
                continue
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    values.setdefault(v, []).append(tgt.id)
    return values


class ConfigLiteralDriftRule(Rule):
    code = "PT005"
    name = "config-literal-drift"

    def __init__(self, config_values: Dict[int, List[str]] = None,
                 root: str = None):
        self._values = config_values
        self._root = root

    def _ensure_values(self) -> Dict[int, List[str]]:
        if self._values is None:
            path = os.path.join(self._root or os.getcwd(), "plenum_tpu",
                                "common", "config.py")
            self._values = load_config_values(path) \
                if os.path.exists(path) else {}
        return self._values

    def applies(self, rel_path: str) -> bool:
        return rel_path.startswith(("plenum_tpu/ops/",
                                    "plenum_tpu/server/"))

    @staticmethod
    def _threshold_position(node: ast.AST, parent: ast.AST) -> bool:
        if isinstance(parent, ast.arguments):
            return node in parent.defaults or node in parent.kw_defaults
        if isinstance(parent, ast.keyword):
            return True
        if isinstance(parent, ast.Compare):
            # ordering comparisons are threshold checks; ==/!= against a
            # width (len(sig) != 64) is structure, not tuning
            return any(isinstance(op, (ast.Gt, ast.GtE, ast.Lt, ast.LtE))
                       for op in parent.ops)
        return False

    def check(self, ctx: ModuleContext) -> List[Finding]:
        values = self._ensure_values()
        if not values:
            return []
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, int)
                    and not isinstance(node.value, bool)):
                continue
            v = node.value
            if v < MIN_VALUE or v not in values:
                continue
            parent = ctx.parent(node)
            if not self._threshold_position(node, parent):
                continue
            out.append(ctx.finding(
                self, node,
                "literal %d duplicates Config.%s — reference the config "
                "knob so the operator's override reaches this site" % (
                    v, "/".join(sorted(set(values[v]))))))
        return out
