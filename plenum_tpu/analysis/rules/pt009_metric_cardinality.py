"""PT009 unbounded-metric-cardinality.

Bug class the telemetry plane (PR 10) was designed to prevent rather
than ship: a metric name built dynamically at a record site —
``hub.observe("latency_%s" % peer, ...)``, ``telemetry.count(f"retry_
{ledger_id}")`` — mints a new time series per distinct value. Every
monitoring system that has ever fallen over has fallen over this way:
the histogram/counter registry grows without bound (each telemetry
histogram is a preallocated ~4 KB bucket array), snapshots and
Prometheus exposition balloon, and the "metric" becomes an unqueryable
per-entity log. The whole point of the ``TM`` registry and ``SEAM_*``
constants (observability/telemetry.py) is that the metric-name set is
CLOSED at code-review time — the dead-name test pins every registry
entry to a recording site, and this rule pins every recording site to
the registry.

Encoding: at a telemetry record call — a call whose method is one of
``observe`` / ``record_launch`` / ``record_roundtrip`` / ``timer``
(any receiver), or ``count`` / ``gauge`` on a receiver whose
attribute chain mentions ``telemetry`` (scoping that keeps
``list.count``/``str.count`` out) — the metric/seam name argument must
not be a DYNAMIC string: f-strings, ``%``/``+`` formatting,
``str.format``/``join`` calls, or any expression mixing a non-constant
into the name is a finding. Registry constants (``TM.X``, ``SEAM_*``
names, aliased imports) and plain literals pass — a literal is bounded
cardinality even when it bypasses the registry (the dead-name test is
the instrument that catches orphaned literals).
"""
from __future__ import annotations

import ast
from typing import List, Optional

from plenum_tpu.analysis.core import Finding, ModuleContext, Rule, attr_parts

# record methods checked on ANY receiver: these names are unique to the
# telemetry API, so a match is a record site
RECORD_METHODS = {"observe", "record_launch", "record_roundtrip", "timer"}
# record methods common enough to collide with builtins (str.count,
# list.count): only checked when the receiver chain says telemetry
SCOPED_METHODS = {"count", "gauge"}
_TELEMETRY_RECEIVER_PARTS = {"telemetry", "hub", "tm", "tmy", "tm_hub"}


def _name_arg(call: ast.Call) -> Optional[ast.AST]:
    """The metric/seam name argument: first positional, or the
    ``name``/``seam`` keyword."""
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg in ("name", "seam"):
            return kw.value
    return None


def _is_literal_str(node: ast.AST) -> bool:
    """String expressions with exactly ONE possible value: literals,
    f-strings without interpolation, literal-only concatenation."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, str)
    if isinstance(node, ast.JoinedStr):
        return not any(isinstance(v, ast.FormattedValue)
                       for v in node.values)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _is_literal_str(node.left) and _is_literal_str(node.right)
    return False


def _is_dynamic_string(node: ast.AST) -> bool:
    """True when the expression can take unboundedly many string
    values: f-strings with interpolation, %/+ formatting with any
    non-literal operand, .format()/.join() calls. A bare Name /
    Attribute reference (a registry constant) and literal-only
    construction are bounded; the SAME name inside a formatting
    expression is not — formatting is exactly how variable values
    leak into metric names."""
    if isinstance(node, ast.JoinedStr):
        return any(isinstance(v, ast.FormattedValue) for v in node.values)
    if isinstance(node, ast.BinOp):
        if isinstance(node.op, ast.Mod):
            return not (_is_literal_str(node.left)
                        and _is_literal_str(node.right))
        if isinstance(node.op, ast.Add):
            # TM.X-style references are bounded on their own, but any
            # concatenation involving one is only bounded when EVERY
            # operand is a literal — a Name operand is a variable part
            return not (_is_literal_str(node.left)
                        and _is_literal_str(node.right))
    if isinstance(node, ast.Call):
        callee = node.func
        if isinstance(callee, ast.Attribute) \
                and callee.attr in ("format", "join"):
            return True
    return False


class UnboundedMetricCardinalityRule(Rule):
    code = "PT009"
    name = "unbounded-metric-cardinality"

    def applies(self, rel_path: str) -> bool:
        return rel_path.startswith("plenum_tpu/")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = node.func
            if not isinstance(callee, ast.Attribute):
                continue
            method = callee.attr
            if method in RECORD_METHODS:
                pass
            elif method in SCOPED_METHODS:
                parts = {p.lower() for p in attr_parts(callee.value)}
                if not (parts & _TELEMETRY_RECEIVER_PARTS):
                    continue
            else:
                continue
            arg = _name_arg(node)
            if arg is None or not _is_dynamic_string(arg):
                continue
            out.append(ctx.finding(
                self, node,
                "dynamically-built metric name at telemetry %s() — "
                "every distinct value mints a new time series "
                "(unbounded registry growth, ballooning exposition); "
                "use a TM/SEAM_* registry constant and carry the "
                "variable part as a value, not a name" % method))
        return out
