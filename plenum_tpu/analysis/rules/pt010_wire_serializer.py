"""PT010 per-message-serializer-call-in-hot-wire-path.

Historical bug class: the wire layers under ``network/`` and
``server/`` invoking a serializer once PER ITEM inside a send/receive
handler's loop. The PR-11 incident is the THREE_PC_BATCH receive path:
every inner vote of every envelope went through
``node_message_factory.get_instance`` (full schema validation + object
construction) only for the columnar intake to strip the object back
down to digest/view/seq columns — per-message deserialization was the
single largest host-ms population left on the ordering money path
after PR 8 made the counting columnar (ROADMAP item 3). The fix is the
flat zero-copy wire (common/serializers/flat_wire.py): ONE pack and
ONE parse per envelope, columns handed straight to the vectorized
intake, typed objects materialized only for votes that enter a store.

Encoding: inside a HOT wire handler — a function whose name matches
``process_*``/``_process_*``/``flush*``/``_flush*``/``send*``/
``receive*``/``unpack*``/``enqueue*``/``read*`` (send/receive shaped)
in a file under ``plenum_tpu/network/`` or ``plenum_tpu/server/`` —
any serializer invocation (``serialize``/``deserialize``/``packb``/
``unpackb``/``to_dict``/``get_instance``) inside a ``for`` loop or
comprehension that iterates a per-item wire collection (``messages``/
``msgs``/``entries``/``requests``/``reqs``/``out``/``items``/
``chunk``/``rx``/``payloads``/``blobs``) is flagged. One serializer
call per ENVELOPE is the design; one per item is the quadratic wire
shape this rule exists to keep dead. Deliberately per-message paths —
the adversary-tap degrade (fault injection needs per-type wire
granularity) and untrusted client-batch unwrapping (one bad entry
must cost one message) — carry justified baseline entries.
"""
from __future__ import annotations

import ast
import re
from typing import List

from plenum_tpu.analysis.core import Finding, ModuleContext, Rule

HANDLER_NAME = re.compile(
    r"^_?(process|flush|send|receive|unpack|enqueue|read)")
SERIALIZER_CALLS = frozenset({
    "serialize", "deserialize", "packb", "unpackb", "to_dict",
    "get_instance"})
COLLECTION = re.compile(
    r"^(messages|msgs|entries|requests|reqs|out|items|chunk|rx|"
    r"payloads|blobs)$", re.IGNORECASE)

_ITER_METHODS = {"items", "keys", "values", "get"}


def _collection_name(node: ast.AST) -> str:
    """Terminal name of an iterable expression (PT008's resolution):
    ``msg.messages``, ``msg.get("messages", [])``, ``out[i:j]`` all
    resolve to the collection identifier the loop walks."""
    if isinstance(node, ast.Call):
        callee = node.func
        if isinstance(callee, ast.Attribute) \
                and callee.attr in _ITER_METHODS:
            # msg.get("messages", []) walks the literal collection key
            if callee.attr == "get" and node.args and isinstance(
                    node.args[0], ast.Constant) and isinstance(
                    node.args[0].value, str):
                return node.args[0].value
            return _collection_name(callee.value)
        return ""
    if isinstance(node, ast.Subscript):
        return _collection_name(node.value)
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _serializer_calls(node: ast.AST) -> List[ast.Call]:
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) \
                and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr in SERIALIZER_CALLS:
            out.append(sub)
    return out


class WireSerializerLoopRule(Rule):
    code = "PT010"
    name = "per-message-serializer-call-in-hot-wire-path"

    def applies(self, rel_path: str) -> bool:
        return rel_path.startswith(("plenum_tpu/network/",
                                    "plenum_tpu/server/",
                                    "plenum_tpu/gateway/"))

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        # one finding per serializer CALL: nested matching loops
        # (`for chunk in out: for m in chunk: ser.serialize(m)`) walk
        # the same call once per enclosing loop — dedupe by location
        # so one defect never needs two baseline entries
        seen: set = set()
        for func in ast.walk(ctx.tree):
            if not isinstance(func, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not HANDLER_NAME.match(func.name):
                continue
            for node in ast.walk(func):
                if isinstance(node, ast.For):
                    iters = [node.iter]
                    bodies = node.body
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.GeneratorExp, ast.DictComp)):
                    iters = [g.iter for g in node.generators]
                    bodies = [node]
                else:
                    continue
                coll = ""
                for it in iters:
                    name = _collection_name(it)
                    if name and COLLECTION.match(name):
                        coll = name
                        break
                if not coll:
                    continue
                for body in bodies:
                    for call in _serializer_calls(body):
                        loc = (call.lineno, call.col_offset)
                        if loc in seen:
                            continue
                        seen.add(loc)
                        out.append(ctx.finding(
                            self, call,
                            "serializer call '%s' inside a per-item "
                            "loop over '%s' in wire handler %s — one "
                            "pack/parse per ITEM is the per-message "
                            "wire shape; pack and parse whole "
                            "envelopes (flat_wire) and hand columns "
                            "to the batch intake, or hoist the "
                            "serializer call out of the loop"
                            % (call.func.attr, coll, func.name)))
        return out
