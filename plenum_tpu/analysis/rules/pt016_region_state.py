"""PT016 cross-region-mutable-state.

The whole-program successor to the PT004 heuristic, built on the
engine's thread-region analysis (engine/summaries.compute_regions).
PR 19's pipelined node broke the reference's single-thread model with
a worker parse stage, a prescreen cache and an exec pool; ROADMAP
item 2 asks that "the analyzer, not review, enforces the ownership
contract" at those seams. PT004 could only see spawns and writes
inside ONE class — but the pipeline hands ``lambda:
self._pipeline_parse(...)`` across a queue from `server/node.py` into
`runtime/pipeline.py`, so the worker side of the program is a
cross-module call closure only the engine can compute.

Encoding: every function symbol carries the set of thread regions it
can execute in (``prod`` / ``worker`` / ``daemon`` — forward closure
from resolved ``Thread(target=...)`` / ``pool.submit`` /
``run_in_executor`` targets, lambda spawn bodies included). Per
class, self-attribute rebinds are bucketed by the writing method's
region (``__init__`` excluded: construction happens before any thread
exists; subscript stores excluded: the sanctioned Tracer fixed-slot
pattern). Two defect shapes, both requiring an unlocked site:

* a **consensus-named attribute** (the OrderingService/Propagator
  vocabulary shared with PT004) written from the worker/daemon side —
  flagged even with no prod-side co-writer, because the pipeline
  ownership contract says workers parse and the prod thread counts;
* any attribute written from **both** a worker-region method and a
  prod-region method with no lock in evidence.

Messages are byte-identical to PT004's so baselined findings migrate
by re-keying the rule id alone (baseline.py handles that). The
runtime twin of this rule is ``runtime/sanitizer.py``: a PT016-clean
seam needs no region pin, and every pinned label names state in this
rule's consensus-owned vocabulary.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from plenum_tpu.analysis.core import Finding, ProgramRule
from plenum_tpu.analysis.rules.pt004_threads import _consensus_attr

# regions whose code runs off the prod thread
OFF_PROD = frozenset({"worker", "daemon"})


class CrossRegionMutableStateRule(ProgramRule):
    code = "PT016"
    name = "cross-region-mutable-state"
    subsumes = "PT004"

    def applies(self, rel_path: str) -> bool:
        return rel_path.startswith("plenum_tpu/")

    def check_program(self, engine, rel_paths) -> List[Finding]:
        # class key -> region bucket -> attr -> [(method, line, col,
        # locked)]; bucketing mirrors PT004 (a multi-region method
        # lands on the worker side: that is where its writes can race)
        classes: Dict[Tuple[str, str], Dict[str, Dict[str, List]]] = {}
        for sym, fn in engine.graph.functions.items():
            cls = fn.get("cls")
            if not cls or fn["name"] == "__init__":
                continue
            writes = fn.get("attr_writes", ())
            if not writes:
                continue
            regions = engine.regions.get(sym, set())
            side = "worker" if regions & OFF_PROD else "prod"
            path = engine.path_of(sym)
            buckets = classes.setdefault((path, cls), {})
            per_attr = buckets.setdefault(side, {})
            for w in writes:
                per_attr.setdefault(w["attr"], []).append(
                    (fn["name"], w["line"], w["col"], w["locked"]))
        out: List[Finding] = []
        for (path, cls), buckets in sorted(classes.items()):
            worker_writes = buckets.get("worker", {})
            prod_writes = buckets.get("prod", {})
            dual = set(worker_writes) & set(prod_writes)
            for attr in sorted(set(worker_writes) - dual):
                if not _consensus_attr(attr):
                    continue
                unlocked = [s for s in worker_writes[attr] if not s[3]]
                if not unlocked:
                    continue
                name, line, col, _ = unlocked[0]
                out.append(Finding(
                    rule=self.code, severity=self.severity, path=path,
                    line=line, col=col,
                    message="self.%s (consensus state) is written from "
                    "the worker-thread path (%s) — consensus state is "
                    "owned by the prod thread; workers may only parse "
                    "and hand immutable results back over the queue" % (
                        attr,
                        "/".join(sorted({s[0]
                                         for s in worker_writes[attr]}))),
                    symbol="%s.%s" % (cls.rsplit(".", 1)[-1], name)))
            for attr in sorted(dual):
                w_sites = worker_writes[attr]
                p_sites = prod_writes[attr]
                unlocked = [s for s in w_sites + p_sites if not s[3]]
                if not unlocked:
                    continue
                name, line, col, _ = unlocked[0]
                out.append(Finding(
                    rule=self.code, severity=self.severity, path=path,
                    line=line, col=col,
                    message="self.%s is written from both the "
                    "worker-thread path (%s) and loop code (%s) without "
                    "a lock — use a lock or the Tracer fixed-slot "
                    "pattern" % (
                        attr,
                        "/".join(sorted({s[0] for s in w_sites})),
                        "/".join(sorted({s[0] for s in p_sites}))),
                    symbol="%s.%s" % (cls.rsplit(".", 1)[-1], name)))
        return out
