"""PT014 unbounded-compile-cardinality.

XLA compiles one executable per distinct operand SHAPE at every
``jax.jit`` / ``pallas_call`` boundary. A launch whose batch axis is
the raw input length therefore pays a fresh multi-second compile for
every distinct size that ever arrives — the exact shape of two shipped
incidents: the per-distinct-size Keccak compiles caught in PR 6 review
(unbucketed trie level sizes), and the r05 bench regression root-caused
in PR 9. The fix discipline is a handful of sanctioned bounded-shape
helpers (``pow2_at_least``, ``launch_lanes``, ``mesh.padded_size``,
the ``pad_messages`` family): every shape that reaches a compiled
callable must route through one, so the compile cache is bounded by
O(log sizes) buckets.

Encoding, per launch site (a call resolving to a jit-decorated
project function, a ``jax.jit(...)``/``pallas_call(...)`` assignment,
or the ``_build_*(...)(...)`` cached-builder idiom):

* operands CONDITIONALLY bucketed — ``padded_size(B) if sharded else
  B`` and every value derived from it — always flag: one branch pays
  per-distinct-shape compiles while the other hides it (the r05
  shape, and the live bls381 finding this rule shipped with);
* otherwise the site needs bucket EVIDENCE: a bucket helper in the
  operand expressions themselves, anywhere in the enclosing function,
  or in a direct callee (one level — a distant pow2 call must not
  excuse a raw local launch);
* all-constant operands are exempt (warm-up calls with literal
  bucket shapes), as are launches inside jit-decorated functions
  (traced inline: the outer boundary owns the shape) and
  ``ops/mesh.py`` (the bucketing layer itself).
"""
from __future__ import annotations

from typing import List

from plenum_tpu.analysis.core import Finding, ProgramRule


class CompileCardinalityRule(ProgramRule):
    code = "PT014"
    name = "unbounded-compile-cardinality"

    @staticmethod
    def _ancestor_buckets(graph, sym: str) -> bool:
        """Nested defs (merkle's `launch` closures) share the
        enclosing function's scope — its bucket calls are evidence."""
        mod, q = sym.split(":", 1)
        while "." in q:
            q = q.rsplit(".", 1)[0]
            anc = graph.functions.get("%s:%s" % (mod, q))
            if anc and anc["buckets"]:
                return True
        return False

    @staticmethod
    def _class_buckets(graph, sym: str, fn: dict) -> bool:
        cls = fn.get("cls")
        if not cls:
            return False
        mod = sym.split(":", 1)[0]
        prefix = "%s:%s." % (mod, cls)
        return any(other["buckets"]
                   for osym, other in graph.functions.items()
                   if osym.startswith(prefix))

    def applies(self, rel_path: str) -> bool:
        return (rel_path.startswith("plenum_tpu/")
                and rel_path != "plenum_tpu/ops/mesh.py")

    def check_program(self, engine, rel_paths) -> List[Finding]:
        out: List[Finding] = []
        graph = engine.graph
        for sym in sorted(graph.functions):
            fn = graph.functions[sym]
            if fn.get("jitted"):
                continue
            path = graph.fn_path[sym]
            if path == "plenum_tpu/ops/mesh.py":
                continue
            summary = engine.summaries.get(sym)
            resolved = {id(call): callee
                        for callee, call in graph.edges[sym]}
            for call in fn["calls"]:
                callee = resolved.get(id(call))
                csum = engine.summaries.get(callee) \
                    if callee is not None else None
                launcher = call.get("builder") \
                    or (csum is not None
                        and csum.launches_param_shapes) \
                    or graph.is_jit_callee(sym, call["chain"])
                if not launcher:
                    continue
                name = call["chain"][-1]
                if call.get("arg_cond_bucketed"):
                    out.append(Finding(
                        rule=self.code, severity=self.severity,
                        path=path, line=call["line"],
                        col=call["col"],
                        message=(
                            "compiled launch %s() with CONDITIONALLY "
                            "bucketed operand shapes (bucketed on one "
                            "branch, raw on another) — the unbucketed "
                            "branch pays one XLA compile per distinct "
                            "size (the r05 regression shape); route "
                            "every branch through pow2_at_least/"
                            "launch_lanes/padded_size" % name),
                        symbol=fn["qname"]))
                    continue
                if call.get("args_all_const") \
                        or call.get("arg_static"):
                    # literal or module-constant operands: fixed
                    # shapes per process, no cardinality to bound
                    continue
                if call.get("arg_param_only"):
                    # pass-through seam: every operand came in through
                    # the function's own parameters — the summary
                    # lifts the obligation to this function's callers
                    # (launches_param_shapes), so no local finding
                    continue
                if call.get("arg_bucketed") or fn["buckets"] \
                        or (summary and summary.routes_bucket) \
                        or self._ancestor_buckets(graph, sym):
                    continue
                if call.get("arg_self_rooted") \
                        and self._class_buckets(graph, sym, fn):
                    # operands live on the object; the owning class
                    # shaped its arrays (pow2 capacities at build /
                    # growth), which any of its methods evidences
                    continue
                out.append(Finding(
                    rule=self.code, severity=self.severity,
                    path=path, line=call["line"], col=call["col"],
                    message=(
                        "compiled launch %s() with no bucket-routing "
                        "evidence — operand shapes that don't route "
                        "through pow2_at_least/launch_lanes/"
                        "padded_size pay one XLA compile per distinct "
                        "batch size (the per-distinct-size Keccak "
                        "incident, PR 6 review)" % name),
                    symbol=fn["qname"]))
        return out
