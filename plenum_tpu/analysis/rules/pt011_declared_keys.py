"""PT011 state-access-without-declared-keys.

Bug class the conflict-lane executor (PR 13) makes structural: the
lane planner (server/execution_lanes.py) partitions every ordered
batch from the handlers' ``touched_keys`` declarations, and the
batched read-window prefetch serves exactly the DECLARED read keys.
A ``WriteRequestHandler`` whose ``dynamic_validation`` /
``update_state`` reaches a state key its ``touched_keys`` cannot
produce breaks the contract the whole pipeline rests on: the request
would lane-plan as non-conflicting while actually racing another
lane's writes, and its reads would silently miss the prefetch window.
Execution stays byte-correct either way (the executor applies in
batch order and reads go pending-buffer-first), but the declaration
drift is invisible at runtime — exactly the kind of rot a lint rule
has to keep dead.

Encoding: inside a class whose base name ends with
``WriteRequestHandler`` / ``WriteHandler``, every
``*.state.get(key)`` / ``*.state.set(key, ...)`` call (receiver
``self.state`` or a local assigned from ``*.get_state(...)``) in a
``dynamic_validation`` or ``update_state`` override is checked for
**reachability from the declaration**: the key expression must be a
call to a function the class's ``touched_keys`` itself calls (the
"key recipe" — ``nym_to_state_key``, ``_path_aml_version``, …), a
name bound from such a call, or a constant name ``touched_keys``
references (``FROZEN_LEDGERS_PATH``). Classes without a declaration
(or with an explicit ``return None`` opt-out) get every state access
flagged — handlers whose key sets are inherently dynamic (NODE's
whole-state alias scan, TAA's digest chains read from state) carry
justified baseline entries; that friction is the point, because an
opt-out silently costs the serial lane.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from plenum_tpu.analysis.core import (
    Finding, ModuleContext, Rule, attr_parts)

_HANDLER_BASES = ("WriteRequestHandler", "WriteHandler")
_CHECKED_METHODS = ("dynamic_validation", "update_state")


def _is_handler_class(cls: ast.ClassDef) -> bool:
    for base in cls.bases:
        parts = attr_parts(base)
        if parts and (parts[0].endswith(_HANDLER_BASES[0])
                      or parts[0].endswith(_HANDLER_BASES[1])):
            return True
    return False


def _terminal_func_name(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Name):
        return call.func.id
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return None


def _recipes(touched: Optional[ast.FunctionDef]) -> Optional[Set[str]]:
    """Names reachable from the declaration: functions/methods it
    calls plus the FREE names it loads (shared key constants like
    FROZEN_LEDGERS_PATH). touched_keys' own locals and parameters are
    excluded — a checked method binding the same local name ('key')
    to an undeclared recipe must not inherit reachability from the
    declaration's unrelated local. None = no touched_keys method."""
    if touched is None:
        return None
    bound: Set[str] = {a.arg for a in touched.args.args}
    bound.update(a.arg for a in touched.args.posonlyargs)
    bound.update(a.arg for a in touched.args.kwonlyargs)
    for node in ast.walk(touched):
        targets = ()
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = (node.target,)
        elif isinstance(node, (ast.For, ast.comprehension)):
            targets = (node.target,)
        for t in targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name):
                    bound.add(sub.id)
    out: Set[str] = set()
    for node in ast.walk(touched):
        if isinstance(node, ast.Call):
            name = _terminal_func_name(node)
            if name:
                out.add(name)
        elif isinstance(node, ast.Name) and node.id not in bound:
            out.add(node.id)
    return out


def _key_reachable(expr: ast.AST, recipes: Set[str],
                   recipe_vars: Set[str]) -> bool:
    if isinstance(expr, ast.Call):
        name = _terminal_func_name(expr)
        return name is not None and name in recipes
    if isinstance(expr, ast.Name):
        return expr.id in recipes or expr.id in recipe_vars
    return False


class DeclaredKeysRule(Rule):
    code = "PT011"
    name = "state-access-without-declared-keys"

    def applies(self, rel_path: str) -> bool:
        return rel_path.startswith("plenum_tpu/")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef) \
                    or not _is_handler_class(cls):
                continue
            methods: Dict[str, ast.FunctionDef] = {
                n.name: n for n in cls.body
                if isinstance(n, ast.FunctionDef)}
            recipes = _recipes(methods.get("touched_keys"))
            for name in _CHECKED_METHODS:
                func = methods.get(name)
                if func is None:
                    continue
                out.extend(self._check_method(ctx, cls, func, recipes))
        return out

    def _check_method(self, ctx: ModuleContext, cls: ast.ClassDef,
                      func: ast.FunctionDef,
                      recipes: Optional[Set[str]]) -> List[Finding]:
        out: List[Finding] = []
        # locals assigned from key recipes, and locals holding states
        # resolved via *.get_state(...) (cross-ledger reads)
        recipe_vars: Set[str] = set()
        state_vars: Set[str] = set()
        for node in ast.walk(func):
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                target = node.targets[0].id
                value = node.value
                if isinstance(value, ast.Call):
                    vname = _terminal_func_name(value)
                    if vname == "get_state":
                        state_vars.add(target)
                    elif recipes and vname in recipes:
                        recipe_vars.add(target)
                elif isinstance(value, ast.Name) and recipes \
                        and value.id in recipes:
                    recipe_vars.add(target)
        for node in ast.walk(func):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute) \
                    or node.func.attr not in ("get", "set") \
                    or not node.args:
                continue
            parts = attr_parts(node.func)
            if len(parts) < 2:
                continue
            receiver_is_state = parts[1] == "state" \
                or parts[1] in state_vars
            if not receiver_is_state:
                continue
            if recipes is None:
                out.append(ctx.finding(
                    self, node,
                    "state.%s in %s of a WriteRequestHandler with no "
                    "touched_keys declaration — declare the handler's "
                    "read/write key recipes (a superset computable "
                    "from the request) so the conflict-lane executor "
                    "can plan it, or return None and record the "
                    "inherently-dynamic justification in the baseline"
                    % (node.func.attr, func.name)))
                continue
            if _key_reachable(node.args[0], recipes, recipe_vars):
                continue
            out.append(ctx.finding(
                self, node,
                "state.%s in %s with a key expression not reachable "
                "from the class's touched_keys declaration — every "
                "state access in dynamic_validation/update_state must "
                "use a key recipe (function or constant) that "
                "touched_keys itself declares, or the lane planner "
                "will misplan the request's conflicts"
                % (node.func.attr, func.name)))
        return out
