"""PT012 nondeterminism-reachable-from-consensus-path.

RBFT safety rests on every honest replica computing byte-identical
state from the same ordered input (PAPER.md §1). The bug class: a
nondeterminism source — something whose value differs across replica
*processes* fed the same messages — sitting anywhere in the transitive
call closure of a consensus-critical decision. The canonical incident
is the PR-7 catchup jitter (round 3): retry delays derived from
``hash(...)`` — and CPython salts str/bytes hashes per process via
PYTHONHASHSEED — were replaced by a ``zlib.crc32`` salt precisely so
seeded simulations still replay and honest nodes stay analyzable; the
crc32 shape is this rule's good fixture.

Sources (extracted per function by engine/symtab.py):

* ``hash()`` of a provably str/bytes value — per-process salted;
* unseeded module-level ``random.*`` (seeded ``Random`` instances
  resolve to a different receiver and stay out);
* ``time.time()``/``monotonic()``/``perf_counter()`` whose VALUE is
  returned to the caller (timer deltas that never escape are fine);
* ``id()`` — CPython address, different every process;
* iteration over a set (hash order; dict iteration is
  insertion-ordered and stays out) not wrapped in ``sorted()``.

Roots: lane planning (``server/execution_lanes.py``), flat-wire pack
(``common/serializers/flat_wire.py`` encode half), view-change
computation, primary selection, and the digest/ordering decisions in
``consensus/ordering_service.py``. A source is reported at ITS OWN
site (stable baseline coordinates) whenever any root reaches it
through the call graph — use ``scripts/plenum_lint --callgraph
<symbol>`` to walk the path.
"""
from __future__ import annotations

import re
from typing import List

from plenum_tpu.analysis.core import Finding, ProgramRule

DEFAULT_ROOTS = (
    ("plenum_tpu/server/execution_lanes.py", r".*"),
    ("plenum_tpu/common/serializers/flat_wire.py",
     r"^(encode_|build_envelope|_ragged_table)"),
    ("plenum_tpu/consensus/view_change_service.py", r".*"),
    ("plenum_tpu/consensus/primary_selector.py", r".*"),
    ("plenum_tpu/consensus/ordering_service.py",
     r"(digest|_order$|_send_batch_of)"),
    # the gateway's lane pre-planning must agree with the node-side
    # planner on the identical admitted stream — same determinism bar
    ("plenum_tpu/gateway/lane_router.py", r".*"),
)

_MESSAGES = {
    "hash-salted": (
        "hash() of a str/bytes value reachable from a consensus-"
        "critical path — PYTHONHASHSEED salts str hashes per process, "
        "so replicas diverge on the same ordered input; use zlib.crc32 "
        "or hashlib (the PR-7 catchup-jitter fix)"),
    "random": (
        "unseeded random.* call reachable from a consensus-critical "
        "path — module-level entropy differs per replica; derive "
        "pseudo-randomness deterministically from ordered input (the "
        "crc32-salted jitter pattern) or use a seeded Random"),
    "time-value": (
        "wall-clock value escapes into a consensus-critical path — "
        "time.* returned as a VALUE (not a timer delta) differs per "
        "replica; clock readings may only enter consensus as signed "
        "proposer input, never computed independently per node"),
    "id": (
        "id() reachable from a consensus-critical path — CPython "
        "object addresses differ per process and per run; key on a "
        "deterministic identity instead"),
    "set-iter": (
        "iteration over a set reachable from a consensus-critical "
        "path — set order follows the per-process str hash salt; "
        "iterate sorted(...) or keep the collection a dict/list "
        "(insertion-ordered)"),
}


class NondeterminismRule(ProgramRule):
    code = "PT012"
    name = "nondeterminism-reachable-from-consensus-path"
    roots = DEFAULT_ROOTS

    def applies(self, rel_path: str) -> bool:
        return rel_path.startswith("plenum_tpu/")

    def check_program(self, engine, rel_paths) -> List[Finding]:
        specs = [(path, re.compile(rx)) for path, rx in self.roots]
        roots = engine.roots_matching(specs)
        out: List[Finding] = []
        seen = set()
        for sym in sorted(engine.reachable(roots)):
            fn = engine.function(sym)
            path = engine.path_of(sym)
            for rec in fn["nondet"]:
                key = (path, rec["line"], rec["col"], rec["kind"])
                if key in seen:
                    continue
                seen.add(key)
                out.append(Finding(
                    rule=self.code, severity=self.severity, path=path,
                    line=rec["line"], col=rec["col"],
                    message="%s (%s)" % (_MESSAGES[rec["kind"]],
                                         rec["detail"]),
                    symbol=fn["qname"]))
        return out
