"""PT013 dispatch-without-collect.

The ops/ seams are split into async halves — ``X_dispatch`` /
``dispatch_X`` / ``begin_X`` enqueue device work and hand back a
generation (un-awaited arrays, deferred tries, read windows); the
matching ``X_collect`` / ``collect_X`` / ``end_X`` / ``resolve_X``
materializes it. PR 8's fused batch window and PR 13's merged hash
resolve hand generations ACROSS functions (the dispatch half returns
its handle; a frame several calls up resolves it), which is exactly
what a per-function rule cannot check: a dropped handle means device
work launched and never awaited — results silently discarded (the
state the caller thinks it wrote never materializes) and every
overlapped launch behind the seam leaks its slot.

Interprocedural encoding, on the engine's effect summaries:

* a call to a dispatch-shaped name opens its family at the site;
* a call to a function whose SUMMARY ``returns_open`` a family opens
  that family too (the handed-across-functions case);
* the site is clean when the handle is collected locally (family-
  matched closer, the seam alias table, or a materializer like
  ``np.asarray``/``.results()``), returned onward (the caller
  inherits), stored (``self.*`` / containers — pipeline objects own
  their generations), or passed to another call (delegated);
* it LEAKS when the result is discarded outright or bound to locals
  that are never used.

Dispatch halves themselves may return open generations — that is
their contract; obligations attach to call sites, so the top frame
that drops the generation is the one named in the finding.
"""
from __future__ import annotations

from typing import List

from plenum_tpu.analysis.core import Finding, ProgramRule
from plenum_tpu.analysis.engine.summaries import (
    site_families, site_verdict)


class DispatchWithoutCollectRule(ProgramRule):
    code = "PT013"
    name = "dispatch-without-collect"

    def applies(self, rel_path: str) -> bool:
        return rel_path.startswith("plenum_tpu/")

    def check_program(self, engine, rel_paths) -> List[Finding]:
        out: List[Finding] = []
        graph = engine.graph
        for sym in sorted(graph.functions):
            fn = graph.functions[sym]
            path = graph.fn_path[sym]
            summary = engine.summaries.get(sym)
            closes = summary.closes if summary else set()
            resolved = {id(call): callee
                        for callee, call in graph.edges[sym]}
            for call in fn["calls"]:
                callee = resolved.get(id(call))
                families = site_families(call, callee,
                                         engine.summaries)
                if not families:
                    continue
                verdict, fams = site_verdict(call, families, fn,
                                             closes)
                if verdict != "leak":
                    continue
                for fam in fams:
                    via = families[fam]
                    out.append(Finding(
                        rule=self.code, severity=self.severity,
                        path=path, line=call["line"],
                        col=call["col"],
                        message=(
                            "dispatch generation '%s' opened via %s "
                            "is never collected: the device work is "
                            "launched and its results dropped — "
                            "collect/resolve it, return the handle "
                            "to the caller, or store it on the "
                            "owning pipeline object" % (
                                fam,
                                via if ":" not in via
                                else graph.display(via) + "()")),
                        symbol=fn["qname"]))
        return out
