"""PT006 broad-except-on-device-path.

Historical bug class: ``except Exception:`` wrapped around a device or
crypto call swallows real backend failures (OOM, bad shapes, a
mis-built native lib) together with the benign not-supported signals it
meant to absorb. PR 2 narrowed ``copy_to_host_async``'s guard to
``(AttributeError, NotImplementedError)`` with one debug log after a
broad except hid an actual transfer bug; that is the precedent this
rule enforces.

A broad handler (bare ``except``, ``Exception`` or ``BaseException``)
fires only when its ``try`` body reaches device/crypto work:

* any call in a file under ``ops/`` or ``crypto/`` (everything there IS
  the device path);
* elsewhere: calls rooted in a name imported from ``jax`` /
  ``plenum_tpu.ops*`` / ``plenum_tpu.crypto*`` / ``plenum_tpu.native``
  / ``cryptography``, calls through receivers whose attribute names
  mention device/verify/bls seams, or the device attr markers
  (``block_until_ready`` & co).

Handlers that re-raise (a bare ``raise`` in the handler body) pass:
catch-log-reraise does not swallow anything.
"""
from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from plenum_tpu.analysis.core import (
    Finding, ModuleContext, Rule, attr_parts)

DEVICE_MODULE_RE = re.compile(
    r"^(jax|jaxlib|jnp|cryptography|plenum_tpu\.(ops|crypto|native))"
    r"($|\.)")
DEVICE_ATTRS = {"block_until_ready", "device_put", "device_get",
                "copy_to_host_async"}
SEAM_SUBSTRINGS = ("device", "verif", "bls")
BROAD_NAMES = {"Exception", "BaseException"}


def _broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    for n in names:
        if isinstance(n, ast.Name) and n.id in BROAD_NAMES:
            return True
    return False


def _reraises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) and n.exc is None
               for n in ast.walk(handler))


def _imported_device_aliases(tree: ast.Module) -> Set[str]:
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if DEVICE_MODULE_RE.match(a.name):
                    aliases.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            if DEVICE_MODULE_RE.match(node.module):
                for a in node.names:
                    aliases.add(a.asname or a.name)
    return aliases


class BroadExceptOnDevicePathRule(Rule):
    code = "PT006"
    name = "broad-except-on-device-path"

    def applies(self, rel_path: str) -> bool:
        return rel_path.startswith("plenum_tpu/")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        aliases = _imported_device_aliases(ctx.tree)
        in_device_dir = ctx.rel_path.startswith(
            ("plenum_tpu/ops/", "plenum_tpu/crypto/"))
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            evidence = self._device_evidence(
                node.body, aliases, in_device_dir)
            if evidence is None:
                continue
            for handler in node.handlers:
                if _broad(handler) and not _reraises(handler):
                    out.append(ctx.finding(
                        self, handler,
                        "broad except over a device/crypto path (%s in "
                        "the try) swallows backend failures — narrow to "
                        "the specific exception types (the PR 2 "
                        "copy_to_host_async precedent) and log once at "
                        "debug" % evidence))
        return out

    @staticmethod
    def _device_evidence(body, aliases: Set[str],
                         in_device_dir: bool) -> Optional[str]:
        for stmt in body:
            for n in ast.walk(stmt):
                if isinstance(n, (ast.Import, ast.ImportFrom)):
                    mod = (n.names[0].name if isinstance(n, ast.Import)
                           else (n.module or ""))
                    if DEVICE_MODULE_RE.match(mod):
                        return "import %s" % mod
                if isinstance(n, ast.Call):
                    parts = attr_parts(n.func)
                    if not parts:
                        continue
                    if in_device_dir:
                        return ".".join(reversed(parts))
                    if parts[-1] in aliases or parts[0] in DEVICE_ATTRS:
                        return ".".join(reversed(parts))
                    if any(s in p.lower() for p in parts
                           if p not in ("self", "cls")
                           for s in SEAM_SUBSTRINGS):
                        return ".".join(reversed(parts))
                elif isinstance(n, ast.Attribute):
                    # non-call seam references still place the try on the
                    # device path (e.g. a worker-thread method handed to
                    # run_in_executor as an argument)
                    if n.attr in DEVICE_ATTRS or any(
                            s in n.attr.lower() for s in SEAM_SUBSTRINGS):
                        return n.attr
        return None
