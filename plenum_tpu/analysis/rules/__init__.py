"""Rule registry — one module per rule, one instance per analysis run.

Adding a rule: create ``ptNNN_<slug>.py`` with a ``Rule`` subclass,
import it here, append the class to ``RULE_CLASSES``, document it in
``docs/static_analysis.md`` and give it fixtures in
``tests/test_plenum_lint.py``. Codes are PTnnn; PT000 is reserved for
parse errors.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from plenum_tpu.analysis.core import Rule, SEVERITIES
from plenum_tpu.analysis.rules.pt001_blocking import BlockingCallRule
from plenum_tpu.analysis.rules.pt002_host_sync import HostSyncInDispatchRule
from plenum_tpu.analysis.rules.pt003_quorum_auth import QuorumBeforeAuthRule
from plenum_tpu.analysis.rules.pt004_threads import CrossThreadSharedStateRule
from plenum_tpu.analysis.rules.pt005_config_drift import (
    ConfigLiteralDriftRule)
from plenum_tpu.analysis.rules.pt006_broad_except import (
    BroadExceptOnDevicePathRule)
from plenum_tpu.analysis.rules.pt007_fixed_retry_timer import (
    FixedRetryTimerRule)
from plenum_tpu.analysis.rules.pt008_per_item_hot_loop import (
    PerItemHotLoopRule)
from plenum_tpu.analysis.rules.pt009_metric_cardinality import (
    UnboundedMetricCardinalityRule)
from plenum_tpu.analysis.rules.pt010_wire_serializer import (
    WireSerializerLoopRule)
from plenum_tpu.analysis.rules.pt011_declared_keys import (
    DeclaredKeysRule)
from plenum_tpu.analysis.rules.pt012_nondeterminism import (
    NondeterminismRule)
from plenum_tpu.analysis.rules.pt013_dispatch_collect import (
    DispatchWithoutCollectRule)
from plenum_tpu.analysis.rules.pt014_compile_cardinality import (
    CompileCardinalityRule)
from plenum_tpu.analysis.rules.pt015_trace_taint import (
    TraceContextTaintRule)
from plenum_tpu.analysis.rules.pt016_region_state import (
    CrossRegionMutableStateRule)
from plenum_tpu.analysis.rules.pt017_handoff import (
    HandoffDisciplineRule)

RULE_CLASSES = (
    BlockingCallRule,
    HostSyncInDispatchRule,
    QuorumBeforeAuthRule,
    CrossThreadSharedStateRule,
    ConfigLiteralDriftRule,
    BroadExceptOnDevicePathRule,
    FixedRetryTimerRule,
    PerItemHotLoopRule,
    UnboundedMetricCardinalityRule,
    WireSerializerLoopRule,
    DeclaredKeysRule,
    NondeterminismRule,
    DispatchWithoutCollectRule,
    CompileCardinalityRule,
    TraceContextTaintRule,
    CrossRegionMutableStateRule,
    HandoffDisciplineRule,
)


def build_rules(disable: Sequence[str] = (),
                select: Sequence[str] = (),
                severities: Optional[Dict[str, str]] = None,
                root: str = None) -> List[Rule]:
    """Instantiate the registry with per-rule enable/severity applied.
    `select` (when non-empty) wins over `disable`; unknown codes raise
    so a typo'd suppression cannot silently disable nothing."""
    known = {cls.code for cls in RULE_CLASSES}
    for code in list(disable) + list(select) + sorted(severities or {}):
        if code.upper() not in known:
            raise ValueError("unknown rule code %r (known: %s)"
                             % (code, ", ".join(sorted(known))))
    disabled = {c.upper() for c in disable}
    selected = {c.upper() for c in select}
    rules: List[Rule] = []
    for cls in RULE_CLASSES:
        if selected and cls.code not in selected:
            continue
        if cls.code in disabled:
            continue
        rule = cls(root=root) if cls is ConfigLiteralDriftRule else cls()
        sev = (severities or {}).get(cls.code)
        if sev is not None:
            if sev not in SEVERITIES:
                raise ValueError("unknown severity %r for %s (one of %s)"
                                 % (sev, cls.code, "/".join(SEVERITIES)))
            rule.severity = sev
        rules.append(rule)
    return rules
