"""PT003 quorum-before-auth.

Historical bug: the pre-PR-1 propagate path (server/propagator.py)
counted quorum votes — and echo-voted — for requests first learned from
a peer's PROPAGATE without authenticating them. One byzantine relay
plus the honest echo then reached the f+1 propagate quorum with a
forged payload (found by the TamperedPropagate adversary scenario).
The fix gates first-sighting payloads on the request authenticator
BEFORE they may enter the vote-collecting state.

Encoding: in ``server/`` and ``consensus/``, any function that receives
a peer sender (a parameter named ``frm`` / ``sender`` — the node-message
handler convention throughout this repo) and mutates propagate-quorum
state (``*.propagates.add(...)``, ``*requests.add(...)``) must
reference an authenticator seam (a name containing ``authenticat``, or
``verify_signature``) on a line at or before the first mutation. Client
-intake paths (no ``frm`` parameter) authenticate at intake and are out
of scope.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from plenum_tpu.analysis.core import (
    Finding, ModuleContext, Rule, attr_parts, dotted,
    walk_skipping_nested_defs)

SENDER_PARAMS = {"frm", "sender", "frm_name", "from_name"}
AUTH_MARKERS = ("authenticat", "verify_signature")


def _is_vote_mutation(call: ast.Call) -> bool:
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr == "add"):
        return False
    receiver = attr_parts(call.func.value)
    return any(p == "propagates" or p.endswith("requests")
               for p in receiver)


def _is_auth_ref(node: ast.AST) -> Optional[str]:
    name = None
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Name):
        name = node.id
    if name is None:
        return None
    low = name.lower()
    if any(m in low for m in AUTH_MARKERS):
        return name
    return None


class QuorumBeforeAuthRule(Rule):
    code = "PT003"
    name = "quorum-before-auth"

    def applies(self, rel_path: str) -> bool:
        return rel_path.startswith(("plenum_tpu/server/",
                                    "plenum_tpu/consensus/"))

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            params = {a.arg for a in fn.args.args
                      + fn.args.posonlyargs + fn.args.kwonlyargs}
            if not params & SENDER_PARAMS:
                continue
            first_mutation = None
            first_auth_line = None
            for sub in walk_skipping_nested_defs(fn):
                if isinstance(sub, ast.Call) and _is_vote_mutation(sub):
                    if first_mutation is None \
                            or sub.lineno < first_mutation.lineno:
                        first_mutation = sub
                auth = _is_auth_ref(sub)
                if auth is not None:
                    if first_auth_line is None \
                            or sub.lineno < first_auth_line:
                        first_auth_line = sub.lineno
            if first_mutation is None:
                continue
            if first_auth_line is None \
                    or first_auth_line > first_mutation.lineno:
                out.append(ctx.finding(
                    self, first_mutation,
                    "peer-message handler %s() mutates quorum/vote state "
                    "(%s) without an authenticator check before the "
                    "mutation — a byzantine relay could forge f+1 "
                    "propagate votes (the PR 1 hole)" % (
                        fn.name,
                        dotted(first_mutation.func) or "vote state")))
        return out
