"""PT001 blocking-call-in-loop-path.

Historical bug class: the node runs single-threaded cooperative loops
(runtime/looper.py prod ticks, asyncio in the verify daemon and
networked node). One synchronous sleep / subprocess / Future.result()
inside a handler stalls every co-scheduled node in the process — the
PR 1 view-change fix (`_vc_started_at` stamped off a blocking path) and
the daemon's run-in-executor design exist precisely to keep these out
of the loop.

Scope: ``server/`` and ``consensus/``. Contexts checked: any ``async
def``, plus synchronous handler-shaped functions (process_*/handle_*/
on_*/prod/serve). Sync file I/O (bare ``open``) is only flagged inside
``async def`` — handlers may legitimately touch files via injected
storage seams, but an event-loop coroutine never should.
"""
from __future__ import annotations

import ast
import re
from typing import List

from plenum_tpu.analysis.core import (
    Finding, ModuleContext, Rule, dotted, walk_skipping_nested_defs)

HANDLER_NAME = re.compile(r"^_{0,2}(process|handle|on)_")
HANDLER_EXACT = {"prod", "serve"}

BLOCKING_CALLS = {"time.sleep", "os.system", "os.popen", "os.wait",
                  "os.waitpid"}
BLOCKING_ROOTS = {"subprocess"}


class BlockingCallRule(Rule):
    code = "PT001"
    name = "blocking-call-in-loop-path"

    def applies(self, rel_path: str) -> bool:
        return rel_path.startswith(("plenum_tpu/server/",
                                    "plenum_tpu/consensus/",
                                    "plenum_tpu/gateway/"))

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            is_async = isinstance(node, ast.AsyncFunctionDef)
            if not (is_async or isinstance(node, ast.FunctionDef)):
                continue
            if not is_async and not (HANDLER_NAME.match(node.name)
                                     or node.name in HANDLER_EXACT):
                continue
            ctx_label = ("async def %s" if is_async
                         else "handler %s") % node.name
            for sub in walk_skipping_nested_defs(node):
                if not isinstance(sub, ast.Call):
                    continue
                msg = self._blocking(sub, is_async)
                if msg:
                    out.append(ctx.finding(
                        self, sub,
                        "%s inside %s — the cooperative loop (and every "
                        "co-scheduled node) stalls with it" % (
                            msg, ctx_label)))
        return out

    @staticmethod
    def _blocking(call: ast.Call, is_async: bool):
        name = dotted(call.func)
        if name in BLOCKING_CALLS:
            return "blocking call %s()" % name
        if name and name.split(".", 1)[0] in BLOCKING_ROOTS:
            return "blocking call %s()" % name
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr == "result":
            return "blocking Future.result() harvest"
        if is_async and name == "open":
            return "synchronous file I/O (open())"
        return None
