"""PT007 fixed-period-retry-timer.

Historical bug class: retry/re-request machinery armed as a
``RepeatingTimer`` with a fixed period. The PR-7 incident is the
catchup leecher (`server/catchup.py`): a `RepeatingTimer(timer,
CATCHUP_TXN_TIMEOUT, self._retry)` re-assigned chunks to
`sorted(connecteds)` at a constant cadence — a dead or lying peer
received the same chunk forever, every leecher in the pool re-requested
in lockstep, and a congested seeder was hammered at exactly the period
that congested it. The fix is one-shot self-rescheduling with capped
exponential backoff + jitter (see `LedgerLeecher._schedule_retry`).

Encoding: a ``RepeatingTimer(...)`` construction on a RETRY PATH whose
interval argument is a numeric literal is flagged. A retry path is one
where either the enclosing function name or the assignment target the
timer lands in mentions retry/resend/resubmit/rearm/backoff/re-request.
The interval must at minimum route through Config (an operator-tunable
name), and retry logic should prefer backoff-aware one-shot
rescheduling over any fixed period — a literal gives the operator no
knob and the fleet no jitter. Periodic NON-retry work (metrics flushes,
watchdog sweeps) is out of scope: a fixed cadence is correct there.
"""
from __future__ import annotations

import ast
import re
from typing import List, Optional

from plenum_tpu.analysis.core import Finding, ModuleContext, Rule

RETRY_NAME = re.compile(
    r"(retry|retries|resend|re_send|resubmit|re_submit|rearm|re_arm|"
    r"backoff|re_request|rerequest)", re.IGNORECASE)


def _is_numeric_literal(node: ast.AST) -> bool:
    """A literal period: 5, 2.0, -1, or literal-only arithmetic like
    60 * 5 — anything carrying no name the operator could override."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool)
    if isinstance(node, ast.UnaryOp):
        return _is_numeric_literal(node.operand)
    if isinstance(node, ast.BinOp):
        return _is_numeric_literal(node.left) \
            and _is_numeric_literal(node.right)
    return False


class FixedRetryTimerRule(Rule):
    code = "PT007"
    name = "fixed-period-retry-timer"

    def applies(self, rel_path: str) -> bool:
        return rel_path.startswith(("plenum_tpu/server/",
                                    "plenum_tpu/consensus/",
                                    "plenum_tpu/client/"))

    @staticmethod
    def _interval_arg(call: ast.Call) -> Optional[ast.AST]:
        """RepeatingTimer(timer, interval, callback, ...) — second
        positional, or the `interval` keyword."""
        for kw in call.keywords:
            if kw.arg == "interval":
                return kw.value
        if len(call.args) >= 2:
            return call.args[1]
        return None

    @staticmethod
    def _target_name(ctx: ModuleContext, call: ast.Call) -> str:
        """The name the constructed timer is bound to (assignment
        target attribute/variable), '' when unbound."""
        parent = ctx.parent(call)
        if isinstance(parent, ast.Assign):
            names = []
            for tgt in parent.targets:
                if isinstance(tgt, ast.Attribute):
                    names.append(tgt.attr)
                elif isinstance(tgt, ast.Name):
                    names.append(tgt.id)
            return " ".join(names)
        return ""

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for func in ast.walk(ctx.tree):
            if not isinstance(func, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            func_is_retry = bool(RETRY_NAME.search(func.name))
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                callee = node.func
                name = callee.attr if isinstance(callee, ast.Attribute) \
                    else callee.id if isinstance(callee, ast.Name) \
                    else ""
                if name != "RepeatingTimer":
                    continue
                interval = self._interval_arg(node)
                if interval is None or not _is_numeric_literal(interval):
                    continue
                target = self._target_name(ctx, node)
                if not (func_is_retry or RETRY_NAME.search(target)):
                    continue
                out.append(ctx.finding(
                    self, node,
                    "RepeatingTimer with a literal period on a retry "
                    "path (%s) — retries need a Config-sourced, "
                    "backoff-aware schedule (capped exponential + "
                    "jitter, see LedgerLeecher._schedule_retry), not a "
                    "fixed cadence that hammers dead peers in lockstep"
                    % (("function %s" % func.name) if func_is_retry
                       else ("target %s" % target))))
        return out
