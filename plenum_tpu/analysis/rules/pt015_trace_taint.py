"""PT015 trace-context-taint-into-consensus-path.

The wire trace stamp (flat_wire ``KIND_TRACE`` section / the typed
envelopes' ``traceCtx`` field) is ADVISORY by contract
(docs/wire.md): a peer controls every byte of it, a corrupt stamp
decodes to ``None``, and message handling must proceed identically
with or without it. That contract only holds if stamp CONTENT is
provably unreachable from consensus decisions — the moment a digest,
ordering, view-change or lane-planning path reads a parsed stamp, a
byzantine peer steers honest-replica state through an "observability"
field and the PT012 determinism story collapses with it.

This rule pins the boundary from both directions:

* **parse-in-consensus-closure** — a function inside the transitive
  call closure of the PT012 consensus roots (execution lanes,
  flat-wire encode half, view change, primary selection, ordering
  digests, gateway lane router) calls the trace-section parse surface
  (``decode_trace_stamp`` / ``TraceStamp.from_wire``). Stamp content
  would flow straight into a consensus decision.
* **parse-reaches-consensus** — the parse surface's own call closure
  contains a consensus root: stamp handling calling back into
  consensus is the same taint flowing the other way (e.g. a decode
  helper that "helpfully" triggers an ordering step).

The receive seams that legitimately parse stamps (node/propagator
``wire_recv`` recording) live outside both closures — they only feed
the tracer ring buffer, which nothing on a consensus path reads.
"""
from __future__ import annotations

import re
from typing import List

from plenum_tpu.analysis.core import Finding, ProgramRule
from plenum_tpu.analysis.rules.pt012_nondeterminism import DEFAULT_ROOTS

# the trace-section parse surface: the only places wire-controlled
# stamp bytes become Python values
_PARSE_TERMINALS = frozenset({"decode_trace_stamp"})
_PARSE_CLASS = "TraceStamp"
_PARSE_CLASS_METHOD = "from_wire"


def _is_parse_call(chain) -> bool:
    if not chain:
        return False
    terminal = chain[-1]
    if terminal in _PARSE_TERMINALS:
        return True
    return terminal == _PARSE_CLASS_METHOD and _PARSE_CLASS in chain


def _is_parse_symbol(fn) -> bool:
    if fn["name"] in _PARSE_TERMINALS:
        return True
    return (fn["name"] == _PARSE_CLASS_METHOD
            and fn.get("cls") == _PARSE_CLASS)


class TraceContextTaintRule(ProgramRule):
    code = "PT015"
    name = "trace-context-taint-into-consensus-path"
    roots = DEFAULT_ROOTS

    def applies(self, rel_path: str) -> bool:
        return rel_path.startswith("plenum_tpu/")

    def check_program(self, engine, rel_paths) -> List[Finding]:
        specs = [(path, re.compile(rx)) for path, rx in self.roots]
        root_syms = engine.roots_matching(specs)
        closure = engine.reachable(root_syms)
        out: List[Finding] = []

        # direction 1: consensus closure must not PARSE stamps
        for sym in sorted(closure):
            fn = engine.function(sym)
            if fn is None:
                continue
            for call in fn["calls"]:
                if not _is_parse_call(call["chain"]):
                    continue
                out.append(Finding(
                    rule=self.code, severity=self.severity,
                    path=engine.path_of(sym),
                    line=call["line"], col=call["col"],
                    message=(
                        "wire trace-context parse (%s) reachable from a "
                        "consensus root — the stamp is peer-controlled "
                        "advisory data; consensus paths must never read "
                        "it (decode at the observability receive seams "
                        "only)" % ".".join(call["chain"])),
                    symbol=fn["qname"]))

        # direction 2: the parse surface must not REACH consensus
        parse_syms = [sym for sym, fn in engine.graph.functions.items()
                      if _is_parse_symbol(fn)]
        root_set = set(root_syms)
        for sym in sorted(parse_syms):
            reached = engine.reachable([sym]) & root_set
            for root_sym in sorted(reached):
                fn = engine.function(sym)
                out.append(Finding(
                    rule=self.code, severity=self.severity,
                    path=engine.path_of(sym),
                    line=fn["line"], col=fn["col"],
                    message=(
                        "trace-stamp parse surface calls into consensus "
                        "root %s — stamp handling must stay advisory "
                        "(record-and-return), never trigger consensus "
                        "work" % engine.symbol_display(root_sym)),
                    symbol=fn["qname"]))
        return out
