"""PT004 cross-thread-shared-state.

Historical context: the verify daemon runs device launches on a
dedicated worker thread while its asyncio loop keeps coalescing, and
the flight recorder (observability/tracing.py) is written from both.
The sanctioned shapes are (a) hold a lock around every cross-thread
attribute write, or (b) the Tracer fixed-slot pattern — writes go into
preallocated ring slots (``self._buf[i] = rec``, a subscript store, not
an attribute rebind) under a tiny critical section.

Encoding, per class: find thread entry points — methods passed as
``threading.Thread(target=self.X)``, ``pool.submit(self.X, ...)`` or
``loop.run_in_executor(pool, self.X, ...)`` — and take their same-class
transitive call closure as the worker side. Any ``self.attr`` written
both by the worker side and by other methods (``__init__`` excluded:
construction happens before the thread exists), where either write is
outside a ``with <something lock-ish>`` block, is flagged. Subscript
stores (the fixed-slot pattern) are not attribute writes and pass.

Pipeline boundaries (runtime/pipeline.py) add two more shapes:

* **Queue-crossing values must be immutable** — bytes, numpy views,
  frozen job records. A ``put``/``put_nowait`` whose argument is a
  freshly built MUTABLE container (dict/list/set literal or
  comprehension) hands the other thread state the producer can still
  reach; flagged wherever it appears.
* **Consensus state is prod-thread-owned** — a worker-side unlocked
  write to a consensus-named attribute (prepares/commits/propagates/
  stashes/suspicions/view_no/last_ordered/ledger/state roots/request
  queues) is flagged even with NO loop-side co-writer: the pipeline
  ownership contract says workers parse, the prod thread counts, so
  the write itself is the defect, not just the race.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from plenum_tpu.analysis.core import (
    Finding, ModuleContext, Rule, attr_parts, dotted)

LOCKISH = ("lock", "mutex", "cond", "sem")

# attribute-name fragments that mean "consensus state" at the pipeline
# boundary: prod-thread-owned, never worker-writable (the
# OrderingService/Propagator vocabulary)
CONSENSUS_ATTRS = ("prepare", "commit", "propagat", "stash", "suspic",
                   "view_no", "last_ordered", "ledger", "state_root",
                   "requestqueue", "request_queue")

# ast nodes that build a fresh MUTABLE container — the shapes that must
# not cross a thread queue (immutable bytes/views/frozen records do)
_MUTABLE_BUILDS = (ast.Dict, ast.List, ast.Set, ast.ListComp,
                   ast.SetComp, ast.DictComp)


def _consensus_attr(attr: str) -> bool:
    low = attr.lower()
    return any(frag in low for frag in CONSENSUS_ATTRS)


def _lockish_expr(expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        name = n.attr if isinstance(n, ast.Attribute) else (
            n.id if isinstance(n, ast.Name) else None)
        if name and any(m in name.lower() for m in LOCKISH):
            return True
    return False


def _self_attr(node: ast.AST):
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _entry_points(cls: ast.ClassDef) -> Set[str]:
    """Method names handed to another thread within this class."""
    out: Set[str] = set()

    def method_ref(node) -> str:
        attr = _self_attr(node)
        return attr if attr else None

    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func) or ""
        parts = attr_parts(node.func)
        if name.endswith("Thread") or (parts and parts[0] == "Thread"):
            for kw in node.keywords:
                if kw.arg == "target":
                    ref = method_ref(kw.value)
                    if ref:
                        out.add(ref)
        elif parts and parts[0] == "submit" and node.args:
            ref = method_ref(node.args[0])
            if ref:
                out.add(ref)
        elif parts and parts[0] == "run_in_executor" \
                and len(node.args) >= 2:
            ref = method_ref(node.args[1])
            if ref:
                out.add(ref)
    return out


class CrossThreadSharedStateRule(Rule):
    code = "PT004"
    name = "cross-thread-shared-state"
    # the engine-backed region analysis (PT016/PT017) supersedes this
    # same-class heuristic: whole-program spawn-target resolution sees
    # cross-class/cross-module worker reach this rule cannot. PT004
    # runs only as the fallback when the engine fails to build.
    subsumed_by = "PT016"

    def applies(self, rel_path: str) -> bool:
        return rel_path.startswith("plenum_tpu/")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        out.extend(self._check_queue_puts(ctx))
        for cls in ast.walk(ctx.tree):
            if isinstance(cls, ast.ClassDef):
                out.extend(self._check_class(ctx, cls))
        return out

    def _check_queue_puts(self, ctx: ModuleContext) -> List[Finding]:
        """Queue-crossing immutability: a put/put_nowait whose argument
        is a freshly built mutable container (dict/list/set literal or
        comprehension) hands the consuming thread state the producer
        can still reach. Queue payloads must be immutable — bytes,
        numpy views, frozen/slotted job records."""
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            parts = attr_parts(node.func)
            if not parts or parts[0] not in ("put", "put_nowait"):
                continue
            for arg in node.args:
                if isinstance(arg, _MUTABLE_BUILDS):
                    out.append(ctx.finding(
                        self, arg,
                        "a freshly built mutable %s crosses a thread "
                        "queue via %s() — queue payloads must be "
                        "immutable (bytes, numpy views, frozen "
                        "records): the consumer would share state the "
                        "producer can still mutate" % (
                            type(arg).__name__.lower(), parts[0]),
                        symbol=dotted(node.func) or parts[0]))
                    break
        return out

    def _check_class(self, ctx: ModuleContext,
                     cls: ast.ClassDef) -> List[Finding]:
        methods: Dict[str, ast.AST] = {
            m.name: m for m in cls.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}
        entries = _entry_points(cls) & set(methods)
        if not entries:
            return []
        # worker side: entry points + same-class transitive callees
        worker: Set[str] = set()
        frontier = list(entries)
        while frontier:
            name = frontier.pop()
            if name in worker:
                continue
            worker.add(name)
            for node in ast.walk(methods[name]):
                if isinstance(node, ast.Call):
                    callee = _self_attr(node.func)
                    if callee in methods and callee not in worker:
                        frontier.append(callee)

        # writes: attr -> list of (method, node, locked)
        def writes(method) -> List[Tuple[str, ast.AST, bool]]:
            found: List[Tuple[str, ast.AST, bool]] = []

            def visit(node, locked):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and node is not method:
                    return
                if isinstance(node, ast.With):
                    inner = locked or any(
                        _lockish_expr(item.context_expr)
                        for item in node.items)
                    for child in ast.iter_child_nodes(node):
                        visit(child, inner)
                    return
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for tgt in targets:
                    attr = _self_attr(tgt)
                    if attr:
                        found.append((attr, node, locked))
                for child in ast.iter_child_nodes(node):
                    visit(child, locked)

            visit(method, False)
            return found

        worker_writes: Dict[str, List] = {}
        loop_writes: Dict[str, List] = {}
        for name, node in methods.items():
            if name == "__init__":
                continue
            bucket = worker_writes if name in worker else loop_writes
            for attr, site, locked in writes(node):
                bucket.setdefault(attr, []).append((name, site, locked))

        out: List[Finding] = []
        # pipeline ownership contract: consensus-named attributes are
        # prod-thread-owned — an unlocked worker-side write is the
        # defect itself, no loop-side co-writer needed
        dual = set(worker_writes) & set(loop_writes)
        for attr in sorted(set(worker_writes) - dual):
            if not _consensus_attr(attr):
                continue
            unlocked = [s for s in worker_writes[attr] if not s[2]]
            if not unlocked:
                continue
            name, site, _ = unlocked[0]
            out.append(ctx.finding(
                self, site,
                "self.%s (consensus state) is written from the "
                "worker-thread path (%s) — consensus state is owned "
                "by the prod thread; workers may only parse and hand "
                "immutable results back over the queue" % (
                    attr,
                    "/".join(sorted({s[0] for s in worker_writes[attr]
                                     }))),
                symbol="%s.%s" % (cls.name, name)))
        for attr in sorted(dual):
            w_sites = worker_writes[attr]
            l_sites = loop_writes[attr]
            unlocked = [s for s in w_sites + l_sites if not s[2]]
            if not unlocked:
                continue
            name, site, _ = unlocked[0]
            out.append(ctx.finding(
                self, site,
                "self.%s is written from both the worker-thread path "
                "(%s) and loop code (%s) without a lock — use a lock or "
                "the Tracer fixed-slot pattern" % (
                    attr,
                    "/".join(sorted({s[0] for s in w_sites})),
                    "/".join(sorted({s[0] for s in l_sites}))),
                symbol="%s.%s" % (cls.name, name)))
        return out
