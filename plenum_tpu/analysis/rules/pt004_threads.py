"""PT004 cross-thread-shared-state.

Historical context: the verify daemon runs device launches on a
dedicated worker thread while its asyncio loop keeps coalescing, and
the flight recorder (observability/tracing.py) is written from both.
The sanctioned shapes are (a) hold a lock around every cross-thread
attribute write, or (b) the Tracer fixed-slot pattern — writes go into
preallocated ring slots (``self._buf[i] = rec``, a subscript store, not
an attribute rebind) under a tiny critical section.

Encoding, per class: find thread entry points — methods passed as
``threading.Thread(target=self.X)``, ``pool.submit(self.X, ...)`` or
``loop.run_in_executor(pool, self.X, ...)`` — and take their same-class
transitive call closure as the worker side. Any ``self.attr`` written
both by the worker side and by other methods (``__init__`` excluded:
construction happens before the thread exists), where either write is
outside a ``with <something lock-ish>`` block, is flagged. Subscript
stores (the fixed-slot pattern) are not attribute writes and pass.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from plenum_tpu.analysis.core import (
    Finding, ModuleContext, Rule, attr_parts, dotted)

LOCKISH = ("lock", "mutex", "cond", "sem")


def _lockish_expr(expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        name = n.attr if isinstance(n, ast.Attribute) else (
            n.id if isinstance(n, ast.Name) else None)
        if name and any(m in name.lower() for m in LOCKISH):
            return True
    return False


def _self_attr(node: ast.AST):
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _entry_points(cls: ast.ClassDef) -> Set[str]:
    """Method names handed to another thread within this class."""
    out: Set[str] = set()

    def method_ref(node) -> str:
        attr = _self_attr(node)
        return attr if attr else None

    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func) or ""
        parts = attr_parts(node.func)
        if name.endswith("Thread") or (parts and parts[0] == "Thread"):
            for kw in node.keywords:
                if kw.arg == "target":
                    ref = method_ref(kw.value)
                    if ref:
                        out.add(ref)
        elif parts and parts[0] == "submit" and node.args:
            ref = method_ref(node.args[0])
            if ref:
                out.add(ref)
        elif parts and parts[0] == "run_in_executor" \
                and len(node.args) >= 2:
            ref = method_ref(node.args[1])
            if ref:
                out.add(ref)
    return out


class CrossThreadSharedStateRule(Rule):
    code = "PT004"
    name = "cross-thread-shared-state"

    def applies(self, rel_path: str) -> bool:
        return rel_path.startswith("plenum_tpu/")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for cls in ast.walk(ctx.tree):
            if isinstance(cls, ast.ClassDef):
                out.extend(self._check_class(ctx, cls))
        return out

    def _check_class(self, ctx: ModuleContext,
                     cls: ast.ClassDef) -> List[Finding]:
        methods: Dict[str, ast.AST] = {
            m.name: m for m in cls.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}
        entries = _entry_points(cls) & set(methods)
        if not entries:
            return []
        # worker side: entry points + same-class transitive callees
        worker: Set[str] = set()
        frontier = list(entries)
        while frontier:
            name = frontier.pop()
            if name in worker:
                continue
            worker.add(name)
            for node in ast.walk(methods[name]):
                if isinstance(node, ast.Call):
                    callee = _self_attr(node.func)
                    if callee in methods and callee not in worker:
                        frontier.append(callee)

        # writes: attr -> list of (method, node, locked)
        def writes(method) -> List[Tuple[str, ast.AST, bool]]:
            found: List[Tuple[str, ast.AST, bool]] = []

            def visit(node, locked):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and node is not method:
                    return
                if isinstance(node, ast.With):
                    inner = locked or any(
                        _lockish_expr(item.context_expr)
                        for item in node.items)
                    for child in ast.iter_child_nodes(node):
                        visit(child, inner)
                    return
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for tgt in targets:
                    attr = _self_attr(tgt)
                    if attr:
                        found.append((attr, node, locked))
                for child in ast.iter_child_nodes(node):
                    visit(child, locked)

            visit(method, False)
            return found

        worker_writes: Dict[str, List] = {}
        loop_writes: Dict[str, List] = {}
        for name, node in methods.items():
            if name == "__init__":
                continue
            bucket = worker_writes if name in worker else loop_writes
            for attr, site, locked in writes(node):
                bucket.setdefault(attr, []).append((name, site, locked))

        out: List[Finding] = []
        for attr in sorted(set(worker_writes) & set(loop_writes)):
            w_sites = worker_writes[attr]
            l_sites = loop_writes[attr]
            unlocked = [s for s in w_sites + l_sites if not s[2]]
            if not unlocked:
                continue
            name, site, _ = unlocked[0]
            out.append(ctx.finding(
                self, site,
                "self.%s is written from both the worker-thread path "
                "(%s) and loop code (%s) without a lock — use a lock or "
                "the Tracer fixed-slot pattern" % (
                    attr,
                    "/".join(sorted({s[0] for s in w_sites})),
                    "/".join(sorted({s[0] for s in l_sites}))),
                symbol="%s.%s" % (cls.name, name)))
        return out
