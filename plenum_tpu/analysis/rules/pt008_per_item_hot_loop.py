"""PT008 per-item-loop-in-hot-3pc-handler.

Historical bug class: per-message 3PC handlers under ``consensus/``
scanning a request/digest/vote collection with a Python loop. The PR-8
incident is ``OrderingService._has_prepared``: every inbound PREPARE
re-counted the sender dict with a comprehension (``len([s for s in
self.prepares[key] if s != primary])``) — O(n) per message, O(n²) per
batch per node, and at 25 validators the counting loop alone dominated
the ordering money path (BENCH_r05: ~209 ordered req/s against ~62k
device verifies/s). The fix is columnar: incremental quorum counters
bumped at vote insert (one dict read per check) and batch intake
(``process_prepare_batch``/``process_commit_batch``) that hoists the
shared checks and compares the digest column in one vectorized pass.

Encoding: inside a HOT per-message handler — a function whose name is
``process_*``/``_process_*``/``validate_*``/``_try_*``/``_has_*``
mentioning a 3PC message type (prepare/commit/pre-prepare/propagate)
and NOT itself a ``*_batch`` variant — any ``for`` loop or
comprehension iterating a request/digest/vote collection
(``prepares``/``commits``/``propagates``/``requests``/``digests``/
``req_idr``/``votes``/``shares``, plain or behind an attribute /
subscript / ``.items()``-style call) is flagged. Batch handlers are
exempt: one loop per inbound BATCH is the columnar design, not the
quadratic shape. Intentionally scalar paths (rare, cold, or
correctness-bound per-item work such as per-share BLS validation)
carry a justified baseline entry or an inline pragma.
"""
from __future__ import annotations

import ast
import re
from typing import List

from plenum_tpu.analysis.core import Finding, ModuleContext, Rule

HANDLER_NAME = re.compile(r"^_?(process|validate|try|has)_")
MSG_3PC = re.compile(
    r"(prepare|pre_?prepare|commit|propagate|three_?pc|3pc)",
    re.IGNORECASE)
COLLECTION = re.compile(
    r"^(prepares|commits|propagates|requests|digests|req_?idr|votes|"
    r"shares|prepares_store|commits_store)$", re.IGNORECASE)

# iterator-protocol helpers that still walk the same collection
_ITER_METHODS = {"items", "keys", "values", "get"}


def _collection_name(node: ast.AST) -> str:
    """The terminal name of an iterable expression: ``self.prepares``,
    ``self.prepares[key]``, ``commits.items()``, ``state.propagates``
    all resolve to the collection identifier the loop walks."""
    if isinstance(node, ast.Call):
        callee = node.func
        if isinstance(callee, ast.Attribute) \
                and callee.attr in _ITER_METHODS:
            return _collection_name(callee.value)
        return ""
    if isinstance(node, ast.Subscript):
        return _collection_name(node.value)
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


class PerItemHotLoopRule(Rule):
    code = "PT008"
    name = "per-item-loop-in-hot-3pc-handler"

    def applies(self, rel_path: str) -> bool:
        return rel_path.startswith(("plenum_tpu/consensus/",
                                    "plenum_tpu/gateway/"))

    @staticmethod
    def _is_hot_handler(name: str) -> bool:
        return bool(HANDLER_NAME.match(name)) \
            and bool(MSG_3PC.search(name)) \
            and "batch" not in name.lower()

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for func in ast.walk(ctx.tree):
            if not isinstance(func, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not self._is_hot_handler(func.name):
                continue
            for node in ast.walk(func):
                if isinstance(node, ast.For):
                    iters = [node.iter]
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.GeneratorExp, ast.DictComp)):
                    iters = [g.iter for g in node.generators]
                else:
                    continue
                for it in iters:
                    coll = _collection_name(it)
                    if not coll or not COLLECTION.match(coll):
                        continue
                    out.append(ctx.finding(
                        self, node,
                        "per-item loop over '%s' inside hot per-message "
                        "handler %s — O(items) per inbound message is "
                        "quadratic per batch; use an incremental "
                        "counter maintained at insert, or move the "
                        "work to the columnar *_batch intake "
                        "(process_prepare_batch/process_commit_batch)"
                        % (coll, func.name)))
                    break
        return out
