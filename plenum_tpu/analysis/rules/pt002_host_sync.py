"""PT002 host-sync-in-dispatch.

Historical bug class: the ops/ seams are split into a *dispatch* half
(enqueue the device program, return un-awaited arrays) and a *collect*
half (materialize). The whole pipelining design — ProofPipeline,
MeshPipeline, the hub's flush/collect split — depends on dispatch
halves never forcing a host sync: one stray ``np.asarray`` /
``block_until_ready`` there serializes every overlapped launch. PR 4
also killed an eager ``jax.devices()[0]`` probe in ed25519_jax that
force-initialized the backend at import scope and would have disabled
Pallas process-wide when it raced the platform env; ``ops/mesh.py``
(probe_platform) is now the ONE sanctioned enumeration point.

Two checks:

* anywhere in the package except ``ops/mesh.py``: calls to
  ``jax.devices`` / ``jax.local_devices`` / ``jax.device_count`` —
  route through ``mesh.probe_platform`` / ``mesh.default_device``.
* in ``ops/`` dispatch-half functions ("dispatch" in the name or a
  ``*_async`` suffix, and not a collect): ``.block_until_ready()``,
  ``jax.device_get``, and ``np.asarray`` / ``float`` / ``int`` applied
  to a device-tainted expression (result of a ``jax.*`` / ``jnp.*``
  call, propagated through local assignments).
"""
from __future__ import annotations

import ast
from typing import List, Set

from plenum_tpu.analysis.core import (
    Finding, ModuleContext, Rule, dotted, walk_skipping_nested_defs)

EAGER_PROBES = {"jax.devices", "jax.local_devices", "jax.device_count",
                "jax.local_device_count"}
DEVICE_GET = {"jax.device_get"}
HOST_CONVERTERS = {"np.asarray", "numpy.asarray", "float", "int"}


def _is_dispatch_half(name: str) -> bool:
    low = name.lower()
    return ("collect" not in low
            and ("dispatch" in low or low.endswith("_async")))


def _device_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted(node.func)
    if not name:
        return False
    root = name.split(".", 1)[0]
    return root in ("jnp", "jax") and name not in EAGER_PROBES


class HostSyncInDispatchRule(Rule):
    code = "PT002"
    name = "host-sync-in-dispatch"

    def applies(self, rel_path: str) -> bool:
        return (rel_path.startswith("plenum_tpu/")
                and rel_path != "plenum_tpu/ops/mesh.py")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                name = dotted(node.func)
                if name in EAGER_PROBES:
                    out.append(ctx.finding(
                        self, node,
                        "eager %s() initializes the JAX backend — route "
                        "device/platform questions through ops/mesh.py "
                        "(probe_platform / default_device)" % name))
        if ctx.rel_path.startswith("plenum_tpu/ops/"):
            for node in ast.walk(ctx.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and _is_dispatch_half(node.name):
                    out.extend(self._check_dispatch(ctx, node))
        return out

    def _check_dispatch(self, ctx: ModuleContext,
                        fn: ast.AST) -> List[Finding]:
        out: List[Finding] = []
        tainted: Set[str] = set()

        def expr_tainted(expr: ast.AST) -> bool:
            for n in ast.walk(expr):
                if _device_call(n):
                    return True
                if isinstance(n, ast.Name) and n.id in tainted:
                    return True
            return False

        def note(node, what):
            out.append(ctx.finding(
                self, node,
                "%s in dispatch-half %s() forces a host sync — the "
                "dispatch/collect overlap (and every pipelined launch "
                "behind it) serializes here" % (what, fn.name)))

        # flow-insensitive taint over this function's OWN assignments
        # (nested defs excluded — their locals are a different scope),
        # iterated to a fixpoint so a->b->c chains resolve regardless
        # of the walk's visit order
        assigns = [sub for sub in walk_skipping_nested_defs(fn)
                   if isinstance(sub, ast.Assign)]
        changed = True
        while changed:
            changed = False
            for sub in assigns:
                if not expr_tainted(sub.value):
                    continue
                for tgt in sub.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name) \
                                and n.id not in tainted:
                            tainted.add(n.id)
                            changed = True
        for sub in walk_skipping_nested_defs(fn):
            if not isinstance(sub, ast.Call):
                continue
            name = dotted(sub.func)
            if isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "block_until_ready":
                note(sub, "block_until_ready()")
            elif name in DEVICE_GET:
                note(sub, "%s()" % name)
            elif name in HOST_CONVERTERS and sub.args \
                    and expr_tainted(sub.args[0]):
                note(sub, "%s() on a device array" % name)
        return out
