"""plenum-lint core — findings, rule protocol, pragmas, the driver.

The analyzer is pure stdlib-`ast`: it never imports the modules it
checks, so it can run under any interpreter state (no JAX init, no
native extensions) and is safe as a tier-1 gate. Each rule encodes one
bug class this repo has actually shipped and fixed by hand (see
docs/static_analysis.md for the catalog and the historical incident
behind every rule).

Suppression layers, weakest to strongest:

* inline pragma  — ``# plenum-lint: disable=PT006`` on the finding's
  line (or ``disable=all``); a pragma comment alone on one of the first
  five lines of a file disables the codes for the whole file.
* baseline      — ``lint_baseline.json`` grandfathers known findings by
  (rule, path, symbol, message) so the gate only fails on NEW findings
  (see baseline.py).
* rule disable  — ``--disable PT005`` / per-rule severity overrides at
  the CLI / Analyzer level.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set

SEVERITIES = ("error", "warning")

PRAGMA_RE = re.compile(
    r"#\s*plenum-lint:\s*disable=([A-Za-z0-9_, ]+|all)")
# pragma-only lines near the top of a file disable codes file-wide
FILE_PRAGMA_HEAD_LINES = 5


def iter_pragmas(lines):
    """Yield ``(lineno, codes, file_wide)`` for every pragma comment —
    the ONE implementation of the pragma syntax, shared by the
    per-module rules (ModuleContext) and the whole-program engine
    (engine/symtab.py), so both suppression layers can never drift."""
    for i, line in enumerate(lines, start=1):
        m = PRAGMA_RE.search(line)
        if not m:
            continue
        codes = {c.strip().upper() for c in m.group(1).split(",")
                 if c.strip()}
        file_wide = (i <= FILE_PRAGMA_HEAD_LINES
                     and line.strip().startswith("#"))
        yield i, codes, file_wide


@dataclass(frozen=True)
class Finding:
    rule: str        # "PT001"
    severity: str    # "error" | "warning"
    path: str        # repo-relative posix path
    line: int
    col: int
    message: str     # line-number-free (stable across drift)
    symbol: str      # dotted enclosing scope, e.g. "VerifyDaemon._batcher"

    def location(self) -> str:
        return "%s:%d:%d" % (self.path, self.line, self.col)

    def render(self) -> str:
        loc = " [%s]" % self.symbol if self.symbol else ""
        return "%s: %s %s: %s%s" % (self.location(), self.rule,
                                    self.severity, self.message, loc)


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain; None for anything dynamic
    (subscripts, call results) — rules treat dynamic receivers as
    unmatchable rather than guessing."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def attr_parts(node: ast.AST) -> List[str]:
    """Every attribute/name component of a chain (dynamic roots allowed:
    ``self._engine[0].x`` still yields ["x"])."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return parts


def walk_skipping_nested_defs(node: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body without descending into nested function /
    class definitions (which get their own analysis context)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(n))


class ModuleContext:
    """One parsed file handed to every rule: tree + source lines +
    pragma map + enclosing-symbol resolution."""

    def __init__(self, rel_path: str, source: str, tree: ast.Module):
        self.rel_path = rel_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.line_pragmas: Dict[int, Set[str]] = {}
        self.file_pragmas: Set[str] = set()
        self._scan_pragmas()
        self._parents: Dict[int, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[id(child)] = parent

    # ------------------------------------------------------------ pragmas

    def _scan_pragmas(self) -> None:
        for i, codes, file_wide in iter_pragmas(self.lines):
            self.line_pragmas.setdefault(i, set()).update(codes)
            if file_wide:
                self.file_pragmas.update(codes)

    def suppressed(self, code: str, line: int) -> bool:
        for codes in (self.file_pragmas, self.line_pragmas.get(line, ())):
            if "ALL" in codes or code.upper() in codes:
                return True
        return False

    # ------------------------------------------------------------ symbols

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    def symbol_for(self, node: ast.AST) -> str:
        """Dotted class/function scope enclosing `node` ("" at module
        level) — the stable coordinate baselines key on."""
        names: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.append(cur.name)
            cur = self.parent(cur)
        return ".".join(reversed(names))

    def finding(self, rule: "Rule", node: ast.AST, message: str,
                symbol: str = None) -> Finding:
        return Finding(
            rule=rule.code, severity=rule.severity, path=self.rel_path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message,
            symbol=self.symbol_for(node) if symbol is None else symbol)


class Rule:
    """One named check. Subclasses set `code`/`name`/`severity` and
    implement check(ctx); `applies` gives cheap path scoping so rules
    only parse-walk the layers their bug class lives in."""

    code = "PT000"
    name = "abstract"
    severity = "error"
    # code of a ProgramRule that supersedes this one: when that rule is
    # active AND the engine builds, this rule is held out of the run (it
    # becomes the engine-unavailable fallback). None = always runs.
    subsumed_by: Optional[str] = None

    def applies(self, rel_path: str) -> bool:
        return True

    def check(self, ctx: ModuleContext) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


class ProgramRule(Rule):
    """Whole-program rule: sees the inter-procedural engine (symbol
    table, call graph, bottom-up summaries) instead of one module at a
    time. ``check_program`` runs ONCE per analysis over the full
    program scope; findings are filtered to the scanned files by the
    driver, so ``--changed`` stays meaningful while resolution is
    always whole-tree."""

    def check(self, ctx: ModuleContext) -> List[Finding]:
        return []

    def check_program(self, engine,
                      rel_paths) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


class ParseErrorRule(Rule):
    """Synthetic rule code for unparseable files — a syntax error in the
    scanned tree must fail the gate, not be skipped silently."""
    code = "PT000"
    name = "parse-error"


_PARSE_ERROR = ParseErrorRule()


class Analyzer:
    def __init__(self, rules: Sequence[Rule], root: str,
                 use_engine_cache: bool = True):
        """root: repository root; finding paths are relative to it."""
        self.rules = [r for r in rules
                      if not isinstance(r, ProgramRule)]
        self.program_rules = [r for r in rules
                              if isinstance(r, ProgramRule)]
        # subsumed heuristics: held out while their superseding
        # ProgramRule is active — they re-enter the per-module pass
        # only when the engine fails to build (the fallback path)
        program_codes = {r.code for r in self.program_rules}
        self.held_rules = [r for r in self.rules
                           if r.subsumed_by in program_codes]
        self.rules = [r for r in self.rules
                      if r not in self.held_rules]
        self.root = os.path.abspath(root)
        self.use_engine_cache = use_engine_cache
        self.engine = None  # built lazily by run_files
        self.engine_error: Optional[str] = None

    # --------------------------------------------------------- file walk

    def collect_files(self, paths: Sequence[str]) -> List[str]:
        out: List[str] = []
        for p in paths:
            p = os.path.abspath(p)
            if os.path.isfile(p):
                if p.endswith(".py"):
                    out.append(p)
                continue
            for base, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(base, f))
        # stable order, no duplicates
        seen, uniq = set(), []
        for f in out:
            if f not in seen:
                seen.add(f)
                uniq.append(f)
        return uniq

    def _rel(self, path: str) -> str:
        rel = os.path.relpath(os.path.abspath(path), self.root)
        return rel.replace(os.sep, "/")

    # ----------------------------------------------------------- analyze

    def run_files(self, files: Sequence[str]) -> List[Finding]:
        findings: List[Finding] = []
        module_rules = list(self.rules)
        if self.program_rules:
            try:
                findings.extend(self._run_program_rules(files))
            except Exception as exc:
                # engine unavailable: the subsumed heuristics are the
                # fallback — coverage degrades to per-module precision
                # instead of disappearing
                self.engine_error = "%s: %s" % (
                    type(exc).__name__, exc)
                module_rules = module_rules + self.held_rules
        for path in files:
            findings.extend(self.run_one(path, module_rules))
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return findings

    def _program_scope(self, files: Sequence[str]) -> List[str]:
        """The file set the engine resolves over: the whole package
        tree when it exists (inter-procedural rules must see callees
        outside a --changed diff), else just the scanned files (fixture
        trees)."""
        pkg = os.path.join(self.root, "plenum_tpu")
        scope = list(files)
        if os.path.isdir(pkg):
            known = set(scope)
            scope.extend(p for p in self.collect_files([pkg])
                         if p not in known)
        return scope

    def _run_program_rules(self, files: Sequence[str]
                           ) -> List[Finding]:
        from plenum_tpu.analysis.engine import Engine
        if self.engine is None:
            self.engine = Engine.build(
                self._program_scope(files), self.root,
                use_cache=self.use_engine_cache)
        scanned = {self._rel(p) for p in files}
        out: List[Finding] = []
        for rule in self.program_rules:
            for f in rule.check_program(self.engine, scanned):
                if f.path in scanned and rule.applies(f.path) \
                        and not self.engine.suppressed(
                            f.path, f.rule, f.line):
                    out.append(f)
        return out

    def run_one(self, path: str,
                rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
        rel = self._rel(path)
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (SyntaxError, ValueError, OSError) as exc:
            return [Finding(
                rule=_PARSE_ERROR.code, severity="error", path=rel,
                line=getattr(exc, "lineno", None) or 1, col=0,
                message="cannot parse file: %s" % exc, symbol="")]
        ctx = ModuleContext(rel, source, tree)
        out: List[Finding] = []
        for rule in (self.rules if rules is None else rules):
            if not rule.applies(rel):
                continue
            for finding in rule.check(ctx):
                if not ctx.suppressed(finding.rule, finding.line):
                    out.append(finding)
        return out

    def run_paths(self, paths: Sequence[str]) -> List[Finding]:
        return self.run_files(self.collect_files(paths))
