"""Finding baseline — grandfathered findings with justifications.

The gate (tests/test_lint_clean.py) must fail on NEW findings while
known, triaged ones ride along. Entries key on
(rule, path, symbol, message) — deliberately NOT on line numbers, so
unrelated edits that shift a file don't invalidate the baseline; a
count field absorbs several identical findings in one symbol.

Every entry carries a one-line ``justification``: a baseline without a
reason is just a muted bug. ``--write-baseline`` emits entries with a
TODO justification for the author to fill in before committing.
Entries that no longer match anything are reported as stale so the
baseline shrinks as code is fixed instead of fossilizing.
"""
from __future__ import annotations

import json
from typing import Dict, List, Tuple

from plenum_tpu.analysis.core import Finding

VERSION = 1

Key = Tuple[str, str, str, str]

# PT004 (per-module thread heuristic) was subsumed by the whole-program
# region rules: the same finding now fires under PT016 (cross-region
# mutable state) or PT017 (handoff discipline) with a byte-identical
# message. Re-key grandfathered entries at load so justifications
# survive the rule split; message fragments discriminate which rule a
# given entry migrated to. Entries whose message matches neither
# fragment stay PT004 — with the engine active PT004 is held out, so
# such entries surface through stale() instead of being dropped
# silently.
_RULE_MIGRATIONS: Tuple[Tuple[str, str, str], ...] = (
    ("PT004", "worker-thread path", "PT016"),
    ("PT004", "crosses a thread queue", "PT017"),
)


def migrate_entries(entries: List[dict]) -> Tuple[List[dict], int]:
    """→ (entries with superseded rule ids re-keyed, migration count)."""
    out, n = [], 0
    for e in entries:
        for old_rule, fragment, new_rule in _RULE_MIGRATIONS:
            if e.get("rule") == old_rule and fragment in e.get("message", ""):
                e = dict(e, rule=new_rule)
                n += 1
                break
        out.append(e)
    return out, n


def _key(f: Finding) -> Key:
    return (f.rule, f.path, f.symbol, f.message)


class Baseline:
    def __init__(self, entries: List[dict] = None):
        self.entries = list(entries or [])

    # ------------------------------------------------------------- load/save

    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path, "r", encoding="utf-8") as f:
                data = json.load(f)
        except FileNotFoundError:
            return cls([])
        if data.get("version") != VERSION:
            raise ValueError(
                "unsupported lint baseline version %r in %s"
                % (data.get("version"), path))
        entries, _ = migrate_entries(data.get("entries", []))
        return cls(entries)

    def save(self, path: str) -> None:
        data = {"version": VERSION, "entries": self.entries}
        with open(path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2, sort_keys=False)
            f.write("\n")

    @classmethod
    def from_findings(cls, findings: List[Finding],
                      justification: str = "TODO: justify or fix"
                      ) -> "Baseline":
        counts: Dict[Key, int] = {}
        order: List[Key] = []
        for f in findings:
            k = _key(f)
            if k not in counts:
                order.append(k)
            counts[k] = counts.get(k, 0) + 1
        entries = []
        for rule, path, symbol, message in order:
            e = {"rule": rule, "path": path, "symbol": symbol,
                 "message": message,
                 "justification": justification}
            n = counts[(rule, path, symbol, message)]
            if n > 1:
                e["count"] = n
            entries.append(e)
        return cls(entries)

    # ------------------------------------------------------------- matching

    def match(self, findings: List[Finding]
              ) -> Tuple[List[Finding], List[Finding]]:
        """→ (new, baselined). Consumes entry counts so a baseline entry
        absorbs at most `count` findings (default 1)."""
        budget: Dict[Key, int] = {}
        for e in self.entries:
            k = (e["rule"], e["path"], e.get("symbol", ""), e["message"])
            budget[k] = budget.get(k, 0) + int(e.get("count", 1))
        new, old = [], []
        for f in findings:
            k = _key(f)
            if budget.get(k, 0) > 0:
                budget[k] -= 1
                old.append(f)
            else:
                new.append(f)
        self._leftover = {k: v for k, v in budget.items() if v > 0}
        return new, old

    def stale(self) -> List[Key]:
        """Entry keys (with remaining budget) the last match() never
        consumed — candidates for deletion."""
        return sorted(getattr(self, "_leftover", {}))
