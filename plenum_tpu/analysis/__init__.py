"""plenum-lint — AST-based consensus-safety and device-hygiene analyzer.

Rules encode this repo's shipped-and-fixed bug classes (PT001–PT014;
see docs/static_analysis.md). Pure stdlib ``ast`` — importing or
running the analyzer never initializes JAX or any native extension,
which is what lets tests/test_lint_clean.py gate tier-1 in-process.

Programmatic entry point::

    from plenum_tpu.analysis import run_analysis
    new, baselined, findings = run_analysis(paths, root=repo_root)
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

from plenum_tpu.analysis.baseline import Baseline
from plenum_tpu.analysis.core import Analyzer, Finding, Rule
from plenum_tpu.analysis.rules import RULE_CLASSES, build_rules

__all__ = ["Analyzer", "Baseline", "Finding", "Rule", "RULE_CLASSES",
           "build_rules", "repo_root", "run_analysis"]


def repo_root() -> str:
    """The checkout root (three levels above this package)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def run_analysis(paths: Sequence[str], root: str = None,
                 baseline_path: Optional[str] = None,
                 disable: Sequence[str] = (),
                 select: Sequence[str] = (),
                 severities=None,
                 ) -> Tuple[List[Finding], List[Finding], Baseline]:
    """Run the full registry over `paths` → (new, baselined, baseline).
    `baseline_path=None` means no baseline (everything is new)."""
    root = root or repo_root()
    analyzer = Analyzer(
        build_rules(disable=disable, select=select,
                    severities=severities, root=root), root)
    findings = analyzer.run_paths(paths)
    baseline = (Baseline.load(baseline_path) if baseline_path
                else Baseline([]))
    new, baselined = baseline.match(findings)
    return new, baselined, baseline
