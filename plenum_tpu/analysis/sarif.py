"""SARIF 2.1.0 output — the interchange format CI review UIs ingest.

GitHub code scanning, GitLab SAST, VS Code's SARIF viewer and most
code-review bots all speak SARIF; emitting it directly means the
pre-commit/CI recipe (``plenum_lint --changed --sarif``, see README)
annotates diffs with findings without any adapter glue.

Mapping choices:

* one ``run`` with the full rule catalog under ``tool.driver.rules``
  (``helpUri`` points at docs/static_analysis.md);
* finding severity ``error``/``warning`` → SARIF ``level`` verbatim;
* baseline state is preserved: grandfathered findings emit
  ``baselineState: "unchanged"``, new ones ``"new"`` — a SARIF
  consumer can mirror the gate's new-findings-only policy;
* ``partialFingerprints`` carries the baseline key (rule, path,
  symbol, message) so result identity is line-drift-proof, same as
  ``lint_baseline.json``.
"""
from __future__ import annotations

from typing import List

from plenum_tpu.analysis.core import Finding, Rule

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")
DOCS_URI = "docs/static_analysis.md"


def _rule_descriptor(rule: Rule) -> dict:
    return {
        "id": rule.code,
        "name": rule.name,
        "shortDescription": {"text": rule.name},
        "helpUri": DOCS_URI,
        "defaultConfiguration": {
            "level": "error" if rule.severity == "error" else "warning",
        },
    }


def to_sarif(findings: List[Finding], baselined: set,
             rules: List[Rule]) -> dict:
    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "level": "error" if f.severity == "error" else "warning",
            "message": {"text": f.message},
            "baselineState": ("unchanged" if f in baselined
                              else "new"),
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {
                        "startLine": max(f.line, 1),
                        "startColumn": max(f.col + 1, 1),
                    },
                },
                "logicalLocations": [{
                    "fullyQualifiedName": f.symbol or f.path,
                }],
            }],
            "partialFingerprints": {
                "plenumLintKey/v1": "%s|%s|%s|%s" % (
                    f.rule, f.path, f.symbol, f.message),
            },
        })
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "plenum-lint",
                    "informationUri": DOCS_URI,
                    "rules": [_rule_descriptor(r) for r in rules],
                },
            },
            "originalUriBaseIds": {
                "SRCROOT": {"description": {
                    "text": "repository root"}},
            },
            "results": results,
        }],
    }
