"""plenum_tpu — a TPU-native Byzantine-fault-tolerant distributed-ledger
framework with the capabilities of indy-plenum (RBFT consensus, multi-ledger
Merkle transaction logs, Merkle-Patricia-Trie state, BLS-multi-signed state
proofs, catchup, view change, audit ledger, pluggable request handling).

Design (see SURVEY.md §7): the consensus control plane is a deterministic,
single-threaded, message-passing event loop on the host (reference:
stp_core/loop/looper.py, plenum/server/node.py:1037). All bulk math —
ed25519 signature verification, BLS12-381 aggregation, SHA-256 Merkle
hashing — lives in `plenum_tpu.ops` as pure batched JAX functions that are
dispatched per prod tick and shard across a `jax.sharding.Mesh`
(`plenum_tpu.parallel`). Scalar CPU fallbacks keep the latency floor low.
"""

__version__ = "0.1.0"
