/* fastpath.c — CPython extension for the consensus per-request hot path.
 *
 * The profile of the ordering pipeline (see docs/performance.md) is flat
 * Python: canonical serialization (digests), strict deep-equality
 * (propagate dedup), base58, and sha256 plumbing dominate once signature
 * verification is off the host. This module collapses those into single
 * C calls:
 *
 *   canonical_json(obj)    -> bytes   == json.dumps(obj, sort_keys=True,
 *                                        separators=(',',':'),
 *                                        ensure_ascii=False).encode()
 *   digest_hex(obj)        -> str     == sha256(canonical_json(obj)).hexdigest()
 *   canonical_msgpack(obj) -> bytes   == msgpack.packb(_sort_deep(obj),
 *                                                      use_bin_type=True)
 *   msgpack_digest_hex(obj)-> str     == sha256(canonical_msgpack(obj)).hexdigest()
 *   deep_eq(a, b)          -> bool    == serializers-strict deep equality
 *                                        (types must match at every node)
 *   b58encode(bytes)       -> str
 *   b58decode(str|bytes)   -> bytes
 *   sha256(bytes)          -> bytes
 *   sha256_hex(bytes)      -> str
 *
 * Exact byte-compatibility with the Python implementations is asserted
 * by tests/test_fastpath_native.py over randomized nested structures —
 * consensus digests and merkle roots depend on it.
 *
 * Reference equivalence: indy-plenum leans on C extensions for the same
 * reason (msgpack C packer, libsodium, rocksdb); this file is the
 * framework's own native layer for the remaining Python-bound costs.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

/* ------------------------------------------------------------------ */
/* SHA-256 (FIPS 180-4), small-message oriented                        */
/* ------------------------------------------------------------------ */

typedef struct {
    uint32_t h[8];
    uint64_t len;
    uint8_t buf[64];
    size_t buflen;
} sha256_ctx;

static const uint32_t K256[64] = {
    0x428a2f98,0x71374491,0xb5c0fbcf,0xe9b5dba5,0x3956c25b,0x59f111f1,
    0x923f82a4,0xab1c5ed5,0xd807aa98,0x12835b01,0x243185be,0x550c7dc3,
    0x72be5d74,0x80deb1fe,0x9bdc06a7,0xc19bf174,0xe49b69c1,0xefbe4786,
    0x0fc19dc6,0x240ca1cc,0x2de92c6f,0x4a7484aa,0x5cb0a9dc,0x76f988da,
    0x983e5152,0xa831c66d,0xb00327c8,0xbf597fc7,0xc6e00bf3,0xd5a79147,
    0x06ca6351,0x14292967,0x27b70a85,0x2e1b2138,0x4d2c6dfc,0x53380d13,
    0x650a7354,0x766a0abb,0x81c2c92e,0x92722c85,0xa2bfe8a1,0xa81a664b,
    0xc24b8b70,0xc76c51a3,0xd192e819,0xd6990624,0xf40e3585,0x106aa070,
    0x19a4c116,0x1e376c08,0x2748774c,0x34b0bcb5,0x391c0cb3,0x4ed8aa4a,
    0x5b9cca4f,0x682e6ff3,0x748f82ee,0x78a5636f,0x84c87814,0x8cc70208,
    0x90befffa,0xa4506ceb,0xbef9a3f7,0xc67178f2
};

#define ROR(x,n) (((x) >> (n)) | ((x) << (32 - (n))))

static void sha256_init(sha256_ctx *c) {
    c->h[0]=0x6a09e667; c->h[1]=0xbb67ae85; c->h[2]=0x3c6ef372;
    c->h[3]=0xa54ff53a; c->h[4]=0x510e527f; c->h[5]=0x9b05688c;
    c->h[6]=0x1f83d9ab; c->h[7]=0x5be0cd19;
    c->len = 0; c->buflen = 0;
}

static void sha256_block(sha256_ctx *c, const uint8_t *p) {
    uint32_t w[64];
    uint32_t a,b,d,e,f,g,h,t1,t2,cc;
    int i;
    for (i = 0; i < 16; i++)
        w[i] = ((uint32_t)p[4*i] << 24) | ((uint32_t)p[4*i+1] << 16) |
               ((uint32_t)p[4*i+2] << 8) | (uint32_t)p[4*i+3];
    for (i = 16; i < 64; i++) {
        uint32_t s0 = ROR(w[i-15],7) ^ ROR(w[i-15],18) ^ (w[i-15] >> 3);
        uint32_t s1 = ROR(w[i-2],17) ^ ROR(w[i-2],19) ^ (w[i-2] >> 10);
        w[i] = w[i-16] + s0 + w[i-7] + s1;
    }
    a=c->h[0]; b=c->h[1]; cc=c->h[2]; d=c->h[3];
    e=c->h[4]; f=c->h[5]; g=c->h[6]; h=c->h[7];
    for (i = 0; i < 64; i++) {
        uint32_t S1 = ROR(e,6) ^ ROR(e,11) ^ ROR(e,25);
        uint32_t ch = (e & f) ^ ((~e) & g);
        t1 = h + S1 + ch + K256[i] + w[i];
        uint32_t S0 = ROR(a,2) ^ ROR(a,13) ^ ROR(a,22);
        uint32_t mj = (a & b) ^ (a & cc) ^ (b & cc);
        t2 = S0 + mj;
        h=g; g=f; f=e; e=d+t1; d=cc; cc=b; b=a; a=t1+t2;
    }
    c->h[0]+=a; c->h[1]+=b; c->h[2]+=cc; c->h[3]+=d;
    c->h[4]+=e; c->h[5]+=f; c->h[6]+=g; c->h[7]+=h;
}

static void sha256_update(sha256_ctx *c, const uint8_t *p, size_t n) {
    c->len += n;
    if (c->buflen) {
        size_t take = 64 - c->buflen;
        if (take > n) take = n;
        memcpy(c->buf + c->buflen, p, take);
        c->buflen += take; p += take; n -= take;
        if (c->buflen == 64) { sha256_block(c, c->buf); c->buflen = 0; }
    }
    while (n >= 64) { sha256_block(c, p); p += 64; n -= 64; }
    if (n) { memcpy(c->buf, p, n); c->buflen = n; }
}

static void sha256_final(sha256_ctx *c, uint8_t out[32]) {
    uint64_t bitlen = c->len * 8;
    uint8_t pad = 0x80;
    uint8_t lenb[8];
    int i;
    sha256_update(c, &pad, 1);
    while (c->buflen != 56) {
        uint8_t z = 0;
        sha256_update(c, &z, 1);
    }
    for (i = 0; i < 8; i++) lenb[i] = (uint8_t)(bitlen >> (56 - 8*i));
    sha256_update(c, lenb, 8);
    for (i = 0; i < 8; i++) {
        out[4*i]   = (uint8_t)(c->h[i] >> 24);
        out[4*i+1] = (uint8_t)(c->h[i] >> 16);
        out[4*i+2] = (uint8_t)(c->h[i] >> 8);
        out[4*i+3] = (uint8_t)(c->h[i]);
    }
}

static const char HEXD[] = "0123456789abcdef";

static PyObject *hex_str(const uint8_t *d, size_t n) {
    char tmp[128];
    size_t i;
    if (n > 64) return NULL;
    for (i = 0; i < n; i++) {
        tmp[2*i] = HEXD[d[i] >> 4];
        tmp[2*i+1] = HEXD[d[i] & 15];
    }
    return PyUnicode_FromStringAndSize(tmp, (Py_ssize_t)(2 * n));
}

/* ------------------------------------------------------------------ */
/* growable byte buffer                                                */
/* ------------------------------------------------------------------ */

typedef struct {
    uint8_t *p;
    size_t len, cap;
    uint8_t stack[4096];
} buf_t;

static void buf_init(buf_t *b) {
    b->p = b->stack; b->len = 0; b->cap = sizeof(b->stack);
}

static void buf_free(buf_t *b) {
    if (b->p != b->stack) PyMem_Free(b->p);
}

static int buf_grow(buf_t *b, size_t need) {
    size_t ncap = b->cap * 2;
    uint8_t *np;
    while (ncap < b->len + need) ncap *= 2;
    if (b->p == b->stack) {
        np = PyMem_Malloc(ncap);
        if (!np) { PyErr_NoMemory(); return -1; }
        memcpy(np, b->stack, b->len);
    } else {
        np = PyMem_Realloc(b->p, ncap);
        if (!np) { PyErr_NoMemory(); return -1; }
    }
    b->p = np; b->cap = ncap;
    return 0;
}

static inline int buf_put(buf_t *b, const void *src, size_t n) {
    if (b->len + n > b->cap && buf_grow(b, n) < 0) return -1;
    memcpy(b->p + b->len, src, n);
    b->len += n;
    return 0;
}

static inline int buf_putc(buf_t *b, uint8_t c) {
    if (b->len + 1 > b->cap && buf_grow(b, 1) < 0) return -1;
    b->p[b->len++] = c;
    return 0;
}

/* ------------------------------------------------------------------ */
/* sorted-key iteration helper                                         */
/*                                                                     */
/* Python's sorted(dict) sorts str keys by code point, which equals    */
/* byte order of their UTF-8 encodings.  Small dicts (requests have    */
/* 4-8 keys) — insertion sort on an index array.                       */
/* ------------------------------------------------------------------ */

typedef struct {
    const char *u8;   /* UTF-8 bytes of the key */
    Py_ssize_t u8len;
    PyObject *key;
    PyObject *val;
} kv_t;

static int cmp_kv(const kv_t *a, const kv_t *b) {
    Py_ssize_t n = a->u8len < b->u8len ? a->u8len : b->u8len;
    int c = memcmp(a->u8, b->u8, (size_t)n);
    if (c) return c;
    return (a->u8len > b->u8len) - (a->u8len < b->u8len);
}

/* Collect dict items with UTF-8 keys, sorted.  Returns count or -1.
 * Caller must PyMem_Free(*out).  All keys must be str. */
static Py_ssize_t dict_sorted_items(PyObject *d, kv_t **out) {
    Py_ssize_t n = PyDict_Size(d), i, j, pos = 0;
    kv_t *items = PyMem_Malloc((size_t)(n > 0 ? n : 1) * sizeof(kv_t));
    PyObject *k, *v;
    if (!items) { PyErr_NoMemory(); return -1; }
    i = 0;
    while (PyDict_Next(d, &pos, &k, &v)) {
        if (!PyUnicode_Check(k)) {
            PyMem_Free(items);
            PyErr_SetString(PyExc_TypeError, "non-str dict key");
            return -1;
        }
        items[i].u8 = PyUnicode_AsUTF8AndSize(k, &items[i].u8len);
        if (!items[i].u8) { PyMem_Free(items); return -1; }
        items[i].key = k; items[i].val = v;
        i++;
    }
    for (i = 1; i < n; i++) {
        kv_t tmp = items[i];
        for (j = i - 1; j >= 0 && cmp_kv(&items[j], &tmp) > 0; j--)
            items[j + 1] = items[j];
        items[j + 1] = tmp;
    }
    *out = items;
    return n;
}

/* ------------------------------------------------------------------ */
/* canonical JSON                                                      */
/* ------------------------------------------------------------------ */

static int json_write(buf_t *b, PyObject *o, int depth, int ascii);

/* ensure_ascii=True string writer: non-ASCII code points become \uXXXX
 * (surrogate pairs above the BMP) — byte-identical to json.dumps's
 * default mode, which the state-value serializer uses. */
static int json_write_str_ascii(buf_t *b, PyObject *s) {
    Py_ssize_t n, i;
    int kind;
    const void *data;
    if (PyUnicode_READY(s) < 0) return -1;
    n = PyUnicode_GET_LENGTH(s);
    kind = PyUnicode_KIND(s);
    data = PyUnicode_DATA(s);
    if (buf_putc(b, '"') < 0) return -1;
    for (i = 0; i < n; i++) {
        Py_UCS4 c = PyUnicode_READ(kind, data, i);
        if (c == '"') { if (buf_put(b, "\\\"", 2) < 0) return -1; }
        else if (c == '\\') { if (buf_put(b, "\\\\", 2) < 0) return -1; }
        else if (c == '\b') { if (buf_put(b, "\\b", 2) < 0) return -1; }
        else if (c == '\f') { if (buf_put(b, "\\f", 2) < 0) return -1; }
        else if (c == '\n') { if (buf_put(b, "\\n", 2) < 0) return -1; }
        else if (c == '\r') { if (buf_put(b, "\\r", 2) < 0) return -1; }
        else if (c == '\t') { if (buf_put(b, "\\t", 2) < 0) return -1; }
        else if (c >= 0x20 && c < 0x7f) {
            if (buf_putc(b, (uint8_t)c) < 0) return -1;
        } else if (c <= 0xffff) {
            char esc[7];
            snprintf(esc, sizeof esc, "\\u%04x", (unsigned)c);
            if (buf_put(b, esc, 6) < 0) return -1;
        } else {
            char esc[13];
            Py_UCS4 v = c - 0x10000;
            snprintf(esc, sizeof esc, "\\u%04x\\u%04x",
                     (unsigned)(0xd800 + (v >> 10)),
                     (unsigned)(0xdc00 + (v & 0x3ff)));
            if (buf_put(b, esc, 12) < 0) return -1;
        }
    }
    return buf_putc(b, '"');
}

static int json_write_str(buf_t *b, PyObject *s) {
    Py_ssize_t n, i, run;
    const char *u = PyUnicode_AsUTF8AndSize(s, &n);
    if (!u) return -1;
    if (buf_putc(b, '"') < 0) return -1;
    run = 0;
    for (i = 0; i < n; i++) {
        uint8_t c = (uint8_t)u[i];
        if (c == '"' || c == '\\' || c < 0x20) {
            if (run && buf_put(b, u + i - run, (size_t)run) < 0) return -1;
            run = 0;
            switch (c) {
            case '"':  if (buf_put(b, "\\\"", 2) < 0) return -1; break;
            case '\\': if (buf_put(b, "\\\\", 2) < 0) return -1; break;
            case '\b': if (buf_put(b, "\\b", 2) < 0) return -1; break;
            case '\f': if (buf_put(b, "\\f", 2) < 0) return -1; break;
            case '\n': if (buf_put(b, "\\n", 2) < 0) return -1; break;
            case '\r': if (buf_put(b, "\\r", 2) < 0) return -1; break;
            case '\t': if (buf_put(b, "\\t", 2) < 0) return -1; break;
            default: {
                char esc[7];
                esc[0]='\\'; esc[1]='u'; esc[2]='0'; esc[3]='0';
                esc[4]=HEXD[c >> 4]; esc[5]=HEXD[c & 15];
                if (buf_put(b, esc, 6) < 0) return -1;
            }
            }
        } else {
            run++;
        }
    }
    if (run && buf_put(b, u + n - run, (size_t)run) < 0) return -1;
    return buf_putc(b, '"');
}

static int json_write_long(buf_t *b, PyObject *o) {
    int overflow = 0;
    long long v = PyLong_AsLongLongAndOverflow(o, &overflow);
    char tmp[24];
    if (!overflow) {
        if (v == -1 && PyErr_Occurred()) return -1;
        snprintf(tmp, sizeof tmp, "%lld", v);
        return buf_put(b, tmp, strlen(tmp));
    }
    /* arbitrary precision: fall back to str() */
    {
        PyObject *s = PyObject_Str(o);
        Py_ssize_t n;
        const char *u;
        int rc;
        if (!s) return -1;
        u = PyUnicode_AsUTF8AndSize(s, &n);
        rc = u ? buf_put(b, u, (size_t)n) : -1;
        Py_DECREF(s);
        return rc;
    }
}

static int json_write_float(buf_t *b, PyObject *o) {
    double v = PyFloat_AS_DOUBLE(o);
    char *s;
    int rc;
    if (v != v) return buf_put(b, "NaN", 3);
    if (v > 1e308 * 10) {} /* silence pedantic warnings */
    if (Py_IS_INFINITY(v))
        return v > 0 ? buf_put(b, "Infinity", 8)
                     : buf_put(b, "-Infinity", 9);
    s = PyOS_double_to_string(v, 'r', 0, Py_DTSF_ADD_DOT_0, NULL);
    if (!s) return -1;
    rc = buf_put(b, s, strlen(s));
    PyMem_Free(s);
    return rc;
}

static int json_write(buf_t *b, PyObject *o, int depth, int ascii) {
    if (depth > 100) {
        /* TypeError on purpose: callers catch TypeError and fall back to
         * the Python serializers, which handle deep nesting — raising a
         * different type here would make C-equipped nodes diverge from
         * fallback nodes on client-controlled inputs. */
        PyErr_SetString(PyExc_TypeError,
                        "structure too deep for native fastpath");
        return -1;
    }
    if (o == Py_None) return buf_put(b, "null", 4);
    if (o == Py_True) return buf_put(b, "true", 4);
    if (o == Py_False) return buf_put(b, "false", 5);
    if (PyUnicode_Check(o))
        return ascii ? json_write_str_ascii(b, o) : json_write_str(b, o);
    if (PyLong_Check(o)) return json_write_long(b, o);
    if (PyFloat_Check(o)) return json_write_float(b, o);
    if (PyDict_Check(o)) {
        kv_t *items;
        Py_ssize_t n = dict_sorted_items(o, &items), i;
        if (n < 0) return -1;
        if (buf_putc(b, '{') < 0) { PyMem_Free(items); return -1; }
        for (i = 0; i < n; i++) {
            int krc;
            if (i && buf_putc(b, ',') < 0) { PyMem_Free(items); return -1; }
            krc = ascii ? json_write_str_ascii(b, items[i].key)
                        : json_write_str(b, items[i].key);
            if (krc < 0 ||
                buf_putc(b, ':') < 0 ||
                json_write(b, items[i].val, depth + 1, ascii) < 0) {
                PyMem_Free(items);
                return -1;
            }
        }
        PyMem_Free(items);
        return buf_putc(b, '}');
    }
    if (PyList_Check(o) || PyTuple_Check(o)) {
        Py_ssize_t n = PySequence_Size(o), i;
        if (buf_putc(b, '[') < 0) return -1;
        for (i = 0; i < n; i++) {
            PyObject *it = PySequence_GetItem(o, i);
            int rc;
            if (!it) return -1;
            if (i && buf_putc(b, ',') < 0) { Py_DECREF(it); return -1; }
            rc = json_write(b, it, depth + 1, ascii);
            Py_DECREF(it);
            if (rc < 0) return -1;
        }
        return buf_putc(b, ']');
    }
    PyErr_Format(PyExc_TypeError, "unsupported type for canonical json: %s",
                 Py_TYPE(o)->tp_name);
    return -1;
}

/* ------------------------------------------------------------------ */
/* canonical msgpack (== msgpack.packb(_sort_deep(x), use_bin_type=1)) */
/* ------------------------------------------------------------------ */

static int mp_write(buf_t *b, PyObject *o, int depth);

static int mp_write_u16(buf_t *b, uint8_t tag, uint32_t v) {
    uint8_t t[3] = { tag, (uint8_t)(v >> 8), (uint8_t)v };
    return buf_put(b, t, 3);
}

static int mp_write_u32(buf_t *b, uint8_t tag, uint32_t v) {
    uint8_t t[5] = { tag, (uint8_t)(v >> 24), (uint8_t)(v >> 16),
                     (uint8_t)(v >> 8), (uint8_t)v };
    return buf_put(b, t, 5);
}

static int mp_write_str(buf_t *b, PyObject *s) {
    Py_ssize_t n;
    const char *u = PyUnicode_AsUTF8AndSize(s, &n);
    if (!u) return -1;
    if (n < 32) {
        if (buf_putc(b, (uint8_t)(0xa0 | n)) < 0) return -1;
    } else if (n < 256) {
        uint8_t t[2] = { 0xd9, (uint8_t)n };
        if (buf_put(b, t, 2) < 0) return -1;
    } else if (n < 65536) {
        if (mp_write_u16(b, 0xda, (uint32_t)n) < 0) return -1;
    } else {
        if (mp_write_u32(b, 0xdb, (uint32_t)n) < 0) return -1;
    }
    return buf_put(b, u, (size_t)n);
}

static int mp_write_long(buf_t *b, PyObject *o) {
    int overflow = 0;
    long long v = PyLong_AsLongLongAndOverflow(o, &overflow);
    if (overflow > 0) {
        /* uint64 range? */
        unsigned long long uv = PyLong_AsUnsignedLongLong(o);
        uint8_t t[9];
        int i;
        if (uv == (unsigned long long)-1 && PyErr_Occurred()) return -1;
        t[0] = 0xcf;
        for (i = 0; i < 8; i++) t[1+i] = (uint8_t)(uv >> (56 - 8*i));
        return buf_put(b, t, 9);
    }
    if (overflow < 0) {
        PyErr_SetString(PyExc_OverflowError, "int out of msgpack range");
        return -1;
    }
    if (v == -1 && PyErr_Occurred()) return -1;
    if (v >= 0) {
        if (v < 0x80) return buf_putc(b, (uint8_t)v);
        if (v < 0x100) {
            uint8_t t[2] = { 0xcc, (uint8_t)v };
            return buf_put(b, t, 2);
        }
        if (v < 0x10000) return mp_write_u16(b, 0xcd, (uint32_t)v);
        if (v < 0x100000000LL) return mp_write_u32(b, 0xce, (uint32_t)v);
        {
            uint8_t t[9];
            int i;
            t[0] = 0xcf;
            for (i = 0; i < 8; i++)
                t[1+i] = (uint8_t)((unsigned long long)v >> (56 - 8*i));
            return buf_put(b, t, 9);
        }
    }
    if (v >= -32) return buf_putc(b, (uint8_t)(0xe0 | (v + 32)));
    if (v >= -128) {
        uint8_t t[2] = { 0xd0, (uint8_t)(int8_t)v };
        return buf_put(b, t, 2);
    }
    if (v >= -32768) return mp_write_u16(b, 0xd1, (uint16_t)(int16_t)v);
    if (v >= -2147483648LL) return mp_write_u32(b, 0xd2, (uint32_t)(int32_t)v);
    {
        uint8_t t[9];
        int i;
        t[0] = 0xd3;
        for (i = 0; i < 8; i++)
            t[1+i] = (uint8_t)((unsigned long long)v >> (56 - 8*i));
        return buf_put(b, t, 9);
    }
}

static int mp_write(buf_t *b, PyObject *o, int depth) {
    if (depth > 100) {
        /* TypeError on purpose: callers catch TypeError and fall back to
         * the Python serializers, which handle deep nesting — raising a
         * different type here would make C-equipped nodes diverge from
         * fallback nodes on client-controlled inputs. */
        PyErr_SetString(PyExc_TypeError,
                        "structure too deep for native fastpath");
        return -1;
    }
    if (o == Py_None) return buf_putc(b, 0xc0);
    if (o == Py_True) return buf_putc(b, 0xc3);
    if (o == Py_False) return buf_putc(b, 0xc2);
    if (PyUnicode_Check(o)) return mp_write_str(b, o);
    if (PyLong_Check(o)) return mp_write_long(b, o);
    if (PyFloat_Check(o)) {
        double v = PyFloat_AS_DOUBLE(o);
        uint64_t bits;
        uint8_t t[9];
        int i;
        memcpy(&bits, &v, 8);
        t[0] = 0xcb;
        for (i = 0; i < 8; i++) t[1+i] = (uint8_t)(bits >> (56 - 8*i));
        return buf_put(b, t, 9);
    }
    if (PyBytes_Check(o)) {
        Py_ssize_t n = PyBytes_GET_SIZE(o);
        if (n < 256) {
            uint8_t t[2] = { 0xc4, (uint8_t)n };
            if (buf_put(b, t, 2) < 0) return -1;
        } else if (n < 65536) {
            if (mp_write_u16(b, 0xc5, (uint32_t)n) < 0) return -1;
        } else {
            if (mp_write_u32(b, 0xc6, (uint32_t)n) < 0) return -1;
        }
        return buf_put(b, PyBytes_AS_STRING(o), (size_t)n);
    }
    if (PyDict_Check(o)) {
        kv_t *items;
        Py_ssize_t n = dict_sorted_items(o, &items), i;
        if (n < 0) return -1;
        if (n < 16) {
            if (buf_putc(b, (uint8_t)(0x80 | n)) < 0) goto fail;
        } else if (n < 65536) {
            if (mp_write_u16(b, 0xde, (uint32_t)n) < 0) goto fail;
        } else {
            if (mp_write_u32(b, 0xdf, (uint32_t)n) < 0) goto fail;
        }
        for (i = 0; i < n; i++) {
            if (mp_write_str(b, items[i].key) < 0 ||
                mp_write(b, items[i].val, depth + 1) < 0)
                goto fail;
        }
        PyMem_Free(items);
        return 0;
    fail:
        PyMem_Free(items);
        return -1;
    }
    if (PyList_Check(o) || PyTuple_Check(o)) {
        Py_ssize_t n = PySequence_Size(o), i;
        if (n < 16) {
            if (buf_putc(b, (uint8_t)(0x90 | n)) < 0) return -1;
        } else if (n < 65536) {
            if (mp_write_u16(b, 0xdc, (uint32_t)n) < 0) return -1;
        } else {
            if (mp_write_u32(b, 0xdd, (uint32_t)n) < 0) return -1;
        }
        for (i = 0; i < n; i++) {
            PyObject *it = PySequence_GetItem(o, i);
            int rc;
            if (!it) return -1;
            rc = mp_write(b, it, depth + 1);
            Py_DECREF(it);
            if (rc < 0) return -1;
        }
        return 0;
    }
    PyErr_Format(PyExc_TypeError,
                 "unsupported type for canonical msgpack: %s",
                 Py_TYPE(o)->tp_name);
    return -1;
}

/* ------------------------------------------------------------------ */
/* strict deep equality                                                */
/* ------------------------------------------------------------------ */

static int deep_eq_impl(PyObject *a, PyObject *b, int depth) {
    /* -1 error, 0 unequal, 1 equal */
    if (depth > 100) {
        /* TypeError on purpose: callers catch TypeError and fall back to
         * the Python serializers, which handle deep nesting — raising a
         * different type here would make C-equipped nodes diverge from
         * fallback nodes on client-controlled inputs. */
        PyErr_SetString(PyExc_TypeError,
                        "structure too deep for native fastpath");
        return -1;
    }
    if (Py_TYPE(a) != Py_TYPE(b)) return 0;
    if (a == b) return 1;
    if (PyDict_Check(a)) {
        Py_ssize_t pos = 0;
        PyObject *k, *v;
        if (PyDict_Size(a) != PyDict_Size(b)) return 0;
        while (PyDict_Next(a, &pos, &k, &v)) {
            PyObject *bv = PyDict_GetItemWithError(b, k);
            int rc;
            if (!bv) return PyErr_Occurred() ? -1 : 0;
            rc = deep_eq_impl(v, bv, depth + 1);
            if (rc != 1) return rc;
        }
        return 1;
    }
    if (PyList_Check(a) || PyTuple_Check(a)) {
        Py_ssize_t n = PySequence_Size(a), i;
        if (n != PySequence_Size(b)) return 0;
        for (i = 0; i < n; i++) {
            PyObject *x = PySequence_GetItem(a, i);
            PyObject *y = PySequence_GetItem(b, i);
            int rc;
            if (!x || !y) { Py_XDECREF(x); Py_XDECREF(y); return -1; }
            rc = deep_eq_impl(x, y, depth + 1);
            Py_DECREF(x); Py_DECREF(y);
            if (rc != 1) return rc;
        }
        return 1;
    }
    return PyObject_RichCompareBool(a, b, Py_EQ);
}

/* ------------------------------------------------------------------ */
/* base58 (bitcoin alphabet)                                           */
/* ------------------------------------------------------------------ */

static const char B58A[] =
    "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz";

static int8_t B58I[256];

static void b58_init_index(void) {
    int i;
    memset(B58I, -1, sizeof B58I);
    for (i = 0; i < 58; i++) B58I[(uint8_t)B58A[i]] = (int8_t)i;
}

/* ------------------------------------------------------------------ */
/* module functions                                                    */
/* ------------------------------------------------------------------ */

static PyObject *py_canonical_json(PyObject *self, PyObject *arg) {
    buf_t b;
    PyObject *out;
    buf_init(&b);
    if (json_write(&b, arg, 0, 0) < 0) { buf_free(&b); return NULL; }
    out = PyBytes_FromStringAndSize((const char *)b.p, (Py_ssize_t)b.len);
    buf_free(&b);
    return out;
}

static PyObject *py_canonical_json_ascii(PyObject *self, PyObject *arg) {
    buf_t b;
    PyObject *out;
    buf_init(&b);
    if (json_write(&b, arg, 0, 1) < 0) { buf_free(&b); return NULL; }
    out = PyBytes_FromStringAndSize((const char *)b.p, (Py_ssize_t)b.len);
    buf_free(&b);
    return out;
}

static PyObject *py_digest_hex(PyObject *self, PyObject *arg) {
    buf_t b;
    sha256_ctx c;
    uint8_t d[32];
    buf_init(&b);
    if (json_write(&b, arg, 0, 0) < 0) { buf_free(&b); return NULL; }
    sha256_init(&c);
    sha256_update(&c, b.p, b.len);
    sha256_final(&c, d);
    buf_free(&b);
    return hex_str(d, 32);
}

static PyObject *py_canonical_msgpack(PyObject *self, PyObject *arg) {
    buf_t b;
    PyObject *out;
    buf_init(&b);
    if (mp_write(&b, arg, 0) < 0) { buf_free(&b); return NULL; }
    out = PyBytes_FromStringAndSize((const char *)b.p, (Py_ssize_t)b.len);
    buf_free(&b);
    return out;
}

static PyObject *py_msgpack_digest_hex(PyObject *self, PyObject *arg) {
    buf_t b;
    sha256_ctx c;
    uint8_t d[32];
    buf_init(&b);
    if (mp_write(&b, arg, 0) < 0) { buf_free(&b); return NULL; }
    sha256_init(&c);
    sha256_update(&c, b.p, b.len);
    sha256_final(&c, d);
    buf_free(&b);
    return hex_str(d, 32);
}

static PyObject *py_deep_eq(PyObject *self, PyObject *args) {
    PyObject *a, *b;
    int rc;
    if (!PyArg_ParseTuple(args, "OO", &a, &b)) return NULL;
    rc = deep_eq_impl(a, b, 0);
    if (rc < 0) return NULL;
    if (rc) Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

static PyObject *py_sha256(PyObject *self, PyObject *arg) {
    Py_buffer view;
    sha256_ctx c;
    uint8_t d[32];
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return NULL;
    sha256_init(&c);
    sha256_update(&c, view.buf, (size_t)view.len);
    sha256_final(&c, d);
    PyBuffer_Release(&view);
    return PyBytes_FromStringAndSize((const char *)d, 32);
}

static PyObject *py_sha256_hex(PyObject *self, PyObject *arg) {
    Py_buffer view;
    sha256_ctx c;
    uint8_t d[32];
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return NULL;
    sha256_init(&c);
    sha256_update(&c, view.buf, (size_t)view.len);
    sha256_final(&c, d);
    PyBuffer_Release(&view);
    return hex_str(d, 32);
}

static PyObject *py_b58encode(PyObject *self, PyObject *arg) {
    Py_buffer view;
    const uint8_t *data;
    size_t n, pad = 0, i, outlen = 0, cap;
    uint8_t *digits;
    PyObject *out;
    char *s;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return NULL;
    data = view.buf;
    n = (size_t)view.len;
    while (pad < n && data[pad] == 0) pad++;
    /* big-base conversion over a byte-digit accumulator:
     * out grows at most ceil(n * 1.366) digits */
    cap = (n - pad) * 137 / 100 + 2;
    digits = PyMem_Malloc(cap);
    if (!digits) { PyBuffer_Release(&view); return PyErr_NoMemory(); }
    for (i = pad; i < n; i++) {
        uint32_t carry = data[i];
        size_t j;
        for (j = 0; j < outlen; j++) {
            uint32_t t = ((uint32_t)digits[j] << 8) + carry;
            digits[j] = (uint8_t)(t % 58);
            carry = t / 58;
        }
        while (carry) {
            digits[outlen++] = (uint8_t)(carry % 58);
            carry /= 58;
        }
    }
    PyBuffer_Release(&view);
    out = PyUnicode_New((Py_ssize_t)(pad + outlen), 127);
    if (!out) { PyMem_Free(digits); return NULL; }
    s = (char *)PyUnicode_DATA(out);
    for (i = 0; i < pad; i++) s[i] = '1';
    for (i = 0; i < outlen; i++)
        s[pad + i] = B58A[digits[outlen - 1 - i]];
    PyMem_Free(digits);
    return out;
}

static PyObject *py_b58decode(PyObject *self, PyObject *arg) {
    const char *s;
    Py_ssize_t n;
    size_t pad = 0, outlen = 0, i, cap;
    uint8_t *bytes_acc;
    PyObject *out, *tmp = NULL;
    if (PyBytes_Check(arg)) {
        s = PyBytes_AS_STRING(arg);
        n = PyBytes_GET_SIZE(arg);
    } else if (PyUnicode_Check(arg)) {
        s = PyUnicode_AsUTF8AndSize(arg, &n);
        if (!s) return NULL;
    } else {
        PyErr_SetString(PyExc_TypeError, "b58decode needs str or bytes");
        return NULL;
    }
    while (pad < (size_t)n && s[pad] == '1') pad++;
    cap = (size_t)n * 733 / 1000 + 2;  /* log(58)/log(256) ~ 0.7326 */
    bytes_acc = PyMem_Malloc(cap);
    if (!bytes_acc) return PyErr_NoMemory();
    for (i = 0; i < (size_t)n; i++) {
        int8_t d = B58I[(uint8_t)s[i]];
        uint32_t carry;
        size_t j;
        if (d < 0) {
            PyMem_Free(bytes_acc);
            PyErr_Format(PyExc_ValueError,
                         "Invalid base58 character: '%c'", s[i]);
            return NULL;
        }
        carry = (uint32_t)d;
        for (j = 0; j < outlen; j++) {
            uint32_t t = (uint32_t)bytes_acc[j] * 58 + carry;
            bytes_acc[j] = (uint8_t)t;
            carry = t >> 8;
        }
        while (carry) {
            bytes_acc[outlen++] = (uint8_t)carry;
            carry >>= 8;
        }
    }
    out = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)(pad + outlen));
    if (out) {
        uint8_t *p = (uint8_t *)PyBytes_AS_STRING(out);
        memset(p, 0, pad);
        for (i = 0; i < outlen; i++)
            p[pad + i] = bytes_acc[outlen - 1 - i];
    }
    PyMem_Free(bytes_acc);
    (void)tmp;
    return out;
}

/* ------------------------------------------------------------------ */
/* client request envelope validation                                  */
/* ------------------------------------------------------------------ */

/* decoded byte length of a base58 string, or -1 on a bad char /
   oversize input. Mirrors Base58Field (fields.py:123) without
   allocating the decoded bytes. */
static int b58_decoded_len(const char *s, Py_ssize_t n) {
    uint8_t acc[64];
    size_t outlen = 0, pad = 0, j;
    Py_ssize_t i;
    if (n > 88) return -1; /* longer than any 64-byte encoding */
    while ((Py_ssize_t)pad < n && s[pad] == '1') pad++;
    for (i = 0; i < n; i++) {
        int8_t d = B58I[(uint8_t)s[i]];
        uint32_t carry;
        if (d < 0) return -1;
        carry = (uint32_t)d;
        for (j = 0; j < outlen; j++) {
            uint32_t t = (uint32_t)acc[j] * 58 + carry;
            acc[j] = (uint8_t)t;
            carry = t >> 8;
        }
        while (carry) {
            if (outlen >= sizeof acc) return -1;
            acc[outlen++] = (uint8_t)carry;
            carry >>= 8;
        }
    }
    return (int)(pad + outlen);
}

/* identifier: str whose b58 decoding is 16 or 32 bytes */
static int valid_identifier(PyObject *o) {
    const char *s;
    Py_ssize_t n;
    int len;
    if (!PyUnicode_Check(o)) return 0;
    s = PyUnicode_AsUTF8AndSize(o, &n);
    if (!s) { PyErr_Clear(); return 0; }
    len = b58_decoded_len(s, n);
    return len == 16 || len == 32;
}

/* signature: non-empty str of at most 512 chars (SignatureField) */
static int valid_signature(PyObject *o) {
    if (!PyUnicode_Check(o)) return 0;
    return PyUnicode_GET_LENGTH(o) > 0 && PyUnicode_GET_LENGTH(o) <= 512;
}

static int nonneg_int(PyObject *o) {
    int overflow;
    long long v;
    if (!PyLong_Check(o) || PyBool_Check(o)) return 0;
    v = PyLong_AsLongLongAndOverflow(o, &overflow);
    if (overflow > 0) return 1;   /* huge positive is still non-negative */
    if (overflow < 0) return 0;
    return v >= 0;
}

/* validate_client_request(dct, protocol_version) ->
     None : envelope definitely valid (the overwhelmingly common case)
     True : not provably valid here -- run the Python validator, which
            either passes or raises with its exact error message.
   Mirrors ClientMessageValidator.validate + _validate_taa
   (common/messages/client_request.py); never produces error text, so
   clients always see the Python path's messages. */
static PyObject *py_validate_client_request(PyObject *self, PyObject *args) {
    PyObject *dct, *op, *idr, *req_id, *sig, *sigs, *pv, *taa;
    long protocol_version;
    if (!PyArg_ParseTuple(args, "Ol", &dct, &protocol_version))
        return NULL;
    if (!PyDict_Check(dct)) Py_RETURN_TRUE;
    idr = PyDict_GetItemString(dct, "identifier");
    req_id = PyDict_GetItemString(dct, "reqId");
    op = PyDict_GetItemString(dct, "operation");
    if (!op || !PyDict_Check(op)) Py_RETURN_TRUE;
    if (!PyDict_GetItemString(op, "type")) Py_RETURN_TRUE;
    if (!req_id || !nonneg_int(req_id)) Py_RETURN_TRUE;
    sigs = PyDict_GetItemString(dct, "signatures");
    if (sigs == Py_None) sigs = NULL;
    if (idr == Py_None) idr = NULL;
    if (!idr && !sigs) Py_RETURN_TRUE;
    if (idr && !valid_identifier(idr)) Py_RETURN_TRUE;
    if (sigs) {
        PyObject *k, *v;
        Py_ssize_t pos = 0;
        if (!PyDict_Check(sigs) || PyDict_GET_SIZE(sigs) == 0)
            Py_RETURN_TRUE;
        while (PyDict_Next(sigs, &pos, &k, &v)) {
            if (!valid_identifier(k) || !valid_signature(v))
                Py_RETURN_TRUE;
        }
    }
    sig = PyDict_GetItemString(dct, "signature");
    if (sig && sig != Py_None && !valid_signature(sig)) Py_RETURN_TRUE;
    pv = PyDict_GetItemString(dct, "protocolVersion");
    if (pv && pv != Py_None) {
        long got;
        if (!PyLong_Check(pv) || PyBool_Check(pv)) Py_RETURN_TRUE;
        got = PyLong_AsLong(pv);
        if (PyErr_Occurred()) { PyErr_Clear(); Py_RETURN_TRUE; }
        if (got != protocol_version) Py_RETURN_TRUE;
    }
    taa = PyDict_GetItemString(dct, "taaAcceptance");
    if (taa && taa != Py_None) {
        PyObject *v;
        Py_ssize_t i, tn;
        const char *ds;
        if (!PyDict_Check(taa)) Py_RETURN_TRUE;
        v = PyDict_GetItemString(taa, "taaDigest");
        if (!v || !PyUnicode_Check(v)) Py_RETURN_TRUE;
        ds = PyUnicode_AsUTF8AndSize(v, &tn);
        if (!ds) { PyErr_Clear(); Py_RETURN_TRUE; }
        if (tn != 64) Py_RETURN_TRUE;
        for (i = 0; i < tn; i++) {
            char c = ds[i];
            if (!((c >= '0' && c <= '9') || (c >= 'a' && c <= 'f')
                  || (c >= 'A' && c <= 'F')))
                Py_RETURN_TRUE;
        }
        v = PyDict_GetItemString(taa, "mechanism");
        if (!v || !PyUnicode_Check(v) || PyUnicode_GET_LENGTH(v) == 0
            || PyUnicode_GET_LENGTH(v) > 256)
            Py_RETURN_TRUE;
        v = PyDict_GetItemString(taa, "time");
        if (!v || !nonneg_int(v)) Py_RETURN_TRUE;
    }
    Py_RETURN_NONE;
}

/* one JSON `"key":value` pair (comma-prefixed unless first) */
static int put_kv_json(buf_t *b, const char *key, PyObject *val,
                       int *first) {
    if (!*first && buf_putc(b, ',') < 0) return -1;
    *first = 0;
    if (buf_putc(b, '"') < 0) return -1;
    if (buf_put(b, key, strlen(key)) < 0) return -1;
    if (buf_put(b, "\":", 2) < 0) return -1;
    return json_write(b, val, 1, 0);
}

/* request_intake(dct, protocol_version) ->
     None                       : envelope not provably valid (use the
                                  Python validate + digest path)
     (digest_hex, payload_digest_hex, signing_bytes)
   One boundary crossing for the whole client-request intake prep:
   envelope validation + the two canonical-JSON digests + the signing
   bytes. Byte-identical to Request.getDigest / getPayloadDigest /
   serialize_msg_for_signing(signingPayloadState()) — the payload JSON
   IS the signing bytes, so payload_digest is its sha256. */
static PyObject *py_request_intake(PyObject *self, PyObject *args) {
    PyObject *dct, *valid, *vargs;
    PyObject *idr, *req_id, *op, *pv, *taa, *end_, *sig, *sigs;
    buf_t pb, db;
    sha256_ctx c;
    uint8_t md[32];
    PyObject *dig = NULL, *pdig = NULL, *ser = NULL, *out = NULL;
    PyObject *pv_default = NULL;
    int first;
    long protocol_version;
    if (!PyArg_ParseTuple(args, "Ol", &dct, &protocol_version))
        return NULL;
    /* reuse the validator: only a provably valid envelope proceeds */
    vargs = Py_BuildValue("(Ol)", dct, protocol_version);
    if (!vargs) return NULL;
    valid = py_validate_client_request(self, vargs);
    Py_DECREF(vargs);
    if (!valid) return NULL;
    if (valid != Py_None) { Py_DECREF(valid); Py_RETURN_NONE; }
    Py_DECREF(valid);
    idr = PyDict_GetItemString(dct, "identifier");
    req_id = PyDict_GetItemString(dct, "reqId");
    op = PyDict_GetItemString(dct, "operation");
    pv = PyDict_GetItemString(dct, "protocolVersion");
    taa = PyDict_GetItemString(dct, "taaAcceptance");
    end_ = PyDict_GetItemString(dct, "endorser");
    sig = PyDict_GetItemString(dct, "signature");
    sigs = PyDict_GetItemString(dct, "signatures");
    if (!idr) idr = Py_None;
    if (!pv) {
        /* ABSENT key defaults to the current protocol version
           (Request.from_dict d.get('protocolVersion', CURRENT));
           an explicit None stays omitted from the payload */
        pv_default = PyLong_FromLong(protocol_version);
        if (!pv_default) return NULL;
        pv = pv_default;
    }
    /* payload JSON == signing bytes (sorted keys; identifier/operation/
       reqId always present, optionals only when non-None) */
    buf_init(&pb);
    first = 1;
    if (buf_putc(&pb, '{') < 0) goto fail;
    if (end_ && end_ != Py_None
        && put_kv_json(&pb, "endorser", end_, &first) < 0) goto fail;
    if (put_kv_json(&pb, "identifier", idr, &first) < 0) goto fail;
    if (put_kv_json(&pb, "operation", op, &first) < 0) goto fail;
    if (pv && pv != Py_None
        && put_kv_json(&pb, "protocolVersion", pv, &first) < 0) goto fail;
    if (put_kv_json(&pb, "reqId", req_id, &first) < 0) goto fail;
    if (taa && taa != Py_None
        && put_kv_json(&pb, "taaAcceptance", taa, &first) < 0) goto fail;
    if (buf_putc(&pb, '}') < 0) goto fail;
    /* digest JSON: payload keys + signature(s), still sorted */
    buf_init(&db);
    first = 1;
    if (buf_putc(&db, '{') < 0) goto fail2;
    if (end_ && end_ != Py_None
        && put_kv_json(&db, "endorser", end_, &first) < 0) goto fail2;
    if (put_kv_json(&db, "identifier", idr, &first) < 0) goto fail2;
    if (put_kv_json(&db, "operation", op, &first) < 0) goto fail2;
    if (pv && pv != Py_None
        && put_kv_json(&db, "protocolVersion", pv, &first) < 0) goto fail2;
    if (put_kv_json(&db, "reqId", req_id, &first) < 0) goto fail2;
    if (sig && sig != Py_None
        && put_kv_json(&db, "signature", sig, &first) < 0) goto fail2;
    if (sigs && sigs != Py_None
        && put_kv_json(&db, "signatures", sigs, &first) < 0) goto fail2;
    if (taa && taa != Py_None
        && put_kv_json(&db, "taaAcceptance", taa, &first) < 0) goto fail2;
    if (buf_putc(&db, '}') < 0) goto fail2;
    sha256_init(&c);
    sha256_update(&c, db.p, db.len);
    sha256_final(&c, md);
    dig = hex_str(md, 32);
    sha256_init(&c);
    sha256_update(&c, pb.p, pb.len);
    sha256_final(&c, md);
    pdig = hex_str(md, 32);
    ser = PyBytes_FromStringAndSize((const char *)pb.p,
                                    (Py_ssize_t)pb.len);
    if (dig && pdig && ser)
        out = PyTuple_Pack(3, dig, pdig, ser);
    Py_XDECREF(dig); Py_XDECREF(pdig); Py_XDECREF(ser);
    Py_XDECREF(pv_default);
    buf_free(&db);
    buf_free(&pb);
    return out;
fail2:
    buf_free(&db);
fail:
    Py_XDECREF(pv_default);
    buf_free(&pb);
    return NULL;
}

static PyMethodDef methods[] = {
    {"validate_client_request", py_validate_client_request, METH_VARARGS,
     "client request envelope check -> None | error str | True"},
    {"request_intake", py_request_intake, METH_VARARGS,
     "validate + digest pair + signing bytes in one pass -> "
     "None | (digest_hex, payload_digest_hex, signing_bytes)"},
    {"canonical_json", py_canonical_json, METH_O,
     "json.dumps(x, sort_keys=True, separators=(',',':'),"
     " ensure_ascii=False).encode() in one C pass"},
    {"canonical_json_ascii", py_canonical_json_ascii, METH_O,
     "json.dumps(x, sort_keys=True, separators=(',',':')).encode()"
     " (ensure_ascii=True) in one C pass"},
    {"digest_hex", py_digest_hex, METH_O,
     "sha256(canonical_json(x)).hexdigest()"},
    {"canonical_msgpack", py_canonical_msgpack, METH_O,
     "msgpack.packb(_sort_deep(x), use_bin_type=True) in one C pass"},
    {"msgpack_digest_hex", py_msgpack_digest_hex, METH_O,
     "sha256(canonical_msgpack(x)).hexdigest()"},
    {"deep_eq", py_deep_eq, METH_VARARGS,
     "type-strict deep equality (serializer-faithful)"},
    {"sha256", py_sha256, METH_O, "sha256 digest bytes"},
    {"sha256_hex", py_sha256_hex, METH_O, "sha256 hexdigest str"},
    {"b58encode", py_b58encode, METH_O, "base58 encode -> str"},
    {"b58decode", py_b58decode, METH_O, "base58 decode -> bytes"},
    {NULL, NULL, 0, NULL}
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "fastpath",
    "native hot-path helpers (canonical serialization, digests, base58)",
    -1, methods
};

PyMODINIT_FUNC PyInit_fastpath(void) {
    b58_init_index();
    return PyModule_Create(&moduledef);
}
