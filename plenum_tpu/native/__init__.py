"""Native (C) components — compiled on first use with the system
compiler, cached next to the source. The framework's answer to the
reference's native library bindings (SURVEY.md §2.9): where indy-plenum
links libsodium/ursa/rocksdb, this package carries its own C sources.
"""
import ctypes
import logging
import os
import subprocess
import sysconfig

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))


def build_and_load(name: str) -> ctypes.CDLL:
    """Compile `<name>.c` into `<name>.so` (if stale) and dlopen it.

    The compile targets a pid-unique temp file that is os.rename()d into
    place, so concurrent processes never dlopen a half-written library."""
    so = _build(name, [])
    return ctypes.CDLL(so)


def build_and_import(name: str):
    """Compile `<name>.c` as a CPython EXTENSION module (Python.h) and
    import it — for native code that builds Python objects directly
    (the RLP codec) rather than crossing a ctypes ABI. The cached .so
    carries the interpreter's ABI tag so a Python upgrade rebuilds
    instead of dlopening a stale wrong-ABI binary."""
    import importlib.machinery
    import importlib.util
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    so = _build(name, ["-I", sysconfig.get_paths()["include"]],
                suffix=suffix)
    loader = importlib.machinery.ExtensionFileLoader(name, so)
    spec = importlib.util.spec_from_file_location(name, so, loader=loader)
    mod = importlib.util.module_from_spec(spec)
    loader.exec_module(mod)
    return mod


def _build(name: str, extra_flags, suffix: str = ".so") -> str:
    src = os.path.join(_DIR, name + ".c")
    so = os.path.join(_DIR, name + suffix)
    if (not os.path.exists(so)
            or os.path.getmtime(so) < os.path.getmtime(src)):
        cc = os.environ.get("CC", "cc")
        tmp = "%s.%d.tmp" % (so, os.getpid())
        # plain -O3: measured FASTER than -march=native here — the
        # auto-vectorizer pessimizes the 64x64->128 carry chains
        cmd = [cc, "-O3", "-shared", "-fPIC", "-std=c11"] + \
            list(extra_flags) + ["-o", tmp, src]
        logger.info("building native module: %s", " ".join(cmd))
        try:
            subprocess.run(cmd, check=True, capture_output=True)
            os.rename(tmp, so)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    return so


_loaded = {}


def load_ext(name: str):
    """build_and_import with a process-wide cache, so every consumer of a
    shared extension (fastpath is imported by serializers, request,
    propagator, base58) gets the same module object and the stale-check
    runs once."""
    mod = _loaded.get(name)
    if mod is None:
        mod = _loaded[name] = build_and_import(name)
    return mod


def try_load_ext(name: str):
    """load_ext, or None when no compiler / build failure — the standard
    guard for optional native fast paths (callers fall back to their
    Python implementation). Central so a future kill-switch or build
    diagnostics change lands in one place."""
    if os.environ.get("PLENUM_TPU_NO_NATIVE"):
        return None
    try:
        return load_ext(name)
    except Exception:                  # pragma: no cover - cc missing
        logger.info("native module %s unavailable; using Python fallback",
                    name, exc_info=True)
        return None
