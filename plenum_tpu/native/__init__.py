"""Native (C) components — compiled on first use with the system
compiler, cached next to the source. The framework's answer to the
reference's native library bindings (SURVEY.md §2.9): where indy-plenum
links libsodium/ursa/rocksdb, this package carries its own C sources.
"""
import ctypes
import logging
import os
import subprocess
import sysconfig

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))


def build_and_load(name: str) -> ctypes.CDLL:
    """Compile `<name>.c` into `<name>.so` (if stale) and dlopen it.

    The compile targets a pid-unique temp file that is os.rename()d into
    place, so concurrent processes never dlopen a half-written library."""
    src = os.path.join(_DIR, name + ".c")
    so = os.path.join(_DIR, name + ".so")
    if (not os.path.exists(so)
            or os.path.getmtime(so) < os.path.getmtime(src)):
        cc = os.environ.get("CC", "cc")
        tmp = "%s.%d.tmp" % (so, os.getpid())
        # plain -O3: measured FASTER than -march=native here — the
        # auto-vectorizer pessimizes the 64x64->128 carry chains
        cmd = [cc, "-O3", "-shared", "-fPIC", "-std=c11", "-o", tmp, src]
        logger.info("building native module: %s", " ".join(cmd))
        try:
            subprocess.run(cmd, check=True, capture_output=True)
            os.rename(tmp, so)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    return ctypes.CDLL(so)
