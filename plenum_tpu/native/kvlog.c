/* kvlog — log-structured KV engine (the framework's RocksDB-lite).
 *
 * Fills the native-storage role the reference delegates to RocksDB/
 * LevelDB (storage/kv_store_rocksdb.py:15): values live ON DISK; only
 * a compact open-addressing index (key bytes + value offset/length)
 * stays in memory. On-disk format is IDENTICAL to the pure-Python
 * KeyValueStorageFile (.kvlog):
 *
 *   record  = [klen u32 LE][vlen u32 LE][key][value]
 *   delete  = [klen u32 LE][0xFFFFFFFF][key]
 *   batch   = [0xFFFFFFFE u32][body u32 LE][records...]
 *
 * so the two backends open each other's files. Crash safety: a torn
 * tail (or torn batch body) is truncated on open. Compaction rewrites
 * live records to <path>.compact and renames it into place.
 *
 * Exported API (ctypes, see storage/kv_native.py):
 *   kv_open/kv_close/kv_flush
 *   kv_put/kv_get/kv_remove  (get copies into caller buffer; returns
 *                             needed length so callers can retry)
 *   kv_batch_begin/kv_batch_end  (frames puts/removes atomically)
 *   kv_count / kv_keys_size / kv_keys  (index snapshot for iteration)
 *   kv_compact
 */
#define _POSIX_C_SOURCE 200809L  /* fileno, ftruncate, strdup under c11 */
#include <errno.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

#define TOMBSTONE 0xFFFFFFFFu
#define BATCH_MARK 0xFFFFFFFEu

typedef struct {
    uint8_t *key;        /* arena pointer */
    uint32_t klen;
    uint64_t voff;       /* value offset in file */
    uint32_t vlen;
    uint8_t used;        /* 0 empty, 1 used, 2 deleted slot */
} slot_t;

typedef struct kvdb {
    FILE *f;             /* append handle */
    FILE *rf;            /* persistent read handle */
    char *path;
    slot_t *slots;
    uint64_t cap;        /* power of two */
    uint64_t count;      /* live keys */
    uint64_t tomb;       /* deleted slots */
    uint64_t file_size;  /* logical end of valid log */
    uint64_t garbage;    /* bytes of dead records (for compaction) */
    /* batch state */
    uint8_t *batch_buf;
    uint64_t batch_len, batch_cap;
    int in_batch;
} kvdb;

static uint64_t fnv1a(const uint8_t *p, uint32_t n) {
    uint64_t h = 1469598103934665603ULL;
    for (uint32_t i = 0; i < n; i++) { h ^= p[i]; h *= 1099511628211ULL; }
    return h;
}

static int grow(kvdb *db);

static slot_t *find_slot(kvdb *db, const uint8_t *key, uint32_t klen,
                         int for_insert) {
    uint64_t mask = db->cap - 1;
    uint64_t i = fnv1a(key, klen) & mask;
    slot_t *first_tomb = NULL;
    for (;;) {
        slot_t *s = &db->slots[i];
        if (s->used == 0)
            return (for_insert && first_tomb) ? first_tomb : s;
        if (s->used == 2) {
            if (for_insert && !first_tomb) first_tomb = s;
        } else if (s->klen == klen && memcmp(s->key, key, klen) == 0) {
            return s;
        }
        i = (i + 1) & mask;
    }
}

static int index_put(kvdb *db, const uint8_t *key, uint32_t klen,
                     uint64_t voff, uint32_t vlen) {
    if ((db->count + db->tomb + 1) * 4 >= db->cap * 3)
        if (grow(db) != 0) return -1;
    slot_t *s = find_slot(db, key, klen, 1);
    if (s->used == 1) {
        db->garbage += 8 + s->klen + s->vlen;  /* old record now dead */
        s->voff = voff; s->vlen = vlen;
        return 0;
    }
    uint8_t *copy = malloc(klen ? klen : 1);
    if (!copy) return -1;
    memcpy(copy, key, klen);
    if (s->used == 2) db->tomb--;
    s->key = copy; s->klen = klen; s->voff = voff; s->vlen = vlen;
    s->used = 1;
    db->count++;
    return 0;
}

static void index_del(kvdb *db, const uint8_t *key, uint32_t klen) {
    slot_t *s = find_slot(db, key, klen, 0);
    if (s->used == 1) {
        db->garbage += 8 + s->klen + s->vlen + 8 + klen; /* rec + tomb */
        free(s->key);
        s->key = NULL; s->used = 2;
        db->count--; db->tomb++;
    }
}

static int grow(kvdb *db) {
    uint64_t old_cap = db->cap;
    slot_t *old = db->slots;
    uint64_t ncap = db->cap * 2;
    slot_t *ns = calloc(ncap, sizeof(slot_t));
    if (!ns) return -1;
    db->slots = ns; db->cap = ncap; db->tomb = 0;
    for (uint64_t i = 0; i < old_cap; i++) {
        if (old[i].used == 1) {
            slot_t *s = find_slot(db, old[i].key, old[i].klen, 1);
            *s = old[i];
        }
    }
    free(old);
    return 0;
}

static uint32_t rd_u32(const uint8_t *p) {
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8)
         | ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
}

static void wr_u32(uint8_t *p, uint32_t v) {
    p[0] = v & 0xFF; p[1] = (v >> 8) & 0xFF;
    p[2] = (v >> 16) & 0xFF; p[3] = (v >> 24) & 0xFF;
}

/* apply records in data[lo, hi); base = file offset of data[0] */
static void apply_records(kvdb *db, const uint8_t *data, uint64_t lo,
                          uint64_t hi, uint64_t base) {
    uint64_t pos = lo;
    while (pos + 8 <= hi) {
        uint32_t klen = rd_u32(data + pos);
        uint32_t vlen = rd_u32(data + pos + 4);
        if (vlen == TOMBSTONE) {
            if (pos + 8 + klen > hi) break;
            index_del(db, data + pos + 8, klen);
            pos += 8 + klen;
        } else {
            if (pos + 8 + (uint64_t)klen + vlen > hi) break;
            index_put(db, data + pos + 8, klen,
                      base + pos + 8 + klen, vlen);
            pos += 8 + (uint64_t)klen + vlen;
        }
    }
}

kvdb *kv_open(const char *path) {
    kvdb *db = calloc(1, sizeof(kvdb));
    if (!db) return NULL;
    db->cap = 1024;
    db->slots = calloc(db->cap, sizeof(slot_t));
    db->path = strdup(path);
    if (!db->slots || !db->path) goto fail;

    FILE *rf = fopen(path, "rb");
    uint64_t valid_end = 0;
    if (rf) {
        fseek(rf, 0, SEEK_END);
        long sz = ftell(rf);
        fseek(rf, 0, SEEK_SET);
        uint8_t *data = malloc(sz > 0 ? (size_t)sz : 1);
        if (!data) { fclose(rf); goto fail; }
        if (sz > 0 && fread(data, 1, (size_t)sz, rf) != (size_t)sz) {
            free(data); fclose(rf); goto fail;
        }
        fclose(rf);
        uint64_t pos = 0, n = (uint64_t)sz;
        while (pos + 8 <= n) {
            uint32_t klen = rd_u32(data + pos);
            uint32_t vlen = rd_u32(data + pos + 4);
            if (klen == BATCH_MARK) {
                if (pos + 8 + vlen > n) break;          /* torn batch */
                apply_records(db, data, pos + 8, pos + 8 + vlen, 0);
                pos += 8 + vlen;
            } else {
                uint64_t body = klen +
                    (vlen == TOMBSTONE ? 0 : (uint64_t)vlen);
                if (pos + 8 + body > n) break;          /* torn tail */
                apply_records(db, data, pos, pos + 8 + body, 0);
                pos += 8 + body;
            }
            valid_end = pos;
        }
        free(data);
        if (valid_end < n) {  /* drop the torn tail */
            FILE *tf = fopen(path, "rb+");
            if (tf) {
                int fd = fileno(tf);
                if (ftruncate(fd, (long)valid_end) != 0) { /* best effort */ }
                fclose(tf);
            }
        }
    }
    db->file_size = valid_end;
    db->f = fopen(path, "ab+");
    if (!db->f) goto fail;
    db->rf = fopen(path, "rb");
    if (!db->rf) { fclose(db->f); goto fail; }
    return db;
fail:
    if (db) { free(db->slots); free(db->path); free(db); }
    return NULL;
}

void kv_flush(kvdb *db) { if (db->f) fflush(db->f); }

void kv_close(kvdb *db) {
    if (!db) return;
    if (db->f) fclose(db->f);
    if (db->rf) fclose(db->rf);
    for (uint64_t i = 0; i < db->cap; i++)
        if (db->slots[i].used == 1) free(db->slots[i].key);
    free(db->slots);
    free(db->batch_buf);
    free(db->path);
    free(db);
}

static int emit(kvdb *db, const uint8_t *rec, uint64_t len) {
    if (db->in_batch) {
        if (db->batch_len + len > db->batch_cap) {
            uint64_t ncap = db->batch_cap ? db->batch_cap * 2 : 4096;
            while (ncap < db->batch_len + len) ncap *= 2;
            uint8_t *nb = realloc(db->batch_buf, ncap);
            if (!nb) return -1;
            db->batch_buf = nb; db->batch_cap = ncap;
        }
        memcpy(db->batch_buf + db->batch_len, rec, len);
        db->batch_len += len;
        return 0;
    }
    if (fwrite(rec, 1, len, db->f) != len) return -1;
    return 0;
}

int kv_put(kvdb *db, const uint8_t *key, uint32_t klen,
           const uint8_t *val, uint32_t vlen) {
    if (vlen >= BATCH_MARK) return -1;
    uint8_t hdr[8];
    wr_u32(hdr, klen); wr_u32(hdr + 4, vlen);
    /* value offset once the record lands in the file */
    uint64_t voff;
    if (db->in_batch) {
        /* position = file_size + 8 (batch hdr) + batch_len + 8 + klen */
        voff = db->file_size + 8 + db->batch_len + 8 + klen;
    } else {
        voff = db->file_size + 8 + klen;
    }
    if (emit(db, hdr, 8) != 0) return -1;
    if (emit(db, key, klen) != 0) return -1;
    if (emit(db, val, vlen) != 0) return -1;
    if (!db->in_batch) {
        db->file_size += 8 + (uint64_t)klen + vlen;
        fflush(db->f);  /* durability-on-return, like the Python backend */
    }
    return index_put(db, key, klen, voff, vlen);
}

int kv_remove(kvdb *db, const uint8_t *key, uint32_t klen) {
    /* removing an absent key is a no-op (matches the Python backend);
     * appending a tombstone for it would grow the log with bytes the
     * garbage counter never sees */
    if (!db->in_batch) {
        slot_t *s = find_slot(db, key, klen, 0);
        if (s->used != 1) return 0;
    }
    uint8_t hdr[8];
    wr_u32(hdr, klen); wr_u32(hdr + 4, TOMBSTONE);
    if (emit(db, hdr, 8) != 0) return -1;
    if (emit(db, key, klen) != 0) return -1;
    if (!db->in_batch) {
        db->file_size += 8 + klen;
        fflush(db->f);
    }
    index_del(db, key, klen);
    return 0;
}

/* → value length, copied into buf up to cap; -1 if absent */
long kv_get(kvdb *db, const uint8_t *key, uint32_t klen,
            uint8_t *buf, uint64_t cap) {
    slot_t *s = find_slot(db, key, klen, 0);
    if (s->used != 1) return -1;
    if (s->vlen <= cap && s->vlen > 0) {
        if (fseek(db->rf, (long)s->voff, SEEK_SET) != 0 ||
            fread(buf, 1, s->vlen, db->rf) != s->vlen)
            return -2;
    }
    return (long)s->vlen;
}

int kv_batch_begin(kvdb *db) {
    if (db->in_batch) return -1;
    db->in_batch = 1;
    db->batch_len = 0;
    return 0;
}

int kv_batch_end(kvdb *db) {
    if (!db->in_batch) return -1;
    db->in_batch = 0;
    uint8_t hdr[8];
    wr_u32(hdr, BATCH_MARK);
    wr_u32(hdr + 4, (uint32_t)db->batch_len);
    if (fwrite(hdr, 1, 8, db->f) != 8) return -1;
    if (db->batch_len &&
        fwrite(db->batch_buf, 1, db->batch_len, db->f) != db->batch_len)
        return -1;
    fflush(db->f);
    db->file_size += 8 + db->batch_len;
    return 0;
}

/* apply a pre-packed buffer of records (same wire format) as ONE
 * atomic batch frame: a single FFI call for the whole batch */
int kv_apply_packed(kvdb *db, const uint8_t *buf, uint64_t len) {
    if (db->in_batch) return -1;
    uint8_t hdr[8];
    wr_u32(hdr, BATCH_MARK);
    wr_u32(hdr + 4, (uint32_t)len);
    if (fwrite(hdr, 1, 8, db->f) != 8) return -1;
    if (len && fwrite(buf, 1, len, db->f) != len) return -1;
    fflush(db->f);  /* one flush per batch */
    /* index: records start at file_size + 8 */
    apply_records(db, buf, 0, len, db->file_size + 8);
    db->file_size += 8 + len;
    return 0;
}

uint64_t kv_count(kvdb *db) { return db->count; }

uint64_t kv_garbage(kvdb *db) { return db->garbage; }

/* size of the concatenated [klen u32][key] snapshot */
uint64_t kv_keys_size(kvdb *db) {
    uint64_t total = 0;
    for (uint64_t i = 0; i < db->cap; i++)
        if (db->slots[i].used == 1) total += 4 + db->slots[i].klen;
    return total;
}

void kv_keys(kvdb *db, uint8_t *buf) {
    uint64_t pos = 0;
    for (uint64_t i = 0; i < db->cap; i++) {
        slot_t *s = &db->slots[i];
        if (s->used != 1) continue;
        wr_u32(buf + pos, s->klen);
        memcpy(buf + pos + 4, s->key, s->klen);
        pos += 4 + s->klen;
    }
}

/* rewrite live records into <path>.compact, swap in, reopen */
int kv_compact(kvdb *db) {
    fflush(db->f);
    size_t plen = strlen(db->path);
    char *tmp = malloc(plen + 9);
    if (!tmp) return -1;
    memcpy(tmp, db->path, plen);
    memcpy(tmp + plen, ".compact", 9);
    FILE *out = fopen(tmp, "wb");
    FILE *in = fopen(db->path, "rb");
    if (!out || !in) {
        if (out) fclose(out);
        if (in) fclose(in);
        free(tmp);
        return -1;
    }
    uint64_t written = 0;
    uint8_t hdr[8];
    int ok = 1;
    uint8_t *vbuf = NULL;
    uint64_t vcap = 0;
    /* new offsets are applied to the index only AFTER the rename
     * succeeds — a failed swap must leave the old offsets valid */
    uint64_t *new_offs = calloc(db->cap, sizeof(uint64_t));
    if (!new_offs) { fclose(in); fclose(out); remove(tmp); free(tmp);
                     return -1; }
    for (uint64_t i = 0; ok && i < db->cap; i++) {
        slot_t *s = &db->slots[i];
        if (s->used != 1) continue;
        if (s->vlen > vcap) {
            uint8_t *nb = realloc(vbuf, s->vlen);
            if (!nb) { ok = 0; break; }
            vbuf = nb; vcap = s->vlen;
        }
        if (s->vlen > 0 &&
            (fseek(in, (long)s->voff, SEEK_SET) != 0 ||
             fread(vbuf, 1, s->vlen, in) != s->vlen)) { ok = 0; break; }
        wr_u32(hdr, s->klen); wr_u32(hdr + 4, s->vlen);
        if (fwrite(hdr, 1, 8, out) != 8 ||
            fwrite(s->key, 1, s->klen, out) != s->klen ||
            (s->vlen && fwrite(vbuf, 1, s->vlen, out) != s->vlen)) {
            ok = 0; break;
        }
        new_offs[i] = written + 8 + s->klen;
        written += 8 + (uint64_t)s->klen + s->vlen;
    }
    free(vbuf);
    fclose(in);
    if (fflush(out) != 0) ok = 0;
    fclose(out);
    if (!ok) { remove(tmp); free(tmp); free(new_offs); return -1; }
    fclose(db->f);
    fclose(db->rf);
    db->f = NULL;
    db->rf = NULL;
    if (rename(tmp, db->path) != 0) {
        /* failed swap: reopen the ORIGINAL log so the store stays
         * usable (old index offsets are untouched and still valid) */
        remove(tmp);
        free(tmp);
        free(new_offs);
        db->f = fopen(db->path, "ab+");
        db->rf = fopen(db->path, "rb");
        return -1;
    }
    free(tmp);
    for (uint64_t i = 0; i < db->cap; i++)
        if (db->slots[i].used == 1)
            db->slots[i].voff = new_offs[i];
    free(new_offs);
    db->f = fopen(db->path, "ab+");
    db->rf = fopen(db->path, "rb");
    db->file_size = written;
    db->garbage = 0;
    return (db->f && db->rf) ? 0 : -1;
}
