/* CPython extension: RLP encode/decode for MPT trie nodes.
 *
 * Exactly the dialect of plenum_tpu/state/rlp.py (which remains the
 * reference implementation and fallback): items are bytes or nested
 * lists of items; canonicality is enforced on decode (no non-canonical
 * single bytes, no leading zeros in lengths, long forms only for
 * payloads >= 56). The trie walks call this on every node load/persist
 * — the hottest serialization path in the state layer (the reference
 * leans on C via its rlp/leveldb dependencies; SURVEY.md §2.9).
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>

/* ------------------------------------------------------------ encode */

/* growable output buffer */
typedef struct {
    char *buf;
    Py_ssize_t len, cap;
} Out;

static int out_reserve(Out *o, Py_ssize_t extra)
{
    if (o->len + extra <= o->cap)
        return 0;
    Py_ssize_t cap = o->cap ? o->cap : 256;
    while (cap < o->len + extra)
        cap *= 2;
    char *nb = PyMem_Realloc(o->buf, cap);
    if (!nb) {
        PyErr_NoMemory();
        return -1;
    }
    o->buf = nb;
    o->cap = cap;
    return 0;
}

static int out_put(Out *o, const char *data, Py_ssize_t n)
{
    if (out_reserve(o, n) < 0)
        return -1;
    memcpy(o->buf + o->len, data, n);
    o->len += n;
    return 0;
}

static int out_byte(Out *o, unsigned char b)
{
    return out_put(o, (const char *)&b, 1);
}

static int put_length(Out *o, Py_ssize_t n, unsigned char offset)
{
    if (n < 56)
        return out_byte(o, (unsigned char)(offset + n));
    unsigned char tmp[9];
    int nb = 0;
    Py_ssize_t v = n;
    while (v) {
        tmp[8 - nb] = (unsigned char)(v & 0xFF);
        v >>= 8;
        nb++;
    }
    if (out_byte(o, (unsigned char)(offset + 55 + nb)) < 0)
        return -1;
    return out_put(o, (const char *)(tmp + 9 - nb), nb);
}

#define RLP_MAX_DEPTH 64   /* LIST nesting bound; MUST equal rlp.py's
                            * MAX_DEPTH (backends must agree on what is
                            * encodable/decodable) */

static int encode_item(Out *o, PyObject *item, int depth)
{
    if (PyBytes_CheckExact(item)) {
        Py_ssize_t n = PyBytes_GET_SIZE(item);
        const char *p = PyBytes_AS_STRING(item);
        if (n == 1 && (unsigned char)p[0] < 0x80)
            return out_put(o, p, 1);
        if (put_length(o, n, 0x80) < 0)
            return -1;
        return out_put(o, p, n);
    }
    if (PyList_CheckExact(item) || PyTuple_CheckExact(item)) {
        if (depth >= RLP_MAX_DEPTH) {
            PyErr_SetString(PyExc_ValueError, "RLP nesting too deep");
            return -1;
        }
        /* encode children into a scratch buffer, then prefix.
         * Re-fetch size/item each iteration and hold a strong ref:
         * encoding a subclass child runs arbitrary Python code that
         * may mutate (realloc) the parent list under us. */
        Out body = {NULL, 0, 0};
        for (Py_ssize_t i = 0; i < PySequence_Fast_GET_SIZE(item); i++) {
            PyObject *kid = PySequence_Fast_GET_ITEM(item, i);
            Py_INCREF(kid);
            int rc = encode_item(&body, kid, depth + 1);
            Py_DECREF(kid);
            if (rc < 0) {
                PyMem_Free(body.buf);
                return -1;
            }
        }
        int rc = put_length(o, body.len, 0xC0);
        if (rc == 0 && body.len)
            rc = out_put(o, body.buf, body.len);
        PyMem_Free(body.buf);
        return rc;
    }
    /* subclasses and bytearray: normalize, matching the Python
     * reference's isinstance fallback (exact-type checks above are a
     * fast path, not a contract change) */
    if (PyByteArray_Check(item) || PyBytes_Check(item)) {
        PyObject *b = PyBytes_FromObject(item);
        if (!b)
            return -1;
        int rc = encode_item(o, b, depth);
        Py_DECREF(b);
        return rc;
    }
    if (PyList_Check(item) || PyTuple_Check(item)) {
        PyObject *l = PySequence_List(item);
        if (!l)
            return -1;
        int rc = encode_item(o, l, depth);
        Py_DECREF(l);
        return rc;
    }
    PyErr_Format(PyExc_TypeError, "cannot RLP-encode %s",
                 Py_TYPE(item)->tp_name);
    return -1;
}

static PyObject *rlp_encode(PyObject *self, PyObject *arg)
{
    Out o = {NULL, 0, 0};
    if (encode_item(&o, arg, 0) < 0) {
        PyMem_Free(o.buf);
        return NULL;
    }
    PyObject *res = PyBytes_FromStringAndSize(o.buf, o.len);
    PyMem_Free(o.buf);
    return res;
}

/* ------------------------------------------------------------ decode */

static PyObject *decode_at(const unsigned char *d, Py_ssize_t *pos,
                           Py_ssize_t end, int depth);

static int read_len(const unsigned char *d, Py_ssize_t *pos,
                    Py_ssize_t end, int ln, Py_ssize_t minimum,
                    Py_ssize_t *out_n)
{
    if (*pos + 1 + ln > end) {
        PyErr_SetString(PyExc_ValueError, "truncated RLP");
        return -1;
    }
    if (d[*pos + 1] == 0) {
        PyErr_SetString(PyExc_ValueError, "leading zero in RLP length");
        return -1;
    }
    Py_ssize_t n = 0;
    for (int i = 0; i < ln; i++) {
        if (n > (PY_SSIZE_T_MAX >> 8)) {
            PyErr_SetString(PyExc_ValueError, "RLP length overflow");
            return -1;
        }
        n = (n << 8) | d[*pos + 1 + i];
    }
    if (n < minimum) {
        PyErr_SetString(PyExc_ValueError, "non-canonical RLP length");
        return -1;
    }
    *pos += 1 + ln;
    /* n > end - *pos, NOT *pos + n > end: attacker-chosen n near
     * PY_SSIZE_T_MAX must not overflow the signed addition (UB) */
    if (n > end - *pos) {
        PyErr_SetString(PyExc_ValueError, "truncated RLP");
        return -1;
    }
    *out_n = n;
    return 0;
}

static PyObject *decode_list(const unsigned char *d, Py_ssize_t *pos,
                             Py_ssize_t end, int depth)
{
    PyObject *out = PyList_New(0);
    if (!out)
        return NULL;
    while (*pos < end) {
        PyObject *item = decode_at(d, pos, end, depth);
        if (!item || PyList_Append(out, item) < 0) {
            Py_XDECREF(item);
            Py_DECREF(out);
            return NULL;
        }
        Py_DECREF(item);
    }
    return out;
}

static PyObject *decode_at(const unsigned char *d, Py_ssize_t *pos,
                           Py_ssize_t end, int depth)
{
    if (*pos >= end) {
        PyErr_SetString(PyExc_ValueError, "empty RLP");
        return NULL;
    }
    unsigned char b0 = d[*pos];
    if (b0 < 0x80) {
        PyObject *r = PyBytes_FromStringAndSize(
            (const char *)d + *pos, 1);
        *pos += 1;
        return r;
    }
    if (b0 < 0xB8) {        /* short string */
        Py_ssize_t n = b0 - 0x80;
        if (*pos + 1 + n > end) {
            PyErr_SetString(PyExc_ValueError, "truncated RLP");
            return NULL;
        }
        if (n == 1 && d[*pos + 1] < 0x80) {
            PyErr_SetString(PyExc_ValueError,
                            "non-canonical RLP single byte");
            return NULL;
        }
        PyObject *r = PyBytes_FromStringAndSize(
            (const char *)d + *pos + 1, n);
        *pos += 1 + n;
        return r;
    }
    if (b0 < 0xC0) {        /* long string */
        Py_ssize_t n;
        if (read_len(d, pos, end, b0 - 0xB7, 56, &n) < 0)
            return NULL;
        PyObject *r = PyBytes_FromStringAndSize((const char *)d + *pos, n);
        *pos += n;
        return r;
    }
    /* list forms: only lists carry nesting depth (mirrors rlp.py) */
    if (depth >= RLP_MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "RLP nesting too deep");
        return NULL;
    }
    if (b0 < 0xF8) {        /* short list */
        Py_ssize_t n = b0 - 0xC0;
        if (*pos + 1 + n > end) {
            PyErr_SetString(PyExc_ValueError, "truncated RLP");
            return NULL;
        }
        *pos += 1;
        Py_ssize_t sub_end = *pos + n;
        PyObject *r = decode_list(d, pos, sub_end, depth + 1);
        return r;
    }
    /* long list */
    Py_ssize_t n;
    if (read_len(d, pos, end, b0 - 0xF7, 56, &n) < 0)
        return NULL;
    Py_ssize_t sub_end = *pos + n;
    return decode_list(d, pos, sub_end, depth + 1);
}

static PyObject *rlp_decode(PyObject *self, PyObject *arg)
{
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0)
        return NULL;
    Py_ssize_t pos = 0;
    PyObject *item = decode_at((const unsigned char *)view.buf, &pos,
                               view.len, 0);
    if (item && pos != view.len) {
        Py_DECREF(item);
        item = NULL;
        PyErr_SetString(PyExc_ValueError, "trailing RLP bytes");
    }
    PyBuffer_Release(&view);
    return item;
}

/* ------------------------------------------------------------ module */

static PyMethodDef Methods[] = {
    {"encode", rlp_encode, METH_O,
     "RLP-encode bytes / nested lists of bytes."},
    {"decode", rlp_decode, METH_O,
     "Decode canonical RLP into bytes / nested lists."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef Module = {
    PyModuleDef_HEAD_INIT, "rlp_c",
    "Native RLP codec for MPT trie nodes.", -1, Methods,
};

PyMODINIT_FUNC PyInit_rlp_c(void)
{
    return PyModule_Create(&Module);
}
