/* mpt_c.c — native Merkle Patricia Trie (the state-trie hot path).
 *
 * Same node format as the Python reference implementation in
 * plenum_tpu/state/trie.py (which mirrors the reference's
 * state/trie/pruning_trie.py semantics): sha3-256 node hashing,
 * RLP node encoding, hex-prefix paths, inline refs for nodes whose
 * encoding is < 32 bytes, nothing deleted on update (old roots stay
 * readable).  Roots are REQUIRED to match the Python trie bit-for-bit —
 * they are consensus state — and tests/test_mpt_native.py cross-checks
 * every operation against the Python implementation.
 *
 * The store is an in-process hash table (sha3 → node blob) with a
 * drain() API: Python persists newly created nodes into the durable KV
 * after each operation, and a miss callback hydrates nodes lazily from
 * that KV after a restart.  All per-node work (RLP decode/encode, sha3,
 * nibble walking) stays in C; Python only crosses the boundary once per
 * trie operation.
 *
 * API (all roots are 32-byte sha3 digests):
 *   h = new(miss_callback or None)
 *   set(h, root, key, value)   -> new_root          (empty value deletes)
 *   delete(h, root, key)       -> new_root
 *   get(h, root, key)          -> bytes | None
 *   proof(h, root, key)        -> [node_blob, ...]  (SPV proof path)
 *   items(h, root)             -> [(key, value), ...]
 *   drain(h)                   -> [(hash32, blob), ...] new since last drain
 *   put_node(h, hash32, blob)                       (bulk hydration)
 *   blank_root()               -> the empty-trie root
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

/* ------------------------------------------------------------------ */
/* Keccak / SHA3-256                                                   */
/* ------------------------------------------------------------------ */

static const uint64_t KRC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL
};

#define ROTL64(x, n) (((x) << (n)) | ((x) >> (64 - (n))))

static void keccakf(uint64_t st[25]) {
    int round, i, j;
    uint64_t t, bc[5];
    static const int rotc[24] = {
        1, 3, 6, 10, 15, 21, 28, 36, 45, 55, 2, 14,
        27, 41, 56, 8, 25, 43, 62, 18, 39, 61, 20, 44
    };
    static const int piln[24] = {
        10, 7, 11, 17, 18, 3, 5, 16, 8, 21, 24, 4,
        15, 23, 19, 13, 12, 2, 20, 14, 22, 9, 6, 1
    };
    for (round = 0; round < 24; round++) {
        /* theta */
        for (i = 0; i < 5; i++)
            bc[i] = st[i] ^ st[i+5] ^ st[i+10] ^ st[i+15] ^ st[i+20];
        for (i = 0; i < 5; i++) {
            t = bc[(i+4) % 5] ^ ROTL64(bc[(i+1) % 5], 1);
            for (j = 0; j < 25; j += 5) st[j+i] ^= t;
        }
        /* rho + pi */
        t = st[1];
        for (i = 0; i < 24; i++) {
            j = piln[i];
            bc[0] = st[j];
            st[j] = ROTL64(t, rotc[i]);
            t = bc[0];
        }
        /* chi */
        for (j = 0; j < 25; j += 5) {
            for (i = 0; i < 5; i++) bc[i] = st[j+i];
            for (i = 0; i < 5; i++)
                st[j+i] ^= (~bc[(i+1) % 5]) & bc[(i+2) % 5];
        }
        /* iota */
        st[0] ^= KRC[round];
    }
}

#define SHA3_RATE 136  /* sha3-256: (1600 - 2*256)/8 */

static void sha3_256(uint8_t out[32], const uint8_t *in, size_t len) {
    uint64_t st[25];
    uint8_t tmp[SHA3_RATE];
    size_t i;
    memset(st, 0, sizeof st);
    while (len >= SHA3_RATE) {
        for (i = 0; i < SHA3_RATE / 8; i++) {
            uint64_t v;
            memcpy(&v, in + 8*i, 8);
            st[i] ^= v;       /* little-endian host assumed (x86/arm) */
        }
        keccakf(st);
        in += SHA3_RATE; len -= SHA3_RATE;
    }
    memset(tmp, 0, sizeof tmp);
    memcpy(tmp, in, len);
    tmp[len] = 0x06;           /* SHA3 domain padding */
    tmp[SHA3_RATE - 1] |= 0x80;
    for (i = 0; i < SHA3_RATE / 8; i++) {
        uint64_t v;
        memcpy(&v, tmp + 8*i, 8);
        st[i] ^= v;
    }
    keccakf(st);
    memcpy(out, st, 32);
}

/* ------------------------------------------------------------------ */
/* arena allocator (reset after every top-level operation)             */
/* ------------------------------------------------------------------ */

typedef struct arena_block {
    struct arena_block *next;
    size_t used, cap;
    /* data follows */
} arena_block;

typedef struct {
    arena_block *head;
} arena_t;

#define ARENA_BLOCK 65536

static void *arena_alloc(arena_t *a, size_t n) {
    arena_block *b = a->head;
    void *p;
    n = (n + 15) & ~(size_t)15;
    if (!b || b->used + n > b->cap) {
        size_t cap = n > ARENA_BLOCK ? n : ARENA_BLOCK;
        arena_block *nb = malloc(sizeof(arena_block) + cap);
        if (!nb) return NULL;
        nb->next = a->head; nb->used = 0; nb->cap = cap;
        a->head = nb;
        b = nb;
    }
    p = (char *)(b + 1) + b->used;
    b->used += n;
    return p;
}

static void arena_reset(arena_t *a) {
    arena_block *b = a->head, *n;
    /* keep the newest block for reuse, free the rest */
    if (b) {
        b->used = 0;
        n = b->next; b->next = NULL;
        while (n) {
            arena_block *nx = n->next;
            free(n);
            n = nx;
        }
    }
}

static void arena_destroy(arena_t *a) {
    arena_block *b = a->head;
    while (b) {
        arena_block *n = b->next;
        free(b);
        b = n;
    }
    a->head = NULL;
}

/* ------------------------------------------------------------------ */
/* item model: bytes or list                                           */
/* ------------------------------------------------------------------ */

typedef struct item {
    int is_list;
    /* bytes */
    const uint8_t *b; size_t blen;
    /* list */
    struct item **kids; size_t n;
} item_t;

static item_t *item_bytes(arena_t *a, const uint8_t *b, size_t n) {
    item_t *it = arena_alloc(a, sizeof(item_t));
    if (!it) return NULL;
    it->is_list = 0; it->b = b; it->blen = n; it->kids = NULL; it->n = 0;
    return it;
}

static item_t *item_list(arena_t *a, size_t n) {
    item_t *it = arena_alloc(a, sizeof(item_t));
    if (!it) return NULL;
    it->is_list = 1; it->b = NULL; it->blen = 0; it->n = n;
    it->kids = arena_alloc(a, n * sizeof(item_t *));
    if (!it->kids && n) return NULL;
    memset(it->kids, 0, n * sizeof(item_t *));
    return it;
}

static int item_is_blank(const item_t *it) {
    return !it->is_list && it->blen == 0;
}

/* ------------------------------------------------------------------ */
/* RLP encode/decode over items                                        */
/* ------------------------------------------------------------------ */

static size_t rlp_enc_size(const item_t *it) {
    if (!it->is_list) {
        size_t n = it->blen;
        if (n == 1 && it->b[0] < 0x80) return 1;
        if (n < 56) return 1 + n;
        { size_t l = n, ll = 0; while (l) { ll++; l >>= 8; }
          return 1 + ll + n; }
    } else {
        size_t body = 0, i;
        for (i = 0; i < it->n; i++) body += rlp_enc_size(it->kids[i]);
        if (body < 56) return 1 + body;
        { size_t l = body, ll = 0; while (l) { ll++; l >>= 8; }
          return 1 + ll + body; }
    }
}

static uint8_t *rlp_enc_write(const item_t *it, uint8_t *p) {
    if (!it->is_list) {
        size_t n = it->blen;
        if (n == 1 && it->b[0] < 0x80) { *p++ = it->b[0]; return p; }
        if (n < 56) { *p++ = (uint8_t)(0x80 + n); }
        else {
            size_t l = n, ll = 0;
            uint8_t lenb[8];
            while (l) { lenb[ll++] = (uint8_t)l; l >>= 8; }
            *p++ = (uint8_t)(0xB7 + ll);
            { size_t i; for (i = 0; i < ll; i++) *p++ = lenb[ll-1-i]; }
        }
        if (n) memcpy(p, it->b, n);
        return p + n;
    } else {
        size_t body = 0, i;
        for (i = 0; i < it->n; i++) body += rlp_enc_size(it->kids[i]);
        if (body < 56) { *p++ = (uint8_t)(0xC0 + body); }
        else {
            size_t l = body, ll = 0;
            uint8_t lenb[8];
            while (l) { lenb[ll++] = (uint8_t)l; l >>= 8; }
            *p++ = (uint8_t)(0xF7 + ll);
            { size_t j; for (j = 0; j < ll; j++) *p++ = lenb[ll-1-j]; }
        }
        for (i = 0; i < it->n; i++) p = rlp_enc_write(it->kids[i], p);
        return p;
    }
}

/* encode into arena; returns buffer + sets *out_len */
static uint8_t *rlp_encode_arena(arena_t *a, const item_t *it,
                                 size_t *out_len) {
    size_t n = rlp_enc_size(it);
    uint8_t *buf = arena_alloc(a, n);
    if (!buf) return NULL;
    rlp_enc_write(it, buf);
    *out_len = n;
    return buf;
}

/* decode; data must outlive the items (views) */
static item_t *rlp_dec_at(arena_t *a, const uint8_t *d, size_t *pos,
                          size_t end, int depth) {
    uint8_t b0;
    if (*pos >= end || depth > 64) return NULL;
    b0 = d[*pos];
    if (b0 < 0x80) {
        item_t *it = item_bytes(a, d + *pos, 1);
        (*pos)++;
        return it;
    }
    if (b0 < 0xB8) {
        size_t n = b0 - 0x80;
        item_t *it;
        if (*pos + 1 + n > end) return NULL;
        it = item_bytes(a, d + *pos + 1, n);
        *pos += 1 + n;
        return it;
    }
    if (b0 < 0xC0) {
        size_t ll = b0 - 0xB7, n = 0, i;
        item_t *it;
        if (*pos + 1 + ll > end) return NULL;
        for (i = 0; i < ll; i++) n = (n << 8) | d[*pos + 1 + i];
        if (*pos + 1 + ll + n > end) return NULL;
        it = item_bytes(a, d + *pos + 1 + ll, n);
        *pos += 1 + ll + n;
        return it;
    }
    {
        size_t body_start, body_end, n = 0, ll, i;
        size_t cnt = 0, p2;
        item_t *it;
        if (b0 < 0xF8) {
            n = b0 - 0xC0;
            body_start = *pos + 1;
        } else {
            ll = b0 - 0xF7;
            if (*pos + 1 + ll > end) return NULL;
            for (i = 0; i < ll; i++) n = (n << 8) | d[*pos + 1 + i];
            body_start = *pos + 1 + ll;
        }
        body_end = body_start + n;
        if (body_end > end) return NULL;
        /* count children */
        p2 = body_start;
        while (p2 < body_end) {
            uint8_t c = d[p2];
            if (c < 0x80) p2 += 1;
            else if (c < 0xB8) p2 += 1 + (size_t)(c - 0x80);
            else if (c < 0xC0) {
                size_t cl = c - 0xB7, cn = 0;
                if (p2 + 1 + cl > body_end) return NULL;
                for (i = 0; i < cl; i++) cn = (cn << 8) | d[p2 + 1 + i];
                p2 += 1 + cl + cn;
            } else if (c < 0xF8) p2 += 1 + (size_t)(c - 0xC0);
            else {
                size_t cl = c - 0xF7, cn = 0;
                if (p2 + 1 + cl > body_end) return NULL;
                for (i = 0; i < cl; i++) cn = (cn << 8) | d[p2 + 1 + i];
                p2 += 1 + cl + cn;
            }
            cnt++;
        }
        if (p2 != body_end) return NULL;
        it = item_list(a, cnt);
        if (!it) return NULL;
        p2 = body_start;
        for (i = 0; i < cnt; i++) {
            it->kids[i] = rlp_dec_at(a, d, &p2, body_end, depth + 1);
            if (!it->kids[i]) return NULL;
        }
        *pos = body_end;
        return it;
    }
}

static item_t *rlp_decode_arena(arena_t *a, const uint8_t *d, size_t len) {
    size_t pos = 0;
    item_t *it = rlp_dec_at(a, d, &pos, len, 0);
    if (!it || pos != len) return NULL;
    return it;
}

/* ------------------------------------------------------------------ */
/* node store: open-addressing hash table sha3 → blob                  */
/* ------------------------------------------------------------------ */

typedef struct {
    uint8_t hash[32];
    uint8_t *blob;      /* malloc'd */
    uint32_t len;
    uint8_t used;
    uint8_t fresh;      /* not yet drained to the durable KV */
    uint64_t last_used; /* access tick, drives eviction */
} slot_t;

typedef struct {
    slot_t *slots;
    size_t cap;         /* power of two */
    size_t count;
    size_t max_nodes;   /* eviction threshold; 0 = unbounded (no KV) */
    uint64_t tick;      /* monotonic access counter */
    PyObject *miss_cb;  /* optional: hash -> blob (durable KV fetch) */
    arena_t arena;
    /* list of fresh hashes for drain() */
    uint8_t (*fresh)[32];
    size_t fresh_n, fresh_cap;
    /* set_many batching: while 1, ref_node keeps nodes as in-memory
       lists instead of hashing/storing them, so path nodes shared by
       the batch's keys are encoded+hashed ONCE at commit instead of
       once per key */
    int deferred;
} mpt_t;

static uint64_t hash64(const uint8_t *h) {
    uint64_t v;
    memcpy(&v, h, 8);
    return v;
}

static int store_grow(mpt_t *m) {
    size_t ncap = m->cap * 2, i;
    slot_t *ns = calloc(ncap, sizeof(slot_t));
    if (!ns) return -1;
    for (i = 0; i < m->cap; i++) {
        if (m->slots[i].used) {
            size_t j = hash64(m->slots[i].hash) & (ncap - 1);
            while (ns[j].used) j = (j + 1) & (ncap - 1);
            ns[j] = m->slots[i];
        }
    }
    free(m->slots);
    m->slots = ns; m->cap = ncap;
    return 0;
}

static slot_t *store_find(mpt_t *m, const uint8_t hash[32]) {
    size_t i = hash64(hash) & (m->cap - 1);
    while (m->slots[i].used) {
        if (memcmp(m->slots[i].hash, hash, 32) == 0) {
            m->slots[i].last_used = ++m->tick;
            return &m->slots[i];
        }
        i = (i + 1) & (m->cap - 1);
    }
    return NULL;
}

/* Every drained/hydrated node is recoverable from the durable KV via the
 * miss callback, so when the in-process store outgrows max_nodes we drop
 * the least-recently-touched non-fresh half.  Fresh (not yet drained)
 * nodes are never evicted.  This bounds a long-running validator's RAM
 * where the Python backend leaned on its capped decode cache. */
static void store_evict(mpt_t *m) {
    size_t i, kept = 0;
    uint64_t sum = 0, threshold;
    size_t evictable = 0;
    slot_t *ns;
    for (i = 0; i < m->cap; i++) {
        if (m->slots[i].used && !m->slots[i].fresh) {
            sum += m->slots[i].last_used;
            evictable++;
        }
    }
    if (!evictable) return;
    threshold = sum / evictable;  /* ~median by mean: drops roughly half */
    ns = calloc(m->cap, sizeof(slot_t));
    if (!ns) return;  /* allocation pressure: skip eviction this round */
    for (i = 0; i < m->cap; i++) {
        if (!m->slots[i].used) continue;
        if (!m->slots[i].fresh && m->slots[i].last_used <= threshold) {
            free(m->slots[i].blob);
            continue;
        }
        {
            size_t j = hash64(m->slots[i].hash) & (m->cap - 1);
            while (ns[j].used) j = (j + 1) & (m->cap - 1);
            ns[j] = m->slots[i];
            kept++;
        }
    }
    free(m->slots);
    m->slots = ns;
    m->count = kept;
}

static int store_put(mpt_t *m, const uint8_t hash[32],
                     const uint8_t *blob, size_t len, int fresh) {
    size_t i;
    if ((m->count + 1) * 4 > m->cap * 3 && store_grow(m) < 0) return -1;
    i = hash64(hash) & (m->cap - 1);
    while (m->slots[i].used) {
        if (memcmp(m->slots[i].hash, hash, 32) == 0) return 0; /* have it */
        i = (i + 1) & (m->cap - 1);
    }
    m->slots[i].blob = malloc(len ? len : 1);
    if (!m->slots[i].blob) return -1;
    memcpy(m->slots[i].blob, blob, len);
    m->slots[i].len = (uint32_t)len;
    memcpy(m->slots[i].hash, hash, 32);
    m->slots[i].used = 1;
    m->slots[i].fresh = (uint8_t)fresh;
    m->slots[i].last_used = ++m->tick;
    m->count++;
    if (fresh) {
        if (m->fresh_n == m->fresh_cap) {
            size_t nc = m->fresh_cap ? m->fresh_cap * 2 : 256;
            void *np = realloc(m->fresh, nc * 32);
            if (!np) return -1;
            m->fresh = np; m->fresh_cap = nc;
        }
        memcpy(m->fresh[m->fresh_n++], hash, 32);
    }
    return 0;
}

/* fetch blob; on miss, consult the Python miss callback (hydration).
 * Returns 0 on success. Sets Python error on failure. */
static int store_get(mpt_t *m, const uint8_t hash[32],
                     const uint8_t **blob, size_t *len) {
    slot_t *s = store_find(m, hash);
    if (s) { *blob = s->blob; *len = s->len; return 0; }
    if (m->miss_cb && m->miss_cb != Py_None) {
        PyObject *arg = PyBytes_FromStringAndSize((const char *)hash, 32);
        PyObject *res;
        if (!arg) return -1;
        res = PyObject_CallFunctionObjArgs(m->miss_cb, arg, NULL);
        Py_DECREF(arg);
        if (!res) return -1;
        if (res == Py_None) {
            Py_DECREF(res);
        } else {
            char *buf;
            Py_ssize_t blen;
            if (PyBytes_AsStringAndSize(res, &buf, &blen) < 0) {
                Py_DECREF(res);
                return -1;
            }
            /* hydrate (not fresh: it came FROM the durable store) */
            if (store_put(m, hash, (const uint8_t *)buf, (size_t)blen,
                          0) < 0) {
                Py_DECREF(res);
                PyErr_NoMemory();
                return -1;
            }
            Py_DECREF(res);
            s = store_find(m, hash);
            *blob = s->blob; *len = s->len;
            return 0;
        }
    }
    {
        char hex[65];
        static const char *H = "0123456789abcdef";
        int i;
        for (i = 0; i < 32; i++) {
            hex[2*i] = H[hash[i] >> 4];
            hex[2*i+1] = H[hash[i] & 15];
        }
        hex[64] = 0;
        PyErr_Format(PyExc_KeyError, "missing trie node %s", hex);
        return -1;
    }
}

/* ------------------------------------------------------------------ */
/* trie algorithms (mirror state/trie.py)                              */
/* ------------------------------------------------------------------ */

static uint8_t BLANK_ROOT_HASH[32];
static int blank_root_ready = 0;

static void ensure_blank_root(void) {
    if (!blank_root_ready) {
        uint8_t enc = 0x80;  /* rlp(b"") */
        sha3_256(BLANK_ROOT_HASH, &enc, 1);
        blank_root_ready = 1;
    }
}

/* load a ref item (inline list / 32-byte hash / blank) into a node */
static item_t *load_ref(mpt_t *m, arena_t *a, item_t *ref) {
    const uint8_t *blob;
    size_t len;
    item_t *node;
    if (ref->is_list) return ref;
    if (ref->blen == 0) return ref;  /* blank */
    if (ref->blen == 32) {
        if (store_get(m, ref->b, &blob, &len) < 0) return NULL;
        node = rlp_decode_arena(a, blob, len);
        if (!node) PyErr_SetString(PyExc_ValueError, "corrupt trie node");
        return node;
    }
    node = rlp_decode_arena(a, ref->b, ref->blen);
    if (!node) PyErr_SetString(PyExc_ValueError, "corrupt inline node");
    return node;
}

/* persist node; return inline item if encoding < 32 bytes else hash item */
static item_t *ref_node(mpt_t *m, arena_t *a, item_t *node) {
    size_t enc_len;
    uint8_t *enc;
    uint8_t *h;
    item_t *out;
    if (item_is_blank(node)) return node;
    if (m->deferred) return node;  /* batch mode: ref-ify at commit */
    enc = rlp_encode_arena(a, node, &enc_len);
    if (!enc) { PyErr_NoMemory(); return NULL; }
    if (enc_len < 32) return node;
    h = arena_alloc(a, 32);
    if (!h) { PyErr_NoMemory(); return NULL; }
    sha3_256(h, enc, enc_len);
    if (store_put(m, h, enc, enc_len, 1) < 0) {
        PyErr_NoMemory();
        return NULL;
    }
    out = item_bytes(a, h, 32);
    return out;
}

/* hex-prefix helpers over nibble arrays */
static item_t *hp_encode_item(arena_t *a, const uint8_t *nib, size_t n,
                              int terminal) {
    size_t total = (n + 2) / 2 + ((n % 2) ? 0 : 0);
    uint8_t *out;
    item_t *it;
    size_t outlen, i;
    int flags = terminal ? 2 : 0;
    if (n % 2 == 1) {
        flags |= 1;
        outlen = (n + 1) / 2;
        out = arena_alloc(a, outlen);
        if (!out) { PyErr_NoMemory(); return NULL; }
        out[0] = (uint8_t)((flags << 4) | nib[0]);
        for (i = 1; i < outlen; i++)
            out[i] = (uint8_t)((nib[2*i-1] << 4) | nib[2*i]);
    } else {
        outlen = n / 2 + 1;
        out = arena_alloc(a, outlen);
        if (!out) { PyErr_NoMemory(); return NULL; }
        out[0] = (uint8_t)(flags << 4);
        for (i = 1; i < outlen; i++)
            out[i] = (uint8_t)((nib[2*i-2] << 4) | nib[2*i-1]);
    }
    (void)total;
    it = item_bytes(a, out, outlen);
    return it;
}

/* decode hex-prefix item -> nibble array (arena), length, terminal flag */
static uint8_t *hp_decode_item(arena_t *a, const item_t *hp,
                               size_t *out_n, int *terminal) {
    size_t total = hp->blen * 2, i;
    uint8_t *nib, flags, skip;
    if (hp->blen == 0) { PyErr_SetString(PyExc_ValueError, "bad hp"); return NULL; }
    nib = arena_alloc(a, total ? total : 1);
    if (!nib) { PyErr_NoMemory(); return NULL; }
    for (i = 0; i < hp->blen; i++) {
        nib[2*i] = hp->b[i] >> 4;
        nib[2*i+1] = hp->b[i] & 15;
    }
    flags = nib[0];
    *terminal = (flags & 2) != 0;
    skip = (flags & 1) ? 1 : 2;
    *out_n = total - skip;
    return nib + skip;
}

/* branch helper: item is a branch iff list of 17 */
#define IS_BRANCH(it) ((it)->is_list && (it)->n == 17)
#define IS_PAIR(it)   ((it)->is_list && (it)->n == 2)

static item_t *blank_item(arena_t *a) {
    return item_bytes(a, NULL, 0);
}

/* forward decls */
static item_t *trie_update(mpt_t *m, arena_t *a, item_t *node,
                           const uint8_t *nib, size_t nlen,
                           const uint8_t *val, size_t vlen);
static item_t *trie_delete_node(mpt_t *m, arena_t *a, item_t *node,
                                const uint8_t *nib, size_t nlen,
                                int *changed);

static item_t *make_leaf(arena_t *a, const uint8_t *nib, size_t nlen,
                         int terminal, const uint8_t *val, size_t vlen) {
    item_t *l = item_list(a, 2);
    if (!l) { PyErr_NoMemory(); return NULL; }
    l->kids[0] = hp_encode_item(a, nib, nlen, terminal);
    if (!l->kids[0]) return NULL;
    l->kids[1] = item_bytes(a, val, vlen);
    if (!l->kids[1]) { PyErr_NoMemory(); return NULL; }
    return l;
}

static item_t *trie_update(mpt_t *m, arena_t *a, item_t *node,
                           const uint8_t *nib, size_t nlen,
                           const uint8_t *val, size_t vlen) {
    if (item_is_blank(node))
        return make_leaf(a, nib, nlen, 1, val, vlen);
    if (IS_BRANCH(node)) {
        item_t *nn = item_list(a, 17);
        size_t i;
        if (!nn) { PyErr_NoMemory(); return NULL; }
        for (i = 0; i < 17; i++) nn->kids[i] = node->kids[i];
        if (nlen == 0) {
            nn->kids[16] = item_bytes(a, val, vlen);
            if (!nn->kids[16]) { PyErr_NoMemory(); return NULL; }
        } else {
            item_t *child = load_ref(m, a, node->kids[nib[0]]);
            item_t *sub, *r;
            if (!child) return NULL;
            sub = trie_update(m, a, child, nib + 1, nlen - 1, val, vlen);
            if (!sub) return NULL;
            r = ref_node(m, a, sub);
            if (!r) return NULL;
            nn->kids[nib[0]] = r;
        }
        return nn;
    }
    /* leaf or extension */
    {
        size_t plen, common = 0;
        int terminal;
        uint8_t *path = hp_decode_item(a, node->kids[0], &plen, &terminal);
        item_t *branch, *out;
        if (!path) return NULL;
        while (common < plen && common < nlen && path[common] == nib[common])
            common++;
        if (terminal && plen == nlen && common == plen) {
            /* exact leaf overwrite */
            item_t *l = item_list(a, 2);
            if (!l) { PyErr_NoMemory(); return NULL; }
            l->kids[0] = node->kids[0];
            l->kids[1] = item_bytes(a, val, vlen);
            if (!l->kids[1]) { PyErr_NoMemory(); return NULL; }
            return l;
        }
        if (!terminal && common == plen) {
            item_t *child = load_ref(m, a, node->kids[1]);
            item_t *sub, *r, *l;
            if (!child) return NULL;
            sub = trie_update(m, a, child, nib + common, nlen - common,
                              val, vlen);
            if (!sub) return NULL;
            r = ref_node(m, a, sub);
            if (!r) return NULL;
            l = item_list(a, 2);
            if (!l) { PyErr_NoMemory(); return NULL; }
            l->kids[0] = node->kids[0];
            l->kids[1] = r;
            return l;
        }
        /* split */
        branch = item_list(a, 17);
        if (!branch) { PyErr_NoMemory(); return NULL; }
        {
            size_t i;
            for (i = 0; i < 17; i++) {
                branch->kids[i] = blank_item(a);
                if (!branch->kids[i]) { PyErr_NoMemory(); return NULL; }
            }
        }
        {
            const uint8_t *old_rest = path + common;
            size_t old_n = plen - common;
            if (terminal) {
                if (old_n) {
                    item_t *l = item_list(a, 2);
                    item_t *r;
                    if (!l) { PyErr_NoMemory(); return NULL; }
                    l->kids[0] = hp_encode_item(a, old_rest + 1, old_n - 1, 1);
                    if (!l->kids[0]) return NULL;
                    l->kids[1] = node->kids[1];
                    r = ref_node(m, a, l);
                    if (!r) return NULL;
                    branch->kids[old_rest[0]] = r;
                } else {
                    branch->kids[16] = node->kids[1];
                }
            } else {
                if (old_n > 1) {
                    item_t *l = item_list(a, 2);
                    item_t *r;
                    if (!l) { PyErr_NoMemory(); return NULL; }
                    l->kids[0] = hp_encode_item(a, old_rest + 1, old_n - 1, 0);
                    if (!l->kids[0]) return NULL;
                    l->kids[1] = node->kids[1];
                    r = ref_node(m, a, l);
                    if (!r) return NULL;
                    branch->kids[old_rest[0]] = r;
                } else {
                    branch->kids[old_rest[0]] = node->kids[1];
                }
            }
        }
        {
            const uint8_t *new_rest = nib + common;
            size_t new_n = nlen - common;
            if (new_n) {
                item_t *l = make_leaf(a, new_rest + 1, new_n - 1, 1,
                                      val, vlen);
                item_t *r;
                if (!l) return NULL;
                r = ref_node(m, a, l);
                if (!r) return NULL;
                branch->kids[new_rest[0]] = r;
            } else {
                branch->kids[16] = item_bytes(a, val, vlen);
                if (!branch->kids[16]) { PyErr_NoMemory(); return NULL; }
            }
        }
        if (common) {
            item_t *r = ref_node(m, a, branch);
            item_t *l;
            if (!r) return NULL;
            l = item_list(a, 2);
            if (!l) { PyErr_NoMemory(); return NULL; }
            l->kids[0] = hp_encode_item(a, nib, common, 0);
            if (!l->kids[0]) return NULL;
            l->kids[1] = r;
            out = l;
        } else {
            out = branch;
        }
        return out;
    }
}

/* merge path prefix onto child (mirror _merge_extension) */
static item_t *merge_extension(mpt_t *m, arena_t *a, const uint8_t *path,
                               size_t plen, item_t *child) {
    if (item_is_blank(child)) return child;
    if (IS_BRANCH(child)) {
        item_t *r = ref_node(m, a, child);
        item_t *l;
        if (!r) return NULL;
        l = item_list(a, 2);
        if (!l) { PyErr_NoMemory(); return NULL; }
        l->kids[0] = hp_encode_item(a, path, plen, 0);
        if (!l->kids[0]) return NULL;
        l->kids[1] = r;
        return l;
    }
    {
        size_t sublen;
        int terminal;
        uint8_t *sub = hp_decode_item(a, child->kids[0], &sublen, &terminal);
        uint8_t *joined;
        item_t *l;
        if (!sub) return NULL;
        joined = arena_alloc(a, plen + sublen ? plen + sublen : 1);
        if (!joined) { PyErr_NoMemory(); return NULL; }
        memcpy(joined, path, plen);
        memcpy(joined + plen, sub, sublen);
        l = item_list(a, 2);
        if (!l) { PyErr_NoMemory(); return NULL; }
        l->kids[0] = hp_encode_item(a, joined, plen + sublen, terminal);
        if (!l->kids[0]) return NULL;
        l->kids[1] = child->kids[1];
        return l;
    }
}

static item_t *normalize_branch(mpt_t *m, arena_t *a, item_t *node) {
    size_t occupied[16], nocc = 0, i;
    int has_value = !item_is_blank(node->kids[16]);
    for (i = 0; i < 16; i++)
        if (!item_is_blank(node->kids[i])) occupied[nocc++] = i;
    if (nocc + (has_value ? 1 : 0) > 1) return node;
    if (has_value) {
        item_t *l = item_list(a, 2);
        if (!l) { PyErr_NoMemory(); return NULL; }
        l->kids[0] = hp_encode_item(a, NULL, 0, 1);
        if (!l->kids[0]) return NULL;
        l->kids[1] = node->kids[16];
        return l;
    }
    if (!nocc) return blank_item(a);
    {
        uint8_t pi = (uint8_t)occupied[0];
        item_t *child = load_ref(m, a, node->kids[pi]);
        if (!child) return NULL;
        return merge_extension(m, a, &pi, 1, child);
    }
}

static item_t *trie_delete_node(mpt_t *m, arena_t *a, item_t *node,
                                const uint8_t *nib, size_t nlen,
                                int *changed) {
    if (item_is_blank(node)) return node;
    if (IS_BRANCH(node)) {
        item_t *nn = item_list(a, 17);
        size_t i;
        if (!nn) { PyErr_NoMemory(); return NULL; }
        for (i = 0; i < 17; i++) nn->kids[i] = node->kids[i];
        if (nlen == 0) {
            nn->kids[16] = blank_item(a);
            if (!nn->kids[16]) { PyErr_NoMemory(); return NULL; }
        } else {
            item_t *child = load_ref(m, a, node->kids[nib[0]]);
            item_t *sub, *r;
            if (!child) return NULL;
            sub = trie_delete_node(m, a, child, nib + 1, nlen - 1, changed);
            if (!sub) return NULL;
            r = ref_node(m, a, sub);
            if (!r) return NULL;
            nn->kids[nib[0]] = r;
        }
        return normalize_branch(m, a, nn);
    }
    {
        size_t plen;
        int terminal;
        uint8_t *path = hp_decode_item(a, node->kids[0], &plen, &terminal);
        if (!path) return NULL;
        if (terminal) {
            if (plen == nlen && memcmp(path, nib, nlen) == 0) {
                *changed = 1;
                return blank_item(a);
            }
            return node;
        }
        if (nlen < plen || memcmp(path, nib, plen) != 0) return node;
        {
            item_t *child = load_ref(m, a, node->kids[1]);
            item_t *sub;
            if (!child) return NULL;
            sub = trie_delete_node(m, a, child, nib + plen, nlen - plen,
                                   changed);
            if (!sub) return NULL;
            if (item_is_blank(sub)) return blank_item(a);
            return merge_extension(m, a, path, plen, sub);
        }
    }
}

/* get: returns 0 found / 1 not found / -1 error; value view into arena */
static int trie_get(mpt_t *m, arena_t *a, item_t *node,
                    const uint8_t *nib, size_t nlen,
                    const uint8_t **val, size_t *vlen) {
    for (;;) {
        if (item_is_blank(node)) return 1;
        if (IS_BRANCH(node)) {
            if (nlen == 0) {
                if (item_is_blank(node->kids[16])) return 1;
                *val = node->kids[16]->b;
                *vlen = node->kids[16]->blen;
                return 0;
            }
            node = load_ref(m, a, node->kids[nib[0]]);
            if (!node) return -1;
            nib++; nlen--;
            continue;
        }
        {
            size_t plen;
            int terminal;
            uint8_t *path = hp_decode_item(a, node->kids[0], &plen,
                                           &terminal);
            if (!path) return -1;
            if (terminal) {
                if (plen == nlen && memcmp(path, nib, nlen) == 0) {
                    *val = node->kids[1]->b;
                    *vlen = node->kids[1]->blen;
                    return 0;
                }
                return 1;
            }
            if (nlen < plen || memcmp(path, nib, plen) != 0) return 1;
            node = load_ref(m, a, node->kids[1]);
            if (!node) return -1;
            nib += plen; nlen -= plen;
        }
    }
}

/* ------------------------------------------------------------------ */
/* Python object plumbing                                              */
/* ------------------------------------------------------------------ */

static void mpt_capsule_destructor(PyObject *cap) {
    mpt_t *m = PyCapsule_GetPointer(cap, "mpt_c.handle");
    size_t i;
    if (!m) return;
    for (i = 0; i < m->cap; i++)
        if (m->slots[i].used) free(m->slots[i].blob);
    free(m->slots);
    free(m->fresh);
    Py_XDECREF(m->miss_cb);
    arena_destroy(&m->arena);
    free(m);
}

static mpt_t *get_handle(PyObject *cap) {
    return PyCapsule_GetPointer(cap, "mpt_c.handle");
}

static PyObject *py_new(PyObject *self, PyObject *args) {
    PyObject *cb = Py_None;
    unsigned long long max_nodes = 1ULL << 18;
    mpt_t *m;
    if (!PyArg_ParseTuple(args, "|OK", &cb, &max_nodes)) return NULL;
    m = calloc(1, sizeof(mpt_t));
    if (!m) return PyErr_NoMemory();
    m->max_nodes = (size_t)max_nodes;
    m->cap = 1 << 12;
    m->slots = calloc(m->cap, sizeof(slot_t));
    if (!m->slots) { free(m); return PyErr_NoMemory(); }
    Py_INCREF(cb);
    m->miss_cb = cb;
    ensure_blank_root();
    return PyCapsule_New(m, "mpt_c.handle", mpt_capsule_destructor);
}

static PyObject *py_blank_root(PyObject *self, PyObject *noarg) {
    ensure_blank_root();
    return PyBytes_FromStringAndSize((const char *)BLANK_ROOT_HASH, 32);
}

/* load root item: BLANK if root == BLANK_ROOT */
static item_t *load_root(mpt_t *m, arena_t *a, const uint8_t *root) {
    if (memcmp(root, BLANK_ROOT_HASH, 32) == 0)
        return blank_item(a);
    {
        item_t ref;
        ref.is_list = 0; ref.b = root; ref.blen = 32;
        ref.kids = NULL; ref.n = 0;
        return load_ref(m, a, &ref);
    }
}

static void key_nibbles(arena_t *a, const uint8_t *key, size_t klen,
                        uint8_t **nib, size_t *nlen) {
    uint8_t *n = arena_alloc(a, klen * 2 ? klen * 2 : 1);
    size_t i;
    if (!n) { *nib = NULL; return; }
    for (i = 0; i < klen; i++) {
        n[2*i] = key[i] >> 4;
        n[2*i+1] = key[i] & 15;
    }
    *nib = n;
    *nlen = klen * 2;
}

/* store root node (always by hash, even when small — _set_root) */
static PyObject *finish_root(mpt_t *m, arena_t *a, item_t *node) {
    size_t enc_len;
    uint8_t *enc;
    uint8_t h[32];
    PyObject *out;
    if (item_is_blank(node)) {
        item_t *blank = blank_item(a);
        if (!blank) { PyErr_NoMemory(); return NULL; }
        node = blank;
    }
    enc = rlp_encode_arena(a, node, &enc_len);
    if (!enc) { PyErr_NoMemory(); return NULL; }
    sha3_256(h, enc, enc_len);
    if (store_put(m, h, enc, enc_len, 1) < 0) return PyErr_NoMemory();
    out = PyBytes_FromStringAndSize((const char *)h, 32);
    return out;
}

static PyObject *py_set(PyObject *self, PyObject *args) {
    PyObject *cap;
    Py_buffer root, key, val;
    mpt_t *m;
    PyObject *out = NULL;
    if (!PyArg_ParseTuple(args, "Oy*y*y*", &cap, &root, &key, &val))
        return NULL;
    m = get_handle(cap);
    if (!m || root.len != 32) {
        PyErr_SetString(PyExc_ValueError, "bad handle or root");
        goto done;
    }
    {
        arena_t *a = &m->arena;
        item_t *node = load_root(m, a, root.buf);
        uint8_t *nib;
        size_t nlen;
        item_t *nroot;
        if (!node) goto done;
        if (val.len == 0) {
            /* empty value == delete (mirror Trie.set) */
            int changed = 0;
            key_nibbles(a, key.buf, (size_t)key.len, &nib, &nlen);
            if (!nib) { PyErr_NoMemory(); goto done; }
            nroot = trie_delete_node(m, a, node, nib, nlen, &changed);
        } else {
            key_nibbles(a, key.buf, (size_t)key.len, &nib, &nlen);
            if (!nib) { PyErr_NoMemory(); goto done; }
            nroot = trie_update(m, a, node, nib, nlen, val.buf,
                                (size_t)val.len);
        }
        if (!nroot) goto done;
        out = finish_root(m, a, nroot);
    }
done:
    if (m) arena_reset(&m->arena);
    PyBuffer_Release(&root);
    PyBuffer_Release(&key);
    PyBuffer_Release(&val);
    return out;
}

/* post-order ref-ification of a deferred subtree: children first, so
   every parent is encoded over its kids' final (hash/inline) form.
   Only list items can be deferred nodes — bytes kids are values,
   hashes, or hex-prefix paths and are left untouched. */
static int commit_kids(mpt_t *m, arena_t *a, item_t *node) {
    size_t i;
    if (!node->is_list) return 0;
    for (i = 0; i < node->n; i++) {
        item_t *kid = node->kids[i];
        if (kid && kid->is_list && !item_is_blank(kid)) {
            item_t *r;
            if (commit_kids(m, a, kid) < 0) return -1;
            r = ref_node(m, a, kid);
            if (!r) return -1;
            node->kids[i] = r;
        }
    }
    return 0;
}

/* set_many(h, root, [(key, value), ...]) -> new root.
   One deferred pass: updates build an in-memory node tree (no hashing,
   no stores), then the final tree is committed bottom-up — upper path
   nodes shared by the batch hash once instead of once per key. Empty
   value deletes, matching set(). Intermediate roots are not stored
   (only the batch's FINAL root is a readable snapshot). */
static PyObject *py_set_many(PyObject *self, PyObject *args) {
    PyObject *cap, *pairs, *fast = NULL;
    Py_buffer root;
    mpt_t *m;
    PyObject *out = NULL;
    Py_ssize_t i, npairs;
    if (!PyArg_ParseTuple(args, "Oy*O", &cap, &root, &pairs))
        return NULL;
    m = get_handle(cap);
    if (!m || root.len != 32) {
        PyErr_SetString(PyExc_ValueError, "bad handle or root");
        goto done;
    }
    fast = PySequence_Fast(pairs, "set_many needs a sequence of pairs");
    if (!fast) goto done;
    npairs = PySequence_Fast_GET_SIZE(fast);
    {
        arena_t *a = &m->arena;
        item_t *node = load_root(m, a, root.buf);
        if (!node) goto done;
        m->deferred = 1;
        for (i = 0; i < npairs; i++) {
            PyObject *pair = PySequence_Fast_GET_ITEM(fast, i);
            PyObject *ko, *vo;
            const uint8_t *kb, *vb;
            Py_ssize_t klen, vlen;
            uint8_t *nib;
            size_t nlen;
            if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) != 2) {
                PyErr_SetString(PyExc_TypeError,
                                "set_many pairs must be (key, value)");
                m->deferred = 0;
                goto done;
            }
            ko = PyTuple_GET_ITEM(pair, 0);
            vo = PyTuple_GET_ITEM(pair, 1);
            if (!PyBytes_Check(ko) || !PyBytes_Check(vo)) {
                PyErr_SetString(PyExc_TypeError,
                                "set_many keys/values must be bytes");
                m->deferred = 0;
                goto done;
            }
            kb = (const uint8_t *)PyBytes_AS_STRING(ko);
            klen = PyBytes_GET_SIZE(ko);
            vb = (const uint8_t *)PyBytes_AS_STRING(vo);
            vlen = PyBytes_GET_SIZE(vo);
            key_nibbles(a, kb, (size_t)klen, &nib, &nlen);
            if (!nib) { PyErr_NoMemory(); m->deferred = 0; goto done; }
            if (vlen == 0) {
                int changed = 0;
                node = trie_delete_node(m, a, node, nib, nlen, &changed);
            } else {
                node = trie_update(m, a, node, nib, nlen, vb,
                                   (size_t)vlen);
            }
            if (!node) { m->deferred = 0; goto done; }
        }
        m->deferred = 0;
        if (commit_kids(m, a, node) < 0) goto done;
        out = finish_root(m, a, node);
    }
done:
    if (m) arena_reset(&m->arena);
    Py_XDECREF(fast);
    PyBuffer_Release(&root);
    return out;
}

static PyObject *py_delete(PyObject *self, PyObject *args) {
    PyObject *cap;
    Py_buffer root, key;
    mpt_t *m;
    PyObject *out = NULL;
    if (!PyArg_ParseTuple(args, "Oy*y*", &cap, &root, &key)) return NULL;
    m = get_handle(cap);
    if (!m || root.len != 32) {
        PyErr_SetString(PyExc_ValueError, "bad handle or root");
        goto done;
    }
    {
        arena_t *a = &m->arena;
        item_t *node = load_root(m, a, root.buf);
        uint8_t *nib;
        size_t nlen;
        item_t *nroot;
        int changed = 0;
        if (!node) goto done;
        key_nibbles(a, key.buf, (size_t)key.len, &nib, &nlen);
        if (!nib) { PyErr_NoMemory(); goto done; }
        nroot = trie_delete_node(m, a, node, nib, nlen, &changed);
        if (!nroot) goto done;
        out = finish_root(m, a, nroot);
    }
done:
    if (m) arena_reset(&m->arena);
    PyBuffer_Release(&root);
    PyBuffer_Release(&key);
    return out;
}

static PyObject *py_get(PyObject *self, PyObject *args) {
    PyObject *cap;
    Py_buffer root, key;
    mpt_t *m;
    PyObject *out = NULL;
    if (!PyArg_ParseTuple(args, "Oy*y*", &cap, &root, &key)) return NULL;
    m = get_handle(cap);
    if (!m || root.len != 32) {
        PyErr_SetString(PyExc_ValueError, "bad handle or root");
        goto done;
    }
    {
        arena_t *a = &m->arena;
        item_t *node = load_root(m, a, root.buf);
        uint8_t *nib;
        size_t nlen;
        const uint8_t *val;
        size_t vlen;
        int rc;
        if (!node) goto done;
        key_nibbles(a, key.buf, (size_t)key.len, &nib, &nlen);
        if (!nib) { PyErr_NoMemory(); goto done; }
        rc = trie_get(m, a, node, nib, nlen, &val, &vlen);
        if (rc < 0) goto done;
        if (rc == 1) {
            out = Py_None;
            Py_INCREF(out);
        } else {
            /* mirror Python: empty value at a branch slot is None, and
             * values are returned as-is otherwise */
            if (vlen == 0) { out = Py_None; Py_INCREF(out); }
            else out = PyBytes_FromStringAndSize((const char *)val,
                                                 (Py_ssize_t)vlen);
        }
    }
done:
    if (m) arena_reset(&m->arena);
    PyBuffer_Release(&root);
    PyBuffer_Release(&key);
    return out;
}

static PyObject *py_proof(PyObject *self, PyObject *args) {
    PyObject *cap;
    Py_buffer root, key;
    mpt_t *m;
    PyObject *out = NULL;
    if (!PyArg_ParseTuple(args, "Oy*y*", &cap, &root, &key)) return NULL;
    m = get_handle(cap);
    if (!m || root.len != 32) {
        PyErr_SetString(PyExc_ValueError, "bad handle or root");
        goto done;
    }
    {
        arena_t *a = &m->arena;
        uint8_t *nib;
        size_t nlen;
        item_t *node;
        PyObject *lst = PyList_New(0);
        if (!lst) goto done;
        if (memcmp(root.buf, BLANK_ROOT_HASH, 32) == 0) {
            out = lst;
            goto done;
        }
        node = load_root(m, a, root.buf);
        if (!node) { Py_DECREF(lst); goto done; }
        key_nibbles(a, key.buf, (size_t)key.len, &nib, &nlen);
        if (!nib) { Py_DECREF(lst); PyErr_NoMemory(); goto done; }
        for (;;) {
            size_t enc_len;
            uint8_t *enc = rlp_encode_arena(a, node, &enc_len);
            PyObject *pb;
            if (!enc) { Py_DECREF(lst); PyErr_NoMemory(); goto done; }
            pb = PyBytes_FromStringAndSize((const char *)enc,
                                           (Py_ssize_t)enc_len);
            if (!pb || PyList_Append(lst, pb) < 0) {
                Py_XDECREF(pb); Py_DECREF(lst); goto done;
            }
            Py_DECREF(pb);
            if (item_is_blank(node)) break;
            if (IS_BRANCH(node)) {
                item_t *ref;
                if (nlen == 0) break;
                ref = node->kids[nib[0]];
                nib++; nlen--;
                if (item_is_blank(ref)) break;
                node = load_ref(m, a, ref);
                if (!node) { Py_DECREF(lst); goto done; }
                continue;
            }
            {
                size_t plen;
                int terminal;
                uint8_t *path = hp_decode_item(a, node->kids[0], &plen,
                                               &terminal);
                if (!path) { Py_DECREF(lst); goto done; }
                if (terminal || nlen < plen ||
                    memcmp(path, nib, plen) != 0)
                    break;
                nib += plen; nlen -= plen;
                node = load_ref(m, a, node->kids[1]);
                if (!node) { Py_DECREF(lst); goto done; }
            }
        }
        out = lst;
    }
done:
    if (m) arena_reset(&m->arena);
    PyBuffer_Release(&root);
    PyBuffer_Release(&key);
    return out;
}

/* recursive walk for items() */
#define WALK_PREFIX_MAX 1024

static int walk_node(mpt_t *m, arena_t *a, item_t *node,
                     uint8_t *prefix, size_t plen, PyObject *lst) {
    if (item_is_blank(node)) return 0;
    if (plen + 64 > WALK_PREFIX_MAX) {
        PyErr_SetString(PyExc_ValueError, "trie key too deep for walk");
        return -1;
    }
    if (IS_BRANCH(node)) {
        size_t i;
        if (!item_is_blank(node->kids[16])) {
            PyObject *k, *v, *t;
            uint8_t *kb = arena_alloc(a, plen / 2 ? plen / 2 : 1);
            if (!kb) { PyErr_NoMemory(); return -1; }
            for (i = 0; i < plen / 2; i++)
                kb[i] = (uint8_t)((prefix[2*i] << 4) | prefix[2*i+1]);
            k = PyBytes_FromStringAndSize((const char *)kb,
                                          (Py_ssize_t)(plen / 2));
            v = PyBytes_FromStringAndSize(
                (const char *)node->kids[16]->b,
                (Py_ssize_t)node->kids[16]->blen);
            if (!k || !v) { Py_XDECREF(k); Py_XDECREF(v); return -1; }
            t = PyTuple_Pack(2, k, v);
            Py_DECREF(k); Py_DECREF(v);
            if (!t || PyList_Append(lst, t) < 0) {
                Py_XDECREF(t);
                return -1;
            }
            Py_DECREF(t);
        }
        for (i = 0; i < 16; i++) {
            if (!item_is_blank(node->kids[i])) {
                item_t *child = load_ref(m, a, node->kids[i]);
                if (!child) return -1;
                prefix[plen] = (uint8_t)i;
                if (walk_node(m, a, child, prefix, plen + 1, lst) < 0)
                    return -1;
            }
        }
        return 0;
    }
    {
        size_t sublen, i;
        int terminal;
        uint8_t *sub = hp_decode_item(a, node->kids[0], &sublen, &terminal);
        if (!sub) return -1;
        if (plen + sublen + 1 > WALK_PREFIX_MAX) {
            PyErr_SetString(PyExc_ValueError, "trie key too deep for walk");
            return -1;
        }
        memcpy(prefix + plen, sub, sublen);
        if (terminal) {
            size_t tot = plen + sublen;
            uint8_t *kb = arena_alloc(a, tot / 2 ? tot / 2 : 1);
            PyObject *k, *v, *t;
            if (!kb) { PyErr_NoMemory(); return -1; }
            for (i = 0; i < tot / 2; i++)
                kb[i] = (uint8_t)((prefix[2*i] << 4) | prefix[2*i+1]);
            k = PyBytes_FromStringAndSize((const char *)kb,
                                          (Py_ssize_t)(tot / 2));
            v = PyBytes_FromStringAndSize((const char *)node->kids[1]->b,
                                          (Py_ssize_t)node->kids[1]->blen);
            if (!k || !v) { Py_XDECREF(k); Py_XDECREF(v); return -1; }
            t = PyTuple_Pack(2, k, v);
            Py_DECREF(k); Py_DECREF(v);
            if (!t || PyList_Append(lst, t) < 0) {
                Py_XDECREF(t);
                return -1;
            }
            Py_DECREF(t);
            return 0;
        }
        {
            item_t *child = load_ref(m, a, node->kids[1]);
            if (!child) return -1;
            return walk_node(m, a, child, prefix, plen + sublen, lst);
        }
    }
}

static PyObject *py_items(PyObject *self, PyObject *args) {
    PyObject *cap;
    Py_buffer root;
    mpt_t *m;
    PyObject *out = NULL;
    if (!PyArg_ParseTuple(args, "Oy*", &cap, &root)) return NULL;
    m = get_handle(cap);
    if (!m || root.len != 32) {
        PyErr_SetString(PyExc_ValueError, "bad handle or root");
        goto done;
    }
    {
        arena_t *a = &m->arena;
        item_t *node = load_root(m, a, root.buf);
        uint8_t *prefix = arena_alloc(a, 1024);  /* keys are short here */
        PyObject *lst;
        if (!node || !prefix) goto done;
        lst = PyList_New(0);
        if (!lst) goto done;
        if (walk_node(m, a, node, prefix, 0, lst) < 0) {
            Py_DECREF(lst);
            goto done;
        }
        out = lst;
    }
done:
    if (m) arena_reset(&m->arena);
    PyBuffer_Release(&root);
    return out;
}

static PyObject *py_drain(PyObject *self, PyObject *args) {
    PyObject *cap;
    mpt_t *m;
    PyObject *lst;
    size_t i;
    if (!PyArg_ParseTuple(args, "O", &cap)) return NULL;
    m = get_handle(cap);
    if (!m) { PyErr_SetString(PyExc_ValueError, "bad handle"); return NULL; }
    lst = PyList_New(0);
    if (!lst) return NULL;
    for (i = 0; i < m->fresh_n; i++) {
        slot_t *s = store_find(m, m->fresh[i]);
        PyObject *h, *b, *t;
        if (!s || !s->fresh) continue;  /* already drained (dup) */
        s->fresh = 0;
        h = PyBytes_FromStringAndSize((const char *)s->hash, 32);
        b = PyBytes_FromStringAndSize((const char *)s->blob,
                                      (Py_ssize_t)s->len);
        if (!h || !b) { Py_XDECREF(h); Py_XDECREF(b); Py_DECREF(lst); return NULL; }
        t = PyTuple_Pack(2, h, b);
        Py_DECREF(h); Py_DECREF(b);
        if (!t || PyList_Append(lst, t) < 0) {
            Py_XDECREF(t); Py_DECREF(lst);
            return NULL;
        }
        Py_DECREF(t);
    }
    m->fresh_n = 0;
    /* Evict ONLY here, between trie operations: during an operation the
     * arena holds item views into slot blobs, and freeing one mid-walk
     * would be a use-after-free.  After drain() every remaining node is
     * recoverable via the miss callback. */
    if (m->max_nodes && m->count > m->max_nodes &&
        m->miss_cb && m->miss_cb != Py_None)
        store_evict(m);
    return lst;
}

static PyObject *py_put_node(PyObject *self, PyObject *args) {
    PyObject *cap;
    Py_buffer hash, blob;
    mpt_t *m;
    if (!PyArg_ParseTuple(args, "Oy*y*", &cap, &hash, &blob)) return NULL;
    m = get_handle(cap);
    if (!m || hash.len != 32) {
        PyBuffer_Release(&hash);
        PyBuffer_Release(&blob);
        PyErr_SetString(PyExc_ValueError, "bad handle or hash");
        return NULL;
    }
    if (store_put(m, hash.buf, blob.buf, (size_t)blob.len, 0) < 0) {
        PyBuffer_Release(&hash);
        PyBuffer_Release(&blob);
        return PyErr_NoMemory();
    }
    PyBuffer_Release(&hash);
    PyBuffer_Release(&blob);
    Py_RETURN_NONE;
}

static PyMethodDef methods[] = {
    {"new", py_new, METH_VARARGS,
     "new(miss_cb=None, max_nodes=2**18) -> handle; max_nodes=0 disables\n"
     "eviction (only safe without a durable KV backing the miss_cb)"},
    {"blank_root", py_blank_root, METH_NOARGS, "empty-trie root hash"},
    {"set", py_set, METH_VARARGS, "set(h, root, key, value) -> new root"},
    {"set_many", py_set_many, METH_VARARGS,
     "set_many(h, root, [(key, value), ...]) -> new root; one deferred-"
     "hash pass (empty value deletes)"},
    {"delete", py_delete, METH_VARARGS, "delete(h, root, key) -> new root"},
    {"get", py_get, METH_VARARGS, "get(h, root, key) -> bytes | None"},
    {"proof", py_proof, METH_VARARGS, "proof(h, root, key) -> [blob]"},
    {"items", py_items, METH_VARARGS, "items(h, root) -> [(k, v)]"},
    {"drain", py_drain, METH_VARARGS,
     "drain(h) -> [(hash, blob)] created since last drain"},
    {"put_node", py_put_node, METH_VARARGS, "put_node(h, hash, blob)"},
    {NULL, NULL, 0, NULL}
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "mpt_c",
    "native Merkle Patricia Trie (state hot path)", -1, methods
};

PyMODINIT_FUNC PyInit_mpt_c(void) {
    ensure_blank_root();
    return PyModule_Create(&moduledef);
}
