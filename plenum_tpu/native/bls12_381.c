/* BLS12-381 native arithmetic — the framework's scalar-floor pairing path.
 *
 * Fills the role Hyperledger Ursa (Rust) plays for the reference
 * (crypto/bls/indy_crypto/bls_crypto_indy_crypto.py): field towers,
 * curve groups and the pairing in portable C (uint128 limb arithmetic,
 * Montgomery multiplication). Python (plenum_tpu/crypto/bls_native.py)
 * orchestrates hashing/serialization and falls back to the pure-Python
 * module when no C compiler is available.
 *
 * Conventions at the ABI boundary:
 *  - field elements: 48-byte big-endian integers (non-Montgomery)
 *  - G1 point: 96 bytes x||y, all-zero = infinity
 *  - G2 point: 192 bytes x.c0||x.c1||y.c0||y.c1, all-zero = infinity
 *  - scalars: 32-byte big-endian
 *  - the final exponentiation computes f^(3·(q^4-q^2+1)/r) via the
 *    Hayashida–Hayasaka–Teruya decomposition (x-1)^2(x+q)(x^2+q^2-1)+3 —
 *    a fixed cube power of the standard ate pairing, so products and
 *    is-one checks are unchanged (3 does not divide r).
 */
#include <stdint.h>
#include <string.h>

typedef uint64_t u64;
typedef unsigned __int128 u128;
typedef uint8_t u8;

#define NL 6

static const u64 Qm[NL] = {
    0xb9feffffffffaaabULL, 0x1eabfffeb153ffffULL, 0x6730d2a0f6b0f624ULL,
    0x64774b84f38512bfULL, 0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL};
static const u64 R2[NL] = {
    0xf4df1f341c341746ULL, 0x0a76e6a609d104f1ULL, 0x8de5476c4c95b6d5ULL,
    0x67eb88a9939d83c0ULL, 0x9a793e85b519952dULL, 0x11988fe592cae3aaULL};
static const u64 N0 = 0x89f3fffcfffcfffdULL;
static const u64 ONE_M[NL] = {
    0x760900000002fffdULL, 0xebf4000bc40c0002ULL, 0x5f48985753c758baULL,
    0x77ce585370525745ULL, 0x5c071a97a256ec6dULL, 0x15f65ec3fa80e493ULL};
static const u64 X_ABS = 0xd201000000010000ULL;

/* ------------------------------------------------------------------ fp */

typedef struct { u64 l[NL]; } fp;

static const fp FP_ZERO = {{0, 0, 0, 0, 0, 0}};

static int fp_is_zero(const fp *a) {
    u64 acc = 0;
    for (int i = 0; i < NL; i++) acc |= a->l[i];
    return acc == 0;
}

static int fp_eq(const fp *a, const fp *b) {
    u64 acc = 0;
    for (int i = 0; i < NL; i++) acc |= a->l[i] ^ b->l[i];
    return acc == 0;
}

static int fp_geq_q(const u64 *t) {
    for (int i = NL - 1; i >= 0; i--) {
        if (t[i] > Qm[i]) return 1;
        if (t[i] < Qm[i]) return 0;
    }
    return 1;
}

static void fp_add(fp *r, const fp *a, const fp *b) {
    u128 c = 0;
    u64 t[NL];
    for (int i = 0; i < NL; i++) {
        c += (u128)a->l[i] + b->l[i];
        t[i] = (u64)c;
        c >>= 64;
    }
    if (c || fp_geq_q(t)) {
        u128 br = 0;
        for (int i = 0; i < NL; i++) {
            u128 d = (u128)t[i] - Qm[i] - br;
            t[i] = (u64)d;
            br = (d >> 64) & 1;
        }
    }
    memcpy(r->l, t, sizeof t);
}

static void fp_sub(fp *r, const fp *a, const fp *b) {
    u128 br = 0;
    u64 t[NL];
    for (int i = 0; i < NL; i++) {
        u128 d = (u128)a->l[i] - b->l[i] - br;
        t[i] = (u64)d;
        br = (d >> 64) & 1;
    }
    if (br) {
        u128 c = 0;
        for (int i = 0; i < NL; i++) {
            c += (u128)t[i] + Qm[i];
            t[i] = (u64)c;
            c >>= 64;
        }
    }
    memcpy(r->l, t, sizeof t);
}

static void fp_neg(fp *r, const fp *a) {
    if (fp_is_zero(a)) { *r = *a; return; }
    u128 br = 0;
    for (int i = 0; i < NL; i++) {
        u128 d = (u128)Qm[i] - a->l[i] - br;
        r->l[i] = (u64)d;
        br = (d >> 64) & 1;
    }
}

/* CIOS Montgomery multiplication (Q < 2^382 = R/4 ⇒ one final sub). */
static void fp_mul(fp *r, const fp *a, const fp *b) {
    u64 t[NL + 2];
    memset(t, 0, sizeof t);
    for (int i = 0; i < NL; i++) {
        u128 c = 0;
        for (int j = 0; j < NL; j++) {
            c += (u128)a->l[j] * b->l[i] + t[j];
            t[j] = (u64)c;
            c >>= 64;
        }
        c += t[NL];
        t[NL] = (u64)c;
        t[NL + 1] = (u64)(c >> 64);
        u64 m = t[0] * N0;
        c = (u128)m * Qm[0] + t[0];
        c >>= 64;
        for (int j = 1; j < NL; j++) {
            c += (u128)m * Qm[j] + t[j];
            t[j - 1] = (u64)c;
            c >>= 64;
        }
        c += t[NL];
        t[NL - 1] = (u64)c;
        t[NL] = t[NL + 1] + (u64)(c >> 64);
        t[NL + 1] = 0;
    }
    if (t[NL] || fp_geq_q(t)) {
        u128 br = 0;
        for (int i = 0; i < NL; i++) {
            u128 d = (u128)t[i] - Qm[i] - br;
            t[i] = (u64)d;
            br = (d >> 64) & 1;
        }
    }
    memcpy(r->l, t, NL * sizeof(u64));
}

static void fp_sqr(fp *r, const fp *a) { fp_mul(r, a, a); }

/* ---- raw (non-Montgomery) 6-limb helpers for ext-gcd inversion ---- */

static int raw_is_one(const u64 *a) {
    if (a[0] != 1) return 0;
    for (int i = 1; i < NL; i++) if (a[i]) return 0;
    return 1;
}

static int raw_geq(const u64 *a, const u64 *b) {
    for (int i = NL - 1; i >= 0; i--) {
        if (a[i] > b[i]) return 1;
        if (a[i] < b[i]) return 0;
    }
    return 1;
}

static void raw_sub(u64 *r, const u64 *a, const u64 *b) {
    u128 br = 0;
    for (int i = 0; i < NL; i++) {
        u128 d = (u128)a[i] - b[i] - br;
        r[i] = (u64)d;
        br = (d >> 64) & 1;
    }
}

static void raw_shr1(u64 *a) {
    for (int i = 0; i < NL - 1; i++)
        a[i] = (a[i] >> 1) | (a[i + 1] << 63);
    a[NL - 1] >>= 1;
}

static void raw_half_mod_q(u64 *a) {
    /* a/2 mod q: if odd, add q first (q odd ⇒ a+q even; carry bit
     * shifts back in) */
    if (a[0] & 1) {
        u128 c = 0;
        for (int i = 0; i < NL; i++) {
            c += (u128)a[i] + Qm[i];
            a[i] = (u64)c;
            c >>= 64;
        }
        raw_shr1(a);
        if (c) a[NL - 1] |= 1ULL << 63;
    } else {
        raw_shr1(a);
    }
}

static void raw_sub_mod_q(u64 *r, const u64 *a, const u64 *b) {
    u128 br = 0;
    for (int i = 0; i < NL; i++) {
        u128 d = (u128)a[i] - b[i] - br;
        r[i] = (u64)d;
        br = (d >> 64) & 1;
    }
    if (br) {
        u128 c = 0;
        for (int i = 0; i < NL; i++) {
            c += (u128)r[i] + Qm[i];
            r[i] = (u64)c;
            c >>= 64;
        }
    }
}

/* Binary extended Euclid (variable-time — all inputs are public
 * consensus data). ~15x faster than Fermat exponentiation. */
static void fp_inv(fp *r, const fp *a) {
    fp one = {{1, 0, 0, 0, 0, 0}}, raw;
    fp_mul(&raw, a, &one);              /* from Montgomery */
    u64 u[NL], v[NL], x1[NL], x2[NL];
    memcpy(u, raw.l, sizeof u);
    memcpy(v, Qm, sizeof v);
    memset(x1, 0, sizeof x1); x1[0] = 1;
    memset(x2, 0, sizeof x2);
    if (fp_is_zero(&raw)) { *r = FP_ZERO; return; }
    while (!raw_is_one(u) && !raw_is_one(v)) {
        while (!(u[0] & 1)) { raw_shr1(u); raw_half_mod_q(x1); }
        while (!(v[0] & 1)) { raw_shr1(v); raw_half_mod_q(x2); }
        if (raw_geq(u, v)) {
            raw_sub(u, u, v);
            raw_sub_mod_q(x1, x1, x2);
        } else {
            raw_sub(v, v, u);
            raw_sub_mod_q(x2, x2, x1);
        }
    }
    fp res;
    memcpy(res.l, raw_is_one(u) ? x1 : x2, sizeof res.l);
    fp r2m; memcpy(r2m.l, R2, sizeof R2);
    fp_mul(r, &res, &r2m);              /* back to Montgomery */
}

static void fp_from_bytes(fp *r, const u8 *in48) {
    fp raw;
    for (int i = 0; i < NL; i++) {
        u64 v = 0;
        for (int j = 0; j < 8; j++)
            v = (v << 8) | in48[(NL - 1 - i) * 8 + j];
        raw.l[i] = v;
    }
    fp r2; memcpy(r2.l, R2, sizeof R2);
    fp_mul(r, &raw, &r2);   /* to Montgomery */
}

static void fp_to_bytes(u8 *out48, const fp *a) {
    fp one = {{1, 0, 0, 0, 0, 0}}, raw;
    fp_mul(&raw, a, &one);  /* from Montgomery */
    for (int i = 0; i < NL; i++) {
        u64 v = raw.l[NL - 1 - i];
        for (int j = 0; j < 8; j++)
            out48[i * 8 + j] = (u8)(v >> (56 - 8 * j));
    }
}

/* ----------------------------------------------------------------- fp2 */
/* fq2 = fp[u]/(u^2+1) */

typedef struct { fp c0, c1; } fp2;

static void fp2_add(fp2 *r, const fp2 *a, const fp2 *b) {
    fp_add(&r->c0, &a->c0, &b->c0);
    fp_add(&r->c1, &a->c1, &b->c1);
}

static void fp2_sub(fp2 *r, const fp2 *a, const fp2 *b) {
    fp_sub(&r->c0, &a->c0, &b->c0);
    fp_sub(&r->c1, &a->c1, &b->c1);
}

static void fp2_neg(fp2 *r, const fp2 *a) {
    fp_neg(&r->c0, &a->c0);
    fp_neg(&r->c1, &a->c1);
}

static void fp2_mul(fp2 *r, const fp2 *a, const fp2 *b) {
    fp t0, t1, t2, t3;
    fp_mul(&t0, &a->c0, &b->c0);
    fp_mul(&t1, &a->c1, &b->c1);
    fp_add(&t2, &a->c0, &a->c1);
    fp_add(&t3, &b->c0, &b->c1);
    fp_mul(&t2, &t2, &t3);      /* (a0+a1)(b0+b1) */
    fp_sub(&t2, &t2, &t0);
    fp_sub(&t2, &t2, &t1);      /* a0b1 + a1b0 */
    fp_sub(&r->c0, &t0, &t1);
    r->c1 = t2;
}

static void fp2_sqr(fp2 *r, const fp2 *a) {
    /* complex squaring: (a0+a1u)² = (a0+a1)(a0−a1) + 2a0a1·u —
     * 2 base mults instead of fp2_mul's 3 */
    fp s, d, t;
    fp_add(&s, &a->c0, &a->c1);
    fp_sub(&d, &a->c0, &a->c1);
    fp_mul(&t, &a->c0, &a->c1);
    fp_mul(&r->c0, &s, &d);
    fp_add(&r->c1, &t, &t);
}

static void fp2_mul_fp(fp2 *r, const fp2 *a, const fp *b) {
    fp_mul(&r->c0, &a->c0, b);
    fp_mul(&r->c1, &a->c1, b);
}

static void fp2_conj(fp2 *r, const fp2 *a) {
    r->c0 = a->c0;
    fp_neg(&r->c1, &a->c1);
}

static void fp2_inv(fp2 *r, const fp2 *a) {
    fp t0, t1;
    fp_sqr(&t0, &a->c0);
    fp_sqr(&t1, &a->c1);
    fp_add(&t0, &t0, &t1);      /* c0^2 + c1^2 */
    fp_inv(&t0, &t0);
    fp_mul(&r->c0, &a->c0, &t0);
    fp_mul(&t1, &a->c1, &t0);
    fp_neg(&r->c1, &t1);
}

/* ξ = 1 + u */
static void fp2_mul_nonres(fp2 *r, const fp2 *a) {
    fp t0;
    fp_sub(&t0, &a->c0, &a->c1);
    fp_add(&r->c1, &a->c0, &a->c1);
    r->c0 = t0;
}

static int fp2_is_zero(const fp2 *a) {
    return fp_is_zero(&a->c0) && fp_is_zero(&a->c1);
}

static int fp2_eq(const fp2 *a, const fp2 *b) {
    return fp_eq(&a->c0, &b->c0) && fp_eq(&a->c1, &b->c1);
}

/* ----------------------------------------------------------------- fp6 */
/* fq6 = fq2[v]/(v^3 - ξ) */

typedef struct { fp2 c0, c1, c2; } fp6;

static void fp6_add(fp6 *r, const fp6 *a, const fp6 *b) {
    fp2_add(&r->c0, &a->c0, &b->c0);
    fp2_add(&r->c1, &a->c1, &b->c1);
    fp2_add(&r->c2, &a->c2, &b->c2);
}

static void fp6_sub(fp6 *r, const fp6 *a, const fp6 *b) {
    fp2_sub(&r->c0, &a->c0, &b->c0);
    fp2_sub(&r->c1, &a->c1, &b->c1);
    fp2_sub(&r->c2, &a->c2, &b->c2);
}

static void fp6_neg(fp6 *r, const fp6 *a) {
    fp2_neg(&r->c0, &a->c0);
    fp2_neg(&r->c1, &a->c1);
    fp2_neg(&r->c2, &a->c2);
}

static void fp6_mul(fp6 *r, const fp6 *a, const fp6 *b) {
    fp2 t0, t1, t2, s, u0, u1, u2;
    fp2_mul(&t0, &a->c0, &b->c0);
    fp2_mul(&t1, &a->c1, &b->c1);
    fp2_mul(&t2, &a->c2, &b->c2);
    /* c0 = t0 + ξ((a1+a2)(b1+b2) - t1 - t2) */
    fp2_add(&u0, &a->c1, &a->c2);
    fp2_add(&u1, &b->c1, &b->c2);
    fp2_mul(&s, &u0, &u1);
    fp2_sub(&s, &s, &t1);
    fp2_sub(&s, &s, &t2);
    fp2_mul_nonres(&s, &s);
    fp2_add(&u0, &s, &t0);
    /* c1 = (a0+a1)(b0+b1) - t0 - t1 + ξ t2 */
    fp2 v0, v1;
    fp2_add(&v0, &a->c0, &a->c1);
    fp2_add(&v1, &b->c0, &b->c1);
    fp2_mul(&s, &v0, &v1);
    fp2_sub(&s, &s, &t0);
    fp2_sub(&s, &s, &t1);
    fp2_mul_nonres(&v0, &t2);
    fp2_add(&u1, &s, &v0);
    /* c2 = (a0+a2)(b0+b2) - t0 - t2 + t1 */
    fp2_add(&v0, &a->c0, &a->c2);
    fp2_add(&v1, &b->c0, &b->c2);
    fp2_mul(&s, &v0, &v1);
    fp2_sub(&s, &s, &t0);
    fp2_sub(&s, &s, &t2);
    fp2_add(&u2, &s, &t1);
    r->c0 = u0; r->c1 = u1; r->c2 = u2;
}

static void fp6_mul_nonres(fp6 *r, const fp6 *a) {
    /* ×v: (c0, c1, c2) -> (ξ c2, c0, c1) */
    fp2 t;
    fp2_mul_nonres(&t, &a->c2);
    r->c2 = a->c1;
    r->c1 = a->c0;
    r->c0 = t;
}

static void fp6_inv(fp6 *r, const fp6 *a) {
    /* standard tower inversion */
    fp2 A, B, C, t0, t1, t2, F;
    fp2_sqr(&t0, &a->c0);
    fp2_mul(&t1, &a->c1, &a->c2);
    fp2_mul_nonres(&t2, &t1);
    fp2_sub(&A, &t0, &t2);                 /* c0^2 - ξ c1 c2 */
    fp2_sqr(&t0, &a->c2);
    fp2_mul_nonres(&t0, &t0);
    fp2_mul(&t1, &a->c0, &a->c1);
    fp2_sub(&B, &t0, &t1);                 /* ξ c2^2 - c0 c1 */
    fp2_sqr(&t0, &a->c1);
    fp2_mul(&t1, &a->c0, &a->c2);
    fp2_sub(&C, &t0, &t1);                 /* c1^2 - c0 c2 */
    fp2_mul(&t0, &a->c2, &B);
    fp2_mul(&t1, &a->c1, &C);
    fp2_add(&t0, &t0, &t1);
    fp2_mul_nonres(&t0, &t0);
    fp2_mul(&t1, &a->c0, &A);
    fp2_add(&F, &t0, &t1);                 /* c0 A + ξ(c2 B + c1 C) */
    fp2_inv(&F, &F);
    fp2_mul(&r->c0, &A, &F);
    fp2_mul(&r->c1, &B, &F);
    fp2_mul(&r->c2, &C, &F);
}

static int fp6_is_zero(const fp6 *a) {
    return fp2_is_zero(&a->c0) && fp2_is_zero(&a->c1) && fp2_is_zero(&a->c2);
}

/* ---------------------------------------------------------------- fp12 */
/* fq12 = fq6[w]/(w^2 - v) */

typedef struct { fp6 c0, c1; } fp12;

static void fp12_mul(fp12 *r, const fp12 *a, const fp12 *b) {
    fp6 t0, t1, t2, t3;
    fp6_mul(&t0, &a->c0, &b->c0);
    fp6_mul(&t1, &a->c1, &b->c1);
    fp6_add(&t2, &a->c0, &a->c1);
    fp6_add(&t3, &b->c0, &b->c1);
    fp6_mul(&t2, &t2, &t3);
    fp6_sub(&t2, &t2, &t0);
    fp6_sub(&t2, &t2, &t1);                /* a0 b1 + a1 b0 */
    fp6_mul_nonres(&t1, &t1);
    fp6_add(&r->c0, &t0, &t1);
    r->c1 = t2;
}

static void fp12_sqr(fp12 *r, const fp12 *a) {
    /* Karatsuba-style: (c0 + c1 w)², w² = v —
     * 2 fp6_muls instead of fp12_mul's 3 */
    fp6 t, s0, s1;
    fp6_mul(&t, &a->c0, &a->c1);
    fp6_add(&s0, &a->c0, &a->c1);
    fp6_mul_nonres(&s1, &a->c1);
    fp6_add(&s1, &s1, &a->c0);
    fp6_mul(&s0, &s0, &s1);             /* (c0+c1)(c0+v c1) */
    fp6_sub(&s0, &s0, &t);
    fp6 vt;
    fp6_mul_nonres(&vt, &t);
    fp6_sub(&r->c0, &s0, &vt);
    fp6_add(&r->c1, &t, &t);
}

static void fp12_conj(fp12 *r, const fp12 *a) {
    r->c0 = a->c0;
    fp6_neg(&r->c1, &a->c1);
}

static void fp12_inv(fp12 *r, const fp12 *a) {
    fp6 t0, t1;
    fp6_mul(&t0, &a->c0, &a->c0);
    fp6_mul(&t1, &a->c1, &a->c1);
    fp6_mul_nonres(&t1, &t1);
    fp6_sub(&t0, &t0, &t1);
    fp6_inv(&t0, &t0);
    fp6_mul(&r->c0, &a->c0, &t0);
    fp6_mul(&t1, &a->c1, &t0);
    fp6_neg(&r->c1, &t1);
}

static void fp12_one(fp12 *r) {
    memset(r, 0, sizeof *r);
    memcpy(r->c0.c0.c0.l, ONE_M, sizeof ONE_M);
}

static int fp12_is_one(const fp12 *a) {
    fp one;
    memcpy(one.l, ONE_M, sizeof ONE_M);
    if (!fp_eq(&a->c0.c0.c0, &one)) return 0;
    if (!fp_is_zero(&a->c0.c0.c1)) return 0;
    if (!fp2_is_zero(&a->c0.c1) || !fp2_is_zero(&a->c0.c2)) return 0;
    return fp6_is_zero(&a->c1);
}

/* -------------------------------------------------------- frobenius */

static fp2 FROB_G[6];       /* γ_k = ξ^(k(q-1)/6), k = 0..5 */
static int frob_ready = 0;

/* fq2 pow by big-endian byte exponent */
static void fp2_pow_bytes(fp2 *r, const fp2 *a, const u8 *e, int elen) {
    fp2 acc;
    memset(&acc, 0, sizeof acc);
    memcpy(acc.c0.l, ONE_M, sizeof ONE_M);
    for (int i = 0; i < elen; i++) {
        for (int b = 7; b >= 0; b--) {
            fp2_sqr(&acc, &acc);
            if ((e[i] >> b) & 1) fp2_mul(&acc, &acc, a);
        }
    }
    *r = acc;
}

static void frob_init(void) {
    if (frob_ready) return;
    /* (q-1)/6 as 48-byte BE: computed from Q limbs */
    u8 e[48];
    /* q-1 then divide by 6 via simple big-int ops on bytes */
    u64 limbs[NL];
    memcpy(limbs, Qm, sizeof Qm);
    limbs[0] -= 1;                       /* q-1 (no borrow: low limb odd) */
    /* divide by 6, big-endian long division over 64-bit limbs */
    u128 rem = 0;
    u64 quot[NL];
    for (int i = NL - 1; i >= 0; i--) {
        u128 cur = (rem << 64) | limbs[i];
        quot[i] = (u64)(cur / 6);
        rem = cur % 6;
    }
    for (int i = 0; i < NL; i++) {
        u64 v = quot[NL - 1 - i];
        for (int j = 0; j < 8; j++)
            e[i * 8 + j] = (u8)(v >> (56 - 8 * j));
    }
    fp2 xi;
    memset(&xi, 0, sizeof xi);
    memcpy(xi.c0.l, ONE_M, sizeof ONE_M);  /* ξ = 1 + u */
    memcpy(xi.c1.l, ONE_M, sizeof ONE_M);
    fp2 g1;
    fp2_pow_bytes(&g1, &xi, e, 48);
    memset(&FROB_G[0], 0, sizeof(fp2));
    memcpy(FROB_G[0].c0.l, ONE_M, sizeof ONE_M);
    FROB_G[1] = g1;
    for (int k = 2; k < 6; k++)
        fp2_mul(&FROB_G[k], &FROB_G[k - 1], &g1);
    frob_ready = 1;
}

/* f^q: conjugate every fq2 coefficient, multiply coefficient of w^k by
 * γ_k. Basis map: c0 = (w^0, w^2, w^4), c1 = (w^1, w^3, w^5). */
static void fp12_frob(fp12 *r, const fp12 *a) {
    fp2 t;
    fp2_conj(&t, &a->c0.c0); r->c0.c0 = t;
    fp2_conj(&t, &a->c0.c1); fp2_mul(&r->c0.c1, &t, &FROB_G[2]);
    fp2_conj(&t, &a->c0.c2); fp2_mul(&r->c0.c2, &t, &FROB_G[4]);
    fp2_conj(&t, &a->c1.c0); fp2_mul(&r->c1.c0, &t, &FROB_G[1]);
    fp2_conj(&t, &a->c1.c1); fp2_mul(&r->c1.c1, &t, &FROB_G[3]);
    fp2_conj(&t, &a->c1.c2); fp2_mul(&r->c1.c2, &t, &FROB_G[5]);
}

/* ------------------------------------------------------------- groups */

typedef struct { fp x, y; int inf; } g1;
typedef struct { fp2 x, y; int inf; } g2;

static void g1_add_aff(g1 *r, const g1 *p, const g1 *q) {
    if (p->inf) { *r = *q; return; }
    if (q->inf) { *r = *p; return; }
    fp lam, t0, t1;
    if (fp_eq(&p->x, &q->x)) {
        fp ysum;
        fp_add(&ysum, &p->y, &q->y);
        if (fp_is_zero(&ysum)) { r->inf = 1; r->x = FP_ZERO; r->y = FP_ZERO; return; }
        fp_sqr(&t0, &p->x);
        fp_add(&t1, &t0, &t0);
        fp_add(&t0, &t1, &t0);          /* 3x² */
        fp_add(&t1, &p->y, &p->y);
        fp_inv(&t1, &t1);
        fp_mul(&lam, &t0, &t1);
    } else {
        fp_sub(&t0, &q->y, &p->y);
        fp_sub(&t1, &q->x, &p->x);
        fp_inv(&t1, &t1);
        fp_mul(&lam, &t0, &t1);
    }
    fp x3, y3;
    fp_sqr(&x3, &lam);
    fp_sub(&x3, &x3, &p->x);
    fp_sub(&x3, &x3, &q->x);
    fp_sub(&t0, &p->x, &x3);
    fp_mul(&y3, &lam, &t0);
    fp_sub(&y3, &y3, &p->y);
    r->x = x3; r->y = y3; r->inf = 0;
}

/* Jacobian coordinates for inversion-free scalar multiplication
 * (a = 0 curve): one field inversion at the very end. */
typedef struct { fp X, Y, Z; } g1j;   /* Z = 0 ⇒ infinity */

static void g1j_dbl(g1j *r, const g1j *p) {
    if (fp_is_zero(&p->Z)) { *r = *p; return; }
    fp A, B, C, D, E, F, t0, t1;
    fp_sqr(&A, &p->X);
    fp_sqr(&B, &p->Y);
    fp_sqr(&C, &B);
    fp_add(&t0, &p->X, &B);
    fp_sqr(&t0, &t0);
    fp_sub(&t0, &t0, &A);
    fp_sub(&t0, &t0, &C);
    fp_add(&D, &t0, &t0);               /* 2((X+B)²−A−C) */
    fp_add(&E, &A, &A);
    fp_add(&E, &E, &A);                 /* 3A */
    fp_sqr(&F, &E);
    fp_sub(&t0, &F, &D);
    fp_sub(&r->X, &t0, &D);             /* F − 2D */
    fp_sub(&t0, &D, &r->X);
    fp_mul(&t0, &E, &t0);
    fp_add(&t1, &C, &C);
    fp_add(&t1, &t1, &t1);
    fp_add(&t1, &t1, &t1);              /* 8C */
    fp_mul(&C, &p->Y, &p->Z);
    fp_sub(&r->Y, &t0, &t1);
    fp_add(&r->Z, &C, &C);              /* 2YZ */
}

/* mixed addition r = p + (x2, y2) affine (madd-2007-bl) */
static void g1j_madd(g1j *r, const g1j *p, const fp *x2, const fp *y2) {
    if (fp_is_zero(&p->Z)) {
        r->X = *x2; r->Y = *y2;
        memcpy(r->Z.l, ONE_M, sizeof ONE_M);
        return;
    }
    fp Z1Z1, U2, S2, H, HH, I, J, rr, V, t0, t1;
    fp_sqr(&Z1Z1, &p->Z);
    fp_mul(&U2, x2, &Z1Z1);
    fp_mul(&S2, y2, &p->Z);
    fp_mul(&S2, &S2, &Z1Z1);
    fp_sub(&H, &U2, &p->X);
    fp_sub(&t0, &S2, &p->Y);
    if (fp_is_zero(&H)) {
        if (fp_is_zero(&t0)) { g1j_dbl(r, p); return; }
        r->X = FP_ZERO; r->Y = FP_ZERO; r->Z = FP_ZERO;  /* infinity */
        return;
    }
    fp_sqr(&HH, &H);
    fp_add(&I, &HH, &HH);
    fp_add(&I, &I, &I);                 /* 4HH */
    fp_mul(&J, &H, &I);
    fp_add(&rr, &t0, &t0);              /* 2(S2−Y1) */
    fp_mul(&V, &p->X, &I);
    fp_sqr(&t0, &rr);
    fp_sub(&t0, &t0, &J);
    fp_sub(&t0, &t0, &V);
    fp_sub(&r->X, &t0, &V);             /* rr²−J−2V */
    fp_sub(&t0, &V, &r->X);
    fp_mul(&t0, &rr, &t0);
    fp_mul(&t1, &p->Y, &J);
    fp_add(&t1, &t1, &t1);
    fp_sub(&r->Y, &t0, &t1);            /* rr(V−X3)−2Y1J */
    fp_add(&t0, &p->Z, &H);
    fp_sqr(&t0, &t0);
    fp_sub(&t0, &t0, &Z1Z1);
    fp_sub(&r->Z, &t0, &HH);            /* (Z1+H)²−Z1Z1−HH */
}

static void g1_mul_scalar(g1 *r, const g1 *p, const u8 *k32) {
    if (p->inf) { *r = *p; return; }
    g1j acc = {FP_ZERO, FP_ZERO, FP_ZERO};
    int started = 0;
    for (int i = 0; i < 32; i++) {       /* big-endian, MSB first */
        for (int b = 7; b >= 0; b--) {
            if (started) g1j_dbl(&acc, &acc);
            if ((k32[i] >> b) & 1) {
                g1j_madd(&acc, &acc, &p->x, &p->y);
                started = 1;
            }
        }
    }
    if (!started || fp_is_zero(&acc.Z)) {
        r->inf = 1; r->x = FP_ZERO; r->y = FP_ZERO;
        return;
    }
    fp zi, zi2, zi3;
    fp_inv(&zi, &acc.Z);
    fp_sqr(&zi2, &zi);
    fp_mul(&zi3, &zi2, &zi);
    fp_mul(&r->x, &acc.X, &zi2);
    fp_mul(&r->y, &acc.Y, &zi3);
    r->inf = 0;
}

static void g2_add_aff(g2 *r, const g2 *p, const g2 *q) {
    if (p->inf) { *r = *q; return; }
    if (q->inf) { *r = *p; return; }
    fp2 lam, t0, t1;
    if (fp2_eq(&p->x, &q->x)) {
        fp2 ysum;
        fp2_add(&ysum, &p->y, &q->y);
        if (fp2_is_zero(&ysum)) { memset(r, 0, sizeof *r); r->inf = 1; return; }
        fp2_sqr(&t0, &p->x);
        fp2_add(&t1, &t0, &t0);
        fp2_add(&t0, &t1, &t0);
        fp2_add(&t1, &p->y, &p->y);
        fp2_inv(&t1, &t1);
        fp2_mul(&lam, &t0, &t1);
    } else {
        fp2_sub(&t0, &q->y, &p->y);
        fp2_sub(&t1, &q->x, &p->x);
        fp2_inv(&t1, &t1);
        fp2_mul(&lam, &t0, &t1);
    }
    fp2 x3, y3;
    fp2_sqr(&x3, &lam);
    fp2_sub(&x3, &x3, &p->x);
    fp2_sub(&x3, &x3, &q->x);
    fp2_sub(&t0, &p->x, &x3);
    fp2_mul(&y3, &lam, &t0);
    fp2_sub(&y3, &y3, &p->y);
    r->x = x3; r->y = y3; r->inf = 0;
}

typedef struct { fp2 X, Y, Z; } g2j;

static void g2j_dbl(g2j *r, const g2j *p) {
    if (fp2_is_zero(&p->Z)) { *r = *p; return; }
    fp2 A, B, C, D, E, F, t0, t1;
    fp2_sqr(&A, &p->X);
    fp2_sqr(&B, &p->Y);
    fp2_sqr(&C, &B);
    fp2_add(&t0, &p->X, &B);
    fp2_sqr(&t0, &t0);
    fp2_sub(&t0, &t0, &A);
    fp2_sub(&t0, &t0, &C);
    fp2_add(&D, &t0, &t0);
    fp2_add(&E, &A, &A);
    fp2_add(&E, &E, &A);
    fp2_sqr(&F, &E);
    fp2_sub(&t0, &F, &D);
    fp2_sub(&r->X, &t0, &D);
    fp2_sub(&t0, &D, &r->X);
    fp2_mul(&t0, &E, &t0);
    fp2_add(&t1, &C, &C);
    fp2_add(&t1, &t1, &t1);
    fp2_add(&t1, &t1, &t1);
    fp2_mul(&C, &p->Y, &p->Z);
    fp2_sub(&r->Y, &t0, &t1);
    fp2_add(&r->Z, &C, &C);
}

static void g2j_madd(g2j *r, const g2j *p, const fp2 *x2, const fp2 *y2) {
    if (fp2_is_zero(&p->Z)) {
        r->X = *x2; r->Y = *y2;
        memset(&r->Z, 0, sizeof r->Z);
        memcpy(r->Z.c0.l, ONE_M, sizeof ONE_M);
        return;
    }
    fp2 Z1Z1, U2, S2, H, HH, I, J, rr, V, t0, t1;
    fp2_sqr(&Z1Z1, &p->Z);
    fp2_mul(&U2, x2, &Z1Z1);
    fp2_mul(&S2, y2, &p->Z);
    fp2_mul(&S2, &S2, &Z1Z1);
    fp2_sub(&H, &U2, &p->X);
    fp2_sub(&t0, &S2, &p->Y);
    if (fp2_is_zero(&H)) {
        if (fp2_is_zero(&t0)) { g2j_dbl(r, p); return; }
        memset(r, 0, sizeof *r);
        return;
    }
    fp2_sqr(&HH, &H);
    fp2_add(&I, &HH, &HH);
    fp2_add(&I, &I, &I);
    fp2_mul(&J, &H, &I);
    fp2_add(&rr, &t0, &t0);
    fp2_mul(&V, &p->X, &I);
    fp2_sqr(&t0, &rr);
    fp2_sub(&t0, &t0, &J);
    fp2_sub(&t0, &t0, &V);
    fp2_sub(&r->X, &t0, &V);
    fp2_sub(&t0, &V, &r->X);
    fp2_mul(&t0, &rr, &t0);
    fp2_mul(&t1, &p->Y, &J);
    fp2_add(&t1, &t1, &t1);
    fp2_sub(&r->Y, &t0, &t1);
    fp2_add(&t0, &p->Z, &H);
    fp2_sqr(&t0, &t0);
    fp2_sub(&t0, &t0, &Z1Z1);
    fp2_sub(&r->Z, &t0, &HH);
}

static void g2_mul_scalar(g2 *r, const g2 *p, const u8 *k32) {
    if (p->inf) { *r = *p; return; }
    g2j acc;
    memset(&acc, 0, sizeof acc);
    int started = 0;
    for (int i = 0; i < 32; i++) {
        for (int b = 7; b >= 0; b--) {
            if (started) g2j_dbl(&acc, &acc);
            if ((k32[i] >> b) & 1) {
                g2j_madd(&acc, &acc, &p->x, &p->y);
                started = 1;
            }
        }
    }
    if (!started || fp2_is_zero(&acc.Z)) {
        memset(r, 0, sizeof *r);
        r->inf = 1;
        return;
    }
    fp2 zi, zi2, zi3;
    fp2_inv(&zi, &acc.Z);
    fp2_sqr(&zi2, &zi);
    fp2_mul(&zi3, &zi2, &zi);
    fp2_mul(&r->x, &acc.X, &zi2);
    fp2_mul(&r->y, &acc.Y, &zi3);
    r->inf = 0;
}

/* ------------------------------------------------------------ pairing */

/* Optimized ate Miller loop: T stays PROJECTIVE in Fp2 on the twist (no
 * inversions in the loop — the old affine-in-fp12 version paid one
 * ext-gcd fp12 inversion per step), line evaluations are sparse fp12
 * elements multiplied in via mul_by_014 (~1/4 of a full fp12_mul).
 * Doubling/addition step formulas: eprint 2010/354 Alg 26/27 (the
 * zkcrypto/blst lineage for this exact curve/tower). Per-step values
 * differ from the Python reference's affine loop by subfield
 * normalization factors, which VANISH in the final exponentiation —
 * so pairing outputs after final_exp are bit-identical to
 * crypto/bls12_381.py (asserted by tests/test_bls_native.py). */

/* fp6 sparse: self * (c0 + c1 v) */
static void fp6_mul_by_01(fp6 *r, const fp6 *s, const fp2 *c0,
                          const fp2 *c1) {
    fp2 a_a, b_b, t1, t2, t3, u;
    fp2_mul(&a_a, &s->c0, c0);
    fp2_mul(&b_b, &s->c1, c1);
    fp2_add(&u, &s->c1, &s->c2);
    fp2_mul(&t1, &u, c1);
    fp2_sub(&t1, &t1, &b_b);
    fp2_mul_nonres(&t1, &t1);
    fp2_add(&t1, &t1, &a_a);            /* c0 s0 + ξ c1 s2 */
    fp2_add(&u, c0, c1);
    fp2_add(&t2, &s->c0, &s->c1);
    fp2_mul(&t2, &t2, &u);
    fp2_sub(&t2, &t2, &a_a);
    fp2_sub(&t2, &t2, &b_b);            /* c0 s1 + c1 s0 */
    fp2_mul(&t3, &s->c2, c0);
    fp2_add(&t3, &t3, &b_b);            /* c0 s2 + c1 s1 */
    r->c0 = t1; r->c1 = t2; r->c2 = t3;
}

/* fp6 sparse: self * (c1 v) */
static void fp6_mul_by_1(fp6 *r, const fp6 *s, const fp2 *c1) {
    fp2 t0, t1, t2;
    fp2_mul(&t0, &s->c2, c1);
    fp2_mul_nonres(&t0, &t0);
    fp2_mul(&t1, &s->c0, c1);
    fp2_mul(&t2, &s->c1, c1);
    r->c0 = t0; r->c1 = t1; r->c2 = t2;
}

/* f *= (c0 + c1 v) + (c4 v) w — the shape of an M-twist line */
static void fp12_mul_by_014(fp12 *f, const fp2 *c0, const fp2 *c1,
                            const fp2 *c4) {
    fp6 aa, bb, t, o6;
    fp2 o;
    fp6_mul_by_01(&aa, &f->c0, c0, c1);
    fp6_mul_by_1(&bb, &f->c1, c4);
    fp2_add(&o, c1, c4);
    fp6_add(&t, &f->c1, &f->c0);
    fp6_mul_by_01(&t, &t, c0, &o);
    fp6_sub(&t, &t, &aa);
    fp6_sub(&t, &t, &bb);
    fp6_mul_nonres(&o6, &bb);
    fp6_add(&f->c0, &o6, &aa);
    f->c1 = t;
}

typedef struct { fp2 X, Y, Z; } g2p;

/* eprint 2010/354 Alg 26: projective doubling + tangent-line coeffs */
static void miller_dbl(g2p *r, fp2 *l0, fp2 *l1, fp2 *l4) {
    fp2 tmp0, tmp1, tmp2, tmp3, tmp4, tmp5, tmp6, zsq, t;
    fp2_sqr(&tmp0, &r->X);
    fp2_sqr(&tmp1, &r->Y);
    fp2_sqr(&tmp2, &tmp1);
    fp2_add(&t, &tmp1, &r->X);
    fp2_sqr(&tmp3, &t);
    fp2_sub(&tmp3, &tmp3, &tmp0);
    fp2_sub(&tmp3, &tmp3, &tmp2);
    fp2_add(&tmp3, &tmp3, &tmp3);
    fp2_add(&tmp4, &tmp0, &tmp0);
    fp2_add(&tmp4, &tmp4, &tmp0);
    fp2_add(&tmp6, &r->X, &tmp4);
    fp2_sqr(&tmp5, &tmp4);
    fp2_sqr(&zsq, &r->Z);
    fp2_sub(&r->X, &tmp5, &tmp3);
    fp2_sub(&r->X, &r->X, &tmp3);
    fp2_add(&t, &r->Z, &r->Y);
    fp2_sqr(&t, &t);
    fp2_sub(&t, &t, &tmp1);
    fp2_sub(&r->Z, &t, &zsq);
    fp2_sub(&t, &tmp3, &r->X);
    fp2_mul(&r->Y, &t, &tmp4);
    fp2_add(&tmp2, &tmp2, &tmp2);
    fp2_add(&tmp2, &tmp2, &tmp2);
    fp2_add(&tmp2, &tmp2, &tmp2);
    fp2_sub(&r->Y, &r->Y, &tmp2);
    fp2_mul(&tmp3, &tmp4, &zsq);
    fp2_add(&tmp3, &tmp3, &tmp3);
    fp2_neg(&tmp3, &tmp3);
    fp2_sqr(&tmp6, &tmp6);
    fp2_sub(&tmp6, &tmp6, &tmp0);
    fp2_sub(&tmp6, &tmp6, &tmp5);
    fp2_add(&tmp1, &tmp1, &tmp1);
    fp2_add(&tmp1, &tmp1, &tmp1);
    fp2_sub(&tmp6, &tmp6, &tmp1);
    fp2_mul(&tmp0, &r->Z, &zsq);
    fp2_add(&tmp0, &tmp0, &tmp0);
    *l0 = tmp0; *l1 = tmp3; *l4 = tmp6;
}

/* eprint 2010/354 Alg 27: mixed addition + secant-line coeffs */
static void miller_add(g2p *r, const g2 *q, fp2 *l0, fp2 *l1, fp2 *l4) {
    fp2 zsq, ysq, t0, t1, t2, t3, t4, t5, t6, t7, t8, t9, t10, ztsq, t;
    fp2_sqr(&zsq, &r->Z);
    fp2_sqr(&ysq, &q->y);
    fp2_mul(&t0, &zsq, &q->x);
    fp2_add(&t, &q->y, &r->Z);
    fp2_sqr(&t1, &t);
    fp2_sub(&t1, &t1, &ysq);
    fp2_sub(&t1, &t1, &zsq);
    fp2_mul(&t1, &t1, &zsq);
    fp2_sub(&t2, &t0, &r->X);
    fp2_sqr(&t3, &t2);
    fp2_add(&t4, &t3, &t3);
    fp2_add(&t4, &t4, &t4);
    fp2_mul(&t5, &t4, &t2);
    fp2_sub(&t6, &t1, &r->Y);
    fp2_sub(&t6, &t6, &r->Y);
    fp2_mul(&t9, &t6, &q->x);
    fp2_mul(&t7, &t4, &r->X);
    fp2_sqr(&r->X, &t6);
    fp2_sub(&r->X, &r->X, &t5);
    fp2_sub(&r->X, &r->X, &t7);
    fp2_sub(&r->X, &r->X, &t7);
    fp2_add(&t, &r->Z, &t2);
    fp2_sqr(&t, &t);
    fp2_sub(&t, &t, &zsq);
    fp2_sub(&r->Z, &t, &t3);
    fp2_add(&t10, &q->y, &r->Z);
    fp2_sub(&t8, &t7, &r->X);
    fp2_mul(&t8, &t8, &t6);
    fp2_mul(&t0, &r->Y, &t5);
    fp2_add(&t0, &t0, &t0);
    fp2_sub(&r->Y, &t8, &t0);
    fp2_sqr(&t10, &t10);
    fp2_sub(&t10, &t10, &ysq);
    fp2_sqr(&ztsq, &r->Z);
    fp2_sub(&t10, &t10, &ztsq);
    fp2_add(&t9, &t9, &t9);
    fp2_sub(&t9, &t9, &t10);
    fp2_add(&t10, &r->Z, &r->Z);
    fp2_neg(&t6, &t6);
    fp2_add(&t1, &t6, &t6);
    *l0 = t10; *l1 = t1; *l4 = t9;
}

/* line eval at P + sparse accumulate: f *= l4 + (l1·xp) v + (l0·yp) v w */
static void miller_ell(fp12 *f, const fp2 *l0, const fp2 *l1,
                       const fp2 *l4, const g1 *p) {
    fp2 c0, c1;
    fp2_mul_fp(&c0, l0, &p->y);
    fp2_mul_fp(&c1, l1, &p->x);
    fp12_mul_by_014(f, l4, &c1, &c0);
}

static void miller(fp12 *f, const g1 *p, const g2 *q) {
    fp12_one(f);
    if (p->inf || q->inf) return;
    g2p r;
    r.X = q->x;
    r.Y = q->y;
    memset(&r.Z, 0, sizeof r.Z);
    memcpy(r.Z.c0.l, ONE_M, sizeof ONE_M);
    fp2 l0, l1, l4;
    int started = 0;
    for (int b = 63; b >= 0; b--) {
        if (!started) {
            if ((X_ABS >> b) & 1) started = 1;  /* skip leading bit */
            continue;
        }
        fp12_sqr(f, f);
        miller_dbl(&r, &l0, &l1, &l4);
        miller_ell(f, &l0, &l1, &l4, p);
        if ((X_ABS >> b) & 1) {
            miller_add(&r, q, &l0, &l1, &l4);
            miller_ell(f, &l0, &l1, &l4, p);
        }
    }
    /* x < 0: f = conj(f) */
    fp12_conj(f, f);
}


/* final exponentiation: f^(3·(q^4-q^2+1)/r) via HHT:
 * (x-1)^2 (x+q) (x^2+q^2-1) + 3, x = -X_ABS */
/* Granger-Scott cyclotomic squaring (valid once in the cyclotomic
 * subgroup, i.e. after the easy part of final exp): 3 "fp4 squarings"
 * ≈ 9 fp2 mults vs fp12_sqr's ~24 */
static void fp4_sqr_parts(fp2 *c0, fp2 *c1, const fp2 *a, const fp2 *b) {
    fp2 t0, t1, t2;
    fp2_sqr(&t0, a);
    fp2_sqr(&t1, b);
    fp2_mul_nonres(&t2, &t1);
    fp2_add(c0, &t2, &t0);
    fp2_add(&t2, a, b);
    fp2_sqr(&t2, &t2);
    fp2_sub(&t2, &t2, &t0);
    fp2_sub(c1, &t2, &t1);
}

static void fp12_cyc_sqr(fp12 *r, const fp12 *f) {
    fp2 z0 = f->c0.c0, z4 = f->c0.c1, z3 = f->c0.c2;
    fp2 z2 = f->c1.c0, z1 = f->c1.c1, z5 = f->c1.c2;
    fp2 t0, t1, t2, t3;
    fp4_sqr_parts(&t0, &t1, &z0, &z1);
    fp2_sub(&z0, &t0, &z0);
    fp2_add(&z0, &z0, &z0);
    fp2_add(&z0, &z0, &t0);
    fp2_add(&z1, &t1, &z1);
    fp2_add(&z1, &z1, &z1);
    fp2_add(&z1, &z1, &t1);
    fp4_sqr_parts(&t0, &t1, &z2, &z3);
    fp4_sqr_parts(&t2, &t3, &z4, &z5);
    fp2_sub(&z4, &t0, &z4);
    fp2_add(&z4, &z4, &z4);
    fp2_add(&z4, &z4, &t0);
    fp2_add(&z5, &t1, &z5);
    fp2_add(&z5, &z5, &z5);
    fp2_add(&z5, &z5, &t1);
    fp2_mul_nonres(&t0, &t3);
    fp2_add(&z2, &t0, &z2);
    fp2_add(&z2, &z2, &z2);
    fp2_add(&z2, &z2, &t0);
    fp2_sub(&z3, &t2, &z3);
    fp2_add(&z3, &z3, &z3);
    fp2_add(&z3, &z3, &t2);
    r->c0.c0 = z0; r->c0.c1 = z4; r->c0.c2 = z3;
    r->c1.c0 = z2; r->c1.c1 = z1; r->c1.c2 = z5;
}

/* pow within the cyclotomic subgroup (hard part of final exp) */
static void fp12_pow_u64_cyc(fp12 *r, const fp12 *a, u64 e) {
    fp12 acc;
    fp12_one(&acc);
    int started = 0;
    for (int b = 63; b >= 0; b--) {
        if (started) fp12_cyc_sqr(&acc, &acc);
        if ((e >> b) & 1) {
            if (!started) { acc = *a; started = 1; }
            else fp12_mul(&acc, &acc, a);
        }
    }
    if (!started) fp12_one(&acc);
    *r = acc;
}

static void fp12_pow_x_cyc(fp12 *r, const fp12 *a) {
    fp12 t;
    fp12_pow_u64_cyc(&t, a, X_ABS);
    fp12_conj(r, &t);
}

static void final_exp(fp12 *r, const fp12 *f) {
    frob_init();
    fp12 t0, t1, m;
    /* easy: f^(q^6-1) = conj(f) * f^-1 ; then ^(q^2+1) */
    fp12_conj(&t0, f);
    fp12_inv(&t1, f);
    fp12_mul(&m, &t0, &t1);
    fp12_frob(&t0, &m);
    fp12_frob(&t0, &t0);
    fp12_mul(&m, &t0, &m);         /* m = f^((q^6-1)(q^2+1)) */

    /* hard: m^((x-1)^2 (x+q) (x^2+q^2-1)) * m^3 — all exponentiations
     * run in the cyclotomic subgroup (Granger-Scott squarings) */
    fp12 a, b, c;
    /* a = m^(x-1); x-1 = -(X_ABS+1) → pow by X_ABS+1 then conj */
    fp12_pow_u64_cyc(&a, &m, X_ABS + 1);
    fp12_conj(&a, &a);
    fp12_pow_u64_cyc(&t0, &a, X_ABS + 1);
    fp12_conj(&a, &t0);            /* a = m^((x-1)^2) (sign squares away:
                                      (-(X+1))² = (X+1)² — conj twice = id,
                                      so conj applied twice is identity;
                                      keep both conjs for clarity) */
    /* b = a^(x+q) = a^x * frob(a) */
    fp12_pow_x_cyc(&t0, &a);
    fp12_frob(&t1, &a);
    fp12_mul(&b, &t0, &t1);
    /* c = b^(x²+q²-1) = (b^x)^x * frob²(b) * conj(b) */
    fp12_pow_x_cyc(&t0, &b);
    fp12_pow_x_cyc(&t0, &t0);
    fp12_frob(&t1, &b);
    fp12_frob(&t1, &t1);
    fp12_mul(&c, &t0, &t1);
    fp12_conj(&t0, &b);
    fp12_mul(&c, &c, &t0);
    /* result = c * m² * m */
    fp12_cyc_sqr(&t0, &m);
    fp12_mul(&t0, &t0, &m);
    fp12_mul(r, &c, &t0);
}

/* fp pow by big-endian bytes (for sqrt) */
static void fp_pow_bytes(fp *r, const fp *a, const u8 *e, int elen) {
    fp acc;
    memcpy(acc.l, ONE_M, sizeof ONE_M);
    for (int i = 0; i < elen; i++) {
        for (int b = 7; b >= 0; b--) {
            fp_sqr(&acc, &acc);
            if ((e[i] >> b) & 1) fp_mul(&acc, &acc, a);
        }
    }
    *r = acc;
}

/* ---------------------------------------------------------------- ABI */

static void g1_from_bytes(g1 *r, const u8 *in96) {
    int zero = 1;
    for (int i = 0; i < 96; i++) if (in96[i]) { zero = 0; break; }
    if (zero) { r->x = FP_ZERO; r->y = FP_ZERO; r->inf = 1; return; }
    fp_from_bytes(&r->x, in96);
    fp_from_bytes(&r->y, in96 + 48);
    r->inf = 0;
}

static void g1_to_bytes(u8 *out96, const g1 *p) {
    if (p->inf) { memset(out96, 0, 96); return; }
    fp_to_bytes(out96, &p->x);
    fp_to_bytes(out96 + 48, &p->y);
}

static void g2_from_bytes(g2 *r, const u8 *in192) {
    int zero = 1;
    for (int i = 0; i < 192; i++) if (in192[i]) { zero = 0; break; }
    if (zero) { memset(r, 0, sizeof *r); r->inf = 1; return; }
    fp_from_bytes(&r->x.c0, in192);
    fp_from_bytes(&r->x.c1, in192 + 48);
    fp_from_bytes(&r->y.c0, in192 + 96);
    fp_from_bytes(&r->y.c1, in192 + 144);
    r->inf = 0;
}

static void g2_to_bytes(u8 *out192, const g2 *p) {
    if (p->inf) { memset(out192, 0, 192); return; }
    fp_to_bytes(out192, &p->x.c0);
    fp_to_bytes(out192 + 48, &p->x.c1);
    fp_to_bytes(out192 + 96, &p->y.c0);
    fp_to_bytes(out192 + 144, &p->y.c1);
}

void b_g1_add(const u8 *a, const u8 *b, u8 *out) {
    g1 p, q, r;
    g1_from_bytes(&p, a);
    g1_from_bytes(&q, b);
    g1_add_aff(&r, &p, &q);
    g1_to_bytes(out, &r);
}

void b_g1_mul(const u8 *p96, const u8 *k32, u8 *out) {
    g1 p, r;
    g1_from_bytes(&p, p96);
    g1_mul_scalar(&r, &p, k32);
    g1_to_bytes(out, &r);
}

void b_g2_add(const u8 *a, const u8 *b, u8 *out) {
    g2 p, q, r;
    g2_from_bytes(&p, a);
    g2_from_bytes(&q, b);
    g2_add_aff(&r, &p, &q);
    g2_to_bytes(out, &r);
}

void b_g2_mul(const u8 *p192, const u8 *k32, u8 *out) {
    g2 p, r;
    g2_from_bytes(&p, p192);
    g2_mul_scalar(&r, &p, k32);
    g2_to_bytes(out, &r);
}

/* ZCash-compressed G1 (48 B) → affine 96 B. Returns 0 ok, 1 infinity,
 * -1 invalid. Must match crypto/bls12_381.py g1_decompress exactly. */
int b_g1_decompress(const u8 *in48, u8 *out96) {
    u8 flags = in48[0];
    if (!(flags & 0x80)) return -1;
    if (flags & 0x40) {
        if (in48[0] != 0xC0) return -1;
        for (int i = 1; i < 48; i++) if (in48[i]) return -1;
        memset(out96, 0, 96);
        return 1;
    }
    u8 xb[48];
    memcpy(xb, in48, 48);
    xb[0] &= 0x1F;
    /* x < q? compare big-endian bytes against q */
    static const u8 QB[48] = {
        0x1a, 0x01, 0x11, 0xea, 0x39, 0x7f, 0xe6, 0x9a, 0x4b, 0x1b, 0xa7,
        0xb6, 0x43, 0x4b, 0xac, 0xd7, 0x64, 0x77, 0x4b, 0x84, 0xf3, 0x85,
        0x12, 0xbf, 0x67, 0x30, 0xd2, 0xa0, 0xf6, 0xb0, 0xf6, 0x24, 0x1e,
        0xab, 0xff, 0xfe, 0xb1, 0x53, 0xff, 0xff, 0xb9, 0xfe, 0xff, 0xff,
        0xff, 0xff, 0xaa, 0xab};
    int lt = 0;
    for (int i = 0; i < 48; i++) {
        if (xb[i] < QB[i]) { lt = 1; break; }
        if (xb[i] > QB[i]) { lt = 0; break; }
    }
    if (!lt) return -1;
    fp x, yy, y, t;
    fp_from_bytes(&x, xb);
    fp_sqr(&yy, &x);
    fp_mul(&yy, &yy, &x);
    fp four;
    memcpy(four.l, ONE_M, sizeof ONE_M);
    fp_add(&four, &four, &four);
    fp_add(&four, &four, &four);
    fp_add(&yy, &yy, &four);            /* x^3 + 4 */
    /* y = yy^((q+1)/4); (q+1)/4 as bytes: q+1 then >>2 */
    u64 qp1[NL];
    memcpy(qp1, Qm, sizeof Qm);
    qp1[0] += 1;                        /* no carry: low limb < 2^64-1 */
    for (int i = 0; i < NL - 1; i++)
        qp1[i] = (qp1[i] >> 2) | (qp1[i + 1] << 62);
    qp1[NL - 1] >>= 2;
    u8 e[48];
    for (int i = 0; i < NL; i++) {
        u64 v = qp1[NL - 1 - i];
        for (int j = 0; j < 8; j++)
            e[i * 8 + j] = (u8)(v >> (56 - 8 * j));
    }
    fp_pow_bytes(&y, &yy, e, 48);
    fp_sqr(&t, &y);
    if (!fp_eq(&t, &yy)) return -1;     /* not on curve */
    /* sign: y > (q-1)/2 ⇔ raw(y) > (q-1)/2 */
    u8 yb[48];
    fp_to_bytes(yb, &y);
    static const u8 QH[48] = {          /* (q-1)/2 big-endian */
        0x0d, 0x00, 0x88, 0xf5, 0x1c, 0xbf, 0xf3, 0x4d, 0x25, 0x8d, 0xd3,
        0xdb, 0x21, 0xa5, 0xd6, 0x6b, 0xb2, 0x3b, 0xa5, 0xc2, 0x79, 0xc2,
        0x89, 0x5f, 0xb3, 0x98, 0x69, 0x50, 0x7b, 0x58, 0x7b, 0x12, 0x0f,
        0x55, 0xff, 0xff, 0x58, 0xa9, 0xff, 0xff, 0xdc, 0xff, 0x7f, 0xff,
        0xff, 0xff, 0xd5, 0x55};
    int big = 0;
    for (int i = 0; i < 48; i++) {
        if (yb[i] > QH[i]) { big = 1; break; }
        if (yb[i] < QH[i]) { big = 0; break; }
    }
    int want_big = (flags >> 5) & 1;
    if (big != want_big) fp_neg(&y, &y);
    fp_to_bytes(out96, &x);
    fp_to_bytes(out96 + 48, &y);
    return 0;
}

/* Aggregate n compressed G1 signatures with Jacobian accumulation and a
 * single final inversion — the per-add fp_inv in g1_add_aff is what
 * made scalar aggregation pay ~an inversion per share. Returns 0 ok,
 * -1 if any share is invalid. out96 = affine x||y (zeros = infinity).
 * Reference: create_multi_sig in
 * crypto/bls/indy_crypto/bls_crypto_indy_crypto.py:99. */
int b_g1_aggregate(int n, const u8 *sigs48, u8 *out96) {
    g1j acc = {FP_ZERO, FP_ZERO, FP_ZERO};
    u8 tmp[96];
    for (int i = 0; i < n; i++) {
        int rc = b_g1_decompress(sigs48 + (size_t)i * 48, tmp);
        if (rc < 0) return -1;
        if (rc == 1) continue;          /* infinity share */
        fp x, y;
        fp_from_bytes(&x, tmp);
        fp_from_bytes(&y, tmp + 48);
        g1j_madd(&acc, &acc, &x, &y);
    }
    if (fp_is_zero(&acc.Z)) { memset(out96, 0, 96); return 0; }
    fp zi, zi2, zi3, x, y;
    fp_inv(&zi, &acc.Z);
    fp_sqr(&zi2, &zi);
    fp_mul(&zi3, &zi2, &zi);
    fp_mul(&x, &acc.X, &zi2);
    fp_mul(&y, &acc.Y, &zi3);
    fp_to_bytes(out96, &x);
    fp_to_bytes(out96 + 48, &y);
    return 0;
}

/* Aggregate n AFFINE points (96-byte x||y each, zeros = infinity) with
 * Jacobian accumulation and one final inversion. The consensus path
 * decompresses each share once at COMMIT-validation time; ordering then
 * aggregates the cached points here without paying the per-share sqrt
 * again. out96 = affine x||y (zeros = infinity). */
void b_g1_aggregate_affine(int n, const u8 *pts96, u8 *out96) {
    g1j acc = {FP_ZERO, FP_ZERO, FP_ZERO};
    for (int i = 0; i < n; i++) {
        g1 p;
        g1_from_bytes(&p, pts96 + (size_t)i * 96);
        if (p.inf) continue;
        g1j_madd(&acc, &acc, &p.x, &p.y);
    }
    if (fp_is_zero(&acc.Z)) { memset(out96, 0, 96); return; }
    fp zi, zi2, zi3, x, y;
    fp_inv(&zi, &acc.Z);
    fp_sqr(&zi2, &zi);
    fp_mul(&zi3, &zi2, &zi);
    fp_mul(&x, &acc.X, &zi2);
    fp_mul(&y, &acc.Y, &zi3);
    fp_to_bytes(out96, &x);
    fp_to_bytes(out96 + 48, &y);
}

/* ∏ e(P_i, Q_i) == 1 ? (one shared final exponentiation) */
/* ------------------------------------------------------------------ */
/* SHA-256 (FIPS 180-4) — needed by the hash-to-curve construction,    */
/* which must be bit-identical to crypto/bls12_381.py hash_to_g1       */
/* ------------------------------------------------------------------ */

typedef uint32_t u32;
typedef struct { u32 h[8]; u64 len; u8 buf[64]; size_t buflen; } sha_ctx;

static const u32 SK256[64] = {
    0x428a2f98,0x71374491,0xb5c0fbcf,0xe9b5dba5,0x3956c25b,0x59f111f1,
    0x923f82a4,0xab1c5ed5,0xd807aa98,0x12835b01,0x243185be,0x550c7dc3,
    0x72be5d74,0x80deb1fe,0x9bdc06a7,0xc19bf174,0xe49b69c1,0xefbe4786,
    0x0fc19dc6,0x240ca1cc,0x2de92c6f,0x4a7484aa,0x5cb0a9dc,0x76f988da,
    0x983e5152,0xa831c66d,0xb00327c8,0xbf597fc7,0xc6e00bf3,0xd5a79147,
    0x06ca6351,0x14292967,0x27b70a85,0x2e1b2138,0x4d2c6dfc,0x53380d13,
    0x650a7354,0x766a0abb,0x81c2c92e,0x92722c85,0xa2bfe8a1,0xa81a664b,
    0xc24b8b70,0xc76c51a3,0xd192e819,0xd6990624,0xf40e3585,0x106aa070,
    0x19a4c116,0x1e376c08,0x2748774c,0x34b0bcb5,0x391c0cb3,0x4ed8aa4a,
    0x5b9cca4f,0x682e6ff3,0x748f82ee,0x78a5636f,0x84c87814,0x8cc70208,
    0x90befffa,0xa4506ceb,0xbef9a3f7,0xc67178f2
};
#define SROR(x,n) (((x) >> (n)) | ((x) << (32 - (n))))

static void sha_init(sha_ctx *c) {
    c->h[0]=0x6a09e667; c->h[1]=0xbb67ae85; c->h[2]=0x3c6ef372;
    c->h[3]=0xa54ff53a; c->h[4]=0x510e527f; c->h[5]=0x9b05688c;
    c->h[6]=0x1f83d9ab; c->h[7]=0x5be0cd19; c->len=0; c->buflen=0;
}

static void sha_block(sha_ctx *c, const u8 *p) {
    u32 w[64], a,b2,cc,d,e,f,g,h2,t1,t2;
    int i;
    for (i = 0; i < 16; i++)
        w[i] = ((u32)p[4*i]<<24)|((u32)p[4*i+1]<<16)
             | ((u32)p[4*i+2]<<8)|(u32)p[4*i+3];
    for (i = 16; i < 64; i++) {
        u32 s0 = SROR(w[i-15],7)^SROR(w[i-15],18)^(w[i-15]>>3);
        u32 s1 = SROR(w[i-2],17)^SROR(w[i-2],19)^(w[i-2]>>10);
        w[i] = w[i-16]+s0+w[i-7]+s1;
    }
    a=c->h[0]; b2=c->h[1]; cc=c->h[2]; d=c->h[3];
    e=c->h[4]; f=c->h[5]; g=c->h[6]; h2=c->h[7];
    for (i = 0; i < 64; i++) {
        u32 S1 = SROR(e,6)^SROR(e,11)^SROR(e,25);
        u32 ch = (e&f)^((~e)&g);
        t1 = h2+S1+ch+SK256[i]+w[i];
        u32 S0 = SROR(a,2)^SROR(a,13)^SROR(a,22);
        u32 mj = (a&b2)^(a&cc)^(b2&cc);
        t2 = S0+mj;
        h2=g; g=f; f=e; e=d+t1; d=cc; cc=b2; b2=a; a=t1+t2;
    }
    c->h[0]+=a; c->h[1]+=b2; c->h[2]+=cc; c->h[3]+=d;
    c->h[4]+=e; c->h[5]+=f; c->h[6]+=g; c->h[7]+=h2;
}

static void sha_update(sha_ctx *c, const u8 *p, size_t n) {
    c->len += n;
    if (c->buflen) {
        size_t take = 64 - c->buflen;
        if (take > n) take = n;
        memcpy(c->buf + c->buflen, p, take);
        c->buflen += take; p += take; n -= take;
        if (c->buflen == 64) { sha_block(c, c->buf); c->buflen = 0; }
    }
    while (n >= 64) { sha_block(c, p); p += 64; n -= 64; }
    if (n) { memcpy(c->buf, p, n); c->buflen = n; }
}

static void sha_final(sha_ctx *c, u8 out[32]) {
    u64 bits = c->len * 8;
    u8 pad = 0x80, z = 0, lb[8];
    int i;
    sha_update(c, &pad, 1);
    while (c->buflen != 56) sha_update(c, &z, 1);
    for (i = 0; i < 8; i++) lb[i] = (u8)(bits >> (56 - 8*i));
    sha_update(c, lb, 8);
    for (i = 0; i < 8; i++) {
        out[4*i]   = (u8)(c->h[i] >> 24);
        out[4*i+1] = (u8)(c->h[i] >> 16);
        out[4*i+2] = (u8)(c->h[i] >> 8);
        out[4*i+3] = (u8)(c->h[i]);
    }
}

/* fixed-exponent Montgomery pow over raw little-endian u64 limbs */
static void fp_pow_limbs(fp *r, const fp *a, const u64 *e, int nlimbs) {
    fp acc;
    int started = 0;
    memcpy(acc.l, ONE_M, sizeof ONE_M);
    for (int i = nlimbs - 1; i >= 0; i--) {
        for (int b = 63; b >= 0; b--) {
            if (started) fp_sqr(&acc, &acc);
            if ((e[i] >> b) & 1) {
                if (!started) { acc = *a; started = 1; }
                else fp_mul(&acc, &acc, a);
            }
        }
    }
    *r = acc;
}

/* hash-to-curve: bit-identical to bls12_381.py hash_to_g1 (try-and-
 * increment over SHA-256, sqrt by (Q+1)/4, smaller root, cofactor
 * cleared by (1+X_ABS)^2/3). Returns 0 ok / -1 if the cofactor mul
 * lands at infinity (the Python path retries ctr in that case too). */
int b_hash_to_g1(const u8 *msg, int msg_len, const u8 *dst, int dst_len,
                 u8 *out96) {
    u64 sqrt_e[NL];      /* (Q+1)/4 */
    u8 cof[32];          /* (1+X_ABS)^2 / 3, big-endian 32 bytes */
    {
        /* (Q+1)/4: Q is odd, Q+1 even; shift the raw modulus right 2 */
        u64 t[NL];
        memcpy(t, Qm, sizeof t);
        t[0] += 1;                   /* Q odd => no carry chain needed */
        for (int i = 0; i < NL; i++) {
            u64 lo = t[i] >> 2;
            if (i + 1 < NL) lo |= t[i + 1] << 62;
            sqrt_e[i] = lo;
        }
        /* cofactor (1+X_ABS)^2/3 fits 128 bits */
        unsigned __int128 c = (unsigned __int128)(X_ABS + 1)
            * (X_ABS + 1) / 3;
        memset(cof, 0, sizeof cof);
        for (int i = 0; i < 16; i++)
            cof[31 - i] = (u8)(c >> (8 * i));
    }
    for (u32 ctr = 0; ; ctr++) {
        u8 d1[32], d2[32], xb[48], ctr_be[4];
        sha_ctx c;
        fp x, yy, y, y2, t;
        ctr_be[0] = (u8)(ctr >> 24); ctr_be[1] = (u8)(ctr >> 16);
        ctr_be[2] = (u8)(ctr >> 8); ctr_be[3] = (u8)ctr;
        sha_init(&c);
        sha_update(&c, dst, (size_t)dst_len);
        sha_update(&c, ctr_be, 4);
        sha_update(&c, msg, (size_t)msg_len);
        sha_final(&c, d1);
        sha_init(&c);
        { u8 one = 1; sha_update(&c, &one, 1); }
        sha_update(&c, d1, 32);
        sha_final(&c, d2);
        memcpy(xb, d1, 32);
        memcpy(xb + 32, d2, 16);
        /* 48-byte big-endian value mod Q — raw reduce then Montgomery */
        {
            /* 48-byte value < 2^384; 2^384/Q < 8, so loop-subtract Q
               (tracked with one overflow limb) until below it */
            u64 v[NL + 1];
            memset(v, 0, sizeof v);
            for (int i = 0; i < 48; i++) {
                int limb = (47 - i) / 8, byte = (47 - i) % 8;
                v[limb] |= (u64)xb[i] << (8 * byte);
            }
            for (;;) {
                int ge;
                if (v[NL] != 0) {
                    ge = 1;
                } else {
                    ge = 1;
                    for (int i = NL - 1; i >= 0; i--) {
                        if (v[i] != Qm[i]) { ge = v[i] > Qm[i]; break; }
                    }
                }
                if (!ge) break;
                unsigned __int128 br = 0;
                for (int i = 0; i < NL; i++) {
                    unsigned __int128 dd = (unsigned __int128)v[i]
                        - Qm[i] - br;
                    v[i] = (u64)dd;
                    br = (dd >> 64) & 1;
                }
                v[NL] -= (u64)br;
            }
            u8 canon[48];
            for (int i = 0; i < 48; i++) {
                int limb = (47 - i) / 8, byte = (47 - i) % 8;
                canon[i] = (u8)(v[limb] >> (8 * byte));
            }
            fp_from_bytes(&x, canon);
        }
        /* yy = x^3 + 4 */
        fp_sqr(&t, &x);
        fp_mul(&yy, &t, &x);
        {
            fp four;
            memcpy(four.l, ONE_M, sizeof ONE_M);
            fp_add(&four, &four, &four);
            fp_add(&four, &four, &four);
            fp_add(&yy, &yy, &four);
        }
        fp_pow_limbs(&y, &yy, sqrt_e, NL);
        fp_sqr(&y2, &y);
        if (memcmp(y2.l, yy.l, sizeof yy.l) != 0)
            continue;  /* not a QR: next counter */
        /* smaller of y, Q-y by canonical value */
        {
            u8 yb[48], nyb[48];
            fp ny;
            fp_neg(&ny, &y);
            fp_to_bytes(yb, &y);
            fp_to_bytes(nyb, &ny);
            if (memcmp(nyb, yb, 48) < 0) y = ny;
        }
        {
            g1 p, r;
            p.x = x; p.y = y; p.inf = 0;
            g1_mul_scalar(&r, &p, cof);
            if (r.inf) continue;  /* mirror the Python retry */
            g1_to_bytes(out96, &r);
            return 0;
        }
    }
}

/* ------------------------------------------------------------------ */
/* prepared pairings: precomputed line coefficients for a fixed Q      */
/*                                                                     */
/* A validator verifies every multi-sig against the SAME two G2        */
/* arguments — the group generator and the pool's aggregated key       */
/* (cached per participant set). The Miller doubling/addition chain    */
/* depends only on Q, so its (l0,l1,l4) line coefficients can be       */
/* computed once per Q and replayed: the per-verify loop then costs    */
/* one shared fp12 squaring chain plus sparse line evaluations.        */
/* ------------------------------------------------------------------ */

/* doubling steps (63) + addition steps (popcount(X_ABS)-1) */
#define MILLER_SLOTS 68
/* each slot: 3 fp2 = 6 fp = 36 u64 (Montgomery form, opaque blob) */
#define PREP_SIZE (MILLER_SLOTS * 3 * sizeof(fp2))

int b_prep_size(void) { return (int)PREP_SIZE; }

int b_miller_precompute(const u8 *g2b, u8 *out) {
    g2 q;
    fp2 *slots = (fp2 *)out;
    g2p r;
    int slot = 0;
    g2_from_bytes(&q, g2b);
    if (q.inf) return -1;
    r.X = q.x;
    r.Y = q.y;
    memset(&r.Z, 0, sizeof r.Z);
    memcpy(r.Z.c0.l, ONE_M, sizeof ONE_M);
    {
        int started = 0;
        for (int b = 63; b >= 0; b--) {
            if (!started) {
                if ((X_ABS >> b) & 1) started = 1;
                continue;
            }
            miller_dbl(&r, &slots[slot * 3], &slots[slot * 3 + 1],
                       &slots[slot * 3 + 2]);
            slot++;
            if ((X_ABS >> b) & 1) {
                miller_add(&r, &q, &slots[slot * 3],
                           &slots[slot * 3 + 1], &slots[slot * 3 + 2]);
                slot++;
            }
        }
    }
    return slot == MILLER_SLOTS ? 0 : -1;
}

/* shared-squaring multi-Miller over prepared lines: ONE fp12 squaring
 * chain for all n pairs (the plain path squares per pair), sparse line
 * evaluation per pair per step. preps = n blobs of PREP_SIZE. */
int b_multi_pairing_is_one_prepared(int n, const u8 *g1s,
                                    const u8 *preps) {
    fp12 f;
    g1 ps[8];
    int live[8];
    int slot = 0;
    if (n < 1 || n > 8) return 0;
    for (int i = 0; i < n; i++) {
        g1_from_bytes(&ps[i], g1s + (size_t)i * 96);
        live[i] = !ps[i].inf;
    }
    fp12_one(&f);
    {
        int started = 0;
        for (int b = 63; b >= 0; b--) {
            if (!started) {
                if ((X_ABS >> b) & 1) started = 1;
                continue;
            }
            fp12_sqr(&f, &f);
            for (int i = 0; i < n; i++) {
                const fp2 *ln = (const fp2 *)(preps + (size_t)i * PREP_SIZE)
                    + (size_t)slot * 3;
                if (live[i])
                    miller_ell(&f, &ln[0], &ln[1], &ln[2], &ps[i]);
            }
            slot++;
            if ((X_ABS >> b) & 1) {
                for (int i = 0; i < n; i++) {
                    const fp2 *ln = (const fp2 *)(preps
                        + (size_t)i * PREP_SIZE) + (size_t)slot * 3;
                    if (live[i])
                        miller_ell(&f, &ln[0], &ln[1], &ln[2], &ps[i]);
                }
                slot++;
            }
        }
    }
    /* x < 0: conj, exactly as miller() does */
    fp12_conj(&f, &f);
    final_exp(&f, &f);
    return fp12_is_one(&f);
}

int b_multi_pairing_is_one(int n, const u8 *g1s, const u8 *g2s) {
    fp12 acc, fi;
    fp12_one(&acc);
    for (int i = 0; i < n; i++) {
        g1 p;
        g2 q;
        g1_from_bytes(&p, g1s + (size_t)i * 96);
        g2_from_bytes(&q, g2s + (size_t)i * 192);
        miller(&fi, &p, &q);
        fp12_mul(&acc, &acc, &fi);
    }
    final_exp(&acc, &acc);
    return fp12_is_one(&acc);
}

/* raw pairing output (final-exponentiated, cube-power convention),
 * serialized as 12×48 bytes — for cross-checking/testing only */
void b_pairing(const u8 *g1b, const u8 *g2b, u8 *out576) {
    g1 p;
    g2 q;
    fp12 f;
    g1_from_bytes(&p, g1b);
    g2_from_bytes(&q, g2b);
    miller(&f, &p, &q);
    final_exp(&f, &f);
    const fp *coeffs[12] = {
        &f.c0.c0.c0, &f.c0.c0.c1, &f.c0.c1.c0, &f.c0.c1.c1,
        &f.c0.c2.c0, &f.c0.c2.c1, &f.c1.c0.c0, &f.c1.c0.c1,
        &f.c1.c1.c0, &f.c1.c1.c1, &f.c1.c2.c0, &f.c1.c2.c1};
    for (int i = 0; i < 12; i++)
        fp_to_bytes(out576 + i * 48, coeffs[i]);
}
