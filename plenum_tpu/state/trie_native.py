"""Native-trie wrapper: the C MPT (native/mpt_c.c) behind the same
interface as state/trie.py's Trie, so PruningState can swap backends.

The C module owns the node blobs (sha3 → RLP) and does all per-node
work; this wrapper keeps the durable-KV contract identical to the
Python trie: every node created by an operation is written through to
the KV store before the call returns, and on a node miss (fresh process
over an existing store) the C side hydrates lazily through a callback
into the same KV. Roots are bit-identical to the Python implementation
(cross-checked in tests/test_mpt_native.py) — they are consensus state.
"""
from __future__ import annotations

from typing import List, Optional

from plenum_tpu.native import try_load_ext

_mpt = try_load_ext("mpt_c")
if _mpt is None:
    # honor the PLENUM_TPU_NO_NATIVE kill-switch (and missing-compiler
    # environments): PruningState catches this import failure and falls
    # back to the Python trie
    raise ImportError("native MPT unavailable or disabled")

BLANK_ROOT = _mpt.blank_root()


class NativeTrie:
    """Drop-in for state/trie.py's Trie over a KeyValueStorage."""

    def __init__(self, store, root_hash: Optional[bytes] = None):
        self._store = store

        def _miss(h: bytes):
            try:
                return bytes(store.get(h))
            except KeyError:
                return None

        self._h = _mpt.new(_miss)
        self.root_hash = bytes(root_hash) if root_hash is not None \
            else BLANK_ROOT

    # ---------------------------------------------------------- write

    def _flush(self):
        put = self._store.put
        for h, blob in _mpt.drain(self._h):
            put(h, blob)

    def set(self, key: bytes, value: bytes):
        self.root_hash = _mpt.set(self._h, self.root_hash, bytes(key),
                                  bytes(value))
        self._flush()

    def set_many(self, pairs):
        """Batched set (empty value deletes): one deferred-hash C pass —
        path nodes shared by the batch hash once, not once per key.
        Only the final root is a readable snapshot."""
        self.root_hash = _mpt.set_many(self._h, self.root_hash,
                                       list(pairs))
        self._flush()

    def delete(self, key: bytes):
        self.root_hash = _mpt.delete(self._h, self.root_hash, bytes(key))
        self._flush()

    # ----------------------------------------------------------- read

    def get(self, key: bytes) -> Optional[bytes]:
        return _mpt.get(self._h, self.root_hash, bytes(key))

    def get_at_root(self, root_hash: bytes, key: bytes) -> Optional[bytes]:
        return _mpt.get(self._h, bytes(root_hash), bytes(key))

    def produce_spv_proof(self, key: bytes,
                          root_hash: Optional[bytes] = None) -> List[bytes]:
        root = root_hash if root_hash is not None else self.root_hash
        return _mpt.proof(self._h, bytes(root), bytes(key))

    def items(self, root_hash: Optional[bytes] = None):
        root = root_hash if root_hash is not None else self.root_hash
        return iter(_mpt.items(self._h, bytes(root)))
