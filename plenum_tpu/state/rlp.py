"""Minimal RLP encoding (trie node serialization).

The reference uses RLP for MPT nodes (state/util/fast_rlp.py). Wire
compatibility with Ethereum is not a goal, but RLP is compact, canonical,
and self-delimiting, so trie hashes are well-defined. Supports bytes and
(nested) lists of bytes — all a trie node needs.
"""
from typing import List, Tuple, Union

RlpItem = Union[bytes, List["RlpItem"]]


# one-byte length prefixes, precomputed (the hot path: trie refs are
# 32-byte hashes and node bodies are usually short)
_STR_PFX = [bytes([0x80 + n]) for n in range(56)]
_LIST_PFX = [bytes([0xC0 + n]) for n in range(56)]


def encode(item: RlpItem) -> bytes:
    t = type(item)
    if t is bytes:
        n = len(item)
        if n == 1 and item[0] < 0x80:
            return item
        if n < 56:
            return _STR_PFX[n] + item
        return _len_prefix(n, 0x80) + item
    if t is list or t is tuple:
        parts = []
        for x in item:
            if type(x) is bytes:          # inline the dominant case
                n = len(x)
                if n == 1 and x[0] < 0x80:
                    parts.append(x)
                elif n < 56:
                    parts.append(_STR_PFX[n] + x)
                else:
                    parts.append(_len_prefix(n, 0x80) + x)
            else:
                parts.append(encode(x))
        body = b"".join(parts)
        n = len(body)
        if n < 56:
            return _LIST_PFX[n] + body
        return _len_prefix(n, 0xC0) + body
    # subclasses (and bytearray) take the old isinstance-based path —
    # the exact-type checks above are only a fast path, not a contract
    # change
    if isinstance(item, (bytes, bytearray)):
        return encode(bytes(item))
    if isinstance(item, (list, tuple)):
        return encode(list(item))
    raise TypeError("cannot RLP-encode {}".format(type(item)))


def _len_prefix(length: int, offset: int) -> bytes:
    if length < 56:
        return bytes([offset + length])
    ll = length.to_bytes((length.bit_length() + 7) // 8, "big")
    return bytes([offset + 55 + len(ll)]) + ll


def decode(data: bytes) -> RlpItem:
    item, rest = _decode_one(bytes(data))
    if rest:
        raise ValueError("trailing RLP bytes")
    return item


def _decode_one(data: bytes) -> Tuple[RlpItem, bytes]:
    if not data:
        raise ValueError("empty RLP")
    b0 = data[0]
    if b0 < 0x80:
        return data[:1], data[1:]
    if b0 < 0xB8:  # short string
        n = b0 - 0x80
        _check(data, 1 + n)
        if n == 1 and data[1] < 0x80:
            raise ValueError("non-canonical RLP single byte")
        return data[1:1 + n], data[1 + n:]
    if b0 < 0xC0:  # long string
        ln = b0 - 0xB7
        n = _read_len(data, ln, 56)
        return data[1 + ln:1 + ln + n], data[1 + ln + n:]
    if b0 < 0xF8:  # short list
        n = b0 - 0xC0
        _check(data, 1 + n)
        return _decode_list(data[1:1 + n]), data[1 + n:]
    ln = b0 - 0xF7  # long list
    n = _read_len(data, ln, 56)
    return _decode_list(data[1 + ln:1 + ln + n]), data[1 + ln + n:]


def _read_len(data: bytes, ln: int, minimum: int) -> int:
    _check(data, 1 + ln)
    if data[1] == 0:
        raise ValueError("leading zero in RLP length")
    n = int.from_bytes(data[1:1 + ln], "big")
    if n < minimum:
        raise ValueError("non-canonical RLP length")
    _check(data, 1 + ln + n)
    return n


def _decode_list(body: bytes) -> List[RlpItem]:
    out = []
    while body:
        item, body = _decode_one(body)
        out.append(item)
    return out


def _check(data: bytes, need: int):
    if len(data) < need:
        raise ValueError("truncated RLP")
