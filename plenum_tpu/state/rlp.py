"""Minimal RLP encoding (trie node serialization).

The reference uses RLP for MPT nodes (state/util/fast_rlp.py). Wire
compatibility with Ethereum is not a goal, but RLP is compact, canonical,
and self-delimiting, so trie hashes are well-defined. Supports bytes and
(nested) lists of bytes — all a trie node needs.

This file is the REFERENCE implementation; the native CPython extension
(native/rlp_c.c — the role the reference delegates to its C rlp/leveldb
dependencies) replaces `encode`/`decode` at import when a compiler is
available. Tests cross-check the two (tests/test_state.py).
"""
from typing import List, Tuple, Union

RlpItem = Union[bytes, List["RlpItem"]]

# both backends bound LIST nesting identically (DoS guard; trie nodes
# are depth <= 2): a list nested more than MAX_DEPTH levels deep is
# invalid to encode AND to decode, in the C and Python codecs alike —
# the backends MUST agree on validity or nodes with and without a C
# compiler would diverge, and encode must never produce what decode
# rejects. Bytes leaves carry no depth of their own.
MAX_DEPTH = 64


# one-byte length prefixes, precomputed (the hot path: trie refs are
# 32-byte hashes and node bodies are usually short)
_STR_PFX = [bytes([0x80 + n]) for n in range(56)]
_LIST_PFX = [bytes([0xC0 + n]) for n in range(56)]


def _encode_py(item: RlpItem, _depth: int = 0) -> bytes:
    t = type(item)
    if t is bytes:
        n = len(item)
        if n == 1 and item[0] < 0x80:
            return item
        if n < 56:
            return _STR_PFX[n] + item
        return _len_prefix(n, 0x80) + item
    if t is list or t is tuple:
        if _depth >= MAX_DEPTH:
            raise ValueError("RLP nesting too deep")
        parts = []
        for x in item:
            if type(x) is bytes:          # inline the dominant case
                n = len(x)
                if n == 1 and x[0] < 0x80:
                    parts.append(x)
                elif n < 56:
                    parts.append(_STR_PFX[n] + x)
                else:
                    parts.append(_len_prefix(n, 0x80) + x)
            else:
                parts.append(_encode_py(x, _depth + 1))
        body = b"".join(parts)
        n = len(body)
        if n < 56:
            return _LIST_PFX[n] + body
        return _len_prefix(n, 0xC0) + body
    # subclasses (and bytearray) take the old isinstance-based path —
    # the exact-type checks above are only a fast path, not a contract
    # change
    if isinstance(item, (bytes, bytearray)):
        return _encode_py(bytes(item), _depth)
    if isinstance(item, (list, tuple)):
        return _encode_py(list(item), _depth)
    raise TypeError("cannot RLP-encode {}".format(type(item)))


def _len_prefix(length: int, offset: int) -> bytes:
    if length < 56:
        return bytes([offset + length])
    ll = length.to_bytes((length.bit_length() + 7) // 8, "big")
    return bytes([offset + 55 + len(ll)]) + ll


def decode(data: bytes) -> RlpItem:
    data = bytes(data)
    item, pos = _decode_at(data, 0, len(data), 0)
    if pos != len(data):
        raise ValueError("trailing RLP bytes")
    return item


def _decode_at(data: bytes, pos: int, end: int,
               depth: int = 0) -> Tuple[RlpItem, int]:
    """Decode one item at offset `pos`, bounded by `end`; returns
    (item, next_pos). Offset-based so only final payloads are sliced —
    the old remainder-slicing decoder copied O(n²) bytes on branch
    nodes (this is the hottest path in the MPT)."""
    if pos >= end:
        raise ValueError("empty RLP")
    b0 = data[pos]
    if b0 < 0x80:
        return data[pos:pos + 1], pos + 1
    if b0 < 0xB8:  # short string
        n = b0 - 0x80
        nxt = pos + 1 + n
        if nxt > end:
            raise ValueError("truncated RLP")
        if n == 1 and data[pos + 1] < 0x80:
            raise ValueError("non-canonical RLP single byte")
        return data[pos + 1:nxt], nxt
    if b0 < 0xC0:  # long string
        body, nxt = _read_len_at(data, pos, b0 - 0xB7, 56, end)
        return data[body:nxt], nxt
    if 0xC0 <= b0 and depth >= MAX_DEPTH:
        raise ValueError("RLP nesting too deep")
    if b0 < 0xF8:  # short list
        n = b0 - 0xC0
        nxt = pos + 1 + n
        if nxt > end:
            raise ValueError("truncated RLP")
        body = pos + 1
    else:  # long list
        body, nxt = _read_len_at(data, pos, b0 - 0xF7, 56, end)
    out = []
    p = body
    while p < nxt:
        item, p = _decode_at(data, p, nxt, depth + 1)
        out.append(item)
    return out, nxt


def _read_len_at(data: bytes, pos: int, ln: int, minimum: int,
                 end: int) -> Tuple[int, int]:
    """→ (payload_start, payload_end) for a long-form item at pos."""
    if pos + 1 + ln > end:
        raise ValueError("truncated RLP")
    if data[pos + 1] == 0:
        raise ValueError("leading zero in RLP length")
    n = int.from_bytes(data[pos + 1:pos + 1 + ln], "big")
    if n < minimum:
        raise ValueError("non-canonical RLP length")
    start = pos + 1 + ln
    if start + n > end:
        raise ValueError("truncated RLP")
    return start, start + n


# keep the pure-Python pair importable regardless of backend (the
# implementations self-recurse, so the native override below cannot
# hijack their internals)
encode_py = _encode_py
decode_py = decode
encode = _encode_py

# the central optional-native guard (native.try_load_ext) owns the
# build-failure policy — no local broad except (PT006), and the
# PLENUM_TPU_NO_NATIVE kill-switch now covers the RLP codec too
from plenum_tpu.native import try_load_ext

_c = try_load_ext("rlp_c")
if _c is not None:
    encode = _c.encode
    decode = _c.decode
    BACKEND = "native"
else:                                  # pragma: no cover - cc missing
    BACKEND = "python"
