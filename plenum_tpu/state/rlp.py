"""Minimal RLP encoding (trie node serialization).

The reference uses RLP for MPT nodes (state/util/fast_rlp.py). Wire
compatibility with Ethereum is not a goal, but RLP is compact, canonical,
and self-delimiting, so trie hashes are well-defined. Supports bytes and
(nested) lists of bytes — all a trie node needs.
"""
from typing import List, Tuple, Union

RlpItem = Union[bytes, List["RlpItem"]]


def encode(item: RlpItem) -> bytes:
    if isinstance(item, (bytes, bytearray)):
        item = bytes(item)
        if len(item) == 1 and item[0] < 0x80:
            return item
        return _len_prefix(len(item), 0x80) + item
    if isinstance(item, (list, tuple)):
        body = b"".join(encode(x) for x in item)
        return _len_prefix(len(body), 0xC0) + body
    raise TypeError("cannot RLP-encode {}".format(type(item)))


def _len_prefix(length: int, offset: int) -> bytes:
    if length < 56:
        return bytes([offset + length])
    ll = length.to_bytes((length.bit_length() + 7) // 8, "big")
    return bytes([offset + 55 + len(ll)]) + ll


def decode(data: bytes) -> RlpItem:
    item, rest = _decode_one(bytes(data))
    if rest:
        raise ValueError("trailing RLP bytes")
    return item


def _decode_one(data: bytes) -> Tuple[RlpItem, bytes]:
    if not data:
        raise ValueError("empty RLP")
    b0 = data[0]
    if b0 < 0x80:
        return data[:1], data[1:]
    if b0 < 0xB8:  # short string
        n = b0 - 0x80
        _check(data, 1 + n)
        if n == 1 and data[1] < 0x80:
            raise ValueError("non-canonical RLP single byte")
        return data[1:1 + n], data[1 + n:]
    if b0 < 0xC0:  # long string
        ln = b0 - 0xB7
        n = _read_len(data, ln, 56)
        return data[1 + ln:1 + ln + n], data[1 + ln + n:]
    if b0 < 0xF8:  # short list
        n = b0 - 0xC0
        _check(data, 1 + n)
        return _decode_list(data[1:1 + n]), data[1 + n:]
    ln = b0 - 0xF7  # long list
    n = _read_len(data, ln, 56)
    return _decode_list(data[1 + ln:1 + ln + n]), data[1 + ln + n:]


def _read_len(data: bytes, ln: int, minimum: int) -> int:
    _check(data, 1 + ln)
    if data[1] == 0:
        raise ValueError("leading zero in RLP length")
    n = int.from_bytes(data[1:1 + ln], "big")
    if n < minimum:
        raise ValueError("non-canonical RLP length")
    _check(data, 1 + ln + n)
    return n


def _decode_list(body: bytes) -> List[RlpItem]:
    out = []
    while body:
        item, body = _decode_one(body)
        out.append(item)
    return out


def _check(data: bytes, need: int):
    if len(data) < need:
        raise ValueError("truncated RLP")
