"""Device-resident MPT state engine — batched trie reads, level-wise
SHA3 node hashing, merged multi-state hash resolution, and state
proofs at read scale.

``state/`` was the last pure-Python crypto hot path: the trie walks one
key at a time and hashes every dirty node one ``hashlib.sha3_256`` call
at a time (state/trie.py). This engine attaches BEHIND
``PruningState``/``Trie`` the same way ``DeviceMerkleTree`` attaches
behind ``CompactMerkleTree`` (attach seam + config batch threshold +
host-fallback circuit breaker in pruning_state.py) and serves three
batched operations, each decomposed into per-LEVEL device dispatches
(ops/trie_jax.py → ops/sha3.py Keccak kernel):

 - ``get_batch``: many key walks advance in lockstep; all level-N node
   loads are deduplicated across keys and hash-verified against their
   refs in ONE fused device dispatch per level (only a bool verdict
   crosses back), then HP-decoded on host and advanced one step. A
   corrupted store can never serve a value that does not hash to the
   root — the host path trusts the store, the device path re-verifies
   for free while batching.
 - ``apply_batch``: a whole 3PC batch's writes run through a
 deferred-hash trie (structural inserts/deletes only — no hashing);
   the dirty nodes are then resolved bottom-up, one device SHA3
   dispatch per level, so path nodes shared by the batch hash once,
   not once per request. Returns the new state root and persists
   exactly the final tree's nodes (same contract as the native
   ``set_many``: only the final root is a readable snapshot).
 - ``proof_batch``: SPV ``proof_nodes`` for hundreds of keys in one
   engine call — the same deduplicated level walk as ``get_batch``
   (shared spine nodes load and verify once per level, not once per
   key), emitting per-key proofs byte-identical to
   ``Trie.produce_spv_proof``.

Results are byte-equal to the pure-Python ``Trie`` (roots, values and
proof nodes — randomized equivalence in tests/test_device_state.py);
levels below ``Config.STATE_DEVICE_HASH_FLOOR`` use hashlib on host,
where the scalar path wins on latency (the root level is one node).

The conflict-lane executor (server/executor.py, PR 13) splits
``apply_batch`` into two halves so MANY states' batches share one set
of hash dispatches per applied 3PC batch:

 - ``begin_apply``: the structural half alone — a whole batch's writes
   merge into the standing trie through ONE recursive bulk merge
   (``_bulk_merge``: sorted keys descend shared path nodes once per
   batch, not once per key — ~2x fewer node visits/copies than per-key
   ``_update`` walks), returning a ``_DeferredApply`` whose dirty
   nodes await hashing.
 - ``resolve_applies``: resolves ANY number of deferred applies (one
   per written state — domain / pool / config in a mixed batch)
   bottom-up with SHARED level-wise SHA3 dispatches: level N of every
   participating trie hashes in the same launch, so lanes and ledgers
   merge at the hash step for free. Hash routing follows the sha256
   "tiled"-backend precedent: device dispatches only where a real
   accelerator serves them (``Config.EXEC_MERGED_DEVICE_HASH`` =
   "auto"); on CPU hosts hashlib beats per-level dispatch overhead at
   MPT node counts.

Both halves are byte-equal to ``apply_batch`` (and to the host trie):
the MPT is content-canonical, so the bulk merge and the per-key walk
produce the identical tree for the identical final mapping.
"""
from __future__ import annotations

import hashlib
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

from plenum_tpu.observability.tracing import CAT_DEVICE, NullTracer
from plenum_tpu.state import rlp
from plenum_tpu.state.trie import (
    BLANK_NODE, BLANK_ROOT, Trie, bytes_to_nibbles, hp_decode, hp_encode)


class CorruptStateError(Exception):
    """A stored trie node does not hash to the ref that points at it."""


class _DeferredTrie(Trie):
    """Trie whose ``_ref`` defers hashing: a batch of updates builds an
    in-memory nested-list node tree (children held inline regardless of
    encoded size); the engine then resolves refs bottom-up with one
    batched SHA3 dispatch per level. Reads during the update (_load)
    still hit the store for untouched subtrees."""

    def _ref(self, node):
        if node == BLANK_NODE:
            return BLANK_NODE
        return node


# ------------------------------------------------ bulk structural merge
#
# One recursive merge of a SORTED key set into the standing trie: every
# shared path node (the root branch, hot spine extensions) is loaded
# and copied once per batch, where per-key ``_update`` walks copy it
# once per key. The MPT is content-canonical — the same final mapping
# yields the same tree whatever the insertion schedule — so the merge
# is byte-equal to per-key updates (randomized equivalence in
# tests/test_executor_lanes.py). Deletes stay on the per-key ``_delete``
# path (branch collapse is order-local and deletes are rare).

def _lcp_sorted(items) -> int:
    """Longest common prefix length over sorted item nibble tuples —
    for a sorted list this is lcp(first, last)."""
    first = items[0][0]
    last = items[-1][0]
    m = 0
    n = min(len(first), len(last))
    while m < n and first[m] == last[m]:
        m += 1
    return m


def _build_subtree(items):
    """Fresh in-memory subtree for sorted (nibbles, value) items under
    a blank slot. Unique keys; at most one item can terminate exactly
    at the common prefix (a prefix sorts before its extensions)."""
    if len(items) == 1:
        nib, val = items[0]
        return [hp_encode(list(nib), True), val]
    m = _lcp_sorted(items)
    branch = [BLANK_NODE] * 16 + [BLANK_NODE]
    i = 0
    n = len(items)
    if len(items[0][0]) == m:
        branch[16] = items[0][1]
        i = 1
    while i < n:
        c = items[i][0][m]
        j = i
        while j < n and items[j][0][m] == c:
            j += 1
        branch[c] = _build_subtree(
            [(nib[m + 1:], val) for nib, val in items[i:j]])
        i = j
    if m:
        return [hp_encode(list(items[0][0][:m]), False), branch]
    return branch


def _bulk_merge(trie, node, items):
    """Merge sorted (nibbles, value) items into ``node`` (in-memory
    form, deferred refs — children held inline). → the new node."""
    if not items:
        return node
    if node == BLANK_NODE:
        return _build_subtree(items)
    if len(node) == 17:  # branch: group items by first nibble
        node = list(node)
        i = 0
        n = len(items)
        if len(items[0][0]) == 0:
            node[16] = items[0][1]
            i = 1
        while i < n:
            c = items[i][0][0]
            j = i
            while j < n and items[j][0][0] == c:
                j += 1
            group = [(nib[1:], val) for nib, val in items[i:j]]
            node[c] = _bulk_merge(trie, trie._load(node[c]), group)
            i = j
        return node
    path, terminal = hp_decode(bytes(node[0]))
    if terminal:
        # absorb the existing leaf as one more item (an exact-match
        # item overwrites it) and rebuild the subtree fresh
        merged = {tuple(path): bytes(node[1])}
        for nib, val in items:
            merged[tuple(nib)] = val
        return _build_subtree(sorted(merged.items()))
    # extension: find the earliest divergence of any item against path
    tp = tuple(path)
    lp = len(tp)
    m = lp
    for nib, _ in items:
        k = 0
        n2 = min(len(nib), lp)
        while k < n2 and nib[k] == tp[k]:
            k += 1
        if k < m:
            m = k
            if m == 0:
                break
    if m == lp:  # every item continues through the extension
        sub = _bulk_merge(trie, trie._load(node[1]),
                          [(nib[lp:], val) for nib, val in items])
        return [node[0], sub]
    # branch at the divergence point; the extension remainder keeps
    # the old child (same shapes per-key _update produces on a split)
    rest = tp[m:]
    branch = [BLANK_NODE] * 16 + [BLANK_NODE]
    if len(rest) == 1:
        branch[rest[0]] = node[1]
    else:
        branch[rest[0]] = [hp_encode(list(rest[1:]), False), node[1]]
    branch = _bulk_merge(trie, branch,
                         [(nib[m:], val) for nib, val in items])
    if m:
        return [hp_encode(list(tp[:m]), False), branch]
    return branch


class _Walk:
    """One key's position in the level-wise batched walk."""

    __slots__ = ("nibbles", "node", "value", "done", "proof")

    def __init__(self, key: bytes, want_proof: bool):
        self.nibbles = bytes_to_nibbles(key)
        self.node = None
        self.value: Optional[bytes] = None
        self.done = False
        self.proof: Optional[List[bytes]] = [] if want_proof else None


class DeviceStateEngine:
    """Batched MPT operations over a trie node store (hash → RLP blob),
    with all level-N node hashing issued as one device dispatch."""

    def __init__(self, store, tracer=None, hash_floor: Optional[int] = None):
        """store: the SAME KeyValueStorage the host trie persists into
        (both backends write identical hash → RLP blobs, so the engine
        reads either's nodes). hash_floor: per-dispatch batch size
        below which hashlib wins on latency (default from Config)."""
        from plenum_tpu.common.config import Config
        self._store = store
        self.tracer = tracer or NullTracer()
        self.hash_floor = (Config.STATE_DEVICE_HASH_FLOOR
                           if hash_floor is None else hash_floor)
        # stats for validator info / bench
        self.dispatches = 0
        self.host_hash_calls = 0

    # ------------------------------------------------------------ hashing

    def _verify_level(self, blobs: List[bytes], refs: List[bytes]) -> None:
        """Hash-verify a level of loaded blobs against their refs —
        fused hash+compare on device (one bool per node crosses back)."""
        if len(blobs) < self.hash_floor:
            self.host_hash_calls += 1
            for blob, ref in zip(blobs, refs):
                if hashlib.sha3_256(blob).digest() != ref:
                    raise CorruptStateError(
                        "trie node {} does not match its stored "
                        "bytes".format(ref.hex()))
            return
        from plenum_tpu.ops import trie_jax
        self.dispatches += 1
        ok = trie_jax.collect_node_verify_batch(
            trie_jax.dispatch_node_verify_batch(blobs, refs))
        if not ok.all():
            bad = [refs[i].hex() for i in range(len(refs)) if not ok[i]]
            raise CorruptStateError(
                "trie node(s) {} do not match their stored "
                "bytes".format(", ".join(bad)))

    def warm(self) -> None:
        """Compile the SHA3 kernels (hash + fused verify) for the
        bucket shapes the serving path actually hits: device levels
        are always >= hash_floor rows (smaller levels take hashlib)
        and batch axes pad to powers of two, so one compile at the
        hash_floor bucket per common node-size class (1-block leaves,
        4-block branches: 17 refs ≈ 530 encoded bytes) covers the
        first serving batches. The persistent XLA cache makes this a
        once-per-host cost."""
        from plenum_tpu.ops import trie_jax
        b = max(2, self.hash_floor)
        for size in (64, 300):  # nblocks buckets 1 and 4
            blobs = [b"%d" % i + b"w" * size for i in range(b)]
            digs = [bytes(r) for r in trie_jax.collect_node_hash_batch(
                trie_jax.dispatch_node_hash_batch(blobs))]
            trie_jax.collect_node_verify_batch(
                trie_jax.dispatch_node_verify_batch(blobs, digs))

    # ---------------------------------------------------- level-wise walk

    def _load_blob(self, ref: bytes) -> bytes:
        try:
            return bytes(self._store.get(ref))
        except KeyError:
            raise KeyError("missing trie node {}".format(ref.hex()))

    def _walk_batch(self, root_hash: bytes, keys: Sequence[bytes],
                    want_proof: bool) -> List[_Walk]:
        walks = [_Walk(bytes(k), want_proof) for k in keys]
        if root_hash == BLANK_ROOT:
            for w in walks:
                w.done = True
            return walks
        root_blob = self._load_blob(bytes(root_hash))
        self._verify_level([root_blob], [bytes(root_hash)])
        root_node = rlp.decode(root_blob)
        for w in walks:
            w.node = root_node
        active = walks
        decoded: Dict[bytes, object] = {}
        while active:
            # advance every walk until it terminates or needs a stored
            # node; collect the level's unique refs across all keys
            need: Dict[bytes, List[_Walk]] = {}
            for w in active:
                ref = self._advance(w)
                if ref is not None:
                    need.setdefault(ref, []).append(w)
            if not need:
                break
            refs = [r for r in need if r not in decoded]
            if refs:
                blobs = [self._load_blob(r) for r in refs]
                self._verify_level(blobs, refs)
                for r, blob in zip(refs, blobs):
                    decoded[r] = rlp.decode(blob)
            active = []
            for r, waiting in need.items():
                node = decoded[r]
                for w in waiting:
                    w.node = node
                    active.append(w)
        return walks

    def _advance(self, w: _Walk) -> Optional[bytes]:
        """Advance one walk through inline nodes until it finishes
        (w.done) or needs a 32-byte stored ref (returned). Mirrors
        Trie._get and Trie.produce_spv_proof exactly — values, proof
        node sequences and termination conditions are byte-identical."""
        while True:
            node = w.node
            if w.proof is not None:
                w.proof.append(rlp.encode(node))
            if node == BLANK_NODE:
                w.done = True
                return None
            if len(node) == 17:  # branch
                if not w.nibbles:
                    w.value = bytes(node[16]) or None
                    w.done = True
                    return None
                ref = node[w.nibbles[0]]
                w.nibbles = w.nibbles[1:]
                if ref == BLANK_NODE:
                    w.done = True
                    return None
            else:  # leaf or extension
                path, terminal = hp_decode(bytes(node[0]))
                if terminal:
                    if path == w.nibbles:
                        w.value = bytes(node[1])
                    w.done = True
                    return None
                if w.nibbles[:len(path)] != path:
                    w.done = True
                    return None
                w.nibbles = w.nibbles[len(path):]
                ref = node[1]
            # resolve the ref like Trie._load, deferring only store IO
            if isinstance(ref, list):
                w.node = ref
                continue
            ref = bytes(ref)
            if len(ref) == 32:
                return ref
            w.node = rlp.decode(ref)

    # ------------------------------------------------------------- reads

    def get_batch(self, root_hash: bytes, keys: Sequence[bytes]
                  ) -> List[Optional[bytes]]:
        """Values for many keys under one root; all level-N node loads
        are hash-verified in one device dispatch per level."""
        with self.tracer.span("state_get", CAT_DEVICE, n=len(keys)):
            walks = self._walk_batch(root_hash, keys, want_proof=False)
        return [w.value for w in walks]

    def proof_batch(self, root_hash: bytes, keys: Sequence[bytes]
                    ) -> List[List[bytes]]:
        """SPV proof nodes for many keys under one root, byte-identical
        to Trie.produce_spv_proof per key — the shared spine loads and
        hash-verifies once per level, not once per key."""
        with self.tracer.span("state_proof", CAT_DEVICE, n=len(keys)):
            walks = self._walk_batch(root_hash, keys, want_proof=True)
        return [w.proof for w in walks]

    def get_with_proof_batch(self, root_hash: bytes,
                             keys: Sequence[bytes]):
        """→ (values, proofs) for many keys from ONE walk — the proof
        walk resolves every key's value anyway, so the read-serving
        path (value + proof per reply) pays one set of store loads and
        device verifies, not two."""
        with self.tracer.span("state_proof", CAT_DEVICE, n=len(keys)):
            walks = self._walk_batch(root_hash, keys, want_proof=True)
        return [w.value for w in walks], [w.proof for w in walks]

    # ------------------------------------------------------------- apply

    def apply_batch(self, root_hash: bytes,
                    pairs: Sequence[Tuple[bytes, bytes]]) -> bytes:
        """Apply a whole batch of (key, value) writes (empty value =
        delete) on top of `root_hash`: structural trie work on host
        with DEFERRED hashing, then every dirty node hashed level-wise
        on device and persisted. → the new state root (byte-equal to
        applying the same final mapping through the host trie)."""
        with self.tracer.span("state_apply", CAT_DEVICE,
                              n=len(pairs)) as sp:
            d0 = self.dispatches
            trie = _DeferredTrie(self._store, bytes(root_hash))
            node = trie._root_node()
            for k, v in pairs:
                nib = bytes_to_nibbles(bytes(k))
                if v:
                    node = trie._update(node, nib, bytes(v))
                else:
                    node = trie._delete(node, nib)
            root = self._resolve_and_store(node)
            sp.add(dispatches=self.dispatches - d0)
            return root

    def _resolve_and_store(self, root_node) -> bytes:
        """Resolve every in-memory (list) node bottom-up: encode with
        children substituted by their resolved refs; nodes under 32
        encoded bytes stay inline (never persisted — same as _ref),
        larger ones batch into one SHA3 dispatch per level and are
        written through hash → blob. The root is always hashed and
        persisted (Trie._set_root contract). ONE implementation serves
        both the legacy whole-batch apply and the merged multi-state
        path: this is the single-handle case of ``_resolve_applies``,
        with device routing pinned on (the PR-6 contract — this seam's
        own ``hash_floor`` already keeps small levels on hashlib)."""
        return _resolve_applies([_DeferredApply(self, root_node, [])],
                                on_device=True, floor=self.hash_floor)[0]

    # ------------------------------------------- deferred (merged) apply

    def begin_apply(self, root_hash: bytes,
                    pairs: Sequence[Tuple[bytes, bytes]]) -> "_DeferredApply":
        """The structural half of apply_batch: merge the batch's writes
        into the standing trie through ONE recursive bulk merge (sorted
        keys descend shared path nodes once per batch) with hashing
        deferred. The returned handle's dirty nodes are resolved later —
        together with other states' handles — by :func:`resolve_applies`,
        so every lane's and every ledger's batch shares one set of
        level-wise SHA3 dispatches. ``begin_apply`` + single-handle
        ``resolve_applies`` is byte-equal to :meth:`apply_batch` (the
        MPT is content-canonical)."""
        trie = _DeferredTrie(self._store, bytes(root_hash))
        node = trie._root_node()
        sets = sorted((tuple(bytes_to_nibbles(bytes(k))), bytes(v))
                      for k, v in pairs if v)
        node = _bulk_merge(trie, node, sets)
        for k, v in pairs:
            if not v:
                node = trie._delete(node, bytes_to_nibbles(bytes(k)))
        return _DeferredApply(self, node, list(pairs))

    @staticmethod
    def _collect_heights(root_node):
        """Reachable in-memory nodes keyed by id, plus each node's
        height (1 + max child height; stored/inline bytes are height
        0). Iterative — spines can outgrow the recursion limit."""
        nodes: Dict[int, object] = {}
        heights: Dict[int, int] = {}
        stack = [(root_node, False)]
        while stack:
            node, processed = stack.pop()
            nid = id(node)
            if processed:
                h = 0
                for c in node:
                    if type(c) is list:
                        h = max(h, heights[id(c)] + 1)
                heights[nid] = h
                continue
            if nid in nodes:
                continue
            nodes[nid] = node
            stack.append((node, True))
            for c in node:
                if type(c) is list and id(c) not in nodes:
                    stack.append((c, False))
        return nodes, heights

    # ------------------------------------------------------------- stats

    def stats(self) -> dict:
        return {
            "hash_floor": self.hash_floor,
            "device_dispatches": self.dispatches,
            "host_hash_calls": self.host_hash_calls,
        }


class _DeferredApply:
    """One state's structural batch update awaiting hash resolution.

    ``pairs`` is retained so a failed merged resolve can fall back to
    the host trie path with the identical write set; ``state`` is set
    by PruningState.begin_flush_deferred so the resolver can hand each
    new root back to its owner."""

    __slots__ = ("engine", "root_node", "pairs", "state")

    def __init__(self, engine: DeviceStateEngine, root_node, pairs):
        self.engine = engine
        self.root_node = root_node
        self.pairs = pairs
        self.state = None


def merged_hash_on_device(use_device=None) -> bool:
    """Routing policy for the merged resolve's level hashing
    (``Config.EXEC_MERGED_DEVICE_HASH``): "auto" keeps device
    dispatches for hosts with a real accelerator and takes hashlib on
    CPU hosts, where per-level dispatch overhead loses to scalar SHA3
    at MPT node counts (the sha256 "tiled" CPU-backend precedent);
    True / False force one side (tests pin the dispatch path)."""
    if use_device is None:
        from plenum_tpu.common.config import Config
        use_device = getattr(Config, "EXEC_MERGED_DEVICE_HASH", "auto")
    if use_device == "auto":
        from plenum_tpu.ops.mesh import is_accelerator
        return is_accelerator()
    return bool(use_device)


def resolve_applies(applies: Sequence[_DeferredApply],
                    use_device=None) -> List[bytes]:
    """Resolve MANY deferred applies bottom-up with SHARED level-wise
    SHA3 dispatches: level N of every participating trie (one per
    written state — domain / pool / config in a mixed batch) hashes in
    the same launch, so execution lanes and ledgers merge at the hash
    step for free. → the new root per apply, byte-equal to resolving
    each handle alone (node digests are content hashes — independent
    of which launch computed them). Nodes under 32 encoded bytes stay
    inline (never persisted — the ``_ref`` contract); every root is
    hashed and persisted (``Trie._set_root`` contract)."""
    if not applies:
        return []
    on_device = merged_hash_on_device(use_device)
    floor = min(ap.engine.hash_floor for ap in applies)
    tracer = applies[0].engine.tracer
    n_pairs = sum(len(ap.pairs) for ap in applies)
    with tracer.span("state_apply_merged", CAT_DEVICE,
                     n=n_pairs, states=len(applies)) as sp:
        d0 = applies[0].engine.dispatches
        roots = _resolve_applies(applies, on_device, floor)
        sp.add(dispatches=applies[0].engine.dispatches - d0)
    return roots


def _hash_level_merged(applies, blobs, on_device, floor):
    """SHA3-256 one merged level: one device dispatch above the floor
    when routed on-device, hashlib otherwise (stats land on the first
    handle's engine — the launch is shared)."""
    if on_device and len(blobs) >= floor:
        from plenum_tpu.ops import trie_jax
        applies[0].engine.dispatches += 1
        return trie_jax.hash_nodes(blobs)
    applies[0].engine.host_hash_calls += 1
    return [hashlib.sha3_256(b).digest() for b in blobs]


def _resolve_applies(applies, on_device, floor) -> List[bytes]:
    roots: List[Optional[bytes]] = [None] * len(applies)
    # collect each handle's in-memory nodes keyed by id + height
    per = []
    by_height = defaultdict(list)   # height -> [(apply_idx, nid, node)]
    for ai, ap in enumerate(applies):
        if ap.root_node == BLANK_NODE:
            encoded = rlp.encode(b"")
            ap.engine._store.put(BLANK_ROOT, encoded)
            roots[ai] = BLANK_ROOT
            per.append(None)
            continue
        nodes, heights = DeviceStateEngine._collect_heights(ap.root_node)
        per.append(nodes)
        for nid, node in nodes.items():
            by_height[heights[nid]].append((ai, nid, node))
    resolved: List[Dict[int, object]] = [{} for _ in applies]
    root_ids = [id(ap.root_node) if per[ai] is not None else None
                for ai, ap in enumerate(applies)]
    root_encoded: List[Optional[bytes]] = [None] * len(applies)
    for h in sorted(by_height):
        level_owner: List[Tuple[int, int]] = []   # (apply_idx, nid)
        level_blobs: List[bytes] = []
        for ai, nid, node in by_height[h]:
            res = resolved[ai]
            subst = [res[id(c)] if type(c) is list else c for c in node]
            encoded = rlp.encode(subst)
            if nid == root_ids[ai]:
                root_encoded[ai] = encoded
            elif len(encoded) < 32:
                res[nid] = subst
            else:
                level_owner.append((ai, nid))
                level_blobs.append(encoded)
        if level_blobs:
            digs = _hash_level_merged(applies, level_blobs,
                                      on_device, floor)
            for (ai, nid), blob, dig in zip(level_owner, level_blobs,
                                            digs):
                applies[ai].engine._store.put(dig, blob)
                resolved[ai][nid] = dig
    for ai, ap in enumerate(applies):
        if roots[ai] is not None:
            continue
        dig = hashlib.sha3_256(root_encoded[ai]).digest()
        ap.engine._store.put(dig, root_encoded[ai])
        roots[ai] = dig
    return roots
