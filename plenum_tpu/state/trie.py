"""Hexary Merkle Patricia Trie over a KV node store, with SPV proofs.

Reference: state/trie/pruning_trie.py:215 (Trie), proof machinery at
:58 (ProofConstructor) and :1105+ (produce/verify). Same capability,
fresh implementation: sha3-256 node hashing (hashlib.sha3_256, as in
state/util/utils.py:7), RLP node encoding, hex-prefix path encoding,
inline references for nodes < 32 bytes.

Node shapes (RLP lists):
  blank     : b''
  leaf      : [hp_encode(nibbles, terminal=True), value]
  extension : [hp_encode(nibbles, terminal=False), ref]
  branch    : [ref0 .. ref15, value]
A ref is the node itself (if its RLP is < 32 bytes) or its sha3 hash.
Nodes are persisted hash → rlp in the KV store; nothing is deleted on
update (history stays readable for old roots — what "pruning" defers to
compaction in the reference as well).
"""
from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence, Tuple

from plenum_tpu.state import rlp

BLANK_NODE = b""
BLANK_ROOT = hashlib.sha3_256(rlp.encode(b"")).digest()


def sha3(data: bytes) -> bytes:
    return hashlib.sha3_256(data).digest()


_HEXVAL = {c: int(c, 16) for c in "0123456789abcdef"}


def bytes_to_nibbles(key: bytes) -> List[int]:
    # bytes.hex() runs in C; one dict hit per nibble beats two shifts +
    # two appends per byte (this is the hottest pure-Python trie helper)
    return [_HEXVAL[c] for c in key.hex()]


def hp_encode(nibbles: Sequence[int], terminal: bool) -> bytes:
    """Hex-prefix encoding: flags nibble (terminal|odd) + packed nibbles."""
    flags = 2 if terminal else 0
    if len(nibbles) % 2 == 1:
        flags |= 1
        nibbles = [flags, *nibbles]
    else:
        nibbles = [flags, 0, *nibbles]
    return bytes((nibbles[i] << 4) | nibbles[i + 1]
                 for i in range(0, len(nibbles), 2))


def hp_decode(data: bytes) -> Tuple[List[int], bool]:
    nibbles = bytes_to_nibbles(data)
    flags = nibbles[0]
    terminal = bool(flags & 2)
    skip = 1 if flags & 1 else 2
    return nibbles[skip:], terminal


from plenum_tpu.common.config import Config as _Config


class Trie:
    # single-sourced from Config (PT005): ONE place to tune the
    # decoded-node cache alongside the other STATE_* knobs
    _DECODE_CACHE_MAX = _Config.STATE_DECODE_CACHE_MAX

    def __init__(self, store, root_hash: Optional[bytes] = None):
        """store: KeyValueStorage-like (get/put raising KeyError on miss)."""
        self._store = store
        self.root_hash = root_hash if root_hash is not None else BLANK_ROOT
        # hash → decoded node. Nodes are content-addressed and immutable,
        # so the cache never goes stale; it just bounds memory. Kills the
        # dominant RLP re-decode cost on the hot write path.
        self._decoded: dict = {}

    # ----------------------------------------------------------- store IO

    def _load(self, ref):
        """Resolve a ref (inline node or 32-byte hash) to a decoded node."""
        if isinstance(ref, list):
            return ref
        if ref == BLANK_NODE:
            return BLANK_NODE
        if len(ref) == 32:
            cached = self._decoded.get(ref)
            if cached is None:
                try:
                    raw = self._store.get(ref)
                except KeyError:
                    raise KeyError("missing trie node {}".format(ref.hex()))
                cached = rlp.decode(raw)
                self._cache_decoded(ref, cached)
            # shallow copy: _update/_delete overwrite node slots in place
            return list(cached) if isinstance(cached, list) else cached
        return rlp.decode(ref)

    def _ref(self, node) -> rlp.RlpItem:
        """Persist node; return inline node if small, else its hash."""
        if node == BLANK_NODE:
            return BLANK_NODE
        encoded = rlp.encode(node)
        if len(encoded) < 32:
            return node
        h = sha3(encoded)
        self._store.put(h, encoded)
        # seed the decode cache: the next walk will load this node right
        # back (freshly written spine nodes ARE the hot set). Shallow
        # copy — callers overwrite slots of the list they passed in.
        self._cache_decoded(h, list(node))
        return h

    def _cache_decoded(self, ref: bytes, node) -> None:
        """Insert into the decode cache, evicting the older half at the
        cap (dicts iterate in insertion order) so neither the load nor
        the persist path can grow it unbounded."""
        if len(self._decoded) >= self._DECODE_CACHE_MAX:
            for stale in list(self._decoded)[:self._DECODE_CACHE_MAX // 2]:
                del self._decoded[stale]
        self._decoded[ref] = node

    def _root_node(self):
        if self.root_hash == BLANK_ROOT:
            return BLANK_NODE
        return self._load(self.root_hash)

    def _set_root(self, node):
        encoded = rlp.encode(node if node != BLANK_NODE else b"")
        h = sha3(encoded)
        self._store.put(h, encoded)
        self.root_hash = h

    # ------------------------------------------------------------ lookup

    def get(self, key: bytes) -> Optional[bytes]:
        return self._get(self._root_node(), bytes_to_nibbles(key))

    def get_at_root(self, root_hash: bytes, key: bytes) -> Optional[bytes]:
        node = BLANK_NODE if root_hash == BLANK_ROOT else self._load(root_hash)
        return self._get(node, bytes_to_nibbles(key))

    def _get(self, node, nibbles: List[int]) -> Optional[bytes]:
        if node == BLANK_NODE:
            return None
        if len(node) == 17:  # branch
            if not nibbles:
                return bytes(node[16]) or None
            child = self._load(node[nibbles[0]])
            return self._get(child, nibbles[1:])
        path, terminal = hp_decode(bytes(node[0]))
        if terminal:
            return bytes(node[1]) if path == nibbles else None
        if nibbles[:len(path)] != path:
            return None
        return self._get(self._load(node[1]), nibbles[len(path):])

    # ------------------------------------------------------------ update

    def set(self, key: bytes, value: bytes):
        if not value:
            return self.delete(key)
        root = self._update(self._root_node(), bytes_to_nibbles(key),
                            bytes(value))
        self._set_root(root)

    def _update(self, node, nibbles: List[int], value: bytes):
        if node == BLANK_NODE:
            return [hp_encode(nibbles, True), value]
        if len(node) == 17:  # branch
            node = list(node)
            if not nibbles:
                node[16] = value
            else:
                child = self._load(node[nibbles[0]])
                node[nibbles[0]] = self._ref(
                    self._update(child, nibbles[1:], value))
            return node
        # leaf or extension
        path, terminal = hp_decode(bytes(node[0]))
        common = 0
        while common < len(path) and common < len(nibbles) \
                and path[common] == nibbles[common]:
            common += 1
        if terminal and path == nibbles:
            return [node[0], value]
        if not terminal and common == len(path):
            sub = self._update(self._load(node[1]), nibbles[common:], value)
            return [node[0], self._ref(sub)]
        # split: branch at the divergence point
        branch = [BLANK_NODE] * 16 + [BLANK_NODE]
        old_rest = path[common:]
        if terminal:
            if old_rest:
                branch[old_rest[0]] = self._ref(
                    [hp_encode(old_rest[1:], True), node[1]])
            else:
                branch[16] = node[1]
        else:
            if len(old_rest) > 1:
                branch[old_rest[0]] = self._ref(
                    [hp_encode(old_rest[1:], False), node[1]])
            else:
                branch[old_rest[0]] = node[1]
        new_rest = nibbles[common:]
        if new_rest:
            branch[new_rest[0]] = self._ref(
                [hp_encode(new_rest[1:], True), value])
        else:
            branch[16] = value
        if common:
            return [hp_encode(nibbles[:common], False), self._ref(branch)]
        return branch

    # ------------------------------------------------------------ delete

    def delete(self, key: bytes):
        root = self._delete(self._root_node(), bytes_to_nibbles(key))
        self._set_root(root)

    def _delete(self, node, nibbles: List[int]):
        if node == BLANK_NODE:
            return BLANK_NODE
        if len(node) == 17:
            node = list(node)
            if not nibbles:
                node[16] = BLANK_NODE
            else:
                child = self._delete(self._load(node[nibbles[0]]), nibbles[1:])
                node[nibbles[0]] = self._ref(child)
            return self._normalize_branch(node)
        path, terminal = hp_decode(bytes(node[0]))
        if terminal:
            return BLANK_NODE if path == nibbles else node
        if nibbles[:len(path)] != path:
            return node
        sub = self._delete(self._load(node[1]), nibbles[len(path):])
        if sub == BLANK_NODE:
            return BLANK_NODE
        return self._merge_extension(path, sub)

    def _normalize_branch(self, node):
        """Collapse a branch with < 2 occupied slots."""
        occupied = [i for i in range(16) if node[i] != BLANK_NODE]
        has_value = node[16] != BLANK_NODE
        if len(occupied) + (1 if has_value else 0) > 1:
            return node
        if has_value:
            return [hp_encode([], True), node[16]]
        if not occupied:
            return BLANK_NODE
        i = occupied[0]
        child = self._load(node[i])
        return self._merge_extension([i], child)

    def _merge_extension(self, path: List[int], child):
        """Prepend `path` to child, merging leaf/extension paths."""
        if child == BLANK_NODE:
            return BLANK_NODE
        if len(child) == 17:
            return [hp_encode(path, False), self._ref(child)]
        sub_path, terminal = hp_decode(bytes(child[0]))
        return [hp_encode(list(path) + sub_path, terminal), child[1]]

    # ------------------------------------------------------------- proofs

    def produce_spv_proof(self, key: bytes,
                          root_hash: Optional[bytes] = None) -> List[bytes]:
        """Encoded trie nodes along the path root → key (SPV proof;
        reference pruning_trie.py:1105+)."""
        root_hash = root_hash if root_hash is not None else self.root_hash
        proof: List[bytes] = []
        if root_hash == BLANK_ROOT:
            return proof
        node = self._load(root_hash)
        nibbles = bytes_to_nibbles(key)
        while True:
            # every visited node goes in; inline nodes are redundant (they
            # live inside the parent's encoding) but harmless
            proof.append(rlp.encode(node))
            if node == BLANK_NODE:
                return proof
            if len(node) == 17:  # branch
                if not nibbles:
                    return proof
                ref = node[nibbles[0]]
                nibbles = nibbles[1:]
                if ref == BLANK_NODE:
                    return proof
                node = self._load(ref)
                continue
            path, terminal = hp_decode(bytes(node[0]))
            if terminal or nibbles[:len(path)] != path:
                return proof
            nibbles = nibbles[len(path):]
            node = self._load(node[1])

    # -------------------------------------------------------------- misc

    def items(self, root_hash: Optional[bytes] = None):
        """Iterate (key, value) pairs under a root."""
        root_hash = root_hash if root_hash is not None else self.root_hash
        node = BLANK_NODE if root_hash == BLANK_ROOT else self._load(root_hash)
        yield from self._walk(node, [])

    def _walk(self, node, prefix: List[int]):
        if node == BLANK_NODE:
            return
        if len(node) == 17:
            if node[16] != BLANK_NODE:
                yield _nibbles_to_bytes(prefix), bytes(node[16])
            for i in range(16):
                if node[i] != BLANK_NODE:
                    yield from self._walk(self._load(node[i]), prefix + [i])
            return
        path, terminal = hp_decode(bytes(node[0]))
        if terminal:
            yield _nibbles_to_bytes(prefix + path), bytes(node[1])
        else:
            yield from self._walk(self._load(node[1]), prefix + path)


def _nibbles_to_bytes(nibbles: List[int]) -> bytes:
    assert len(nibbles) % 2 == 0
    return bytes((nibbles[i] << 4) | nibbles[i + 1]
                 for i in range(0, len(nibbles), 2))


def verify_proof(root_hash: bytes, key: bytes, value: Optional[bytes],
                 proof_nodes: Sequence[bytes]) -> bool:
    """Stateless SPV verification: replay `proof_nodes` as a node store
    keyed by hash; membership (value == stored) or non-membership
    (value is None) both verifiable."""
    class _Dict:
        def __init__(self, items):
            self._d = {sha3(n): bytes(n) for n in items}

        def get(self, k):
            return self._d[k]

        def put(self, k, v):
            self._d[k] = v

    if root_hash == BLANK_ROOT and not proof_nodes:
        return value is None
    trie = Trie(_Dict(proof_nodes), root_hash)
    try:
        got = trie.get(key)
    except KeyError:
        return False
    return got == value
