"""State: committed vs uncommitted heads over the trie.

Reference: state/state.py:5 (State ABC), state/pruning_state.py:14
(PruningState). `headHash` moves with every applied-but-uncommitted batch;
`committedHeadHash` moves only on 3PC commit; revert rewinds head to the
committed root (the trie keeps all nodes, so rewinding is just a root
swap — same trick the reference uses).

Device engine seam: `attach_device_engine` routes batched gets, whole
pending-buffer flushes and multi-key proof generation through the
device MPT engine (state/device_state.py) — the same attach shape as
`CompactMerkleTree.attach_device_engine`: calls below the config batch
threshold keep the host trie path, every engine failure falls back to
the host path, and a persistently failing engine opens the circuit
breaker (cooldown + single recovery probe, utils/device_breaker.py) so
a sick device can never tax the serving path yet a healed one resumes.
"""
from __future__ import annotations

import logging
from abc import ABC, abstractmethod
from typing import List, Optional, Sequence

from plenum_tpu.common.serializers.base58 import b58encode
from plenum_tpu.state.device_state import CorruptStateError
from plenum_tpu.state.trie import BLANK_ROOT, Trie, verify_proof

logger = logging.getLogger(__name__)

try:
    from plenum_tpu.state.trie_native import NativeTrie as _TrieBackend
except Exception:                      # pragma: no cover - cc missing
    _TrieBackend = Trie


class State(ABC):
    @abstractmethod
    def set(self, key: bytes, value: bytes): ...

    @abstractmethod
    def get(self, key: bytes, isCommitted: bool = True) -> Optional[bytes]: ...

    @abstractmethod
    def remove(self, key: bytes): ...

    @property
    @abstractmethod
    def head(self): ...

    @property
    @abstractmethod
    def committedHead(self): ...

    @abstractmethod
    def commit(self, rootHash: Optional[bytes] = None): ...

    @abstractmethod
    def revertToHead(self, headHash: bytes): ...

    @property
    @abstractmethod
    def headHash(self) -> bytes: ...

    @property
    @abstractmethod
    def committedHeadHash(self) -> bytes: ...


from plenum_tpu.common.config import Config as _Config

# read-window miss marker: the window stores None for keys ABSENT at
# the pre-batch root (a hit that must not fall through to a trie walk)
_WINDOW_MISS = object()


class PruningState(State):
    # key under which the committed root hash survives restarts
    rootHashKey = b"\x88\x88committedRoot"

    # device MPT engine routing (state/device_state.py): batched calls
    # at/above this many keys go through the engine; below it the host
    # trie wins on latency. Single-sourced from Config like the
    # MERKLE_DEVICE_* knobs.
    _engine_batch_min = _Config.STATE_DEVICE_BATCH_MIN
    # consecutive engine failures before the breaker opens (every
    # failure already falls back to the host trie path)
    _ENGINE_MAX_FAILURES = 3

    def __init__(self, kv):
        """kv: KeyValueStorage for trie nodes (+ the committed-root key)."""
        self._kv = kv
        try:
            committed = bytes(kv.get(self.rootHashKey))
        except KeyError:
            committed = BLANK_ROOT
        self._trie = _TrieBackend(kv, committed)
        self._committed_root = committed
        # write buffer: set/remove land here; the trie absorbs the whole
        # batch in ONE deferred-hash pass when the head root is actually
        # needed (headHash / commit). Shared path nodes then hash once
        # per batch instead of once per request. Uncommitted gets read
        # through the buffer, so apply-loop read-your-writes holds.
        self._pending: dict = {}
        # bumps on every write; validation memos key on it (cheaper than
        # forcing a flush to compare head roots)
        self.mutation_count = 0
        # prefetched read window (conflict-lane executor): pre-batch
        # values for the batch's DECLARED read keys, served by
        # uncommitted get() after the pending-buffer check — a key
        # written this batch is in _pending (exact), an unwritten key's
        # pre-batch value is the window's (exact), so the window can
        # never serve a stale value. Any flush or rewind drops it.
        self._read_window: Optional[dict] = None
        self._engine = None
        self._engine_breaker = None

    # ----------------------------------------------------- device engine

    def attach_device_engine(self, engine=None, batch_min: int = None,
                             warm: bool = False):
        """Route batched gets / whole-batch flushes / multi-key proof
        generation through a device MPT engine
        (state/device_state.DeviceStateEngine). Calls below `batch_min`
        keys keep the host trie path — it wins below the routing
        threshold. warm=True compiles the SHA3 kernels now, keeping the
        one-time jit cost off the first serving call."""
        if engine is None:
            from plenum_tpu.state.device_state import DeviceStateEngine
            engine = DeviceStateEngine(self._kv)
        self._engine = engine
        from plenum_tpu.utils.device_breaker import DeviceCircuitBreaker
        # KeyError (genuinely missing node — the host path fails the
        # same way) and CorruptStateError (a node that does not hash
        # to its ref — an integrity failure the host path would
        # silently serve) are NOT device faults: they propagate
        self._engine_breaker = DeviceCircuitBreaker(
            "state device engine", "the host trie",
            max_failures=self._ENGINE_MAX_FAILURES,
            reraise=(KeyError, CorruptStateError))
        if batch_min is not None:
            self._engine_batch_min = batch_min
        if warm:
            try:
                engine.warm()
            except Exception:  # plenum-lint: disable=PT006 — warm-up is
                # best-effort: a broken backend must not fail bootstrap;
                # the first real batch retries and the breaker detaches
                logger.warning("state engine warm-up failed; it will "
                               "retry lazily", exc_info=True)
        return engine

    def _engine_call(self, fn, label: str):
        """Run one engine operation under the shared circuit breaker
        (utils/device_breaker.py): None on failure — the caller serves
        from the host trie. A persistently failing engine opens the
        breaker (cooldown with zero device I/O, then a single recovery
        probe); the engine stays attached so a healed device resumes
        serving without a re-attach."""
        if self._engine is None:
            return None
        engine = self._engine
        ok, out = self._engine_breaker.run(lambda: fn(engine), label)
        return out if ok else None

    # ------------------------------------------------------------ writes

    def set(self, key: bytes, value: bytes):
        self._pending[bytes(key)] = bytes(value)
        self.mutation_count += 1

    def remove(self, key: bytes):
        self._pending[bytes(key)] = b""  # empty == delete (trie semantics)
        self.mutation_count += 1

    def _flush_pending(self):
        if not self._pending:
            return
        # the window holds PRE-BATCH values; once the batch's writes
        # land in the trie the pending-first shield is gone, so the
        # window must go with it
        self._read_window = None
        pending, self._pending = self._pending, {}
        if self._engine is not None \
                and len(pending) >= self._engine_batch_min:
            # whole-batch device apply: every dirty node hashed
            # level-wise in one SHA3 dispatch per level; the root is
            # byte-equal to the host path's (content-canonical trie)
            root = self._engine_call(
                lambda eng: eng.apply_batch(self._trie.root_hash,
                                            list(pending.items())),
                "apply_batch")
            if root is not None:
                self._trie.root_hash = root
                return
        self._host_apply_pairs(pending)

    def begin_flush_deferred(self):
        """The structural half of a pending-buffer flush (conflict-lane
        executor): merge the whole buffer into the trie with hashing
        deferred and return a ``_DeferredApply`` handle for the shared
        :func:`flush_states_merged` resolve — so a batch that writes
        several ledgers' states hashes ALL their dirty nodes in one set
        of level-wise dispatches. Returns None when the host path
        already served the flush (no engine, open breaker, or a buffer
        below the batch threshold — identical routing to
        ``_flush_pending``)."""
        if not self._pending:
            return None
        if self._engine is None \
                or len(self._pending) < self._engine_batch_min:
            self._flush_pending()
            return None
        self._read_window = None
        pending, self._pending = self._pending, {}
        handle = self._engine_call(
            lambda eng: eng.begin_apply(self._trie.root_hash,
                                        list(pending.items())),
            "begin_apply")
        if handle is None:
            self._host_apply_pairs(pending)
            return None
        handle.state = self
        return handle

    def _host_apply_pairs(self, pending: dict) -> None:
        """Host-trie fallback for a popped pending buffer (engine
        failure mid-flush): same write set, same final root."""
        set_many = getattr(self._trie, "set_many", None)
        if set_many is not None:
            set_many(list(pending.items()))
            return
        for k, v in pending.items():
            if v:
                self._trie.set(k, v)
            else:
                self._trie.delete(k)

    def get(self, key: bytes, isCommitted: bool = True) -> Optional[bytes]:
        if isCommitted:
            return self._trie.get_at_root(self._committed_root, key)
        k = bytes(key)
        if k in self._pending:
            return self._pending[k] or None
        win = self._read_window
        if win is not None:
            hit = win.get(k, _WINDOW_MISS)
            if hit is not _WINDOW_MISS:
                return hit
        return self._trie.get(k)

    def get_for_root_hash(self, root_hash: bytes, key: bytes
                          ) -> Optional[bytes]:
        return self._trie.get_at_root(root_hash, key)

    # ------------------------------------------------------ batched reads

    def get_batch(self, keys: Sequence[bytes], isCommitted: bool = True
                  ) -> List[Optional[bytes]]:
        """Values for many keys in one call: the device engine walks
        every key level-lockstep with one hash-verify dispatch per
        level; uncommitted reads still see the pending write buffer."""
        if isCommitted:
            return self.get_batch_for_root_hash(self._committed_root,
                                                keys)
        out: List[Optional[bytes]] = [None] * len(keys)
        missing_idx, missing_keys = [], []
        for i, key in enumerate(keys):
            k = bytes(key)
            if k in self._pending:
                out[i] = self._pending[k] or None
            else:
                missing_idx.append(i)
                missing_keys.append(k)
        if missing_keys:
            vals = self.get_batch_for_root_hash(self._trie.root_hash,
                                                missing_keys)
            for i, v in zip(missing_idx, vals):
                out[i] = v
        return out

    def get_batch_for_root_hash(self, root_hash: bytes,
                                keys: Sequence[bytes]
                                ) -> List[Optional[bytes]]:
        if len(keys) >= self._engine_batch_min:
            vals = self._engine_call(
                lambda eng: eng.get_batch(root_hash, keys), "get_batch")
            if vals is not None:
                return vals
        return [self._trie.get_at_root(root_hash, k) for k in keys]

    # -------------------------------------------------------- read window

    def begin_read_window(self, keys: Sequence[bytes]) -> bool:
        """Prefetch pre-batch values for the batch's DECLARED read keys
        into one dict (conflict-lane executor, server/executor.py): the
        per-request validation/apply reads those keys as dict hits
        instead of one trie walk each. Exactness holds for ANY
        interleaving of reads and writes because uncommitted ``get``
        checks the pending write buffer first — the window only ever
        answers for keys untouched so far this batch, where the
        pre-batch value IS the serial value. → True if a window was
        installed."""
        if not keys:
            return False
        root = self._trie.root_hash
        win: dict = {}
        missing: List[bytes] = []
        for k in keys:
            kb = bytes(k)
            if kb not in self._pending:
                missing.append(kb)
        if missing:
            # host walks, one per key: the trie's decode cache holds the
            # hot spine, so this beats the engine's lockstep walk on
            # every measured shape (the walk is host work either way —
            # the device only ever hash-VERIFIES, which own-state apply
            # reads skip under the host trust-the-store contract)
            get_at_root = self._trie.get_at_root
            for k in missing:
                win[k] = get_at_root(root, k)
        self._read_window = win
        return True

    def end_read_window(self) -> None:
        self._read_window = None

    # ------------------------------------------------------- commit/revert

    def commit(self, rootHash: Optional[bytes] = None):
        """Advance the committed head (to `rootHash` if given — must be a
        root previously produced by apply — else to the current head).
        The working head is NOT moved: later uncommitted batches may
        already be staged on top of the committed prefix (3PC pipelines
        several batches in flight)."""
        self._flush_pending()
        root = rootHash if rootHash is not None else self._trie.root_hash
        self._committed_root = root
        self._kv.put(self.rootHashKey, root)

    def revertToHead(self, headHash: bytes):
        self._pending.clear()  # buffered writes belong to the abandoned head
        self._read_window = None
        self.mutation_count += 1
        self._trie.root_hash = headHash

    # ------------------------------------------------------------- heads

    @property
    def head(self):
        self._flush_pending()
        return self._trie

    @property
    def committedHead(self):
        return _TrieBackend(self._kv, self._committed_root)

    @property
    def headHash(self) -> bytes:
        self._flush_pending()
        return self._trie.root_hash

    @property
    def committedHeadHash(self) -> bytes:
        return self._committed_root

    @property
    def committedHeadHash_b58(self) -> str:
        return b58encode(self._committed_root)

    # ------------------------------------------------------------- proofs

    def generate_state_proof(self, key: bytes, root: Optional[bytes] = None,
                             serialize: bool = False):
        """Proof nodes for `key`; serialize=True wraps them in one
        base64-encoded RLP list (the wire form clients receive)."""
        nodes = self._trie.produce_spv_proof(
            key, root if root is not None else self.committedHeadHash)
        if serialize:
            return self.serialize_proof(nodes)
        return nodes

    def generate_state_proof_batch(self, keys: Sequence[bytes],
                                   root: Optional[bytes] = None,
                                   serialize: bool = False) -> List:
        """Proof nodes for MANY keys under one root in one engine call
        (shared spine nodes load and hash-verify once per level, not
        once per key); each entry is byte-identical to
        generate_state_proof for the same key."""
        root = root if root is not None else self.committedHeadHash
        proofs = None
        if len(keys) >= self._engine_batch_min:
            proofs = self._engine_call(
                lambda eng: eng.proof_batch(root, keys), "proof_batch")
        if proofs is None:
            proofs = [self._trie.produce_spv_proof(k, root) for k in keys]
        if serialize:
            return [self.serialize_proof(nodes) for nodes in proofs]
        return proofs

    def get_with_proofs_batch(self, keys: Sequence[bytes],
                              root: Optional[bytes] = None,
                              serialize: bool = False):
        """→ (values, proofs) for many keys under one root from ONE
        engine walk (the proof walk resolves values anyway) — the
        read-serving shape, where every reply carries both. Entries
        are byte-identical to get_for_root_hash + generate_state_proof
        per key."""
        root = root if root is not None else self.committedHeadHash
        out = None
        if len(keys) >= self._engine_batch_min:
            out = self._engine_call(
                lambda eng: eng.get_with_proof_batch(root, keys),
                "get_with_proof_batch")
        if out is None:
            out = ([self._trie.get_at_root(root, k) for k in keys],
                   [self._trie.produce_spv_proof(k, root) for k in keys])
        values, proofs = out
        if serialize:
            proofs = [self.serialize_proof(nodes) for nodes in proofs]
        return values, proofs

    @staticmethod
    def serialize_proof(nodes: Sequence[bytes]) -> str:
        """Wire form clients receive: one base64-encoded RLP list."""
        import base64
        from plenum_tpu.state import rlp as _rlp
        return base64.b64encode(_rlp.encode(list(nodes))).decode("ascii")

    @staticmethod
    def deserialize_proof(proof: str) -> List[bytes]:
        import base64
        from plenum_tpu.state import rlp as _rlp
        return [bytes(n) for n in _rlp.decode(base64.b64decode(proof))]

    @staticmethod
    def verify_state_proof(root_hash: bytes, key: bytes,
                           value: Optional[bytes],
                           proof_nodes: List[bytes]) -> bool:
        return verify_proof(root_hash, key, value, proof_nodes)

    def close(self):
        self._kv.close()


def flush_states_merged(states, use_device=None, exec_map=None) -> None:
    """Flush MANY states' pending buffers through ONE merged hash
    resolution (conflict-lane executor, server/executor.py): each
    state's structural update runs with hashing deferred
    (``begin_flush_deferred``), then every participating trie's dirty
    nodes resolve together in shared level-wise SHA3 dispatches
    (state/device_state.resolve_applies). States the engine cannot
    serve (no engine, open breaker, sub-threshold buffers) flush
    through their host path inside ``begin_flush_deferred``; a failed
    merged resolve falls back to the host trie per state with the
    identical write set — roots are byte-equal on every path.

    ``exec_map``: optional order-preserving parallel map (the node
    pipeline's execution pool). Host-path states fan across it — each
    owns its trie, pending buffer and kv store, so their structural
    merges are independent — while engine-routed states stay on the
    calling thread (the shared device engine serializes launches
    anyway). Roots are a pure function of each state's write set, so
    fan-out cannot change them."""
    states = [st for st in states if st is not None]
    fanned = []
    if exec_map is not None and len(states) > 1:
        # the same routing predicate begin_flush_deferred applies; a
        # state it still routes to the engine just returns its handle
        # from the pool thread and joins the merged resolve below
        host = [st for st in states
                if st._pending and (
                    st._engine is None
                    or len(st._pending) < st._engine_batch_min)]
        if len(host) > 1:
            host_ids = set(map(id, host))
            states = [st for st in states if id(st) not in host_ids]
            fanned = [h for h in exec_map(
                lambda st: st.begin_flush_deferred(), host)
                if h is not None]
    handles = fanned + [
        h for h in (st.begin_flush_deferred() for st in states)
        if h is not None]
    if not handles:
        return
    from plenum_tpu.state.device_state import resolve_applies
    first = handles[0].state
    ok, roots = first._engine_breaker.run(
        lambda: resolve_applies(handles, use_device=use_device),
        "resolve_merged")
    if ok:
        for h, root in zip(handles, roots):
            h.state._trie.root_hash = root
        return
    for h in handles:
        h.state._host_apply_pairs(dict(h.pairs))
