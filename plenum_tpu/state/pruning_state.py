"""State: committed vs uncommitted heads over the trie.

Reference: state/state.py:5 (State ABC), state/pruning_state.py:14
(PruningState). `headHash` moves with every applied-but-uncommitted batch;
`committedHeadHash` moves only on 3PC commit; revert rewinds head to the
committed root (the trie keeps all nodes, so rewinding is just a root
swap — same trick the reference uses).
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import List, Optional

from plenum_tpu.common.serializers.base58 import b58encode
from plenum_tpu.state.trie import BLANK_ROOT, Trie, verify_proof

try:
    from plenum_tpu.state.trie_native import NativeTrie as _TrieBackend
except Exception:                      # pragma: no cover - cc missing
    _TrieBackend = Trie


class State(ABC):
    @abstractmethod
    def set(self, key: bytes, value: bytes): ...

    @abstractmethod
    def get(self, key: bytes, isCommitted: bool = True) -> Optional[bytes]: ...

    @abstractmethod
    def remove(self, key: bytes): ...

    @property
    @abstractmethod
    def head(self): ...

    @property
    @abstractmethod
    def committedHead(self): ...

    @abstractmethod
    def commit(self, rootHash: Optional[bytes] = None): ...

    @abstractmethod
    def revertToHead(self, headHash: bytes): ...

    @property
    @abstractmethod
    def headHash(self) -> bytes: ...

    @property
    @abstractmethod
    def committedHeadHash(self) -> bytes: ...


class PruningState(State):
    # key under which the committed root hash survives restarts
    rootHashKey = b"\x88\x88committedRoot"

    def __init__(self, kv):
        """kv: KeyValueStorage for trie nodes (+ the committed-root key)."""
        self._kv = kv
        try:
            committed = bytes(kv.get(self.rootHashKey))
        except KeyError:
            committed = BLANK_ROOT
        self._trie = _TrieBackend(kv, committed)
        self._committed_root = committed
        # write buffer: set/remove land here; the trie absorbs the whole
        # batch in ONE deferred-hash pass when the head root is actually
        # needed (headHash / commit). Shared path nodes then hash once
        # per batch instead of once per request. Uncommitted gets read
        # through the buffer, so apply-loop read-your-writes holds.
        self._pending: dict = {}
        # bumps on every write; validation memos key on it (cheaper than
        # forcing a flush to compare head roots)
        self.mutation_count = 0

    # ------------------------------------------------------------ writes

    def set(self, key: bytes, value: bytes):
        self._pending[bytes(key)] = bytes(value)
        self.mutation_count += 1

    def remove(self, key: bytes):
        self._pending[bytes(key)] = b""  # empty == delete (trie semantics)
        self.mutation_count += 1

    def _flush_pending(self):
        if not self._pending:
            return
        pending, self._pending = self._pending, {}
        set_many = getattr(self._trie, "set_many", None)
        if set_many is not None:
            set_many(list(pending.items()))
        else:
            for k, v in pending.items():
                if v:
                    self._trie.set(k, v)
                else:
                    self._trie.delete(k)

    def get(self, key: bytes, isCommitted: bool = True) -> Optional[bytes]:
        if isCommitted:
            return self._trie.get_at_root(self._committed_root, key)
        k = bytes(key)
        if k in self._pending:
            return self._pending[k] or None
        return self._trie.get(k)

    def get_for_root_hash(self, root_hash: bytes, key: bytes
                          ) -> Optional[bytes]:
        return self._trie.get_at_root(root_hash, key)

    # ------------------------------------------------------- commit/revert

    def commit(self, rootHash: Optional[bytes] = None):
        """Advance the committed head (to `rootHash` if given — must be a
        root previously produced by apply — else to the current head).
        The working head is NOT moved: later uncommitted batches may
        already be staged on top of the committed prefix (3PC pipelines
        several batches in flight)."""
        self._flush_pending()
        root = rootHash if rootHash is not None else self._trie.root_hash
        self._committed_root = root
        self._kv.put(self.rootHashKey, root)

    def revertToHead(self, headHash: bytes):
        self._pending.clear()  # buffered writes belong to the abandoned head
        self.mutation_count += 1
        self._trie.root_hash = headHash

    # ------------------------------------------------------------- heads

    @property
    def head(self):
        self._flush_pending()
        return self._trie

    @property
    def committedHead(self):
        return _TrieBackend(self._kv, self._committed_root)

    @property
    def headHash(self) -> bytes:
        self._flush_pending()
        return self._trie.root_hash

    @property
    def committedHeadHash(self) -> bytes:
        return self._committed_root

    @property
    def committedHeadHash_b58(self) -> str:
        return b58encode(self._committed_root)

    # ------------------------------------------------------------- proofs

    def generate_state_proof(self, key: bytes, root: Optional[bytes] = None,
                             serialize: bool = False):
        """Proof nodes for `key`; serialize=True wraps them in one
        base64-encoded RLP list (the wire form clients receive)."""
        nodes = self._trie.produce_spv_proof(
            key, root if root is not None else self.committedHeadHash)
        if serialize:
            import base64
            from plenum_tpu.state import rlp as _rlp
            return base64.b64encode(_rlp.encode(list(nodes))).decode("ascii")
        return nodes

    @staticmethod
    def deserialize_proof(proof: str) -> List[bytes]:
        import base64
        from plenum_tpu.state import rlp as _rlp
        return [bytes(n) for n in _rlp.decode(base64.b64decode(proof))]

    @staticmethod
    def verify_state_proof(root_hash: bytes, key: bytes,
                           value: Optional[bytes],
                           proof_nodes: List[bytes]) -> bool:
        return verify_proof(root_hash, key, value, proof_nodes)

    def close(self):
        self._kv.close()
