"""State layer: Merkle Patricia Trie with committed/uncommitted heads and
SPV proofs (reference: state/ — State ABC state/state.py:5, PruningState
state/pruning_state.py:14, Trie state/trie/pruning_trie.py:215).
"""
from plenum_tpu.state.trie import Trie, verify_proof
from plenum_tpu.state.pruning_state import PruningState, State
from plenum_tpu.state.device_state import (
    CorruptStateError, DeviceStateEngine)

__all__ = ["Trie", "verify_proof", "PruningState", "State",
           "DeviceStateEngine", "CorruptStateError"]
