from plenum_tpu.ledger.tree_hasher import TreeHasher  # noqa: F401
from plenum_tpu.ledger.compact_merkle_tree import CompactMerkleTree  # noqa: F401
from plenum_tpu.ledger.merkle_verifier import MerkleVerifier  # noqa: F401
from plenum_tpu.ledger.ledger import Ledger  # noqa: F401
