"""Append-only Certificate-Transparency-style merkle tree with O(log n)
frontier state, inclusion & consistency proofs.

Reference: ledger/compact_merkle_tree.py:13 — same capabilities, new design:
full aligned subtrees are persisted by (start, height) in the HashStore, so
`merkle_tree_hash(start, end)` resolves any range in O(log² n) lookups and
the RFC 6962 proof algorithms (§2.1.1/§2.1.2) read straight from storage.
Batched audit-path generation for catchup rides the TreeHasher TPU seam.
"""
import logging
from typing import List, Optional, Sequence, Tuple

import numpy as np

from plenum_tpu.ledger.hash_store import HashStore, MemoryHashStore, NullHashStore
from plenum_tpu.ledger.tree_hasher import TreeHasher, _largest_pow2_lt

logger = logging.getLogger(__name__)


def _array_to_digest_list(arr: 'np.ndarray') -> List[bytes]:
    """[B, 32] u8 → 32-byte bytes objects via ONE flat copy (hash-store
    writes are the only consumer that still needs bytes)."""
    flat = np.ascontiguousarray(arr, dtype=np.uint8).tobytes()
    return [flat[i:i + 32] for i in range(0, len(flat), 32)]


from plenum_tpu.common.config import Config as _Config


class CompactMerkleTree:
    # batches at/above this go level-wise instead of scalar frontier
    # merges (extend), and are eligible for the device engine
    BULK_MIN = 1024
    # proof batches below this stay on the host memo path — it WINS for
    # small batches (BENCH_r05: per-batch device latency is the floor).
    # Defaults come from Config so there is ONE place to tune them.
    _device_proof_min = _Config.MERKLE_DEVICE_PROOF_MIN
    _device_proof_chunk = _Config.MERKLE_DEVICE_PROOF_CHUNK
    _device_pipeline_depth = _Config.MERKLE_DEVICE_PIPELINE_DEPTH
    _device_engine = None
    # consecutive device failures before the breaker opens (every
    # failure already falls back to the host memo path; policy lives in
    # utils/device_breaker.py, shared with the state engine seam)
    _DEVICE_MAX_FAILURES = 3
    _device_breaker = None

    def __init__(self, hasher: TreeHasher = None,
                 hash_store: HashStore = None):
        self.hasher = hasher or TreeHasher()
        self.hash_store = hash_store if hash_store is not None \
            else MemoryHashStore()
        self._size = 0
        # frontier: maximal full subtrees, descending height,
        # entries (start, height, hash)
        self._frontier: List[Tuple[int, int, bytes]] = []
        # (size, root) — valid while _size matches (appends change _size;
        # reset/load/copy set _size too, so size is the full invalidator)
        self._root_cache: Optional[Tuple[int, bytes]] = None

    # ------------------------------------------------------------ state

    @property
    def tree_size(self) -> int:
        return self._size

    def __len__(self):
        return self._size

    @property
    def hashes(self) -> Tuple[bytes, ...]:
        return tuple(h for _, _, h in self._frontier)

    @property
    def root_hash(self) -> bytes:
        # cached by size: callers re-read the root several times per
        # batch (executor roots, audit txns, state checks) and each
        # recompute is O(log n) hashes
        cached = self._root_cache
        if cached is not None and cached[0] == self._size:
            return cached[1]
        if not self._frontier:
            root = self.hasher.hash_empty()
        else:
            root = self._frontier[-1][2]
            for _, _, h in reversed(self._frontier[:-1]):
                root = self.hasher.hash_children(h, root)
        self._root_cache = (self._size, root)
        return root

    @property
    def root_hash_hex(self) -> str:
        return self.root_hash.hex()

    # ---------------------------------------------------------- appends

    def append(self, new_leaf: bytes) -> List[bytes]:
        """Append a raw leaf entry; returns the audit path of the appended
        leaf in the resulting tree (the pre-merge frontier, smallest subtree
        first) — same contract as the reference's append."""
        return self._append_hash(self.hasher.hash_leaf(new_leaf))

    def _append_hash(self, leaf_hash: bytes,
                     want_path: bool = True) -> List[bytes]:
        # the audit-path copy is skipped on the commit hot path
        # (want_path=False): building a frontier snapshot per txn cost
        # ~12 us x every committed txn and nearly every caller drops it
        audit_path = [h for _, _, h in reversed(self._frontier)] \
            if want_path else []
        index = self._size
        self.hash_store.write_leaf(index, leaf_hash)
        entry = (index, 0, leaf_hash)
        frontier = self._frontier
        hash_children = self.hasher.hash_children
        write_subtree = self.hash_store.write_subtree
        while frontier and frontier[-1][1] == entry[1]:
            s, h, left = frontier.pop()
            merged = hash_children(left, entry[2])
            entry = (s, h + 1, merged)
            write_subtree(s, h + 1, merged)
        frontier.append(entry)
        self._size += 1
        return audit_path

    def extend(self, new_leaves: Sequence[bytes]):
        """Batched append: leaf hashing goes through the TPU seam;
        large batches additionally hash interior nodes level-by-level in
        batches — from empty (_bulk_build) OR onto an existing tree
        (_bulk_extend): ~2n hashes in ~log n seam dispatches instead of
        n scalar frontier merges."""
        self.extend_hashes(self.hasher.hash_leaves(list(new_leaves)))

    def extend_hashes(self, leaf_hashes: List[bytes]):
        """Append precomputed RFC 6962 leaf digests (same routing as
        extend, for callers that already hold the hashes)."""
        if len(leaf_hashes) >= self.BULK_MIN:
            if self._size == 0:
                self._bulk_build(leaf_hashes)
                if self._device_engine is not None \
                        and not self._device_breaker.open \
                        and self._device_engine.tree_size == 0:
                    # keep the engine warm through the big growth event
                    # (recovery/catchup) — one fused dispatch now, so a
                    # later proof batch only syncs the scalar delta.
                    # An open breaker skips this: no device I/O while
                    # cooling down.
                    try:
                        self._device_engine.build_from_leaf_hashes(
                            leaf_hashes)
                    except Exception:
                        logger.warning("device engine bulk warm-up "
                                       "failed; it will retry lazily",
                                       exc_info=True)
            else:
                self._bulk_extend(leaf_hashes)
            return
        for leaf_hash in leaf_hashes:
            self._append_hash(leaf_hash, want_path=False)

    def _bulk_build(self, leaf_hashes: List[bytes]):
        """Construct the whole tree from scratch with level-wise batched
        node hashing, persisting every full aligned subtree exactly as
        the incremental path would (same hash store contents, same
        frontier)."""
        assert self._size == 0
        for i, h in enumerate(leaf_hashes):
            self.hash_store.write_leaf(i, h)
        frontier_rev: List[Tuple[int, int, bytes]] = []
        level = leaf_hashes
        height = 0
        while level:
            if len(level) == 1:
                # left-aligned level ⇒ a lone element is index 0,
                # covering leaves [0, 2^height)
                frontier_rev.append((0, height, level[0]))
                break
            if len(level) % 2 == 1:
                start = (len(level) - 1) << height
                frontier_rev.append((start, height, level[-1]))
                level = level[:-1]
            level = self._hash_level_pairs(level)
            height += 1
            for i, h in enumerate(level):
                self.hash_store.write_subtree(i << height, height, h)
        self._frontier = [entry for entry in reversed(frontier_rev)]
        self._size = len(leaf_hashes)

    def _hash_level_pairs(self, children: List[bytes]) -> List[bytes]:
        """Pair-hash one level: children[2i], children[2i+1] → parent i.
        Large levels go through the ARRAY seam — one flat join + one
        dispatch, skipping the ~n per-pair tuple/message objects the
        list seam would build (the digests here are immediately
        re-consumed by the next level and the hash store)."""
        m = len(children) // 2
        hasher = self.hasher
        if m >= getattr(hasher, "_batch_threshold", 1 << 62) \
                and hasattr(hasher, "hash_node_pairs_array"):
            arr = np.frombuffer(b"".join(children[:2 * m]),
                                dtype=np.uint8).reshape(m, 64)
            return _array_to_digest_list(hasher.hash_node_pairs_array(arr))
        return hasher.hash_node_pairs(
            [(children[i], children[i + 1]) for i in range(0, 2 * m, 2)])

    def _bulk_extend(self, leaf_hashes: List[bytes]):
        """Level-wise batched append onto a NON-empty tree: the same
        ~2n node hashes the scalar frontier merges would compute, one
        seam dispatch per level (or the attached device engine's
        incremental append), with identical hash-store contents and
        frontier. At height h the only pre-existing child a new parent
        can need is the old frontier entry at h (the odd tail node)."""
        old_n = self._size
        new_n = old_n + len(leaf_hashes)
        write_leaf = self.hash_store.write_leaf
        for i, h in enumerate(leaf_hashes):
            write_leaf(old_n + i, h)
        write_subtree = self.hash_store.write_subtree
        fr = {height: value for _, height, value in self._frontier}
        new_levels = {0: leaf_hashes}
        eng = self._device_engine
        nodes = None
        if eng is not None and eng.tree_size == old_n:
            # device-resident incremental append: ~2b device hashes,
            # one small dispatch per level; new complete nodes come
            # back as arrays and are persisted at the identical
            # (start, height) keys. Breaker-guarded: a failure serves
            # this extend from the host level-wise path, and the engine
            # is reset so a half-applied append can never survive into
            # a later proof sync.
            def _attempt():
                return eng.append_leaf_hashes(
                    np.frombuffer(b"".join(leaf_hashes), dtype=np.uint8)
                    .reshape(-1, 32), return_nodes=True)

            ok, nodes = self._device_breaker.run(_attempt, "bulk extend")
            if not ok:
                nodes = None
                try:
                    if eng.tree_size != old_n:  # half-applied append
                        eng.reset()
                except Exception:
                    logger.debug("device engine reset after failed bulk "
                                 "extend also failed", exc_info=True)
        if nodes is not None:
            for height, pos, rows in nodes:
                if height == 0:
                    continue  # leaves were written above
                vals = _array_to_digest_list(rows)
                for i, v in enumerate(vals):
                    write_subtree((pos + i) << height, height, v)
                new_levels[height] = vals
        else:
            level_vals = leaf_hashes
            h = 0
            while True:
                o1, c1 = old_n >> (h + 1), new_n >> (h + 1)
                if c1 == o1:
                    break
                children = ([fr[h]] if (old_n >> h) & 1 else []) \
                    + level_vals
                parents = self._hash_level_pairs(children[:2 * (c1 - o1)])
                for i, ph in enumerate(parents):
                    write_subtree((o1 + i) << (h + 1), h + 1, ph)
                new_levels[h + 1] = parents
                level_vals = parents
                h += 1
        frontier = []
        for height in range(new_n.bit_length() - 1, -1, -1):
            if not (new_n >> height) & 1:
                continue
            idx = (new_n >> height) - 1
            if idx < (old_n >> height):
                value = fr[height]
            else:
                value = new_levels[height][idx - (old_n >> height)]
            frontier.append((idx << height, height, value))
        self._frontier = frontier
        self._size = new_n

    # ------------------------------------------- device proof engine

    def attach_device_engine(self, engine=None, proof_min: int = None,
                             chunk: int = None, pipeline_depth: int = None,
                             warm: bool = False):
        """Route large inclusion-proof batches and large extends
        through a device-resident tree (ops/merkle.DeviceMerkleTree).
        Batches below `proof_min` keep the host memo path — it wins
        below the routing threshold (BENCH_r05); the engine lazily
        catches up from the hash store, so scalar appends stay O(1).
        warm=True syncs a non-empty tree now, keeping the one-time
        build (+ jit compile) off the first serving call."""
        if engine is None:
            from plenum_tpu.ops.merkle import DeviceMerkleTree
            engine = DeviceMerkleTree(self.hasher)
        self._device_engine = engine
        from plenum_tpu.utils.device_breaker import DeviceCircuitBreaker
        self._device_breaker = DeviceCircuitBreaker(
            "device proof engine", "the host memo path",
            max_failures=self._DEVICE_MAX_FAILURES)
        if proof_min is not None:
            self._device_proof_min = proof_min
        if chunk is not None:
            self._device_proof_chunk = chunk
        if pipeline_depth is not None:
            self._device_pipeline_depth = pipeline_depth
        if warm and self._size and not isinstance(self.hash_store,
                                                  NullHashStore):
            try:
                self._device_sync()
            except Exception:
                logger.warning("device engine warm-up failed; it will "
                               "retry lazily", exc_info=True)
        return engine

    def _device_sync(self) -> bool:
        """Catch the attached engine up to the committed tree by
        incrementally appending the missing leaf digests from the hash
        store — complete RFC 6962 nodes are immutable, so catch-up
        after b scalar appends costs ~2b device hashes, never a
        rebuild. Bulk builds/extends advance the engine inline, so the
        delta here is normally just the last few scalar appends."""
        eng = self._device_engine
        if eng.tree_size > self._size:
            eng.reset()  # the host tree was reset/reloaded under us
        if eng.tree_size < self._size:
            missing = self.hash_store.read_leaves(eng.tree_size,
                                                  self._size)
            if eng.tree_size == 0:
                eng.build_from_leaf_hashes(missing)
            else:
                eng.append_leaf_hashes(missing)
        return eng.tree_size == self._size

    def _device_proofs_batch(self, ms, n: int) -> Optional[List[List[bytes]]]:
        """Serve a large proof batch from the device engine, or None to
        fall back to the host memo path."""
        if (self._device_engine is None
                or len(ms) < self._device_proof_min
                or isinstance(self.hash_store, NullHashStore)
                or self.hash_store.leaf_count < self._size):
            return None

        def attempt():
            if not self._device_sync():
                return None
            from plenum_tpu.ops.merkle import ProofPipeline
            pipe = ProofPipeline(self._device_engine,
                                 depth=self._device_pipeline_depth)
            return pipe.run(ms, n=n, chunk=self._device_proof_chunk)

        # shared circuit breaker (utils/device_breaker.py): every
        # failure serves this batch from the host memo path; a
        # persistently sick device opens the breaker (cooldown, then a
        # single recovery probe) — the engine stays attached so a
        # healed device resumes serving without a re-attach
        ok, out = self._device_breaker.run(attempt, "proof batch")
        return out if ok else None

    def __copy__(self):
        other = CompactMerkleTree(self.hasher, NullHashStore())
        other._size = self._size
        other._frontier = list(self._frontier)
        return other

    def copy_shadow(self) -> 'CompactMerkleTree':
        """A root-only copy for uncommitted staging (no proof support)."""
        return self.__copy__()

    # ------------------------------------------------------ range hashes

    def merkle_tree_hash(self, start: int, end: int) -> bytes:
        """MTH over leaves [start, end) (0-based, end exclusive)."""
        if not 0 <= start <= end <= self._size:
            raise IndexError("{}..{} outside tree of size {}"
                             .format(start, end, self._size))
        return self._mth(start, end)

    def _mth(self, start: int, end: int) -> bytes:
        width = end - start
        if width == 0:
            return self.hasher.hash_empty()
        if width == 1:
            return self.hash_store.read_leaf(start)
        # full aligned subtree? look it up
        if width & (width - 1) == 0 and start % width == 0:
            h = width.bit_length() - 1
            stored = self.hash_store.read_subtree(start, h)
            if stored is not None:
                return stored
        k = _largest_pow2_lt(width)
        return self.hasher.hash_children(self._mth(start, start + k),
                                         self._mth(start + k, end))

    # ----------------------------------------------------------- proofs

    def inclusion_proof(self, m: int, n: int) -> List[bytes]:
        """Audit path for leaf index m in the size-n prefix tree
        (RFC 6962 §2.1.1 PATH(m, D[0:n]))."""
        if not 0 <= m < n <= self._size:
            raise IndexError("invalid inclusion proof request ({}, {}) "
                             "for size {}".format(m, n, self._size))
        return self._path(m, 0, n)

    def _path(self, m: int, start: int, end: int) -> List[bytes]:
        n = end - start
        if n <= 1:
            return []
        k = _largest_pow2_lt(n)
        if m - start < k:
            return self._path(m, start, start + k) + [self._mth(start + k, end)]
        return self._path(m, start + k, end) + [self._mth(start, start + k)]

    def inclusion_proofs_batch(self, ms, n: int) -> List[List[bytes]]:
        """Audit paths for MANY leaves of the same size-n prefix with a
        shared subtree-hash memo. A committed batch's replies all prove
        against the same tree, and contiguous leaves share nearly every
        upper node — the memo collapses per-proof cost to the few
        bottom siblings unique to each leaf (the per-reply
        inclusion_proof was a top-3 cost on the ordering money path)."""
        if not ms:
            return []
        if not (0 <= min(ms) and max(ms) < n <= self._size):
            raise IndexError("invalid inclusion proof batch ({}, {}) "
                             "for size {}".format(min(ms), n, self._size))
        device = self._device_proofs_batch(ms, n)
        if device is not None:
            return device
        memo = {}
        hash_children = self.hasher.hash_children
        read_leaf = self.hash_store.read_leaf
        read_subtree = self.hash_store.read_subtree

        def mth(start, end):
            key = (start, end)
            h = memo.get(key)
            if h is not None:
                return h
            width = end - start
            if width == 1:
                h = read_leaf(start)
            else:
                h = None
                if width & (width - 1) == 0 and start % width == 0:
                    h = read_subtree(start, width.bit_length() - 1)
                if h is None:
                    k = _largest_pow2_lt(width)
                    h = hash_children(mth(start, start + k),
                                      mth(start + k, end))
            memo[key] = h
            return h

        out = []
        for m in ms:
            path = []
            start, end = 0, n
            while end - start > 1:
                k = _largest_pow2_lt(end - start)
                if m - start < k:
                    path.append(mth(start + k, end))
                    end = start + k
                else:
                    path.append(mth(start, start + k))
                    start = start + k
            path.reverse()
            out.append(path)
        return out

    def consistency_proof(self, first: int, second: int) -> List[bytes]:
        """PROOF(m, D[0:n]) (RFC 6962 §2.1.2) that size-`first` tree is a
        prefix of the size-`second` tree."""
        if not 0 < first <= second <= self._size:
            raise IndexError("invalid consistency proof request ({}, {}) "
                             "for size {}".format(first, second, self._size))
        return self._subproof(first, 0, second, True)

    def _subproof(self, m: int, start: int, end: int, complete: bool) -> List[bytes]:
        n = end - start
        if m == n:
            return [] if complete else [self._mth(start, end)]
        k = _largest_pow2_lt(n)
        if m <= k:
            return self._subproof(m, start, start + k, complete) + \
                [self._mth(start + k, end)]
        return self._subproof(m - k, start + k, end, False) + \
            [self._mth(start, start + k)]

    # --------------------------------------------------------- recovery

    def load_from_hash_store(self, tree_size: int):
        """Rebuild the frontier for `tree_size` from persisted subtree
        hashes (reference recoverTreeFromHashStore)."""
        self._frontier = []
        self._size = tree_size
        self._root_cache = None  # content replaced wholesale
        start = 0
        remaining = tree_size
        while remaining > 0:
            h = remaining.bit_length() - 1
            width = 1 << h
            if h == 0:
                node = self.hash_store.read_leaf(start)
            else:
                node = self.hash_store.read_subtree(start, h)
                if node is None:
                    raise ValueError("hash store missing subtree ({}, {})"
                                     .format(start, h))
            self._frontier.append((start, h, node))
            start += width
            remaining -= width

    def verify_consistency(self, expected_leaf_count: int) -> bool:
        return self.hash_store.leaf_count >= expected_leaf_count

    def reset(self):
        self._size = 0
        self._frontier = []
        self._root_cache = None  # size alone can't invalidate a shrink
        if self._device_engine is not None:
            try:
                self._device_engine.reset()
            except Exception:  # plenum-lint: disable=PT006 — a sick
                # device must not block a host-tree reset; the breaker
                # path resyncs (or keeps falling back) on next use
                logger.debug("device engine reset failed", exc_info=True)
        self.hash_store.reset()

    def __repr__(self):
        return "CompactMerkleTree(size={}, root={})".format(
            self._size, self.root_hash.hex()[:16])
