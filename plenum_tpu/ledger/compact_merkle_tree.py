"""Append-only Certificate-Transparency-style merkle tree with O(log n)
frontier state, inclusion & consistency proofs.

Reference: ledger/compact_merkle_tree.py:13 — same capabilities, new design:
full aligned subtrees are persisted by (start, height) in the HashStore, so
`merkle_tree_hash(start, end)` resolves any range in O(log² n) lookups and
the RFC 6962 proof algorithms (§2.1.1/§2.1.2) read straight from storage.
Batched audit-path generation for catchup rides the TreeHasher TPU seam.
"""
from typing import List, Optional, Sequence, Tuple

from plenum_tpu.ledger.hash_store import HashStore, MemoryHashStore, NullHashStore
from plenum_tpu.ledger.tree_hasher import TreeHasher, _largest_pow2_lt


class CompactMerkleTree:
    def __init__(self, hasher: TreeHasher = None,
                 hash_store: HashStore = None):
        self.hasher = hasher or TreeHasher()
        self.hash_store = hash_store if hash_store is not None \
            else MemoryHashStore()
        self._size = 0
        # frontier: maximal full subtrees, descending height,
        # entries (start, height, hash)
        self._frontier: List[Tuple[int, int, bytes]] = []
        # (size, root) — valid while _size matches (appends change _size;
        # reset/load/copy set _size too, so size is the full invalidator)
        self._root_cache: Optional[Tuple[int, bytes]] = None

    # ------------------------------------------------------------ state

    @property
    def tree_size(self) -> int:
        return self._size

    def __len__(self):
        return self._size

    @property
    def hashes(self) -> Tuple[bytes, ...]:
        return tuple(h for _, _, h in self._frontier)

    @property
    def root_hash(self) -> bytes:
        # cached by size: callers re-read the root several times per
        # batch (executor roots, audit txns, state checks) and each
        # recompute is O(log n) hashes
        cached = self._root_cache
        if cached is not None and cached[0] == self._size:
            return cached[1]
        if not self._frontier:
            root = self.hasher.hash_empty()
        else:
            root = self._frontier[-1][2]
            for _, _, h in reversed(self._frontier[:-1]):
                root = self.hasher.hash_children(h, root)
        self._root_cache = (self._size, root)
        return root

    @property
    def root_hash_hex(self) -> str:
        return self.root_hash.hex()

    # ---------------------------------------------------------- appends

    def append(self, new_leaf: bytes) -> List[bytes]:
        """Append a raw leaf entry; returns the audit path of the appended
        leaf in the resulting tree (the pre-merge frontier, smallest subtree
        first) — same contract as the reference's append."""
        return self._append_hash(self.hasher.hash_leaf(new_leaf))

    def _append_hash(self, leaf_hash: bytes,
                     want_path: bool = True) -> List[bytes]:
        # the audit-path copy is skipped on the commit hot path
        # (want_path=False): building a frontier snapshot per txn cost
        # ~12 us x every committed txn and nearly every caller drops it
        audit_path = [h for _, _, h in reversed(self._frontier)] \
            if want_path else []
        index = self._size
        self.hash_store.write_leaf(index, leaf_hash)
        entry = (index, 0, leaf_hash)
        frontier = self._frontier
        hash_children = self.hasher.hash_children
        write_subtree = self.hash_store.write_subtree
        while frontier and frontier[-1][1] == entry[1]:
            s, h, left = frontier.pop()
            merged = hash_children(left, entry[2])
            entry = (s, h + 1, merged)
            write_subtree(s, h + 1, merged)
        frontier.append(entry)
        self._size += 1
        return audit_path

    def extend(self, new_leaves: Sequence[bytes]):
        """Batched append: leaf hashing goes through the TPU seam; a bulk
        rebuild from empty additionally hashes interior nodes level-by-
        level in batches (the 1M-leaf path: ~2n hashes in ~log n device
        dispatches instead of n scalar frontier merges)."""
        leaf_hashes = self.hasher.hash_leaves(list(new_leaves))
        if self._size == 0 and len(leaf_hashes) >= 1024:
            self._bulk_build(leaf_hashes)
            return
        for leaf_hash in leaf_hashes:
            self._append_hash(leaf_hash)

    def _bulk_build(self, leaf_hashes: List[bytes]):
        """Construct the whole tree from scratch with level-wise batched
        node hashing, persisting every full aligned subtree exactly as
        the incremental path would (same hash store contents, same
        frontier)."""
        assert self._size == 0
        for i, h in enumerate(leaf_hashes):
            self.hash_store.write_leaf(i, h)
        frontier_rev: List[Tuple[int, int, bytes]] = []
        level = leaf_hashes
        height = 0
        while level:
            if len(level) == 1:
                # left-aligned level ⇒ a lone element is index 0,
                # covering leaves [0, 2^height)
                frontier_rev.append((0, height, level[0]))
                break
            if len(level) % 2 == 1:
                start = (len(level) - 1) << height
                frontier_rev.append((start, height, level[-1]))
                level = level[:-1]
            pairs = [(level[i], level[i + 1])
                     for i in range(0, len(level), 2)]
            level = self.hasher.hash_node_pairs(pairs)
            height += 1
            for i, h in enumerate(level):
                self.hash_store.write_subtree(i << height, height, h)
        self._frontier = [entry for entry in reversed(frontier_rev)]
        self._size = len(leaf_hashes)

    def __copy__(self):
        other = CompactMerkleTree(self.hasher, NullHashStore())
        other._size = self._size
        other._frontier = list(self._frontier)
        return other

    def copy_shadow(self) -> 'CompactMerkleTree':
        """A root-only copy for uncommitted staging (no proof support)."""
        return self.__copy__()

    # ------------------------------------------------------ range hashes

    def merkle_tree_hash(self, start: int, end: int) -> bytes:
        """MTH over leaves [start, end) (0-based, end exclusive)."""
        if not 0 <= start <= end <= self._size:
            raise IndexError("{}..{} outside tree of size {}"
                             .format(start, end, self._size))
        return self._mth(start, end)

    def _mth(self, start: int, end: int) -> bytes:
        width = end - start
        if width == 0:
            return self.hasher.hash_empty()
        if width == 1:
            return self.hash_store.read_leaf(start)
        # full aligned subtree? look it up
        if width & (width - 1) == 0 and start % width == 0:
            h = width.bit_length() - 1
            stored = self.hash_store.read_subtree(start, h)
            if stored is not None:
                return stored
        k = _largest_pow2_lt(width)
        return self.hasher.hash_children(self._mth(start, start + k),
                                         self._mth(start + k, end))

    # ----------------------------------------------------------- proofs

    def inclusion_proof(self, m: int, n: int) -> List[bytes]:
        """Audit path for leaf index m in the size-n prefix tree
        (RFC 6962 §2.1.1 PATH(m, D[0:n]))."""
        if not 0 <= m < n <= self._size:
            raise IndexError("invalid inclusion proof request ({}, {}) "
                             "for size {}".format(m, n, self._size))
        return self._path(m, 0, n)

    def _path(self, m: int, start: int, end: int) -> List[bytes]:
        n = end - start
        if n <= 1:
            return []
        k = _largest_pow2_lt(n)
        if m - start < k:
            return self._path(m, start, start + k) + [self._mth(start + k, end)]
        return self._path(m, start + k, end) + [self._mth(start, start + k)]

    def inclusion_proofs_batch(self, ms, n: int) -> List[List[bytes]]:
        """Audit paths for MANY leaves of the same size-n prefix with a
        shared subtree-hash memo. A committed batch's replies all prove
        against the same tree, and contiguous leaves share nearly every
        upper node — the memo collapses per-proof cost to the few
        bottom siblings unique to each leaf (the per-reply
        inclusion_proof was a top-3 cost on the ordering money path)."""
        if not ms:
            return []
        if not (0 <= min(ms) and max(ms) < n <= self._size):
            raise IndexError("invalid inclusion proof batch ({}, {}) "
                             "for size {}".format(min(ms), n, self._size))
        memo = {}
        hash_children = self.hasher.hash_children
        read_leaf = self.hash_store.read_leaf
        read_subtree = self.hash_store.read_subtree

        def mth(start, end):
            key = (start, end)
            h = memo.get(key)
            if h is not None:
                return h
            width = end - start
            if width == 1:
                h = read_leaf(start)
            else:
                h = None
                if width & (width - 1) == 0 and start % width == 0:
                    h = read_subtree(start, width.bit_length() - 1)
                if h is None:
                    k = _largest_pow2_lt(width)
                    h = hash_children(mth(start, start + k),
                                      mth(start + k, end))
            memo[key] = h
            return h

        out = []
        for m in ms:
            path = []
            start, end = 0, n
            while end - start > 1:
                k = _largest_pow2_lt(end - start)
                if m - start < k:
                    path.append(mth(start + k, end))
                    end = start + k
                else:
                    path.append(mth(start, start + k))
                    start = start + k
            path.reverse()
            out.append(path)
        return out

    def consistency_proof(self, first: int, second: int) -> List[bytes]:
        """PROOF(m, D[0:n]) (RFC 6962 §2.1.2) that size-`first` tree is a
        prefix of the size-`second` tree."""
        if not 0 < first <= second <= self._size:
            raise IndexError("invalid consistency proof request ({}, {}) "
                             "for size {}".format(first, second, self._size))
        return self._subproof(first, 0, second, True)

    def _subproof(self, m: int, start: int, end: int, complete: bool) -> List[bytes]:
        n = end - start
        if m == n:
            return [] if complete else [self._mth(start, end)]
        k = _largest_pow2_lt(n)
        if m <= k:
            return self._subproof(m, start, start + k, complete) + \
                [self._mth(start + k, end)]
        return self._subproof(m - k, start + k, end, False) + \
            [self._mth(start, start + k)]

    # --------------------------------------------------------- recovery

    def load_from_hash_store(self, tree_size: int):
        """Rebuild the frontier for `tree_size` from persisted subtree
        hashes (reference recoverTreeFromHashStore)."""
        self._frontier = []
        self._size = tree_size
        self._root_cache = None  # content replaced wholesale
        start = 0
        remaining = tree_size
        while remaining > 0:
            h = remaining.bit_length() - 1
            width = 1 << h
            if h == 0:
                node = self.hash_store.read_leaf(start)
            else:
                node = self.hash_store.read_subtree(start, h)
                if node is None:
                    raise ValueError("hash store missing subtree ({}, {})"
                                     .format(start, h))
            self._frontier.append((start, h, node))
            start += width
            remaining -= width

    def verify_consistency(self, expected_leaf_count: int) -> bool:
        return self.hash_store.leaf_count >= expected_leaf_count

    def reset(self):
        self._size = 0
        self._frontier = []
        self._root_cache = None  # size alone can't invalidate a shrink
        self.hash_store.reset()

    def __repr__(self):
        return "CompactMerkleTree(size={}, root={})".format(
            self._size, self.root_hash.hex()[:16])
