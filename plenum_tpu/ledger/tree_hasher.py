"""RFC 6962 domain-separated SHA-256 hashing for the merkle transaction log.

Reference: ledger/tree_hasher.py:7 — leaf hash H(0x00||data), node hash
H(0x01||left||right). THE hot hash path (SURVEY.md §2.6): the batch entry
points below are the TPU seam — `hash_leaves` / `hash_node_pairs` route to
the JAX SHA-256 kernel (plenum_tpu.ops.sha256) above a configurable batch
threshold, with the C-backed hashlib loop as the scalar floor.
"""
import hashlib
from typing import List, Sequence, Tuple

import numpy as np


class TreeHasher:
    def __init__(self, hashfunc=hashlib.sha256, batch_backend=None,
                 batch_threshold: int = 256):
        self.hashfunc = hashfunc
        # batch_backend: object with leaf_hashes(list[bytes])->list[bytes]
        # and node_hashes(list[(l,r)])->list[bytes]; see ops/sha256.py
        self._batch_backend = batch_backend
        self._batch_threshold = batch_threshold

    def hash_empty(self) -> bytes:
        return self.hashfunc().digest()

    def hash_leaf(self, data: bytes) -> bytes:
        return self.hashfunc(b"\x00" + data).digest()

    def hash_children(self, left: bytes, right: bytes) -> bytes:
        return self.hashfunc(b"\x01" + left + right).digest()

    # ---- batch entry points (TPU seam) ----

    def hash_leaves(self, datas: Sequence[bytes]) -> List[bytes]:
        if (self._batch_backend is not None
                and len(datas) >= self._batch_threshold):
            return self._batch_backend.leaf_hashes(datas)
        return [self.hash_leaf(d) for d in datas]

    def hash_leaves_dispatch(self, datas: Sequence[bytes]):
        """Launch-only half of hash_leaves: above the device threshold
        the batch is padded and LAUNCHED without syncing the result, so
        the caller overlaps independent host work (the fused per-3PC-
        batch dispatch) before hash_leaves_collect. On the scalar floor
        the digests are computed eagerly and the handle just carries
        them — dispatch+collect is then exactly hash_leaves."""
        if (self._batch_backend is not None
                and len(datas) >= self._batch_threshold
                and hasattr(self._batch_backend, "leaf_hashes_dispatch")):
            return ("device", self._batch_backend.leaf_hashes_dispatch(
                datas))
        return ("host", [self.hash_leaf(d) for d in datas])

    def hash_leaves_collect(self, handle) -> List[bytes]:
        kind, payload = handle
        if kind == "device":
            return self._batch_backend.leaf_hashes_collect(payload)
        return payload

    def hash_node_pairs(self, pairs: Sequence[Tuple[bytes, bytes]]) -> List[bytes]:
        if (self._batch_backend is not None
                and len(pairs) >= self._batch_threshold):
            return self._batch_backend.node_hashes(pairs)
        return [self.hash_children(l, r) for l, r in pairs]

    def hash_node_pairs_array(self, pairs: 'np.ndarray') -> 'np.ndarray':
        """[m, 64] u8 rows (left||right digest bytes) → [m, 32] u8 node
        digests: the array sibling of hash_node_pairs for level-wise
        bulk paths whose output is immediately re-paired — skips the
        per-pair message objects and the per-digest bytes objects."""
        pairs = np.ascontiguousarray(pairs, dtype=np.uint8).reshape(-1, 64)
        m = pairs.shape[0]
        if (self._batch_backend is not None
                and m >= self._batch_threshold
                and hasattr(self._batch_backend, "node_hashes_array")):
            return self._batch_backend.node_hashes_array(pairs)
        out = np.empty((m, 32), dtype=np.uint8)
        hashfunc = self.hashfunc
        flat = pairs.tobytes()
        for i in range(m):
            out[i] = np.frombuffer(
                hashfunc(b"\x01" + flat[i * 64:(i + 1) * 64]).digest(),
                dtype=np.uint8)
        return out

    # ---- whole-tree hashing (used by verifier and tests) ----

    def hash_full_tree(self, leaves: Sequence[bytes]) -> bytes:
        """MTH over a list of raw leaf entries (RFC 6962 §2.1)."""
        n = len(leaves)
        if n == 0:
            return self.hash_empty()
        if n == 1:
            return self.hash_leaf(leaves[0])
        k = _largest_pow2_lt(n)
        return self.hash_children(self.hash_full_tree(leaves[:k]),
                                  self.hash_full_tree(leaves[k:]))


def _largest_pow2_lt(n: int) -> int:
    """Largest power of two strictly less than n (n >= 2)."""
    return 1 << ((n - 1).bit_length() - 1)
