"""Ledger: merkle-hashed append-only transaction log with uncommitted
staging for 3PC apply/revert.

Reference: ledger/ledger.py:17 (base) + plenum/common/ledger.py (staging
subclass) — merged into one class here. Txns are msgpack-serialized into an
int-keyed KV store; each committed txn's leaf hash feeds the
CompactMerkleTree; uncommitted txns extend a shadow tree (root-only) so
state roots for PRE-PREPARE are available before commit.
"""
from typing import Callable, Dict, Generator, List, Optional, Tuple

from plenum_tpu.common.serializers.base58 import b58decode, b58encode
from plenum_tpu.common.serializers.serialization import ledger_txn_serializer
from plenum_tpu.common.txn_util import get_seq_no, append_txn_metadata
from plenum_tpu.ledger.compact_merkle_tree import CompactMerkleTree
from plenum_tpu.ledger.hash_store import KVHashStore, MemoryHashStore
from plenum_tpu.ledger.tree_hasher import TreeHasher
from plenum_tpu.storage.kv_store import KeyValueStorage
from plenum_tpu.storage.kv_memory import KeyValueStorageInMemory

SEQ_NO_PAD = 20


def _seq_key(seq_no: int) -> bytes:
    return str(seq_no).zfill(SEQ_NO_PAD).encode()


class Ledger:
    def __init__(self,
                 tree: CompactMerkleTree = None,
                 txn_store: KeyValueStorage = None,
                 txn_serializer=None,
                 genesis_txn_initiator=None,
                 tree_hasher: TreeHasher = None):
        hasher = tree_hasher or TreeHasher()
        self.tree = tree or CompactMerkleTree(hasher, MemoryHashStore())
        self.hasher = self.tree.hasher
        self._store = txn_store if txn_store is not None \
            else KeyValueStorageInMemory()
        self.txn_serializer = txn_serializer or ledger_txn_serializer
        self.genesis_txn_initiator = genesis_txn_initiator
        self.seqNo = 0
        # uncommitted staging (reference plenum/common/ledger.py)
        self.uncommittedTxns: List[dict] = []
        # (serialized, leaf_hash) per staged txn: the bytes that fed the
        # shadow tree ARE the bytes commit must store/hash — reusing
        # them both halves the serialization work and guarantees the
        # committed root equals the root the pool agreed on
        self._uncommitted_blobs: List[Tuple[bytes, bytes]] = []
        self.uncommittedTree: Optional[CompactMerkleTree] = None
        self.uncommittedRootHash: Optional[bytes] = None
        self.recoverTree()
        if self.size == 0 and genesis_txn_initiator is not None:
            for txn in genesis_txn_initiator():
                self.add(txn)

    # --------------------------------------------------------- recovery

    def recoverTree(self):
        """Rebuild tree state from the txn store (reference ledger.py:70)."""
        count = sum(1 for _ in self._store.iterator(include_value=False))
        if count == 0:
            self.seqNo = 0
            return
        try:
            self.tree.load_from_hash_store(count)
            self.seqNo = count
        except Exception:
            self.recoverTreeFromTxnLog()

    def recoverTreeFromTxnLog(self):
        """Bulk rebuild: one batched leaf-hash dispatch plus level-wise
        node hashing through the TreeHasher TPU seam (reference
        ledger.py:70 recoverTree rebuilds leaf-by-leaf on hashlib)."""
        self.tree.reset()
        values = [bytes(v) for _, v in self._store.iterator()]
        self.tree.extend(values)
        self.seqNo = len(values)

    # ---------------------------------------------------------- commits

    def add_quiet(self, txn: dict) -> int:
        """Append a committed txn; returns its seqNo. The commit hot path:
        no merkle-info dict is built — Replies fetch proofs on demand via
        merkleInfo(seq_no), so computing root + audit-path b58 strings
        per append (reference ledger.py:115 does) is wasted work."""
        seq_no = self.seqNo + 1
        append_txn_metadata(txn, seq_no=seq_no)
        serialized = self.serialize_for_tree(txn)
        self.tree._append_hash(self.hasher.hash_leaf(serialized),
                               want_path=False)
        self._store.put(_seq_key(seq_no), serialized)
        self.seqNo = seq_no
        return seq_no

    def add(self, txn: dict) -> dict:
        """Append a committed txn; returns merkle info (seqNo, rootHash,
        auditPath) (reference ledger.py:115)."""
        seq_no = self.seqNo + 1
        append_txn_metadata(txn, seq_no=seq_no)
        serialized = self.serialize_for_tree(txn)
        audit_path = self.tree.append(serialized)
        self._store.put(_seq_key(seq_no), serialized)
        self.seqNo = seq_no
        return {
            'seqNo': seq_no,
            'rootHash': self.hashToStr(self.tree.root_hash),
            'auditPath': [self.hashToStr(h) for h in audit_path],
        }

    append = add

    # ----------------------------------------------- uncommitted staging

    def append_txns_metadata(self, txns: List[dict], txn_time: int = None):
        for i, txn in enumerate(txns):
            seq_no = self.uncommitted_size + i + 1
            append_txn_metadata(txn, seq_no=seq_no, txn_time=txn_time)
        return txns

    def appendTxns(self, txns: List[dict]) -> Tuple[Tuple[int, int], List[dict]]:
        """Stage txns: extend the shadow tree, track uncommitted root.
        Returns ((start, end), txns)."""
        return self.stage_txns_collect(self.stage_txns_dispatch(txns))

    def stage_txns_dispatch(self, txns: List[dict]):
        """Async half of appendTxns: serialize the batch and LAUNCH the
        leaf-hash computation (ONE seam dispatch, device-backed above
        the TreeHasher threshold) without syncing the digests — the
        fused per-3PC-batch dispatch overlaps the MPT pending-apply
        under this launch. No other staging may touch this ledger
        between dispatch and collect (the executor stages one batch at
        a time per ledger)."""
        if self.uncommittedTree is None:
            self.uncommittedTree = self.tree.copy_shadow()
        serialize = self.serialize_for_tree
        serialized_all = [serialize(txn) for txn in txns]
        return (txns, serialized_all,
                self.hasher.hash_leaves_dispatch(serialized_all))

    def stage_txns_collect(self, staged) -> Tuple[Tuple[int, int],
                                                  List[dict]]:
        """Blocking half of appendTxns: collect the launched leaf
        hashes and merge them into the shadow frontier (O(b log n)
        cheap host work)."""
        txns, serialized_all, handle = staged
        first = self.uncommitted_size + 1
        shadow_append = self.uncommittedTree._append_hash
        blob_append = self._uncommitted_blobs.append
        leaf_hashes = self.hasher.hash_leaves_collect(handle)
        for serialized, leaf_hash in zip(serialized_all, leaf_hashes):
            shadow_append(leaf_hash, want_path=False)
            blob_append((serialized, leaf_hash))
        self.uncommittedTxns.extend(txns)
        # root is NOT folded here: staging runs once per request, the
        # root is read once per batch — uncommitted_root_hash computes
        # it on demand (the tree caches by size)
        self.uncommittedRootHash = None
        last = self.uncommitted_size
        return (first, last), txns

    def commitTxns(self, count: int) -> Tuple[Tuple[int, int], List[dict]]:
        """Move the oldest `count` uncommitted txns into the durable log +
        real tree (reference plenum/common/ledger.py commitTxns). Commit
        replays the STAGED bytes/leaf hashes — txns are FIFO, their
        metadata (seq_no, time) was fixed at staging, and the agreed
        uncommitted root was computed from exactly these leaves."""
        committed = []
        first = self.seqNo + 1
        store_put, tree_append = self._store.put, self.tree._append_hash
        for txn, (serialized, leaf_hash) in zip(
                self.uncommittedTxns[:count],
                self._uncommitted_blobs[:count]):
            seq_no = self.seqNo + 1
            tree_append(leaf_hash, want_path=False)
            store_put(_seq_key(seq_no), serialized)
            self.seqNo = seq_no
            committed.append(txn)
        self.uncommittedTxns = self.uncommittedTxns[count:]
        self._uncommitted_blobs = self._uncommitted_blobs[count:]
        if not self.uncommittedTxns:
            self.uncommittedTree = None
            self.uncommittedRootHash = None
        # else: the shadow tree already contains exactly the leaves the
        # committed tree just gained plus the remaining staged txns — its
        # root is unchanged, so no rebuild is needed.
        return (first, self.seqNo), committed

    def discardTxns(self, count: int):
        """Drop the newest `count` uncommitted txns (batch revert)."""
        remaining = self.uncommittedTxns[:-count] if count else self.uncommittedTxns
        self.uncommittedTxns = []
        self._uncommitted_blobs = []
        self.uncommittedTree = None
        self.uncommittedRootHash = None
        if remaining:
            self.appendTxns(remaining)

    @property
    def uncommitted_size(self) -> int:
        return self.seqNo + len(self.uncommittedTxns)

    @property
    def uncommitted_root_hash(self) -> bytes:
        if self.uncommittedTree is not None:
            return self.uncommittedTree.root_hash
        if self.uncommittedRootHash is not None:
            return self.uncommittedRootHash
        return self.tree.root_hash

    # ------------------------------------------------------------ reads

    def getBySeqNo(self, seq_no: int) -> Optional[dict]:
        try:
            raw = self._store.get(_seq_key(seq_no))
        except KeyError:
            return None
        return self.txn_serializer.deserialize(raw)

    def get_by_seq_no_uncommitted(self, seq_no: int) -> Optional[dict]:
        if seq_no <= self.seqNo:
            return self.getBySeqNo(seq_no)
        idx = seq_no - self.seqNo - 1
        if idx < len(self.uncommittedTxns):
            return self.uncommittedTxns[idx]
        return None

    def __getitem__(self, seq_no: int):
        return self.getBySeqNo(seq_no)

    def getAllTxn(self, frm: int = None, to: int = None
                  ) -> Generator[Tuple[int, dict], None, None]:
        start = _seq_key(frm) if frm is not None else None
        end = _seq_key(to) if to is not None else None
        for key, value in self._store.iterator(start=start, end=end):
            yield int(key), self.txn_serializer.deserialize(value)

    def get_last_txn(self) -> Optional[dict]:
        return self.getBySeqNo(self.seqNo) if self.seqNo else None

    def get_last_committed_txn(self) -> Optional[dict]:
        return self.get_last_txn()

    @property
    def size(self) -> int:
        return self.seqNo

    def __len__(self):
        return self.size

    @property
    def root_hash(self) -> str:
        return self.hashToStr(self.tree.root_hash)

    @property
    def root_hash_raw(self) -> bytes:
        return self.tree.root_hash

    # ------------------------------------------------------------ proofs

    def merkleInfo(self, seq_no: int) -> Dict:
        """Inclusion proof of txn `seq_no` in the current tree (reference
        ledger.py:196)."""
        if not 0 < seq_no <= self.seqNo:
            raise ValueError("invalid seqNo {}".format(seq_no))
        path = self.tree.inclusion_proof(seq_no - 1, self.seqNo)
        return {
            'seqNo': seq_no,
            'rootHash': self.hashToStr(self.tree.root_hash),
            'auditPath': [self.hashToStr(h) for h in path],
        }

    def merkleInfoBatch(self, seq_nos) -> List[Dict]:
        """merkleInfo for many txns of one committed batch in one call:
        the audit paths share a subtree-hash memo AND a digest→b58 memo
        (the per-hash b58 string is recomputed across overlapping paths
        otherwise). Order matches `seq_nos`."""
        size = self.seqNo
        for s in seq_nos:
            if not 0 < s <= size:
                raise ValueError("invalid seqNo {}".format(s))
        paths = self.tree.inclusion_proofs_batch(
            [s - 1 for s in seq_nos], size)
        root = self.hashToStr(self.tree.root_hash)
        to_str = self.hashToStr
        str_memo: Dict[bytes, str] = {}

        def enc(h):
            s = str_memo.get(h)
            if s is None:
                s = str_memo[h] = to_str(h)
            return s

        return [{'seqNo': s, 'rootHash': root,
                 'auditPath': [enc(h) for h in path]}
                for s, path in zip(seq_nos, paths)]

    auditProof = merkleInfo

    # -------------------------------------------------------------- util

    def serialize_for_tree(self, txn: dict) -> bytes:
        return self.txn_serializer.serialize(txn)

    @staticmethod
    def hashToStr(h: bytes) -> str:
        return b58encode(h)

    @staticmethod
    def strToHash(s: str) -> bytes:
        return b58decode(s)

    def start(self, loop=None):
        pass

    def stop(self):
        self._store.close()
        self.tree.hash_store.close()

    def reset(self):
        self.tree.reset()
        self._store.drop()
        self.seqNo = 0
        self.uncommittedTxns = []
        self._uncommitted_blobs = []
        self.uncommittedTree = None
        self.uncommittedRootHash = None
