"""Hash stores: leaf hashes by index + full-subtree hashes by (start, height).

Reference: ledger/hash_stores/hash_store.py (positions of leaves/nodes) —
re-designed here: instead of the reference's sequential node numbering, full
aligned subtrees are keyed directly by (start_leaf, height), which makes the
recursive range-hash/proof algorithms straight lookups.
"""
from abc import ABC, abstractmethod
from typing import Optional

from plenum_tpu.storage.kv_store import KeyValueStorage


class HashStore(ABC):
    @abstractmethod
    def write_leaf(self, index: int, leaf_hash: bytes) -> None:
        """index is 0-based."""

    @abstractmethod
    def read_leaf(self, index: int) -> bytes:
        ...

    def read_leaves(self, start: int, end: int) -> list:
        """Leaf hashes for indices [start, end) — bulk variant for the
        device-engine catch-up path; stores with cheap range access
        override the per-leaf loop."""
        read = self.read_leaf
        return [read(i) for i in range(start, end)]

    @abstractmethod
    def write_subtree(self, start: int, height: int, node_hash: bytes) -> None:
        ...

    @abstractmethod
    def read_subtree(self, start: int, height: int) -> Optional[bytes]:
        ...

    @property
    @abstractmethod
    def leaf_count(self) -> int:
        ...

    @abstractmethod
    def reset(self) -> None:
        ...

    def close(self):
        pass

    @property
    def is_persistent(self) -> bool:
        return False


class MemoryHashStore(HashStore):
    def __init__(self):
        self._leaves = []
        self._nodes = {}

    def write_leaf(self, index, leaf_hash):
        if index == len(self._leaves):
            self._leaves.append(leaf_hash)
        else:
            # overwrite during recovery replay
            self._leaves[index] = leaf_hash

    def read_leaf(self, index):
        return self._leaves[index]

    def read_leaves(self, start, end):
        return self._leaves[start:end]

    def write_subtree(self, start, height, node_hash):
        self._nodes[(start, height)] = node_hash

    def read_subtree(self, start, height):
        return self._nodes.get((start, height))

    @property
    def leaf_count(self):
        return len(self._leaves)

    def reset(self):
        self._leaves = []
        self._nodes = {}


class NullHashStore(HashStore):
    """Discards everything — used by shadow (uncommitted) tree copies that
    only need root computation, never proofs."""

    def __init__(self):
        self._leaf_count = 0

    def write_leaf(self, index, leaf_hash):
        self._leaf_count = max(self._leaf_count, index + 1)

    def read_leaf(self, index):
        raise KeyError("NullHashStore stores nothing")

    def write_subtree(self, start, height, node_hash):
        pass

    def read_subtree(self, start, height):
        return None

    @property
    def leaf_count(self):
        return self._leaf_count

    def reset(self):
        self._leaf_count = 0


class KVHashStore(HashStore):
    """Durable hash store over any KeyValueStorage (reference:
    storage/db_hash_store.py)."""

    def __init__(self, store: KeyValueStorage):
        self._store = store
        self._leaf_count = 0
        for k, _ in store.iterator(start=b'l:', end=b'l:\xff'):
            idx = int(k[2:])
            self._leaf_count = max(self._leaf_count, idx + 1)

    @staticmethod
    def _leaf_key(index: int) -> bytes:
        return b'l:' + str(index).zfill(20).encode()

    @staticmethod
    def _node_key(start: int, height: int) -> bytes:
        return b'n:' + str(start).zfill(20).encode() + b':' + \
            str(height).zfill(3).encode()

    def write_leaf(self, index, leaf_hash):
        self._store.put(self._leaf_key(index), leaf_hash)
        self._leaf_count = max(self._leaf_count, index + 1)

    def read_leaf(self, index):
        return self._store.get(self._leaf_key(index))

    def write_subtree(self, start, height, node_hash):
        self._store.put(self._node_key(start, height), node_hash)

    def read_subtree(self, start, height):
        try:
            return self._store.get(self._node_key(start, height))
        except KeyError:
            return None

    @property
    def leaf_count(self):
        return self._leaf_count

    def reset(self):
        self._store.drop()
        self._leaf_count = 0

    def close(self):
        self._store.close()

    @property
    def is_persistent(self):
        return True
