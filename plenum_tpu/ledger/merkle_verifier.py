"""Client/catchup-side merkle proof verification (reference:
ledger/merkle_verifier.py — RFC 6962 verification algorithms).

Batch verification of many audit paths (catchup reps) is exposed via
`verify_leaf_inclusion_batch`, which routes the per-level hashing through the
TreeHasher TPU seam.
"""
from typing import List, Sequence, Tuple

from plenum_tpu.ledger.tree_hasher import TreeHasher


class ProofError(Exception):
    pass


class MerkleVerifier:
    def __init__(self, hasher: TreeHasher = None):
        self.hasher = hasher or TreeHasher()

    # ------------------------------------------------------- inclusion

    def calculate_root_from_audit_path(self, leaf_hash: bytes,
                                       leaf_index: int, tree_size: int,
                                       audit_path: Sequence[bytes]) -> bytes:
        fn, sn = leaf_index, tree_size - 1
        r = leaf_hash
        for p in audit_path:
            if sn == 0:
                raise ProofError("audit path too long")
            if fn & 1 or fn == sn:
                r = self.hasher.hash_children(p, r)
                if not fn & 1:
                    while fn & 1 == 0 and fn != 0:
                        fn >>= 1
                        sn >>= 1
            else:
                r = self.hasher.hash_children(r, p)
            fn >>= 1
            sn >>= 1
        if sn != 0:
            raise ProofError("audit path too short")
        return r

    def verify_leaf_hash_inclusion(self, leaf_hash: bytes, leaf_index: int,
                                   audit_path: Sequence[bytes],
                                   tree_size: int, root_hash: bytes) -> bool:
        calc = self.calculate_root_from_audit_path(
            leaf_hash, leaf_index, tree_size, audit_path)
        if calc != root_hash:
            raise ProofError(
                "inclusion check failed: calculated {} expected {}"
                .format(calc.hex(), root_hash.hex()))
        return True

    def verify_leaf_inclusion(self, leaf: bytes, leaf_index: int,
                              audit_path: Sequence[bytes],
                              tree_size: int, root_hash: bytes) -> bool:
        return self.verify_leaf_hash_inclusion(
            self.hasher.hash_leaf(leaf), leaf_index, audit_path,
            tree_size, root_hash)

    def verify_leaf_inclusion_batch(
            self, items: Sequence[Tuple[bytes, int, Sequence[bytes]]],
            tree_size: int, root_hash: bytes) -> bool:
        """Verify many (leaf, index, audit_path) against one root — the
        catchup-rep hot path. Leaf hashing batches through the TPU seam;
        path folding is per-item (paths differ in shape)."""
        leaf_hashes = self.hasher.hash_leaves([leaf for leaf, _, _ in items])
        for leaf_hash, (_, idx, path) in zip(leaf_hashes, items):
            self.verify_leaf_hash_inclusion(leaf_hash, idx, path,
                                            tree_size, root_hash)
        return True

    # ----------------------------------------------------- consistency

    def verify_tree_consistency(self, old_tree_size: int, new_tree_size: int,
                                old_root: bytes, new_root: bytes,
                                proof: Sequence[bytes]) -> bool:
        if old_tree_size < 0 or new_tree_size < 0:
            raise ValueError("negative tree size")
        if old_tree_size > new_tree_size:
            raise ProofError("old size {} > new size {}"
                             .format(old_tree_size, new_tree_size))
        if old_tree_size == new_tree_size:
            if old_root != new_root:
                raise ProofError("inconsistency: same size, different roots")
            return True
        if old_tree_size == 0:
            return True  # anything is consistent with the empty tree
        # RFC 9162 §2.1.4.2 verification
        proof = list(proof)
        if old_tree_size & (old_tree_size - 1) == 0:
            # old tree was a full subtree: its root is an implicit first
            # proof element
            proof = [old_root] + proof
        if not proof:
            raise ProofError("empty consistency proof")
        fn, sn = old_tree_size - 1, new_tree_size - 1
        while fn & 1:
            fn >>= 1
            sn >>= 1
        fr = sr = proof[0]
        for c in proof[1:]:
            if sn == 0:
                raise ProofError("consistency proof too long")
            if fn & 1 or fn == sn:
                fr = self.hasher.hash_children(c, fr)
                sr = self.hasher.hash_children(c, sr)
                while fn & 1 == 0 and fn != 0:
                    fn >>= 1
                    sn >>= 1
            else:
                sr = self.hasher.hash_children(sr, c)
            fn >>= 1
            sn >>= 1
        if fr != old_root:
            raise ProofError("consistency check failed for old root")
        if sr != new_root:
            raise ProofError("consistency check failed for new root")
        if sn != 0:
            raise ProofError("consistency proof too short")
        return True

    @staticmethod
    def audit_path_length(index: int, tree_size: int) -> int:
        length = 0
        last_node = tree_size - 1
        while last_node > 0:
            if index & 1 or index < last_node:
                length += 1
            index >>= 1
            last_node >>= 1
        return length
