"""Genesis transaction bootstrap (reference: ledger/genesis_txn/ — file
with one JSON txn per line, or an in-memory list)."""
import json
import os
from typing import Iterator, List


class GenesisTxnInitiatorFromFile:
    def __init__(self, data_dir: str, txn_file: str):
        self._path = os.path.join(data_dir, txn_file)

    def __call__(self) -> Iterator[dict]:
        if not os.path.exists(self._path):
            return
        with open(self._path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    yield json.loads(line)


class GenesisTxnInitiatorFromMem:
    def __init__(self, txns: List[dict]):
        self._txns = txns

    def __call__(self) -> Iterator[dict]:
        return iter([json.loads(json.dumps(t)) for t in self._txns])


def create_genesis_txn_file(txns: List[dict], data_dir: str, txn_file: str):
    os.makedirs(data_dir, exist_ok=True)
    path = os.path.join(data_dir, txn_file)
    with open(path, 'w') as fh:
        for txn in txns:
            fh.write(json.dumps(txn, sort_keys=True) + '\n')
    return path
