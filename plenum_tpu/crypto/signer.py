"""Identity signers — the reference's Signer seam.

Reference: `stp_core/crypto/signer.py:9` (Signer ABC),
`plenum/common/signer_simple.py:13` (SimpleSigner: identifier = b58 verkey),
`plenum/common/signer_did.py:76` (DidSigner: identifier = b58 of first 16
bytes of verkey, abbreviated verkey with '~' prefix).
"""
from __future__ import annotations

import os
from abc import ABC, abstractmethod
from typing import Optional

from plenum_tpu.common.serializers.base58 import b58decode, b58encode
from plenum_tpu.common.serializers.serialization import serialize_msg_for_signing
from . import ed25519


class Signer(ABC):
    @property
    @abstractmethod
    def identifier(self) -> str: ...

    @property
    @abstractmethod
    def verkey(self) -> str: ...

    @abstractmethod
    def sign(self, msg) -> str: ...


try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey as _OsslSk)
except ImportError:          # pragma: no cover - cryptography is baked in
    _OsslSk = None


class SimpleSigner(Signer):
    """identifier == full b58 verkey.

    Signing rides OpenSSL (Ed25519 is deterministic per RFC 8032, so
    the output is bit-identical) with the pure-Python implementation as
    the reference fallback — the libsodium role in the reference's
    stp_core/crypto/nacl_wrappers.py."""

    def __init__(self, seed: Optional[bytes] = None):
        self.seed = seed or os.urandom(32)
        if len(self.seed) != 32:
            raise ValueError("seed must be 32 bytes")
        self._ossl = (_OsslSk.from_private_bytes(self.seed)
                      if _OsslSk is not None else None)
        if self._ossl is not None:
            # same derivation as the pure-Python path, ~100x faster
            from cryptography.hazmat.primitives.serialization import (
                Encoding, PublicFormat)
            self.verraw = self._ossl.public_key().public_bytes(
                Encoding.Raw, PublicFormat.Raw)
        else:                                          # pragma: no cover
            self.verraw, _ = ed25519.keypair_from_seed(self.seed)
        self.verstr = b58encode(self.verraw)

    @property
    def identifier(self) -> str:
        return self.verstr

    @property
    def verkey(self) -> str:
        return self.verstr

    def sign_bytes(self, data: bytes) -> bytes:
        if self._ossl is not None:
            return self._ossl.sign(data)
        return ed25519.sign(data, self.seed)

    def sign(self, msg) -> str:
        """Sign a dict (canonical signing serialization) or bytes → b58."""
        data = msg if isinstance(msg, bytes) else serialize_msg_for_signing(msg)
        return b58encode(self.sign_bytes(data))


class DidSigner(Signer):
    """DID-style: identifier = b58(verkey[:16]), abbreviated verkey =
    '~' + b58(verkey[16:])."""

    def __init__(self, seed: Optional[bytes] = None):
        self._simple = SimpleSigner(seed)
        raw = self._simple.verraw
        self._identifier = b58encode(raw[:16])
        self._abbreviated = "~" + b58encode(raw[16:])

    @property
    def seed(self) -> bytes:
        return self._simple.seed

    @property
    def identifier(self) -> str:
        return self._identifier

    @property
    def verkey(self) -> str:
        return self._abbreviated

    @property
    def full_verkey(self) -> str:
        return self._simple.verstr

    def sign(self, msg) -> str:
        return self._simple.sign(msg)


def verkey_from_identifier(identifier: str, verkey: Optional[str]) -> bytes:
    """Resolve raw 32-byte verkey from (identifier, maybe-abbreviated verkey).

    Reference semantics: a '~'-prefixed verkey is completed by the
    identifier's 16 bytes; a missing verkey means the identifier IS the
    verkey (cryptonym).
    """
    if not verkey:
        return b58decode(identifier)
    if verkey.startswith("~"):
        return b58decode(identifier) + b58decode(verkey[1:])
    return b58decode(verkey)
