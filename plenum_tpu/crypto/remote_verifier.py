"""RemoteVerifier — batch-verification provider that offloads to the
verify daemon (server/verify_daemon.py) over a local socket.

Same dispatch()/collect() interface as the in-process providers
(crypto/batch_verifier.py), plus ready(): the node's prod loop polls it
so the daemon round trip (device launch + tunnel RTT) overlaps consensus
work instead of blocking a tick. The socket is plain blocking TCP used
non-blockingly for reads; frames are length-prefixed msgpack (see the
daemon's protocol doc).
"""
from __future__ import annotations

import logging
import socket
import struct
import time
from typing import Dict, List, Sequence, Tuple

import msgpack

logger = logging.getLogger(__name__)

LEN = struct.Struct("<I")
# re-dial pacing: a dead daemon must not turn every dispatch into a
# blocking connect attempt on the prod loop
RECONNECT_COOLDOWN = 1.0
RECONNECT_TIMEOUT = 0.5

VerifyItem = Tuple[bytes, bytes, bytes]


class _RemotePending:
    def __init__(self, verifier: "RemoteVerifier", req_id: int, n: int):
        self._verifier = verifier
        self._req_id = req_id
        self._n = n

    def ready(self) -> bool:
        v = self._verifier
        if self._req_id in v._results or v._sock is None:
            return True
        v._pump(block=False)
        return self._req_id in v._results or v._sock is None

    def collect(self) -> List[bool]:
        v = self._verifier
        while self._req_id not in v._results:
            if v._sock is None:
                v._results.setdefault(self._req_id, b"")
                break
            # block until THIS request's frame lands — returning on just
            # any response would mis-handle out-of-order harvest when
            # more than one request is in flight
            v._pump(block=True, until=self._req_id)
        body = v._results.pop(self._req_id, b"")
        # a short body (daemon rejected the frame, or the link dropped
        # mid-request) fails the missing tail instead of crashing the
        # caller's result slicing
        return [i < len(body) and body[i] == 1 for i in range(self._n)]


class RemoteVerifier:
    """Failure policy: if the daemon drops or times out, every in-flight
    request resolves to all-False (the node nacks those client requests;
    clients resubmit) and the connection is re-dialed lazily on the next
    dispatch — a daemon restart must never take the node's prod loop
    down with an unhandled ConnectionError."""

    name = "remote"

    def __init__(self, addr: Tuple[str, int] = None, timeout: float = 30.0):
        self._addr = addr or ("127.0.0.1", 9999)
        self._timeout = timeout
        self._sock = None
        self._rx = b""
        self._results: Dict[int, bytes] = {}
        self._outstanding: Dict[int, int] = {}  # req_id -> item count
        self._next_id = 0
        self._last_dial_fail = 0.0
        # initial connect is best-effort: in multi-process deployments
        # the daemon may come up after the node (start-ordering race,
        # daemon restart); dispatch() re-dials lazily, so construction
        # must not hard-fail
        try:
            self._connect()
        except OSError as e:
            logger.warning(
                "verify daemon at %s:%d not reachable at construction "
                "(%s) — will re-dial on first dispatch", self._addr[0],
                self._addr[1], e)
            self._sock = None
            self._last_dial_fail = time.monotonic()

    def _connect(self, timeout: float = None):
        self._sock = socket.create_connection(
            self._addr, timeout=self._timeout if timeout is None
            else timeout)
        self._sock.settimeout(self._timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._rx = b""

    def _drop_link(self):
        """Fail all in-flight requests and discard the socket."""
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        for req_id in list(self._outstanding):
            self._results[req_id] = b""  # short body == all False
            del self._outstanding[req_id]

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # -------------------------------------------------------- dispatch

    def dispatch(self, items: Sequence[VerifyItem]) -> _RemotePending:
        self._next_id += 1
        req_id = self._next_id
        frame = msgpack.packb(
            [req_id, [[bytes(m), bytes(s), bytes(vk)]
                      for m, s, vk in items]], use_bin_type=True)
        self._outstanding[req_id] = len(items)
        if self._sock is None and time.monotonic() - self._last_dial_fail \
                < RECONNECT_COOLDOWN:
            # paced re-dial: fail this batch WITHOUT touching the
            # cooldown clock — refreshing it here would push the expiry
            # forward on every dispatch and starve reconnection forever
            # under sustained traffic
            self._drop_link()
            return _RemotePending(self, req_id, len(items))
        try:
            if self._sock is None:
                # short-timeout re-dial: the prod loop must not block up
                # to self._timeout per intake batch while the daemon
                # host is black-holing SYNs
                self._connect(timeout=RECONNECT_TIMEOUT)
                logger.info("reconnected to verify daemon at %s:%d",
                            self._addr[0], self._addr[1])
            self._sock.sendall(LEN.pack(len(frame)) + frame)
        except OSError as e:
            if self._sock is None:
                self._last_dial_fail = time.monotonic()
                logger.warning("verify daemon at %s:%d unavailable (%s); "
                               "failing batch of %d", self._addr[0],
                               self._addr[1], e, len(items))
            else:
                logger.warning("verify daemon link lost (%s); failing "
                               "in-flight requests", e)
            self._drop_link()
        return _RemotePending(self, req_id, len(items))

    def verify_batch(self, items: Sequence[VerifyItem]) -> List[bool]:
        return self.dispatch(items).collect()

    # ------------------------------------------------------------- recv

    def _pump(self, block: bool, until: int = None):
        """Read frames. block=False drains whatever is buffered;
        block=True reads until the `until` req_id arrives (or, with no
        target, until anything does) or the timeout drops the link."""
        if self._sock is None:
            return  # dropped link already resolved everything to False
        self._sock.settimeout(self._timeout if block else 0.0)
        try:
            while True:
                chunk = self._sock.recv(1 << 20)
                if not chunk:
                    raise ConnectionError("verify daemon closed")
                self._rx += chunk
                self._drain_frames()
                if block and (until in self._results if until is not None
                              else bool(self._results)):
                    return
        except (BlockingIOError, socket.timeout):
            if block:
                self._drop_link()
        except (ConnectionError, OSError):
            self._drop_link()
        finally:
            if self._sock is not None:
                self._sock.settimeout(self._timeout)

    def _drain_frames(self):
        while len(self._rx) >= 4:
            (n,) = LEN.unpack(self._rx[:4])
            if len(self._rx) < 4 + n:
                return
            req_id, body = msgpack.unpackb(self._rx[4:4 + n], raw=False)
            self._rx = self._rx[4 + n:]
            self._results[req_id] = body
            self._outstanding.pop(req_id, None)
