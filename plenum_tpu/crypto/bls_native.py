"""ctypes bridge to the native BLS12-381 module — drop-in for the hot
functions of plenum_tpu.crypto.bls12_381 (the pure-Python module remains
the reference implementation and the fallback when no C compiler is
available).

Same point representation at the Python boundary as bls12_381.py:
G1 = (x, y) int tuple / None; G2 = (Fq2, Fq2) / None. Conversion to the
C ABI (48-byte big-endian field elements) costs nanoseconds against
millisecond-scale curve operations.

Reference parity: crypto/bls/indy_crypto/bls_crypto_indy_crypto.py binds
Rust ursa for exactly these operations.
"""
from __future__ import annotations

import ctypes
import logging
import subprocess
from typing import List, Optional, Sequence, Tuple

from plenum_tpu.crypto.bls12_381 import (
    Fq2, G1Point, G2Point, Q, R)

logger = logging.getLogger(__name__)

_lib = None
_build_error: Optional[Exception] = None


def _get_lib():
    global _lib
    if _lib is None:
        from plenum_tpu.native import build_and_load
        lib = build_and_load("bls12_381")
        lib.b_g1_add.argtypes = [ctypes.c_char_p] * 2 + [ctypes.c_char_p]
        lib.b_g1_mul.argtypes = [ctypes.c_char_p] * 2 + [ctypes.c_char_p]
        lib.b_g2_add.argtypes = [ctypes.c_char_p] * 2 + [ctypes.c_char_p]
        lib.b_g2_mul.argtypes = [ctypes.c_char_p] * 2 + [ctypes.c_char_p]
        lib.b_multi_pairing_is_one.argtypes = [
            ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p]
        lib.b_multi_pairing_is_one.restype = ctypes.c_int
        lib.b_g1_decompress.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.b_g1_decompress.restype = ctypes.c_int
        lib.b_pairing.argtypes = [ctypes.c_char_p] * 2 + [ctypes.c_char_p]
        lib.b_hash_to_g1.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                     ctypes.c_char_p, ctypes.c_int,
                                     ctypes.c_char_p]
        lib.b_hash_to_g1.restype = ctypes.c_int
        lib.b_prep_size.restype = ctypes.c_int
        lib.b_miller_precompute.argtypes = [ctypes.c_char_p,
                                            ctypes.c_char_p]
        lib.b_miller_precompute.restype = ctypes.c_int
        lib.b_multi_pairing_is_one_prepared.argtypes = [
            ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p]
        lib.b_multi_pairing_is_one_prepared.restype = ctypes.c_int
        lib.b_g1_aggregate.argtypes = [
            ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p]
        lib.b_g1_aggregate.restype = ctypes.c_int
        lib.b_g1_aggregate_affine.argtypes = [
            ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p]
        _lib = lib
    return _lib


def available() -> bool:
    global _build_error
    try:
        _get_lib()
        return True
    except (OSError, AttributeError, ValueError,
            subprocess.SubprocessError) as e:
        # the compile/dlopen/symbol-binding failure surface, narrowed
        # (PT006): cc missing/failing (SubprocessError, FileNotFound),
        # bad .so (OSError), stale lib missing a symbol (AttributeError)
        if _build_error is None:
            logger.debug("native BLS backend unavailable: %s", e)
        _build_error = e
        return False


def build_error() -> Optional[Exception]:
    """Why available() last returned False (None if it never failed)."""
    return _build_error


# ------------------------------------------------------- conversions

def _g1_bytes(p: G1Point) -> bytes:
    if p is None:
        return b"\x00" * 96
    return p[0].to_bytes(48, "big") + p[1].to_bytes(48, "big")

def _g1_from(b: bytes) -> G1Point:
    if not any(b):
        return None
    return (int.from_bytes(b[:48], "big"), int.from_bytes(b[48:], "big"))

def _g2_bytes(p: G2Point) -> bytes:
    if p is None:
        return b"\x00" * 192
    x, y = p
    return (x.c0.to_bytes(48, "big") + x.c1.to_bytes(48, "big")
            + y.c0.to_bytes(48, "big") + y.c1.to_bytes(48, "big"))

def _g2_from(b: bytes) -> G2Point:
    if not any(b):
        return None
    return (Fq2(int.from_bytes(b[:48], "big"),
                int.from_bytes(b[48:96], "big")),
            Fq2(int.from_bytes(b[96:144], "big"),
                int.from_bytes(b[144:], "big")))


# --------------------------------------------------------------- ops

def g1_add(p: G1Point, q: G1Point) -> G1Point:
    out = ctypes.create_string_buffer(96)
    _get_lib().b_g1_add(_g1_bytes(p), _g1_bytes(q), out)
    return _g1_from(out.raw)


def g1_mul(p: G1Point, k: int) -> G1Point:
    out = ctypes.create_string_buffer(96)
    _get_lib().b_g1_mul(_g1_bytes(p), (k % R).to_bytes(32, "big"),
                        out)
    return _g1_from(out.raw)


def g2_add(p: G2Point, q: G2Point) -> G2Point:
    out = ctypes.create_string_buffer(192)
    _get_lib().b_g2_add(_g2_bytes(p), _g2_bytes(q), out)
    return _g2_from(out.raw)


def g2_mul(p: G2Point, k: int) -> G2Point:
    out = ctypes.create_string_buffer(192)
    _get_lib().b_g2_mul(_g2_bytes(p), (k % R).to_bytes(32, "big"),
                        out)
    return _g2_from(out.raw)


def g1_aggregate_compressed(sigs: Sequence[bytes]) -> G1Point:
    """Sum of n compressed signatures in ONE call: per-share decompress
    + Jacobian mixed add, a single field inversion at the end (vs one
    inversion per share through repeated g1_add). Raises ValueError on
    any undecodable share, mirroring g1_decompress."""
    n = len(sigs)
    out = ctypes.create_string_buffer(96)
    rc = _get_lib().b_g1_aggregate(n, b"".join(sigs), out)
    if rc != 0:
        raise ValueError("invalid G1 signature in aggregate")
    return _g1_from(out.raw)


def g1_aggregate_points(points: Sequence[G1Point]) -> G1Point:
    """Sum of already-decompressed affine points in ONE call (Jacobian
    accumulation + single inversion). The ordering path uses this with
    the verifier's share-point cache: decompression was paid once at
    COMMIT validation."""
    out = ctypes.create_string_buffer(96)
    _get_lib().b_g1_aggregate_affine(
        len(points), b"".join(_g1_bytes(p) for p in points), out)
    return _g1_from(out.raw)


def multi_pairing_is_one(pairs: Sequence[Tuple[G1Point, G2Point]]) -> bool:
    n = len(pairs)
    g1s = b"".join(_g1_bytes(p) for p, _ in pairs)
    g2s = b"".join(_g2_bytes(q) for _, q in pairs)
    return bool(_get_lib().b_multi_pairing_is_one(n, g1s, g2s))


def hash_to_g1(msg: bytes, dst: bytes = b"PLENUM_TPU_BLS_G1") -> G1Point:
    """Full-native try-and-increment hash-to-curve — bit-identical to
    bls12_381.hash_to_g1 (cross-checked in tests)."""
    out = ctypes.create_string_buffer(96)
    rc = _get_lib().b_hash_to_g1(bytes(msg), len(msg), bytes(dst),
                                 len(dst), out)
    if rc != 0:
        raise ValueError("hash_to_g1 failed")
    return _g1_from(out.raw)


def miller_precompute(q: G2Point) -> bytes:
    """Per-step Miller line coefficients for a FIXED G2 argument —
    opaque blob consumed by multi_pairing_is_one_prepared. A validator
    pairs against the same G2 points on every verify (the generator and
    the pool's aggregated key), so the Q-only half of the Miller loop
    is hoisted out of the per-verify path."""
    lib = _get_lib()
    out = ctypes.create_string_buffer(lib.b_prep_size())
    rc = lib.b_miller_precompute(_g2_bytes(q), out)
    if rc != 0:
        raise ValueError("cannot precompute lines for this G2 point")
    return out.raw


def multi_pairing_is_one_prepared(
        pairs: Sequence[Tuple[G1Point, bytes]]) -> bool:
    """Πᵢ e(Pᵢ, Qᵢ) == 1 with every Qᵢ given as a miller_precompute
    blob. ONE shared fp12 squaring chain for all pairs."""
    n = len(pairs)
    if not 1 <= n <= 8:
        # the C fast path sizes its stack for the verification shapes
        # (2 pairs); outside it, callers must use the plain path
        raise ValueError("prepared multi-pairing supports 1..8 pairs")
    g1s = b"".join(_g1_bytes(p) for p, _ in pairs)
    preps = b"".join(prep for _, prep in pairs)
    return bool(_get_lib().b_multi_pairing_is_one_prepared(n, g1s, preps))


def g1_decompress(data: bytes) -> G1Point:
    if len(data) != 48:
        raise ValueError("bad G1 length")
    out = ctypes.create_string_buffer(96)
    rc = _get_lib().b_g1_decompress(bytes(data), out)
    if rc < 0:
        raise ValueError("invalid compressed G1 point")
    if rc == 1:
        return None
    return _g1_from(out.raw)


def pairing_bytes(p: G1Point, q: G2Point) -> bytes:
    """Final-exponentiated pairing (cube-power convention) — testing."""
    out = ctypes.create_string_buffer(576)
    _get_lib().b_pairing(_g1_bytes(p), _g2_bytes(q), out)
    return out.raw
