"""BLS12-381 pairing-friendly curve — scalar Python implementation.

The reference delegates BLS multi-signatures to Hyperledger Ursa (Rust,
`crypto/bls/indy_crypto/bls_crypto_indy_crypto.py`, SURVEY.md §2.9). This
module is a from-scratch implementation of the curve arithmetic and the
optimal ate pairing, used by plenum_tpu.crypto.bls for state-proof
multi-signatures. It is the correctness/scalar path. The hot paths live
elsewhere: native/bls12_381.c (pairings, scalar mults, batch
aggregation) and ops/bls381_jax.py (the TPU kernel batching decompress +
G1 tree-aggregation over many share-sets per device dispatch); pairings
stay on the host — there are only 2 per verify regardless of signer
count.

Scheme layout: signatures in G1 (48 B compressed), public keys in G2
(96 B compressed) — minimal-signature-size variant.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

# ------------------------------------------------------------ parameters

# Field modulus
Q = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
# Subgroup order
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# BLS parameter x (negative: x = -0xd201000000010000)
X_ABS = 0xD201000000010000

G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1

G2_X = (0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E)
G2_Y = (0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE)


# ------------------------------------------------------------ Fq towers

def _inv(a: int, m: int) -> int:
    return pow(a, m - 2, m)


class Fq2:
    """Fq[u] / (u^2 + 1)."""
    __slots__ = ("c0", "c1")

    def __init__(self, c0: int, c1: int):
        self.c0 = c0 % Q
        self.c1 = c1 % Q

    def __add__(self, o):
        return Fq2(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o):
        return Fq2(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self):
        return Fq2(-self.c0, -self.c1)

    def __mul__(self, o):
        if isinstance(o, int):
            return Fq2(self.c0 * o, self.c1 * o)
        a, b, c, d = self.c0, self.c1, o.c0, o.c1
        ac, bd = a * c, b * d
        return Fq2(ac - bd, (a + b) * (c + d) - ac - bd)

    __rmul__ = __mul__

    def __eq__(self, o):
        return self.c0 == o.c0 and self.c1 == o.c1

    def sq(self):
        a, b = self.c0, self.c1
        return Fq2((a + b) * (a - b), 2 * a * b)

    def inv(self):
        norm = _inv(self.c0 * self.c0 + self.c1 * self.c1, Q)
        return Fq2(self.c0 * norm, -self.c1 * norm)

    def conj(self):
        return Fq2(self.c0, -self.c1)

    def mul_by_nonresidue(self):
        # ξ = 1 + u
        return Fq2(self.c0 - self.c1, self.c0 + self.c1)

    def is_zero(self):
        return self.c0 == 0 and self.c1 == 0

    def sqrt(self) -> Optional["Fq2"]:
        """Square root in Fq2 (q ≡ 3 mod 4 variant algorithm)."""
        if self.is_zero():
            return Fq2(0, 0)
        a1 = self ** ((Q - 3) // 4)
        alpha = a1.sq() * self
        x0 = a1 * self
        if alpha == Fq2(Q - 1, 0):
            return Fq2(-x0.c1, x0.c0)
        b = (alpha + Fq2(1, 0)) ** ((Q - 1) // 2)
        cand = b * x0
        if cand.sq() == self:
            return cand
        return None

    def __pow__(self, e: int):
        result = Fq2(1, 0)
        base = self
        while e > 0:
            if e & 1:
                result = result * base
            base = base.sq()
            e >>= 1
        return result

    def __repr__(self):
        return f"Fq2({hex(self.c0)}, {hex(self.c1)})"


FQ2_ONE = Fq2(1, 0)
FQ2_ZERO = Fq2(0, 0)


class Fq6:
    """Fq2[v] / (v^3 - ξ), ξ = 1+u."""
    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0: Fq2, c1: Fq2, c2: Fq2):
        self.c0, self.c1, self.c2 = c0, c1, c2

    def __add__(self, o):
        return Fq6(self.c0 + o.c0, self.c1 + o.c1, self.c2 + o.c2)

    def __sub__(self, o):
        return Fq6(self.c0 - o.c0, self.c1 - o.c1, self.c2 - o.c2)

    def __neg__(self):
        return Fq6(-self.c0, -self.c1, -self.c2)

    def __mul__(self, o):
        a0, a1, a2 = self.c0, self.c1, self.c2
        b0, b1, b2 = o.c0, o.c1, o.c2
        t0, t1, t2 = a0 * b0, a1 * b1, a2 * b2
        c0 = ((a1 + a2) * (b1 + b2) - t1 - t2).mul_by_nonresidue() + t0
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1 + t2.mul_by_nonresidue()
        c2 = (a0 + a2) * (b0 + b2) - t0 - t2 + t1
        return Fq6(c0, c1, c2)

    def __eq__(self, o):
        return self.c0 == o.c0 and self.c1 == o.c1 and self.c2 == o.c2

    def sq(self):
        return self * self

    def mul_by_nonresidue(self):
        return Fq6(self.c2.mul_by_nonresidue(), self.c0, self.c1)

    def inv(self):
        a0, a1, a2 = self.c0, self.c1, self.c2
        t0 = a0.sq() - (a1 * a2).mul_by_nonresidue()
        t1 = a2.sq().mul_by_nonresidue() - a0 * a1
        t2 = a1.sq() - a0 * a2
        denom = (a0 * t0 + (a2 * t1 + a1 * t2).mul_by_nonresidue()).inv()
        return Fq6(t0 * denom, t1 * denom, t2 * denom)

    def is_zero(self):
        return self.c0.is_zero() and self.c1.is_zero() and self.c2.is_zero()


FQ6_ONE = Fq6(FQ2_ONE, FQ2_ZERO, FQ2_ZERO)
FQ6_ZERO = Fq6(FQ2_ZERO, FQ2_ZERO, FQ2_ZERO)


class Fq12:
    """Fq6[w] / (w^2 - v)."""
    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fq6, c1: Fq6):
        self.c0, self.c1 = c0, c1

    def __add__(self, o):
        return Fq12(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o):
        return Fq12(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self):
        return Fq12(-self.c0, -self.c1)

    def __mul__(self, o):
        a0, a1, b0, b1 = self.c0, self.c1, o.c0, o.c1
        t0, t1 = a0 * b0, a1 * b1
        return Fq12(t0 + t1.mul_by_nonresidue(),
                    (a0 + a1) * (b0 + b1) - t0 - t1)

    def sq(self):
        return self * self

    def __eq__(self, o):
        return self.c0 == o.c0 and self.c1 == o.c1

    def inv(self):
        t = (self.c0.sq() - self.c1.sq().mul_by_nonresidue()).inv()
        return Fq12(self.c0 * t, -(self.c1 * t))

    def conj(self):
        """x → x^(q^6) (Fq6 coefficients are fixed by Frobenius^6)."""
        return Fq12(self.c0, -self.c1)

    def is_zero(self):
        return self.c0.is_zero() and self.c1.is_zero()

    def __pow__(self, e: int):
        if e < 0:
            return self.inv() ** (-e)
        result = FQ12_ONE
        base = self
        while e > 0:
            if e & 1:
                result = result * base
            base = base.sq()
            e >>= 1
        return result


FQ12_ONE = Fq12(FQ6_ONE, FQ6_ZERO)
FQ12_ZERO = Fq12(FQ6_ZERO, FQ6_ZERO)


def _fq12_from_fq2(a: Fq2) -> Fq12:
    return Fq12(Fq6(a, FQ2_ZERO, FQ2_ZERO), FQ6_ZERO)


def _fq12_from_int(n: int) -> Fq12:
    return _fq12_from_fq2(Fq2(n, 0))


# ------------------------------------------------------------ groups

# Affine points as tuples (x, y) with None = infinity.
G1Point = Optional[Tuple[int, int]]
G2Point = Optional[Tuple[Fq2, Fq2]]


def g1_add(p: G1Point, q: G1Point) -> G1Point:
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    if x1 == x2:
        if (y1 + y2) % Q == 0:
            return None
        lam = 3 * x1 * x1 * _inv(2 * y1, Q) % Q
    else:
        lam = (y2 - y1) * _inv(x2 - x1, Q) % Q
    x3 = (lam * lam - x1 - x2) % Q
    return (x3, (lam * (x1 - x3) - y1) % Q)


def g1_neg(p: G1Point) -> G1Point:
    return None if p is None else (p[0], (-p[1]) % Q)


def g1_mul(p: G1Point, k: int) -> G1Point:
    k %= R
    acc = None
    while k:
        if k & 1:
            acc = g1_add(acc, p)
        p = g1_add(p, p)
        k >>= 1
    return acc


def g2_add(p: G2Point, q: G2Point) -> G2Point:
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    if x1 == x2:
        if (y1 + y2).is_zero():
            return None
        lam = (x1.sq() * 3) * (y1 * 2).inv()
    else:
        lam = (y2 - y1) * (x2 - x1).inv()
    x3 = lam.sq() - x1 - x2
    return (x3, lam * (x1 - x3) - y1)


def g2_neg(p: G2Point) -> G2Point:
    return None if p is None else (p[0], -p[1])


def g2_mul(p: G2Point, k: int) -> G2Point:
    k %= R
    acc = None
    while k:
        if k & 1:
            acc = g2_add(acc, p)
        p = g2_add(p, p)
        k >>= 1
    return acc


G1_GEN: G1Point = (G1_X, G1_Y)
G2_GEN: G2Point = (Fq2(*G2_X), Fq2(*G2_Y))


def g1_is_on_curve(p: G1Point) -> bool:
    if p is None:
        return True
    x, y = p
    return (y * y - x * x * x - 4) % Q == 0


def g2_is_on_curve(p: G2Point) -> bool:
    if p is None:
        return True
    x, y = p
    # y^2 = x^3 + 4(1+u)
    return y.sq() == x.sq() * x + Fq2(4, 4)


def g1_in_subgroup(p: G1Point, g1_mul_fn=None) -> bool:
    """On-curve + r-torsion. g1_mul reduces scalars mod r, so mul-by-r
    cannot be used directly (it would be vacuously None); instead check
    (r−1)·p == −p ⇔ r·p = O ⇔ ord(p) | r (r prime).

    g1_mul_fn lets a faster backend (bls_ops) supply the scalar mult
    while keeping this single implementation of the security check."""
    if p is None:
        return True
    mul = g1_mul_fn or g1_mul
    return g1_is_on_curve(p) and mul(p, R - 1) == g1_neg(p)


def g2_in_subgroup(p: G2Point, g2_mul_fn=None) -> bool:
    if p is None:
        return True
    mul = g2_mul_fn or g2_mul
    return g2_is_on_curve(p) and mul(p, R - 1) == g2_neg(p)


# ------------------------------------------------------------ pairing

# w and the untwisting constants: BLS12-381 uses the M-twist
# E': y² = x³ + 4ξ (ξ = 1+u), with Ψ(x', y') = (x'/w², y'/w³) ∈ E(Fq12).
_W = Fq12(FQ6_ZERO, FQ6_ONE)
_W2_INV = (_W * _W).inv()
_W3_INV = (_W * _W * _W).inv()


def _untwist(q: G2Point) -> Tuple[Fq12, Fq12]:
    x, y = q
    return (_fq12_from_fq2(x) * _W2_INV, _fq12_from_fq2(y) * _W3_INV)


def miller_loop(p: G1Point, q: G2Point) -> Fq12:
    """Generic affine Miller loop over E(Fq12) — correctness-first: the
    twist point is untwisted once and all slopes/lines live in Fq12."""
    if p is None or q is None:
        return FQ12_ONE
    xa = _fq12_from_int(p[0])
    ya = _fq12_from_int(p[1])
    qx, qy = _untwist(q)
    tx, ty = qx, qy
    f = FQ12_ONE
    bits = bin(X_ABS)[2:]
    for b in bits[1:]:
        # doubling step: tangent at T, evaluated at P
        lam = (tx.sq() * _fq12_from_int(3)) * (ty * _fq12_from_int(2)).inv()
        line = (ya - ty) - lam * (xa - tx)
        f = f.sq() * line
        x3 = lam.sq() - tx - tx
        ty = lam * (tx - x3) - ty
        tx = x3
        if b == "1":
            # addition step: chord through T and Q, evaluated at P
            lam = (ty - qy) * (tx - qx).inv()
            line = (ya - ty) - lam * (xa - tx)
            f = f * line
            x3 = lam.sq() - tx - qx
            ty = lam * (tx - x3) - ty
            tx = x3
    # the BLS parameter x is negative: conjugate the result
    return f.conj()


def final_exponentiation(f: Fq12) -> Fq12:
    """f^((q^12-1)/r) by plain square-and-multiply (correctness-first;
    there are only 2 pairings per multi-sig verify regardless of n)."""
    return f ** ((Q ** 12 - 1) // R)


def pairing(p: G1Point, q: G2Point) -> Fq12:
    return final_exponentiation(miller_loop(p, q))


def multi_pairing(pairs: Sequence[Tuple[G1Point, G2Point]]) -> Fq12:
    """∏ e(p_i, q_i) with one shared final exponentiation."""
    f = FQ12_ONE
    for p, q in pairs:
        f = f * miller_loop(p, q)
    return final_exponentiation(f)


# ------------------------------------------------------------ serialization
# ZCash-style compressed encodings: 48 B (G1) / 96 B (G2), flag bits in
# the top three bits of the first byte.

def g1_compress(p: G1Point) -> bytes:
    if p is None:
        return bytes([0xC0] + [0] * 47)
    x, y = p
    flag = 0x80 | (0x20 if y > (Q - 1) // 2 else 0)
    b = bytearray(x.to_bytes(48, "big"))
    b[0] |= flag
    return bytes(b)


def g1_decompress(data: bytes) -> G1Point:
    if len(data) != 48:
        raise ValueError("bad G1 length")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed unsupported")
    if flags & 0x40:
        if any(data[1:]) or data[0] != 0xC0:
            raise ValueError("bad infinity encoding")
        return None
    x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if x >= Q:
        raise ValueError("x out of range")
    yy = (x * x * x + 4) % Q
    y = pow(yy, (Q + 1) // 4, Q)
    if y * y % Q != yy:
        raise ValueError("not on curve")
    big = y > (Q - 1) // 2
    if bool(flags & 0x20) != big:
        y = Q - y
    return (x, y)


def g2_compress(p: G2Point) -> bytes:
    if p is None:
        return bytes([0xC0] + [0] * 95)
    x, y = p
    # sign bit: y lexicographically greater than −y, comparing (c1, c0)
    big = (y.c1, y.c0) > ((Q - y.c1) % Q, (Q - y.c0) % Q)
    flag = 0x80 | (0x20 if big else 0)
    b = bytearray(x.c1.to_bytes(48, "big") + x.c0.to_bytes(48, "big"))
    b[0] |= flag
    return bytes(b)


def g2_decompress(data: bytes) -> G2Point:
    if len(data) != 96:
        raise ValueError("bad G2 length")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed unsupported")
    if flags & 0x40:
        if any(data[1:]) or data[0] != 0xC0:
            raise ValueError("bad infinity encoding")
        return None
    c1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    c0 = int.from_bytes(data[48:96], "big")
    if c0 >= Q or c1 >= Q:
        raise ValueError("x out of range")
    x = Fq2(c0, c1)
    yy = x.sq() * x + Fq2(4, 4)
    y = yy.sqrt()
    if y is None:
        raise ValueError("not on curve")
    big = (y.c1, y.c0) > ((Q - y.c1) % Q, (Q - y.c0) % Q)
    if bool(flags & 0x20) != big:
        y = -y
    return (x, y)


def hash_to_g1(msg: bytes, dst: bytes = b"PLENUM_TPU_BLS_G1",
               g1_mul_fn=None) -> G1Point:
    """Deterministic hash-to-curve by try-and-increment over SHA-256.

    Not the IRTF SSWU suite — this framework defines its own wire format
    (no Ursa compatibility requirement); try-and-increment is simple,
    deterministic, and its variable-time nature leaks nothing secret
    (inputs are public consensus data).

    ``g1_mul_fn`` lets the backend dispatch (bls_ops) run the cofactor
    clearing on the native path — ONE construction, consensus-critical:
    every node must hash to the identical point.
    """
    import hashlib as _h
    mul = g1_mul_fn or g1_mul
    ctr = 0
    while True:
        d = _h.sha256(dst + ctr.to_bytes(4, "big") + msg).digest()
        x = int.from_bytes(d + _h.sha256(b"\x01" + d).digest()[:16], "big") % Q
        yy = (x * x * x + 4) % Q
        y = pow(yy, (Q + 1) // 4, Q)
        if y * y % Q == yy:
            # clear cofactor to land in the r-torsion subgroup
            h = ((1 - (-X_ABS)) ** 2) // 3  # G1 cofactor (x-1)^2/3
            p = mul((x, min(y, Q - y)), h)
            if p is not None:
                return p
        ctr += 1
