"""Crypto layer: ed25519 signing/verify, BLS12-381 multi-signatures.

Mirrors the reference's pluggable seams (SURVEY.md §2.7):
`stp_core/crypto/signer.py:9` (Signer), `crypto/bls/bls_crypto.py:15,32`
(BlsCryptoSigner/Verifier). Scalar paths are pure Python; bulk verification
routes to the JAX kernels in plenum_tpu.ops.
"""
