"""BLS multi-signatures over BLS12-381 — the state-proof seam.

Mirrors the reference's pluggable BLS abstractions (SURVEY.md §2.7):
`crypto/bls/bls_crypto.py:15,32` (BlsCryptoSigner / BlsCryptoVerifier) and
`crypto/bls/bls_multi_signature.py:70` (MultiSignature value object). The
concrete backend is our from-scratch BLS12-381 (bls12_381.py) instead of
Ursa: signatures in G1 (48 B), public keys in G2 (96 B), aggregation by
plain point addition, one 2-pairing check per multi-sig verify.

Proof-of-possession guards against rogue-key attacks: a key share ships
with a signature over its own compressed public key under a distinct
domain separation tag.
"""
from __future__ import annotations

import base64
import hashlib
from abc import ABC, abstractmethod
from typing import List, Optional, Sequence

from . import bls_ops as bls

_DST_SIG = b"PLENUM_TPU_BLS_SIG"
_DST_POP = b"PLENUM_TPU_BLS_POP"


def b58_encode(data: bytes) -> str:
    """Public codec for the b58 wire form of sigs/keys (tooling and
    benches use these instead of reaching into module privates)."""
    from plenum_tpu.common.serializers.base58 import b58encode
    return b58encode(data)


def b58_decode(s: str) -> bytes:
    """Inverse of ``b58_encode``; raises ValueError on bad input."""
    from plenum_tpu.common.serializers.base58 import b58decode
    return b58decode(s)


# historic internal names, kept for in-module brevity
_b58 = b58_encode
_unb58 = b58_decode


class BlsCryptoVerifier(ABC):
    """Reference seam: crypto/bls/bls_crypto.py:15."""

    @abstractmethod
    def verify_sig(self, signature: str, message: bytes, pk: str) -> bool: ...

    @abstractmethod
    def verify_multi_sig(self, signature: str, message: bytes,
                         pks: Sequence[str]) -> bool: ...

    @abstractmethod
    def create_multi_sig(self, signatures: Sequence[str]) -> str: ...

    @abstractmethod
    def verify_key_proof_of_possession(self, key_proof: str, pk: str) -> bool: ...

    # batch seams with scalar-loop defaults: callers (consensus share
    # unroll, client proof batches) call these unconditionally; backends
    # that can amortize (one device pairing launch per batch) override
    def verify_sigs_batch(
            self, checks: Sequence[tuple]) -> List[bool]:
        """checks: (signature, message, pk) triples → per-item verdicts
        identical to mapping ``verify_sig``."""
        return [self.verify_sig(s, m, pk) for (s, m, pk) in checks]

    def verify_multi_sigs_batch(
            self, checks: Sequence[tuple]) -> List[bool]:
        """checks: (signature, message, pks) triples → per-item verdicts
        identical to mapping ``verify_multi_sig``."""
        return [self.verify_multi_sig(s, m, pks) for (s, m, pks) in checks]


class BlsCryptoSigner(ABC):
    """Reference seam: crypto/bls/bls_crypto.py:32."""

    @abstractmethod
    def sign(self, message: bytes) -> str: ...

    @property
    @abstractmethod
    def pk(self) -> str: ...


def generate_bls_keys(seed: Optional[bytes] = None):
    """→ (sk_int, pk_str, key_proof_str)."""
    if seed is None:
        import os
        seed = os.urandom(32)
    sk = int.from_bytes(hashlib.sha512(b"PLENUM_TPU_BLS_KEYGEN" + seed)
                        .digest(), "big") % bls.R
    if sk == 0:
        sk = 1
    pk_point = bls.g2_mul(bls.G2_GEN, sk)
    pk_bytes = bls.g2_compress(pk_point)
    pop_point = bls.g1_mul(bls.hash_to_g1(pk_bytes, _DST_POP), sk)
    return sk, _b58(pk_bytes), _b58(bls.g1_compress(pop_point))


class BlsCryptoSignerPlenum(BlsCryptoSigner):
    def __init__(self, sk: int, pk: str):
        self._sk = sk
        self._pk = pk

    @classmethod
    def generate(cls, seed: Optional[bytes] = None):
        sk, pk, proof = generate_bls_keys(seed)
        return cls(sk, pk), proof

    @property
    def pk(self) -> str:
        return self._pk

    def sign(self, message: bytes) -> str:
        h = bls.hash_to_g1(message, _DST_SIG)
        return _b58(bls.g1_compress(bls.g1_mul(h, self._sk)))


class BlsCryptoVerifierPlenum(BlsCryptoVerifier):
    """Validator public keys are static pool state — decompression,
    subgroup membership and the aggregate key are cached per key-set
    (the reference's ursa keys are likewise deserialized once)."""

    # Miller-line blob for the FIXED -G2 generator argument of every
    # verification (shared by all instances; computed once per process)
    _neg_g2_prep = None

    def __init__(self):
        self._pk_cache = {}        # b58 pk -> (point, in_subgroup)
        self._agg_cache = {}       # tuple(pks) -> aggregate point | None
        # b58 sig -> decompressed G1 point: every share was already
        # decompressed once in validate_commit's verify_sig; ordering
        # must not pay the ~50 us sqrt per share a second time
        self._sig_point_cache = {}
        # G2 point (by id of cached object) -> prepared Miller lines:
        # a validator re-verifies against the same pool key-set every
        # batch, so the Q-only pairing work is paid once per set
        self._prep_cache = {}

    def _prepared(self, key, point):
        """Miller-precompute blob for a cached G2 point (None when the
        backend lacks prepared pairings)."""
        if bls.miller_precompute is None:
            return None
        blob = self._prep_cache.get(key)
        if blob is None:
            try:
                blob = bls.miller_precompute(point)
            except ValueError:
                return None
            if len(self._prep_cache) > 1024:
                self._prep_cache.clear()
            self._prep_cache[key] = blob
        return blob

    def _pairing_is_one(self, sig_point, h_point, q_key, q_point) -> bool:
        """e(sig, -G2)·e(H(m), Q) == 1, through the prepared path when
        the native backend offers it."""
        if bls.multi_pairing_is_one_prepared is not None:
            cls = BlsCryptoVerifierPlenum
            if cls._neg_g2_prep is None and bls.miller_precompute:
                cls._neg_g2_prep = bls.miller_precompute(
                    bls.g2_neg(bls.G2_GEN))
            q_prep = self._prepared(q_key, q_point)
            if cls._neg_g2_prep is not None and q_prep is not None:
                return bls.multi_pairing_is_one_prepared(
                    [(sig_point, cls._neg_g2_prep), (h_point, q_prep)])
        return bls.multi_pairing_is_one(
            [(sig_point, bls.g2_neg(bls.G2_GEN)), (h_point, q_point)])

    def _g1(self, s: str):
        return bls.g1_decompress(_unb58(s))

    def _g2(self, s: str):
        return bls.g2_decompress(_unb58(s))

    def _pk_point(self, pk: str):
        """→ (point, valid) with caching; valid ⇒ on-curve + subgroup."""
        hit = self._pk_cache.get(pk)
        if hit is not None:
            return hit
        try:
            p = self._g2(pk)
            valid = p is not None and bls.g2_in_subgroup(p)
        except (ValueError, KeyError):
            p, valid = None, False
        if len(self._pk_cache) > 4096:
            self._pk_cache.clear()
        self._pk_cache[pk] = (p, valid)
        return p, valid

    def warm_keys(self, pks: Sequence[str]) -> None:
        """Precompute every key-dependent cost for a pool key-set at
        catchup/membership-change time instead of at first verify: G2
        decompression + subgroup check per key, the aggregate key of
        the full set, and its prepared Miller lines (plus the fixed
        -G2 preparation). The per-key subgroup checks (~3.5 ms each —
        the bulk of the lazy cold cost) are warmed for EVERY later
        key-subset; the aggregate key + Miller lines are warmed for the
        full set, so a verify against a fresh n-f participant subset
        still lazily pays that subset's aggregation (microseconds
        native) + one Miller precompute (~0.2 ms). The reference pays
        the equivalent at key-deserialization time (ursa
        VerKey::from_bytes, bls_crypto_indy_crypto.py:84)."""
        cls = BlsCryptoVerifierPlenum
        if cls._neg_g2_prep is None and bls.miller_precompute is not None:
            cls._neg_g2_prep = bls.miller_precompute(bls.g2_neg(bls.G2_GEN))
        for pk in pks:
            self._pk_point(pk)
        key = tuple(pks)
        agg = self._aggregate_pks(key)
        if agg is not None:
            self._prepared(key, agg)

    def _aggregate_pks(self, pks: Sequence[str]):
        key = tuple(pks)
        if key in self._agg_cache:
            return self._agg_cache[key]
        agg = None
        for pk in pks:
            p, valid = self._pk_point(pk)
            if not valid:
                agg = None
                break
            agg = bls.g2_add(agg, p)
        if len(self._agg_cache) > 1024:
            self._agg_cache.clear()
        self._agg_cache[key] = agg
        return agg

    def _sig_cached(self, signature: str):
        """Decompressed share point, memoized (ordering re-reads every
        share create_multi_sig-side; never pay the sqrt twice). May
        raise ValueError/KeyError on undecodable input."""
        sig = self._sig_point_cache.get(signature)
        if sig is None:
            sig = self._g1(signature)
            if len(self._sig_point_cache) > 8192:
                self._sig_point_cache.clear()
            self._sig_point_cache[signature] = sig
        return sig

    def verify_sig(self, signature: str, message: bytes, pk: str) -> bool:
        try:
            sig = self._sig_cached(signature)
        except (ValueError, KeyError):
            return False
        pub, valid = self._pk_point(pk)
        if sig is None or not valid:
            return False
        if not bls.g1_in_subgroup(sig):
            return False
        h = bls.hash_to_g1(message, _DST_SIG)
        # e(sig, G2) == e(H(m), pk)  ⇔  e(sig, -G2)·e(H(m), pk) == 1
        return self._pairing_is_one(sig, h, pk, pub)

    def verify_multi_sig(self, signature: str, message: bytes,
                         pks: Sequence[str]) -> bool:
        if not pks:
            return False
        key = tuple(pks)
        agg_pk = self._aggregate_pks(key)
        try:
            sig = self._g1(signature)
        except (ValueError, KeyError):
            return False
        if sig is None or agg_pk is None:
            return False
        if not bls.g1_in_subgroup(sig):
            return False
        h = bls.hash_to_g1(message, _DST_SIG)
        return self._pairing_is_one(sig, h, key, agg_pk)

    # ------------------------------------------------------ batch verify
    # One device pairing launch per batch (ops/bls381_pairing via
    # bls_ops.multi_pairing_is_one_jobs) when the batch clears
    # Config.BLS_PAIRING_DEVICE_MIN; below it the scalar path with its
    # prepared Miller lines wins. Every host-side pre-check (decode,
    # subgroup, key validity) runs EXACTLY as in the scalar methods, so
    # batch and scalar verdicts agree item-for-item — only the pairing
    # product itself moves to the device.

    _neg_g2_c = None     # compressed -G2: fixed first pair of every job

    @classmethod
    def _neg_g2_bytes(cls) -> bytes:
        if cls._neg_g2_c is None:
            cls._neg_g2_c = bls.g2_compress(bls.g2_neg(bls.G2_GEN))
        return cls._neg_g2_c

    def _job_pairs(self, signature: str, message: bytes, pub):
        """The 2-pair job e(sig,-G2)·e(H(m),pub) in compressed bytes;
        pre-checks already passed, so both pairs decode live on device."""
        h = bls.hash_to_g1(message, _DST_SIG)
        return [(b58_decode(signature), self._neg_g2_bytes()),
                (bls.g1_compress(h), bls.g2_compress(pub))]

    def _job_single(self, signature: str, message: bytes, pk: str):
        """verify_sig's pre-checks → job, or None for an immediate
        False verdict (mirrors the scalar early-outs line for line)."""
        try:
            sig = self._sig_cached(signature)
        except (ValueError, KeyError):
            return None
        pub, valid = self._pk_point(pk)
        if sig is None or not valid or not bls.g1_in_subgroup(sig):
            return None
        return self._job_pairs(signature, message, pub)

    def _job_multi(self, signature: str, message: bytes, pks):
        if not pks:
            return None
        agg_pk = self._aggregate_pks(tuple(pks))
        try:
            sig = self._g1(signature)
        except (ValueError, KeyError):
            return None
        if sig is None or agg_pk is None or not bls.g1_in_subgroup(sig):
            return None
        return self._job_pairs(signature, message, agg_pk)

    def _verify_batch(self, checks, job_of):
        results = [False] * len(checks)
        jobs, live = [], []
        for i, check in enumerate(checks):
            job = job_of(*check)
            if job is not None:
                jobs.append(job)
                live.append(i)
        for i, ok in zip(live, bls.multi_pairing_is_one_jobs(jobs)):
            results[i] = bool(ok)
        return results

    def verify_sigs_batch(self, checks) -> List[bool]:
        if not bls.pairing_device_ready(len(checks)):
            return [self.verify_sig(s, m, pk) for (s, m, pk) in checks]
        return self._verify_batch(checks, self._job_single)

    def verify_multi_sigs_batch(self, checks) -> List[bool]:
        if not bls.pairing_device_ready(len(checks)):
            return [self.verify_multi_sig(s, m, pks)
                    for (s, m, pks) in checks]
        return self._verify_batch(checks, self._job_multi)

    def create_multi_sig(self, signatures: Sequence[str]) -> str:
        """One backend call for the whole share-set: Jacobian
        accumulation with a single final inversion, instead of an affine
        add — and its field inversion — per share. Shares this verifier
        already pairing-checked (validate_commit path) aggregate from
        their CACHED decompressed points, skipping the per-share sqrt
        entirely — on the ordering money path aggregation is then pure
        point addition."""
        # NOTE: a cache VALUE of None is legitimate (the infinity
        # encoding decompresses to None), so membership — not just
        # get() — distinguishes a miss
        cache = self._sig_point_cache
        pts = []
        misses = []
        for s in signatures:
            p = cache.get(s)
            if p is None and s not in cache:
                misses.append(s)
            pts.append(p)
        if len(misses) == len(signatures):
            # fully cold (no shares seen): one batched native call
            agg = bls.g1_aggregate_compressed(
                [_unb58(s) for s in signatures])
            return _b58(bls.g1_compress(agg))
        if misses:
            for i, s in enumerate(signatures):
                if pts[i] is None and s not in cache:
                    pts[i] = self._g1(s)
        agg = bls.g1_aggregate_points(pts)
        return _b58(bls.g1_compress(agg))

    def verify_key_proof_of_possession(self, key_proof: str, pk: str) -> bool:
        try:
            proof = self._g1(key_proof)
        except (ValueError, KeyError):
            return False
        pub, valid = self._pk_point(pk)
        if proof is None or not valid:
            return False
        if not bls.g1_in_subgroup(proof):
            return False
        pk_bytes = _unb58(pk)
        h = bls.hash_to_g1(pk_bytes, _DST_POP)
        return self._pairing_is_one(proof, h, pk, pub)


class MultiSignatureValue:
    """What gets BLS-signed on ordering: the batch's roots and 3PC info.
    Reference: crypto/bls/bls_multi_signature.py (MultiSignatureValue)."""

    def __init__(self, ledger_id: int, state_root_hash: str,
                 txn_root_hash: str, pool_state_root_hash: str,
                 timestamp: int):
        self.ledger_id = ledger_id
        self.state_root_hash = state_root_hash
        self.txn_root_hash = txn_root_hash
        self.pool_state_root_hash = pool_state_root_hash
        self.timestamp = timestamp

    def as_dict(self) -> dict:
        return {
            "ledger_id": self.ledger_id,
            "state_root_hash": self.state_root_hash,
            "txn_root_hash": self.txn_root_hash,
            "pool_state_root_hash": self.pool_state_root_hash,
            "timestamp": self.timestamp,
        }

    def as_single_value(self) -> bytes:
        items = sorted(self.as_dict().items())
        return b"|".join(f"{k}={v}".encode() for k, v in items)

    @classmethod
    def from_dict(cls, d: dict) -> "MultiSignatureValue":
        return cls(d["ledger_id"], d["state_root_hash"], d["txn_root_hash"],
                   d["pool_state_root_hash"], d["timestamp"])

    def __eq__(self, other):
        return isinstance(other, MultiSignatureValue) and \
            self.as_dict() == other.as_dict()


class MultiSignature:
    """Aggregated signature + participant names + signed value.
    Reference: crypto/bls/bls_multi_signature.py:70."""

    def __init__(self, signature: str, participants: List[str],
                 value: MultiSignatureValue):
        self.signature = signature
        self.participants = list(participants)
        self.value = value

    def as_dict(self) -> dict:
        return {"signature": self.signature,
                "participants": self.participants,
                "value": self.value.as_dict()}

    @classmethod
    def from_dict(cls, d: dict) -> "MultiSignature":
        return cls(d["signature"], d["participants"],
                   MultiSignatureValue.from_dict(d["value"]))

    def __eq__(self, other):
        return isinstance(other, MultiSignature) and \
            self.as_dict() == other.as_dict()
