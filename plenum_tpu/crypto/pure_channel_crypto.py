"""Pure-Python stand-ins for the `cryptography` primitives the
transport layer uses — X25519 (RFC 7748), ChaCha20-Poly1305 (RFC 8439),
HKDF-SHA256 (RFC 5869) and object-style Ed25519 keys over the existing
RFC 8032 implementation in crypto/ed25519.py.

`cryptography` (OpenSSL) is a soft dependency: images that ship it get
native speed; images without it (some accelerator containers) fall back
here with identical wire behavior. The API mirrors exactly the slice of
`cryptography.hazmat` that network/crypto_channel.py, network/keys.py
and network/stack.py consume, so those modules switch import source and
nothing else. Scalar Python speed is acceptable there: handshake
messages and consensus frames are small, and bulk client-signature
verification has its own batched device path (crypto/batch_verifier)."""
from __future__ import annotations

import hashlib
import hmac as _hmac
import os
from typing import Optional

from plenum_tpu.crypto import ed25519 as _ed

_P = 2 ** 255 - 19


class InvalidSignature(Exception):
    pass


# --------------------------------------------------------- Ed25519 objects


class _RawEncoding:
    Raw = "raw"


class _RawFormat:
    Raw = "raw"


class serialization:                          # namespace mirror
    Encoding = _RawEncoding
    PublicFormat = _RawFormat
    PrivateFormat = _RawFormat

    class NoEncryption:
        pass


class _SHA256:
    name = "sha256"
    digest_size = 32


class hashes:                                 # namespace mirror
    SHA256 = _SHA256


class Ed25519PublicKey:
    def __init__(self, raw: bytes):
        if len(raw) != 32:
            raise ValueError("ed25519 public key must be 32 bytes")
        self._raw = bytes(raw)

    @classmethod
    def from_public_bytes(cls, raw: bytes) -> "Ed25519PublicKey":
        return cls(raw)

    def public_bytes(self, encoding=None, fmt=None) -> bytes:
        return self._raw

    def verify(self, signature: bytes, data: bytes) -> None:
        if not _ed.verify(bytes(data), bytes(signature), self._raw):
            raise InvalidSignature("ed25519 signature invalid")


class Ed25519PrivateKey:
    def __init__(self, seed: bytes):
        if len(seed) != 32:
            raise ValueError("ed25519 private key must be 32 bytes")
        self._seed = bytes(seed)
        self._pub = _ed.publickey_from_seed(self._seed)

    @classmethod
    def from_private_bytes(cls, seed: bytes) -> "Ed25519PrivateKey":
        return cls(seed)

    @classmethod
    def generate(cls) -> "Ed25519PrivateKey":
        return cls(os.urandom(32))

    def sign(self, data: bytes) -> bytes:
        return _ed.sign(bytes(data), self._seed)

    def public_key(self) -> Ed25519PublicKey:
        return Ed25519PublicKey(self._pub)

    def private_bytes(self, encoding=None, fmt=None,
                      encryption_algorithm=None) -> bytes:
        return self._seed


# ------------------------------------------------------------ X25519


def _x25519(k: bytes, u: bytes) -> bytes:
    """RFC 7748 scalar multiplication on Curve25519."""
    kb = bytearray(k)
    kb[0] &= 248
    kb[31] &= 127
    kb[31] |= 64
    k_int = int.from_bytes(bytes(kb), "little")
    x1 = int.from_bytes(u, "little") & ((1 << 255) - 1)
    a24 = 121665
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in reversed(range(255)):
        k_t = (k_int >> t) & 1
        swap ^= k_t
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t
        a = (x2 + z2) % _P
        aa = a * a % _P
        b = (x2 - z2) % _P
        bb = b * b % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = d * a % _P
        cb = c * b % _P
        x3 = (da + cb) % _P
        x3 = x3 * x3 % _P
        z3 = (da - cb) % _P
        z3 = z3 * z3 % _P
        z3 = z3 * x1 % _P
        x2 = aa * bb % _P
        z2 = e * (aa + a24 * e) % _P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    out = x2 * pow(z2, _P - 2, _P) % _P
    return out.to_bytes(32, "little")


_X25519_BASE = (9).to_bytes(32, "little")


class X25519PublicKey:
    def __init__(self, raw: bytes):
        if len(raw) != 32:
            raise ValueError("x25519 public key must be 32 bytes")
        self._raw = bytes(raw)

    @classmethod
    def from_public_bytes(cls, raw: bytes) -> "X25519PublicKey":
        return cls(raw)

    def public_bytes(self, encoding=None, fmt=None) -> bytes:
        return self._raw


class X25519PrivateKey:
    def __init__(self, raw: bytes):
        self._raw = bytes(raw)

    @classmethod
    def generate(cls) -> "X25519PrivateKey":
        return cls(os.urandom(32))

    @classmethod
    def from_private_bytes(cls, raw: bytes) -> "X25519PrivateKey":
        return cls(raw)

    def public_key(self) -> X25519PublicKey:
        return X25519PublicKey(_x25519(self._raw, _X25519_BASE))

    def exchange(self, peer: X25519PublicKey) -> bytes:
        shared = _x25519(self._raw, peer.public_bytes())
        if shared == b"\x00" * 32:
            raise ValueError("x25519 all-zero shared secret")
        return shared


# -------------------------------------------------------------- HKDF


def hkdf_sha256(secret: bytes, salt: bytes, info: bytes, n: int) -> bytes:
    """RFC 5869 extract-and-expand with HMAC-SHA256."""
    prk = _hmac.new(salt or b"\x00" * 32, secret, hashlib.sha256).digest()
    okm = b""
    t = b""
    i = 1
    while len(okm) < n:
        t = _hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        okm += t
        i += 1
    return okm[:n]


class HKDF:
    """Object-style mirror of cryptography's HKDF (SHA256 only)."""

    def __init__(self, algorithm=None, length: int = 32,
                 salt: Optional[bytes] = None, info: bytes = b""):
        self._length = length
        self._salt = salt or b""
        self._info = info or b""

    def derive(self, secret: bytes) -> bytes:
        return hkdf_sha256(secret, self._salt, self._info, self._length)


# -------------------------------------------- ChaCha20-Poly1305 (RFC 8439)


def _rotl32(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF


def _chacha20_block(key_words, counter: int, nonce_words) -> bytes:
    state = [0x61707865, 0x3320646E, 0x79622D32, 0x6B206574,
             *key_words, counter & 0xFFFFFFFF, *nonce_words]
    x = list(state)
    for _ in range(10):
        for a, b, c, d in ((0, 4, 8, 12), (1, 5, 9, 13), (2, 6, 10, 14),
                           (3, 7, 11, 15), (0, 5, 10, 15), (1, 6, 11, 12),
                           (2, 7, 8, 13), (3, 4, 9, 14)):
            x[a] = (x[a] + x[b]) & 0xFFFFFFFF
            x[d] = _rotl32(x[d] ^ x[a], 16)
            x[c] = (x[c] + x[d]) & 0xFFFFFFFF
            x[b] = _rotl32(x[b] ^ x[c], 12)
            x[a] = (x[a] + x[b]) & 0xFFFFFFFF
            x[d] = _rotl32(x[d] ^ x[a], 8)
            x[c] = (x[c] + x[d]) & 0xFFFFFFFF
            x[b] = _rotl32(x[b] ^ x[c], 7)
    out = bytearray()
    for i in range(16):
        out += ((x[i] + state[i]) & 0xFFFFFFFF).to_bytes(4, "little")
    return bytes(out)


def _chacha20_xor(key: bytes, counter: int, nonce: bytes,
                  data: bytes) -> bytes:
    key_words = [int.from_bytes(key[i:i + 4], "little")
                 for i in range(0, 32, 4)]
    nonce_words = [int.from_bytes(nonce[i:i + 4], "little")
                   for i in range(0, 12, 4)]
    out = bytearray(len(data))
    for block_i in range((len(data) + 63) // 64):
        ks = _chacha20_block(key_words, counter + block_i, nonce_words)
        lo = block_i * 64
        chunk = data[lo:lo + 64]
        out[lo:lo + len(chunk)] = bytes(
            a ^ b for a, b in zip(chunk, ks))
    return bytes(out)


def _poly1305(msg: bytes, key: bytes) -> bytes:
    r = int.from_bytes(key[:16], "little") \
        & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key[16:32], "little")
    p = (1 << 130) - 5
    acc = 0
    for i in range(0, len(msg), 16):
        n = int.from_bytes(msg[i:i + 16] + b"\x01", "little")
        acc = (acc + n) * r % p
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(data: bytes) -> bytes:
    rem = len(data) % 16
    return b"\x00" * (16 - rem) if rem else b""


class ChaCha20Poly1305:
    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("chacha20poly1305 key must be 32 bytes")
        self._key = bytes(key)

    def _tag(self, nonce: bytes, ct: bytes, aad: bytes) -> bytes:
        otk = _chacha20_block(
            [int.from_bytes(self._key[i:i + 4], "little")
             for i in range(0, 32, 4)],
            0,
            [int.from_bytes(nonce[i:i + 4], "little")
             for i in range(0, 12, 4)])[:32]
        mac_data = (aad + _pad16(aad) + ct + _pad16(ct)
                    + len(aad).to_bytes(8, "little")
                    + len(ct).to_bytes(8, "little"))
        return _poly1305(mac_data, otk)

    def encrypt(self, nonce: bytes, plaintext: bytes,
                aad: Optional[bytes]) -> bytes:
        aad = aad or b""
        ct = _chacha20_xor(self._key, 1, nonce, plaintext)
        return ct + self._tag(nonce, ct, aad)

    def decrypt(self, nonce: bytes, ciphertext: bytes,
                aad: Optional[bytes]) -> bytes:
        aad = aad or b""
        if len(ciphertext) < 16:
            raise ValueError("ciphertext too short")
        ct, tag = ciphertext[:-16], ciphertext[-16:]
        if not _hmac.compare_digest(tag, self._tag(nonce, ct, aad)):
            raise ValueError("poly1305 tag mismatch")
        return _chacha20_xor(self._key, 1, nonce, ct)
