"""Backend dispatch for BLS12-381 curve operations.

The hot operations (scalar mults, pairing checks) route to the native C
module (plenum_tpu/native/bls12_381.c — the framework's ursa equivalent,
~100-300x the pure-Python speed) when a C compiler is available, and
fall back to the pure-Python reference implementation otherwise. Select
explicitly with PLENUM_TPU_BLS=python|native.

Serialization, constants and the Fq towers always come from the Python
module — they are not hot and keep a single source of truth for the
wire format.
"""
from __future__ import annotations

import os
from typing import Sequence, Tuple

from plenum_tpu.crypto import bls12_381 as _py
from plenum_tpu.crypto.bls12_381 import (  # noqa: F401  (re-exports)
    FQ12_ONE, G1Point, G2Point, G1_GEN, G2_GEN, Q, R, X_ABS,
    g1_compress, g1_decompress, g1_is_on_curve, g1_neg,
    g2_compress, g2_decompress, g2_is_on_curve, g2_neg)


def _pick_backend():
    import logging
    log = logging.getLogger(__name__)
    mode = os.environ.get("PLENUM_TPU_BLS", "auto")
    if mode not in ("auto", "native", "python"):
        log.warning("unrecognized PLENUM_TPU_BLS=%r; using auto", mode)
        mode = "auto"
    if mode == "python":
        return None
    try:
        from plenum_tpu.crypto import bls_native
        if bls_native.available():
            return bls_native
        err = bls_native.build_error()
    except (ImportError, OSError, AttributeError) as e:
        # pragma: no cover - import failure path, narrowed (PT006):
        # available() already absorbs build/load errors, so only a
        # broken import of the bridge module itself lands here
        log.debug("BLS native bridge import failed: %s", e)
        err = e
    if mode == "native":
        raise RuntimeError(
            "PLENUM_TPU_BLS=native but the C backend failed to build: %s"
            % (err,))
    log.warning("native BLS backend unavailable (%s); falling back to the "
                "~100-300x slower pure-Python pairing", err)
    return None


_native = _pick_backend()
BACKEND = "native" if _native is not None else "python"

if _native is not None:
    g1_add = _native.g1_add
    g1_mul = _native.g1_mul
    g2_add = _native.g2_add
    g2_mul = _native.g2_mul
    multi_pairing_is_one = _native.multi_pairing_is_one
    g1_decompress = _native.g1_decompress  # noqa: F811 (hot override)
    # prepared pairings: precomputed line coefficients for fixed G2
    # arguments (verifiers pair against the same generator/pool-key on
    # every verify); None on the Python backend — callers fall back
    miller_precompute = _native.miller_precompute
    multi_pairing_is_one_prepared = _native.multi_pairing_is_one_prepared
    g1_aggregate_compressed = _native.g1_aggregate_compressed
    g1_aggregate_points = _native.g1_aggregate_points
else:
    g1_add = _py.g1_add
    g1_mul = _py.g1_mul
    g2_add = _py.g2_add
    g2_mul = _py.g2_mul
    miller_precompute = None
    multi_pairing_is_one_prepared = None

    def multi_pairing_is_one(
            pairs: Sequence[Tuple[G1Point, G2Point]]) -> bool:
        return _py.multi_pairing(pairs) == _py.FQ12_ONE

    def g1_aggregate_compressed(sigs: Sequence[bytes]) -> G1Point:
        agg = None
        for s in sigs:
            agg = _py.g1_add(agg, _py.g1_decompress(s))
        return agg

    def g1_aggregate_points(points) -> G1Point:
        agg = None
        for p in points:
            agg = _py.g1_add(agg, p)
        return agg


def hash_to_g1(msg: bytes, dst: bytes = b"PLENUM_TPU_BLS_G1") -> G1Point:
    """The single shared try-and-increment construction from bls12_381;
    fully native when the C backend is up (sha256 + sqrt + cofactor in
    one call), else the Python construction with the fast scalar mul."""
    if _native is not None:
        return _native.hash_to_g1(msg, dst)
    return _py.hash_to_g1(msg, dst, g1_mul_fn=g1_mul)


def g1_in_subgroup(p: G1Point) -> bool:
    """The single shared check from bls12_381, with the scalar mult
    running on the fast backend."""
    return _py.g1_in_subgroup(p, g1_mul_fn=g1_mul)


def g2_in_subgroup(p: G2Point) -> bool:
    return _py.g2_in_subgroup(p, g2_mul_fn=g2_mul)
