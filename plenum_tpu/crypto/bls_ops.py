"""Backend dispatch for BLS12-381 curve operations.

The hot operations (scalar mults, pairing checks) route to the native C
module (plenum_tpu/native/bls12_381.c — the framework's ursa equivalent,
~100-300x the pure-Python speed) when a C compiler is available, and
fall back to the pure-Python reference implementation otherwise. Select
explicitly with PLENUM_TPU_BLS=python|native.

Serialization, constants and the Fq towers always come from the Python
module — they are not hot and keep a single source of truth for the
wire format.
"""
from __future__ import annotations

import os
from typing import Sequence, Tuple

from plenum_tpu.crypto import bls12_381 as _py
from plenum_tpu.crypto.bls12_381 import (  # noqa: F401  (re-exports)
    FQ12_ONE, G1Point, G2Point, G1_GEN, G2_GEN, Q, R, X_ABS,
    g1_compress, g1_decompress, g1_is_on_curve, g1_neg,
    g2_compress, g2_decompress, g2_is_on_curve, g2_neg)


def _pick_backend():
    import logging
    log = logging.getLogger(__name__)
    mode = os.environ.get("PLENUM_TPU_BLS", "auto")
    if mode not in ("auto", "native", "python"):
        log.warning("unrecognized PLENUM_TPU_BLS=%r; using auto", mode)
        mode = "auto"
    if mode == "python":
        return None
    try:
        from plenum_tpu.crypto import bls_native
        if bls_native.available():
            return bls_native
        err = bls_native.build_error()
    except (ImportError, OSError, AttributeError) as e:
        # pragma: no cover - import failure path, narrowed (PT006):
        # available() already absorbs build/load errors, so only a
        # broken import of the bridge module itself lands here
        log.debug("BLS native bridge import failed: %s", e)
        err = e
    if mode == "native":
        raise RuntimeError(
            "PLENUM_TPU_BLS=native but the C backend failed to build: %s"
            % (err,))
    log.warning("native BLS backend unavailable (%s); falling back to the "
                "~100-300x slower pure-Python pairing", err)
    return None


_native = _pick_backend()
BACKEND = "native" if _native is not None else "python"

if _native is not None:
    g1_add = _native.g1_add
    g1_mul = _native.g1_mul
    g2_add = _native.g2_add
    g2_mul = _native.g2_mul
    multi_pairing_is_one = _native.multi_pairing_is_one
    g1_decompress = _native.g1_decompress  # noqa: F811 (hot override)
    # prepared pairings: precomputed line coefficients for fixed G2
    # arguments (verifiers pair against the same generator/pool-key on
    # every verify); None on the Python backend — callers fall back
    miller_precompute = _native.miller_precompute
    multi_pairing_is_one_prepared = _native.multi_pairing_is_one_prepared
    g1_aggregate_compressed = _native.g1_aggregate_compressed
    g1_aggregate_points = _native.g1_aggregate_points
else:
    g1_add = _py.g1_add
    g1_mul = _py.g1_mul
    g2_add = _py.g2_add
    g2_mul = _py.g2_mul
    miller_precompute = None
    multi_pairing_is_one_prepared = None

    def multi_pairing_is_one(
            pairs: Sequence[Tuple[G1Point, G2Point]]) -> bool:
        return _py.multi_pairing(pairs) == _py.FQ12_ONE

    def g1_aggregate_compressed(sigs: Sequence[bytes]) -> G1Point:
        agg = None
        for s in sigs:
            agg = _py.g1_add(agg, _py.g1_decompress(s))
        return agg

    def g1_aggregate_points(points) -> G1Point:
        agg = None
        for p in points:
            agg = _py.g1_add(agg, p)
        return agg


# ---------------------------------------------------------------- device
# Batched pairing / MSM (ops/bls381_pairing.py). Jobs of compressed
# (G1, G2) byte pairs run as one bucketed Miller-loop launch with a
# single shared final exponentiation; the host path below implements
# the SAME verdict semantics pair-for-pair, so a device step-down is
# invisible to callers. The heavy ops/ imports stay lazy — this module
# loads on every node, jax only on the first batch above threshold.

# env knob shared with ops/bls381_pairing: "native"/"off" pins the host
# path; runtime failures step the family down permanently through the
# same mesh registry as the Pallas kernels
BLS_TOWER_ENV = "PLENUM_TPU_BLS_TOWER"


def pairing_device_ready(n_jobs: int) -> bool:
    """True when a batch of ``n_jobs`` pairing-product checks should
    take the device kernel: batch clears Config.BLS_PAIRING_DEVICE_MIN,
    the feature is on, and the tower backend has not been pinned off or
    stepped down."""
    from plenum_tpu.common.config import Config
    if not getattr(Config, "BLS_DEVICE_PAIRING", True):
        return False
    if n_jobs < int(getattr(Config, "BLS_PAIRING_DEVICE_MIN", 4)):
        return False
    try:
        from plenum_tpu.ops import mesh
    except ImportError:  # pragma: no cover - jax-less deployment
        return False
    return mesh.xla_backend_enabled(BLS_TOWER_ENV)


def pairing_job_host(pairs) -> bool:
    """Host reference semantics for ONE pairing-product job — the
    contract the device kernel is pinned byte-equal to: a both-infinity
    pair is neutral (skipped), a one-sided infinity fails the job, any
    undecodable / off-curve point fails the job, else the product over
    the decoded pairs must be exactly 1. NO subgroup checks — callers
    (crypto/bls.py) gate those before building jobs, identically on
    both paths."""
    try:
        decoded = []
        for s1, s2 in pairs:
            p = g1_decompress(bytes(s1))
            q = _py.g2_decompress(bytes(s2))
            if (p is None) != (q is None):
                return False
            if p is None:
                continue
            decoded.append((p, q))
        if not decoded:
            return True
        return multi_pairing_is_one(decoded)
    except (ValueError, KeyError, TypeError, ZeroDivisionError):
        # undecodable bytes, or a degenerate inversion inside the
        # Python Miller loop on an adversarial (e.g. 2-torsion) point
        return False


def multi_pairing_is_one_jobs(jobs) -> list:
    """Batch of independent pairing-product checks → verdict per job.
    Each job is a sequence of (compressed G1, compressed G2) byte
    pairs. One device launch for the whole batch above the threshold;
    per-job host evaluation (``pairing_job_host``) otherwise, and as
    the permanent step-down after a device failure."""
    jobs = [list(j) for j in jobs]
    if not jobs:
        return []
    if pairing_device_ready(len(jobs)):
        try:
            from plenum_tpu.ops import bls381_pairing as _bp
            verdict, _ok = _bp.pairing_jobs(jobs)
            return [bool(v) for v in verdict]
        except Exception as e:  # pragma: no cover  # plenum-lint: disable=PT006
            # any device-side failure (OOM, compile, runtime) must step
            # the family down and serve host verdicts, never crash a
            # verify path — same contract as the sha256/ed25519 Pallas
            # fallbacks
            import logging
            from plenum_tpu.ops import mesh
            mesh.disable_pallas_backend(BLS_TOWER_ENV)
            logging.getLogger(__name__).warning(
                "device BLS pairing failed (%s); stepped down to the "
                "host path permanently", e)
    return [pairing_job_host(j) for j in jobs]


def g1_msm(points: Sequence[bytes], scalars: Sequence[int]):
    """Σ sᵢ·Pᵢ over G1 — windowed multi-scalar multiplication. Device
    kernel (shared doubling chain across the whole batch) above
    Config.BLS_MSM_DEVICE_MIN when the tower backend is up; host
    double-and-add per point otherwise. ``points`` are compressed
    bytes; scalars are reduced mod r on both paths. Returns an affine
    point, or None for the identity; raises ValueError on undecodable
    input (both paths)."""
    if len(points) != len(scalars):
        raise ValueError("points/scalars length mismatch")
    if not points:
        return None
    from plenum_tpu.common.config import Config
    n_min = int(getattr(Config, "BLS_MSM_DEVICE_MIN", 8))
    use_device = len(points) >= n_min \
        and getattr(Config, "BLS_DEVICE_PAIRING", True)
    if use_device:
        try:
            from plenum_tpu.ops import mesh
            use_device = mesh.xla_backend_enabled(BLS_TOWER_ENV)
        except ImportError:  # pragma: no cover - jax-less deployment
            use_device = False
    if use_device:
        try:
            from plenum_tpu.ops import bls381_pairing as _bp
            point, ok = _bp.msm_g1(points, scalars)
            if not ok:
                raise ValueError("undecodable point in MSM input")
            return point
        except ValueError:
            raise
        except Exception as e:  # pragma: no cover  # plenum-lint: disable=PT006
            # step-down, not crash: the host double-and-add below
            # serves every MSM the device path would have
            import logging
            from plenum_tpu.ops import mesh
            mesh.disable_pallas_backend(BLS_TOWER_ENV)
            logging.getLogger(__name__).warning(
                "device BLS MSM failed (%s); stepped down to the host "
                "path permanently", e)
    agg = None
    for raw, s in zip(points, scalars):
        p = g1_decompress(bytes(raw))
        if p is None:
            continue
        agg = g1_add(agg, g1_mul(p, s % R))
    return agg


def hash_to_g1(msg: bytes, dst: bytes = b"PLENUM_TPU_BLS_G1") -> G1Point:
    """The single shared try-and-increment construction from bls12_381;
    fully native when the C backend is up (sha256 + sqrt + cofactor in
    one call), else the Python construction with the fast scalar mul."""
    if _native is not None:
        return _native.hash_to_g1(msg, dst)
    return _py.hash_to_g1(msg, dst, g1_mul_fn=g1_mul)


def g1_in_subgroup(p: G1Point) -> bool:
    """The single shared check from bls12_381, with the scalar mult
    running on the fast backend."""
    return _py.g1_in_subgroup(p, g1_mul_fn=g1_mul)


def g2_in_subgroup(p: G2Point) -> bool:
    return _py.g2_in_subgroup(p, g2_mul_fn=g2_mul)
