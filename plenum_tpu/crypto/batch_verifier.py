"""Batched ed25519 verification provider — the north-star dispatch seam.

The reference authenticates each request inline through libsodium
(`plenum/server/client_authn.py:84`). Here verification requests are
gathered per prod tick and dispatched as ONE device batch when the queue
is deep enough; small batches take the scalar floor so a quiet pool never
regresses (SURVEY.md §7 "hard parts" #3: dispatch policy by queue depth).

Providers:
  - ScalarVerifier: pure-Python RFC 8032 (crypto/ed25519.py), per item —
    the reference implementation used for cross-checking only.
  - OpenSSLVerifier: per-item verification through OpenSSL's Ed25519
    (`cryptography`) — the honest CPU floor, equivalent to the
    reference's libsodium path (~10-20k verifies/s/core).
  - JaxBatchVerifier: one fused TPU dispatch (ops/ed25519_jax.py).
  - AdaptiveVerifier: routes by batch size; default `tpu_batch` provider.

All providers share one interface: verify_batch([(msg, sig, vk)]) → [bool].
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

from plenum_tpu.observability.tracing import CAT_DEVICE, NullTracer
from plenum_tpu.observability import telemetry as _telemetry

VerifyItem = Tuple[bytes, bytes, bytes]  # (message, signature64, verkey32)


class _Ready:
    """Already-materialized result (scalar paths)."""

    def __init__(self, results: List[bool]):
        self._results = results

    def ready(self) -> bool:
        return True

    def collect(self) -> List[bool]:
        return self._results


class _PendingDevice:
    """In-flight device batch: JAX dispatch is async — creating this does
    not block; collect() materializes (blocks on the device)."""

    def __init__(self, ok_device, valid, n):
        self._ok = ok_device
        self._valid = valid
        self._n = n

    def ready(self) -> bool:
        is_ready = getattr(self._ok, "is_ready", None)
        return bool(is_ready()) if is_ready is not None else True

    def collect(self) -> List[bool]:
        import numpy as np
        return list(np.asarray(self._ok)[:self._n] & self._valid)


class ScalarVerifier:
    name = "scalar"

    def verify_batch(self, items: Sequence[VerifyItem]) -> List[bool]:
        from . import ed25519
        return [ed25519.verify(m, s, vk) for (m, s, vk) in items]

    def dispatch(self, items: Sequence[VerifyItem]) -> _Ready:
        return _Ready(self.verify_batch(items))


try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (  # noqa
        Ed25519PublicKey as _OpenSSLEd25519PublicKey)
    HAVE_OPENSSL = True
except ImportError:        # soft dep: scalar RFC 8032 fallback below
    HAVE_OPENSSL = False


class OpenSSLVerifier:
    """The CPU production floor (libsodium-equivalent): OpenSSL Ed25519
    via `cryptography`. Reference: stp_core/crypto/nacl_wrappers.py.
    When `cryptography` is not installed, falls back to the
    pure-Python RFC 8032 implementation — identical verdicts, scalar
    speed floor."""

    name = "cpu"

    def verify_batch(self, items: Sequence[VerifyItem]) -> List[bool]:
        if not HAVE_OPENSSL:
            return ScalarVerifier().verify_batch(items)
        from cryptography.exceptions import InvalidSignature
        out = []
        for msg, sig, vk in items:
            try:
                _OpenSSLEd25519PublicKey.from_public_bytes(
                    bytes(vk)).verify(bytes(sig), bytes(msg))
                out.append(True)
            except (InvalidSignature, ValueError):
                out.append(False)
        return out

    def dispatch(self, items: Sequence[VerifyItem]) -> _Ready:
        return _Ready(self.verify_batch(items))


class JaxBatchVerifier:
    name = "tpu_batch"

    def verify_batch(self, items: Sequence[VerifyItem]) -> List[bool]:
        return self.dispatch(items).collect()

    def dispatch(self, items: Sequence[VerifyItem]) -> "_PendingDevice":
        """Enqueue the device batch WITHOUT blocking on the result —
        JAX dispatch is asynchronous, so the caller (prod loop) overlaps
        consensus work / other nodes\' dispatches with the device round
        trip and harvests later (SURVEY.md §7 backpressure design)."""
        from plenum_tpu.ops import ed25519_jax
        msgs = [m for m, _, _ in items]
        sigs = [s for _, s, _ in items]
        vks = [vk for _, _, vk in items]
        ok_dev, valid, n = ed25519_jax.verify_batch_async(msgs, sigs, vks)
        return _PendingDevice(ok_dev, valid, n)


def _default_threshold(threshold):
    """Single-source the scalar-vs-device batch threshold from
    Config.VERIFIER_BATCH_THRESHOLD (like the MERKLE_DEVICE_* knobs);
    an explicit ctor argument still wins."""
    if threshold is not None:
        return threshold
    from plenum_tpu.common.config import Config
    return Config.VERIFIER_BATCH_THRESHOLD


class AdaptiveVerifier:
    """Scalar floor below `threshold` items, device batch above
    (default: Config.VERIFIER_BATCH_THRESHOLD)."""

    name = "adaptive"

    def __init__(self, threshold: int = None, scalar=None, batch=None):
        self.threshold = _default_threshold(threshold)
        self._scalar = scalar or OpenSSLVerifier()
        self._batch = batch or JaxBatchVerifier()

    def verify_batch(self, items: Sequence[VerifyItem]) -> List[bool]:
        if len(items) >= self.threshold:
            return self._batch.verify_batch(items)
        return self._scalar.verify_batch(items)

    def dispatch(self, items: Sequence[VerifyItem]):
        if len(items) >= self.threshold:
            return self._batch.dispatch(items)
        return self._scalar.dispatch(items)


class _HubPending:
    """One dispatch's slice of a coalesced device launch."""

    def __init__(self, hub, gen, lo, hi):
        self._hub = hub
        self._gen = gen
        self._lo = lo
        self._hi = hi

    def ready(self) -> bool:
        pending = self._gen.pending
        if pending is None:
            return False  # generation not flushed yet
        r = getattr(pending, "ready", None)
        return bool(r()) if r is not None else True

    def collect(self) -> List[bool]:
        hub = self._hub
        # the harvest: when results are not yet materialized this span
        # IS the host-visible device round trip for this slice
        with hub.tracer.span("hub_collect", CAT_DEVICE,
                             n=self._hi - self._lo):
            hub._flush(self._gen)
            return self._gen.results()[self._lo:self._hi]


def dedup_items(items: Sequence[VerifyItem]
                ) -> Tuple[List[VerifyItem], List[int]]:
    """→ (unique_items, index) where index[i] is item i's slot in the
    unique list. Verification is pure, and co-resident nodes all verify
    the SAME client requests — callers sharing a device (hub,
    verify daemon) would otherwise pay n× the work for one answer."""
    uniq: dict = {}
    order: List[VerifyItem] = []
    index: List[int] = []
    for item in items:
        pos = uniq.get(item)
        if pos is None:
            pos = uniq[item] = len(order)
            order.append(item)
        index.append(pos)
    return order, index


class _HubGeneration:
    def __init__(self):
        self.items: List[VerifyItem] = []
        self.pending = None
        self._results = None
        self._index = None  # per-item slot in the deduped launch
        self._tm_device = False     # launched on the device path
        self._tm_new_shape = False  # that launch compiled a new bucket
        self._tm_hub = None         # telemetry hub stamped at flush

    def dedup(self) -> List[VerifyItem]:
        order, self._index = dedup_items(self.items)
        return order

    def results(self) -> List[bool]:
        if self._results is None:
            if self._tm_device:
                # the materialization below IS this generation's
                # dispatch→collect round trip as the host sees it
                hub = self._tm_hub or _telemetry.get_seam_hub()
                t0 = hub.clock()
                res = self.pending.collect()
                hub.record_roundtrip(
                    _telemetry.SEAM_HUB, (hub.clock() - t0) * 1e3,
                    first_call=self._tm_new_shape)
            else:
                res = self.pending.collect()
            idx = self._index
            self._results = res if idx is None \
                else [res[i] for i in idx]
        return self._results


class CoalescingVerifierHub:
    """Coalesces concurrent dispatches from co-resident consumers
    (RBFT protocol instances sharing a node, or pool nodes sharing a
    host process) into ONE device launch.

    The verify kernel is latency-bound — the 256-bit scalar-mult ladder
    is a long sequential dependency chain, so a 512-item launch costs
    ~1/3 of an 8192-item launch (118 ms vs 344 ms on one chip) — which
    makes k small concurrent launches cost ~k× one fused launch. The
    hub queues dispatch() calls and launches the union the first time
    any participant harvests; per-dispatch slices keep results isolated.

    Same dispatch()/verify_batch() interface as the other providers, so
    it drops into ClientAuthNr unchanged.

    Standalone construction (the gateway tier, tests, tools): every
    collaborator is an explicit ctor argument — ``tracer`` (flight
    recorder; NullTracer default), ``telemetry`` (the hub that receives
    the SEAM_HUB launch/round-trip accounting; defaults to the lazy
    process-wide seam hub so node-owned wiring is unchanged) and
    ``threshold`` (Config single-source default). Nothing here reaches
    into a Node.
    """

    name = "tpu_hub"

    def __init__(self, batch=None, scalar=None, threshold: int = None,
                 tracer=None, telemetry=None):
        self._batch = batch or JaxBatchVerifier()
        self._scalar = scalar or OpenSSLVerifier()
        self.threshold = _default_threshold(threshold)
        self._gen = _HubGeneration()
        # node/bench may still attach a recorder post-ctor (plain
        # attribute); explicit injection is the standalone path
        self.tracer = tracer if tracer is not None else NullTracer()
        self._telemetry = telemetry  # None = lazy process seam hub

    @property
    def telemetry(self):
        """The telemetry hub this hub's SEAM_HUB accounting lands in:
        the injected one, or (default) the process-wide seam hub."""
        return self._telemetry if self._telemetry is not None \
            else _telemetry.get_seam_hub()

    def dispatch(self, items: Sequence[VerifyItem]) -> _HubPending:
        gen = self._gen
        lo = len(gen.items)
        gen.items.extend(items)
        # queue-depth counter: how deep the open generation is when each
        # co-resident consumer lands — the coalescing evidence
        self.tracer.counter("hub_queue_depth", len(gen.items))
        return _HubPending(self, gen, lo, len(gen.items))

    def flush(self) -> None:
        """Close the current generation and START its (async) device
        launch now, instead of waiting for the first collect. Callers
        that know a coalescing window just ended (all co-resident nodes
        dispatched their chunk) use this to overlap the device round
        trip with the consensus work that follows; pending handles
        already issued for this generation stay valid."""
        self._flush(self._gen)

    def _flush(self, gen: _HubGeneration) -> None:
        if gen.pending is not None:
            return
        # rotate FIRST: a failing dispatch must poison only this
        # generation, not wedge every future dispatch from every
        # co-resident consumer
        if gen is self._gen:
            self._gen = _HubGeneration()
        with self.tracer.span("hub_flush", CAT_DEVICE,
                              items=len(gen.items)) as _sp:
            launch_items = gen.dedup()
            _sp.add(unique=len(launch_items))
            if not launch_items:
                gen.pending = _Ready([])
            elif len(launch_items) < self.threshold:
                # quiet pool: a lone small generation takes the CPU floor
                # rather than paying a full device launch
                gen.pending = self._scalar.dispatch(launch_items)
            else:
                # hub-seam lane accounting: unique items launched vs the
                # bucket the async verify pads them into (the SAME
                # pow2/mesh bucket math the launch pays — single-sourced
                # in ed25519_jax.launch_lanes)
                from plenum_tpu.ops.ed25519_jax import launch_lanes
                lanes = launch_lanes(len(launch_items))
                gen._tm_device = True
                gen._tm_hub = self.telemetry
                gen._tm_new_shape = gen._tm_hub.record_launch(
                    _telemetry.SEAM_HUB,
                    len(launch_items), lanes, shape=lanes)
                gen.pending = self._batch.dispatch(launch_items)

    def verify_batch(self, items: Sequence[VerifyItem]) -> List[bool]:
        return self.dispatch(items).collect()


def _make_remote(**kwargs):
    from plenum_tpu.crypto.remote_verifier import RemoteVerifier
    return RemoteVerifier(**kwargs)


_PROVIDERS = {
    "scalar": ScalarVerifier,
    "cpu": OpenSSLVerifier,
    "tpu_batch": JaxBatchVerifier,
    "tpu_hub": CoalescingVerifierHub,
    "adaptive": AdaptiveVerifier,
    "remote": _make_remote,
}


def create_verifier(name: str = "adaptive", **kwargs):
    try:
        cls = _PROVIDERS[name]
    except KeyError:
        raise ValueError(f"unknown verifier provider {name!r}; "
                         f"one of {sorted(_PROVIDERS)}")
    return cls(**kwargs)
