"""Batched ed25519 verification provider — the north-star dispatch seam.

The reference authenticates each request inline through libsodium
(`plenum/server/client_authn.py:84`). Here verification requests are
gathered per prod tick and dispatched as ONE device batch when the queue
is deep enough; small batches take the scalar floor so a quiet pool never
regresses (SURVEY.md §7 "hard parts" #3: dispatch policy by queue depth).

Providers:
  - ScalarVerifier: pure-Python RFC 8032 (crypto/ed25519.py), per item.
  - JaxBatchVerifier: one fused TPU dispatch (ops/ed25519_jax.py).
  - AdaptiveVerifier: routes by batch size; default `tpu_batch` provider.

All providers share one interface: verify_batch([(msg, sig, vk)]) → [bool].
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

VerifyItem = Tuple[bytes, bytes, bytes]  # (message, signature64, verkey32)


class ScalarVerifier:
    name = "scalar"

    def verify_batch(self, items: Sequence[VerifyItem]) -> List[bool]:
        from . import ed25519
        return [ed25519.verify(m, s, vk) for (m, s, vk) in items]


class JaxBatchVerifier:
    name = "tpu_batch"

    def verify_batch(self, items: Sequence[VerifyItem]) -> List[bool]:
        from plenum_tpu.ops import ed25519_jax
        msgs = [m for m, _, _ in items]
        sigs = [s for _, s, _ in items]
        vks = [vk for _, _, vk in items]
        return list(ed25519_jax.verify_batch(msgs, sigs, vks))


class AdaptiveVerifier:
    """Scalar floor below `threshold` items, device batch above."""

    name = "adaptive"

    def __init__(self, threshold: int = 32, scalar=None, batch=None):
        self.threshold = threshold
        self._scalar = scalar or ScalarVerifier()
        self._batch = batch or JaxBatchVerifier()

    def verify_batch(self, items: Sequence[VerifyItem]) -> List[bool]:
        if len(items) >= self.threshold:
            return self._batch.verify_batch(items)
        return self._scalar.verify_batch(items)


_PROVIDERS = {
    "scalar": ScalarVerifier,
    "tpu_batch": JaxBatchVerifier,
    "adaptive": AdaptiveVerifier,
}


def create_verifier(name: str = "adaptive", **kwargs):
    try:
        cls = _PROVIDERS[name]
    except KeyError:
        raise ValueError(f"unknown verifier provider {name!r}; "
                         f"one of {sorted(_PROVIDERS)}")
    return cls(**kwargs)
