"""Deterministic signature fixtures shared by bench.py, __graft_entry__,
and tests — one generator so every harness exercises the same data path.
"""
from typing import List, Tuple

import numpy as np


def make_signed_batch(count: int, seed: int = 0, unique: int = None,
                      msg_prefix: bytes = b"fixture"
                      ) -> Tuple[List[bytes], List[bytes], List[bytes]]:
    """→ (msgs, sigs, verkeys), `unique` distinct keypairs tiled to
    `count` entries (signing is pure-Python; tiling keeps fixture
    generation cheap while device work is identical per entry)."""
    from plenum_tpu.crypto import ed25519 as ed

    unique = min(count, unique or count)
    rng = np.random.RandomState(seed)
    msgs, sigs, vks = [], [], []
    for i in range(unique):
        kseed = bytes(rng.randint(0, 256, 32, dtype=np.uint8))
        vk, _ = ed.keypair_from_seed(kseed)
        msg = msg_prefix + b"-%d" % i
        msgs.append(msg)
        sigs.append(ed.sign(msg, kseed))
        vks.append(vk)
    reps = (count + unique - 1) // unique
    return ((msgs * reps)[:count], (sigs * reps)[:count],
            (vks * reps)[:count])
