"""Deterministic signature fixtures shared by bench.py, __graft_entry__,
and tests — one generator so every harness exercises the same data path.
"""
from typing import List, Tuple

import numpy as np


def make_signed_batch(count: int, seed: int = 0, unique: int = None,
                      msg_prefix: bytes = b"fixture"
                      ) -> Tuple[List[bytes], List[bytes], List[bytes]]:
    """→ (msgs, sigs, verkeys), `unique` distinct keypairs tiled to
    `count` entries. Keygen+signing ride OpenSSL when available (RFC
    8032 Ed25519 is deterministic, so outputs are bit-identical to the
    pure-Python reference path) — at count=10k+ the pure-Python path
    costs minutes, the OpenSSL one milliseconds."""
    from plenum_tpu.crypto.signer import SimpleSigner

    unique = min(count, unique or count)
    rng = np.random.RandomState(seed)
    msgs, sigs, vks = [], [], []
    for i in range(unique):
        kseed = bytes(rng.randint(0, 256, 32, dtype=np.uint8))
        signer = SimpleSigner(seed=kseed)   # OpenSSL path w/ py fallback
        msg = msg_prefix + b"-%d" % i
        msgs.append(msg)
        sigs.append(signer.sign_bytes(msg))
        vks.append(signer.verraw)
    reps = (count + unique - 1) // unique
    return ((msgs * reps)[:count], (sigs * reps)[:count],
            (vks * reps)[:count])
