"""Pure-Python ed25519 (RFC 8032) — the scalar floor of the verify path.

The reference binds libsodium via libnacl (`stp_core/crypto/nacl_wrappers.py`,
SURVEY.md §2.9). Here the scalar implementation is self-contained Python
(used for signing, key generation, and single-signature verification);
bulk verification dispatches to the batched JAX kernel in
plenum_tpu.ops.ed25519_jax, which this module cross-checks in tests.

Implementation is textbook RFC 8032 over extended twisted-Edwards
coordinates; speed is secondary here (the hot path is the TPU batch).
"""
from __future__ import annotations

import hashlib
from typing import Tuple

P = 2 ** 255 - 19
L = 2 ** 252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)

# Base point
G_Y = (4 * pow(5, P - 2, P)) % P


def _recover_x(y: int, sign: int) -> int:
    """x from y via sqrt((y^2-1)/(d y^2+1)); raises ValueError if none."""
    if y >= P:
        raise ValueError("non-canonical y")
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    # candidate root of u/v: u * v^3 * (u * v^7)^((p-5)/8)
    x = (u * pow(v, 3, P) * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P)) % P
    vxx = v * x * x % P
    if vxx == u:
        pass
    elif vxx == (P - u) % P:
        x = x * SQRT_M1 % P
    else:
        raise ValueError("not a square")
    if x == 0 and sign == 1:
        raise ValueError("invalid sign for x=0")
    if x & 1 != sign:
        x = P - x
    return x


G_X = _recover_x(G_Y, 0)

# Extended coordinates (X, Y, Z, T), T = X*Y/Z
_IDENT = (0, 1, 1, 0)
_G_EXT = (G_X, G_Y, 1, G_X * G_Y % P)


def _pt_add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = t1 * 2 * D * t2 % P
    d = z1 * 2 * z2 % P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def _pt_double(p):
    x1, y1, z1, _ = p
    a = x1 * x1 % P
    b = y1 * y1 % P
    c = 2 * z1 * z1 % P
    e = ((x1 + y1) * (x1 + y1) - a - b) % P
    g = (b - a) % P
    f = (g - c) % P
    h = (-a - b) % P
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def _pt_mul(s: int, p):
    q = _IDENT
    while s > 0:
        if s & 1:
            q = _pt_add(q, p)
        p = _pt_double(p)
        s >>= 1
    return q


def _pt_compress(p) -> bytes:
    x, y, z, _ = p
    zinv = pow(z, P - 2, P)
    x, y = x * zinv % P, y * zinv % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _pt_decompress(data: bytes):
    if len(data) != 32:
        raise ValueError("bad point length")
    n = int.from_bytes(data, "little")
    sign = n >> 255
    y = n & ((1 << 255) - 1)
    x = _recover_x(y, sign)
    return (x, y, 1, x * y % P)


def _pt_equal(p, q) -> bool:
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


def _sha512_int(*parts: bytes) -> int:
    h = hashlib.sha512()
    for part in parts:
        h.update(part)
    return int.from_bytes(h.digest(), "little")


def _clamp(h: bytes) -> int:
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def publickey_from_seed(seed: bytes) -> bytes:
    h = hashlib.sha512(seed).digest()
    a = _clamp(h)
    return _pt_compress(_pt_mul(a, _G_EXT))


def keypair_from_seed(seed: bytes) -> Tuple[bytes, bytes]:
    """seed (32B) → (verkey 32B, secret = seed||verkey 64B)."""
    vk = publickey_from_seed(seed)
    return vk, seed + vk


def sign(msg: bytes, seed: bytes) -> bytes:
    """Detached 64-byte signature with secret seed (32 bytes)."""
    h = hashlib.sha512(seed).digest()
    a = _clamp(h)
    prefix = h[32:]
    vk = _pt_compress(_pt_mul(a, _G_EXT))
    r = _sha512_int(prefix, msg) % L
    R = _pt_compress(_pt_mul(r, _G_EXT))
    k = _sha512_int(R, vk, msg) % L
    s = (r + k * a) % L
    return R + s.to_bytes(32, "little")


def verify(msg: bytes, sig: bytes, verkey: bytes) -> bool:
    """Cofactorless verification: [S]B == R + [k]A."""
    if len(sig) != 64 or len(verkey) != 32:
        return False
    try:
        A = _pt_decompress(verkey)
        R = _pt_decompress(sig[:32])
    except ValueError:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    k = _sha512_int(sig[:32], verkey, msg) % L
    return _pt_equal(_pt_mul(s, _G_EXT), _pt_add(R, _pt_mul(k, A)))
