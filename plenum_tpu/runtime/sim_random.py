"""Seeded deterministic randomness for simulation tests.

Reference: plenum/test/simulation/sim_random.py:34 (DefaultSimRandom).
Lives in the runtime package (not tests) because randomized simulation is a
first-class determinism tool (SURVEY.md §5.2).
"""
import random
from abc import ABC, abstractmethod
from typing import Any, Iterable, List


class SimRandom(ABC):
    @abstractmethod
    def integer(self, min_value: int, max_value: int) -> int:
        ...

    @abstractmethod
    def float(self, min_value: float, max_value: float) -> float:
        ...

    @abstractmethod
    def string(self, min_len: int, max_len: int = None) -> str:
        ...

    @abstractmethod
    def choice(self, *args) -> Any:
        ...

    @abstractmethod
    def sample(self, population: Iterable, k: int) -> List:
        ...

    @abstractmethod
    def shuffle(self, items: List) -> List:
        ...


class DefaultSimRandom(SimRandom):
    def __init__(self, seed: int = 0):
        self._seed = seed
        self._random = random.Random(seed)

    @property
    def seed(self) -> int:
        return self._seed

    def integer(self, min_value: int, max_value: int) -> int:
        return self._random.randint(min_value, max_value)

    def float(self, min_value: float, max_value: float) -> float:
        return self._random.uniform(min_value, max_value)

    def string(self, min_len: int, max_len: int = None) -> str:
        alpha = 'abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789'
        length = self.integer(min_len, max_len if max_len is not None else min_len)
        return ''.join(self.choice(*alpha) for _ in range(length))

    def choice(self, *args) -> Any:
        return self._random.choice(args)

    def sample(self, population, k: int) -> List:
        return self._random.sample(list(population), k)

    def shuffle(self, items: List) -> List:
        items = list(items)
        self._random.shuffle(items)
        return items
