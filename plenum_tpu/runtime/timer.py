"""Timer service: abstract clock + ordered callback queue.

Reference: plenum/common/timer.py:13 (TimerService), :27 (QueueTimer),
:60 (RepeatingTimer). This is the *only* clock consensus services see, so a
MockTimer (plenum_tpu/testing/mock_timer.py) makes the whole consensus layer
deterministically testable with no real time or sockets (SURVEY.md §4 rung 2).
"""
import heapq
import time
from abc import ABC, abstractmethod
from typing import Callable


class TimerService(ABC):
    @abstractmethod
    def get_current_time(self) -> float:
        ...

    @abstractmethod
    def schedule(self, delay: float, callback: Callable) -> None:
        ...

    @abstractmethod
    def cancel(self, callback: Callable) -> None:
        """Cancel all scheduled occurrences of callback."""


class QueueTimer(TimerService):
    """Production timer: events fire from `service()` which the owning loop
    calls every prod tick (reference plenum/common/timer.py:27).

    Heap entries are ``[timestamp, seq, callback]``: the seq breaks ties so
    equal-timestamp events fire FIFO and callbacks are never compared.
    cancel() tombstones entries in place (callback → None); peeks/pops skip
    tombstones lazily, keeping every operation O(log n) on the timer-driven
    hot loop (this is the single clock under all consensus services)."""

    def __init__(self, get_current_time: Callable[[], float] = time.perf_counter):
        self._get_current_time = get_current_time
        self._heap = []
        self._seq = 0
        self._live = 0

    def queue_size(self) -> int:
        return self._live

    def get_current_time(self) -> float:
        return self._get_current_time()

    def schedule(self, delay: float, callback: Callable) -> None:
        self._seq += 1
        heapq.heappush(self._heap,
                       [self.get_current_time() + delay, self._seq, callback])
        self._live += 1

    def cancel(self, callback: Callable) -> None:
        for entry in self._heap:
            if entry[2] == callback:
                entry[2] = None
                self._live -= 1
        # schedule/cancel churn (watchdogs rescheduled per message) can
        # leave long-delay tombstones resident for their full horizon;
        # compact when they outnumber live entries so cancel() scans and
        # heap pushes stay proportional to real load
        if len(self._heap) > 2 * self._live + 8:
            self._heap = [e for e in self._heap if e[2] is not None]
            heapq.heapify(self._heap)

    def _peek(self):
        """Next live entry ([timestamp, seq, callback]) or None."""
        heap = self._heap
        while heap and heap[0][2] is None:
            heapq.heappop(heap)
        return heap[0] if heap else None

    def _pop(self):
        """Remove and return the next live entry, or None."""
        entry = self._peek()
        if entry is not None:
            heapq.heappop(self._heap)
            self._live -= 1
        return entry

    def service(self) -> int:
        """Fire all due events; returns count fired."""
        count = 0
        now = self.get_current_time()
        while True:
            entry = self._peek()
            if entry is None or entry[0] > now:
                break
            heapq.heappop(self._heap)
            self._live -= 1
            entry[2]()
            count += 1
        return count

    def next_wakeup_in(self):
        entry = self._peek()
        if entry is None:
            return None
        return max(0.0, entry[0] - self.get_current_time())


class RepeatingTimer:
    """Re-schedules callback every `interval` until stopped (reference
    plenum/common/timer.py:60)."""

    def __init__(self, timer: TimerService, interval: float,
                 callback: Callable, active: bool = True):
        assert interval > 0
        self._timer = timer
        self._interval = interval
        self._callback = callback
        self._active = False
        # Distinct bound wrapper so cancel() of one RepeatingTimer never
        # cancels another timer using the same raw callback.
        def _wrapped():
            if self._active:
                self._callback()
                # the callback may have called stop(); don't reschedule then
                if self._active:
                    self._timer.schedule(self._interval, _wrapped)
        self._wrapped = _wrapped
        if active:
            self.start()

    def start(self) -> None:
        if not self._active:
            self._active = True
            self._timer.schedule(self._interval, self._wrapped)

    def stop(self) -> None:
        if self._active:
            self._active = False
            self._timer.cancel(self._wrapped)

    def update_interval(self, interval: float) -> None:
        assert interval > 0
        self._interval = interval
