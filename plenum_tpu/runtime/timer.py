"""Timer service: abstract clock + ordered callback queue.

Reference: plenum/common/timer.py:13 (TimerService), :27 (QueueTimer),
:60 (RepeatingTimer). This is the *only* clock consensus services see, so a
MockTimer (plenum_tpu/testing/mock_timer.py) makes the whole consensus layer
deterministically testable with no real time or sockets (SURVEY.md §4 rung 2).
"""
import time
from abc import ABC, abstractmethod
from typing import Callable, NamedTuple

from sortedcontainers import SortedList


class TimerService(ABC):
    @abstractmethod
    def get_current_time(self) -> float:
        ...

    @abstractmethod
    def schedule(self, delay: float, callback: Callable) -> None:
        ...

    @abstractmethod
    def cancel(self, callback: Callable) -> None:
        """Cancel all scheduled occurrences of callback."""


class TimerEvent(NamedTuple):
    # ordering is always via SortedList's explicit timestamp key — never
    # compare TimerEvents directly (callbacks aren't orderable)
    timestamp: float
    callback: Callable


class QueueTimer(TimerService):
    """Production timer: events fire from `service()` which the owning loop
    calls every prod tick (reference plenum/common/timer.py:27)."""

    def __init__(self, get_current_time: Callable[[], float] = time.perf_counter):
        self._get_current_time = get_current_time
        self._events = SortedList(key=lambda ev: ev.timestamp)

    def queue_size(self) -> int:
        return len(self._events)

    def get_current_time(self) -> float:
        return self._get_current_time()

    def schedule(self, delay: float, callback: Callable) -> None:
        self._events.add(TimerEvent(timestamp=self.get_current_time() + delay,
                                    callback=callback))

    def cancel(self, callback: Callable) -> None:
        for ev in [ev for ev in self._events if ev.callback == callback]:
            self._events.remove(ev)

    def service(self) -> int:
        """Fire all due events; returns count fired."""
        count = 0
        now = self.get_current_time()
        while self._events and self._events[0].timestamp <= now:
            ev = self._events.pop(0)
            ev.callback()
            count += 1
        return count

    def next_wakeup_in(self):
        if not self._events:
            return None
        return max(0.0, self._events[0].timestamp - self.get_current_time())


class RepeatingTimer:
    """Re-schedules callback every `interval` until stopped (reference
    plenum/common/timer.py:60)."""

    def __init__(self, timer: TimerService, interval: float,
                 callback: Callable, active: bool = True):
        assert interval > 0
        self._timer = timer
        self._interval = interval
        self._callback = callback
        self._active = False
        # Distinct bound wrapper so cancel() of one RepeatingTimer never
        # cancels another timer using the same raw callback.
        def _wrapped():
            if self._active:
                self._callback()
                # the callback may have called stop(); don't reschedule then
                if self._active:
                    self._timer.schedule(self._interval, _wrapped)
        self._wrapped = _wrapped
        if active:
            self.start()

    def start(self) -> None:
        if not self._active:
            self._active = True
            self._timer.schedule(self._interval, self._wrapped)

    def stop(self) -> None:
        if self._active:
            self._active = False
            self._timer.cancel(self._wrapped)

    def update_interval(self, interval: float) -> None:
        assert interval > 0
        self._interval = interval
