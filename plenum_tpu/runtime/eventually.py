"""Async polling-with-timeout, used pervasively by integration tests.

Reference: stp_core/loop/eventually.py:124 (eventually), :50 (eventuallyAll).
"""
import asyncio
import inspect
import time
from typing import Callable


async def eventually(coro_func: Callable, *args,
                     retry_wait: float = 0.1,
                     timeout: float = 5.0,
                     acceptable_fails: int = None) -> object:
    """Poll `coro_func(*args)` until it stops raising, up to `timeout` sec.
    If `acceptable_fails` is given, raise after that many failed attempts
    even when time remains."""
    assert timeout > 0
    start = time.perf_counter()
    fails = 0
    while True:
        try:
            res = coro_func(*args)
            if inspect.isawaitable(res):
                res = await res
            return res
        except Exception:
            fails += 1
            remaining = timeout - (time.perf_counter() - start)
            if remaining <= 0:
                raise
            if acceptable_fails is not None and fails > acceptable_fails:
                raise
            await asyncio.sleep(min(retry_wait, remaining))


async def eventuallyAll(*coro_funcs, total_timeout: float = 10.0,
                        retry_wait: float = 0.1):
    """Each check gets whatever remains of the shared budget (reference
    eventually.py:50) — one slow check may use most of it."""
    deadline = time.perf_counter() + total_timeout
    results = []
    for f in coro_funcs:
        remaining = max(0.001, deadline - time.perf_counter())
        results.append(await eventually(f, retry_wait=retry_wait,
                                        timeout=remaining))
    return results
