"""Pipeline-parallel node runtime — worker stages feeding the prod thread.

The reference is explicitly a single-process cooperative system, so a
node's ordered throughput is capped by the SUM of its stage costs: wire
parse, signature pre-screen, 3PC counting, execution and reply all
compete for one core however fast each stage got individually. This
module breaks that ceiling without touching the consensus semantics:

* **Wire parse + ed25519 pre-screen** run on a dedicated worker thread.
  Flat envelopes are immutable byte buffers (PR 11), so they cross the
  thread boundary without copying or pickling; the parse result
  (``ParsedEnvelope``: plain numpy views over those bytes) is equally
  immutable on the way back.
* **The prod thread keeps sole ownership of ALL consensus state.** The
  worker never calls into ordering, propagation, ledgers or state — it
  only turns bytes into views and warms a verdict cache. Every
  consensus side effect (vote counting, suspicions, stashes, sends)
  happens at :meth:`NodePipeline.drain`, on the prod thread, in exact
  arrival order. ``OrderingService.bind_owner_thread`` enforces this
  contract at the intake seams.
* **Execution fan-out**: per-state structural merges in
  ``flush_states_merged`` are independent (PR 13), so the executor
  fans them across :meth:`exec_map`'s small thread pool while apply
  order — the semantics — stays strictly batch order on the prod
  thread.

Determinism is by construction, not by luck: jobs are delivered in
submission order through ONE FIFO, and the drain runs at the same
simulated instant the serial path would have processed the message (the
node schedules a zero-delay drain on its timer at first submission), so
a pipelined pool and a serial pool produce byte-equal ledger and state
roots for any input stream — the tier-1 A/B in tests/test_pipeline.py
holds that under the randomized adversarial columnar harness.

Backpressure: the parse queue is bounded (``Config.PIPELINE_QUEUE_
DEPTH``); a full queue blocks the submitting side until the worker
catches up, and the queue depth folds into the ``BACKLOG_DEPTH`` gauge
the PR-16 gateway admission ladder sheds on — pressure propagates to
the front door instead of growing an unbounded buffer. Per-stage drain
hooks run on view change and catchup start so no stale parse job
straddles a protocol epoch.

Serial fallback, the step-down philosophy of every device seam: the
pipeline is gated by ``Config.PIPELINE_ENABLED`` (default off), and a
dead worker thread degrades to inline parsing at the drain site — the
node slows down, it never wedges.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional

from plenum_tpu.observability.telemetry import TM, NullTelemetryHub
from plenum_tpu.observability.tracing import CAT_3PC, NullTracer
from plenum_tpu.runtime.sanitizer import HandoffToken

logger = logging.getLogger(__name__)

# auto worker sizing cap: beyond a few workers the prod thread is the
# bottleneck again and extra threads only add scheduler noise
_AUTO_WORKER_CAP = 4

_STOP = object()


def resolve_workers(configured: Optional[int] = None,
                    fallback: Optional[int] = None) -> int:
    """The single worker-sizing rule (Config.PIPELINE_WORKERS): an
    explicit value wins; None = ``fallback`` when the caller has a
    structural reason for one (the verify daemon's serialize-by-one
    floor), else auto = cores−1, capped, floor 1."""
    if configured is not None:
        return max(1, int(configured))
    if fallback is not None:
        return max(1, int(fallback))
    cores = os.cpu_count() or 1
    return max(1, min(_AUTO_WORKER_CAP, cores - 1))


def resolve_queue_depth(configured: Optional[int] = None) -> int:
    return max(1, int(256 if configured is None else configured))


class BoundedQueue:
    """Bounded SPSC FIFO: one producer (the prod thread) blocks on a
    full queue — that IS the backpressure — and one consumer (the
    stage worker) blocks on an empty one. Items must be immutable or
    handed over whole (bytes, numpy views, frozen job records): the
    producer never touches an item again after ``put`` (plenum-lint
    PT004 checks the queue-crossing shapes)."""

    def __init__(self, depth: int):
        self._items: deque = deque()
        self._cond = threading.Condition()
        self._closed = False
        self.depth_max = int(depth)

    def __len__(self) -> int:
        return len(self._items)

    def put(self, item) -> None:
        with self._cond:
            while len(self._items) >= self.depth_max \
                    and not self._closed:
                self._cond.wait(0.05)
            self._items.append(item)
            self._cond.notify_all()

    def get(self, timeout: Optional[float] = None):
        """Next item, or None on close/timeout."""
        with self._cond:
            while not self._items and not self._closed:
                if not self._cond.wait(timeout):
                    return None
            if not self._items:
                return None
            item = self._items.popleft()
            self._cond.notify_all()
            return item

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()


class PipelineJob:
    """One unit crossing the stage boundary. ``work`` (or None for a
    passthrough) runs on the worker thread; ``result``/``error`` are
    written by exactly one side before ``done`` is set, then only read
    — the handoff is the Event, never shared mutation."""

    __slots__ = ("work", "msg", "frm", "result", "error", "done",
                 "enq_perf", "token")

    def __init__(self, work: Optional[Callable], msg, frm):
        self.work = work
        self.msg = msg
        self.frm = frm
        self.result = None
        self.error = None
        self.done = threading.Event()
        self.enq_perf = time.perf_counter()
        # sanitizer handoff token (None when the sanitizer is off):
        # released/acquired at each queue crossing so an out-of-turn
        # touch raises instead of racing
        self.token = None
        if work is None:
            self.done.set()

    def run(self) -> None:
        try:
            self.result = self.work()
        except Exception as e:           # delivered to the prod thread
            self.error = e
        # hand the payload back BEFORE done is observable, so the prod
        # thread can never win the race against its own re-acquire
        if self.token is not None:
            self.token.release("prod")
        self.done.set()


class PrescreenCache:
    """Positive-only ed25519 verdict cache, written by the pre-screen
    worker and read by the prod thread's authenticator. Keyed on the
    EXACT (signing bytes, signature, verkey) triple the authenticator
    would verify, so a hit can only ever skip a verification that was
    bound to succeed — a rotated verkey in domain state changes the
    triple and misses, and a miss (or any worker failure) falls through
    to the full prod-thread path. Filter, not authority: observable
    outcomes are byte-identical with the cache on or off."""

    def __init__(self, max_entries: int = 8192):
        self._hits: dict = {}
        self._max = int(max_entries)
        self._lock = threading.Lock()

    def add(self, ser: bytes, sig: bytes, vk: bytes) -> None:
        with self._lock:
            if len(self._hits) >= self._max:
                # the _raw_cache precedent: wholesale clear beats LRU
                # bookkeeping on a cache where misses only cost a
                # scalar verify
                self._hits.clear()
            self._hits[(bytes(ser), bytes(sig), bytes(vk))] = True

    def check(self, item) -> bool:
        """(ser, sig, vk) triple → True only on a cached positive."""
        try:
            ser, sig, vk = item
            key = (bytes(ser), bytes(sig), bytes(vk))
        except Exception:
            return False
        with self._lock:
            return self._hits.get(key, False)

    def __len__(self) -> int:
        return len(self._hits)


class NodePipeline:
    """The node's stage/queue runtime: one parse/pre-screen worker fed
    through a bounded SPSC queue, a FIFO of jobs awaiting prod-thread
    delivery, and a small thread pool for execution fan-out.

    ``deliver(job)`` — injected by the node — runs on the prod thread
    for every job, in submission order; it owns every consensus side
    effect. The worker side only ever executes ``job.work()``."""

    def __init__(self, deliver: Callable, config=None, telemetry=None,
                 tracer=None, name: str = "", sanitizer=None):
        self.name = name
        self._deliver = deliver
        self.sanitizer = sanitizer
        self._tm = telemetry if telemetry is not None \
            else NullTelemetryHub()
        self.tracer = tracer if tracer is not None else NullTracer()
        workers = resolve_workers(
            getattr(config, "PIPELINE_WORKERS", None))
        depth = resolve_queue_depth(
            getattr(config, "PIPELINE_QUEUE_DEPTH", None))
        self.workers = workers
        # prod-owned FIFO of all jobs (parse + passthrough) in arrival
        # order — the drain order IS the serial path's processing order
        self._jobs: deque = deque()
        # worker-fed subset: only jobs with work cross this queue
        self._in = BoundedQueue(depth)
        self._draining = False
        self._exec_pool: Optional[ThreadPoolExecutor] = None
        if workers > 1:
            self._exec_pool = ThreadPoolExecutor(
                max_workers=workers,
                thread_name_prefix="%s-pipe-exec" % (name or "node"))
        self._worker = threading.Thread(
            target=self._worker_loop, daemon=True,
            name="%s-pipe-parse" % (name or "node"))
        self._worker.start()

    # ------------------------------------------------------ submission

    def submit(self, work: Optional[Callable], msg, frm) -> None:
        """Enqueue one inbound message. ``work`` runs on the worker
        (wire parse + pre-screen); None marks a passthrough that the
        drain hands straight to the serial delivery path. Blocks when
        the parse queue is at depth — backpressure, surfaced to the
        admission ladder through the depth gauge."""
        job = PipelineJob(work, msg, frm)
        self._jobs.append(job)
        if work is not None:
            if self._worker.is_alive():
                if self.sanitizer is not None:
                    job.token = HandoffToken(self.sanitizer,
                                             "pipeline parse job")
                    job.token.release("worker")
                self._in.put(job)
            else:
                # dead-worker step-down: parse inline on the submitter
                job.run()
        self._tm.gauge(TM.PIPELINE_QUEUE_DEPTH, len(self._jobs))

    @property
    def depth(self) -> int:
        """Jobs awaiting prod-thread delivery (the backpressure signal
        folded into BACKLOG_DEPTH for the admission ladder)."""
        return len(self._jobs)

    # ----------------------------------------------------------- drain

    def drain(self) -> int:
        """Deliver every queued job on the calling (prod) thread, in
        submission order. Blocking on an unfinished parse is charged to
        the ``queue_wait`` budget stage — handoff latency stays
        attributable instead of smearing into 3PC. Re-entrant calls
        (a delivered job triggering a view-change drain hook) are
        no-ops: the outer drain already owns the queue."""
        if self._draining:
            return 0
        self._draining = True
        delivered = 0
        try:
            while self._jobs:
                job = self._jobs[0]
                if not job.done.is_set():
                    with self.tracer.span("queue_wait", CAT_3PC):
                        while not job.done.wait(0.1):
                            if not self._worker.is_alive():
                                # serial step-down: ownership collapses
                                # back to the single surviving thread —
                                # no handoff left to discipline
                                job.token = None
                                job.run()
                                break
                self._jobs.popleft()
                if job.token is not None:
                    job.token.acquire("prod")
                self._tm.observe(
                    TM.PIPELINE_QUEUE_WAIT_MS,
                    (time.perf_counter() - job.enq_perf) * 1e3)
                self._deliver(job)
                delivered += 1
        finally:
            self._draining = False
        return delivered

    # ------------------------------------------------- execution lanes

    def exec_map(self, fn: Callable, items: List) -> List:
        """Order-preserving map across the execution pool — the
        fan-out seam ``flush_states_merged`` uses for independent
        per-state structural merges. Falls back to an inline loop for
        degenerate sizes or a serial pool."""
        items = list(items)
        if self._exec_pool is None or len(items) <= 1:
            return [fn(x) for x in items]
        self._tm.gauge(TM.PIPELINE_EXEC_QUEUE_DEPTH, len(items))
        try:
            return list(self._exec_pool.map(fn, items))
        finally:
            self._tm.gauge(TM.PIPELINE_EXEC_QUEUE_DEPTH, 0)

    # ------------------------------------------------------- lifecycle

    def stop(self) -> None:
        self._in.put(_STOP)
        self._in.close()
        if self._exec_pool is not None:
            self._exec_pool.shutdown(wait=False)

    # ----------------------------------------------------- worker side

    def _worker_loop(self) -> None:
        if self.sanitizer is not None:
            # this thread IS the worker region for the node's pins
            self.sanitizer.bind_region("worker")
        while True:
            job = self._in.get()
            if job is None or job is _STOP:
                return
            if job.token is not None:
                job.token.acquire("worker")
            t0 = time.perf_counter()
            job.run()               # releases the token back to prod
            self._tm.observe(TM.PIPELINE_PARSE_MS,
                             (time.perf_counter() - t0) * 1e3)
