"""Runtime ownership sanitizer — region pins and handoff tokens.

The static half of the ownership story is plenum-lint's thread-region
analysis (PT016/PT017): every function gets a set of executing regions
(prod / worker / daemon), and consensus-named state may only be
written from the prod region. This module is the runtime twin: the
same contract, enforced at the same seams, on live threads — so every
e2e test that runs with the sanitizer on doubles as a race check.

Three pieces:

* :class:`OwnershipSanitizer` — label-based region pins. A node binds
  its thread identities to region names (``bind_region("prod")``),
  pins consensus-critical objects to regions by label
  (``pin("vote stores", "prod")``), and guarded code calls
  ``check(label)`` at its intake seams. A check on an unpinned label
  is a no-op (the exact ``_owner_thread is None`` behavior of the old
  ``OrderingService`` guard this generalizes); a check from the wrong
  thread raises :class:`RegionViolation` naming the owning region and
  both thread ids, with the flight-recorder timeline dumped first
  (the Scenario invariant-dump convention).
* :class:`HandoffToken` — queue-boundary ownership transfer. The
  producer releases the token toward the consuming region before
  ``put``; the consumer acquires it after ``get``. Acquiring a token
  that was not released to your region means a payload was touched
  out of turn — the runtime shape of PT017's handoff discipline.
* :data:`CONSENSUS_PINS` — the canonical label → attribute-fragment
  table. Every pinned label names state in the static analysis's
  consensus-owned vocabulary (pt004/PT016 ``CONSENSUS_ATTRS``); the
  agreement test in tests/test_sanitizer.py pins that correspondence
  so the static and runtime halves cannot drift.

Opt-in: ``Config.SANITIZER_ENABLED`` (tri-state, None = environment
decides) or ``PLENUM_TPU_SANITIZE=1``. The sim-pool test fixtures set
the environment flag suite-wide; production default is off.
"""
from __future__ import annotations

import logging
import os
import tempfile
import threading
from typing import Dict, Optional, Tuple

logger = logging.getLogger(__name__)

# label -> consensus-attribute fragments (the PT004/PT016 vocabulary)
# the pinned object's state lives under. Static/runtime agreement:
# every fragment here MUST appear in analysis.rules.pt004_threads.
# CONSENSUS_ATTRS — tests/test_sanitizer.py enforces the subset — and
# a PT016-clean seam outside this table needs no pin at all.
CONSENSUS_PINS: Dict[str, Tuple[str, ...]] = {
    "3PC intake": ("prepare", "commit", "view_no", "last_ordered"),
    "vote stores": ("prepare", "commit"),
    "stashes": ("stash",),
    "state pending buffers": ("state_root", "ledger"),
    "lane planner": ("request_queue", "requestqueue"),
}

_dump_seq = [0]


def sanitizer_enabled(config=None) -> bool:
    """The one opt-in rule: an explicit ``Config.SANITIZER_ENABLED``
    (True/False) wins; None defers to ``PLENUM_TPU_SANITIZE`` (the
    test fixtures' suite-wide switch); absent both → off."""
    val = getattr(config, "SANITIZER_ENABLED", None) \
        if config is not None else None
    if val is not None:
        return bool(val)
    env = os.environ.get("PLENUM_TPU_SANITIZE")
    return env not in (None, "", "0", "false")


class RegionViolation(RuntimeError):
    """Consensus-owned state touched from the wrong thread region.
    A RuntimeError subclass so the original ``bind_owner_thread``
    contract (and every test pinned to it) holds unchanged."""


class OwnershipSanitizer:
    """Region pins for consensus-critical objects.

    Thread-safety of the sanitizer itself: bindings and pins are
    written during single-threaded wiring (node construction, worker
    startup) and only read afterwards; ``check`` is a dict lookup plus
    an int compare, cheap enough for vote-counting hot paths (the
    sanitizer_overhead bench gates it under 2%)."""

    def __init__(self, name: str = "", tracer=None):
        self.name = name
        self.tracer = tracer
        self._regions: Dict[str, int] = {}   # region -> thread ident
        self._pins: Dict[str, str] = {}      # label  -> owning region

    # ------------------------------------------------------------ wiring

    def bind_region(self, region: str, ident: Optional[int] = None
                    ) -> None:
        """Declare which thread IS a region (None = current thread)."""
        self._regions[region] = int(
            threading.get_ident() if ident is None else ident)

    def pin(self, label: str, region: str) -> None:
        """Pin a labeled object to its owning region."""
        self._pins[label] = region

    def pinned(self, label: str) -> Optional[str]:
        return self._pins.get(label)

    @property
    def pins(self) -> Dict[str, str]:
        return dict(self._pins)

    # ------------------------------------------------------------ checks

    def check(self, label: str) -> None:
        """Assert the calling thread owns ``label``. Unpinned labels
        and unbound regions pass — enabling the sanitizer never
        changes behavior until a pin says otherwise."""
        region = self._pins.get(label)
        if region is None:
            return
        owner = self._regions.get(region)
        if owner is None:
            return
        current = threading.get_ident()
        if current != owner:
            self.violation(label, region, owner, current)

    def violation(self, label: str, region: str, owner: int,
                  current: int) -> None:
        """Raise with owning region + both threads named, flight
        recorder dumped first. The message prefix is byte-identical to
        the original OrderingService guard for label='3PC intake',
        region='prod' — one implementation, same contract."""
        msg = ("%s off the %s thread: consensus state is owned by "
               "thread %d, called from %d" % (label, region, owner,
                                              current))
        path = self.dump_trace()
        if path:
            logger.error("ownership violation — flight-recorder "
                         "timeline dumped to %s (load in "
                         "ui.perfetto.dev)", path)
            msg += " [flight recorder: %s]" % path
        raise RegionViolation(msg)

    def dump_trace(self, path: Optional[str] = None,
                   tag: str = "sanitizer_violation") -> Optional[str]:
        """Write this node's tracer ring buffer as a Chrome trace —
        the Scenario invariant-dump convention, scoped to one node.
        → path, or None when nothing is traced."""
        tracer = self.tracer
        if tracer is None or not getattr(tracer, "enabled", False):
            return None
        from plenum_tpu.observability.export import export_chrome_trace
        if path is None:
            out_dir = os.environ.get("PLENUM_TPU_TRACE_DIR") \
                or tempfile.gettempdir()
            _dump_seq[0] += 1
            path = os.path.join(
                out_dir, "%s_trace_%d_%d.json"
                % (tag, os.getpid(), _dump_seq[0]))
        try:
            return export_chrome_trace([tracer], path)
        except OSError:
            logger.warning("could not write flight-recorder trace to "
                           "%s", path, exc_info=True)
            return None


class HandoffToken:
    """Ownership transfer across one queue boundary.

    States: held by a region ("prod"), or in flight toward one
    (("in-flight", "worker")). ``release(to)`` is called by the
    producer just before ``put``; ``acquire(region)`` by the consumer
    right after ``get``. Acquiring from the wrong state means the
    payload crossed the boundary out of turn. The sanctioned serial
    step-down (dead worker, prod runs the job inline) drops the token
    instead: with one thread left there is no handoff to discipline."""

    __slots__ = ("sanitizer", "label", "state")

    def __init__(self, sanitizer: OwnershipSanitizer, label: str,
                 holder: str = "prod"):
        self.sanitizer = sanitizer
        self.label = label
        self.state = holder

    def release(self, to_region: str) -> None:
        self.state = ("in-flight", to_region)

    def acquire(self, region: str) -> None:
        if self.state != ("in-flight", region):
            owner = self.state[1] if isinstance(self.state, tuple) \
                else self.state
            san = self.sanitizer
            san.violation(
                "handoff token %r" % self.label, owner,
                san._regions.get(owner, -1), threading.get_ident())
        # cross-region by design, ordered without a lock: release
        # happens-before put() and the consumer's acquire happens-after
        # get() (or after done.set() on the way back) — the queue's own
        # synchronization is the fence
        self.state = region  # plenum-lint: disable=PT016
