"""Tx/Rx channel abstraction used by catchup services.

Reference: plenum/common/channel.py:13 (TxChannel), :23 (RxChannel),
:54 (create_direct_channel). Direct channels dispatch synchronously; a
queued service drains on prod (QueuedChannelService reference channel.py:71).
"""
from abc import ABC, abstractmethod
from collections import deque
from typing import Any, Callable, Tuple


class TxChannel(ABC):
    @abstractmethod
    def put_nowait(self, msg: Any) -> None:
        ...


class RxChannel(ABC):
    @abstractmethod
    def set_handler(self, handler: Callable[[Any], None]) -> None:
        ...


class _DirectRouter(RxChannel):
    def __init__(self):
        self._handlers = []

    def set_handler(self, handler):
        self._handlers.append(handler)

    def _dispatch(self, msg):
        for h in self._handlers:
            h(msg)


class _DirectTx(TxChannel):
    def __init__(self, router: _DirectRouter):
        self._router = router

    def put_nowait(self, msg):
        self._router._dispatch(msg)


def create_direct_channel() -> Tuple[TxChannel, RxChannel]:
    router = _DirectRouter()
    return _DirectTx(router), router


class QueuedChannelService:
    """Buffers messages; `service()` drains them into handlers (call from
    the prod loop)."""

    def __init__(self):
        self._router = _DirectRouter()
        self._queue = deque()

    @property
    def tx(self) -> TxChannel:
        svc = self
        class _Tx(TxChannel):
            def put_nowait(self, msg):
                svc._queue.append(msg)
        return _Tx()

    @property
    def rx(self) -> RxChannel:
        return self._router

    def service(self, limit: int = None) -> int:
        count = 0
        while self._queue and (limit is None or count < limit):
            self._router._dispatch(self._queue.popleft())
            count += 1
        return count
