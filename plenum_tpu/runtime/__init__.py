from plenum_tpu.runtime.timer import TimerService, QueueTimer, RepeatingTimer  # noqa: F401
from plenum_tpu.runtime.bus import InternalBus, ExternalBus, Router  # noqa: F401
from plenum_tpu.runtime.stashing_router import (  # noqa: F401
    StashingRouter, PROCESS, DISCARD, STASH,
)
