"""Prodable lifecycle + Motor state machine.

Reference: stp_core/loop/looper.py:21 (Prodable), stp_core/loop/motor.py:10
(Motor), stp_core/loop/startable.py (Status).
"""
from abc import ABC, abstractmethod
from enum import IntEnum


class Status(IntEnum):
    stopped = 0
    starting = 1
    started = 2
    started_hungry = 3
    stopping = 4

    @classmethod
    def going(cls):
        return (cls.starting, cls.started, cls.started_hungry)


class Prodable(ABC):
    """Anything the Looper services every tick."""

    @property
    @abstractmethod
    def name(self) -> str:
        ...

    @abstractmethod
    async def prod(self, limit: int = None) -> int:
        """Do up to `limit` units of work; return units done."""

    @abstractmethod
    def start(self, loop) -> None:
        ...

    @abstractmethod
    def stop(self) -> None:
        ...


class Motor(Prodable):
    """Prodable with a Status state machine (reference motor.py:10)."""

    def __init__(self):
        self._status = Status.stopped

    def get_status(self) -> Status:
        return self._status

    def set_status(self, value: Status):
        self._status = value

    status = property(fget=get_status, fset=set_status)

    def isReady(self) -> bool:
        return self.status == Status.started

    def isGoing(self) -> bool:
        return self.status in Status.going()

    def start(self, loop) -> None:
        old = self._status
        self._status = Status.starting
        self.onStarting(old)

    def stop(self, *args, **kwargs):
        if self.status in (Status.stopping, Status.stopped):
            return
        self._status = Status.stopping
        self.onStopping(*args, **kwargs)
        self._status = Status.stopped

    def onStarting(self, old_status: Status):
        pass

    def onStopping(self, *args, **kwargs):
        pass

    async def prod(self, limit: int = None) -> int:
        return 0
