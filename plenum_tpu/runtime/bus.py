"""Typed pub-sub buses.

Reference: plenum/common/event_bus.py:6 (InternalBus), :11 (ExternalBus);
base Router plenum/common/router.py:5. All intra-replica coordination is
messages on an InternalBus; all network sends go through an ExternalBus whose
send handler is the transport (or the SimNetwork in tests).
"""
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Type


def _unwrap_three_pc_batch(message) -> Optional[list]:
    """Inner typed messages of a coalesced envelope — THREE_PC_BATCH or
    a flat-wire FLAT_WIRE envelope — or None when `message` is neither.
    Lazy import: the runtime layer must stay importable without the
    message schema module loaded. Dict entries (a real-transport typed
    envelope) are reconstructed through the message factory and flat
    payloads re-materialized through the codec so the tap always sees
    typed per-message granularity; an unreconstructable entry is
    dropped here exactly as the node's own intake would drop it."""
    from plenum_tpu.common.messages.node_messages import (
        FlatBatch, ThreePCBatch)
    if isinstance(message, FlatBatch):
        from plenum_tpu.common.serializers import flat_wire
        # malformed / all-entries-invalid envelopes pass through WHOLE
        # (the receiving node owns that judgement) — the policy is
        # single-sourced next to the codec
        return flat_wire.unwrap_for_tap(message.payload)
    if not isinstance(message, ThreePCBatch):
        return None
    from plenum_tpu.common.messages.message_factory import (
        node_message_factory)
    out = []
    for entry in message.messages:
        if isinstance(entry, dict):
            try:
                entry = node_message_factory.get_instance(**entry)
            except Exception:
                continue
        out.append(entry)
    return out


class Router:
    """Maps message type → list of handlers; dispatch is synchronous."""

    def __init__(self):
        self._handlers: Dict[Type, List[Callable]] = {}

    def subscribe(self, message_type: Type, handler: Callable) -> Callable:
        self._handlers.setdefault(message_type, []).append(handler)
        def unsubscribe():
            self._handlers[message_type].remove(handler)
        return unsubscribe

    def handlers(self, message_type: Type) -> List[Callable]:
        return self._handlers.get(message_type, [])


class InternalBus(Router):
    def send(self, message: Any, *args):
        result = None
        for handler in self.handlers(type(message)):
            result = handler(message, *args)
        return result


class ExternalBus(Router):
    """Network-facing bus: `send` goes out via the transport handler;
    `process_incoming` dispatches received messages with their sender name.
    Tracks connected peers (reference event_bus.py:11).

    An optional TAP is the single interception seam for fault-injection
    tooling (testing/adversary): it sees every send/receive and may
    rewrite, duplicate, or drop traffic. The bus itself carries no
    behavior — it only routes what the tap returns."""

    class Connected(NamedTuple):
        pass

    class Disconnected(NamedTuple):
        pass

    def __init__(self, send_handler: Callable[[Any, Optional[Any]], None]):
        super().__init__()
        self._send_handler = send_handler
        self._connecteds = set()
        self._tap = None

    @property
    def connecteds(self) -> set:
        return self._connecteds

    def set_tap(self, tap) -> None:
        """Install a send/recv tap: an object with
        ``on_send(message, dst) -> Optional[List[(message, dst)]]`` and
        ``on_incoming(message, frm) -> Optional[List[(message, frm)]]``.
        ``None`` means pass-through; a list replaces the original
        (empty list = drop). Only one tap per bus — chaining belongs in
        the tap implementation, not here."""
        if self._tap is not None and tap is not None:
            raise ValueError("tap already installed")
        self._tap = tap

    def clear_tap(self) -> None:
        self._tap = None

    @property
    def has_tap(self) -> bool:
        """True while a fault-injection tap is installed — coalescing
        senders (ThreePCOutbox) fall back to per-message sends so the
        tap keeps seeing the per-type wire granularity its behaviors
        match on."""
        return self._tap is not None

    def send(self, message: Any, dst=None) -> None:
        """dst None = broadcast; str = single peer; list = multiple peers."""
        if self._tap is not None:
            routed = self._tap.on_send(message, dst)
            if routed is not None:
                for m, d in routed:
                    self._send_handler(m, d)
                return
        self._send_handler(message, dst)

    def send_raw(self, message: Any, dst=None) -> None:
        """Send bypassing the tap — used by the tap itself to release
        held/rewritten traffic without re-entering interception."""
        self._send_handler(message, dst)

    def process_incoming(self, message: Any, frm: str):
        if self._tap is not None and not isinstance(
                message, (self.Connected, self.Disconnected)):
            # coalesced 3PC envelopes from honest (untapped) senders
            # unwrap BEFORE the tap: behaviors match on per-type 3PC
            # votes, and an envelope passed through whole would smuggle
            # every inner vote past them — the receive-side mirror of
            # the ThreePCOutbox per-message degrade on the send side
            inner = _unwrap_three_pc_batch(message)
            if inner is not None:
                result = None
                for entry in inner:
                    result = self.process_incoming(entry, frm)
                return result
            routed = self._tap.on_incoming(message, frm)
            if routed is not None:
                result = None
                for m, f in routed:
                    result = self._dispatch(m, f)
                return result
        return self._dispatch(message, frm)

    def _dispatch(self, message: Any, frm: str):
        result = None
        for handler in self.handlers(type(message)):
            result = handler(message, frm)
        return result

    def update_connecteds(self, connecteds: set) -> None:
        new = connecteds - self._connecteds
        gone = self._connecteds - connecteds
        self._connecteds = set(connecteds)
        for name in new:
            self.process_incoming(self.Connected(), name)
        for name in gone:
            self.process_incoming(self.Disconnected(), name)
