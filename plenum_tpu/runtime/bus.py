"""Typed pub-sub buses.

Reference: plenum/common/event_bus.py:6 (InternalBus), :11 (ExternalBus);
base Router plenum/common/router.py:5. All intra-replica coordination is
messages on an InternalBus; all network sends go through an ExternalBus whose
send handler is the transport (or the SimNetwork in tests).
"""
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Type


class Router:
    """Maps message type → list of handlers; dispatch is synchronous."""

    def __init__(self):
        self._handlers: Dict[Type, List[Callable]] = {}

    def subscribe(self, message_type: Type, handler: Callable) -> Callable:
        self._handlers.setdefault(message_type, []).append(handler)
        def unsubscribe():
            self._handlers[message_type].remove(handler)
        return unsubscribe

    def handlers(self, message_type: Type) -> List[Callable]:
        return self._handlers.get(message_type, [])


class InternalBus(Router):
    def send(self, message: Any, *args):
        result = None
        for handler in self.handlers(type(message)):
            result = handler(message, *args)
        return result


class ExternalBus(Router):
    """Network-facing bus: `send` goes out via the transport handler;
    `process_incoming` dispatches received messages with their sender name.
    Tracks connected peers (reference event_bus.py:11)."""

    class Connected(NamedTuple):
        pass

    class Disconnected(NamedTuple):
        pass

    def __init__(self, send_handler: Callable[[Any, Optional[Any]], None]):
        super().__init__()
        self._send_handler = send_handler
        self._connecteds = set()

    @property
    def connecteds(self) -> set:
        return self._connecteds

    def send(self, message: Any, dst=None) -> None:
        """dst None = broadcast; str = single peer; list = multiple peers."""
        self._send_handler(message, dst)

    def process_incoming(self, message: Any, frm: str):
        result = None
        for handler in self.handlers(type(message)):
            result = handler(message, frm)
        return result

    def update_connecteds(self, connecteds: set) -> None:
        new = connecteds - self._connecteds
        gone = self._connecteds - connecteds
        self._connecteds = set(connecteds)
        for name in new:
            self.process_incoming(self.Connected(), name)
        for name in gone:
            self.process_incoming(self.Disconnected(), name)
