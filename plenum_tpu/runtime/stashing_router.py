"""StashingRouter: route messages through handlers that return verdicts;
stash-and-replay on state change.

Reference: plenum/common/stashing_router.py:93 (StashingRouter),
:43 (UnsortedStash), :69 (SortedStash). A handler returns
(PROCESS|DISCARD|STASH, reason); stashed messages are replayed when the
owner signals the relevant state change via process_all_stashed/
process_stashed_until_first_restash.
"""
import logging
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple, Type

try:
    from sortedcontainers import SortedList
except ImportError:            # soft dep: stdlib fallback
    from plenum_tpu.utils.sorted_fallback import SortedList

logger = logging.getLogger(__name__)

PROCESS = 0
DISCARD = 1
STASH = 2

# Verdict helper: handlers return None (== PROCESS) or (code, reason)
Verdict = Optional[Tuple[int, Any]]


class UnsortedStash:
    def __init__(self, limit: int):
        self._limit = limit
        self._data = deque()

    def push(self, item) -> bool:
        if len(self._data) >= self._limit:
            return False
        self._data.append(item)
        return True

    def pop(self):
        return self._data.popleft() if self._data else None

    def __len__(self):
        return len(self._data)

    def __iter__(self):
        return iter(self._data)


class SortedStash:
    def __init__(self, limit: int, key: Callable):
        self._limit = limit
        self._key = key
        self._data = SortedList(key=lambda item: key(*item))

    def push(self, item) -> bool:
        if len(self._data) >= self._limit:
            return False
        self._data.add(item)
        return True

    def pop(self):
        return self._data.pop(0) if self._data else None

    def __len__(self):
        return len(self._data)

    def __iter__(self):
        return iter(self._data)


class StashingRouter:
    def __init__(self, limit: int, buses, unstash_handler: Callable = None,
                 sort_key: Callable = None):
        """buses: iterable of Router-like objects (InternalBus/ExternalBus) to
        subscribe on. sort_key(msg, *extra) orders replay within a stash."""
        self._limit = limit
        self._buses = list(buses)
        self._unstash_handler = unstash_handler
        self._sort_key = sort_key
        self._handlers: Dict[Type, Callable] = {}
        self._stashes: Dict[Tuple[Type, int], Any] = {}
        self._unsubscribers = []

    def subscribe(self, message_type: Type, handler: Callable):
        self._handlers[message_type] = handler
        for bus in self._buses:
            self._unsubscribers.append(
                bus.subscribe(message_type, self._create_bus_handler(handler)))

    def unsubscribe_all(self):
        """Detach every bus subscription (backup replica removal)."""
        for unsub in self._unsubscribers:
            try:
                unsub()
            except ValueError:
                pass
        self._unsubscribers = []

    def _create_bus_handler(self, handler):
        def bus_handler(message, *args):
            return self._process(handler, message, *args)
        return bus_handler

    def _process(self, handler, message, *args) -> bool:
        verdict = handler(message, *args)
        if verdict is None:
            return True
        code, reason = verdict
        if code == PROCESS:
            return True
        if code == DISCARD:
            self.discard(message, reason)
            return True
        self._stash(code, handler, message, *args)
        return False

    def _stash(self, code, handler, message, *args):
        key = (type(message), code)
        stash = self._stashes.get(key)
        if stash is None:
            if self._sort_key is not None:
                stash = SortedStash(self._limit, self._sort_key)
            else:
                stash = UnsortedStash(self._limit)
            self._stashes[key] = stash
        if not stash.push((message, *args)):
            logger.warning("Cannot stash %s with code %s: stash is full "
                           "(limit %s) — dropping", type(message).__name__,
                           code, self._limit)
            self.discard(message, "stash overflow")

    def discard(self, message, reason):
        pass  # subclass/metric hook

    # ------------------------------------------------- batch-intake seams

    def stash(self, code: int, message, *args):
        """Stash one message directly under `code` WITHOUT running its
        handler first — the columnar 3PC intake decides whole-batch
        verdicts up front and routes the must-wait items here; replay
        goes through the normal subscribed per-message handler."""
        handler = self._handlers.get(type(message))
        self._stash(code, handler, message, *args)

    def route(self, message, *args) -> bool:
        """Run the subscribed handler for `message` with full verdict
        processing (stash/discard), exactly as a bus delivery would —
        used by batch intake paths to feed individual messages through
        the same machinery as singles. → True if processed/discarded."""
        handler = self._handlers.get(type(message))
        if handler is None:
            return True
        return self._process(handler, message, *args)

    def stash_size(self, code: int = None) -> int:
        return sum(len(s) for (t, c), s in self._stashes.items()
                   if code is None or c == code)

    def process_all_stashed(self, code: int = None):
        """Replay all stashed messages (for given stash code); messages that
        stash again go back (possibly under a different code)."""
        for (t, c), stash in list(self._stashes.items()):
            if code is not None and c != code:
                continue
            items = []
            while len(stash):
                items.append(stash.pop())
            for item in items:
                self._resolve_and_process(item)

    def process_stashed_until_first_restash(self, code: int = None):
        for (t, c), stash in list(self._stashes.items()):
            if code is not None and c != code:
                continue
            while len(stash):
                item = stash.pop()
                if not self._resolve_and_process(item):
                    break

    def _resolve_and_process(self, item) -> bool:
        message, *args = item
        # an unstash_handler REPLACES processing — it re-routes the message
        # into the owner's inbox for handling on the next tick (reference
        # stashing_router.py:193-197); the two paths are mutually exclusive
        if self._unstash_handler is not None:
            self._unstash_handler(message, *args)
            return True
        handler = self._handlers.get(type(message))
        if handler is None:
            return True
        return self._process(handler, message, *args)
