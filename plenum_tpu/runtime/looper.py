"""Looper: owns the asyncio loop and repeatedly prods registered Prodables.

Reference: stp_core/loop/looper.py:64 (Looper), :142 (prodAllOnce),
:204 (runOnceNicely), :222 (runForever). The entire node is cooperative
multitasking driven from here — no threads (SURVEY.md §1 execution model).
"""
import asyncio
import logging
import signal
import sys
import time
from typing import List, Optional

from plenum_tpu.runtime.motor import Prodable

logger = logging.getLogger(__name__)


class Looper:
    def __init__(self, prodables: Optional[List[Prodable]] = None,
                 loop: Optional[asyncio.AbstractEventLoop] = None,
                 autoStart: bool = True):
        self.prodables: List[Prodable] = []
        self.loop = loop or self._new_loop()
        self.protected_loop = loop is not None
        self.running = True
        if autoStart:
            for p in (prodables or []):
                self.add(p)
        else:
            for p in (prodables or []):
                if p.name in [q.name for q in self.prodables]:
                    raise RuntimeError(
                        "Prodable {} already added".format(p.name))
                self.prodables.append(p)
        # larger sleep when nothing happened, to not spin the CPU
        # (reference looper.py:200-218)
        self._min_sleep = 0.0
        self._max_sleep = 0.01
        self.runFut = self.loop.create_task(self.runForever()) if autoStart else None
        if not self.protected_loop and sys.platform != 'win32':
            try:
                self.loop.add_signal_handler(signal.SIGTERM, self._handle_sig)
            except (NotImplementedError, RuntimeError):
                pass

    def _new_loop(self):
        try:
            loop = asyncio.get_event_loop()
            if loop.is_closed():
                raise RuntimeError("closed")
            return loop
        except RuntimeError:
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            return loop

    def _handle_sig(self):
        self.running = False

    def add(self, prodable: Prodable) -> None:
        if prodable.name in [p.name for p in self.prodables]:
            raise RuntimeError("Prodable {} already added".format(prodable.name))
        self.prodables.append(prodable)
        prodable.start(self.loop)

    def removeProdable(self, prodable: Prodable) -> None:
        if prodable in self.prodables:
            self.prodables.remove(prodable)
            prodable.stop()

    async def prodAllOnce(self) -> int:
        """One scheduling pass over all prodables (reference looper.py:142)."""
        count = 0
        for p in list(self.prodables):
            count += await p.prod()
        return count

    async def runOnceNicely(self) -> int:
        count = await self.prodAllOnce()
        sleep = self._min_sleep if count > 0 else self._max_sleep
        await asyncio.sleep(sleep)
        return count

    async def runFor(self, seconds: float):
        end = time.perf_counter() + seconds
        while time.perf_counter() < end:
            await self.runOnceNicely()

    async def runForever(self):
        while self.running:
            await self.runOnceNicely()

    def run(self, *coros):
        """Run coroutines to completion while servicing prodables."""
        async def wrapper():
            results = []
            for coro in coros:
                results.append(await coro)
            return results[0] if len(results) == 1 else results
        if coros:
            return self.loop.run_until_complete(wrapper())
        return self.loop.run_until_complete(self.runForever())

    async def shutdown(self):
        self.running = False
        if self.runFut is not None:
            try:
                await self.runFut
            except asyncio.CancelledError:
                pass
        for p in self.prodables:
            p.stop()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.loop.run_until_complete(self.shutdown())
        if not self.protected_loop:
            self.loop.close()

    async def __aenter__(self):
        return self

    async def __aexit__(self, exc_type, exc, tb):
        await self.shutdown()
