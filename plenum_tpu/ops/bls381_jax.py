"""Batched BLS12-381 G1 aggregation on TPU (JAX).

The reference aggregates BLS signature shares one at a time through
Hyperledger Ursa (`crypto/bls/indy_crypto/bls_crypto_indy_crypto.py:99`,
`create_multi_sig`). This kernel aggregates MANY independent share-sets
per device dispatch — B jobs x n compressed signatures in, B aggregate
points out — so the ~150 ms tunnel round-trip amortizes over hundreds of
aggregations (the BASELINE.json "BLS aggregate n=4/25/100" configs).

TPU-first design (same recipe as ops/ed25519_jax.py, adapted to a
generic 381-bit prime):
 - Field arithmetic over Fq (q = BLS12-381 modulus) in radix 2^12:
   32 int32 limbs per element. Limb products are <= 2^24 and 32-column
   sums <= 2^29, so everything stays in native int32 on the VPU.
 - q has no pseudo-Mersenne structure, so reduction is MONTGOMERY:
   values live in the Montgomery domain (a*2^384 mod q) and `mont_mul`
   runs a 32-step radix-2^12 REDC inside the kernel. Entry/exit from
   the domain happens on device (mul by R^2 / by 1), so the host only
   does byte->limb bit-plumbing (vectorized numpy, no Python bigints).
 - Decompression (the per-signature cost that dominates the C scalar
   path at ~70 us/share) is batched: sqrt(x^3+4) is one fixed-exponent
   fori_loop over all B*n shares at once.
 - Point addition uses the Renes-Costello-Batina COMPLETE formulas for
   a=0 short-Weierstrass curves (12M + 2*mul_b3): branchless, handles
   identity/doubling/inverses uniformly — no data-dependent control
   flow, exactly what XLA wants. (E(Fq) has odd order, so the formulas
   are complete on the whole curve.)
 - Aggregation is a log2(n) tree reduction over the share axis; the
   batch axis is embarrassingly parallel, so `aggregate_dispatch`
   shards the job axis across the device mesh through the production
   dispatcher (ops/mesh.py) with zero collectives — job batches at or
   above `Config.MESH_SHARD_MIN` on a multi-chip host are identity-
   padded per device and launched as one SPMD program.

The scalar/native paths stay authoritative for single aggregates (a
device dispatch costs more than one 100-share aggregate on CPU). This
kernel is currently exercised by bench.py, the multichip dryrun
(__graft_entry__) and tests only — the ordering path aggregates through
crypto/bls_ops (native C / pure Python); wiring a queue-depth router
that batches concurrent ordering-path aggregations onto this kernel is
future work and NOT yet a production code path.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from plenum_tpu.observability import telemetry as _tmy
from plenum_tpu.ops import pow2_at_least

# ---------------------------------------------------------------- constants

NLIMB = 32
RADIX = 12
MASK = (1 << RADIX) - 1

Q = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R_MONT = 1 << (NLIMB * RADIX)          # 2^384
R2 = (R_MONT * R_MONT) % Q             # to-Montgomery factor
QPRIME = (-pow(Q, -1, 1 << RADIX)) % (1 << RADIX)  # -q^-1 mod 2^12
HALF = (Q - 1) // 2


def _int_to_limbs(v: int, n: int = NLIMB) -> np.ndarray:
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = v & MASK
        v >>= RADIX
    assert v == 0
    return out


def limbs_to_int(limbs) -> int:
    v = 0
    for i in reversed(range(len(limbs))):
        v = (v << RADIX) | int(limbs[i])
    return v


def _exp_bits(e: int) -> np.ndarray:
    return np.array([int(b) for b in bin(e)[2:]], dtype=np.int32)


_Q_L = _int_to_limbs(Q)
_2Q_L = _int_to_limbs(2 * Q)
_HALF_P1_L = _int_to_limbs(HALF + 1)
_R2_L = _int_to_limbs(R2)
_ONE_STD_L = _int_to_limbs(1)
_ONE_M_L = _int_to_limbs(R_MONT % Q)          # 1 in Montgomery form
_FOUR_M_L = _int_to_limbs(4 * R_MONT % Q)     # curve b=4, Montgomery
_B3_M_L = _int_to_limbs(12 * R_MONT % Q)      # 3b = 12, Montgomery
_SQRT_BITS = _exp_bits((Q + 1) // 4)          # q = 3 mod 4 sqrt exponent

# Anti-diagonal scatter: flat outer-product index (i*32+j) -> column i+j.
# One [..,1024]x[1024,63] int32 matmul replaces 1024 unrolled MACs.
def _fold_matrix() -> np.ndarray:
    m = np.zeros((NLIMB * NLIMB, 2 * NLIMB - 1), dtype=np.int32)
    for i in range(NLIMB):
        for j in range(NLIMB):
            m[i * NLIMB + j, i + j] = 1
    return m


_FOLD_MAT = _fold_matrix()


# Squaring variant: only the 528 i<=j products, with weight 2 off the
# diagonal — halves the outer-product work of fsq, and the sqrt chain
# that dominates decompression is ~2/3 squarings.
def _sq_fold():
    ii, jj = [], []
    m = np.zeros((NLIMB * (NLIMB + 1) // 2, 2 * NLIMB), dtype=np.int32)
    for i in range(NLIMB):
        for j in range(i, NLIMB):
            m[len(ii), i + j] = 1 if i == j else 2
            ii.append(i)
            jj.append(j)
    return np.array(ii), np.array(jj), m


_SQ_I, _SQ_J, _SQ_FOLD = _sq_fold()


# ----------------------------------------------------- limb normalization

def _carry_par(c):
    """One parallel carry round; caller guarantees top-column headroom."""
    cr = c >> RADIX
    pad = [(0, 0)] * (c.ndim - 1) + [(1, 0)]
    return (c & MASK) + jnp.pad(cr[..., :-1], pad)


def _carry_seq(x):
    """Exact sequential carry chain as a lax.scan over the limb axis.
    Handles negative limbs via arithmetic shifts; the final value must
    fit 32 limbs nonnegative.

    This used to be 32 unrolled elementwise steps ("noise next to a
    mul's 2k multiplies") — true for runtime, catastrophically false
    for COMPILE time once the pairing tower landed: every _cond_sub a
    bound-normalization inserts and every _redc tail carries one of
    these, so the unrolled form put ~130 HLO ops at hundreds of sites
    inside the Miller fori body (104 s XLA compile for the loop alone,
    measured on CPU). The scan body is ~4 ops traced once per site;
    same arithmetic, ~8x smaller module."""
    xm = jnp.moveaxis(x, -1, 0)

    def step(c, col):
        t = col + c
        cr = t >> RADIX
        return cr, t - (cr << RADIX)

    cr, cols = lax.scan(step, jnp.zeros_like(xm[0]), xm[:-1])
    last = (xm[-1] + cr)[None]
    return jnp.moveaxis(jnp.concatenate([cols, last], axis=0), 0, -1)


def _cond_sub(v, const_l: np.ndarray):
    """v - const if v >= const else v, for carry-normalized nonneg v."""
    d = _carry_seq(v - jnp.asarray(const_l))
    neg = (d[..., -1:] < 0)
    return jnp.where(neg, v, d)


def _geq(v, const_l: np.ndarray):
    """v >= const (both canonical-normalized), -> bool[...]."""
    d = _carry_seq(v - jnp.asarray(const_l))
    return d[..., -1] >= 0


# ----------------------------------------------------- field arithmetic
#
# Invariant: a "normalized" element has limbs in [0, 2^12) (mul outputs
# may briefly sit at MASK+1 before the final seq chain — we always end
# with _carry_seq so the invariant is exact) and value < 2q. mont_mul
# output < q*(1 + 4q/2^384) < 1.41q; fadd/fsub re-establish < 2q with
# one conditional subtract of 2q.

def fadd(a, b):
    return _cond_sub(_carry_seq(a + b), _2Q_L)


def fsub(a, b):
    return _cond_sub(_carry_seq(a + jnp.asarray(_2Q_L) - b), _2Q_L)


def _redc(c, unroll=None):
    """Montgomery reduction of 63 product columns (cols < 2^29) to a
    normalized < 1.41q element: 32-step radix-2^12 REDC.

    unroll=True flattens the step chain so XLA fuses it — right for
    code traced ONCE (the fpow loop body that dominates decompression)
    running on TPU. unroll=False keeps a compact fori_loop — right for
    padd (traced at every tree level, ~3% of the arithmetic) and for
    the CPU backend, where the 32x bigger unrolled graph buys nothing
    but compile time (tests + the driver's CPU-mesh dryrun).
    Measured on the v5e: unrolling bought nothing (the fold matmul
    dominates, not loop bookkeeping) at 2x the compile time, so auto
    resolves to the compact loop everywhere."""
    if unroll is None:
        unroll = False
    # pad to 64 BEFORE carrying (col 62 carries into 63) and so the 32
    # REDC shift-downs leave 32 result columns
    pad = [(0, 0)] * (c.ndim - 1) + [(0, 1)]
    c = jnp.pad(c, pad)
    c = _carry_par(c)
    acc = _carry_par(c)                         # cols <= MASK + 2^6
    if unroll:
        # no physical shifting: step i computes its m from column i and
        # adds m * q into columns i..i+31
        cols = [acc[..., i] for i in range(2 * NLIMB)]
        for i in range(NLIMB):
            m = ((cols[i] & MASK) * QPRIME) & MASK
            for j in range(NLIMB):
                cols[i + j] = cols[i + j] + m * np.int32(_Q_L[j])
            cols[i + 1] = cols[i + 1] + (cols[i] >> RADIX)  # exact carry
        c = jnp.stack(cols[NLIMB:], axis=-1)    # cols < 2^30
    else:
        ql = jnp.asarray(np.pad(_Q_L, (0, NLIMB)))

        def redc_step(i, acc):
            m = ((acc[..., 0] & MASK) * QPRIME) & MASK
            full = acc + m[..., None] * ql
            carry = full[..., 0] >> RADIX       # low 12 bits are 0
            nxt = jnp.concatenate(
                [full[..., 1:], jnp.zeros_like(full[..., :1])], axis=-1)
            return nxt.at[..., 0].add(carry)

        acc = lax.fori_loop(0, NLIMB, redc_step, acc)
        c = acc[..., :NLIMB]                    # cols < 2^30, value < 1.41q
    c = _carry_par(c)
    c = _carry_par(c)
    return _carry_seq(c)


def mont_mul(a, b, unroll=None):
    """a * b * 2^-384 mod q (Montgomery product). a, b normalized < 2q;
    output normalized < 1.41q."""
    outer = a[..., :, None] * b[..., None, :]
    flat = outer.reshape(outer.shape[:-2] + (NLIMB * NLIMB,))
    return _redc(flat @ jnp.asarray(_FOLD_MAT)[:, :2 * NLIMB - 1],
                 unroll=unroll)


def fsq(a, unroll=None):
    prods = a[..., _SQ_I] * a[..., _SQ_J]
    return _redc((prods @ jnp.asarray(_SQ_FOLD))[..., :2 * NLIMB - 1],
                 unroll=unroll)


def fpow(x, bits: np.ndarray):
    """x^e (Montgomery domain) for a fixed public msb-first exponent."""
    bits_j = jnp.asarray(bits)
    one = jnp.broadcast_to(jnp.asarray(_ONE_M_L), x.shape)

    def body(i, acc):
        acc = fsq(acc)
        return jnp.where(bits_j[i] == 1, mont_mul(acc, x), acc)

    return lax.fori_loop(0, len(bits), body, one)


def to_mont(a_std):
    return mont_mul(a_std, jnp.broadcast_to(jnp.asarray(_R2_L), a_std.shape))


def from_mont(a_m):
    return mont_mul(
        a_m, jnp.broadcast_to(jnp.asarray(_ONE_STD_L), a_m.shape))


def fcanon(v):
    """Canonical representative in [0, q) from a < 2q normalized value."""
    return _cond_sub(v, _Q_L)


def feq(a, b):
    return jnp.all(fcanon(a) == fcanon(b), axis=-1)


def fneg(a):
    return fsub(jnp.zeros_like(a), a)


# ----------------------------------------------------- curve arithmetic
#
# Projective (X:Y:Z), y^2 = x^3 + 4, identity (0:1:0). Complete addition
# per Renes-Costello-Batina 2016 Alg. 7 (a=0, b3=12) — validated against
# the scalar reference over identity/doubling/inverse cases.

def _pm(a, b):
    # padd is traced at every tree level: compact-graph variant
    return mont_mul(a, b, unroll=False)


def padd(P1, P2):
    X1, Y1, Z1 = P1
    X2, Y2, Z2 = P2
    b3 = jnp.broadcast_to(jnp.asarray(_B3_M_L), X1.shape)
    t0 = _pm(X1, X2); t1 = _pm(Y1, Y2); t2 = _pm(Z1, Z2)
    t3 = fadd(X1, Y1); t4 = fadd(X2, Y2); t3 = _pm(t3, t4)
    t4 = fadd(t0, t1); t3 = fsub(t3, t4); t4 = fadd(Y1, Z1)
    X3 = fadd(Y2, Z2); t4 = _pm(t4, X3); X3 = fadd(t1, t2)
    t4 = fsub(t4, X3); X3 = fadd(X1, Z1); Y3 = fadd(X2, Z2)
    X3 = _pm(X3, Y3); Y3 = fadd(t0, t2); Y3 = fsub(X3, Y3)
    X3 = fadd(t0, t0); t0 = fadd(X3, t0); t2 = _pm(b3, t2)
    Z3 = fadd(t1, t2); t1 = fsub(t1, t2); Y3 = _pm(b3, Y3)
    X3 = _pm(t4, Y3); t2 = _pm(t3, t1); X3 = fsub(t2, X3)
    Y3 = _pm(Y3, t0); t1 = _pm(t1, Z3); Y3 = fadd(t1, Y3)
    t0 = _pm(t0, t3); Z3 = _pm(Z3, t4); Z3 = fadd(Z3, t0)
    return (X3, Y3, Z3)


def _identity(shape):
    z = jnp.zeros(shape + (NLIMB,), dtype=jnp.int32)
    one = jnp.broadcast_to(jnp.asarray(_ONE_M_L), shape + (NLIMB,))
    return (z, one, z)


# ----------------------------------------------------- decompress + sum

def decompress(x_std, sign_big, is_inf, valid_in):
    """Batched G1 decompress. x_std: [..., 32] standard-domain limbs
    (x < q enforced host-side), sign_big/is_inf/valid_in: bool[...].
    Returns ((X, Y, Z) Montgomery projective, valid[...])."""
    x_m = to_mont(x_std)
    u = fadd(mont_mul(fsq(x_m), x_m),
             jnp.broadcast_to(jnp.asarray(_FOUR_M_L), x_m.shape))
    y = fpow(u, _SQRT_BITS)
    on_curve = feq(fsq(y), u)
    y_canon = fcanon(from_mont(y))
    got_big = _geq(y_canon, _HALF_P1_L)              # y > (q-1)/2
    flip = got_big != sign_big
    y = jnp.where(flip[..., None], fneg(y), y)
    Xp, Yp, Zp = (x_m, y,
                  jnp.broadcast_to(jnp.asarray(_ONE_M_L), x_m.shape))
    idX, idY, idZ = _identity(x_std.shape[:-1])
    inf = is_inf[..., None]
    P = (jnp.where(inf, idX, Xp), jnp.where(inf, idY, Yp),
         jnp.where(inf, idZ, Zp))
    valid = valid_in & (on_curve | is_inf)
    return P, valid


def _tree_sum(P, n_pad: int):
    """Sum points over axis 1 ([B, n_pad] -> [B]) via log2 levels of
    complete additions. n_pad must be a power of two (identity-padded)."""
    levels = int(n_pad).bit_length() - 1
    assert 1 << levels == n_pad
    for _ in range(levels):
        P = padd(tuple(c[:, 0::2] for c in P),
                 tuple(c[:, 1::2] for c in P))
    return tuple(c[:, 0] for c in P)


@jax.jit
def _aggregate_kernel(x_std, sign_big, is_inf, valid_in):
    """[B, n, 32] limbs + flags -> ([B,32]x3 standard-domain projective
    coords, valid[B] = all shares decodable). Decompression (the
    dominant cost: one sqrt per share) runs on exactly the n real
    shares; identity padding to the tree's power-of-two width happens
    at the point level afterwards."""
    P, valid = decompress(x_std, sign_big, is_inf, valid_in)
    n = x_std.shape[1]
    n_pad = 1 << max(0, (n - 1).bit_length())
    if n_pad > n:
        idX, idY, idZ = _identity((x_std.shape[0], n_pad - n))
        P = tuple(jnp.concatenate([c, pad], axis=1)
                  for c, pad in zip(P, (idX, idY, idZ)))
    X, Y, Z = _tree_sum(P, n_pad)
    return (fcanon(from_mont(X)), fcanon(from_mont(Y)),
            fcanon(from_mont(Z)), jnp.all(valid, axis=1))


# ----------------------------------------------------- host byte plumbing

def pack_compressed(sigs: np.ndarray):
    """[N, 48] uint8 big-endian compressed G1 -> (x limbs [N, 32] int32,
    sign_big [N], is_inf [N], valid [N]) — vectorized numpy, no Python
    bigints on the hot path."""
    sigs = np.asarray(sigs, dtype=np.uint8)
    N = sigs.shape[0]
    flags = sigs[:, 0]
    compressed = (flags & 0x80) != 0
    is_inf = (flags & 0x40) != 0
    sign_big = (flags & 0x20) != 0
    body = sigs.copy()
    body[:, 0] &= 0x1F
    le = body[:, ::-1].astype(np.int32)              # little-endian bytes
    groups = le.reshape(N, 16, 3)                    # 3 bytes = 2 limbs
    v24 = groups[:, :, 0] + (groups[:, :, 1] << 8) + (groups[:, :, 2] << 16)
    limbs = np.empty((N, NLIMB), dtype=np.int32)
    limbs[:, 0::2] = v24 & MASK
    limbs[:, 1::2] = v24 >> RADIX
    # x < q (lexicographic compare against q's limbs, from the top)
    lt = np.zeros(N, dtype=bool)
    decided = np.zeros(N, dtype=bool)
    for i in range(NLIMB - 1, -1, -1):
        qi = int(_Q_L[i])
        lt |= (~decided) & (limbs[:, i] < qi)
        decided |= limbs[:, i] != qi
    inf_ok = is_inf & (flags == 0xC0) & ~np.any(sigs[:, 1:], axis=1)
    valid = compressed & (inf_ok | (~is_inf & lt))
    limbs[~valid | is_inf] = 0
    return limbs, sign_big & ~is_inf, is_inf & valid, valid


def _proj_to_affine(x: int, y: int, z: int) -> Optional[Tuple[int, int]]:
    if z == 0:
        return None
    zi = pow(z, Q - 2, Q)
    return (x * zi % Q, y * zi % Q)


_POW2 = np.array([1 << (RADIX * i) for i in range(NLIMB)], dtype=object)


def _limbs_to_ints(arr: np.ndarray) -> np.ndarray:
    """[..., 32] int32 -> [...] Python-int (object) array, vectorized."""
    return (arr.astype(object) * _POW2).sum(axis=-1)


def aggregate_g1_jobs(jobs: Sequence[Sequence[bytes]]):
    """Aggregate B independent share-sets in one device dispatch.

    jobs: B sequences of 48-byte compressed G1 signatures (ragged ok —
    each job is identity-padded to the common power-of-two width).
    Returns (points, valid): points[i] is the affine aggregate
    (x, y) | None of job i, valid[i] is False if any share of job i
    failed to decode (mirror of g1_decompress raising).
    """
    B = len(jobs)
    if B == 0:
        return [], np.zeros(0, dtype=bool)
    nmax = max(1, max(len(j) for j in jobs))
    X, Y, Z, ok = aggregate_dispatch(jobs, nmax)
    X, Y, Z, ok = (np.asarray(X), np.asarray(Y), np.asarray(Z),
                   np.asarray(ok))
    xs, ys, zs = _limbs_to_ints(X), _limbs_to_ints(Y), _limbs_to_ints(Z)
    pts = [_proj_to_affine(int(xs[i]), int(ys[i]), int(zs[i]))
           if ok[i] else None for i in range(B)]
    return pts, ok


def aggregate_dispatch(jobs, n: int):
    """Device-async building block for pipelined benchmarking and the
    verify-hub path: returns the un-awaited device arrays for a batch
    of jobs padded to a common (static) width n. Short jobs are padded
    with compressed-infinity shares (identity under addition).

    Job batches clearing the mesh gate (ops/mesh.py) shard the job
    axis over every chip: padding JOBS are all-infinity share sets
    (decode valid, aggregate to the identity) and their rows are
    sliced off lazily, so collect sees exactly B results."""
    B = len(jobs)
    from plenum_tpu.ops import mesh as mesh_mod
    m = mesh_mod.get_mesh()
    sharded = m.should_shard(B)
    # both branches bucket the job axis: the unsharded path used to
    # launch the raw B and paid one XLA compile per distinct job-batch
    # size (the PT014 / r05 regression shape); identity-padded jobs
    # aggregate to infinity and their rows are sliced off lazily
    Bp = m.padded_size(B, min_per_device=1) if sharded \
        else pow2_at_least(max(B, 1))
    # job-axis lane accounting: real shares vs the Bp×n identity-padded
    # grid (short jobs pad with infinity shares, padding jobs are whole
    # wasted rows)
    _tmy.get_seam_hub().record_launch(
        _tmy.SEAM_BLS, sum(len(j) for j in jobs), Bp * n, shape=(Bp, n))
    raw = np.zeros((Bp, n, 48), dtype=np.uint8)
    raw[:, :, 0] = 0xC0
    for i, job in enumerate(jobs):
        for j, s in enumerate(job):
            raw[i, j] = np.frombuffer(s, dtype=np.uint8)
    limbs, sign_big, is_inf, valid = pack_compressed(
        raw.reshape(Bp * n, 48))
    arrays = (limbs.reshape(Bp, n, NLIMB), sign_big.reshape(Bp, n),
              is_inf.reshape(Bp, n), valid.reshape(Bp, n))
    if sharded:
        outs = m.dispatch(_aggregate_kernel, arrays, n=B)
        if Bp != B:
            outs = tuple(o[:B] for o in outs)
        return outs
    m.note_passthrough(B)
    outs = _aggregate_kernel(*(jnp.asarray(a) for a in arrays))
    if Bp != B:
        outs = tuple(o[:B] for o in outs)
    return outs


def aggregate_collect(handles) -> Tuple[List[Optional[Tuple[int, int]]],
                                        np.ndarray]:
    """Await + post-process a handle from aggregate_dispatch."""
    X, Y, Z, ok = (np.asarray(h) for h in handles)
    xs, ys, zs = _limbs_to_ints(X), _limbs_to_ints(Y), _limbs_to_ints(Z)
    pts = [_proj_to_affine(int(xs[i]), int(ys[i]), int(zs[i]))
           if ok[i] else None for i in range(len(ok))]
    return pts, ok
