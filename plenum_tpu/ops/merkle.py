"""Device-resident merkle tree — the TPU-native bulk path.

The reference hashes merkle nodes one at a time through OpenSSL
(ledger/tree_hasher.py:7). The host-side CompactMerkleTree batches leaf
hashing through ops/sha256, but a large build is transfer-bound: every
level would round-trip host↔device. This module instead keeps the WHOLE
tree on device:

 - `build` runs ONE fused jit: leaf SHA-256, then every interior level
   derived on device (node blocks are packed from digest pairs with pure
   uint32 shifts — no host byte juggling), returning a tuple of
   device-resident level arrays. Only the root/frontier (a few hashes)
   ever leave the device.
 - `audit_path_batch` is a gather kernel: sibling indices are
   (m >> h) ^ 1 per level, so a k-proof batch is k·depth gathers and ONE
   small download — the BASELINE "1M-leaf audit-path batch" config.

Power-of-two sizes are computed exactly; other sizes are padded to the
next power of two and only full aligned subtrees inside the real range
are ever read (pad garbage mixes strictly to the right of them), with
the true root folded from the frontier on host (log n scalar hashes).
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from plenum_tpu.ops.sha256 import (
    _sha256_blocks, digests_to_bytes, pad_messages)


@functools.partial(jax.jit, static_argnames=("msg_len", "nblocks"))
def _pack_uniform(raw, msg_len: int, nblocks: int):
    """[B, msg_len] u8 → [B, nblocks, 16] u32 SHA-padded words, entirely
    on device — uploading raw bytes instead of padded u32 words cuts the
    host→device transfer ~2.5× for typical txn-sized leaves."""
    b = raw.shape[0]
    out = jnp.zeros((b, nblocks * 64), dtype=jnp.uint8)
    out = out.at[:, :msg_len].set(raw)
    out = out.at[:, msg_len].set(jnp.uint8(0x80))
    bitlen = (msg_len * 8).to_bytes(8, "big")
    end = ((msg_len + 9 + 63) // 64) * 64
    out = out.at[:, end - 8:end].set(
        jnp.asarray(np.frombuffer(bitlen, dtype=np.uint8)))
    w = out.reshape(b, nblocks, 16, 4).astype(jnp.uint32)
    return (w[..., 0] << 24) | (w[..., 1] << 16) | (w[..., 2] << 8) \
        | w[..., 3]


def _node_blocks(left, right):
    """[B,8],[B,8] u32 digests → [B,2,16] u32 message blocks for
    H(0x01 || left || right) (65 bytes, SHA-padded)."""
    l8 = left >> jnp.uint32(8)
    lc = (left & jnp.uint32(0xff)) << jnp.uint32(24)
    r8 = right >> jnp.uint32(8)
    rc = (right & jnp.uint32(0xff)) << jnp.uint32(24)
    w0 = jnp.uint32(0x01 << 24) | l8[:, 0]
    ws = [w0]
    for i in range(1, 8):
        ws.append(lc[:, i - 1] | l8[:, i])
    ws.append(lc[:, 7] | r8[:, 0])
    for i in range(1, 8):
        ws.append(rc[:, i - 1] | r8[:, i])
    w16 = rc[:, 7] | jnp.uint32(0x80 << 16)
    zeros = jnp.zeros_like(w0)
    block1 = [w16] + [zeros] * 14 + [
        jnp.broadcast_to(jnp.uint32(65 * 8), w0.shape)]
    words = jnp.stack(ws + block1, axis=1)  # [B, 32]
    return words.reshape(words.shape[0], 2, 16)


@functools.partial(jax.jit, static_argnames=("nblocks", "depth"))
def _build_levels(leaf_words, leaf_nvalid, nblocks: int, depth: int):
    """leaf_words [P, nblocks, 16] → tuple of P/2, P/4, … 1 digest
    arrays ([*, 8] u32), all resident on device."""
    cur = _sha256_blocks(leaf_words, leaf_nvalid, nblocks)
    levels = [cur]
    two = jnp.full((1,), 2, dtype=jnp.int32)
    for _ in range(depth):
        blocks = _node_blocks(cur[0::2], cur[1::2])
        nv = jnp.broadcast_to(two, (blocks.shape[0],))
        cur = _sha256_blocks(blocks, nv, 2)
        levels.append(cur)
    return tuple(levels)


@jax.jit
def _gather_paths(levels, indices):
    """Sibling digests for each index at each level: [k, depth, 8]."""
    cols = []
    for h, level in enumerate(levels[:-1]):
        sib = (indices >> h) ^ 1
        cols.append(level[sib])
    return jnp.stack(cols, axis=1)


@functools.partial(jax.jit, static_argnames=("n_low",))
def _gather_low_paths(levels, indices, n_low: int):
    """Sibling digests for the n_low BOTTOM levels only: [k, n_low, 8].
    The top levels have fewer nodes than proofs in a batch, so their
    digests are downloaded once per build and joined host-side — the
    device->host tunnel is the bottleneck (~20 MB/s measured), and this
    cuts the per-batch download ~3x for 10k-proof batches."""
    cols = []
    for h in range(n_low):
        sib = (indices >> h) ^ 1
        cols.append(levels[h][sib])
    return jnp.stack(cols, axis=1)


class DeviceMerkleTree:
    """An RFC 6962 tree whose node hashes live in device memory."""

    # levels at or under this node count are mirrored to host at build
    # time (~4 MiB total for a 1M-leaf tree — 6% of the tree) so proof
    # batches never re-download them; only the huge bottom levels are
    # gathered per batch. The device-to-host tunnel (~19 MB/s measured)
    # is the extraction bottleneck, so per-batch bytes ARE the rate.
    _TOP_CACHE = 131072

    def __init__(self, hasher=None):
        from plenum_tpu.ledger.tree_hasher import TreeHasher
        self.hasher = hasher or TreeHasher()
        self._levels = None          # tuple of device arrays, leaves first
        self._size = 0
        self._padded = 0

    @property
    def tree_size(self) -> int:
        return self._size

    def build(self, leaves: Sequence[bytes]) -> bytes:
        """Hash `leaves` and every interior level on device; → root."""
        n = len(leaves)
        if n == 0:
            self._levels, self._size, self._padded = None, 0, 0
            return self.hasher.hash_empty()
        padded = 1
        while padded < n:
            padded *= 2
        msgs = [b"\x00" + d for d in leaves]
        if padded > n:
            msgs = msgs + [msgs[-1]] * (padded - n)
        depth = padded.bit_length() - 1
        ln0 = len(msgs[0])
        if all(len(m) == ln0 for m in msgs):
            # uniform leaves: upload raw bytes, pad/pack on device
            nblocks = 1
            while nblocks * 64 < ln0 + 9:
                nblocks *= 2
            raw = np.frombuffer(b"".join(msgs), dtype=np.uint8) \
                .reshape(padded, ln0)
            words = _pack_uniform(jnp.asarray(raw), ln0, nblocks)
            nvalid = jnp.full((padded,), (ln0 + 9 + 63) // 64,
                              dtype=jnp.int32)
        else:
            host_words, host_nvalid, nblocks = pad_messages(msgs)
            words = jnp.asarray(host_words)
            nvalid = jnp.asarray(host_nvalid)
        self._levels = _build_levels(words, nvalid, nblocks, depth)
        self._size, self._padded = n, padded
        # host cache of every level small enough that a proof batch
        # would re-download it anyway (<= _TOP_CACHE nodes): one small
        # transfer now, then per-batch downloads carry only the big
        # bottom levels
        self._top_cache = {}
        for h, level in enumerate(self._levels):
            if level.shape[0] <= self._TOP_CACHE:
                self._top_cache[h] = np.asarray(level).astype(">u4", order="C") \
                    .view(np.uint8).reshape(level.shape[0], 32)
        return self.root_hash

    # ------------------------------------------------------------- reads

    def _level_entry(self, height: int, index: int) -> bytes:
        arr = self._levels[height][index:index + 1]
        return digests_to_bytes(np.asarray(arr))[0]

    @property
    def root_hash(self) -> bytes:
        if self._size == 0:
            return self.hasher.hash_empty()
        if self._size == self._padded:
            return self._level_entry(len(self._levels) - 1, 0)
        # fold the frontier: for each set bit h of n the full aligned
        # subtree starts at n with bits ≤ h cleared — entirely inside the
        # real range, so pad garbage never contaminates it
        accum = None
        n = self._size
        for height in range(len(self._levels)):
            if n & (1 << height):
                start = (n >> (height + 1)) << (height + 1)
                entry = self._level_entry(height, start >> height)
                accum = entry if accum is None else \
                    self.hasher.hash_children(entry, accum)
        return accum

    def _path_levels(self):
        """(n_low, top_heights): bottom levels gathered on device
        per batch, top levels joined from the host mirror."""
        depth = len(self._levels) - 1
        n_low = 0
        while n_low < depth and n_low not in self._top_cache:
            n_low += 1
        return n_low, list(range(n_low, depth))

    def _check_pow2(self):
        if self._size != self._padded:
            raise ValueError("batched audit paths need a power-of-two "
                             "tree (got size {})".format(self._size))

    def dispatch_path_batch(self, indices: Sequence[int]):
        """Start the device gather for one proof batch; returns an
        opaque handle. Pair with collect_path_batch — interleaving
        dispatch/collect across batches overlaps the next gather with
        the current download (the tunnel is the bottleneck)."""
        self._check_pow2()
        idx_np = np.asarray(list(indices), dtype=np.int32)
        if len(self._levels) == 1:
            return (idx_np, None)
        n_low, _tops = self._path_levels()
        low = None
        if n_low:
            low = _gather_low_paths(self._levels, jnp.asarray(idx_np),
                                    n_low)
            try:
                low.copy_to_host_async()
            except Exception:
                pass
        return (idx_np, low)

    def collect_path_batch(self, handle) -> np.ndarray:
        """Await a dispatch_path_batch handle -> uint8[k, depth, 32]
        (leaf-sibling first). Top levels come from the host mirror via
        vectorized numpy gathers — no device traffic, no per-digest
        Python objects."""
        idx_np, low = handle
        depth = len(self._levels) - 1
        k = idx_np.shape[0]
        out = np.empty((k, depth, 32), dtype=np.uint8)
        n_low, tops = self._path_levels()
        if low is not None:
            out[:, :n_low] = np.asarray(low).astype(">u4", order="C") \
                .view(np.uint8).reshape(k, n_low, 32)
        for h in tops:
            out[:, h] = self._top_cache[h][(idx_np >> h) ^ 1]
        return out

    def audit_path_batch_array(self, indices) -> np.ndarray:
        """Audit paths for many leaves -> uint8[k, depth, 32] in one
        device gather (bottom levels) + host joins (cached top levels).
        Exact only for power-of-two sizes — the production
        CompactMerkleTree serves ragged sizes."""
        return self.collect_path_batch(self.dispatch_path_batch(indices))

    def audit_path_batch(self, indices: Sequence[int]) -> List[List[bytes]]:
        """List-of-lists variant of audit_path_batch_array (per-sibling
        bytes objects are the compat format; the array form is ~100k
        Python-object constructions cheaper per 10k proofs)."""
        if len(self._levels) == 1:
            self._check_pow2()
            # single-leaf tree: the audit path of leaf 0 is empty
            return [[] for _ in indices]
        arr = self.audit_path_batch_array(indices)
        k, depth = arr.shape[0], arr.shape[1]
        flat = arr.reshape(k * depth, 32).tobytes()
        mv = memoryview(flat)
        return [[bytes(mv[(i * depth + h) * 32:(i * depth + h + 1) * 32])
                 for h in range(depth)] for i in range(k)]

    def verify_path(self, leaf: bytes, index: int, path: List[bytes],
                    root: bytes) -> bool:
        h = self.hasher.hash_leaf(leaf)
        for height, sibling in enumerate(path):
            if (index >> height) & 1:
                h = self.hasher.hash_children(sibling, h)
            else:
                h = self.hasher.hash_children(h, sibling)
        return h == root
