"""Device-resident merkle tree — the TPU-native proof/build engine.

The reference hashes merkle nodes one at a time through OpenSSL
(ledger/tree_hasher.py:7). The host-side CompactMerkleTree batches leaf
hashing through ops/sha256, but proofs and rebuilds were host work. This
module keeps the WHOLE tree on device and serves production shapes:

 - `build` runs ONE fused jit: leaf SHA-256, then every interior level
   derived on device (node blocks are packed from digest pairs with pure
   uint32 shifts — no host byte juggling).
 - `append_leaf_hashes` is the incremental path: device-resident level
   tails grow by ~2b hashes for b appended leaves instead of a full
   rebuild — complete RFC 6962 nodes are immutable, so an append only
   ever writes NEW rows. Levels are hashed in FUSED groups of
   Config.MERKLE_FUSED_LEVELS per dispatch (hash level i, pair
   in-kernel, hash level i+1, …), so dispatches-per-append drop from
   O(log n) to O(log n / K).
 - the SHA-256 compression itself routes per batch size
   (ops/sha256.select_backend): the fused Pallas kernel on
   accelerators, the cache-tiled XLA expression on the CPU backend,
   the plain expression for small levels — one static decision per
   build/append jit, byte-identical outputs on every path.
 - `dispatch_proof_batch`/`collect_proof_batch` serve RFC 6962
   inclusion proofs for ANY tree size (ragged included): an inclusion
   proof decomposes into the leaf's path inside its full aligned
   frontier subtree (a plain sibling gather, heights 0..h_j-1) plus one
   fold of the frontier subtrees to its right and the roots of those to
   its left — all O(log n) host joins shared across the batch.
 - the sibling gather is FUSED with big-endian byte packing in one jit,
   so a proof batch leaves the device as a single dense uint8 buffer —
   the ~19 MB/s D2H tunnel plus a host-side byteswap was the measured
   bottleneck (BENCH_r05: 0.66x the host proof floor).
 - `ProofPipeline` double-buffers dispatch/collect across batches so
   the next gather overlaps the current download.

Top levels (few nodes, shared by every proof) are mirrored to host
LAZILY — first proof batch after a build/growth pays one download; the
mirror then grows incrementally with each append, so per-batch device
traffic carries only the huge bottom levels.

Multi-chip (ops/mesh.py): builds clearing the mesh gate hash their
leaves and interior levels as ONE batch-axis-sharded SPMD program over
every chip (the leaf level dominates the hash count), then land the
level arrays back on the default device so the incremental append and
mirror paths are unchanged. Proof gathers shard the INDEX axis — each
proof row is an independent sibling gather — against bottom levels
replicated across the mesh (memoized per level array, invalidated by
appends; serving is read-heavy, so replication amortizes over batches).
"""
from __future__ import annotations

import functools
import logging
import os
from collections import deque
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from plenum_tpu.observability.tracing import CAT_DEVICE
from plenum_tpu.observability.telemetry import (
    SEAM_MERKLE_APPEND as _TM_SEAM_APPEND,
    SEAM_MERKLE_BUILD as _TM_SEAM_BUILD,
    get_seam_hub as _get_telemetry)
from plenum_tpu.ops import pow2_at_least as _pow2_at_least
from plenum_tpu.ops.sha256 import (
    _sha256_blocks, compress_blocks, digests_to_array, pad_messages,
    select_backend)

logger = logging.getLogger(__name__)

_async_copy_noted = False


def _start_async_copy(arr):
    """Begin the D2H copy for `arr` so a later np.asarray doesn't block.
    Narrow except: only the backend's not-supported signals are
    swallowed (logged once at debug); anything else is a real error."""
    global _async_copy_noted
    try:
        arr.copy_to_host_async()
    except (AttributeError, NotImplementedError) as exc:
        if not _async_copy_noted:
            _async_copy_noted = True
            logger.debug("async device->host copy unavailable (%s); "
                         "proof collects will block on transfer", exc)


def _get_mesh():
    from plenum_tpu.ops import mesh as mesh_mod
    return mesh_mod.get_mesh()


def _to_default_device(levels):
    """Land (possibly mesh-sharded) level arrays on the default device:
    the append/mirror/read paths dispatch single-device programs, and
    jit rejects operands committed to different device sets — one
    device-to-device copy after a sharded build keeps every downstream
    path byte-identical and oblivious."""
    import jax
    from plenum_tpu.ops import mesh as mesh_mod
    dev = mesh_mod.default_device()
    return [jax.device_put(lv, dev) for lv in levels]


@functools.partial(jax.jit, static_argnames=("msg_len", "nblocks"))
def _pack_uniform(raw, msg_len: int, nblocks: int):
    """[B, msg_len] u8 → [B, nblocks, 16] u32 SHA-padded words, entirely
    on device — uploading raw bytes instead of padded u32 words cuts the
    host→device transfer ~2.5× for typical txn-sized leaves."""
    b = raw.shape[0]
    out = jnp.zeros((b, nblocks * 64), dtype=jnp.uint8)
    out = out.at[:, :msg_len].set(raw)
    out = out.at[:, msg_len].set(jnp.uint8(0x80))
    bitlen = (msg_len * 8).to_bytes(8, "big")
    end = ((msg_len + 9 + 63) // 64) * 64
    out = out.at[:, end - 8:end].set(
        jnp.asarray(np.frombuffer(bitlen, dtype=np.uint8)))
    w = out.reshape(b, nblocks, 16, 4).astype(jnp.uint32)
    return (w[..., 0] << 24) | (w[..., 1] << 16) | (w[..., 2] << 8) \
        | w[..., 3]


def _node_blocks(left, right):
    """[B,8],[B,8] u32 digests → [B,2,16] u32 message blocks for
    H(0x01 || left || right) (65 bytes, SHA-padded)."""
    l8 = left >> jnp.uint32(8)
    lc = (left & jnp.uint32(0xff)) << jnp.uint32(24)
    r8 = right >> jnp.uint32(8)
    rc = (right & jnp.uint32(0xff)) << jnp.uint32(24)
    w0 = jnp.uint32(0x01 << 24) | l8[:, 0]
    ws = [w0]
    for i in range(1, 8):
        ws.append(lc[:, i - 1] | l8[:, i])
    ws.append(lc[:, 7] | r8[:, 0])
    for i in range(1, 8):
        ws.append(rc[:, i - 1] | r8[:, i])
    w16 = rc[:, 7] | jnp.uint32(0x80 << 16)
    zeros = jnp.zeros_like(w0)
    block1 = [w16] + [zeros] * 14 + [
        jnp.broadcast_to(jnp.uint32(65 * 8), w0.shape)]
    words = jnp.stack(ws + block1, axis=1)  # [B, 32]
    return words.reshape(words.shape[0], 2, 16)


def _hash_pairs(cur, backend: str = "plain"):
    """[2m, 8] u32 digests → [m, 8] parent digests (device). The
    compression routes per-level: a batch big enough for the Pallas
    kernel / CPU cache tiling takes it, the small top levels keep the
    plain expression (compress_blocks re-checks the static shape)."""
    blocks = _node_blocks(cur[0::2], cur[1::2])
    nv = jnp.full((blocks.shape[0],), 2, dtype=jnp.int32)
    return compress_blocks(blocks, nv, 2, backend)


@functools.partial(jax.jit, static_argnames=("nblocks", "depth",
                                             "backend"))
def _build_levels(leaf_words, leaf_nvalid, nblocks: int, depth: int,
                  backend: str = "plain"):
    """leaf_words [P, nblocks, 16] → tuple of P, P/2, … 1 digest
    arrays ([*, 8] u32), all resident on device. ONE jit covers leaf
    hashing and every interior level — `backend` (static) decides the
    compression lowering (Pallas kernel / CPU tiles / plain XLA) and
    rides the mesh-sharded dispatch unchanged."""
    cur = compress_blocks(leaf_words, leaf_nvalid, nblocks, backend)
    levels = [cur]
    for _ in range(depth):
        cur = _hash_pairs(cur, backend)
        levels.append(cur)
    return tuple(levels)


@functools.partial(jax.jit, static_argnames=("depth", "backend"))
def _build_levels_from_digest_bytes(arr_u8, depth: int,
                                    backend: str = "plain"):
    """[P, 32] u8 big-endian leaf DIGESTS → device level tuple (no leaf
    hashing — the resync path feeds hash-store contents straight in)."""
    w = arr_u8.reshape(arr_u8.shape[0], 8, 4).astype(jnp.uint32)
    cur = (w[..., 0] << 24) | (w[..., 1] << 16) | (w[..., 2] << 8) \
        | w[..., 3]
    levels = [cur]
    for _ in range(depth):
        cur = _hash_pairs(cur, backend)
        levels.append(cur)
    return tuple(levels)


@jax.jit
def _digest_words(arr_u8):
    """[B, 32] u8 big-endian digest bytes → [B, 8] u32 words."""
    w = arr_u8.reshape(arr_u8.shape[0], 8, 4).astype(jnp.uint32)
    return (w[..., 0] << 24) | (w[..., 1] << 16) | (w[..., 2] << 8) \
        | w[..., 3]


@jax.jit
def _place(level, vals, start, count):
    """Scatter vals[0:count] into level[start:start+count]; rows past
    `count` are dropped (vals is bucket-padded to bound recompiles)."""
    ar = jnp.arange(vals.shape[0], dtype=jnp.int32)
    idx = jnp.where(ar < count, start + ar, level.shape[0])
    return level.at[idx].set(vals, mode="drop")


@functools.partial(jax.jit, static_argnames=("bucket",))
def _append_level_step(child, parent, p0, cnt, bucket: int):
    """Hash parent nodes [p0, p0+cnt) from consecutive child pairs and
    scatter them into `parent`. Gathers clamp / scatters drop the
    bucket-padding rows, so one compile serves every append of up to
    `bucket` new nodes at this level shape."""
    ar = jnp.arange(bucket, dtype=jnp.int32)
    pi = p0 + ar
    dig = _sha256_blocks(
        _node_blocks(child[2 * pi], child[2 * pi + 1]),
        jnp.full((bucket,), 2, dtype=jnp.int32), 2)
    idx = jnp.where(ar < cnt, pi, parent.shape[0])
    return parent.at[idx].set(dig, mode="drop"), dig


@functools.partial(jax.jit, static_argnames=("buckets", "backend"))
def _append_levels_fused(child, parents, p0s, cnts, buckets,
                         backend: str = "plain"):
    """Multi-level tree fusion: hash K=len(parents) consecutive tree
    levels in ONE device dispatch — hash level i's new pairs, scatter
    them into level i+1, pair THOSE in-kernel, hash level i+2, … —
    instead of one dispatch per level. Node hashes are always exactly
    2 blocks (65 bytes), so the whole chain is a fixed-shape uint32
    program; dispatches-per-append drop from O(log n) to
    O(log n / K) (the MTU tree-unit schedule, PAPERS.md).

    child is the lowest (already-updated) level; parents are the K
    levels above it, p0s/cnts the per-level write windows (dynamic —
    one compile serves every append with these array shapes and
    `buckets`). Per level the gather clamps / the scatter drops the
    bucket-padding rows, exactly like _append_level_step, so the
    padding rows never corrupt parent state even though their hashes
    are garbage. Returns the updated parent arrays plus each level's
    bucket-padded digests (for hash-store persistence / mirrors)."""
    outs = []
    digs = []
    cur = child
    for j, (parent, bucket) in enumerate(zip(parents, buckets)):
        ar = jnp.arange(bucket, dtype=jnp.int32)
        pi = p0s[j] + ar
        dig = compress_blocks(
            _node_blocks(cur[2 * pi], cur[2 * pi + 1]),
            jnp.full((bucket,), 2, dtype=jnp.int32), 2, backend)
        idx = jnp.where(ar < cnts[j], pi, parent.shape[0])
        cur = parent.at[idx].set(dig, mode="drop")
        outs.append(cur)
        digs.append(dig)
    return tuple(outs), tuple(digs)


@functools.partial(jax.jit, static_argnames=("rows",))
def _grown(old, rows: int):
    pad = jnp.zeros((rows - old.shape[0], 8), dtype=jnp.uint32)
    return jnp.concatenate([old, pad], axis=0)


@jax.jit
def _gather_pack(levels, indices):
    """FUSED sibling-gather + big-endian byte packing: for each level h
    in the tuple, gather digests at (m >> h) ^ 1 and emit ONE dense
    uint8 buffer [k, len(levels)*32] — the proof batch leaves the
    device already in wire byte order, so collect is a plain reshape
    instead of a host-side astype('>u4') byteswap over megabytes."""
    cols = []
    for h, level in enumerate(levels):
        sib = (indices >> h) ^ 1
        cols.append(level[sib])
    g = jnp.stack(cols, axis=1)  # [k, n_low, 8] u32
    b = jnp.stack([(g >> 24) & 0xff, (g >> 16) & 0xff,
                   (g >> 8) & 0xff, g & 0xff], axis=-1)
    return b.astype(jnp.uint8).reshape(g.shape[0], len(levels) * 32)


@jax.jit
def _read_row(level, idx):
    return jax.lax.dynamic_slice(level, (idx, 0), (1, 8))


class DeviceMerkleTree:
    """An RFC 6962 tree whose node hashes live in device memory.

    Supports ANY size (ragged included) for builds, incremental appends
    and inclusion-proof batches. Complete nodes are immutable, so the
    level arrays only ever grow; capacity doubles like a vector to
    bound reallocation and recompiles.
    """

    # levels at or under this node count are mirrored to host (lazily,
    # on first proof batch; then kept fresh incrementally by appends)
    # so proof batches never re-download them; only the huge bottom
    # levels are gathered per batch. The device-to-host tunnel
    # (~19 MB/s measured) is the extraction bottleneck, so per-batch
    # bytes ARE the rate.
    _TOP_CACHE = int(os.environ.get("PLENUM_MERKLE_TOP_CACHE", "262144"))

    def __init__(self, hasher=None):
        from plenum_tpu.ledger.tree_hasher import TreeHasher
        from plenum_tpu.observability.tracing import NullTracer
        self.hasher = hasher or TreeHasher()
        self._levels: Optional[List] = None  # device arrays, leaves first
        self._size = 0
        self._cap = 0
        self._mirror = {}          # height -> host uint8 [cap>>h, 32]
        self._mirror_count = {}    # height -> mirrored complete prefix
        self._froot_cache = {}     # proof size n -> frontier root bytes
        self._repl_cache = {}      # height -> (replica, snap rows, sharding)
        self.tracer = NullTracer()
        # cumulative device-IO counters (never reset with the tree):
        # the flight-recorder spans carry the same events; these make
        # "no re-materialization" assertable in tests/bench without a
        # tracer attached
        self.dispatch_stats = {
            "build_dispatches": 0,        # fused build jits launched
            "append_dispatches": 0,       # _place + level-group steps
            "gather_dispatches": 0,       # per-proof-batch low gathers
            "mirror_level_downloads": 0,  # full-level host downloads
            "mirror_rows_downloaded": 0,
            "replica_broadcasts": 0,      # mesh replications of a level
            "row_reads": 0,               # single-row device reads
        }

    def attach_tracer(self, tracer) -> None:
        """Feed this tree's dispatch spans to a flight recorder (the
        serving ProofPipeline carries its own tracer; this one covers
        builds/appends/mirror downloads)."""
        from plenum_tpu.observability.tracing import NullTracer
        self.tracer = tracer or NullTracer()

    # ------------------------------------------------------------ state

    @property
    def tree_size(self) -> int:
        return self._size

    @property
    def _padded(self) -> int:
        # kept for introspection/back-compat: capacity == padded size
        return self._cap if self._size else 0

    def reset(self):
        self._levels, self._size, self._cap = None, 0, 0
        self._mirror, self._mirror_count, self._froot_cache = {}, {}, {}
        self._repl_cache = {}

    def _depth(self) -> int:
        return self._cap.bit_length() - 1 if self._cap else 0

    def _n_low(self) -> int:
        """First host-mirrored height; heights below it are gathered on
        device per proof batch."""
        h = 0
        while h < self._depth() and (self._cap >> h) > self._TOP_CACHE:
            h += 1
        return h

    def _invalidate(self):
        self._froot_cache = {}

    # ----------------------------------------------------------- builds

    _BUILD_VALIDATED = set()   # (key..., backend) whose execution completed

    def _run_build(self, launch, padded: int, key: tuple):
        """Backend-routed build launch with the Pallas step-down chain
        (ed25519_jax._dispatch_kernel precedent): `launch(backend)`
        returns the level tuple; a Pallas backend is proven by ONE
        block_until_ready per (key, backend) — dispatch is async, so a
        runtime failure at an untested shape would otherwise surface
        at a later np.asarray outside any except and the fallback
        would never engage. Any Pallas failure steps down to the XLA
        expression for the whole process (shared probe registry)."""
        backend = select_backend(padded)
        while True:
            try:
                levels = launch(backend)
                if backend.startswith("pallas") \
                        and key + (backend,) not in self._BUILD_VALIDATED:
                    # deliberate ONE-TIME sync per build-shape family;
                    # later builds stay fully async
                    jax.block_until_ready(levels)  # plenum-lint: disable=PT002
                    self._BUILD_VALIDATED.add(key + (backend,))
                self.dispatch_stats["build_dispatches"] += 1
                return levels
            except Exception:  # pragma: no cover  # plenum-lint: disable=PT006
                # the fallback engine itself: ANY Pallas failure must
                # step down to the XLA expression, never fail a build
                if not backend.startswith("pallas"):
                    raise
                logger.exception(
                    "pallas sha256 build failed; falling back to XLA")
                from plenum_tpu.ops import mesh as mesh_mod
                from plenum_tpu.ops import sha256_pallas as sp
                mesh_mod.disable_pallas_backend(sp.PALLAS_ENV)
                backend = select_backend(padded)
                if backend.startswith("pallas"):
                    backend = "plain"

    def build(self, leaves: Sequence[bytes]) -> bytes:
        """Hash `leaves` and every interior level on device; → root."""
        n = len(leaves)
        if n == 0:
            self.reset()
            return self.hasher.hash_empty()
        padded = _pow2_at_least(n)
        msgs = [b"\x00" + d for d in leaves]
        if padded > n:
            # pad garbage only ever mixes into INCOMPLETE nodes, which
            # no read path touches
            msgs = msgs + [msgs[-1]] * (padded - n)
        depth = padded.bit_length() - 1
        ln0 = len(msgs[0])
        dm = _get_mesh()
        # builds shard the tree's power-of-two capacity as-is (no extra
        # row padding), so the capacity must divide over the mesh —
        # with a sub-device-count MESH_SHARD_MIN the gate can pass on a
        # tree smaller than the device count, where device_put would
        # reject the sharding
        shard = dm.should_shard(padded) and padded % dm.n_devices == 0
        if all(len(m) == ln0 for m in msgs):
            # uniform leaves: upload raw bytes, pad/pack on device
            nblocks = 1
            while nblocks * 64 < ln0 + 9:
                nblocks *= 2
            raw = np.frombuffer(b"".join(msgs), dtype=np.uint8) \
                .reshape(padded, ln0)
            nv_host = np.full((padded,), (ln0 + 9 + 63) // 64,
                              dtype=np.int32)
            if shard:
                raw_dev, nvalid = dm.put_sharded([raw, nv_host])
            else:
                raw_dev, nvalid = jnp.asarray(raw), jnp.asarray(nv_host)
            words = _pack_uniform(raw_dev, ln0, nblocks)
        else:
            host_words, host_nvalid, nblocks = pad_messages(msgs)
            if shard:
                words, nvalid = dm.put_sharded([host_words, host_nvalid])
            else:
                words = jnp.asarray(host_words)
                nvalid = jnp.asarray(host_nvalid)
        def launch(be):
            _get_telemetry().record_launch(
                _TM_SEAM_BUILD, n, padded, shape=(padded, nblocks))
            if shard:
                return _to_default_device(dm.dispatch(
                    lambda w, nv: _build_levels(w, nv, nblocks, depth, be),
                    [words, nvalid], n=padded))
            dm.note_passthrough(padded)
            with self.tracer.span("merkle_build_dispatch", CAT_DEVICE,
                                  n=padded):
                return _build_levels(words, nvalid, nblocks, depth, be)

        levels = self._run_build(launch, padded, ("leaves", nblocks, depth))
        self._levels = list(levels)
        self._size, self._cap = n, padded
        self._mirror, self._mirror_count, self._froot_cache = {}, {}, {}
        self._repl_cache = {}
        return self.root_hash

    def build_from_leaf_hashes(self, digests) -> bytes:
        """Build the device levels from precomputed RFC 6962 LEAF
        DIGESTS (list of 32-byte bytes or uint8 [n, 32]) — the resync
        path from a hash store: no leaf hashing, one fused dispatch."""
        arr = self._digest_rows(digests)
        n = arr.shape[0]
        if n == 0:
            self.reset()
            return self.hasher.hash_empty()
        padded = _pow2_at_least(n)
        if padded > n:
            arr = np.concatenate(
                [arr, np.zeros((padded - n, 32), dtype=np.uint8)])
        depth = padded.bit_length() - 1
        dm = _get_mesh()
        shard = dm.should_shard(padded) and padded % dm.n_devices == 0

        def launch(be):
            _get_telemetry().record_launch(
                _TM_SEAM_BUILD, n, padded, shape=(padded, 1))
            if shard:
                return _to_default_device(dm.dispatch(
                    lambda a: _build_levels_from_digest_bytes(a, depth, be),
                    [arr], n=padded))
            dm.note_passthrough(padded)
            with self.tracer.span("merkle_build_dispatch", CAT_DEVICE,
                                  n=padded):
                return _build_levels_from_digest_bytes(
                    jnp.asarray(arr), depth, be)

        levels = self._run_build(launch, padded, ("digests", depth))
        self._levels = list(levels)
        self._size, self._cap = n, padded
        self._mirror, self._mirror_count, self._froot_cache = {}, {}, {}
        self._repl_cache = {}
        return self.root_hash

    @staticmethod
    def _digest_rows(digests) -> np.ndarray:
        if isinstance(digests, np.ndarray):
            return np.ascontiguousarray(digests, dtype=np.uint8) \
                .reshape(-1, 32)
        return np.frombuffer(b"".join(digests), dtype=np.uint8) \
            .reshape(-1, 32).copy()

    # ------------------------------------------------ incremental append

    def _ensure_capacity(self, n: int):
        if self._levels is None:
            cap = _pow2_at_least(max(n, 1))
            self._cap = cap
            self._levels = [jnp.zeros((cap >> h, 8), dtype=jnp.uint32)
                            for h in range(cap.bit_length())]
            self._mirror, self._mirror_count = {}, {}
            self._repl_cache = {}
            return
        if n <= self._cap:
            return
        new_cap = self._cap
        while new_cap < n:
            new_cap *= 2
        levels = [_grown(lv, new_cap >> h)
                  for h, lv in enumerate(self._levels)]
        for h in range(len(levels), new_cap.bit_length()):
            levels.append(jnp.zeros((new_cap >> h, 8), dtype=jnp.uint32))
        self._levels, self._cap = levels, new_cap
        # complete node rows are immutable, so growth PRESERVES the
        # host mirrors: grow each kept level's array (zero rows for
        # nodes not yet complete) and keep its mirrored-prefix count.
        # Flushing here (the PR-2/PR-4 behavior) made the first proof
        # batch after every capacity doubling re-download the whole
        # mirrored top of the tree — and build() always fills capacity
        # exactly, so the FIRST append after any build paid it (the
        # r05 audit-path regression suspect). Levels that fall below
        # the new _n_low() move to the per-batch device gather.
        n_low = self._n_low()
        for h in list(self._mirror):
            if h < n_low:
                del self._mirror[h]
                self._mirror_count.pop(h, None)
                continue
            rows = new_cap >> h
            old = self._mirror[h]
            if old.shape[0] < rows:
                grown = np.zeros((rows, 32), dtype=np.uint8)
                grown[:old.shape[0]] = old
                self._mirror[h] = grown
        # replica snapshots survive too: _replicated_level re-checks
        # row needs against each snapshot's complete prefix

    def append_leaf_hashes(self, digests, return_nodes: bool = False):
        """Append leaf DIGESTS incrementally: ~2b device hashes for b
        leaves, no rebuild. Levels are hashed in FUSED groups of
        Config.MERKLE_FUSED_LEVELS per device dispatch
        (_append_levels_fused: hash level i, pair in-kernel, hash
        level i+1, …), so an append costs 1 + ceil(levels/K)
        dispatches instead of 1 + levels.

        With return_nodes=True, returns [(height, first_node_index,
        uint8 [cnt, 32])] for every newly COMPLETE node — exactly the
        (start, height) entries a CompactMerkleTree hash store persists
        for the same append."""
        from plenum_tpu.common.config import Config
        arr = self._digest_rows(digests)
        b = arr.shape[0]
        if b == 0:
            return [] if return_nodes else None
        n0 = self._size
        n1 = n0 + b
        self._ensure_capacity(n1)
        bucket0 = _pow2_at_least(b)
        if bucket0 > b:
            arr_up = np.zeros((bucket0, 32), dtype=np.uint8)
            arr_up[:b] = arr
        else:
            arr_up = arr
        _tm_hub = _get_telemetry()
        _tm_hub.record_launch(_TM_SEAM_APPEND, b, bucket0, shape=bucket0)
        with self.tracer.span("merkle_append_dispatch", CAT_DEVICE,
                              levels=0, n=b):
            self._levels[0] = _place(
                self._levels[0], _digest_words(jnp.asarray(arr_up)),
                n0, b)
        self.dispatch_stats["append_dispatches"] += 1
        news = [(0, n0, b, None)]  # level-0 digests are the host input
        fuse = max(1, int(getattr(Config, "MERKLE_FUSED_LEVELS", 1)))
        h = 0
        while True:
            group = []   # [(level, p0, cnt)] for up to `fuse` levels
            while len(group) < fuse:
                level = h + len(group) + 1
                p0 = n0 >> level
                cnt = (n1 >> level) - p0
                if cnt == 0:
                    break
                group.append((level, p0, cnt))
            if not group:
                break
            if len(group) == 1:
                level, p0, cnt = group[0]
                _tm_hub.record_launch(_TM_SEAM_APPEND, cnt,
                                      _pow2_at_least(cnt),
                                      shape=_pow2_at_least(cnt))
                with self.tracer.span("merkle_append_dispatch",
                                      CAT_DEVICE, levels=1, n=cnt):
                    self._levels[level], dig = _append_level_step(
                        self._levels[level - 1], self._levels[level],
                        p0, cnt, _pow2_at_least(cnt))
                digs = (dig,)
            else:
                parents = tuple(self._levels[lv] for lv, _, _ in group)
                buckets = tuple(_pow2_at_least(c) for _, _, c in group)
                p0s = jnp.asarray([p for _, p, _ in group],
                                  dtype=jnp.int32)
                cnts = jnp.asarray([c for _, _, c in group],
                                   dtype=jnp.int32)
                _tm_hub.record_launch(_TM_SEAM_APPEND,
                                      sum(c for _, _, c in group),
                                      sum(buckets), shape=buckets)
                with self.tracer.span("merkle_append_dispatch",
                                      CAT_DEVICE, levels=len(group),
                                      n=int(group[0][2])):
                    outs, digs = _append_levels_fused(
                        self._levels[h], parents, p0s, cnts, buckets,
                        select_backend(buckets[0]))
                for (level, _, _), out_lv in zip(group, outs):
                    self._levels[level] = out_lv
            self.dispatch_stats["append_dispatches"] += 1
            for (level, p0, cnt), dig in zip(group, digs):
                news.append((level, p0, cnt, dig))
            h += len(group)
        self._size = n1
        self._invalidate()
        out = []
        for height, pos, cnt, dig in news:
            mirrored = height in self._mirror
            if not (return_nodes or mirrored):
                continue
            rows = arr[:b] if dig is None \
                else digests_to_array(np.asarray(dig))[:cnt]
            if mirrored and self._mirror_count.get(height, 0) == pos:
                self._mirror[height][pos:pos + cnt] = rows
                self._mirror_count[height] = pos + cnt
            if return_nodes:
                out.append((height, pos, rows))
        return out if return_nodes else None

    # ---------------------------------------------------------- mirrors

    def _ensure_mirrors(self):
        """Materialize/refresh the host mirror of every top level (node
        count <= _TOP_CACHE). One full-level download per build/growth;
        appends keep the mirror fresh incrementally after that."""
        for h in range(self._n_low(), self._depth() + 1):
            want = self._size >> h
            if self._mirror_count.get(h, 0) < want or h not in self._mirror:
                with self.tracer.span("merkle_mirror_download",
                                      CAT_DEVICE, height=h,
                                      rows=int(self._levels[h].shape[0])):
                    self._mirror[h] = digests_to_array(
                        np.asarray(self._levels[h]))
                self._mirror_count[h] = want
                self.dispatch_stats["mirror_level_downloads"] += 1
                self.dispatch_stats["mirror_rows_downloaded"] += \
                    int(self._levels[h].shape[0])

    # ------------------------------------------------------------- reads

    def _node_bytes(self, height: int, index: int) -> bytes:
        mc = self._mirror_count.get(height, 0)
        if index < mc:
            return self._mirror[height][index].tobytes()
        self.dispatch_stats["row_reads"] += 1
        row = np.asarray(_read_row(self._levels[height],
                                   jnp.int32(index)))
        return digests_to_array(row).tobytes()

    @staticmethod
    def _frontier_of(n: int) -> List[Tuple[int, int]]:
        """Full aligned subtrees of a size-n tree: [(height, node_idx)]
        left to right (descending height)."""
        return [(h, (n >> h) - 1)
                for h in range(n.bit_length() - 1, -1, -1)
                if (n >> h) & 1]

    def _frontier_roots(self, n: int) -> List[bytes]:
        roots = self._froot_cache.get(n)
        if roots is None:
            roots = [self._node_bytes(h, idx)
                     for h, idx in self._frontier_of(n)]
            self._froot_cache[n] = roots
        return roots

    @property
    def root_hash(self) -> bytes:
        if self._size == 0:
            return self.hasher.hash_empty()
        roots = self._frontier_roots(self._size)
        accum = roots[-1]
        for r in reversed(roots[:-1]):
            accum = self.hasher.hash_children(r, accum)
        return accum

    # ------------------------------------------- proofs (any tree size)

    def _replicated_level(self, h: int, dm, need_rows: int):
        """Mesh-replicated copy of level h, memoized as a SNAPSHOT:
        complete node rows are immutable, so a replica broadcast when
        the level held `snap` complete nodes serves ANY later gather
        whose sibling rows stay inside that prefix — appends no longer
        invalidate it. (The PR-4 memo was keyed on array IDENTITY,
        and appends swap every level array, so serving under a write
        load re-broadcast the whole bottom of the tree across the mesh
        after every append — the read-path re-materialization the r05
        numbers flagged.) A gather needing rows beyond the snapshot
        re-broadcasts and advances it; a mesh reconfiguration rebuilds
        the sharding object, which misses the identity check."""
        import jax
        sh = dm.replicated()
        cached = self._repl_cache.get(h)
        if cached is not None and cached[2] is sh \
                and cached[1] >= need_rows:
            return cached[0]
        repl = jax.device_put(self._levels[h], sh)
        self.dispatch_stats["replica_broadcasts"] += 1
        self._repl_cache[h] = (repl, self._size >> h, sh)
        return repl

    def _gather_low(self, idx_np: np.ndarray, g: int, n: int):
        """Fused sibling-gather+pack of the bottom g levels for one
        proof batch against the size-`n` prefix. Batches clearing the
        mesh gate (ops/mesh.py) shard the INDEX axis over every chip —
        each proof row is an independent gather — against replicated
        level operands; smaller batches keep the single-device
        dispatch."""
        dm = _get_mesh()
        k = int(idx_np.shape[0])
        self.dispatch_stats["gather_dispatches"] += 1
        if dm.should_shard(k):
            levels = []
            for h in range(g):
                # rows this gather USES at level h: every sibling a
                # leaf's path actually keeps lies inside its full
                # aligned subtree, hence inside the size-n prefix's
                # complete nodes (RFC 6962). The raw sibling max can
                # exceed that — collect discards entries at or above a
                # leaf's subtree height — so clamp to the complete
                # count or the snapshot memo could never satisfy a
                # batch touching the ragged tail.
                need = min(int(np.max((idx_np >> h) ^ 1)) + 1,
                           max(1, n >> h))
                levels.append(self._replicated_level(h, dm, need))
            levels = tuple(levels)
            kp = dm.padded_size(k, min_per_device=1)
            idx_p = idx_np if kp == k else np.concatenate(
                [idx_np, np.repeat(idx_np[:1], kp - k)])
            low = dm.dispatch(lambda ix: _gather_pack(levels, ix),
                              [idx_p], n=k)
            return low[:k] if kp != k else low
        dm.note_passthrough(k)
        return _gather_pack(tuple(self._levels[:g]), jnp.asarray(idx_np))

    def dispatch_proof_batch(self, indices: Sequence[int],
                             n: Optional[int] = None):
        """Start the device gather for one RFC 6962 inclusion-proof
        batch against the size-`n` prefix tree (default: current size).
        Pair with collect_proof_batch; interleaving dispatch/collect
        across batches overlaps the next gather with the current
        download (ProofPipeline does this for you)."""
        n = self._size if n is None else n
        if not 0 < n <= self._size:
            raise ValueError("invalid proof-batch size {} for tree of "
                             "size {}".format(n, self._size))
        idx_np = np.asarray(list(indices), dtype=np.int32)
        if idx_np.size and not (0 <= idx_np.min()
                                and int(idx_np.max()) < n):
            raise ValueError("proof index out of range for size "
                             "{}".format(n))
        if n == 1:
            return (idx_np, None, n, 0, [], [])
        self._ensure_mirrors()
        fr = self._frontier_of(n)
        roots = self._frontier_roots(n)
        h0 = fr[0][0]
        g = min(self._n_low(), h0)
        low = None
        if g and idx_np.size:
            low = self._gather_low(idx_np, g, n)
            _start_async_copy(low)
        return (idx_np, low, n, g, fr, roots)

    def collect_proof_batch(self, handle) -> List[List[bytes]]:
        """Await a dispatch_proof_batch handle → per-leaf RFC 6962
        audit paths (leaf-sibling first), byte-identical to
        CompactMerkleTree.inclusion_proofs_batch."""
        idx_np, low, n, g, fr, roots = handle
        k = idx_np.shape[0]
        if n == 1 or k == 0:
            return [[] for _ in range(k)]
        low_np = (np.asarray(low).reshape(k, g, 32)
                  if low is not None else None)
        r = len(fr)
        starts = np.asarray([node_idx << h for h, node_idx in fr],
                            dtype=np.int64)
        js = np.searchsorted(starts, idx_np.astype(np.int64),
                             side="right") - 1
        # MTH of everything right of subtree j, shared across the batch
        sfx: List[Optional[bytes]] = [None] * r
        accum = None
        hash_children = self.hasher.hash_children
        for j in range(r - 1, 0, -1):
            accum = roots[j] if accum is None \
                else hash_children(roots[j], accum)
            sfx[j - 1] = accum
        h0 = fr[0][0]
        # vectorized host joins for the mirrored middle heights
        mirror_cols = {h: self._mirror[h][(idx_np >> h) ^ 1]
                       for h in range(g, h0)}
        out = []
        for i in range(k):
            j = int(js[i])
            hj = fr[j][0]
            path = []
            for h in range(hj):
                if h < g:
                    path.append(low_np[i, h].tobytes())
                else:
                    path.append(mirror_cols[h][i].tobytes())
            if j < r - 1:
                path.append(sfx[j])
            for jj in range(j - 1, -1, -1):
                path.append(roots[jj])
            out.append(path)
        return out

    def inclusion_proofs(self, indices: Sequence[int],
                         n: Optional[int] = None) -> List[List[bytes]]:
        """Audit paths for many leaves of the size-n prefix tree, served
        from device levels — works for ANY n <= tree_size."""
        return self.collect_proof_batch(
            self.dispatch_proof_batch(indices, n))

    # ------------------------------ dense power-of-two fast path (bench)

    def _check_pow2(self):
        if self._size != self._cap:
            raise ValueError("dense audit-path batches need a "
                             "power-of-two tree (got size {}); use "
                             "inclusion_proofs for ragged sizes"
                             .format(self._size))

    def dispatch_path_batch(self, indices: Sequence[int]):
        """Dense power-of-two variant of dispatch_proof_batch: the
        collect returns one uint8[k, depth, 32] buffer."""
        self._check_pow2()
        idx_np = np.asarray(list(indices), dtype=np.int32)
        if self._depth() == 0:
            return (idx_np, None)
        self._ensure_mirrors()
        g = min(self._n_low(), self._depth())
        low = None
        if g:
            low = self._gather_low(idx_np, g, self._size)
            _start_async_copy(low)
        return (idx_np, low)

    def collect_path_batch(self, handle) -> np.ndarray:
        """Await a dispatch_path_batch handle -> uint8[k, depth, 32]
        (leaf-sibling first). The device half arrives already packed
        big-endian (no host byteswap); top levels come from the host
        mirror via vectorized numpy gathers."""
        idx_np, low = handle
        depth = self._depth()
        k = idx_np.shape[0]
        out = np.empty((k, depth, 32), dtype=np.uint8)
        g = min(self._n_low(), depth)
        if low is not None:
            out[:, :g] = np.asarray(low).reshape(k, g, 32)
        for h in range(g, depth):
            out[:, h] = self._mirror[h][(idx_np >> h) ^ 1]
        return out

    def audit_path_batch_array(self, indices) -> np.ndarray:
        """Audit paths for many leaves -> uint8[k, depth, 32] in one
        device gather (bottom levels) + host joins (mirrored top
        levels). Power-of-two sizes only (the dense shape); ragged
        sizes go through inclusion_proofs."""
        return self.collect_path_batch(self.dispatch_path_batch(indices))

    def audit_path_batch(self, indices: Sequence[int]) -> List[List[bytes]]:
        """List-of-lists audit paths for the CURRENT tree size — ragged
        sizes included (RFC 6962 frontier decomposition)."""
        if self._size == self._cap:
            # dense fast path
            if self._depth() == 0:
                return [[] for _ in indices]
            arr = self.audit_path_batch_array(indices)
            k, depth = arr.shape[0], arr.shape[1]
            flat = arr.reshape(k * depth, 32).tobytes()
            mv = memoryview(flat)
            return [[bytes(mv[(i * depth + h) * 32:
                             (i * depth + h + 1) * 32])
                     for h in range(depth)] for i in range(k)]
        return self.inclusion_proofs(indices, self._size)

    def verify_path(self, leaf: bytes, index: int, path: List[bytes],
                    root: bytes) -> bool:
        """Power-of-two fold check (kept for the dense bench path; use
        MerkleVerifier for ragged sizes)."""
        h = self.hasher.hash_leaf(leaf)
        for height, sibling in enumerate(path):
            if (index >> height) & 1:
                h = self.hasher.hash_children(sibling, h)
            else:
                h = self.hasher.hash_children(h, sibling)
        return h == root


class ProofPipeline:
    """Double-buffered proof-batch streamer over a DeviceMerkleTree.

    Generalizes the dispatch/collect interleave into the serving shape
    used by `Ledger.merkleInfoBatch` routing and the catchup rep
    seeder: up to `depth` gathers stay in flight, so the device works
    on batch i+1 while the host drains batch i's download (the D2H
    tunnel is the bottleneck)."""

    def __init__(self, tree: DeviceMerkleTree, depth: int = 2,
                 dense: bool = False, tracer=None):
        from plenum_tpu.observability.tracing import NullTracer
        self._tree = tree
        self._depth = max(1, depth)
        self._dense = dense
        self._tracer = tracer or NullTracer()

    def stream(self, batches, n: Optional[int] = None):
        """Yield one result per index batch, in order. Results are
        uint8[k, depth, 32] buffers in dense mode, per-leaf bytes-list
        paths otherwise."""
        if self._dense:
            dispatch = self._tree.dispatch_path_batch
            collect = self._tree.collect_path_batch
        else:
            dispatch = functools.partial(
                self._tree.dispatch_proof_batch, n=n)
            collect = self._tree.collect_proof_batch
        from plenum_tpu.observability.tracing import CAT_DEVICE
        tracer = self._tracer
        pending = deque()
        for batch in batches:
            # dispatch span = host-side launch cost; the in-flight
            # counter shows whether the double-buffering actually keeps
            # the device busy between collects
            with tracer.span("proof_dispatch", CAT_DEVICE, n=len(batch)):
                pending.append(dispatch(batch))
            tracer.counter("proof_inflight", len(pending))
            if len(pending) >= self._depth:
                with tracer.span("proof_collect", CAT_DEVICE):
                    out = collect(pending.popleft())
                yield out
        while pending:
            with tracer.span("proof_collect", CAT_DEVICE):
                out = collect(pending.popleft())
            yield out

    def run(self, indices: Sequence[int], n: Optional[int] = None,
            chunk: int = None) -> List[List[bytes]]:
        """Split one large proof request into pipelined chunks and
        return the concatenated per-leaf paths. chunk defaults from
        Config.MERKLE_DEVICE_PROOF_CHUNK (single-sourced; explicit
        callers — the ledger routing — pass their own)."""
        if chunk is None:
            from plenum_tpu.common.config import Config
            chunk = Config.MERKLE_DEVICE_PROOF_CHUNK
        idx = list(indices)
        if not idx:
            return []
        batches = [idx[i:i + chunk] for i in range(0, len(idx), chunk)]
        out: List[List[bytes]] = []
        for part in self.stream(batches, n=n):
            out.extend(part)
        return out
