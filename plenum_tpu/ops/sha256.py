"""Batched SHA-256 in JAX — the merkle tree's TPU hash path.

The reference hashes merkle leaves/nodes one at a time through OpenSSL
(`ledger/tree_hasher.py:7`, `hashlib.sha256`). Here the compression
function is a pure uint32 JAX program, `vmap`-style batched over thousands
of independent messages per device step: leaf hashing during bulk ledger
append/catchup, node hashing level-by-level when rebuilding or batch-proving
(BASELINE.json "1M-leaf audit-path batch" config).

Design notes (TPU-first):
 - All arithmetic is uint32 — native on the VPU; no 64-bit emulation.
 - Message padding happens on host (cheap, data-dependent lengths); the
   device sees fixed-shape [batch, nblocks, 16] uint32 words plus a
   per-message block count, and masks inactive blocks inside a lax.scan.
 - One compiled executable per (nblocks) bucket; callers bucket message
   lengths (merkle node hashes are always exactly 2 blocks: 65 bytes).
 - The 64 rounds run under lax.fori_loop with the schedule computed
   in-loop from a rolling 16-word window, keeping VMEM pressure flat.

Backend routing (`select_backend` / `compress_blocks`): on a real
accelerator, batches of a kernel block or more run the fused Pallas
compression kernel (ops/sha256_pallas.py — schedule + 64 rounds in
VMEM, no op-by-op lowering); on the CPU backend, large batches run the
same XLA expression TILED over cache-sized chunks (`lax.map`) so the
per-op temps stay L2-resident instead of sweeping HBM per op (~2.4x
measured); small batches keep the plain expression. The routing is a
trace-time (static) decision, so ops/merkle's fused build jit rides
whichever backend the caller selected.
"""
from __future__ import annotations

import functools
import logging
from typing import List, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from plenum_tpu.observability import telemetry as _tmy
from plenum_tpu.ops import pow2_at_least, scatter_ragged_rows

logger = logging.getLogger(__name__)

_IV = np.array([
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
], dtype=np.uint32)

_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5,
    0x3956c25b, 0x59f111f1, 0x923f82a4, 0xab1c5ed5,
    0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174,
    0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7,
    0xc6e00bf3, 0xd5a79147, 0x06ca6351, 0x14292967,
    0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3,
    0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5,
    0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f, 0x682e6ff3,
    0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
], dtype=np.uint32)


def _rotr(x, n):
    return (x >> jnp.uint32(n)) | (x << jnp.uint32(32 - n))


def _compress(state, block):
    """One SHA-256 compression. state: [..., 8] u32, block: [..., 16] u32."""
    a, b, c, d, e, f, g, h = [state[..., i] for i in range(8)]
    k = jnp.asarray(_K)

    # Rolling 16-word schedule window, advanced one word per round.
    w = jnp.moveaxis(block, -1, 0)  # [16, ...]

    def round_fn(t, carry):
        a, b, c, d, e, f, g, h, w = carry
        wt = w[0]
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k[t] + wt
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        # next schedule word from the rolling window
        w1 = w[1]
        w14 = w[14]
        sig0 = _rotr(w1, 7) ^ _rotr(w1, 18) ^ (w1 >> jnp.uint32(3))
        sig1 = _rotr(w14, 17) ^ _rotr(w14, 19) ^ (w14 >> jnp.uint32(10))
        w_next = w[0] + sig0 + w[9] + sig1
        w = jnp.concatenate([w[1:], w_next[None]], axis=0)
        return (t1 + t2, a, b, c, d + t1, e, f, g, w)

    init = (a, b, c, d, e, f, g, h, w)
    a, b, c, d, e, f, g, h, _ = lax.fori_loop(0, 64, round_fn, init)
    out = jnp.stack([a, b, c, d, e, f, g, h], axis=-1)
    return state + out


@functools.partial(jax.jit, static_argnames=("nblocks",))
def _sha256_blocks(blocks, nvalid, nblocks: int):
    """blocks: [B, nblocks, 16] u32; nvalid: [B] i32 → digests [B, 8] u32."""
    state = jnp.broadcast_to(jnp.asarray(_IV), blocks.shape[:-2] + (8,))

    def step(state, xs):
        block, idx = xs
        new = _compress(state, block)
        mask = (idx < nvalid)[..., None]
        return jnp.where(mask, new, state), None

    idxs = jnp.arange(nblocks, dtype=jnp.int32)
    # scan over the block axis
    blocks_t = jnp.moveaxis(blocks, -2, 0)  # [nblocks, B, 16]
    state, _ = lax.scan(step, state, (blocks_t, idxs))
    return state


@functools.partial(jax.jit, static_argnames=("nblocks", "tile"))
def _sha256_blocks_tiled(blocks, nvalid, nblocks: int, tile: int):
    """CPU-backend variant of _sha256_blocks: identical math, but the
    batch axis is processed `tile` rows at a time under lax.map so
    every intermediate of the ~1600-op compression chain is a
    tile-sized (L2-resident) temp instead of a batch-wide HBM sweep —
    the XLA CPU lowering is memory-bound without it (~2.4x measured at
    tile=4096 on 1M-row batches). Requires B % tile == 0 (callers pad;
    merkle level sizes are powers of two)."""
    b = blocks.shape[0]
    bt = blocks.reshape(b // tile, tile, nblocks, 16)
    nvt = nvalid.reshape(b // tile, tile)

    def one(args):
        blk, nv = args
        state = jnp.broadcast_to(jnp.asarray(_IV), (tile, 8))

        def step(state, xs):
            block, idx = xs
            new = _compress(state, block)
            mask = (idx < nv)[..., None]
            return jnp.where(mask, new, state), None

        idxs = jnp.arange(nblocks, dtype=jnp.int32)
        state, _ = lax.scan(step, state,
                            (jnp.moveaxis(blk, -2, 0), idxs))
        return state

    return lax.map(one, (bt, nvt)).reshape(b, 8)


# ------------------------------------------------------ backend routing

def _config_tile() -> int:
    from plenum_tpu.common.config import Config
    return Config.SHA256_CPU_TILE


def select_backend(batch_rows: int) -> str:
    """Trace-time backend decision for one compression dispatch:
    "pallas" (accelerator, batch fills a kernel block), "tiled" (CPU
    backend, batch spans 2+ cache tiles) or "plain". The env override
    PLENUM_TPU_SHA256_BACKEND supports "xla" (disable Pallas — the
    shared probe handles it) and "pallas_interp" (force the Pallas
    kernel in interpreter mode: byte-for-byte kernel coverage on
    CPU-only hosts; tests use it through this exact seam)."""
    import os
    from plenum_tpu.common.config import Config
    from plenum_tpu.ops import mesh as mesh_mod
    from plenum_tpu.ops import sha256_pallas as sp
    if os.environ.get(sp.PALLAS_ENV) == "pallas_interp" \
            and batch_rows >= sp.BLOCK:
        return "pallas_interp"
    if sp.pallas_available() \
            and batch_rows >= Config.SHA256_PALLAS_MIN_BATCH:
        return "pallas"
    if mesh_mod.probe_platform() == "cpu" \
            and batch_rows >= 2 * Config.SHA256_CPU_TILE:
        return "tiled"
    return "plain"


def compress_blocks(blocks, nvalid, nblocks: int, backend: str = "plain"):
    """Route one [B, nblocks, 16]-words compression to `backend`.
    Traceable — ops/merkle's fused build/append jits call this inline
    with a static backend string; the pallas_call and the lax.map tile
    loop both trace into the enclosing jit."""
    if backend in ("pallas", "pallas_interp"):
        from plenum_tpu.ops import sha256_pallas as sp
        if int(blocks.shape[0]) >= sp.BLOCK:
            return sp.sha256_blocks(blocks, nvalid, nblocks,
                                    interpret=(backend == "pallas_interp"))
        # small batches (the top tree levels inside a fused build jit)
        # would pad to a full kernel block — the plain expression is
        # cheaper than hashing up to BLOCK-1 garbage rows
        return _sha256_blocks(blocks, nvalid, nblocks)
    if backend == "tiled":
        tile = _config_tile()
        b = int(blocks.shape[0])
        if b % tile == 0 and b >= 2 * tile:
            return _sha256_blocks_tiled(blocks, nvalid, nblocks, tile)
    return _sha256_blocks(blocks, nvalid, nblocks)


_ROUTED_VALIDATED = set()     # (backend, nblocks) whose execution completed


def sha256_blocks_routed(blocks, nvalid, nblocks: int):
    """Standalone dispatch half with backend routing + the Pallas
    fallback chain: pick the backend for this batch size, launch, and
    prove execution ONCE per (backend, nblocks) — JAX dispatch is
    async, so a runtime failure at an untested shape would otherwise
    surface at the caller's np.asarray, outside any except, and the
    fallback would never engage (ed25519_jax._dispatch_kernel
    precedent). Any Pallas failure steps down to the XLA expression
    permanently (shared probe registry)."""
    backend = select_backend(int(blocks.shape[0]))
    while True:
        tile = _config_tile()
        b = int(blocks.shape[0])
        pad = (-b) % tile if backend == "tiled" else 0
        try:
            if pad:
                bl = jnp.pad(blocks, ((0, pad), (0, 0), (0, 0)))
                nv = jnp.pad(nvalid, (0, pad), constant_values=1)
                out = compress_blocks(bl, nv, nblocks, backend)
            else:
                out = compress_blocks(blocks, nvalid, nblocks, backend)
            if backend.startswith("pallas") \
                    and (backend, nblocks) not in _ROUTED_VALIDATED:
                # deliberate ONE-TIME sync per shape family to prove
                # execution; later calls stay fully async
                out.block_until_ready()  # plenum-lint: disable=PT002
                _ROUTED_VALIDATED.add((backend, nblocks))
            return out[:b] if pad else out
        except Exception:  # pragma: no cover  # plenum-lint: disable=PT006
            # the fallback engine itself: ANY Pallas failure (VMEM,
            # lowering, runtime) must step down to the XLA expression,
            # never crash a hash path
            if not backend.startswith("pallas"):
                raise
            logger.exception("pallas sha256 failed; falling back to XLA")
            from plenum_tpu.ops import mesh as mesh_mod
            from plenum_tpu.ops import sha256_pallas as sp
            mesh_mod.disable_pallas_backend(sp.PALLAS_ENV)
            backend = select_backend(b)
            if backend.startswith("pallas"):
                backend = "plain"


def pad_messages(msgs: Sequence[bytes], nblocks: int = None
                 ) -> Tuple[np.ndarray, np.ndarray, int]:
    """SHA-pad `msgs` into ([B, nblocks, 16] u32 big-endian words, [B] i32)."""
    need = [(len(m) + 9 + 63) // 64 for m in msgs]
    maxb = max(need) if need else 1
    if nblocks is None:
        # bucket to power of two to bound recompiles
        nblocks = 1
        while nblocks < maxb:
            nblocks *= 2
    assert maxb <= nblocks
    ln0 = len(msgs[0]) if msgs else 0
    uniform = bool(msgs) and all(len(m) == ln0 for m in msgs)
    if not msgs or uniform:
        out = np.zeros((len(msgs), nblocks * 64), dtype=np.uint8)
    if uniform:
        # uniform lengths (merkle node hashes, fixed-size leaves): one
        # vectorized fill instead of a per-message Python loop — the
        # host-side padding is the bottleneck at 1M-leaf scale
        out[:, :ln0] = np.frombuffer(b"".join(msgs), dtype=np.uint8) \
            .reshape(len(msgs), ln0)
        out[:, ln0] = 0x80
        end = need[0] * 64
        out[:, end - 8:end] = np.frombuffer(
            (ln0 * 8).to_bytes(8, "big"), dtype=np.uint8)
    elif msgs:
        # mixed lengths: one flat vectorized scatter covering every
        # block-count bucket at once (shared core in
        # ops.scatter_ragged_rows — sha3 pads through the same helper).
        # The bucket (block count) only decides where each row's 64-bit
        # length field lands, and the row-relative scatter handles that
        # per message.
        width = nblocks * 64
        out, lens = scatter_ragged_rows(msgs, width)
        flat = out.reshape(-1)
        rows = np.arange(len(msgs), dtype=np.int64)
        flat[rows * width + lens] = 0x80
        ends = np.asarray(need, dtype=np.int64) * 64
        bits = lens * 8
        base = rows * width + ends - 8
        for k in range(8):
            flat[base + k] = (bits >> (8 * (7 - k))) & 0xff
    words = out.reshape(len(msgs), nblocks, 16, 4)
    words = (words[..., 0].astype(np.uint32) << 24
             | words[..., 1].astype(np.uint32) << 16
             | words[..., 2].astype(np.uint32) << 8
             | words[..., 3].astype(np.uint32))
    nvalid = np.asarray(need, dtype=np.int32)
    # block-lane accounting: every message occupies a full `nblocks`
    # row on device but only `need` of its blocks do compression work —
    # the bucket's wasted compressions are this seam's padding
    _tmy.get_seam_hub().record_launch(
        _tmy.SEAM_SHA256, int(nvalid.sum()), len(msgs) * nblocks,
        shape=(len(msgs), nblocks))
    return words, nvalid, nblocks


def digests_to_bytes(dig: np.ndarray) -> List[bytes]:
    """[B, 8] u32 → list of 32-byte digests."""
    arr = np.asarray(dig).astype(">u4")
    return [arr[i].tobytes() for i in range(arr.shape[0])]


def digests_to_array(dig: np.ndarray) -> np.ndarray:
    """[B, 8] u32 → [B, 32] u8 big-endian digest bytes: the array
    sibling of digests_to_bytes for callers that immediately re-consume
    the digests (level pairing, device upload, dense proof buffers)
    instead of needing per-digest Python bytes objects."""
    arr = np.ascontiguousarray(np.asarray(dig).astype(">u4"))
    return arr.view(np.uint8).reshape(-1, 32)


@jax.jit
def _node_words_from_digest_pairs(pairs_u8):
    """[m, 64] u8 rows (left||right digest bytes) → [m, 2, 16] u32
    SHA-padded words for H(0x01 || left || right), entirely on device —
    no per-pair Python message objects on host."""
    m = pairs_u8.shape[0]
    out = jnp.zeros((m, 128), dtype=jnp.uint8)
    out = out.at[:, 0].set(jnp.uint8(0x01))
    out = out.at[:, 1:65].set(pairs_u8)
    out = out.at[:, 65].set(jnp.uint8(0x80))
    out = out.at[:, 120:128].set(jnp.asarray(
        np.frombuffer((65 * 8).to_bytes(8, "big"), dtype=np.uint8)))
    w = out.reshape(m, 2, 16, 4).astype(jnp.uint32)
    return (w[..., 0] << 24) | (w[..., 1] << 16) | (w[..., 2] << 8) \
        | w[..., 3]


def sha256_node_pairs_array(pairs: np.ndarray) -> np.ndarray:
    """[m, 64] u8 rows of left||right digests → [m, 32] u8 node digests
    H(0x01||l||r). Digest bytes stay in arrays end to end."""
    pairs = np.ascontiguousarray(pairs, dtype=np.uint8).reshape(-1, 64)
    m = pairs.shape[0]
    # bucket the row axis: level-wise bulk builds hand this every
    # distinct level size, and the raw m paid one XLA compile each
    # (the PT014 per-distinct-size incident class); pad rows hash
    # garbage the tail slice drops
    mp = pow2_at_least(max(m, 1))
    if mp != m:
        padded = np.zeros((mp, 64), dtype=np.uint8)
        padded[:m] = pairs
        pairs = padded
    words = _node_words_from_digest_pairs(jnp.asarray(pairs))
    nvalid = jnp.full((pairs.shape[0],), 2, dtype=jnp.int32)
    return digests_to_array(np.asarray(
        sha256_blocks_routed(words, nvalid, 2)))[:m]


def sha256_many(msgs: Sequence[bytes]) -> List[bytes]:
    """Batched SHA-256 over arbitrary same-or-mixed-length messages."""
    return sha256_many_collect(sha256_many_dispatch(msgs))


def sha256_many_dispatch(msgs: Sequence[bytes]):
    """Async half of sha256_many: host padding + device LAUNCH, no
    result sync — the returned handle's digests are still in flight, so
    the caller can overlap independent host work (the fused per-3PC-
    batch dispatch overlaps the MPT pending-apply under this launch)
    before sha256_many_collect pulls the bytes."""
    if not msgs:
        return None
    words, nvalid, nblocks = pad_messages(msgs)
    return sha256_blocks_routed(jnp.asarray(words), jnp.asarray(nvalid),
                                nblocks)


def sha256_many_collect(handle) -> List[bytes]:
    """Blocking half: digests of a sha256_many_dispatch launch."""
    if handle is None:
        return []
    return digests_to_bytes(np.asarray(handle))


class JaxSha256Backend:
    """Batch backend for `TreeHasher` (ledger/tree_hasher.py seam)."""

    def leaf_hashes(self, datas: Sequence[bytes]) -> List[bytes]:
        return sha256_many([b"\x00" + d for d in datas])

    def leaf_hashes_dispatch(self, datas: Sequence[bytes]):
        """Launch-only half of leaf_hashes (fused-dispatch seam)."""
        return sha256_many_dispatch([b"\x00" + d for d in datas])

    def leaf_hashes_collect(self, handle) -> List[bytes]:
        return sha256_many_collect(handle)

    def node_hashes(self, pairs: Sequence[Tuple[bytes, bytes]]) -> List[bytes]:
        return sha256_many([b"\x01" + l + r for l, r in pairs])

    def node_hashes_array(self, pairs: np.ndarray) -> np.ndarray:
        """[m, 64] u8 (left||right) → [m, 32] u8 — the array seam for
        level-wise bulk tree building (no per-pair Python objects)."""
        return sha256_node_pairs_array(pairs)


_default_backend = None


def get_default_backend() -> JaxSha256Backend:
    """Process-wide backend so every ledger shares the compiled
    executables (one per nblocks bucket)."""
    global _default_backend
    if _default_backend is None:
        _default_backend = JaxSha256Backend()
    return _default_backend
