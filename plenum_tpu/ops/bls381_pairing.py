"""Batched BLS12-381 pairing verification and windowed MSM (JAX).

The last crypto hot path living outside the device: every multi-sig
*verify* bottoms out in two scalar pairings per signature
(``crypto/bls_ops.multi_pairing_is_one``). This module batches MANY
independent pairing-product checks into ONE device dispatch — B jobs x
P (G1, G2) pairs in, B booleans out — so the Miller loops of a whole
committee's worth of proofs and the single shared final exponentiation
amortize one launch, exactly the `aggregate_dispatch` recipe one level
up the tower.

Kernel shape (see ops/bls381_tower.py for the field layer):

 - decompress G1 (bls381_jax) and G2 (tower fp2 sqrt) for all B*P
   points at once;
 - one branchless Jacobian Miller loop (fori over the 63 fixed bits of
   |x|, addition step always computed and bit-selected) accumulating
   the sparse line A + B*w^3 + C*w^5 per pair — every multiply layer
   is ONE stacked mont_mul across all pairs and Karatsuba lanes;
 - fp12 product over the pair axis, then ONE shared final
   exponentiation: easy part conj*inv + frobenius^2, hard part a w=2
   windowed fori over the 635 base-4 digits of (q^4-q^2+1)/r;
 - verdict: product == 1 AND every pair of the job decoded. Invalid /
   infinity pairs contribute the neutral factor (their curve slots are
   filled with generator points so the arithmetic stays nondegenerate,
   then masked to one) — garbage can flip a verdict to False, never
   crash, and the condition f^((q^12-1)/r) == 1 is the SAME exponent
   test the python/native backends apply, so verdicts match bit for
   bit for every decodable input.

The MSM kernel aggregates sum(s_i * P_i) for one shared-weight set per
dispatch: per-point multiples table (w=4, 16 entries, complete RCB
additions so the identity rows cost nothing), a Horner fori over the
64 scalar nibbles, then a log2(N) tree sum.

Routing: `crypto/bls_ops` consults `mesh.xla_backend_enabled(ENV)` and
steps the whole family down permanently on any device failure — same
registry, same validate-once discipline as the Pallas SHA-256 path.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from plenum_tpu.observability import telemetry as _tmy
from plenum_tpu.ops import pow2_at_least
from plenum_tpu.ops.bls381_jax import (
    NLIMB, Q, _limbs_to_ints, _proj_to_affine,
    decompress, from_mont, fcanon, pack_compressed)
from plenum_tpu.ops.bls381_tower import (
    TV, _Fp2Field, _FqField, _mont_l, _norm, _radd, _rsub, _tstack,
    fp2_mul_many, fp12_conj, fp12_frob2, fp12_inv, fp12_mul, fp12_one,
    fp12_eq_one, fp12_sq, g2_decompress, g2_identity, pack_g2_compressed,
    padd_rcb, tneg, _ONE2_M)

# step-down family for the whole device tower path (pairing + MSM +
# G2 aggregation); "native"/"off" pins the scalar backends. Defined in
# crypto/bls_ops (the router) so the two never diverge.
from plenum_tpu.crypto.bls_ops import BLS_TOWER_ENV  # noqa: E402

# ---------------------------------------------------------------- constants

X_ABS = 0xD201000000010000                    # |x|, the BLS parameter
R_ORD = 0x73EDA753299D7D483339D80809A1D805_53BDA402FFFE5BFEFFFFFFFF00000001
_MILLER_BITS = np.array(
    [int(b) for b in bin(X_ABS)[2:]][1:], dtype=np.int32)

_HARD_D = (Q ** 4 - Q ** 2 + 1) // R_ORD      # hard-part exponent


def _base4_digits(e: int) -> np.ndarray:
    out = []
    while e:
        out.append(e & 3)
        e >>= 2
    return np.array(out[::-1], dtype=np.int32)


_HARD_DIGITS = _base4_digits(_HARD_D)
assert _HARD_DIGITS[0] != 0

# generators (standard BLS12-381), substituted into inactive pair
# slots so the branchless curve arithmetic never degenerates
_G1X = int("17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905"
           "A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB", 16)
_G1Y = int("08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF6"
           "00DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1", 16)
_G2X = (int("024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02"
            "B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8", 16),
        int("13E02B6052719F607DACD3A088274F65596BD0D09920B61A"
            "B5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E", 16))
_G2Y = (int("0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A7"
            "6D429A695160D12C923AC9CC3BACA289E193548608B82801", 16),
        int("0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF"
            "267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE", 16))
assert (_G1Y * _G1Y - _G1X ** 3 - 4) % Q == 0
_G1X_M = _mont_l(_G1X)
_G1Y_M = _mont_l(_G1Y)
_G2X_M = np.stack([_mont_l(_G2X[0]), _mont_l(_G2X[1])])
_G2Y_M = np.stack([_mont_l(_G2Y[0]), _mont_l(_G2Y[1])])


# ------------------------------------------------------------ Miller loop

def _sparse12(A: TV, Bc: TV, C: TV) -> TV:
    """Line value A + B·w^3 + C·w^5 as a full fp12 element: fp2 slots
    0 (c0.e0), 4 (c1.e1), 5 (c1.e2) — w-power k = i + 2j."""
    A = _norm(A, 2.0)
    Bc = _norm(Bc, 2.0)
    C = _norm(C, 2.0)
    z = jnp.zeros_like(A.a[..., 0, :])
    rows = [A.a[..., 0, :], A.a[..., 1, :], z, z, z, z, z, z,
            Bc.a[..., 0, :], Bc.a[..., 1, :],
            C.a[..., 0, :], C.a[..., 1, :]]
    return TV(jnp.stack(rows, axis=-2), 2.0)


def _lane(p: TV, k: int) -> TV:
    return TV(p.a[..., k, :, :], p.b)


def _n2(t: TV) -> TV:
    return _norm(t, 2.0)


def _miller(px, py, qx, qy) -> TV:
    """Batched ate Miller loop. px/py: [..., 32] Montgomery affine G1;
    qx/qy: [..., 2, 32] Montgomery affine G2 (twist). Returns the
    conjugated (x < 0) Miller value f as TV [..., 12, 32].

    Jacobian doubling/addition with polynomial (inversion-free) line
    coefficients; the dropped Fq2* scalings (xi, 2YZ^3, HZ) lie in
    subfields killed by the final exponentiation's easy part. The
    addition step runs every iteration and is bit-selected — one traced
    body, no data-dependent control flow."""
    bits_j = jnp.asarray(_MILLER_BITS)
    PXE = TV(jnp.stack([px, jnp.zeros_like(px)], axis=-2), 2.0)
    pyv = TV(py, 2.0)
    two_py = _n2(_radd(pyv, pyv))
    PY2XI = _tstack([two_py, two_py], -2)     # xi·2py (tangent line A)
    PY1XI = _tstack([pyv, pyv], -2)           # xi·py  (chord line A)
    QX = TV(qx, 2.0)
    QY = TV(qy, 2.0)
    one2 = jnp.broadcast_to(jnp.asarray(_ONE2_M), qx.shape)
    f0 = fp12_one(px.shape[:-1])

    def body(i, carry):
        Xa_, Ya_, Za_, fa_ = carry
        X, Y, Z = TV(Xa_, 2.0), TV(Ya_, 2.0), TV(Za_, 2.0)
        f = TV(fa_, 2.0)
        # ---- doubling step: T <- 2T, tangent line, eval at P
        l1 = fp2_mul_many(_tstack([X, Y, Z, Y], -3),
                          _tstack([X, Y, Z, Z], -3))
        X2, Y2, Z2, YZ = (_lane(l1, k) for k in range(4))
        M = _radd(_radd(X2, X2), X2)                      # 3X^2
        l2 = fp2_mul_many(
            _tstack([Y2, X2, X, YZ, X2, M], -3),
            _tstack([Y2, X, Y2, Z2, Z2, M], -3))
        Y4, X3, XY2, YZ3, X2Z2, M2 = (_lane(l2, k) for k in range(6))
        S2x = _radd(XY2, XY2)
        S4 = _n2(_radd(S2x, S2x))                         # 4·X·Y^2
        Xd = _n2(_rsub(_rsub(M2, S4), S4))                # M^2 - 2S
        Zd = _n2(_radd(YZ, YZ))                           # 2YZ
        SmX = _rsub(S4, Xd)
        T3 = _radd(_radd(X2Z2, X2Z2), X2Z2)               # 3·X^2·Z^2
        l3 = fp2_mul_many(
            _tstack([YZ3, T3, M, Zd], -3),
            _tstack([PY2XI, PXE, _n2(SmX), Zd], -3))
        Ad, Cm, MS, Z2a = (_lane(l3, k) for k in range(4))
        e2 = _radd(Y4, Y4)
        e4 = _radd(e2, e2)
        e8 = _radd(e4, e4)                                # 8·Y^4
        Yd = _n2(_rsub(MS, _n2(e8)))
        Bd = _rsub(_radd(_radd(X3, X3), X3),
                   _radd(Y2, Y2))                         # 3X^3 - 2Y^2
        fd = fp12_mul(fp12_sq(f), _sparse12(Ad, Bd, tneg(Cm)))
        # ---- addition step: T <- T + Q, chord line (always computed,
        # bit-selected — one traced body, no data-dependent control)
        l4 = fp2_mul_many(_tstack([QX, Z2a], -3),
                          _tstack([Z2a, Zd], -3))
        U, Z3a = _lane(l4, 0), _lane(l4, 1)
        H = _n2(_rsub(U, Xd))
        l5 = fp2_mul_many(_tstack([QY, H, H], -3),
                          _tstack([Z3a, H, Zd], -3))
        S2c, H2, HZ = (_lane(l5, k) for k in range(3))
        Rr = _n2(_rsub(S2c, Yd))
        l6 = fp2_mul_many(
            _tstack([H2, Xd, HZ, Rr, HZ, Rr, Rr], -3),
            _tstack([H, H2, PY1XI, QX, QY, PXE, Rr], -3))
        H3, XH2, Aa, Rqx, HZqy, CmA, R2 = (_lane(l6, k)
                                           for k in range(7))
        Ba = _rsub(Rqx, _n2(HZqy))
        Xa = _n2(_rsub(_rsub(R2, _n2(H3)), _n2(_radd(XH2, XH2))))
        XmX = _rsub(XH2, Xa)
        l7 = fp2_mul_many(_tstack([Rr, Yd, Zd], -3),
                          _tstack([_n2(XmX), H3, H], -3))
        Ya1, YH3, Za2 = (_lane(l7, k) for k in range(3))
        Ya = _rsub(Ya1, _n2(YH3))
        fa = fp12_mul(fd, _sparse12(Aa, Ba, tneg(CmA)))
        bit = (bits_j[i] == 1)

        def sel(a: TV, d: TV):
            return jnp.where(bit, _n2(a).a, _n2(d).a)

        return (sel(Xa, Xd), sel(Ya, Yd), sel(_norm(Za2, 2.0), Zd),
                sel(fa, fd))

    init = (qx, qy, one2, f0)
    _, _, _, f_end = lax.fori_loop(0, len(_MILLER_BITS), body, init)
    return fp12_conj(TV(f_end, 2.0))


def _final_exp(f: TV) -> TV:
    """f^((q^12-1)/r), split: easy part (q^6-1)(q^2+1) via conj, inv
    and frobenius^2; hard part (q^4-q^2+1)/r as a w=2 windowed fori
    over 635 base-4 digits (digit 0 multiplies by one — branchless)."""
    z = fp12_mul(fp12_conj(f), fp12_inv(f))         # f^(q^6-1)
    y = fp12_mul(fp12_frob2(z), z)                  # ^(q^2+1)
    y2 = fp12_sq(y)
    y3 = fp12_mul(y2, y)
    one = fp12_one(y.a.shape[:-2])
    tab = jnp.stack([one, y.a, y2.a, y3.a], axis=0)
    dig = jnp.asarray(_HARD_DIGITS)

    def body(i, acc):
        a = fp12_sq(fp12_sq(TV(acc, 2.0)))
        m = lax.dynamic_index_in_dim(tab, dig[i], 0, keepdims=False)
        return fp12_mul(a, TV(m, 2.0)).a

    acc0 = tab[int(_HARD_DIGITS[0])]          # leading digit is static
    out = lax.fori_loop(1, len(_HARD_DIGITS), body, acc0)
    return TV(out, 2.0)


@jax.jit
def _pairing_kernel(g1x, g1s, g1i, g1v, g2c1, g2c0, g2s, g2i, g2v):
    """[B, P, ...] packed compressed points -> (verdict[B], decode_ok
    [B]). verdict = decode_ok AND prod_j e(G1_j, G2_j) == 1."""
    (X1, Y1, _Z1), v1 = decompress(g1x, g1s, g1i, g1v)
    qx, qy, v2 = g2_decompress(g2c1, g2c0, g2s, g2i, g2v)
    # both-infinity pairs are NEUTRAL (bucket padding); a one-sided
    # identity point is a malformed check and fails the whole job —
    # the host backends apply the identical rule, so verdicts agree
    pad_pair = g1i & g2i
    live = ~g1i & ~g2i
    pair_ok = v1 & v2 & (pad_pair | live)
    active = pair_ok & live
    am1 = active[..., None]
    am2 = active[..., None, None]
    px = jnp.where(am1, X1, jnp.asarray(_G1X_M))
    py = jnp.where(am1, Y1, jnp.asarray(_G1Y_M))
    qxa = jnp.where(am2, qx.a, jnp.asarray(_G2X_M))
    qya = jnp.where(am2, qy.a, jnp.asarray(_G2Y_M))
    f = _miller(px, py, qxa, qya)                   # [B, P, 12, 32]
    f = TV(jnp.where(am2, f.a, fp12_one(f.a.shape[:-2])), 2.0)
    width = f.a.shape[1]
    while width > 1:                                # pair-axis product
        f = fp12_mul(TV(f.a[:, 0::2], 2.0), TV(f.a[:, 1::2], 2.0))
        width //= 2
    f = TV(f.a[:, 0], 2.0)
    is_one = fp12_eq_one(_final_exp(f))
    job_ok = jnp.all(pair_ok, axis=1)
    return is_one & job_ok, job_ok


# --------------------------------------------------------------- MSM

def _tree_sum_rcb(P, n_pad: int, field):
    """[n_pad, ...] identity-padded points -> single point, log2 levels
    of stacked complete additions (3 stacked multiplies per level)."""
    levels = int(n_pad).bit_length() - 1
    assert 1 << levels == n_pad
    for _ in range(levels):
        P = padd_rcb(tuple(TV(c.a[0::2], c.b) for c in P),
                     tuple(TV(c.a[1::2], c.b) for c in P), field)
    return tuple(TV(c.a[0], c.b) for c in P)


@jax.jit
def _msm_kernel(x_std, sign_big, is_inf, valid_in, digits):
    """sum(s_i * P_i): [N, 32] compressed-G1 limbs + [N, 64] base-16
    scalar digits (msb-first) -> standard-domain projective coords +
    ok (= all points decoded). Per-point w=4 multiples table, Horner
    over nibble windows, then a tree sum across the point axis."""
    (X, Y, Z), valid = decompress(x_std, sign_big, is_inf, valid_in)
    N = x_std.shape[0]
    Pt = (TV(X, 2.0), TV(Y, 2.0), TV(Z, 2.0))
    idX, idY, idZ = g1_identity_flat(N)
    tab0 = tuple(jnp.broadcast_to(c.a[None], (16,) + c.a.shape)
                 for c in (idX, idY, idZ))

    def build(k, tab):
        prev = tuple(TV(lax.dynamic_index_in_dim(
            c, k - 1, 0, keepdims=False), 2.0) for c in tab)
        nxt = padd_rcb(prev, Pt, _FqField)
        return tuple(lax.dynamic_update_index_in_dim(
            c, _norm(n, 2.0).a, k, 0) for c, n in zip(tab, nxt))

    tab = tuple(lax.dynamic_update_index_in_dim(c, p.a, 1, 0)
                for c, p in zip(tab0, Pt))
    tab = lax.fori_loop(2, 16, build, tab)
    dig_t = jnp.transpose(digits)                   # [64, N]

    def horner(w, acc):
        accP = tuple(TV(c, 2.0) for c in acc)
        for _ in range(4):                          # acc <- 16*acc
            accP = padd_rcb(accP, accP, _FqField)
        d = lax.dynamic_index_in_dim(dig_t, w, 0, keepdims=False)
        sel = tuple(jnp.take_along_axis(
            c, d[None, :, None], axis=0)[0] for c in tab)
        accP = padd_rcb(accP, tuple(TV(s, 2.0) for s in sel),
                        _FqField)
        return tuple(_norm(c, 2.0).a for c in accP)

    acc = lax.fori_loop(0, digits.shape[1], horner,
                        tuple(c.a for c in (idX, idY, idZ)))
    n_pad = 1 << max(0, (N - 1).bit_length())
    accP = tuple(TV(c, 2.0) for c in acc)
    if n_pad > N:
        pad = g1_identity_flat(n_pad - N)
        accP = tuple(TV(jnp.concatenate([c.a, p.a], axis=0), 2.0)
                     for c, p in zip(accP, pad))
    Xs, Ys, Zs = _tree_sum_rcb(accP, n_pad, _FqField)
    return (fcanon(from_mont(Xs.a)), fcanon(from_mont(Ys.a)),
            fcanon(from_mont(Zs.a)), jnp.all(valid))


def g1_identity_flat(n: int):
    z = jnp.zeros((n, NLIMB), dtype=jnp.int32)
    one = jnp.broadcast_to(jnp.asarray(_mont_l(1)), (n, NLIMB))
    return TV(z, 1.0), TV(one, 1.0), TV(z, 1.0)


# ----------------------------------------------------- G2 aggregation

@jax.jit
def _g2_aggregate_kernel(c1_std, c0_std, sign_big, is_inf, valid_in):
    """[B, n, 32] G2 limb halves + flags -> standard-domain projective
    fp2 coords [B, 2, 32] x3 + valid[B] — the G2 mirror of the G1
    `_aggregate_kernel` (pubkey aggregation for multi-sig verify)."""
    x, y, valid = g2_decompress(c1_std, c0_std, sign_big, is_inf,
                                valid_in)
    B, n = c1_std.shape[0], c1_std.shape[1]
    idX, idY, idZ = g2_identity((B, n))
    dead = (~valid | is_inf)[..., None, None]
    one2b = jnp.broadcast_to(jnp.asarray(_ONE2_M), x.a.shape)
    P = (TV(jnp.where(dead, idX.a, x.a), 2.0),
         TV(jnp.where(dead, idY.a, y.a), 2.0),
         TV(jnp.where(dead, idZ.a, one2b), 2.0))
    n_pad = 1 << max(0, (n - 1).bit_length())
    if n_pad > n:
        pad = g2_identity((B, n_pad - n))
        P = tuple(TV(jnp.concatenate([c.a, p.a], axis=1), 2.0)
                  for c, p in zip(P, pad))
    levels = int(n_pad).bit_length() - 1
    for _ in range(levels):
        P = padd_rcb(tuple(TV(c.a[:, 0::2], c.b) for c in P),
                     tuple(TV(c.a[:, 1::2], c.b) for c in P),
                     _Fp2Field)
    Xs, Ys, Zs = (TV(c.a[:, 0], c.b) for c in P)
    std = tuple(fcanon(from_mont(c.a)) for c in (Xs, Ys, Zs))
    return std[0], std[1], std[2], jnp.all(valid_in & (valid | is_inf),
                                           axis=1)


# ----------------------------------------------- dispatch / collect

_VALIDATED = set()            # bucket shapes whose execution completed


def _pack_pair_arrays(jobs, Bp: int, Pp: int):
    g1raw = np.zeros((Bp, Pp, 48), dtype=np.uint8)
    g1raw[:, :, 0] = 0xC0
    g2raw = np.zeros((Bp, Pp, 96), dtype=np.uint8)
    g2raw[:, :, 0] = 0xC0
    for i, job in enumerate(jobs):
        for j, (s1, s2) in enumerate(job):
            g1raw[i, j] = np.frombuffer(s1, dtype=np.uint8)
            g2raw[i, j] = np.frombuffer(s2, dtype=np.uint8)
    l1, s1, i1, v1 = pack_compressed(g1raw.reshape(Bp * Pp, 48))
    c1, c0, s2, i2, v2 = pack_g2_compressed(g2raw.reshape(Bp * Pp, 96))
    return (l1.reshape(Bp, Pp, NLIMB), s1.reshape(Bp, Pp),
            i1.reshape(Bp, Pp), v1.reshape(Bp, Pp),
            c1.reshape(Bp, Pp, NLIMB), c0.reshape(Bp, Pp, NLIMB),
            s2.reshape(Bp, Pp), i2.reshape(Bp, Pp),
            v2.reshape(Bp, Pp))


def pairing_dispatch(jobs: Sequence[Sequence[Tuple[bytes, bytes]]]):
    """Launch one batched pairing-product check for B jobs, each a
    list of (compressed G1 48 B, compressed G2 96 B) pairs. Both axes
    are pow2-bucketed (short jobs pad with infinity pairs = neutral
    factors; padding jobs are all-infinity rows sliced off lazily);
    job batches clearing the mesh gate shard the job axis. Returns the
    un-awaited device arrays for `pairing_collect`."""
    B = len(jobs)
    pmax = max(1, max((len(j) for j in jobs), default=1))
    Pp = pow2_at_least(pmax)
    from plenum_tpu.ops import mesh as mesh_mod
    m = mesh_mod.get_mesh()
    sharded = m.should_shard(B)
    Bp = m.padded_size(B, min_per_device=1) if sharded \
        else pow2_at_least(max(B, 1))
    _tmy.get_seam_hub().record_launch(
        _tmy.SEAM_BLS_PAIR, sum(len(j) for j in jobs), Bp * Pp,
        shape=(Bp, Pp))
    arrays = _pack_pair_arrays(jobs, Bp, Pp)
    if sharded:
        outs = m.dispatch(_pairing_kernel, arrays, n=B,
                          label="pairing_dispatch")
    else:
        m.note_passthrough(B)
        from plenum_tpu.observability.tracing import CAT_BLS
        with m.tracer.span("pairing_dispatch", CAT_BLS, n=B,
                           padded=Bp, pairs=Pp):
            outs = _pairing_kernel(*(jnp.asarray(a) for a in arrays))
    if Bp != B:
        outs = tuple(o[:B] for o in outs)
    # validate-once per bucket shape: JAX dispatch is async, so a
    # runtime failure at an untested shape would otherwise surface at
    # the caller's np.asarray outside any except and the step-down
    # would never engage (sha256_blocks_routed precedent)
    shape = ("pair", Bp, Pp)
    if shape not in _VALIDATED:
        outs[0].block_until_ready()  # plenum-lint: disable=PT002
        _VALIDATED.add(shape)
    return outs


def pairing_collect(handles) -> Tuple[np.ndarray, np.ndarray]:
    """Await a `pairing_dispatch` handle -> (verdict[B], decode_ok[B])
    numpy bools."""
    from plenum_tpu.ops import mesh as mesh_mod
    from plenum_tpu.observability.tracing import CAT_BLS
    m = mesh_mod.get_mesh()
    with m.tracer.span("pairing_collect", CAT_BLS):
        verdict, ok = (np.asarray(h) for h in handles)
    return verdict, ok


def pairing_jobs(jobs) -> Tuple[np.ndarray, np.ndarray]:
    """Dispatch + collect in one call (the synchronous routing entry
    used by crypto/bls_ops)."""
    if len(jobs) == 0:
        return np.zeros(0, dtype=bool), np.zeros(0, dtype=bool)
    return pairing_collect(pairing_dispatch(jobs))


def msm_dispatch(points: Sequence[bytes], scalars: Sequence[int]):
    """Launch sum(s_i * P_i) over compressed G1 points. The point axis
    is pow2-bucketed (infinity points with zero scalars pad — every
    multiple of the identity is the identity, so padding rows cost
    nothing through the complete additions). Reduction crosses the
    point axis, so this seam never mesh-shards (note_passthrough)."""
    N = len(points)
    Np = pow2_at_least(max(N, 1))
    from plenum_tpu.ops import mesh as mesh_mod
    m = mesh_mod.get_mesh()
    _tmy.get_seam_hub().record_launch(_tmy.SEAM_BLS_MSM, N, Np,
                                      shape=(Np,))
    raw = np.zeros((Np, 48), dtype=np.uint8)
    raw[:, 0] = 0xC0
    for i, p in enumerate(points):
        raw[i] = np.frombuffer(p, dtype=np.uint8)
    digits = np.zeros((Np, 64), dtype=np.int32)
    sb = np.zeros((Np, 32), dtype=np.uint8)
    for i, s in enumerate(scalars):
        sb[i] = np.frombuffer((s % R_ORD).to_bytes(32, "big"),
                              dtype=np.uint8)
    digits[:, 0::2] = sb >> 4
    digits[:, 1::2] = sb & 0xF
    limbs, sign_big, is_inf, valid = pack_compressed(raw)
    m.note_passthrough(N)
    from plenum_tpu.observability.tracing import CAT_BLS
    with m.tracer.span("msm_dispatch", CAT_BLS, n=N, padded=Np):
        outs = _msm_kernel(jnp.asarray(limbs), jnp.asarray(sign_big),
                           jnp.asarray(is_inf), jnp.asarray(valid),
                           jnp.asarray(digits))
    shape = ("msm", Np)
    if shape not in _VALIDATED:
        outs[3].block_until_ready()  # plenum-lint: disable=PT002
        _VALIDATED.add(shape)
    return outs


def msm_collect(handles) -> Optional[Tuple[int, int]]:
    """Await an `msm_dispatch` handle -> affine (x, y) ints or None
    (identity / undecodable input)."""
    from plenum_tpu.ops import mesh as mesh_mod
    from plenum_tpu.observability.tracing import CAT_BLS
    m = mesh_mod.get_mesh()
    with m.tracer.span("msm_collect", CAT_BLS):
        X, Y, Z, ok = (np.asarray(h) for h in handles)
    if not bool(ok):
        return None
    xi = int(_limbs_to_ints(X[None])[0])
    yi = int(_limbs_to_ints(Y[None])[0])
    zi = int(_limbs_to_ints(Z[None])[0])
    return _proj_to_affine(xi, yi, zi)


def msm_g1(points: Sequence[bytes], scalars: Sequence[int]):
    """Synchronous MSM: (affine point | None, decode_ok)."""
    if len(points) == 0:
        return None, True
    outs = msm_dispatch(points, scalars)
    ok = bool(np.asarray(outs[3]))
    return msm_collect(outs), ok


def g2_aggregate_dispatch(jobs: Sequence[Sequence[bytes]], n: int):
    """Batched G2 aggregation (pubkey sets), mirror of the G1
    `aggregate_dispatch`: B jobs x n compressed 96-byte points, both
    axes identity-padded to pow2 buckets."""
    B = len(jobs)
    from plenum_tpu.ops import mesh as mesh_mod
    m = mesh_mod.get_mesh()
    Bp = pow2_at_least(max(B, 1))
    _tmy.get_seam_hub().record_launch(
        _tmy.SEAM_BLS, sum(len(j) for j in jobs), Bp * n, shape=(Bp, n))
    raw = np.zeros((Bp, n, 96), dtype=np.uint8)
    raw[:, :, 0] = 0xC0
    for i, job in enumerate(jobs):
        for j, s in enumerate(job):
            raw[i, j] = np.frombuffer(s, dtype=np.uint8)
    c1, c0, sg, inf, valid = pack_g2_compressed(raw.reshape(Bp * n, 96))
    arrays = (c1.reshape(Bp, n, NLIMB), c0.reshape(Bp, n, NLIMB),
              sg.reshape(Bp, n), inf.reshape(Bp, n),
              valid.reshape(Bp, n))
    m.note_passthrough(B)
    outs = _g2_aggregate_kernel(*(jnp.asarray(a) for a in arrays))
    if Bp != B:
        outs = tuple(o[:B] for o in outs)
    shape = ("g2agg", Bp, n)
    if shape not in _VALIDATED:
        outs[3].block_until_ready()  # plenum-lint: disable=PT002
        _VALIDATED.add(shape)
    return outs


def g2_aggregate_collect(handles):
    """-> (points, valid): points[i] = affine (Fq2-int-pair x, y) |
    None per job."""
    X, Y, Z, ok = (np.asarray(h) for h in handles)
    out: List[Optional[Tuple[Tuple[int, int], Tuple[int, int]]]] = []
    for i in range(len(ok)):
        if not ok[i]:
            out.append(None)
            continue
        x0, x1 = (int(_limbs_to_ints(X[i][None, c])[0])
                  for c in range(2))
        y0, y1 = (int(_limbs_to_ints(Y[i][None, c])[0])
                  for c in range(2))
        z0, z1 = (int(_limbs_to_ints(Z[i][None, c])[0])
                  for c in range(2))
        if z0 == 0 and z1 == 0:
            out.append(None)        # projective identity
            continue
        # affine via Fq2 inversion on host ints
        den = (z0 * z0 + z1 * z1) % Q
        di = pow(den, Q - 2, Q)
        iz = (z0 * di % Q, (-z1) * di % Q)

        def fq2mul(a, b):
            return ((a[0] * b[0] - a[1] * b[1]) % Q,
                    (a[0] * b[1] + a[1] * b[0]) % Q)

        out.append((fq2mul((x0, x1), iz), fq2mul((y0, y1), iz)))
    return out, ok
