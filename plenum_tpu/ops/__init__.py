"""TPU-accelerated batch primitives (JAX/XLA).

The framework's hot data paths — merkle SHA-256 hashing, ed25519 signature
verification, BLS12-381 aggregation — are expressed as pure batched JAX
functions in this package, dispatched from the host-side consensus loop
behind pluggable provider seams (SURVEY.md §2.9).
"""
