"""TPU-accelerated batch primitives (JAX/XLA).

The framework's hot data paths — merkle SHA-256 hashing, ed25519 signature
verification, BLS12-381 aggregation — are expressed as pure batched JAX
functions in this package, dispatched from the host-side consensus loop
behind pluggable provider seams (SURVEY.md §2.9).
"""
import os

import numpy as np


def pow2_at_least(n: int) -> int:
    """Smallest power of two >= n — the shared bucket-rounding rule for
    batch padding (ops/mesh.py) and tree capacity (ops/merkle.py)."""
    p = 1
    while p < n:
        p *= 2
    return p


def scatter_ragged_rows(msgs, width: int):
    """Scatter variable-length messages into a zero-filled
    ``[len(msgs), width]`` uint8 buffer with ONE flat vectorized
    scatter — the shared core of the mixed-length host padding in
    ``ops/sha256.pad_messages`` and ``ops/sha3.pad_sha3_messages``
    (a per-message Python loop was the host bottleneck for large
    mixed batches in both).

    Returns ``(out, lens)``: the row buffer and the per-message byte
    lengths as int64 — each hash pads its own domain/length markers on
    top (SHA-2: 0x80 + 64-bit big-endian bit length; SHA-3: 0x06 +
    final-byte 0x80 XOR).
    """
    n = len(msgs)
    out = np.zeros((n, width), dtype=np.uint8)
    lens = np.fromiter((len(m) for m in msgs), dtype=np.int64, count=n)
    joined = np.frombuffer(b"".join(msgs), dtype=np.uint8)
    if joined.shape[0]:
        flat = out.reshape(-1)
        starts = np.zeros(n, dtype=np.int64)
        np.cumsum(lens[:-1], out=starts[1:])
        rows = np.arange(n, dtype=np.int64)
        dst = np.repeat(rows * width, lens) \
            + (np.arange(joined.shape[0], dtype=np.int64)
               - np.repeat(starts, lens))
        flat[dst] = joined
    return out, lens


def enable_persistent_compilation_cache(path: str = None) -> str:
    """Point XLA's persistent compilation cache at `path` (default:
    <repo>/.jax_cache). The big verify buckets take 30-110s to compile;
    with the cache, every process after the first loads them in
    milliseconds. Must use jax.config (the JAX_COMPILATION_CACHE_DIR
    env var alone does not activate the cache on all backends)."""
    import jax
    if path is None:
        path = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return path
